"""Admin HTTP command handler.

Reference: src/main/CommandHandler.{h,cpp} — routes at :87-125. The
dispatch core (`handle`) is pure so tests exercise commands without
sockets; `run_http_server` wraps it in a stdlib ThreadingHTTPServer whose
handlers post work onto the main VirtualClock, preserving the reference's
single-main-thread discipline (docs/architecture.md:24-36).
"""

from __future__ import annotations

import base64
import binascii
import json
import threading
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..herder.tx_queue import AddResult
from ..util.logging import get_logger, set_log_level
from ..xdr.transaction import TransactionEnvelope

log = get_logger("default")


class CommandHandler:
    def __init__(self, app):
        self.app = app

    # ------------------------------------------------------------ dispatch --
    def handle(self, command: str, params: Optional[Dict[str, str]] = None,
               ) -> dict:
        params = params or {}
        routes = {
            "info": self._info,
            "metrics": self._metrics,
            "clearmetrics": self._clear_metrics,
            "tx": self._tx,
            "manualclose": self._manual_close,
            "upgrades": self._upgrades,
            "ll": self._log_level,
            "peers": self._peers,
            "quorum": self._quorum,
            "maintenance": self._maintenance,
            "setcursor": self._set_cursor,
            "getcursor": self._get_cursor,
            "dropcursor": self._drop_cursor,
            "self-check": self._self_check,
            "surveytopology": self._survey_topology,
            "getsurveyresult": self._get_survey_result,
            "ban": self._ban,
            "unban": self._unban,
            "bans": self._bans,
            "connect": self._connect,
            "droppeer": self._drop_peer,
            "scp": self._scp_info,
            "getledgerentry": self._get_ledger_entry,
            "generateload": self._generate_load,
            "perf": self._perf,
            "chaos": self._chaos,
            "backendstatus": self._backend_status,
            "starttrace": self._start_trace,
            "stoptrace": self._stop_trace,
            "dumptrace": self._dump_trace,
            # input recording (replay/): docs/REPLAY.md
            "recordstart": self._record_start,
            "recordstop": self._record_stop,
            "recorddump": self._record_dump,
            "clusterstatus": self._cluster_status,
            "timeseries": self._timeseries,
            "slo": self._slo,
            "controller": self._controller,
            # read-serving tier (query/): snapshot-consistent reads
            "account": self._account,
            "txstatus": self._tx_status,
            "snapshotinfo": self._snapshot_info,
        }
        fn = routes.get(command)
        if fn is None:
            return {"exception": f"unknown command: {command}"}
        rec = getattr(self.app, "input_recorder", None)
        if rec is not None and rec.active:
            # state-mutating admin commands are node inputs: recorded
            # on arrival (before execution, like a wire frame) so
            # replay re-drives them at the same instant. `tx` is
            # recorded as an INJECT inside _tx, bytes-exact.
            rec.record_admin(command, params)
        try:
            return fn(params)
        except Exception as e:  # surfaced as the reference does
            log.error("command %s failed: %s", command, e)
            return {"exception": str(e)}

    # -------------------------------------------------------------- routes --
    def _info(self, params) -> dict:
        return {"info": self.app.info()}

    def _sync_verify_cache_meters(self) -> None:
        """Drain the process-wide verify-cache hit/miss counters (only
        reachable via flush_verify_cache_counts before) into
        crypto.verify.cache.{hit,miss} meters, so they ride the metrics
        route and the Prometheus exposition like every other metric.
        The meters always exist (zero-valued) so scrapers see stable
        families."""
        from ..crypto.keys import flush_verify_cache_counts
        h, m = flush_verify_cache_counts()
        hit = self.app.metrics.meter("crypto", "verify", "cache", "hit")
        miss = self.app.metrics.meter("crypto", "verify", "cache", "miss")
        if h:
            hit.mark(h)
        if m:
            miss.mark(m)

    def _metrics(self, params) -> dict:
        # perf zones ride along so the per-phase closeLedger breakdown
        # (ledger.close.applyTx / .seal / .complete, …) is visible from
        # the same admin endpoint operators already scrape
        self._sync_verify_cache_meters()
        if params.get("format") == "prometheus":
            # text exposition for scrapers: the whole MetricsRegistry
            # plus the zone report as labeled gauge families
            from ..util.metrics import render_prometheus
            return {"_raw_body": render_prometheus(
                        self.app.metrics.to_json(),
                        self.app.perf.report()),
                    "_content_type":
                        "text/plain; version=0.0.4; charset=utf-8"}
        out = {"metrics": self.app.metrics.to_json(),
               "perf_zones": self.app.perf.report()}
        from ..util import chaos
        if chaos.ENABLED:
            # chaos.injected.* counters surface beside the metrics an
            # operator is already watching during an injection run
            out["chaos"] = chaos.status()
        return out

    def _clear_metrics(self, params) -> dict:
        self.app.metrics.clear()
        # the zone registry is the same operator surface: clearing one
        # and not the other left `perf` reporting stale zones forever
        self.app.perf.reset()
        # per-peer message/byte/duplicate counters and the hash-keyed
        # stamp dicts reset too, so bench legs sharing one process
        # measure each window from a clean slate (previously only
        # meters and perf zones reset — the peers route kept counting
        # across legs)
        overlay = getattr(self.app, "overlay_manager", None)
        if overlay is not None:
            overlay.reset_peer_counters()
        prop = getattr(self.app, "propagation", None)
        if prop is not None:
            prop.clear()
        self.app.herder.reset_observability()
        bv = getattr(self.app, "batch_verifier", None)
        if bv is not None and hasattr(bv, "breaker_state"):
            # the breaker state gauge is level, not flow: a clear must
            # not report an OPEN breaker as CLOSED until the next
            # transition happens to re-set it
            bv.refresh_gauge()
        # telemetry ring + scrape cursors (the epoch rotates, so a
        # scraper holding an old since= token resyncs with reset=true
        # instead of silently gapping) and the SLO sliding-window
        # state reset too — the PR 7 contract: bench legs in one
        # process measure each window from a clean slate. Bad-sig
        # accounting still deliberately survives (it feeds the
        # per-peer drop threshold).
        tel = getattr(self.app, "telemetry", None)
        if tel is not None:
            tel.clear()
        slo = getattr(self.app, "slo", None)
        if slo is not None:
            slo.reset()
        # the adaptive controller's learned state too (ISSUE 11
        # satellite): knobs back to config, shed probabilities to
        # zero, decision log cleared, epoch rotated — a frozen or
        # mis-trained controller must not leak tuning into the next
        # bench leg sharing this process
        ctl = getattr(self.app, "controller", None)
        if ctl is not None:
            ctl.reset()
        # the read tier's learned hedge-trigger window resets with the
        # registry its latency timer lives in
        qsvc = getattr(self.app, "query_service", None)
        if qsvc is not None:
            qsvc.reset_stats()
        return {"status": "ok"}

    # ------------------------------------------------------ flight recorder --
    def _start_trace(self, params) -> dict:
        """Begin span recording (util/tracing.py — the Tracy-capture
        analogue): starttrace[?capacity=N] ring-buffers events until
        stoptrace/dumptrace."""
        rec = self.app.flight_recorder
        cap = params.get("capacity")
        rec.start(capacity=int(cap) if cap else None)
        return {"status": "ok", "capacity": rec._capacity}

    def _stop_trace(self, params) -> dict:
        rec = self.app.flight_recorder
        if not rec.active:
            return {"exception": "no trace is recording"}
        return {"status": "ok", **rec.stop()}

    def _dump_trace(self, params) -> dict:
        """Dump the recorded span buffer as Chrome trace-event JSON
        (load in Perfetto / chrome://tracing, or feed to
        scripts/trace_report.py). dumptrace?path=/x.json writes a file;
        without path the document is returned inline."""
        rec = self.app.flight_recorder
        doc = rec.to_chrome_trace()
        path = params.get("path")
        if path:
            # create-only ('x'): an admin GET must never be a
            # truncate-arbitrary-file primitive (the chaos route's
            # production-gate precedent; overwriting an existing file
            # fails loudly instead)
            with open(path, "x") as f:
                json.dump(doc, f)
            return {"status": "ok", "path": path,
                    "events": len(doc["traceEvents"]),
                    "dropped": rec.dropped}
        return {"trace": doc}

    def _record_start(self, params) -> dict:
        """Attach an input recorder (replay/recorder.py) and start
        capturing this node's inputs: recordstart[?path=<file>]. With
        `path` the log streams to a create-only file (torn-tail
        tolerant across a kill); without it the log buffers in memory
        for `recorddump`. Gated like `chaos`: recording captures every
        inbound frame verbatim, so a production node must not accept
        it over HTTP."""
        if not self.app.config.ALLOW_INPUT_RECORDING:
            return {"exception":
                    "input recording disabled (ALLOW_INPUT_RECORDING)"}
        if getattr(self.app, "input_recorder", None) is not None and \
                self.app.input_recorder.active:
            return {"exception": "recording already active"}
        from ..replay.recorder import InputRecorder
        rec = InputRecorder(self.app, path=params.get("path"))
        rec.begin()     # open("xb") — never truncates an existing file
        self.app.input_recorder = rec
        out = {"status": "recording", "node": rec.node_hex}
        if rec.path is not None:
            out["path"] = rec.path
        return out

    def _record_stop(self, params) -> dict:
        """Write the END marker and detach: recordstop. The stats echo
        what was captured; a file-backed log is complete on disk."""
        if not self.app.config.ALLOW_INPUT_RECORDING:
            return {"exception":
                    "input recording disabled (ALLOW_INPUT_RECORDING)"}
        rec = getattr(self.app, "input_recorder", None)
        if rec is None or not rec.active:
            return {"exception": "no active recording"}
        stats = rec.finish(reason="recordstop")
        return {"status": "stopped", **stats}

    def _record_dump(self, params) -> dict:
        """Dump an in-memory recording: recorddump?path=<file>. Like
        `dumptrace`, create-only — the admin API must never be a
        truncate-arbitrary-file primitive. Valid after recordstop (the
        buffer survives until the next recordstart)."""
        if not self.app.config.ALLOW_INPUT_RECORDING:
            return {"exception":
                    "input recording disabled (ALLOW_INPUT_RECORDING)"}
        rec = getattr(self.app, "input_recorder", None)
        if rec is None:
            return {"exception": "nothing recorded"}
        if rec.active:
            return {"exception": "recording still active (recordstop "
                    "first, or recordstart?path= to stream to disk)"}
        if rec.path is not None:
            return {"exception": "recording already streamed to "
                    f"{rec.path}"}
        path = params.get("path")
        if not path:
            return {"exception": "missing 'path' parameter"}
        data = rec.to_bytes()
        with open(path, "xb") as f:
            f.write(data)
        return {"status": "ok", "path": path, "bytes": len(data)}

    def _tx(self, params) -> dict:
        """Submit a base64-XDR TransactionEnvelope (reference:
        CommandHandler::tx :115)."""
        blob = params.get("blob")
        if not blob:
            return {"exception": "missing 'blob' parameter"}
        try:
            raw = base64.b64decode(blob, validate=True)
            env = TransactionEnvelope.from_bytes(raw)
        except (binascii.Error, Exception) as e:
            return {"exception": f"malformed envelope: {e}"}
        from ..tx.frame import make_frame
        frame = make_frame(env, self.app.config.network_id())
        rec = getattr(self.app, "input_recorder", None)
        if rec is not None and rec.active:
            rec.record_inject([raw], direct=True)
        res = self.app.herder.recv_transaction(frame)
        out = {"status": _add_result_name(res)}
        if res == AddResult.ADD_STATUS_ERROR and frame.result is not None:
            out["error"] = base64.b64encode(
                frame.result.to_bytes()).decode()
        return out

    def _manual_close(self, params) -> dict:
        self.app.manual_close()
        return {"status": "Manually triggered a ledger close with sequence "
                          f"number {self.app.ledger_manager.get_last_closed_ledger_num()}"}

    def _upgrades(self, params) -> dict:
        """reference: CommandHandler::upgrades — mode=get|set|clear."""
        from ..herder.upgrades import UpgradeParameters
        mode = params.get("mode", "get")
        up = self.app.herder.upgrades
        if mode == "get":
            import base64
            p = up.get_parameters()
            return {"upgrades": {
                "upgradetime": p.upgrade_time,
                "protocolversion": p.protocol_version,
                "basefee": p.base_fee,
                "basereserve": p.base_reserve,
                "maxtxsetsize": p.max_tx_set_size,
                "maxsorobantxsetsize": p.max_soroban_tx_set_size,
                "configupgradesetkey":
                    base64.b64encode(
                        p.config_upgrade_set_key.to_bytes()).decode()
                    if p.config_upgrade_set_key is not None else None,
            }}
        if mode == "clear":
            up.set_parameters(UpgradeParameters())
            return {"status": "ok"}
        if mode == "set":
            def _opt(name):
                v = params.get(name)
                return int(v) if v is not None else None
            cfg_key = None
            if params.get("configupgradesetkey"):
                import base64
                from ..xdr.contract import ConfigUpgradeSetKey
                cfg_key = ConfigUpgradeSetKey.from_bytes(
                    base64.b64decode(params["configupgradesetkey"],
                                     validate=True))
            up.set_parameters(UpgradeParameters(
                upgrade_time=int(params.get("upgradetime", 0)),
                protocol_version=_opt("protocolversion"),
                base_fee=_opt("basefee"),
                base_reserve=_opt("basereserve"),
                max_tx_set_size=_opt("maxtxsetsize"),
                max_soroban_tx_set_size=_opt("maxsorobantxsetsize"),
                config_upgrade_set_key=cfg_key))
            return {"status": "ok"}
        return {"exception": f"unknown mode: {mode}"}

    def _log_level(self, params) -> dict:
        level = params.get("level")
        if not level:
            return {"exception": "missing 'level'"}
        set_log_level(level, params.get("partition"))
        return {"status": "ok"}

    def _peers(self, params) -> dict:
        overlay = getattr(self.app, "overlay_manager", None)
        if overlay is None:
            return {"authenticated_peers": {"inbound": [], "outbound": []}}
        return {"authenticated_peers": overlay.peers_json()}

    def _quorum(self, params) -> dict:
        """reference: CommandHandler::quorum; ?transitive=true also runs
        the quorum-intersection analysis."""
        herder = self.app.herder
        analyze = (params or {}).get("transitive", "") in ("true", "1")
        if hasattr(herder, "quorum_json"):
            return herder.quorum_json(analyze=analyze)
        return {"node": "unknown", "qset": {}}

    def _maintenance(self, params) -> dict:
        count = int(params.get("count", 50000))
        deleted = self.app.maintainer.perform_maintenance(count)
        return {"status": "ok", "deleted": deleted}

    def _set_cursor(self, params) -> dict:
        """reference: CommandHandler::setcursor (ExternalQueue)."""
        resid = params.get("id")
        cursor = params.get("cursor")
        if not resid or cursor is None:
            return {"exception": "missing id or cursor"}
        self.app.maintainer.external_queue.set_cursor_for_resource(
            resid, int(cursor))
        return {"status": "ok"}

    def _get_cursor(self, params) -> dict:
        return {"cursors": self.app.maintainer.external_queue.get_cursor(
            params.get("id"))}

    def _drop_cursor(self, params) -> dict:
        resid = params.get("id")
        if not resid:
            return {"exception": "missing id"}
        self.app.maintainer.external_queue.delete_cursor(resid)
        return {"status": "ok"}

    def _self_check(self, params) -> dict:
        from .self_check import self_check
        ok, report = self_check(self.app)
        return {"status": "ok" if ok else "failed", "report": report}

    def _survey_topology(self, params) -> dict:
        """reference: CommandHandler surveytopology — node param is a
        strkey public key."""
        from ..crypto.strkey import StrKey
        node = params.get("node")
        if not node or self.app.overlay_manager is None:
            return {"exception": "missing node or no overlay"}
        self.app.overlay_manager.survey_manager.survey_peer(
            StrKey.decode_ed25519_public(node))
        return {"status": "ok"}

    def _get_survey_result(self, params) -> dict:
        if self.app.overlay_manager is None:
            return {"exception": "no overlay"}
        return {"topology":
                self.app.overlay_manager.survey_manager.results_json()}

    def _ban_and_drop(self, raw: bytes, reason: str,
                      ban: bool) -> int:
        """Shared by ban/droppeer: optionally ban, then drop matching
        authenticated peers."""
        if ban:
            self.app.overlay_manager.ban_manager.ban_node(raw)
        dropped = 0
        for peer in self.app.overlay_manager.get_authenticated_peers():
            if peer.peer_id == raw:
                peer.drop(reason)
                dropped += 1
        return dropped

    def _ban(self, params) -> dict:
        from ..crypto.strkey import StrKey
        node = params.get("node")
        if not node or self.app.overlay_manager is None:
            return {"exception": "missing node or no overlay"}
        self._ban_and_drop(StrKey.decode_ed25519_public(node),
                           "banned", ban=True)
        return {"status": "ok"}

    def _unban(self, params) -> dict:
        from ..crypto.strkey import StrKey
        node = params.get("node")
        if not node or self.app.overlay_manager is None:
            return {"exception": "missing node or no overlay"}
        self.app.overlay_manager.ban_manager.unban_node(
            StrKey.decode_ed25519_public(node))
        return {"status": "ok"}

    def _bans(self, params) -> dict:
        from ..crypto.strkey import StrKey
        if self.app.overlay_manager is None:
            return {"exception": "no overlay"}
        return {"bans": [StrKey.encode_ed25519_public(n) for n in
                         self.app.overlay_manager.ban_manager
                         .banned_nodes()]}

    def _connect(self, params) -> dict:
        """reference: CommandHandler::connect — dial peer=ip&port=N."""
        peer_ip = params.get("peer")
        port = params.get("port")
        if not peer_ip or not port or self.app.overlay_manager is None:
            return {"exception": "missing peer/port or no overlay"}
        from ..overlay.tcp_peer import connect_to
        self.app.overlay_manager.peer_manager.ensure_exists(
            peer_ip, int(port))
        connect_to(self.app.overlay_manager, peer_ip, int(port))
        return {"status": "ok"}


    def _drop_peer(self, params) -> dict:
        """reference: CommandHandler::dropPeer — droppeer?node=ID[&ban=1]."""
        from ..crypto.strkey import StrKey
        node = params.get("node")
        if not node or self.app.overlay_manager is None:
            return {"exception":
                    "Must specify at least peer id: droppeer?node=NODE_ID"}
        dropped = self._ban_and_drop(
            StrKey.decode_ed25519_public(node), "dropped by admin",
            ban=params.get("ban") in ("1", "true"))
        return {"status": "ok", "dropped": dropped}

    def _scp_info(self, params) -> dict:
        """reference: CommandHandler::scpInfo — per-slot consensus state
        (scp?limit=N)."""
        herder = self.app.herder
        if herder.scp is None:
            return {"exception": "node has no SCP (no NODE_SEED)"}
        limit = int(params.get("limit", "2"))
        slots = {}
        for idx in sorted(herder.scp.known_slots, reverse=True)[:limit]:
            slot = herder.scp.known_slots[idx]
            bp, np_ = slot.ballot, slot.nomination
            slots[str(idx)] = {
                "phase": bp.phase.name,
                "ballot_counter": bp.current.counter
                if bp.current is not None else 0,
                "heard_from": len(bp.latest_envelopes),
                "nomination": {
                    "votes": len(np_.votes),
                    "accepted": len(np_.accepted),
                    "candidates": len(np_.candidates),
                },
                "fully_validated": slot.is_fully_validated(),
            }
        from ..crypto.strkey import StrKey
        return {"scp": {"you": StrKey.encode_ed25519_public(
                            self.app.config.node_id()),
                        "slots": slots}}

    def _get_ledger_entry(self, params) -> dict:
        """reference: CommandHandler::getLedgerEntry :709 —
        getledgerentry?key=<base64 LedgerKey XDR>."""
        import base64
        from ..ledger.ledger_txn import LedgerTxn
        from ..xdr.ledger_entries import LedgerKey
        key_b64 = params.get("key")
        if not key_b64:
            return {"exception": "Must specify ledger key: "
                    "getledgerentry?key=<LedgerKey in base64 XDR format>"}
        key = LedgerKey.from_bytes(base64.b64decode(key_b64,
                                                    validate=True))
        out = {"ledger":
               self.app.ledger_manager.get_last_closed_ledger_num()}
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            le = ltx.load_without_record(key)
            if le is not None:
                out["state"] = "live"
                out["entry"] = base64.b64encode(le.to_bytes()).decode()
            else:
                out["state"] = "dead"
        return out

    # ------------------------------------------------------- read tier --
    def _account(self, params) -> dict:
        """account?id=<G... strkey | 64-char hex> — snapshot-consistent
        account read through the query-worker pool (docs/READ_PATH.md).
        Every answer names the exact closed ledger it was read at."""
        import base64
        from ..crypto.strkey import StrKey
        acct = params.get("id")
        if not acct:
            return {"exception": "Must specify account: "
                    "account?id=<strkey or hex account id>"}
        if len(acct) == 64:
            try:
                raw = bytes.fromhex(acct)
            except ValueError:
                return {"exception": f"bad account id: {acct}"}
        else:
            raw = StrKey.decode_ed25519_public(acct)
        deadline = params.get("deadline_ms")
        res = self.app.query_service.query_account(
            raw, deadline_ms=float(deadline) if deadline else None)
        out = {"ledger_seq": res.get("ledger_seq"),
               "found": res.get("found", False),
               "latency_ms": res.get("latency_ms")}
        for k in ("shed", "timeout", "error"):
            if k in res:
                out[k] = res[k]
        if res.get("entry_xdr"):
            out["entry"] = base64.b64encode(res["entry_xdr"]).decode()
        return out

    def _tx_status(self, params) -> dict:
        """txstatus?hash=<64-char hex envelope hash (tx.full_hash(),
        the completion stream's result-pair key)> — result XDR + the
        ledger it applied in, from the completion-fed status ring."""
        import base64
        h = params.get("hash")
        if not h:
            return {"exception": "Must specify tx hash: "
                    "txstatus?hash=<hex transaction hash>"}
        try:
            raw = bytes.fromhex(h)
        except ValueError:
            return {"exception": f"bad tx hash: {h}"}
        deadline = params.get("deadline_ms")
        res = self.app.query_service.query_tx_status(
            raw, deadline_ms=float(deadline) if deadline else None)
        out = {"ledger_seq": res.get("ledger_seq"),
               "found": res.get("found", False),
               "latency_ms": res.get("latency_ms")}
        for k in ("shed", "timeout", "error"):
            if k in res:
                out[k] = res[k]
        if res.get("result_xdr"):
            out["result"] = base64.b64encode(res["result_xdr"]).decode()
        return out

    def _snapshot_info(self, params) -> dict:
        """snapshotinfo — the read tier's serving state: newest
        snapshot seq, open snapshot count, pool/shed/hedge tallies."""
        snaps = self.app.snapshots.stats()
        return {"snapshot": snaps,
                "pinned_buckets":
                    len(self.app.snapshots.pinned_bucket_hashes()),
                "tx_status_entries": len(self.app.tx_status),
                "service": self.app.query_service.stats()}

    def _generate_load(self, params) -> dict:
        """reference: CommandHandler::generateLoad — synthesize load
        (generateload?mode=create|pay|zipf&accounts=N&txs=N
        [&exponent=F]). `zipf` is the hot-account skew mode (ISSUE 16's
        Zipfian loadgen, ISSUE 20's matrix cell): rank-weighted
        source/destination draws, reproducible per node."""
        from ..simulation.load_generator import LoadGenerator
        mode = params.get("mode", "create")
        if getattr(self, "_load_generator", None) is None:
            self._load_generator = LoadGenerator(self.app)
        lg = self._load_generator
        if mode == "create":
            n = int(params.get("accounts", "100"))
            created = lg.generate_accounts(n)
            return {"status": "ok", "mode": mode, "submitted": created}
        if mode in ("pay", "zipf"):
            if len(lg.accounts) < 2:
                return {"exception": "run generateload?mode=create and "
                        "close a ledger first"}
            n = int(params.get("txs", "100"))
            lg.sync_account_seqs()  # learn seqnums from the last close
            if mode == "zipf":
                submitted = lg.generate_payments_zipf(
                    n, exponent=float(params.get("exponent", "1.0")))
            else:
                submitted = lg.generate_payments(n)
            return {"status": "ok", "mode": mode, "submitted": submitted}
        return {"exception": f"unknown load mode: {mode}"}

    def _perf(self, params) -> dict:
        """Zone-timing report (our Tracy analogue, SURVEY.md §5.1);
        perf?reset=1 clears this node's zones."""
        report = self.app.perf.report()
        if params.get("reset") in ("1", "true"):
            self.app.perf.reset()
        return {"perf": report}

    def _chaos(self, params) -> dict:
        """Runtime chaos control: chaos?mode=status|install|clear.
        install takes seed=N and schedule=<JSON list of fault specs>
        (see docs/CHAOS.md). status is always served; install/clear
        require ALLOW_CHAOS_INJECTION — a production node must not
        accept fault injection over HTTP."""
        from ..util import chaos
        mode = params.get("mode", "status")
        if mode == "status":
            return {"chaos": chaos.status()}
        if not self.app.config.ALLOW_CHAOS_INJECTION:
            return {"exception":
                    "chaos injection disabled (ALLOW_CHAOS_INJECTION)"}
        if mode == "install":
            seed = int(params.get("seed", "0"))
            schedule = chaos.schedule_from_json(
                json.loads(params.get("schedule", "[]")))
            chaos.install(chaos.ChaosEngine(seed, schedule))
            return {"status": "ok", "chaos": chaos.status()}
        if mode == "clear":
            chaos.uninstall()
            return {"status": "ok"}
        return {"exception": f"unknown chaos mode: {mode}"}

    def _backend_status(self, params) -> dict:
        """Device-backend supervisor state (ops/backend_supervisor.py):
        aggregate breaker state, the surviving-mesh summary, and
        per-device rows (state, consecutive failures, probe ages,
        dispatch/skip counters, quarantined handles).
        backendstatus?action=trip|reset[&device=N] forces a breaker
        transition — whole-mesh, or one device so a single chip can be
        drained/readmitted — gated behind ALLOW_CHAOS_INJECTION like
        the chaos route: a production node must not accept forced
        degradation over HTTP. Plain status is always served; the
        cluster harness (simulation/cluster.py) polls it per node into
        CLUSTER artifacts."""
        sup = getattr(self.app, "batch_verifier", None)
        if sup is None or not hasattr(sup, "breaker_state"):
            return {"exception": "no supervised device backend "
                    "(SIGNATURE_VERIFY_BACKEND != tpu)"}
        action = params.get("action")
        if action:
            if not self.app.config.ALLOW_CHAOS_INJECTION:
                return {"exception": "backend actions disabled "
                        "(ALLOW_CHAOS_INJECTION)"}
            device = params.get("device")
            try:
                device = int(device) if device is not None else None
                if device is not None and not \
                        0 <= device < sup.mesh_status()["devices"]:
                    raise ValueError(device)
            except (TypeError, ValueError):
                return {"exception": f"bad device index: {device!r}"}
            if action == "trip":
                sup.force_trip(device=device)
            elif action == "reset":
                sup.force_reset(device=device)
            else:
                return {"exception": f"unknown action: {action}"}
        return {"backend": sup.status()}

    def _timeseries(self, params) -> dict:
        """Telemetry time-series scrape (util/timeseries.py):
        `timeseries[?since=<cursor>][&limit=N][&summary=1]`. The reply
        carries an opaque `cursor` token; passing it back as `since=`
        returns only newer samples — incremental scraping for the
        cluster harness. `reset: true` means the epoch changed
        (restart / clearmetrics) or the continuation point fell off
        the bounded ring, and the buffer was served from the start
        instead. `limit=N` serves the OLDEST N pending samples with
        the cursor pointing at the last one served (`truncated:
        true`), so chained limited scrapes walk the series gap-free.
        `summary=1` returns the bounded series summary (the bench
        artifact form) rather than raw samples."""
        tel = self.app.telemetry
        if params.get("summary") in ("1", "true"):
            from ..util.timeseries import summarize_samples
            return {"timeseries": {
                "epoch": tel.series.epoch,
                "period_s": tel.period_s,
                "summary": summarize_samples(tel.series.samples())}}
        limit = params.get("limit")
        doc = tel.series.to_doc(since=params.get("since"),
                                limit=int(limit) if limit else None)
        doc["period_s"] = tel.period_s
        return {"timeseries": doc}

    def _slo(self, params) -> dict:
        """SLO watchdog status (ops/slo.py): per-rule OK/WARN/BREACH
        verdict, last value vs threshold, breach tallies and the
        composite `overall` — evaluated continuously over the
        telemetry series, this route just reads the current state."""
        return {"slo": self.app.slo.status()}

    def _controller(self, params) -> dict:
        """Adaptive control plane (ops/controller.py): live knob
        values vs config, shed probabilities + per-gate drop tallies,
        the learned close-capacity estimate, and the decision-log
        tail. `controller?action=freeze` pins every knob/shed level
        as-is, `?action=reset` restores config knobs and zeroes the
        learned state (epoch rotates) — both gated behind
        ALLOW_CHAOS_INJECTION like the chaos/backendstatus actions: a
        production node must not accept control-plane overrides over
        HTTP. Plain status is always served; simulation/cluster.py
        polls it into CLUSTER artifacts."""
        ctl = self.app.controller
        action = params.get("action")
        if action:
            if not self.app.config.ALLOW_CHAOS_INJECTION:
                return {"exception": "controller actions disabled "
                        "(ALLOW_CHAOS_INJECTION)"}
            if action == "freeze":
                ctl.freeze()
            elif action == "reset":
                ctl.reset()
            else:
                return {"exception": f"unknown action: {action}"}
        return {"controller": ctl.status()}

    def _cluster_status(self, params) -> dict:
        """Structured per-node health/SLO snapshot (mesh observatory):
        one JSON document a cluster harness can collect from every
        node over HTTP and judge without scraping full metrics —
        ledger position, close latency, tx e2e quantiles, flood
        redundancy, peer accounting, breaker state, and a composite
        `healthy` verdict. ROADMAP item 4's multi-process simulation
        driver collects its per-node verdicts from exactly this."""
        from .application import _state_name
        from ..util.timeseries import timer_quantiles
        app = self.app
        lm = app.ledger_manager

        def timer_ms(name: str) -> dict:
            # the shared per-timer read discipline (util/timeseries.py
            # — the telemetry sampler reads the same shape)
            return timer_quantiles(app.metrics, name)

        peers = []
        drop_reasons = {}
        bad_sig = duplicates = 0
        if app.overlay_manager is not None:
            peers = app.overlay_manager.get_authenticated_peers()
            drop_reasons = dict(app.overlay_manager.drop_reasons)
            bad_sig = sum(p.bad_sig_drops for p in peers)
            duplicates = sum(p.duplicate_messages for p in peers)
        backend = None
        sup = getattr(app, "batch_verifier", None)
        if sup is not None and hasattr(sup, "breaker_state"):
            backend = {"state": sup.state,
                       "failures": sup.status()["failures"]}
        from ..crypto.strkey import StrKey
        out = {
            "node": StrKey.encode_ed25519_public(app.config.node_id())
            if app.config.NODE_SEED is not None else None,
            "label": app.flight_recorder.label or "node",
            "state": _state_name(app.state),
            "herder_state": app.herder.get_state().name,
            "ledger": {
                "num": lm.get_last_closed_ledger_num(),
                "hash": lm.get_last_closed_ledger_hash().hex(),
            },
            "close": timer_ms("ledger.ledger.close"),
            "tx_e2e": timer_ms("ledger.transaction.e2e"),
            "slot_phases": {
                p: timer_ms("scp.slot." + p)
                for p in ("nominate", "prepare", "confirm", "total")},
            "flood": app.propagation.report()
            if getattr(app, "propagation", None) is not None else {},
            "peers": {"authenticated": len(peers),
                      "drop_reasons": drop_reasons,
                      "bad_sig_drops": bad_sig,
                      "duplicates": duplicates},
            "backend": backend,
            "pending_txs": app.herder.tx_queue.size_txs(),
        }
        from .application import AppState
        out["healthy"] = bool(
            app.state == AppState.APP_SYNCED_STATE
            and (backend is None or backend["state"] == "CLOSED"))
        headers = params.get("headers")
        if headers:
            # clusterstatus?headers=A-B: per-seq header hashes for the
            # requested range, so the multi-process harness can judge
            # byte-identical honest-survivor chains over HTTP without
            # a second route (simulation/cluster.py verdicts)
            lo, _, hi = headers.partition("-")
            lo = max(2, int(lo))
            hi = int(hi) if hi else lm.get_last_closed_ledger_num()
            rows = app.database.query_all(
                "SELECT ledgerseq, ledgerhash FROM ledgerheaders "
                "WHERE ledgerseq BETWEEN ? AND ?", (lo, hi))
            out["headers"] = {str(seq): bytes(h).hex()
                              for seq, h in rows}
        return {"clusterstatus": out}


def _add_result_name(res: AddResult) -> str:
    # reference: CommandHandler formats TransactionQueue::AddResult
    return {
        AddResult.ADD_STATUS_PENDING: "PENDING",
        AddResult.ADD_STATUS_DUPLICATE: "DUPLICATE",
        AddResult.ADD_STATUS_ERROR: "ERROR",
        AddResult.ADD_STATUS_TRY_AGAIN_LATER: "TRY_AGAIN_LATER",
        AddResult.ADD_STATUS_FILTERED: "FILTERED",
    }[res]


def run_http_server(handler: CommandHandler, port: int,
                    public: bool = False,
                    max_client: int = 128,
                    clock=None) -> "threading.Thread":
    """Serve the admin API (reference: CommandHandler ctor binds libhttp
    on 127.0.0.1:HTTP_PORT unless PUBLIC_HTTP_PORT; HTTP_MAX_CLIENT
    bounds the accept backlog).

    With `clock` (the `run` command passes the app's VirtualClock),
    each request is POSTED onto the main crank loop and the socket
    thread waits for the result — the single-main-thread discipline
    the reference keeps by running libhttp on the main io_context.
    Without it (socketless tests, ad-hoc servers with their own crank
    arrangements) commands run directly on the handler thread, which
    is only safe while nothing cranks concurrently: the multi-process
    cluster harness found `generateload`'s LedgerTxn racing a
    concurrent close's trim_invalid ("parent already has an open child
    LedgerTxn") when dispatch stayed on the socket thread."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # thread-domain: http
            from ..util import threads
            if threads.CHECK:
                threads.bind("http")
            parsed = urlparse(self.path)
            command = parsed.path.strip("/")
            params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
            if clock is None:
                out = handler.handle(command, params)
            elif clock.stopped:
                # a job posted after clock.stop() would never run and
                # would pin this socket thread for the full timeout
                out = {"exception": "node is shutting down"}
            else:
                box: dict = {}
                done = threading.Event()

                def job():
                    try:
                        box["out"] = handler.handle(command, params)
                    finally:
                        done.set()

                clock.post(job)
                if not done.wait(30.0):
                    # the job stays queued: it may STILL execute once
                    # the loop unblocks — callers must not read this
                    # as "not executed" and retry a non-idempotent
                    # command
                    box.setdefault(
                        "out",
                        {"exception":
                         "main loop did not service the request "
                         "within 30s (the command may still execute; "
                         "do not blindly retry)"})
                out = box.get("out") or {
                    "exception": "request dispatch failed"}
            if isinstance(out, dict) and "_raw_body" in out:
                # non-JSON responses (Prometheus text exposition)
                body = out["_raw_body"].encode()
                ctype = out.get("_content_type", "text/plain")
            else:
                body = json.dumps(out).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet
            pass

    host = "" if public else "127.0.0.1"

    class _Server(ThreadingHTTPServer):
        request_queue_size = max(1, max_client)

    server = _Server((host, port), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.server = server  # type: ignore[attr-defined]
    thread.start()
    return thread
