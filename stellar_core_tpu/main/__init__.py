"""Application layer: Config, Application facade, admin API, node state.

Reference: src/main — SURVEY.md §1 layer 10.
"""

from .application import Application, AppState
from .config import Config, QuorumSetConfig, get_test_config
from .persistent_state import PersistentState, StateEntry

__all__ = [
    "Application", "AppState", "Config", "QuorumSetConfig",
    "get_test_config", "PersistentState", "StateEntry",
]
