"""Node configuration.

Reference: src/main/Config.{h,cpp} — a TOML file of ~130 flags parsed in
Config::load (Config.cpp:740-780). We implement the load path with the
stdlib ``tomllib`` and keep the reference's UPPER_SNAKE field names so
operator configs read the same. Node *roles* are derived MODE_* booleans
(Config.h:300-353) that offline commands and tests flip instead of forking
code paths.
"""

from __future__ import annotations

try:
    import tomllib
except ImportError:                                  # pragma: no cover
    # python < 3.11: gate the stdlib TOML parser; config-file loading
    # raises only if actually used without a parser available
    try:
        import tomli as tomllib
    except ImportError:
        tomllib = None
from typing import Dict, List, Optional

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256


class QuorumSetConfig:
    """Declarative quorum set: threshold + validators + inner sets
    (reference: Config.h QUORUM_SET, parsed in Config.cpp)."""

    def __init__(self, threshold: int = 0,
                 validators: Optional[List[bytes]] = None,
                 inner_sets: Optional[List["QuorumSetConfig"]] = None):
        self.threshold = threshold
        self.validators = validators or []
        self.inner_sets = inner_sets or []

    def to_scp_quorum_set(self):
        from ..xdr.scp import SCPQuorumSet
        from ..xdr.types import NodeID, PublicKey
        return SCPQuorumSet(
            threshold=self.threshold,
            validators=[PublicKey.ed25519(v) for v in self.validators],
            innerSets=[s.to_scp_quorum_set() for s in self.inner_sets])


class Config:
    # reference: Config.h field-for-field for the subset we support
    def __init__(self):
        # identity
        self.NETWORK_PASSPHRASE = "Standalone Network ; February 2017"
        self.NODE_SEED: Optional[SecretKey] = None
        self.NODE_IS_VALIDATOR = False
        self.NODE_HOME_DOMAIN = ""

        # modes (reference: RUN_STANDALONE Config.h:137, MANUAL_CLOSE :140)
        self.RUN_STANDALONE = False
        self.MANUAL_CLOSE = False
        # periodic self-check, seconds; 0 disables (reference:
        # AUTOMATIC_SELF_CHECK_PERIOD, ApplicationImpl.cpp:823-826)
        self.AUTOMATIC_SELF_CHECK_PERIOD = 0.0
        self.MODE_DOES_CATCHUP = True   # reference: Config.cpp:116
        # store tx/txfee/txset history tables (reference:
        # MODE_STORES_HISTORY_MISC, Config.h:339 — in-memory replay and
        # catchup utility modes turn this off)
        self.MODE_STORES_HISTORY_MISC = True
        self.FORCE_SCP = False

        # admin HTTP. In the `run` command, 0 binds an OS-assigned
        # ephemeral port (reported on stdout / the `info` route /
        # --port-file, so parallel harness nodes never collide) and a
        # negative value disables the server entirely.
        self.HTTP_PORT = 11626
        self.PUBLIC_HTTP_PORT = False

        # storage
        self.DATABASE = "sqlite3://:memory:"
        self.BUCKET_DIR_PATH: Optional[str] = None  # None = tmp dir

        # ledger
        self.LEDGER_PROTOCOL_VERSION = 21
        self.EXPECTED_LEDGER_CLOSE_TIME = 5.0
        self.MAX_TX_SET_SIZE = 1000  # ops (reference: TESTING default 100)

        # overlay
        self.PEER_PORT = 11625
        self.TARGET_PEER_CONNECTIONS = 8
        self.MAX_PENDING_CONNECTIONS = 500
        self.KNOWN_PEERS: List[str] = []
        self.PREFERRED_PEERS: List[str] = []
        self.MAX_ADVERT_CACHE_SIZE = 50000
        # advert-batch drain cadence (reference: FLOOD_ADVERT_PERIOD_MS,
        # Config.h — pull-mode adverts leave in batches on this timer)
        self.FLOOD_ADVERT_PERIOD_MS = 100
        # unanswered FLOOD_DEMANDs are re-demanded from a different
        # peer after this long (reference: FLOOD_DEMAND_PERIOD_MS +
        # TxDemandsManager retry backoff). 2000, not the reference's
        # 200: a demand here is answered on the advertiser's next
        # crank, and a crank busy with a ledger close parks for
        # seconds — at 200ms the TPSMT leg measured 45% of demands
        # "timing out" (35k spurious retries, ~10k duplicate bodies,
        # exactly the redundancy single-flight exists to kill); the
        # deadline must cover peer CRANK latency under load, not just
        # wire RTT (ISSUE 12)
        self.FLOOD_DEMAND_PERIOD_MS = 2000
        self.PEER_FLOOD_READING_CAPACITY = 200
        self.PEER_READING_CAPACITY = 201
        self.FLOW_CONTROL_SEND_MORE_BATCH_SIZE = 40
        self.PEER_FLOOD_READING_CAPACITY_BYTES = 300000
        self.FLOW_CONTROL_SEND_MORE_BATCH_SIZE_BYTES = 100000

        # consensus
        self.QUORUM_SET = QuorumSetConfig()
        self.UNSAFE_QUORUM = False
        self.QUORUM_INTERSECTION_CHECKER = True

        # herder/tx queue
        self.TRANSACTION_QUEUE_SIZE_MULTIPLIER = 2
        self.TRANSACTION_QUEUE_BAN_DEPTH = 10
        self.TRANSACTION_QUEUE_PENDING_DEPTH = 4

        # history archives: name -> {"get": tmpl, "put": tmpl, "mkdir": tmpl}
        self.HISTORY: Dict[str, Dict[str, str]] = {}
        self.CATCHUP_COMPLETE = False
        self.CATCHUP_RECENT = 0
        # streaming catchup pipeline (catchup/pipeline.py,
        # docs/CATCHUP.md): overlap download → verify → device
        # prevalidate → apply across checkpoints instead of replaying
        # them strictly one at a time; False keeps the sequential
        # CatchupWork reference path
        self.CATCHUP_PIPELINE = True
        # checkpoints the download stage may run ahead of apply
        self.CATCHUP_PIPELINE_AHEAD_CHECKPOINTS = 8
        # byte budget for downloaded-but-unapplied checkpoint files: a
        # fast archive over a slow apply parks the download stage here
        self.CATCHUP_PIPELINE_BYTE_BUDGET = 64 * 1024 * 1024
        # verified checkpoints ahead of apply the device prevalidation
        # stage may fuse into one coalesced signature batch
        self.CATCHUP_PIPELINE_PREVALIDATE_AHEAD = 4

        # upgrades this validator votes for (reference: Upgrades params
        # come via the `upgrades` admin endpoint; the TESTING_UPGRADE_*
        # config fields seed them for tests)
        self.TESTING_UPGRADE_LEDGER_PROTOCOL_VERSION: Optional[int] = None
        self.TESTING_UPGRADE_DESIRED_FEE: Optional[int] = None
        self.TESTING_UPGRADE_RESERVE: Optional[int] = None
        self.TESTING_UPGRADE_MAX_TX_SET_SIZE: Optional[int] = None

        # invariants (reference: INVARIANT_CHECKS, regex list)
        self.INVARIANT_CHECKS: List[str] = []

        # serve entry loads from bucket indexes instead of SQL
        # (reference: EXPERIMENTAL_BUCKETLIST_DB, bucket/readme.md:86-105)
        self.EXPERIMENTAL_BUCKETLIST_DB = False

        # artificial testing knobs (reference: Config.h:168-211)
        self.ARTIFICIALLY_GENERATE_LOAD_FOR_TESTING = False
        self.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = False
        self.ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING = 0
        # force every bucket merge to run synchronously on the calling
        # thread — the pessimal schedule (reference:
        # ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING)
        self.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING = False
        # honor the `chaos` admin route's install/clear modes
        # (util/chaos.py) — a production node must not accept fault
        # injection over HTTP, so this is off unless a test/staging
        # config opts in
        self.ALLOW_CHAOS_INJECTION = False
        # honor the `recordstart`/`recordstop`/`recorddump` admin
        # routes (replay/recorder.py) — recording captures every
        # inbound frame verbatim, so like chaos it is off unless a
        # test/staging config opts in
        self.ALLOW_INPUT_RECORDING = False
        # microseconds slept by an io-poller on EVERY clock crank —
        # models a slow main thread (reference:
        # ARTIFICIALLY_SLEEP_MAIN_THREAD_FOR_TESTING)
        self.ARTIFICIALLY_SLEEP_MAIN_THREAD_FOR_TESTING_US = 0
        # simulated per-transaction apply latency: durations (ms) drawn
        # by weight, deterministically rotated per applied tx
        # (reference: OP_APPLY_SLEEP_TIME_WEIGHT/_DURATION_FOR_TESTING,
        # ledger/LedgerManagerImpl.cpp:945-969)
        self.OP_APPLY_SLEEP_TIME_WEIGHT_FOR_TESTING: List[int] = []
        self.OP_APPLY_SLEEP_TIME_DURATION_FOR_TESTING: List[float] = []
        # conflict-staged parallel tx apply inside ledger close
        # (ledger/parallel_apply.py; the parallel apply phases of
        # SOSP 2019 §6): worker count, 0 = sequential apply. Results
        # are byte-identical either way — the knob trades close
        # latency against threads.
        self.APPLY_PARALLEL = 4
        # txsets below this size skip staging (setup outweighs overlap)
        self.APPLY_PARALLEL_MIN_TXS = 8

        # retention/maintenance tuning (reference:
        # AUTOMATIC_MAINTENANCE_PERIOD/_COUNT, Config.h)
        self.AUTOMATIC_MAINTENANCE_PERIOD = 3600.0
        self.AUTOMATIC_MAINTENANCE_COUNT = 50000
        # SCP slots kept in memory behind the LCL (reference:
        # MAX_SLOTS_TO_REMEMBER, Herder.h)
        self.MAX_SLOTS_TO_REMEMBER = 12

        # meta stream for downstream systems (reference:
        # METADATA_OUTPUT_STREAM — fd:N or file path; we support paths)
        self.METADATA_OUTPUT_STREAM = ""
        # rotated LedgerCloseMeta debug files under
        # <bucket-dir>/meta-debug, 0 = off (reference:
        # METADATA_DEBUG_LEDGERS, Config.h:422)
        self.METADATA_DEBUG_LEDGERS = 0

        # emit (off-consensus) soroban diagnostic events into V3 meta
        # (reference: ENABLE_SOROBAN_DIAGNOSTIC_EVENTS, Config.h:571)
        self.ENABLE_SOROBAN_DIAGNOSTIC_EVENTS = False

        # ---- tranche 3 (round 5) ----
        # eviction/archival genesis overrides (reference: Config.h
        # OVERRIDE_EVICTION_PARAMS_FOR_TESTING + TESTING_* fields —
        # applied to the StateArchivalSettings entry at creation)
        self.OVERRIDE_EVICTION_PARAMS_FOR_TESTING = False
        self.TESTING_EVICTION_SCAN_SIZE = 1000
        self.TESTING_MAX_ENTRIES_TO_ARCHIVE = 100
        self.TESTING_MINIMUM_PERSISTENT_ENTRY_LIFETIME = 16
        self.TESTING_STARTING_EVICTION_SCAN_LEVEL = 1

        # tx queue: at most ONE pending tx per source account
        # (reference: LIMIT_TX_QUEUE_SOURCE_ACCOUNT)
        self.LIMIT_TX_QUEUE_SOURCE_ACCOUNT = False

        # rate-limited tx flooding, per lane (reference:
        # FLOOD_TX_PERIOD_MS / FLOOD_OP_RATE_PER_LEDGER and the soroban
        # twins — accepted txs advert in budgeted batches per period;
        # period 0 = advert immediately)
        self.FLOOD_TX_PERIOD_MS = 0
        self.FLOOD_OP_RATE_PER_LEDGER = 2.0
        self.FLOOD_SOROBAN_TX_PERIOD_MS = 0
        self.FLOOD_SOROBAN_RATE_PER_LEDGER = 2.0
        # outbound queue cap for TRANSACTION messages per peer, bytes;
        # oldest dropped first (reference: OUTBOUND_TX_QUEUE_BYTE_LIMIT)
        self.OUTBOUND_TX_QUEUE_BYTE_LIMIT = 1024 * 3200
        # total per-peer outbound queue byte budget across ALL flooded
        # classes (ISSUE 20 backpressure): past it, the lowest drop-
        # priority class sheds first (gossip, then tx, SCP last and
        # only to newer SCP) so a slow or partitioned peer can never
        # balloon a healthy node's memory. 0 disables the budget.
        self.OUTBOUND_QUEUE_BYTE_LIMIT = 1024 * 4096

        # ledger/db tuning (reference: ENTRY_CACHE_SIZE,
        # PREFETCH_BATCH_SIZE, MAX_BATCH_WRITE_COUNT/_BYTES)
        self.ENTRY_CACHE_SIZE = 4096
        self.PREFETCH_BATCH_SIZE = 1000
        self.MAX_BATCH_WRITE_COUNT = 1024
        self.MAX_BATCH_WRITE_BYTES = 1024 * 1024
        # abort the process instead of failing the tx on internal apply
        # errors (reference: HALT_ON_INTERNAL_TRANSACTION_ERROR)
        self.HALT_ON_INTERNAL_TRANSACTION_ERROR = False
        # dict-backed ledger root, no per-entry SQL (reference:
        # MODE_USES_IN_MEMORY_LEDGER — in-memory replay/catchup modes)
        self.MODE_USES_IN_MEMORY_LEDGER = False

        # bucket subsystem (reference: DISABLE_BUCKET_GC,
        # DISABLE_XDR_FSYNC, ARTIFICIALLY_REDUCE_MERGE_COUNTS_FOR_TESTING,
        # CATCHUP_WAIT_MERGES_TX_APPLY_FOR_TESTING)
        self.DISABLE_BUCKET_GC = False
        self.DISABLE_XDR_FSYNC = False
        self.ARTIFICIALLY_REDUCE_MERGE_COUNTS_FOR_TESTING = False
        self.CATCHUP_WAIT_MERGES_TX_APPLY_FOR_TESTING = False

        # overlay/http/ops (reference: HTTP_MAX_CLIENT,
        # PREFERRED_PEERS_ONLY, MAX_ADDITIONAL_PEER_CONNECTIONS,
        # ALLOW_LOCALHOST_FOR_TESTING, MODE_AUTO_STARTS_OVERLAY,
        # PUBLISH_TO_ARCHIVE_DELAY, HISTOGRAM_WINDOW_SIZE,
        # LOG_FILE_PATH, LOG_COLOR)
        self.HTTP_MAX_CLIENT = 128
        self.PREFERRED_PEERS_ONLY = False
        # inbound slots on top of the outbound target; None = the
        # reference's "auto" (8x TARGET_PEER_CONNECTIONS, derived at
        # use time so a later TARGET change is honored)
        self.MAX_ADDITIONAL_PEER_CONNECTIONS: Optional[int] = None
        self.ALLOW_LOCALHOST_FOR_TESTING = False
        self.MODE_AUTO_STARTS_OVERLAY = True
        self.PUBLISH_TO_ARCHIVE_DELAY = 0.0
        self.HISTOGRAM_WINDOW_SIZE = 5
        self.LOG_FILE_PATH = ""
        self.LOG_COLOR = False

        # ---- tranche 4 (round 5) ----
        # subprocess concurrency bound (reference:
        # MAX_CONCURRENT_SUBPROCESSES)
        self.MAX_CONCURRENT_SUBPROCESSES = 16
        # store ledger headers (off in throwaway replay modes;
        # reference: MODE_STORES_HISTORY_LEDGERHEADERS)
        self.MODE_STORES_HISTORY_LEDGERHEADERS = True
        # per-bucket sleep during bucket-apply catchup, seconds
        # (reference: ARTIFICIALLY_DELAY_BUCKET_APPLICATION_FOR_TESTING)
        self.ARTIFICIALLY_DELAY_BUCKET_APPLICATION_FOR_TESTING = 0.0
        # overlay tick stops topping up outbound connections
        # (reference: ARTIFICIALLY_SKIP_CONNECTION_ADJUSTMENT_FOR_TESTING)
        self.ARTIFICIALLY_SKIP_CONNECTION_ADJUSTMENT_FOR_TESTING = False
        # BucketIndex tuning (reference:
        # EXPERIMENTAL_BUCKETLIST_DB_INDEX_CUTOFF (MB) /
        # _INDEX_PAGE_SIZE_EXPONENT)
        self.EXPERIMENTAL_BUCKETLIST_DB_INDEX_CUTOFF = 20
        self.EXPERIMENTAL_BUCKETLIST_DB_INDEX_PAGE_SIZE_EXPONENT = 14
        # overlay protocol window advertised in HELLO (reference:
        # OVERLAY_PROTOCOL_VERSION / OVERLAY_PROTOCOL_MIN_VERSION)
        self.OVERLAY_PROTOCOL_VERSION = 29
        self.OVERLAY_PROTOCOL_MIN_VERSION = 27
        # header-flags upgrade vote (reference: TESTING_UPGRADE_FLAGS)
        self.TESTING_UPGRADE_FLAGS: Optional[int] = None
        # byte-level flow control off = message counts only (reference:
        # ENABLE_FLOW_CONTROL_BYTES). NETWORK-WIDE setting: senders stop
        # honoring byte budgets, so a mixed network drops bytes-off
        # peers as protocol violators — exactly as in the reference
        self.ENABLE_FLOW_CONTROL_BYTES = True
        # version string advertised in HELLO (reference: VERSION_STR)
        self.VERSION_STR = ""            # "" = built-in default
        # genesis takes protocol + soroban settings from this config;
        # off = protocol-0 genesis, upgrades voted in (reference:
        # USE_CONFIG_FOR_GENESIS)
        self.USE_CONFIG_FOR_GENESIS = True
        # report/halt on internal tx errors only from this protocol on
        # (reference: LEDGER_PROTOCOL_MIN_VERSION_INTERNAL_ERROR_REPORT)
        self.LEDGER_PROTOCOL_MIN_VERSION_INTERNAL_ERROR_REPORT = 0
        # genesis soroban settings get loadgen-scale limits (reference:
        # TESTING_SOROBAN_HIGH_LIMIT_OVERRIDE)
        self.TESTING_SOROBAN_HIGH_LIMIT_OVERRIDE = False
        # meta stream runs one ledger behind the LCL (reference:
        # EXPERIMENTAL_PRECAUTION_DELAY_META)
        self.EXPERIMENTAL_PRECAUTION_DELAY_META = False
        # merges always run at the newest bucket protocol (reference:
        # ARTIFICIALLY_REPLAY_WITH_NEWEST_BUCKET_LOGIC_FOR_TESTING)
        self.ARTIFICIALLY_REPLAY_WITH_NEWEST_BUCKET_LOGIC_FOR_TESTING = \
            False
        # extra wait before each unanswered-demand retry, ms (reference:
        # FLOOD_DEMAND_BACKOFF_DELAY_MS)
        self.FLOOD_DEMAND_BACKOFF_DELAY_MS = 500
        # persist bucket indexes beside the bucket files (reference:
        # EXPERIMENTAL_BUCKETLIST_DB_PERSIST_INDEX)
        self.EXPERIMENTAL_BUCKETLIST_DB_PERSIST_INDEX = False
        # cross-check every indexed best-offer lookup against a full
        # scan (reference: BEST_OFFER_DEBUGGING_ENABLED)
        self.BEST_OFFER_DEBUGGING_ENABLED = False

        # crypto backend (our addition, SURVEY.md §5.6)
        self.SIGNATURE_VERIFY_BACKEND = "native"  # native|python|tpu
        # device topology for the tpu backend: auto = sharded dp mesh
        # whenever more than one device is visible, single chip otherwise
        # (SURVEY.md §2.3/§5.8; ops/verifier.py, ops/multihost.py)
        self.SIGNATURE_VERIFY_MESH = "auto"  # auto|single|sharded|hybrid
        # coalescing verify service (ops/verify_service.py; engaged with
        # the tpu backend): live-path signature verifies queue until the
        # batch reaches VERIFY_MAX_BATCH tuples or the oldest waits
        # VERIFY_BATCH_DEADLINE_MS, then dispatch as one device batch
        self.VERIFY_BATCH_DEADLINE_MS = 2.0
        self.VERIFY_MAX_BATCH = 256
        # flushes below this many signatures run native per-signature —
        # the fixed device dispatch cost loses to the host verifier
        # there (bench.py --min-batch records the measured crossover;
        # VERIFY_DEVICE_MIN_BATCH=<n> in the environment overrides)
        self.VERIFY_DEVICE_MIN_BATCH = 16

        # device-backend supervisor (ops/backend_supervisor.py): the
        # PER-DEVICE circuit-breaker array + hung-dispatch watchdog
        # wrapped around the tpu backend (docs/ROBUSTNESS.md). The
        # knobs apply to each device's breaker: a sick chip trips
        # alone and the verify mesh shrinks around it; native
        # fallback engages only when every device is down. Trip a
        # device OPEN after this many consecutive dispatch failures
        # attributed to it (fatal errors trip immediately)
        self.VERIFY_BREAKER_FAILURE_THRESHOLD = 3
        # a device collect handle that hasn't produced results after
        # this long is quarantined; the flush resolves through native
        # verify and the breaker records a timeout-class failure
        self.VERIFY_DISPATCH_DEADLINE_MS = 2000.0
        # HALF_OPEN canary re-probe backoff: base doubles per failed
        # probe up to max, with deterministic per-node jitter
        self.VERIFY_BREAKER_PROBE_BASE_MS = 1000.0
        self.VERIFY_BREAKER_PROBE_MAX_MS = 30000.0
        # canary batch size: at least VERIFY_DEVICE_MIN_BATCH or the
        # probe exercises only the host bypass, not the device
        self.VERIFY_BREAKER_CANARY_BATCH = 16

        # telemetry time-series (util/timeseries.py): a bounded ring
        # of periodic health snapshots (close/tx-e2e/slot quantiles,
        # verify occupancy + queue depth, breaker state, flood
        # duplicate ratio, dispatch batch/padding, host loadavg),
        # sampled every TELEMETRY_SAMPLE_PERIOD seconds on the app
        # clock (VirtualClock in sims, wall clock in `run`). 0 leaves
        # the recurring timer unarmed — sample_now() still works, the
        # opt-in tests and manual-close benches use. Scraped over the
        # `timeseries` route with the since=<cursor> contract.
        self.TELEMETRY_SAMPLE_PERIOD = 1.0
        self.TELEMETRY_RING_CAPACITY = 600
        # SLO watchdog (ops/slo.py) thresholds, evaluated per sample:
        # close p99 / tx-e2e p99 ceilings (ms), how long the device
        # breaker may sit OPEN before degraded mode counts as a breach
        # (s), and the flood-redundancy ceiling (duplicate deliveries
        # per unique message). Verdicts ride slo.* counters, trace
        # instants, and the `slo` admin route.
        self.SLO_CLOSE_P99_MS = 5000.0
        self.SLO_TX_E2E_P99_MS = 15000.0
        self.SLO_BREAKER_OPEN_DWELL_S = 10.0
        self.SLO_DUPLICATE_RATIO_MAX = 8.0
        # read-tier ceiling: query.read.latency p99 (ms) — the read
        # path degrades (sheds) before the write path ever does
        self.SLO_READ_P99_MS = 100.0

        # read-serving tier (query/): worker pool size, bounded
        # admission queue depth, per-request deadline, and the floor on
        # the hedged-second-lookup trigger (the hedge normally fires at
        # the rolling p95 read latency; the floor stops hedge storms
        # while the estimate is still cold). Tx-status ring: capacity in
        # transactions and the TTL (s) against ledger close time.
        self.QUERY_WORKER_THREADS = 4
        self.QUERY_QUEUE_LIMIT = 512
        self.QUERY_DEADLINE_MS = 250.0
        self.QUERY_HEDGE_MIN_MS = 5.0
        self.QUERY_TX_STATUS_CAPACITY = 65536
        self.QUERY_TX_STATUS_TTL = 600.0

        # adaptive control plane (ops/controller.py): a recurring
        # tick on the app clock reads the newest telemetry sample and
        # (a) AIMD-searches the three VERIFY_* batch knobs above from
        # measured occupancy + queue-wait p99, (b) ramps tx-submit /
        # flood-admission shed probabilities from the SLO watchdog's
        # WARN/BREACH verdicts plus a learned-backlog surge gate.
        # 0 leaves the timer unarmed — tick() still works, which is
        # how the surge bench and virtual-time tests drive
        # deterministic control steps (the TELEMETRY_SAMPLE_PERIOD
        # discipline). Frozen/reset over the `controller` admin route.
        self.CONTROLLER_TICK_PERIOD = 1.0
        # AIMD step sizes: additive max-batch probe / multiplicative
        # deadline+batch back-off / deadline stretch toward device
        # profitability (Clipper's adaptive batch search, PAPERS.md)
        self.CONTROLLER_AIMD_INCREASE = 16
        self.CONTROLLER_AIMD_DECREASE = 0.5
        self.CONTROLLER_DEADLINE_GROW = 1.25
        # the latency objective the batch search holds: verify-service
        # submit→dispatch wait p99 (ms)
        self.CONTROLLER_QUEUE_WAIT_TARGET_MS = 5.0
        # shed ladder: WARN ramps tx-submit by SHED_STEP, BREACH ramps
        # tx by 2x and flood by 1x; OK decays both by SHED_DECAY; both
        # probabilities cap at SHED_MAX (never a full blackout — some
        # load must keep flowing so recovery is observable)
        self.CONTROLLER_SHED_STEP = 0.2
        self.CONTROLLER_SHED_DECAY = 0.1
        self.CONTROLLER_SHED_MAX = 0.95
        # surge gate: slam the tx-submit shed to SHED_MAX when the
        # pending queue exceeds what would close inside
        # SLO_CLOSE_P99_MS x this factor at the learned per-tx cost
        self.CONTROLLER_BACKLOG_FACTOR = 0.4

        # drop a peer once this many of its transactions failed
        # signature verification (overlay/manager.py): a bad-sig
        # flooder burns device verify batches on work that can never
        # apply — past the threshold it goes through the standard drop
        # path and stops monopolizing batch admission. 0 disables.
        # Counted on the batched-admission path (the verify service
        # path a flooder actually attacks).
        self.PEER_BAD_SIG_DROP_THRESHOLD = 100

        # overlay socket deadlines (overlay/tcp_peer.py): a black-holed
        # peer must not pin a connection slot forever. Transport must
        # carry a first byte within PEER_CONNECT_TIMEOUT of dialing;
        # the handshake must reach GOT_AUTH within
        # PEER_AUTHENTICATION_TIMEOUT of transport establishment
        # (reference: PEER_AUTHENTICATION_TIMEOUT, Config.h); an
        # authenticated peer silent for PEER_TIMEOUT is dropped
        # (reference: PEER_TIMEOUT). Seconds; 0 disables that check.
        self.PEER_CONNECT_TIMEOUT = 5.0
        self.PEER_AUTHENTICATION_TIMEOUT = 2.0
        self.PEER_TIMEOUT = 30.0

        # how long a failed/ineffective catchup (target, lcl) attempt
        # suppresses an identical retry (catchup/manager.py) — long
        # enough for the archive to publish a new checkpoint. Each
        # node jitters its own window (+0..25%, seeded by node id) so
        # simultaneously out-of-sync nodes don't hammer the archive in
        # lockstep (Tail-at-Scale retry decorrelation, PAPERS.md)
        self.RETRY_SUPPRESSION_SECONDS = 300.0

        # worker threads
        self.WORKER_THREADS = 4

        # lazily drawn per-process seed for watcher nodes (no
        # NODE_SEED) — see jitter_seed()
        self._fallback_jitter_seed = None

    # ------------------------------------------------------------- derived --
    def network_id(self) -> bytes:
        """networkID = SHA256(passphrase) (reference:
        main/ApplicationImpl.cpp networkID())."""
        return sha256(self.NETWORK_PASSPHRASE.encode())

    def node_id(self) -> bytes:
        assert self.NODE_SEED is not None
        return self.NODE_SEED.public_key().raw

    def jitter_seed(self) -> int:
        """Per-node seed for decorrelation jitter (breaker probe
        backoff, catchup retry suppression): stable for one node — the
        chaos repro contract — and decorrelated across nodes. Watcher
        nodes (no NODE_SEED) get a per-process random seed drawn once:
        a constant fallback would make every watcher jitter in
        lockstep, defeating the retry decorrelation entirely."""
        if self.NODE_SEED is None:
            if self._fallback_jitter_seed is None:
                import os
                self._fallback_jitter_seed = int.from_bytes(
                    os.urandom(8), "little")
            return self._fallback_jitter_seed
        return int.from_bytes(self.node_id()[:8], "little")

    def mode_stores_history(self) -> bool:
        return bool(self.HISTORY)

    # Node-role booleans (reference: Config MODE_* flags,
    # main/Config.h:300-353 — offline commands and tests flip these
    # instead of forking code paths). Only roles with real behavior in
    # this build are modeled: the bucket list is always on, and
    # in-memory mode is is_in_memory_mode().
    def mode_does_catchup(self) -> bool:
        # reference default: true everywhere; offline commands flip the
        # attribute off (Config.cpp:116, CommandLine.cpp:1001)
        return self.MODE_DOES_CATCHUP

    def max_inbound_peer_connections(self) -> int:
        """reference: MAX_ADDITIONAL_PEER_CONNECTIONS "auto" derives
        from the outbound target."""
        if self.MAX_ADDITIONAL_PEER_CONNECTIONS is not None:
            return self.MAX_ADDITIONAL_PEER_CONNECTIONS
        return 8 * self.TARGET_PEER_CONNECTIONS

    def mode_auto_starts_overlay(self) -> bool:
        # reference: MODE_AUTO_STARTS_OVERLAY (off in offline/utility
        # modes even when not standalone)
        return self.MODE_AUTO_STARTS_OVERLAY and not self.RUN_STANDALONE

    def is_in_memory_mode(self) -> bool:
        return self.DATABASE == "sqlite3://:memory:"

    def database_path(self) -> str:
        if self.DATABASE.startswith("sqlite3://"):
            return self.DATABASE[len("sqlite3://"):]
        raise ValueError(f"unsupported DATABASE: {self.DATABASE}")

    # -------------------------------------------------------------- loading --
    @classmethod
    def load(cls, path: str) -> "Config":
        if tomllib is None:
            raise RuntimeError(
                "no TOML parser available (python>=3.11 or the tomli "
                "package is required to load config files)")
        with open(path, "rb") as f:
            doc = tomllib.load(f)
        return cls.from_dict(doc)

    @classmethod
    def from_dict(cls, doc: dict) -> "Config":
        cfg = cls()
        for key, val in doc.items():
            if key == "NODE_SEED":
                cfg.NODE_SEED = _parse_node_seed(val)
            elif key == "QUORUM_SET":
                cfg.QUORUM_SET = _parse_quorum_set(val)
            elif key == "HISTORY":
                cfg.HISTORY = {name: dict(cmds) for name, cmds in val.items()}
            elif hasattr(cfg, key):
                setattr(cfg, key, val)
            else:
                raise ValueError(f"unknown config key: {key}")
        if cfg.NODE_IS_VALIDATOR and cfg.NODE_SEED is None:
            raise ValueError("NODE_IS_VALIDATOR requires NODE_SEED")
        return cfg


def _parse_node_seed(val: str) -> SecretKey:
    from ..crypto.strkey import StrKey
    # "SXXX... self" form from the reference example configs
    seed = val.split()[0]
    return SecretKey.from_seed(StrKey.decode_ed25519_seed(seed))


def _parse_quorum_set(doc: dict) -> QuorumSetConfig:
    from ..crypto.strkey import StrKey
    validators = [StrKey.decode_ed25519_public(v.split()[0])
                  for v in doc.get("VALIDATORS", [])]
    inner = [_parse_quorum_set(s) for s in doc.get("INNER_SETS", [])]
    threshold = doc.get("THRESHOLD",
                        doc.get("THRESHOLD_PERCENT", 0))
    if "THRESHOLD_PERCENT" in doc and "THRESHOLD" not in doc:
        n = len(validators) + len(inner)
        threshold = max(1, (doc["THRESHOLD_PERCENT"] * n + 99) // 100)
    return QuorumSetConfig(threshold, validators, inner)


_test_instance_counter = [0]


def get_test_config(instance: Optional[int] = None,
                    in_memory: bool = True) -> Config:
    """Per-instance test config (reference: test/test.h getTestConfig):
    distinct ports, deterministic per-instance node seed, in-memory
    sqlite, manual close standalone mode."""
    if instance is None:
        instance = _test_instance_counter[0]
        _test_instance_counter[0] += 1
    cfg = Config()
    cfg.RUN_STANDALONE = True
    cfg.MANUAL_CLOSE = True
    cfg.NODE_IS_VALIDATOR = True
    cfg.FORCE_SCP = True
    # tests never call the `run` command, which is the only place the
    # HTTP server starts (0 there now means "bind an ephemeral port" —
    # the cluster harness semantics; a negative value disables)
    cfg.HTTP_PORT = 0
    cfg.ALLOW_CHAOS_INJECTION = True
    cfg.ALLOW_INPUT_RECORDING = True
    # virtual-time tests step timer-to-timer; the hourly maintenance
    # timer would let idle cranks leap an hour, so tests opt in
    cfg.AUTOMATIC_MAINTENANCE_PERIOD = 0.0
    # same discipline for the telemetry sampler: a recurring 1 s timer
    # on every test app's clock heap would keep idle crank_until loops
    # stepping to their timeout instead of exiting on an empty heap —
    # tests (and the manual-close benches) drive sample_now() or opt
    # in per scenario; `run`-mode nodes keep the production default
    cfg.TELEMETRY_SAMPLE_PERIOD = 0.0
    # the adaptive controller's recurring tick too: tests drive
    # controller.tick() manually where a scenario wants the loop
    cfg.CONTROLLER_TICK_PERIOD = 0.0
    cfg.PEER_PORT = 32000 + 2 * instance
    cfg.NETWORK_PASSPHRASE = "(V) (;,,;) (V)"  # reference test passphrase
    cfg.NODE_SEED = SecretKey.from_seed(
        sha256(b"test-node-seed-%d" % instance))
    cfg.QUORUM_SET = QuorumSetConfig(
        threshold=1, validators=[cfg.node_id()])
    cfg.UNSAFE_QUORUM = True
    cfg.MAX_TX_SET_SIZE = 100
    cfg.INVARIANT_CHECKS = [".*"]
    # tests dial 127.0.0.1 freely (reference: getTestConfig sets this)
    cfg.ALLOW_LOCALHOST_FOR_TESTING = True
    # reference: getTestConfig disables XDR fsync (production keeps it)
    cfg.DISABLE_XDR_FSYNC = True
    return cfg
