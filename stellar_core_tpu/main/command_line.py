"""CLI subcommands.

Reference: src/main/CommandLine.cpp (subcommand list :1638-1698). We
implement the operator-facing core with argparse: run, new-db, gen-seed,
sec-to-pub, convert-id, version, http-command, offline-info, print-xdr,
sign-transaction, manualclose helpers arrive with their subsystems.
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
from typing import List, Optional

from ..crypto.keys import SecretKey
from ..crypto.strkey import StrKey
from .config import Config

VERSION = "stellar-core-tpu 0.1.0"


def _load_config(args) -> Config:
    if args.conf:
        return Config.load(args.conf)
    return Config()


def cmd_version(args) -> int:
    print(VERSION)
    # XDR identity, as the reference prints its .x hashes in `version`
    from ..xdr.schema import identity
    for build, h in identity().items():
        print(f"xdr ({build}): {h}")
    return 0


def cmd_gen_seed(args) -> int:
    """reference: runGenSeed — print a fresh keypair."""
    import os
    sk = SecretKey.from_seed(os.urandom(32))
    print("Secret seed:", StrKey.encode_ed25519_seed(sk.seed))
    print("Public:", StrKey.encode_ed25519_public(sk.public_key().raw))
    return 0


def cmd_sec_to_pub(args) -> int:
    """reference: runSecToPub — seed on stdin → public key."""
    seed = input().strip()
    sk = SecretKey.from_seed(StrKey.decode_ed25519_seed(seed))
    print(StrKey.encode_ed25519_public(sk.public_key().raw))
    return 0


def cmd_convert_id(args) -> int:
    """reference: runConvertId — show every representation of a key."""
    s = args.id
    try:
        raw = StrKey.decode_ed25519_public(s)
        print(json.dumps({"strkey": s, "hex": raw.hex()}))
        return 0
    except Exception:
        pass
    raw = bytes.fromhex(s)
    print(json.dumps({"strkey": StrKey.encode_ed25519_public(raw),
                      "hex": s}))
    return 0


def cmd_new_db(args) -> int:
    """reference: runNewDB — initialize the database schema."""
    from ..db.database import create_database
    cfg = _load_config(args)
    db = create_database(cfg)
    db.initialize()
    db.close()
    print("database initialized")
    return 0


def cmd_run(args) -> int:
    """reference: runWithHelp → ApplicationUtils::runApp :274."""
    import os
    import signal

    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    from .command_handler import run_http_server

    cfg = _load_config(args)
    if cfg.LOG_FILE_PATH or cfg.LOG_COLOR:
        # before Application.create: startup (schema upgrade, bucket
        # adoption, catchup decisions) must reach the log file too
        from ..util.logging import init_logging
        init_logging(args.ll, log_file_path=cfg.LOG_FILE_PATH,
                     color=cfg.LOG_COLOR)
    clock = VirtualClock(ClockMode.REAL_TIME)
    app = Application.create(clock, cfg, new_db=args.new_db)
    app.start()
    http_thread = None
    if cfg.HTTP_PORT >= 0:
        # HTTP_PORT=0 binds an OS-assigned ephemeral port so parallel
        # harness nodes never collide; the actual bound port is
        # reported on stdout, on the `info` route, and (for a spawning
        # harness that can't parse stdout races) via --port-file
        http_thread = run_http_server(app.command_handler, cfg.HTTP_PORT,
                                      cfg.PUBLIC_HTTP_PORT,
                                      max_client=cfg.HTTP_MAX_CLIENT,
                                      clock=clock)
        bound_port = http_thread.server.server_address[1]
        app.http_port = bound_port
        print(f"HTTP port: {bound_port}", flush=True)
        if args.port_file:
            # write-then-rename: a poller must never read a torn file
            tmp = args.port_file + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(bound_port))
            os.replace(tmp, args.port_file)
    # graceful SIGTERM: stop the crank loop so the finally-block
    # shutdown drains the deferred-completion queue and flushes the
    # flight recorder — harness teardown loses no tx-history/meta
    # tails. (kill -9 churn bypasses this by design: a real kill must
    # still lose the non-durable tails.)
    signal.signal(signal.SIGTERM, lambda *_: clock.stop())
    try:
        while not clock.stopped:
            app.crank(block=True)
    except KeyboardInterrupt:
        pass
    finally:
        if http_thread is not None:
            http_thread.server.shutdown()
        app.shutdown()
    return 0


def cmd_catchup(args) -> int:
    """reference: runCatchup — offline catchup from configured
    archives: `catchup <to>/<count>` (count currently ignored: full
    replay to <to>)."""
    from ..catchup import CatchupConfiguration, CatchupWork
    from ..history.archive import HistoryArchive
    from ..util.timer import ClockMode, VirtualClock
    from ..work import State, run_work_to_completion
    from .application import Application

    cfg = _load_config(args)
    to_ledger = int(args.destination.split("/")[0]) \
        if args.destination != "current" else 0
    clock = VirtualClock(ClockMode.REAL_TIME)
    app = Application.create(clock, cfg, new_db=args.new_db)
    app.start()
    try:
        if not app.history_manager.archives:
            print("no history archives configured")
            return 1
        archive = next(a for a in app.history_manager.archives
                       if a.has_get())
        work = CatchupWork(app, archive,
                           CatchupConfiguration(to_ledger=to_ledger))
        state = run_work_to_completion(app, work, timeout_virtual=86400)
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        print(f"catchup {state.name}, LCL {lcl}")
        return 0 if state == State.WORK_SUCCESS else 1
    finally:
        app.shutdown()
    return 0


def cmd_publish(args) -> int:
    """reference: runPublish — flush the publish queue."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.REAL_TIME), cfg,
                             new_db=False)
    app.start()
    try:
        n = app.history_manager.publish_queued_history()
        print(f"published {n} checkpoints")
        return 0
    finally:
        app.shutdown()


def cmd_self_check(args) -> int:
    """reference: runSelfCheck (main/ApplicationUtils.cpp:487-517)."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    from .self_check import self_check
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.REAL_TIME), cfg,
                             new_db=False)
    app.start()
    try:
        ok, report = self_check(app)
        print(json.dumps(report, indent=2))
        return 0 if ok else 1
    finally:
        app.shutdown()


def cmd_http_command(args) -> int:
    """reference: runHttpCommand — send a command to a running node."""
    import urllib.request
    cfg = _load_config(args)
    url = f"http://127.0.0.1:{cfg.HTTP_PORT}/{args.command}"
    with urllib.request.urlopen(url) as resp:
        print(resp.read().decode())
    return 0


def cmd_print_xdr(args) -> int:
    """reference: dumpXdrStream/printXdr — decode one XDR file to json."""
    from ..xdr import transaction as txxdr, ledger as ledgerxdr
    types = {
        "TransactionEnvelope": txxdr.TransactionEnvelope,
        "LedgerHeader": ledgerxdr.LedgerHeader,
        "TransactionSet": ledgerxdr.TransactionSet,
    }
    cls = types.get(args.filetype)
    if cls is None:
        print(f"unsupported filetype {args.filetype}", file=sys.stderr)
        return 1
    with open(args.file, "rb") as f:
        data = f.read()
    if args.base64:
        data = base64.b64decode(data)
    obj = cls.from_bytes(data)
    print(obj)
    return 0


def cmd_encode_asset(args) -> int:
    """reference: runEncodeAsset (CommandLine.cpp:1059-1090) — print a
    base64-encoded XDR Asset."""
    from ..crypto.strkey import StrKey
    from ..xdr.ledger_entries import Asset
    from ..xdr.types import PublicKey
    code, issuer = args.code, args.issuer
    if not code and not issuer:
        asset = Asset.native()
    elif not code or not issuer:
        print("If one of code or issuer is defined, the other must be "
              "defined", file=sys.stderr)
        return 1
    else:
        if len(code) > 12:
            print("asset code too long (max 12)", file=sys.stderr)
            return 1
        raw = StrKey.decode_ed25519_public(issuer)
        asset = Asset.credit(code.encode(), PublicKey.ed25519(raw))
    print(base64.b64encode(asset.to_bytes()).decode())
    return 0


def cmd_sign_transaction(args) -> int:
    """reference: signtxn (main/dumpxdr.cpp:377-460) — append a
    signature to a TransactionEnvelope and print it."""
    from ..crypto.keys import SecretKey
    from ..crypto.sha import sha256
    from ..crypto.strkey import StrKey
    from ..xdr.transaction import (DecoratedSignature, EnvelopeType,
                                   TransactionEnvelope,
                                   TransactionSignaturePayload,
                                   _TaggedTransaction)
    with open(args.file, "rb") as f:
        data = f.read()
    if args.base64:
        data = base64.b64decode(data)
    env = TransactionEnvelope.from_bytes(data)

    seed = args.seed
    if seed is None:
        seed = sys.stdin.readline().strip()
    sk = SecretKey.from_seed(StrKey.decode_ed25519_seed(seed))

    network_id = sha256(args.netid.encode())
    if env.disc == EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
        tagged = _TaggedTransaction(
            EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, env.value.tx)
        sigs = env.value.signatures
    elif env.disc == EnvelopeType.ENVELOPE_TYPE_TX:
        tagged = _TaggedTransaction(
            EnvelopeType.ENVELOPE_TYPE_TX, env.value.tx)
        sigs = env.value.signatures
    else:
        print("unsupported envelope type", file=sys.stderr)
        return 1
    payload = TransactionSignaturePayload(
        networkId=network_id, taggedTransaction=tagged)
    h = sha256(payload.to_bytes())
    pub = sk.public_key().raw
    sigs.append(DecoratedSignature(hint=pub[-4:], signature=sk.sign(h)))
    out = env.to_bytes()
    if args.base64:
        print(base64.b64encode(out).decode())
    else:
        sys.stdout.buffer.write(out)
    return 0


def cmd_offline_info(args) -> int:
    """reference: runOfflineInfo — print the info JSON without running
    the node."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        app.ledger_manager.load_last_known_ledger()
        print(json.dumps(app.info(), indent=2))
        return 0
    finally:
        app.shutdown()


def cmd_dump_ledger(args) -> int:
    """reference: dumpLedger (main/ApplicationUtils.cpp:549-640) —
    dump/aggregate the current ledger state from the bucket list,
    filtered by an xdrquery expression."""
    from ..util.timer import ClockMode, VirtualClock
    from ..util.xdrquery import (XDRAccumulator, XDRFieldExtractor,
                                 XDRMatcher)
    from ..xdr.json_repr import to_jsonable
    from .application import Application

    if args.group_by and not args.agg:
        print("--group-by without --agg is not allowed", file=sys.stderr)
        return 1
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        lm = app.ledger_manager
        lm.load_last_known_ledger()
        min_ledger = None
        if args.last_modified_ledger_count is not None:
            lcl = lm.get_last_closed_ledger_num()
            # exactly `count` ledgers: [lcl - count + 1, lcl]
            min_ledger = max(0, lcl - args.last_modified_ledger_count + 1)
        # validate the queries before touching the output file so a bad
        # query can't truncate an existing dump
        matcher = XDRMatcher(args.filter_query) \
            if args.filter_query else None
        if matcher is not None:
            from ..xdr.ledger_entries import LedgerEntry
            matcher.match_xdr(LedgerEntry())
        group_by = XDRFieldExtractor(args.group_by) \
            if args.group_by else None
        if args.agg:
            XDRAccumulator(args.agg)  # parse check
        accumulators = {}
        out = open(args.output_file, "w") if args.output_file \
            else sys.stdout
        try:
            count = [0]

            def accept(entry) -> bool:
                return matcher is None or matcher.match_xdr(entry)

            def process(entry) -> bool:
                if args.agg:
                    key = tuple(group_by.extract_fields(entry)) \
                        if group_by else ()
                    acc = accumulators.get(key)
                    if acc is None:
                        acc = accumulators[key] = XDRAccumulator(args.agg)
                    acc.add_entry(entry)
                else:
                    out.write(json.dumps(to_jsonable(entry)) + "\n")
                count[0] += 1
                return args.limit is None or count[0] < args.limit

            bl = app.bucket_manager.bucket_list
            bl.visit_ledger_entries(accept, process,
                                    min_last_modified=min_ledger)
            if args.agg:
                for key, acc in sorted(accumulators.items(),
                                       key=lambda kv: str(kv[0])):
                    row = {}
                    if group_by is not None:
                        row.update(dict(zip(group_by.field_names(),
                                            key)))
                    row.update(acc.get_values())
                    out.write(json.dumps(row) + "\n")
        finally:
            if out is not sys.stdout:
                out.close()
        return 0
    finally:
        app.shutdown()


def cmd_report_last_history_checkpoint(args) -> int:
    """reference: reportLastHistoryCheckpoint
    (main/ApplicationUtils.cpp:752-800) — fetch and print the archive's
    current HAS."""
    from ..catchup import GetHistoryArchiveStateWork
    from ..util.timer import ClockMode, VirtualClock
    from ..work import State, run_work_to_completion
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        archives = [a for a in app.history_manager.archives
                    if a.has_get()]
        if not archives:
            print("no readable history archives configured",
                  file=sys.stderr)
            return 1
        work = GetHistoryArchiveStateWork(app, archives[0])
        if run_work_to_completion(app, work) != State.WORK_SUCCESS:
            print("failed to fetch archive state", file=sys.stderr)
            return 1
        text = work.has.to_json()
        if args.output_file:
            with open(args.output_file, "w") as f:
                f.write(text)
        else:
            print(text)
        return 0
    finally:
        app.shutdown()


def cmd_verify_checkpoints(args) -> int:
    """reference: runWriteVerifiedCheckpointHashes
    (CommandLine.cpp:984-1050) — verify the archive's full hash chain
    and write trusted [ledger, hash] pairs for every checkpoint."""
    from ..catchup import GetHistoryArchiveStateWork
    from ..catchup.catchup_work import DownloadVerifyLedgerChainWork
    from ..history import CHECKPOINT_FREQUENCY, checkpoint_containing
    from ..util.timer import ClockMode, VirtualClock
    from ..work import State, run_work_to_completion
    from .application import Application
    import tempfile

    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        archives = [a for a in app.history_manager.archives
                    if a.has_get()]
        if not archives:
            print("no readable history archives configured",
                  file=sys.stderr)
            return 1
        archive = archives[0]
        has_work = GetHistoryArchiveStateWork(app, archive)
        if run_work_to_completion(app, has_work) != State.WORK_SUCCESS:
            print("failed to fetch archive state", file=sys.stderr)
            return 1
        tip = has_work.has.current_ledger
        first_cp = checkpoint_containing(1)
        cps = list(range(first_cp, checkpoint_containing(tip) + 1,
                         CHECKPOINT_FREQUENCY))
        tmp = tempfile.mkdtemp(prefix="verify-checkpoints-")
        try:
            chain = DownloadVerifyLedgerChainWork(app, archive, cps, tmp)
            ok = run_work_to_completion(
                app, chain, timeout_virtual=86400) == State.WORK_SUCCESS
        finally:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
        if not ok:
            print("ledger chain verification FAILED", file=sys.stderr)
            return 1
        # optional trusted anchor: both flags or neither
        if (args.trusted_hash is None) != (args.trusted_ledger is None):
            print("--trusted-ledger and --trusted-hash must be given "
                  "together", file=sys.stderr)
            return 1
        if args.trusted_hash is not None:
            anchor = chain.headers.get(args.trusted_ledger)
            if anchor is None or bytes(anchor.hash).hex() != \
                    args.trusted_hash.lower():
                print(f"trusted hash mismatch at ledger "
                      f"{args.trusted_ledger}", file=sys.stderr)
                return 1
        pairs = [[seq, bytes(chain.headers[seq].hash).hex()]
                 for seq in sorted(
                     (s for s in chain.headers if
                      (s + 1) % CHECKPOINT_FREQUENCY == 0 or s == tip),
                     reverse=True)]
        with open(args.output_file, "w") as f:
            json.dump(pairs, f, indent=1)
        print(f"verified {len(chain.headers)} headers; wrote "
              f"{len(pairs)} checkpoint hashes")
        return 0
    finally:
        app.shutdown()


def cmd_new_hist(args) -> int:
    """reference: initializeHistories →
    HistoryArchiveManager::initializeHistoryArchive
    (HistoryArchiveManager.cpp:200-240) — refuse if the archive already
    has a HAS, else put a fresh empty one."""
    import os as _os
    import tempfile
    from ..history.archive import HAS_PATH, HistoryArchiveState
    cfg = _load_config(args)
    from ..history.manager import HistoryManager

    class _A:  # minimal app facade for HistoryManager
        config = cfg
    archives = {a.name: a for a in HistoryManager(_A()).archives}
    for label in args.labels:
        archive = archives.get(label)
        if archive is None:
            print(f"unknown history archive '{label}'", file=sys.stderr)
            return 1
        if not archive.has_put():
            print(f"archive '{label}' has no put command",
                  file=sys.stderr)
            return 1
        # probe for existing state
        if archive.has_get():
            probe = tempfile.mktemp(prefix="has-probe-")
            if _os.system(archive.get_file_cmd(HAS_PATH, probe)) == 0 \
                    and _os.path.exists(probe):
                _os.unlink(probe)
                print(f"history archive '{label}' already initialized!",
                      file=sys.stderr)
                return 1
        from ..bucket.bucket_list import BucketList
        has = HistoryArchiveState.from_bucket_list(
            0, BucketList(), cfg.NETWORK_PASSPHRASE)
        local = tempfile.mktemp(prefix="has-init-")
        with open(local, "w") as f:
            f.write(has.to_json())
        rc = _os.system(archive.put_file_cmd(local, HAS_PATH))
        _os.unlink(local)
        if rc != 0:
            print(f"failed to initialize archive '{label}'",
                  file=sys.stderr)
            return 1
        print(f"initialized history archive '{label}'")
    return 0


def cmd_diag_bucket_stats(args) -> int:
    """reference: diagnostics::bucketStats (main/Diagnostics.cpp:16-100)
    — per-entry-type counts/bytes of one bucket file."""
    import io as _io
    from ..history.archive import read_gz
    from ..util.xdr_stream import read_record
    from ..xdr.ledger import BucketEntry, BucketEntryType

    if args.file.endswith(".gz"):
        data = read_gz(args.file)
    else:
        with open(args.file, "rb") as f:
            data = f.read()
    bio = _io.BytesIO(data)
    bucket_counts: dict = {}
    entry_counts: dict = {}
    entry_bytes: dict = {}
    per_account: dict = {}
    while True:
        rec = read_record(bio)
        if rec is None:
            break
        be = BucketEntry.from_bytes(rec)
        bucket_counts[be.disc.name] = bucket_counts.get(be.disc.name,
                                                        0) + 1
        if be.disc in (BucketEntryType.LIVEENTRY,
                       BucketEntryType.INITENTRY):
            le = be.value
            t = le.data.disc.name
            entry_counts[t] = entry_counts.get(t, 0) + 1
            entry_bytes[t] = entry_bytes.get(t, 0) + len(rec)
            if args.aggregate_account_stats:
                owner = None
                d = le.data
                if d.arm_name in ("account", "trustLine", "data"):
                    owner = bytes(d.value.accountID.value).hex()
                elif d.arm_name == "offer":
                    owner = bytes(d.value.sellerID.value).hex()
                if owner is not None:
                    pa = per_account.setdefault(owner,
                                                {"count": 0, "bytes": 0})
                    pa["count"] += 1
                    pa["bytes"] += len(rec)
    report = {"bucketEntries": bucket_counts,
              "ledgerEntriesCount": entry_counts,
              "ledgerEntriesSizeBytes": entry_bytes}
    if args.aggregate_account_stats:
        report["perAccount"] = per_account
    print(json.dumps(report, indent=2))
    return 0


def cmd_merge_bucketlist(args) -> int:
    """reference: mergeBucketList (main/ApplicationUtils.cpp:521-546) —
    merge the whole bucket list into one bucket file for diagnostics."""
    import os as _os
    from ..bucket.bucket import Bucket, merge_buckets
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        if not app.ledger_manager.load_last_known_ledger():
            print("no last-known ledger in DB", file=sys.stderr)
            return 1
        bl = app.bucket_manager.bucket_list
        merged = Bucket.empty()
        buckets = []
        for lvl in bl.levels:
            lvl.commit()
            buckets.extend([lvl.curr, lvl.snap])
        # fold oldest -> newest so each newer bucket shadows the merged
        # older state; final fold drops tombstones (bottom-level merge)
        for b in reversed(buckets):
            merged = merge_buckets(merged, b)
        merged = merge_buckets(merged, Bucket.empty(), keep_dead=False)
        out = _os.path.join(args.output_dir,
                            f"bucket-{merged.hash.hex()}.xdr")
        merged.write_to(out)
        print(f"wrote merged bucket {out}")
        return 0
    finally:
        app.shutdown()


def cmd_rebuild_ledger_from_buckets(args) -> int:
    """reference: runRebuildLedgerFromBuckets (CommandLine.cpp:1541) —
    drop the SQL ledger-entry tables and repopulate them from the
    bucket list."""
    from ..ledger.ledger_txn import LedgerTxn
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        lm = app.ledger_manager
        if not lm.load_last_known_ledger():
            print("no last-known ledger in DB", file=sys.stderr)
            return 1
        count = [0]
        with app.database.transaction():
            for t in app.database.entry_tables():
                app.database.execute(f"DELETE FROM {t}")
            with LedgerTxn(lm.root) as ltx:
                def process(entry) -> bool:
                    # work on a copy (create() would restamp
                    # lastModifiedLedgerSeq on the shared bucket object)
                    copy = entry.copy()
                    ltx.create(copy)
                    copy.lastModifiedLedgerSeq = \
                        entry.lastModifiedLedgerSeq
                    count[0] += 1
                    return True

                app.bucket_manager.bucket_list.visit_ledger_entries(
                    lambda e: True, process)
                ltx.commit()
        print(f"rebuilt {count[0]} ledger entries from buckets")
        return 0
    finally:
        app.shutdown()


def cmd_replay_debug_meta(args) -> int:
    """reference: runReplayDebugMeta (CommandLine.cpp:721-760) +
    catchup/ReplayDebugMetaWork — re-apply ledgers from the rotated
    debug-meta files under <meta-dir>/meta-debug."""
    import gzip
    import io as _io
    import os as _os
    from ..herder.tx_set import TxSetFrame
    from ..ledger.ledger_manager import LedgerCloseData
    from ..util.timer import ClockMode, VirtualClock
    from ..util.xdr_stream import read_record
    from ..xdr.ledger import LedgerCloseMeta
    from .application import Application

    cfg = _load_config(args)
    meta_dir = _os.path.join(args.meta_dir, "meta-debug")
    if not _os.path.isdir(meta_dir):
        print(f"no meta-debug dir under {args.meta_dir}",
              file=sys.stderr)
        return 1
    files = sorted(
        _os.path.join(meta_dir, f) for f in _os.listdir(meta_dir)
        if f.startswith("meta-debug-"))
    if not files:
        print("no debug meta files found", file=sys.stderr)
        return 1
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        lm = app.ledger_manager
        lm.meta_debug_dir = None  # don't write what we're reading
        if not lm.load_last_known_ledger():
            print("no last-known ledger in DB", file=sys.stderr)
            return 1
        applied = 0
        for path in files:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                while True:
                    try:
                        rec = read_record(f)
                    except OSError:
                        # a crash can truncate the tail record of the
                        # last segment; everything before it is intact
                        print("warning: truncated record at end of "
                              f"{path}", file=sys.stderr)
                        break
                    if rec is None:
                        break
                    meta = LedgerCloseMeta.from_bytes(rec)
                    v = meta.value
                    hdr = v.ledgerHeader.header
                    seq = hdr.ledgerSeq
                    lcl = lm.get_last_closed_ledger_num()
                    if seq <= lcl:
                        continue
                    if args.target_ledger and seq > args.target_ledger:
                        break
                    if seq != lcl + 1:
                        print(f"gap in debug meta: have LCL {lcl}, "
                              f"next record is ledger {seq}",
                              file=sys.stderr)
                        return 1
                    frame = TxSetFrame(v.txSet, cfg.network_id())
                    lm.close_ledger(LedgerCloseData(seq, frame,
                                                    hdr.scpValue))
                    if lm.get_last_closed_ledger_hash() != \
                            bytes(v.ledgerHeader.hash):
                        print(f"replay diverged at ledger {seq}",
                              file=sys.stderr)
                        return 1
                    applied += 1
        print(f"replayed {applied} ledgers from debug meta, LCL "
              f"{lm.get_last_closed_ledger_num()}")
        return 0
    finally:
        app.shutdown()


def cmd_upgrade_db(args) -> int:
    """reference: runUpgradeDB — apply pending schema upgrades."""
    import os as _os
    from ..db.database import create_database
    cfg = _load_config(args)
    if cfg.DATABASE.startswith("sqlite3://"):
        path = cfg.database_path()
        if path != ":memory:" and not _os.path.exists(path):
            print(f"database {path} does not exist", file=sys.stderr)
            return 1
    db = create_database(cfg)
    before = db.get_schema_version()
    db.upgrade_to_current_schema()
    after = db.get_schema_version()
    db.close()
    print(f"schema version {before} -> {after}")
    return 0


def cmd_gen_fuzz(args) -> int:
    """reference: runGenFuzz — write a random fuzzer input file."""
    import os as _os
    from .fuzzer import OverlayFuzzer, TransactionFuzzer
    seed = args.seed if args.seed is not None else \
        int.from_bytes(_os.urandom(4), "big")
    cls = TransactionFuzzer if args.mode == "tx" else OverlayFuzzer
    cls.gen_fuzz(args.file, seed)  # pure generation, no node needed
    print(f"wrote {args.mode} fuzz input (seed {seed}) to {args.file}")
    return 0


def cmd_fuzz(args) -> int:
    """reference: runFuzz (test/fuzz.cpp) — inject one input file into
    a prepared node; exit 0 = survived."""
    from .fuzzer import OverlayFuzzer, TransactionFuzzer
    fz = TransactionFuzzer() if args.mode == "tx" else OverlayFuzzer()
    try:
        interesting = fz.inject(args.file)
    finally:
        fz.shutdown()
    print("interesting input" if interesting
          else "uninteresting (malformed) input")
    return 0


def cmd_fuzz_coverage(args) -> int:
    """Coverage-guided loop (reference: the AFL harness of
    docs/fuzzing.md, with sys.monitoring instrumentation instead of
    afl-clang)."""
    from .fuzz_coverage import run_coverage_fuzz
    stats = run_coverage_fuzz(args.mode, runs=args.runs, seed=args.seed,
                              corpus_dir=args.corpus_dir,
                              time_budget=args.seconds)
    print(f"runs={stats.runs} interesting={stats.interesting} "
          f"corpus={stats.corpus_size} "
          f"locations={stats.total_locations} "
          f"crashes={len(stats.crashes)}")
    return 1 if stats.crashes else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="stellar-core-tpu")
    p.add_argument("--conf", help="config file (TOML)", default=None)
    p.add_argument("--ll", help="log level", default="info")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)
    sub.add_parser("gen-seed").set_defaults(fn=cmd_gen_seed)
    sub.add_parser("sec-to-pub").set_defaults(fn=cmd_sec_to_pub)
    cid = sub.add_parser("convert-id")
    cid.add_argument("id")
    cid.set_defaults(fn=cmd_convert_id)
    sub.add_parser("new-db").set_defaults(fn=cmd_new_db)
    run = sub.add_parser("run")
    run.add_argument("--new-db", action="store_true")
    run.add_argument("--port-file", default=None,
                     help="write the bound admin HTTP port here "
                          "(useful with HTTP_PORT=0)")
    run.set_defaults(fn=cmd_run)
    http = sub.add_parser("http-command")
    http.add_argument("command")
    http.set_defaults(fn=cmd_http_command)
    cu = sub.add_parser("catchup")
    cu.add_argument("destination", help="<ledger>/<count> or 'current'")
    cu.add_argument("--new-db", action="store_true")
    cu.set_defaults(fn=cmd_catchup)
    sub.add_parser("publish").set_defaults(fn=cmd_publish)
    sub.add_parser("self-check").set_defaults(fn=cmd_self_check)
    pxdr = sub.add_parser("print-xdr")
    pxdr.add_argument("file")
    pxdr.add_argument("--filetype", default="TransactionEnvelope")
    pxdr.add_argument("--base64", action="store_true")
    pxdr.set_defaults(fn=cmd_print_xdr)
    ea = sub.add_parser("encode-asset")
    ea.add_argument("--code", default="")
    ea.add_argument("--issuer", default="")
    ea.set_defaults(fn=cmd_encode_asset)
    st = sub.add_parser("sign-transaction")
    st.add_argument("file")
    st.add_argument("--netid", required=True)
    st.add_argument("--base64", action="store_true")
    st.add_argument("--seed", default=None,
                    help="secret seed (read from stdin if omitted)")
    st.set_defaults(fn=cmd_sign_transaction)
    sub.add_parser("offline-info").set_defaults(fn=cmd_offline_info)
    dl = sub.add_parser("dump-ledger")
    dl.add_argument("--output-file", default=None)
    dl.add_argument("--filter-query", default=None)
    dl.add_argument("--last-modified-ledger-count", type=int, default=None)
    dl.add_argument("--limit", type=int, default=None)
    dl.add_argument("--group-by", default=None)
    dl.add_argument("--agg", default=None)
    dl.set_defaults(fn=cmd_dump_ledger)
    rl = sub.add_parser("report-last-history-checkpoint")
    rl.add_argument("--output-file", default=None)
    rl.set_defaults(fn=cmd_report_last_history_checkpoint)
    vc = sub.add_parser("verify-checkpoints")
    vc.add_argument("--output-file", required=True)
    vc.add_argument("--trusted-ledger", type=int, default=None)
    vc.add_argument("--trusted-hash", default=None)
    vc.set_defaults(fn=cmd_verify_checkpoints)
    nh = sub.add_parser("new-hist")
    nh.add_argument("labels", nargs="+")
    nh.set_defaults(fn=cmd_new_hist)
    dbs = sub.add_parser("diag-bucket-stats")
    dbs.add_argument("file")
    dbs.add_argument("--aggregate-account-stats", action="store_true")
    dbs.set_defaults(fn=cmd_diag_bucket_stats)
    mb = sub.add_parser("merge-bucketlist")
    mb.add_argument("--output-dir", default=".")
    mb.set_defaults(fn=cmd_merge_bucketlist)
    sub.add_parser("rebuild-ledger-from-buckets").set_defaults(
        fn=cmd_rebuild_ledger_from_buckets)
    rdm = sub.add_parser("replay-debug-meta")
    rdm.add_argument("--meta-dir", required=True,
                     help="directory containing meta-debug/")
    rdm.add_argument("--target-ledger", type=int, default=0)
    rdm.set_defaults(fn=cmd_replay_debug_meta)
    sub.add_parser("upgrade-db").set_defaults(fn=cmd_upgrade_db)
    gf = sub.add_parser("gen-fuzz")
    gf.add_argument("file")
    gf.add_argument("--mode", choices=["tx", "overlay"], default="tx")
    gf.add_argument("--seed", type=int, default=None)
    gf.set_defaults(fn=cmd_gen_fuzz)
    fz = sub.add_parser("fuzz")
    fz.add_argument("file")
    fz.add_argument("--mode", choices=["tx", "overlay"], default="tx")
    fz.set_defaults(fn=cmd_fuzz)
    cf = sub.add_parser("fuzz-coverage")
    cf.add_argument("--mode", choices=["tx", "overlay"], default="tx")
    cf.add_argument("--runs", type=int, default=500)
    cf.add_argument("--seconds", type=float, default=None)
    cf.add_argument("--seed", type=int, default=1)
    cf.add_argument("--corpus-dir", default="fuzz-corpus")
    cf.set_defaults(fn=cmd_fuzz_coverage)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from ..util.logging import init_logging
    args = build_parser().parse_args(argv)
    init_logging(args.ll)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
