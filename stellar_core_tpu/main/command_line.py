"""CLI subcommands.

Reference: src/main/CommandLine.cpp (subcommand list :1638-1698). We
implement the operator-facing core with argparse: run, new-db, gen-seed,
sec-to-pub, convert-id, version, http-command, offline-info, print-xdr,
sign-transaction, manualclose helpers arrive with their subsystems.
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
from typing import List, Optional

from ..crypto.keys import SecretKey
from ..crypto.strkey import StrKey
from .config import Config

VERSION = "stellar-core-tpu 0.1.0"


def _load_config(args) -> Config:
    if args.conf:
        return Config.load(args.conf)
    return Config()


def cmd_version(args) -> int:
    print(VERSION)
    return 0


def cmd_gen_seed(args) -> int:
    """reference: runGenSeed — print a fresh keypair."""
    import os
    sk = SecretKey.from_seed(os.urandom(32))
    print("Secret seed:", StrKey.encode_ed25519_seed(sk.seed))
    print("Public:", StrKey.encode_ed25519_public(sk.public_key().raw))
    return 0


def cmd_sec_to_pub(args) -> int:
    """reference: runSecToPub — seed on stdin → public key."""
    seed = input().strip()
    sk = SecretKey.from_seed(StrKey.decode_ed25519_seed(seed))
    print(StrKey.encode_ed25519_public(sk.public_key().raw))
    return 0


def cmd_convert_id(args) -> int:
    """reference: runConvertId — show every representation of a key."""
    s = args.id
    try:
        raw = StrKey.decode_ed25519_public(s)
        print(json.dumps({"strkey": s, "hex": raw.hex()}))
        return 0
    except Exception:
        pass
    raw = bytes.fromhex(s)
    print(json.dumps({"strkey": StrKey.encode_ed25519_public(raw),
                      "hex": s}))
    return 0


def cmd_new_db(args) -> int:
    """reference: runNewDB — initialize the database schema."""
    from ..db.database import Database
    cfg = _load_config(args)
    db = Database(cfg.database_path())
    db.initialize()
    db.close()
    print("database initialized")
    return 0


def cmd_run(args) -> int:
    """reference: runWithHelp → ApplicationUtils::runApp :274."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    from .command_handler import run_http_server

    cfg = _load_config(args)
    clock = VirtualClock(ClockMode.REAL_TIME)
    app = Application.create(clock, cfg, new_db=args.new_db)
    app.start()
    http_thread = None
    if cfg.HTTP_PORT:
        http_thread = run_http_server(app.command_handler, cfg.HTTP_PORT,
                                      cfg.PUBLIC_HTTP_PORT)
    try:
        while not clock.stopped:
            app.crank(block=True)
    except KeyboardInterrupt:
        pass
    finally:
        if http_thread is not None:
            http_thread.server.shutdown()
        app.shutdown()
    return 0


def cmd_catchup(args) -> int:
    """reference: runCatchup — offline catchup from configured
    archives: `catchup <to>/<count>` (count currently ignored: full
    replay to <to>)."""
    from ..catchup import CatchupConfiguration, CatchupWork
    from ..history.archive import HistoryArchive
    from ..util.timer import ClockMode, VirtualClock
    from ..work import State, run_work_to_completion
    from .application import Application

    cfg = _load_config(args)
    to_ledger = int(args.destination.split("/")[0]) \
        if args.destination != "current" else 0
    clock = VirtualClock(ClockMode.REAL_TIME)
    app = Application.create(clock, cfg, new_db=args.new_db)
    app.start()
    try:
        if not app.history_manager.archives:
            print("no history archives configured")
            return 1
        archive = next(a for a in app.history_manager.archives
                       if a.has_get())
        work = CatchupWork(app, archive,
                           CatchupConfiguration(to_ledger=to_ledger))
        state = run_work_to_completion(app, work, timeout_virtual=86400)
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        print(f"catchup {state.name}, LCL {lcl}")
        return 0 if state == State.WORK_SUCCESS else 1
    finally:
        app.shutdown()
    return 0


def cmd_publish(args) -> int:
    """reference: runPublish — flush the publish queue."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.REAL_TIME), cfg,
                             new_db=False)
    app.start()
    try:
        n = app.history_manager.publish_queued_history()
        print(f"published {n} checkpoints")
        return 0
    finally:
        app.shutdown()


def cmd_self_check(args) -> int:
    """reference: runSelfCheck (main/ApplicationUtils.cpp:487-517)."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    from .self_check import self_check
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.REAL_TIME), cfg,
                             new_db=False)
    app.start()
    try:
        ok, report = self_check(app)
        print(json.dumps(report, indent=2))
        return 0 if ok else 1
    finally:
        app.shutdown()


def cmd_http_command(args) -> int:
    """reference: runHttpCommand — send a command to a running node."""
    import urllib.request
    cfg = _load_config(args)
    url = f"http://127.0.0.1:{cfg.HTTP_PORT}/{args.command}"
    with urllib.request.urlopen(url) as resp:
        print(resp.read().decode())
    return 0


def cmd_print_xdr(args) -> int:
    """reference: dumpXdrStream/printXdr — decode one XDR file to json."""
    from ..xdr import transaction as txxdr, ledger as ledgerxdr
    types = {
        "TransactionEnvelope": txxdr.TransactionEnvelope,
        "LedgerHeader": ledgerxdr.LedgerHeader,
        "TransactionSet": ledgerxdr.TransactionSet,
    }
    cls = types.get(args.filetype)
    if cls is None:
        print(f"unsupported filetype {args.filetype}", file=sys.stderr)
        return 1
    with open(args.file, "rb") as f:
        data = f.read()
    if args.base64:
        data = base64.b64decode(data)
    obj = cls.from_bytes(data)
    print(obj)
    return 0


def cmd_encode_asset(args) -> int:
    """reference: runEncodeAsset (CommandLine.cpp:1059-1090) — print a
    base64-encoded XDR Asset."""
    from ..crypto.strkey import StrKey
    from ..xdr.ledger_entries import Asset
    from ..xdr.types import PublicKey
    code, issuer = args.code, args.issuer
    if not code and not issuer:
        asset = Asset.native()
    elif not code or not issuer:
        print("If one of code or issuer is defined, the other must be "
              "defined", file=sys.stderr)
        return 1
    else:
        if len(code) > 12:
            print("asset code too long (max 12)", file=sys.stderr)
            return 1
        raw = StrKey.decode_ed25519_public(issuer)
        asset = Asset.credit(code.encode(), PublicKey.ed25519(raw))
    print(base64.b64encode(asset.to_bytes()).decode())
    return 0


def cmd_sign_transaction(args) -> int:
    """reference: signtxn (main/dumpxdr.cpp:377-460) — append a
    signature to a TransactionEnvelope and print it."""
    from ..crypto.keys import SecretKey
    from ..crypto.sha import sha256
    from ..crypto.strkey import StrKey
    from ..xdr.transaction import (DecoratedSignature, EnvelopeType,
                                   TransactionEnvelope,
                                   TransactionSignaturePayload,
                                   _TaggedTransaction)
    with open(args.file, "rb") as f:
        data = f.read()
    if args.base64:
        data = base64.b64decode(data)
    env = TransactionEnvelope.from_bytes(data)

    seed = args.seed
    if seed is None:
        seed = sys.stdin.readline().strip()
    sk = SecretKey.from_seed(StrKey.decode_ed25519_seed(seed))

    network_id = sha256(args.netid.encode())
    if env.disc == EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
        tagged = _TaggedTransaction(
            EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, env.value.tx)
        sigs = env.value.signatures
    elif env.disc == EnvelopeType.ENVELOPE_TYPE_TX:
        tagged = _TaggedTransaction(
            EnvelopeType.ENVELOPE_TYPE_TX, env.value.tx)
        sigs = env.value.signatures
    else:
        print("unsupported envelope type", file=sys.stderr)
        return 1
    payload = TransactionSignaturePayload(
        networkId=network_id, taggedTransaction=tagged)
    h = sha256(payload.to_bytes())
    pub = sk.public_key().raw
    sigs.append(DecoratedSignature(hint=pub[-4:], signature=sk.sign(h)))
    out = env.to_bytes()
    if args.base64:
        print(base64.b64encode(out).decode())
    else:
        sys.stdout.buffer.write(out)
    return 0


def cmd_offline_info(args) -> int:
    """reference: runOfflineInfo — print the info JSON without running
    the node."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        app.ledger_manager.load_last_known_ledger()
        print(json.dumps(app.info(), indent=2))
        return 0
    finally:
        app.shutdown()


def cmd_dump_ledger(args) -> int:
    """reference: dumpLedger (main/ApplicationUtils.cpp:549-640) —
    dump/aggregate the current ledger state from the bucket list,
    filtered by an xdrquery expression."""
    from ..util.timer import ClockMode, VirtualClock
    from ..util.xdrquery import (XDRAccumulator, XDRFieldExtractor,
                                 XDRMatcher)
    from ..xdr.json_repr import to_jsonable
    from .application import Application

    if args.group_by and not args.agg:
        print("--group-by without --agg is not allowed", file=sys.stderr)
        return 1
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg,
                             new_db=False)
    try:
        lm = app.ledger_manager
        lm.load_last_known_ledger()
        min_ledger = None
        if args.last_modified_ledger_count is not None:
            lcl = lm.get_last_closed_ledger_num()
            # exactly `count` ledgers: [lcl - count + 1, lcl]
            min_ledger = max(0, lcl - args.last_modified_ledger_count + 1)
        # validate the queries before touching the output file so a bad
        # query can't truncate an existing dump
        matcher = XDRMatcher(args.filter_query) \
            if args.filter_query else None
        if matcher is not None:
            from ..xdr.ledger_entries import LedgerEntry
            matcher.match_xdr(LedgerEntry())
        group_by = XDRFieldExtractor(args.group_by) \
            if args.group_by else None
        if args.agg:
            XDRAccumulator(args.agg)  # parse check
        accumulators = {}
        out = open(args.output_file, "w") if args.output_file \
            else sys.stdout
        try:
            count = [0]

            def accept(entry) -> bool:
                return matcher is None or matcher.match_xdr(entry)

            def process(entry) -> bool:
                if args.agg:
                    key = tuple(group_by.extract_fields(entry)) \
                        if group_by else ()
                    acc = accumulators.get(key)
                    if acc is None:
                        acc = accumulators[key] = XDRAccumulator(args.agg)
                    acc.add_entry(entry)
                else:
                    out.write(json.dumps(to_jsonable(entry)) + "\n")
                count[0] += 1
                return args.limit is None or count[0] < args.limit

            bl = app.bucket_manager.bucket_list
            bl.visit_ledger_entries(accept, process,
                                    min_last_modified=min_ledger)
            if args.agg:
                for key, acc in sorted(accumulators.items(),
                                       key=lambda kv: str(kv[0])):
                    row = {}
                    if group_by is not None:
                        row.update(dict(zip(group_by.field_names(),
                                            key)))
                    row.update(acc.get_values())
                    out.write(json.dumps(row) + "\n")
        finally:
            if out is not sys.stdout:
                out.close()
        return 0
    finally:
        app.shutdown()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="stellar-core-tpu")
    p.add_argument("--conf", help="config file (TOML)", default=None)
    p.add_argument("--ll", help="log level", default="info")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)
    sub.add_parser("gen-seed").set_defaults(fn=cmd_gen_seed)
    sub.add_parser("sec-to-pub").set_defaults(fn=cmd_sec_to_pub)
    cid = sub.add_parser("convert-id")
    cid.add_argument("id")
    cid.set_defaults(fn=cmd_convert_id)
    sub.add_parser("new-db").set_defaults(fn=cmd_new_db)
    run = sub.add_parser("run")
    run.add_argument("--new-db", action="store_true")
    run.set_defaults(fn=cmd_run)
    http = sub.add_parser("http-command")
    http.add_argument("command")
    http.set_defaults(fn=cmd_http_command)
    cu = sub.add_parser("catchup")
    cu.add_argument("destination", help="<ledger>/<count> or 'current'")
    cu.add_argument("--new-db", action="store_true")
    cu.set_defaults(fn=cmd_catchup)
    sub.add_parser("publish").set_defaults(fn=cmd_publish)
    sub.add_parser("self-check").set_defaults(fn=cmd_self_check)
    pxdr = sub.add_parser("print-xdr")
    pxdr.add_argument("file")
    pxdr.add_argument("--filetype", default="TransactionEnvelope")
    pxdr.add_argument("--base64", action="store_true")
    pxdr.set_defaults(fn=cmd_print_xdr)
    ea = sub.add_parser("encode-asset")
    ea.add_argument("--code", default="")
    ea.add_argument("--issuer", default="")
    ea.set_defaults(fn=cmd_encode_asset)
    st = sub.add_parser("sign-transaction")
    st.add_argument("file")
    st.add_argument("--netid", required=True)
    st.add_argument("--base64", action="store_true")
    st.add_argument("--seed", default=None,
                    help="secret seed (read from stdin if omitted)")
    st.set_defaults(fn=cmd_sign_transaction)
    sub.add_parser("offline-info").set_defaults(fn=cmd_offline_info)
    dl = sub.add_parser("dump-ledger")
    dl.add_argument("--output-file", default=None)
    dl.add_argument("--filter-query", default=None)
    dl.add_argument("--last-modified-ledger-count", type=int, default=None)
    dl.add_argument("--limit", type=int, default=None)
    dl.add_argument("--group-by", default=None)
    dl.add_argument("--agg", default=None)
    dl.set_defaults(fn=cmd_dump_ledger)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from ..util.logging import init_logging
    args = build_parser().parse_args(argv)
    init_logging(args.ll)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
