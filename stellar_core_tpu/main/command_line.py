"""CLI subcommands.

Reference: src/main/CommandLine.cpp (subcommand list :1638-1698). We
implement the operator-facing core with argparse: run, new-db, gen-seed,
sec-to-pub, convert-id, version, http-command, offline-info, print-xdr,
sign-transaction, manualclose helpers arrive with their subsystems.
"""

from __future__ import annotations

import argparse
import base64
import json
import sys
from typing import List, Optional

from ..crypto.keys import SecretKey
from ..crypto.strkey import StrKey
from .config import Config

VERSION = "stellar-core-tpu 0.1.0"


def _load_config(args) -> Config:
    if args.conf:
        return Config.load(args.conf)
    return Config()


def cmd_version(args) -> int:
    print(VERSION)
    return 0


def cmd_gen_seed(args) -> int:
    """reference: runGenSeed — print a fresh keypair."""
    import os
    sk = SecretKey.from_seed(os.urandom(32))
    print("Secret seed:", StrKey.encode_ed25519_seed(sk.seed))
    print("Public:", StrKey.encode_ed25519_public(sk.public_key().raw))
    return 0


def cmd_sec_to_pub(args) -> int:
    """reference: runSecToPub — seed on stdin → public key."""
    seed = input().strip()
    sk = SecretKey.from_seed(StrKey.decode_ed25519_seed(seed))
    print(StrKey.encode_ed25519_public(sk.public_key().raw))
    return 0


def cmd_convert_id(args) -> int:
    """reference: runConvertId — show every representation of a key."""
    s = args.id
    try:
        raw = StrKey.decode_ed25519_public(s)
        print(json.dumps({"strkey": s, "hex": raw.hex()}))
        return 0
    except Exception:
        pass
    raw = bytes.fromhex(s)
    print(json.dumps({"strkey": StrKey.encode_ed25519_public(raw),
                      "hex": s}))
    return 0


def cmd_new_db(args) -> int:
    """reference: runNewDB — initialize the database schema."""
    from ..db.database import Database
    cfg = _load_config(args)
    db = Database(cfg.database_path())
    db.initialize()
    db.close()
    print("database initialized")
    return 0


def cmd_run(args) -> int:
    """reference: runWithHelp → ApplicationUtils::runApp :274."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    from .command_handler import run_http_server

    cfg = _load_config(args)
    clock = VirtualClock(ClockMode.REAL_TIME)
    app = Application.create(clock, cfg, new_db=args.new_db)
    app.start()
    http_thread = None
    if cfg.HTTP_PORT:
        http_thread = run_http_server(app.command_handler, cfg.HTTP_PORT,
                                      cfg.PUBLIC_HTTP_PORT)
    try:
        while not clock.stopped:
            app.crank(block=True)
    except KeyboardInterrupt:
        pass
    finally:
        if http_thread is not None:
            http_thread.server.shutdown()
        app.shutdown()
    return 0


def cmd_catchup(args) -> int:
    """reference: runCatchup — offline catchup from configured
    archives: `catchup <to>/<count>` (count currently ignored: full
    replay to <to>)."""
    from ..catchup import CatchupConfiguration, CatchupWork
    from ..history.archive import HistoryArchive
    from ..util.timer import ClockMode, VirtualClock
    from ..work import State, run_work_to_completion
    from .application import Application

    cfg = _load_config(args)
    to_ledger = int(args.destination.split("/")[0]) \
        if args.destination != "current" else 0
    clock = VirtualClock(ClockMode.REAL_TIME)
    app = Application.create(clock, cfg, new_db=args.new_db)
    app.start()
    try:
        if not app.history_manager.archives:
            print("no history archives configured")
            return 1
        archive = next(a for a in app.history_manager.archives
                       if a.has_get())
        work = CatchupWork(app, archive,
                           CatchupConfiguration(to_ledger=to_ledger))
        state = run_work_to_completion(app, work, timeout_virtual=86400)
        lcl = app.ledger_manager.get_last_closed_ledger_num()
        print(f"catchup {state.name}, LCL {lcl}")
        return 0 if state == State.WORK_SUCCESS else 1
    finally:
        app.shutdown()
    return 0


def cmd_publish(args) -> int:
    """reference: runPublish — flush the publish queue."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.REAL_TIME), cfg,
                             new_db=False)
    app.start()
    try:
        n = app.history_manager.publish_queued_history()
        print(f"published {n} checkpoints")
        return 0
    finally:
        app.shutdown()


def cmd_self_check(args) -> int:
    """reference: runSelfCheck (main/ApplicationUtils.cpp:487-517)."""
    from ..util.timer import ClockMode, VirtualClock
    from .application import Application
    from .self_check import self_check
    cfg = _load_config(args)
    app = Application.create(VirtualClock(ClockMode.REAL_TIME), cfg,
                             new_db=False)
    app.start()
    try:
        ok, report = self_check(app)
        print(json.dumps(report, indent=2))
        return 0 if ok else 1
    finally:
        app.shutdown()


def cmd_http_command(args) -> int:
    """reference: runHttpCommand — send a command to a running node."""
    import urllib.request
    cfg = _load_config(args)
    url = f"http://127.0.0.1:{cfg.HTTP_PORT}/{args.command}"
    with urllib.request.urlopen(url) as resp:
        print(resp.read().decode())
    return 0


def cmd_print_xdr(args) -> int:
    """reference: dumpXdrStream/printXdr — decode one XDR file to json."""
    from ..xdr import transaction as txxdr, ledger as ledgerxdr
    types = {
        "TransactionEnvelope": txxdr.TransactionEnvelope,
        "LedgerHeader": ledgerxdr.LedgerHeader,
        "TransactionSet": ledgerxdr.TransactionSet,
    }
    cls = types.get(args.filetype)
    if cls is None:
        print(f"unsupported filetype {args.filetype}", file=sys.stderr)
        return 1
    with open(args.file, "rb") as f:
        data = f.read()
    if args.base64:
        data = base64.b64decode(data)
    obj = cls.from_bytes(data)
    print(obj)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="stellar-core-tpu")
    p.add_argument("--conf", help="config file (TOML)", default=None)
    p.add_argument("--ll", help="log level", default="info")
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("version").set_defaults(fn=cmd_version)
    sub.add_parser("gen-seed").set_defaults(fn=cmd_gen_seed)
    sub.add_parser("sec-to-pub").set_defaults(fn=cmd_sec_to_pub)
    cid = sub.add_parser("convert-id")
    cid.add_argument("id")
    cid.set_defaults(fn=cmd_convert_id)
    sub.add_parser("new-db").set_defaults(fn=cmd_new_db)
    run = sub.add_parser("run")
    run.add_argument("--new-db", action="store_true")
    run.set_defaults(fn=cmd_run)
    http = sub.add_parser("http-command")
    http.add_argument("command")
    http.set_defaults(fn=cmd_http_command)
    cu = sub.add_parser("catchup")
    cu.add_argument("destination", help="<ledger>/<count> or 'current'")
    cu.add_argument("--new-db", action="store_true")
    cu.set_defaults(fn=cmd_catchup)
    sub.add_parser("publish").set_defaults(fn=cmd_publish)
    sub.add_parser("self-check").set_defaults(fn=cmd_self_check)
    pxdr = sub.add_parser("print-xdr")
    pxdr.add_argument("file")
    pxdr.add_argument("--filetype", default="TransactionEnvelope")
    pxdr.add_argument("--base64", action="store_true")
    pxdr.set_defaults(fn=cmd_print_xdr)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    from ..util.logging import init_logging
    args = build_parser().parse_args(argv)
    init_logging(args.ll)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
