"""Fuzz harness: tx and overlay modes.

Reference: test/FuzzerImpl.{h,cpp} + docs/fuzzing.md — `gen-fuzz` writes
a random input file, `fuzz` injects one input into a prepared node. The
tx fuzzer interprets input as an XDR vector of Operations applied from a
funded source account; the overlay fuzzer interprets it as a
StellarMessage delivered over an authenticated loopback connection. A
fuzzer run "passes" when the node survives (rejecting is fine); any
crash propagates.
"""

from __future__ import annotations

import random
from typing import Any

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..util.timer import ClockMode, VirtualClock
from ..xdr.runtime import (Array, EnumType, Lazy, Opaque,
                           Optional as XdrOptional, Reader, Struct, Union,
                           VarArray, VarOpaque, Writer, XdrError, _Bool,
                           _Composite, _Int32, _Int64, _Uint32, _Uint64)

FUZZER_MAX_OPERATIONS = 5


class XdrGenerator:
    """Random instances of declarative XDR types (reference: autocheck
    generators used by genFuzz; sizes biased small the same way)."""

    def __init__(self, rng: random.Random, max_elems: int = 3,
                 max_depth: int = 12):
        self.rng = rng
        self.max_elems = max_elems
        self.max_depth = max_depth

    def gen(self, t: Any, depth: int = 0) -> Any:
        if isinstance(t, Lazy):
            t = t._get()
        if isinstance(t, _Composite):
            t = t.cls
        if depth > self.max_depth:
            # bottom out with defaults to bound recursion
            return t.default() if hasattr(t, "default") else t()
        if isinstance(t, type) and issubclass(t, Struct):
            return t(**{fn: self.gen(ft, depth + 1)
                        for fn, ft in t._FIELDS})
        if isinstance(t, type) and issubclass(t, Union):
            disc = self.rng.choice(list(t._ARMS))
            arm = t._ARMS[disc]
            if arm is None or arm[1] is None:
                return t(disc)
            return t(disc, self.gen(arm[1], depth + 1))
        if isinstance(t, EnumType):
            return self.rng.choice(list(t.enum_cls))
        if isinstance(t, XdrOptional):
            if self.rng.random() < 0.5:
                return None
            return self.gen(t.elem, depth + 1)
        if isinstance(t, Opaque):
            return bytes(self.rng.getrandbits(8) for _ in range(t.n))
        if isinstance(t, VarOpaque):
            n = self.rng.randint(0, min(t.max_len, 32))
            return bytes(self.rng.getrandbits(8) for _ in range(n))
        if isinstance(t, Array):
            return [self.gen(t.elem, depth + 1) for _ in range(t.n)]
        if isinstance(t, VarArray):
            n = self.rng.randint(0, min(t.max_len, self.max_elems))
            return [self.gen(t.elem, depth + 1) for _ in range(n)]
        if isinstance(t, _Bool):
            return self.rng.random() < 0.5
        if isinstance(t, (_Int32, _Int64)):
            # biased small, occasionally extreme (autocheck-style)
            if self.rng.random() < 0.1:
                lo, hi = ((-2**31, 2**31 - 1) if isinstance(t, _Int32)
                          else (-2**63, 2**63 - 1))
                return self.rng.randint(lo, hi)
            return self.rng.randint(-100, 1000)
        if isinstance(t, (_Uint32, _Uint64)):
            if self.rng.random() < 0.1:
                hi = 2**32 - 1 if isinstance(t, _Uint32) else 2**64 - 1
                return self.rng.randint(0, hi)
            return self.rng.randint(0, 1000)
        raise TypeError(f"cannot generate {t!r}")


class TransactionFuzzer:
    """reference: FuzzerImpl.h:35 TransactionFuzzer — input is an XDR
    vector of Operations, applied from a funded account on a prepared
    standalone node."""

    def __init__(self):
        from .application import Application
        from .config import get_test_config
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        cfg = get_test_config()
        self.app = Application.create(self.clock, cfg)
        self.app.start()
        # deterministic funded accounts the ops can reference
        from ..tx.tx_utils import make_account_ledger_entry
        from ..ledger.ledger_txn import LedgerTxn
        from ..xdr.types import PublicKey
        self.accounts = []
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            for i in range(8):
                sk = SecretKey.from_seed(sha256(b"fuzz-acct-%d" % i))
                le = make_account_ledger_entry(
                    PublicKey.ed25519(sk.public_key().raw), 10**12,
                    seq_num=0)
                ltx.create(le)
                self.accounts.append(sk)
            ltx.commit()

    @staticmethod
    def _ops_type():
        from ..xdr.transaction import Operation
        return VarArray(Operation, FUZZER_MAX_OPERATIONS)

    def _current_seq(self, sk: SecretKey) -> int:
        from ..ledger.ledger_txn import LedgerTxn
        from ..xdr.ledger_entries import (LedgerEntryType, LedgerKey,
                                          _LedgerKeyAccount)
        from ..xdr.types import PublicKey
        key = LedgerKey(LedgerEntryType.ACCOUNT, _LedgerKeyAccount(
            accountID=PublicKey.ed25519(sk.public_key().raw)))
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            le = ltx.load(key)
            return le.data.value.seqNum

    def inject(self, path: str) -> bool:
        """Returns True if the input parsed and was executed (possibly
        rejected); False for uninteresting (malformed) input."""
        with open(path, "rb") as f:
            data = f.read()
        try:
            r = Reader(data)
            ops = self._ops_type().unpack(r)
        except XdrError:
            return False
        if not r.done() or not ops:
            return False
        from ..tx.frame import make_frame
        from ..xdr.transaction import (EnvelopeType, Memo, MemoType,
                                       MuxedAccount, Preconditions,
                                       PreconditionType, Transaction,
                                       TransactionEnvelope,
                                       TransactionV1Envelope, _TxExt)
        source = self.accounts[0]
        muxed = MuxedAccount.from_ed25519(source.public_key().raw)
        seq = self._current_seq(source) + 1
        tx = Transaction(
            sourceAccount=muxed, fee=100 * len(ops), seqNum=seq,
            cond=Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE), operations=list(ops),
            ext=_TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=tx, signatures=[]))
        try:
            frame = make_frame(env, self.app.config.network_id())
        except Exception:
            return False
        from ..xdr.transaction import DecoratedSignature
        frame.signatures.append(DecoratedSignature(
            hint=source.public_key().hint(),
            signature=source.sign(frame.contents_hash())))
        frame.envelope.value.signatures = frame.signatures
        self.app.herder.recv_transaction(frame)
        self.app.manual_close()
        return True

    @classmethod
    def gen_fuzz(cls, path: str, seed: int) -> None:
        rng = random.Random(seed)
        gen = XdrGenerator(rng)
        n = rng.randint(1, FUZZER_MAX_OPERATIONS)
        from ..xdr.transaction import Operation
        ops = [gen.gen(Operation) for _ in range(n)]
        w = Writer()
        cls._ops_type().pack(w, ops)
        with open(path, "wb") as f:
            f.write(bytes(w.buf))

    def shutdown(self) -> None:
        self.app.shutdown()


class OverlayFuzzer:
    """reference: FuzzerImpl.h:66 OverlayFuzzer — input is one
    StellarMessage delivered over an authenticated loopback pair."""

    def __init__(self):
        from ..simulation import Simulation
        from .config import QuorumSetConfig

        def manual(cfg):
            cfg.MANUAL_CLOSE = True

        seeds = [SecretKey.from_seed(sha256(b"fuzz-ovl-%d" % i))
                 for i in range(2)]
        node_ids = [s.public_key().raw for s in seeds]
        qset = QuorumSetConfig(threshold=2, validators=list(node_ids))
        self.sim = Simulation(network_passphrase="fuzz overlay net")
        for sk in seeds:
            self.sim.add_node(sk, qset, configure=manual)
        self.sim.start_all_nodes()
        self.apps = self.sim.apps()
        self.sim.add_pending_connection(node_ids[0], node_ids[1])
        self.conn = self.sim.connections[0]
        self.conn.crank()

    def inject(self, path: str) -> bool:
        from ..xdr.overlay import StellarMessage
        with open(path, "rb") as f:
            data = f.read()
        try:
            msg = StellarMessage.from_bytes(data)
        except XdrError:
            return False
        self.conn.initiator.send_message(msg)
        self.conn.crank()
        # the receiving node must still close ledgers
        self.apps[1].manual_close()
        return True

    @classmethod
    def gen_fuzz(cls, path: str, seed: int) -> None:
        from ..xdr.overlay import MessageType, StellarMessage
        rng = random.Random(seed)
        gen = XdrGenerator(rng)
        msg = gen.gen(StellarMessage)
        # HELLO/AUTH/ERROR on an authenticated link are uninteresting
        # (reference: isBadOverlayFuzzerInput)
        while msg.disc in (MessageType.HELLO, MessageType.AUTH,
                           MessageType.ERROR_MSG):
            msg = gen.gen(StellarMessage)
        with open(path, "wb") as f:
            f.write(msg.to_bytes())

    def shutdown(self) -> None:
        self.sim.stop_all_nodes()
