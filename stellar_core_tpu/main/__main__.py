"""`python -m stellar_core_tpu.main <cmd>` — the CLI entrypoint
(reference: main() in main/main.cpp dispatching to CommandLine)."""

import sys

from .command_line import main

if __name__ == "__main__":
    sys.exit(main())
