"""Maintainer + ExternalQueue: SQL history GC with consumer cursors.

Reference: src/main/Maintainer.{h,cpp} (cron-like deletion of old
txhistory/scphistory rows) and src/main/ExternalQueue.{h,cpp} (Horizon
et al. register cursors through `setcursor`; maintenance never deletes
past the lowest cursor).
"""

from __future__ import annotations

from typing import Dict, Optional

from ..util.logging import get_logger
from ..util.timer import VirtualTimer

log = get_logger("History")


class ExternalQueue:
    """reference: ExternalQueue.h:14-37 — pubsub table of resource ids
    → last-read ledger."""

    def __init__(self, app):
        self.app = app

    def set_cursor_for_resource(self, resid: str, cursor: int) -> None:
        self.app.database.execute(
            "INSERT OR REPLACE INTO pubsub (resid, lastread) VALUES (?,?)",
            (resid, cursor))

    def get_cursor(self, resid: Optional[str] = None) -> Dict[str, int]:
        if resid is not None:
            row = self.app.database.query_one(
                "SELECT lastread FROM pubsub WHERE resid=?", (resid,))
            return {resid: row[0]} if row else {}
        return {r: c for r, c in self.app.database.query_all(
            "SELECT resid, lastread FROM pubsub")}

    def delete_cursor(self, resid: str) -> None:
        self.app.database.execute(
            "DELETE FROM pubsub WHERE resid=?", (resid,))

    def min_cursor(self) -> Optional[int]:
        row = self.app.database.query_one(
            "SELECT MIN(lastread) FROM pubsub")
        return row[0] if row and row[0] is not None else None


class Maintainer:
    """reference: Maintainer.h:16-25 — periodic `performMaintenance`
    deleting history rows older than what every consumer has read."""

    def __init__(self, app):
        self.app = app
        self.external_queue = ExternalQueue(app)
        self._timer: Optional[VirtualTimer] = None

    def start(self, period_seconds: float = 3600.0,
              count: int = 50000) -> None:
        self._timer = VirtualTimer(self.app.clock)
        self._timer.expires_from_now(period_seconds)

        def tick():
            self.perform_maintenance(count)
            self.start(period_seconds, count)

        self._timer.async_wait(tick)

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def perform_maintenance(self, count: int) -> int:
        """Delete up to `count` ledgers' history below the safe floor:
        min(consumer cursors, last checkpointed ledger). Also the one
        sanctioned full-heap GC pass (util/gcpolicy.py): reference
        cycles from long runs are reclaimed here, at history-GC
        cadence, never inside a ledger close."""
        from ..util import gcpolicy
        gcpolicy.maintenance_collect()
        lcl = self.app.ledger_manager.get_last_closed_ledger_num()
        from ..history.archive import CHECKPOINT_FREQUENCY
        floor = max(1, lcl - 2 * CHECKPOINT_FREQUENCY)
        min_cursor = self.external_queue.min_cursor()
        if min_cursor is not None:
            floor = min(floor, min_cursor)
        low = max(1, floor - count)
        db = self.app.database
        deleted = 0
        for table in ("txhistory", "txfeehistory", "txsethistory",
                      "scphistory"):
            cur = db.execute(
                f"DELETE FROM {table} WHERE ledgerseq >= ? AND "
                f"ledgerseq < ?", (low, floor))
            deleted += cur.rowcount
        log.debug("maintenance deleted %d rows below ledger %d",
                  deleted, floor)
        return deleted
