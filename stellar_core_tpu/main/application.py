"""Application — the facade owning every subsystem.

Reference: src/main/ApplicationImpl.{h,cpp} — one object owning the
clock, config, database, bucket manager, ledger manager, herder, overlay,
history, metrics, and the admin command handler (ApplicationImpl.h:129-200).
`start()` (:782) restores the last known ledger and brings the node in
sync; the run loop cranks the VirtualClock until stopped.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

from ..bucket.manager import BucketManager
from ..db.database import Database
from ..herder.herder import Herder
from ..invariant.invariants import register_default_invariants
from ..invariant.manager import InvariantManager
from ..ledger.ledger_manager import LedgerManager
from ..util.logging import get_logger
from ..util.metrics import MetricsRegistry
from ..util.scheduler import Scheduler
from ..util.timer import ClockMode, VirtualClock
from .config import Config
from .persistent_state import PersistentState, StateEntry

log = get_logger("default")


class AppState:
    # reference: Application::State
    APP_CREATED_STATE = 0
    APP_ACQUIRING_CONSENSUS_STATE = 1
    APP_CONNECTED_STANDBY_STATE = 2
    APP_CATCHING_UP_STATE = 3
    APP_SYNCED_STATE = 4
    APP_STOPPING_STATE = 5


class Application:
    @classmethod
    def create(cls, clock: VirtualClock, config: Config,
               new_db: bool = True) -> "Application":
        return cls(clock, config, new_db=new_db)

    def __init__(self, clock: VirtualClock, config: Config,
                 new_db: bool = True):
        # process-wide, first app wins: keep CPython's automatic
        # full-heap (gen2) collections off the close/crank paths —
        # they scan the whole live set for up to seconds and reclaim
        # ~nothing here; the Maintainer cron runs the explicit pass
        # instead (util/gcpolicy.py has the measurements)
        from ..util import gcpolicy
        gcpolicy.install()
        self.clock = clock
        self.config = config
        self.state = AppState.APP_CREATED_STATE
        self.metrics = MetricsRegistry(
            window_minutes=config.HISTOGRAM_WINDOW_SIZE or None)
        from ..util.perf import ZoneRegistry
        from ..util.tracing import FlightRecorder
        self.perf = ZoneRegistry()
        # flight recorder (util/tracing.py): idle until the admin
        # `starttrace` route / bench --trace starts it; the perf zones
        # route their begin/end events through it while recording
        self.flight_recorder = FlightRecorder()
        self.perf.tracer = self.flight_recorder
        # input recorder (replay/recorder.py): attached by the
        # `recordstart` admin route or a Simulation driver; None means
        # every recording hook is a single attribute check
        self.input_recorder = None
        self.scheduler = Scheduler()

        from ..db.database import create_database
        self.database = create_database(config, metrics=self.metrics)
        if new_db or config.is_in_memory_mode():
            self.database.initialize()
        else:
            self.database.upgrade_to_current_schema()
        self.persistent_state = PersistentState(self.database)
        self.persistent_state.set(StateEntry.NETWORK_PASSPHRASE,
                                  config.NETWORK_PASSPHRASE)

        bucket_dir = config.BUCKET_DIR_PATH
        if bucket_dir is None:
            self._tmp_bucket_dir = tempfile.TemporaryDirectory(
                prefix="buckets-")
            bucket_dir = self._tmp_bucket_dir.name
        else:
            self._tmp_bucket_dir = None
            os.makedirs(bucket_dir, exist_ok=True)
        # process-global level cadence (consensus-affecting, testing
        # only). Only ever SET here: a constructor must not flip the
        # cadence under an already-live app's bucket list — tests that
        # enable it reset it themselves when done
        if config.ARTIFICIALLY_REDUCE_MERGE_COUNTS_FOR_TESTING:
            from ..bucket.bucket_list import set_reduced_merge_counts
            set_reduced_merge_counts(True)
        self.bucket_manager = BucketManager(
            bucket_dir, num_workers=config.WORKER_THREADS,
            pessimize_merges=config.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING,
            disable_gc=config.DISABLE_BUCKET_GC,
            disable_xdr_fsync=config.DISABLE_XDR_FSYNC)
        self.bucket_manager.bucket_list.perf = self.perf

        self.invariant_manager = InvariantManager(metrics=self.metrics)
        if config.INVARIANT_CHECKS:
            register_default_invariants(self.invariant_manager)

        meta_stream = None
        self._meta_file = None
        if config.METADATA_OUTPUT_STREAM:
            from ..util.xdr_stream import write_record
            self._meta_file = open(config.METADATA_OUTPUT_STREAM, "ab")

            def meta_stream(meta, _f=self._meta_file):
                write_record(_f, meta.to_bytes())
                _f.flush()

        self.ledger_manager = LedgerManager(
            db=self.database,
            bucket_manager=self.bucket_manager,
            invariants=self.invariant_manager,
            metrics=self.metrics,
            meta_stream=meta_stream,
            entry_cache_size=config.ENTRY_CACHE_SIZE,
            in_memory_ledger=config.MODE_USES_IN_MEMORY_LEDGER)

        self.ledger_manager.perf = self.perf
        if config.NODE_SEED is not None:
            # chaos fault schedules target nodes by id (util/chaos.py)
            self.ledger_manager.chaos_label = config.node_id().hex()
            # trace process-track label + pid separate the nodes of a
            # multi-node in-process simulation in Perfetto
            self.flight_recorder.label = config.node_id().hex()[:8]
            self.flight_recorder.pid = 1 + (config.PEER_PORT or 0)
        self.ledger_manager.stores_history_misc = \
            config.MODE_STORES_HISTORY_MISC
        self.ledger_manager.halt_on_internal_error = \
            config.HALT_ON_INTERNAL_TRANSACTION_ERROR
        self.ledger_manager.internal_error_min_protocol = \
            config.LEDGER_PROTOCOL_MIN_VERSION_INTERNAL_ERROR_REPORT
        self.ledger_manager.stores_history_ledgerheaders = \
            config.MODE_STORES_HISTORY_LEDGERHEADERS
        self.ledger_manager.delay_meta = \
            config.EXPERIMENTAL_PRECAUTION_DELAY_META
        if config.TESTING_SOROBAN_HIGH_LIMIT_OVERRIDE:
            self.ledger_manager.soroban_high_limits = True
        if config.ARTIFICIALLY_REPLAY_WITH_NEWEST_BUCKET_LOGIC_FOR_TESTING:
            from ..bucket.bucket import set_newest_merge_logic
            set_newest_merge_logic(True)
        if config.EXPERIMENTAL_BUCKETLIST_DB_PERSIST_INDEX:
            from ..bucket.bucket_index import set_persist_index
            set_persist_index(True)
        # BucketIndex tuning is process-global; only a NON-DEFAULT
        # config ever sets it (an unrelated default-config app must not
        # retune live apps' lazily-built indexes — tests that tune it
        # reset it themselves)
        if (config.EXPERIMENTAL_BUCKETLIST_DB_INDEX_CUTOFF,
                config.EXPERIMENTAL_BUCKETLIST_DB_INDEX_PAGE_SIZE_EXPONENT
                ) != (20, 14):
            from ..bucket.bucket_index import configure_index
            configure_index(
                cutoff_mb=config.EXPERIMENTAL_BUCKETLIST_DB_INDEX_CUTOFF,
                page_size_exponent=config.
                EXPERIMENTAL_BUCKETLIST_DB_INDEX_PAGE_SIZE_EXPONENT)
        if config.BEST_OFFER_DEBUGGING_ENABLED and \
                hasattr(self.ledger_manager.root, "best_offer_debugging"):
            self.ledger_manager.root.best_offer_debugging = True
        if config.OVERRIDE_EVICTION_PARAMS_FOR_TESTING:
            self.ledger_manager.archival_overrides = {
                "evictionScanSize": config.TESTING_EVICTION_SCAN_SIZE,
                "maxEntriesToArchive":
                    config.TESTING_MAX_ENTRIES_TO_ARCHIVE,
                "minPersistentTTL":
                    config.TESTING_MINIMUM_PERSISTENT_ENTRY_LIFETIME,
                "startingEvictionScanLevel":
                    config.TESTING_STARTING_EVICTION_SCAN_LEVEL,
            }
        root = self.ledger_manager.root
        if hasattr(root, "prefetch_batch"):
            root.prefetch_batch = config.PREFETCH_BATCH_SIZE
            root.max_batch_write_count = config.MAX_BATCH_WRITE_COUNT
            root.max_batch_write_bytes = config.MAX_BATCH_WRITE_BYTES
        # off-consensus diagnostic events into V3 meta (reference:
        # ENABLE_SOROBAN_DIAGNOSTIC_EVENTS)
        self.ledger_manager.root.soroban_diagnostics = \
            config.ENABLE_SOROBAN_DIAGNOSTIC_EVENTS
        if config.OP_APPLY_SLEEP_TIME_WEIGHT_FOR_TESTING:
            weights = list(config.OP_APPLY_SLEEP_TIME_WEIGHT_FOR_TESTING)
            durations = list(
                config.OP_APPLY_SLEEP_TIME_DURATION_FOR_TESTING)
            if len(weights) != len(durations) or sum(weights) <= 0 or \
                    any(w < 0 for w in weights):
                raise ValueError(
                    "OP_APPLY_SLEEP_TIME_WEIGHT/_DURATION_FOR_TESTING "
                    "must be equal-length with positive total weight")
            self.ledger_manager.apply_sleep = (weights, durations)
        # conflict-staged parallel apply (ledger/parallel_apply.py):
        # APPLY_PARALLEL=0 is the sequential fallback knob
        self.ledger_manager.apply_parallel = config.APPLY_PARALLEL
        self.ledger_manager.apply_parallel_min_txs = \
            config.APPLY_PARALLEL_MIN_TXS
        if config.EXPERIMENTAL_BUCKETLIST_DB:
            # serve entry loads from the bucket indexes (SQL keeps
            # offers + remains the fallback store; reference:
            # EXPERIMENTAL_BUCKETLIST_DB, bucket/readme.md:55-105)
            root = self.ledger_manager.root
            if hasattr(root, "serve_from_bucket_list"):
                root.serve_from_bucket_list(
                    self.bucket_manager.bucket_list)
        # one shared device batch verifier per app when configured — the
        # herder's txset validation and catchup's checkpoint
        # prevalidation both feed it (SURVEY.md §3.2/§3.3 collection
        # points; BASELINE.md configs #2/#3)
        self.batch_verifier = None
        self.verify_service = None
        if config.SIGNATURE_VERIFY_BACKEND == "tpu":
            # the device verifier rides behind the backend supervisor
            # (ops/backend_supervisor.py): a circuit breaker + hung-
            # dispatch watchdog shared by EVERY device caller — verify
            # service, txset prevalidator, catchup, self_check — so a
            # dead/flapping/hung device degrades to native verify
            # without per-flush failure latency (docs/ROBUSTNESS.md)
            from ..ops.backend_supervisor import BackendSupervisor
            self.batch_verifier = BackendSupervisor(
                self._make_batch_verifier(), clock=clock,
                metrics=self.metrics, perf=self.perf,
                failure_threshold=config.VERIFY_BREAKER_FAILURE_THRESHOLD,
                dispatch_deadline_ms=config.VERIFY_DISPATCH_DEADLINE_MS,
                probe_base_ms=config.VERIFY_BREAKER_PROBE_BASE_MS,
                probe_max_ms=config.VERIFY_BREAKER_PROBE_MAX_MS,
                canary_batch=config.VERIFY_BREAKER_CANARY_BATCH,
                jitter_seed=config.jitter_seed(),
                chaos_label=config.node_id().hex()
                if config.NODE_SEED is not None else "")
            # coalescing front-end for the LIVE per-signature paths
            # (flood admission, SCP envelopes, StellarValue sigs):
            # deadline micro-batching into the device verifier
            from ..ops.verify_service import VerifyService
            self.verify_service = VerifyService(
                self.batch_verifier, clock=clock, metrics=self.metrics,
                perf=self.perf, max_batch=config.VERIFY_MAX_BATCH,
                deadline_ms=config.VERIFY_BATCH_DEADLINE_MS)
            # staged apply prewarms each stage's signatures through the
            # same service so worker verifies hit the process cache
            self.ledger_manager.verify_service = self.verify_service
        self.herder = Herder(config, self.ledger_manager,
                             metrics=self.metrics,
                             verify=self._make_verify(),
                             batch_verifier=self.batch_verifier,
                             verify_service=self.verify_service)
        self.herder.perf = self.perf
        self.herder.set_clock(clock)
        # hash-keyed flood propagation tracking (mesh observatory,
        # overlay/propagation.py): overlay recv/send and herder
        # admit/externalize stamp into one bounded per-node map
        from ..overlay.propagation import PropagationTracker
        self.propagation = PropagationTracker(metrics=self.metrics)
        self.herder.propagation = self.propagation
        self._seed_testing_upgrades()

        from ..history.manager import HistoryManager
        from ..process.process_manager import ProcessManager
        from ..work import WorkScheduler
        self.process_manager = ProcessManager(
            self, max_concurrent=config.MAX_CONCURRENT_SUBPROCESSES)
        self.work_scheduler = WorkScheduler(self)
        self.history_manager = HistoryManager(self)
        # bucket GC must keep every bucket a queued-but-unpublished
        # checkpoint still references (the publish-queue refcount the
        # reference folds into forgetUnreferencedBuckets)
        self.bucket_manager.gc_ref_providers.append(
            self.history_manager.queued_bucket_hashes)
        self.ledger_manager.history_manager = self.history_manager
        self.ledger_manager.persistent_state = self.persistent_state
        self.ledger_manager.network_passphrase = config.NETWORK_PASSPHRASE
        if config.METADATA_DEBUG_LEDGERS:
            self.ledger_manager.meta_debug_dir = os.path.join(
                bucket_dir, "meta-debug")
            self.ledger_manager.meta_debug_ledgers = \
                config.METADATA_DEBUG_LEDGERS

        self.overlay_manager = None
        if config.NODE_SEED is not None:
            from ..overlay.manager import OverlayManager
            self.overlay_manager = OverlayManager(self)

        from ..catchup.manager import CatchupManager
        self.catchup_manager = CatchupManager(self)
        self.herder.catchup_manager = self.catchup_manager

        from .maintainer import Maintainer
        self.maintainer = Maintainer(self)

        from .command_handler import CommandHandler
        self.command_handler = CommandHandler(self)

        # telemetry time-series + SLO watchdog (util/timeseries.py,
        # ops/slo.py): a bounded ring of periodic health snapshots on
        # this app's clock, every sample judged against the declarative
        # SLO rules. The sampler's recurring timer arms in start()
        # (TELEMETRY_SAMPLE_PERIOD=0 leaves it manual — sample_now());
        # scraped via the `timeseries`/`slo` admin routes.
        from ..ops.slo import SloWatchdog, default_rules
        from ..util.timeseries import TelemetrySampler
        self.telemetry = TelemetrySampler(
            self, capacity=config.TELEMETRY_RING_CAPACITY,
            period_s=config.TELEMETRY_SAMPLE_PERIOD)
        self.slo = SloWatchdog(default_rules(config),
                               metrics=self.metrics,
                               recorder=self.flight_recorder)
        self.telemetry.observers.append(self.slo.observe)
        # adaptive control plane (ops/controller.py): closes the loop
        # over the sampler + watchdog — AIMD batch-knob search plus
        # graduated admission shedding. Its recurring tick arms in
        # start() (CONTROLLER_TICK_PERIOD=0 leaves it manual); the
        # herder's tx-submit gate and the overlay's flood-admission
        # gate consult its shed probabilities.
        from ..ops.controller import AdaptiveController
        self.controller = AdaptiveController(
            self, metrics=self.metrics, recorder=self.flight_recorder)
        self.herder.controller = self.controller

        # read-serving tier (query/): refcounted bucket-list snapshots
        # captured per close (crank-side closed_hooks), a tx-status
        # store fed from the deferred-completion stream, and the
        # bounded query-worker pool. Snapshots pin their buckets
        # against GC via the same provider mechanism the publish queue
        # uses; reads shed BEFORE writes via the controller's read
        # ladder.
        from ..query import QueryService, SnapshotManager, TxStatusStore
        self.snapshots = SnapshotManager(self.bucket_manager.bucket_list,
                                         metrics=self.metrics)
        self.bucket_manager.gc_ref_providers.append(
            self.snapshots.pinned_bucket_hashes)
        self.tx_status = TxStatusStore(
            capacity=config.QUERY_TX_STATUS_CAPACITY,
            ttl_s=config.QUERY_TX_STATUS_TTL, metrics=self.metrics)
        self.query_service = QueryService(
            self, self.snapshots, self.tx_status, self.metrics, config)
        self.ledger_manager.closed_hooks.append(
            self.snapshots.on_ledger_closed)
        self.ledger_manager.completion_hooks.append(
            self.tx_status.record_ledger)

    # -------------------------------------------------------------- wiring --
    def _make_batch_verifier(self):
        """Device-batch verifier per SIGNATURE_VERIFY_MESH: production
        multi-chip nodes shard the batch data-parallel over every
        visible device (ICI mesh); `hybrid` folds multi-host layouts
        into a (dcn, ici) mesh so DCN only carries the result gather."""
        import jax

        mode = self.config.SIGNATURE_VERIFY_MESH
        min_batch = self.config.VERIFY_DEVICE_MIN_BATCH
        ndev = len(jax.devices())
        if mode == "auto":
            mode = "sharded" if ndev > 1 else "single"
        if mode == "single":
            from ..ops.verifier import TpuBatchVerifier
            return TpuBatchVerifier(perf=self.perf,
                                    device_min_batch=min_batch,
                                    metrics=self.metrics)
        if mode == "sharded":
            from ..ops.verifier import ShardedBatchVerifier
            return ShardedBatchVerifier(perf=self.perf,
                                        device_min_batch=min_batch,
                                        metrics=self.metrics)
        if mode == "hybrid":
            from ..ops.multihost import HybridShardedVerifier
            return HybridShardedVerifier(perf=self.perf,
                                         device_min_batch=min_batch,
                                         metrics=self.metrics)
        raise ValueError(
            f"unknown SIGNATURE_VERIFY_MESH: {mode}")

    def _make_verify(self):
        from ..tx.signature_checker import default_verify
        backend = self.config.SIGNATURE_VERIFY_BACKEND
        if backend in ("native", "python"):
            return default_verify
        if backend == "tpu":
            # per-signature fallback path; batch prevalidation is injected
            # at the txset/checkpoint collection points (SURVEY.md §3.3)
            return default_verify
        raise ValueError(f"unknown SIGNATURE_VERIFY_BACKEND: {backend}")

    def _seed_testing_upgrades(self) -> None:
        from ..herder.upgrades import UpgradeParameters
        c = self.config
        if any(v is not None for v in (
                c.TESTING_UPGRADE_LEDGER_PROTOCOL_VERSION,
                c.TESTING_UPGRADE_DESIRED_FEE,
                c.TESTING_UPGRADE_RESERVE,
                c.TESTING_UPGRADE_MAX_TX_SET_SIZE,
                c.TESTING_UPGRADE_FLAGS)):
            self.herder.upgrades.set_parameters(UpgradeParameters(
                upgrade_time=0,
                protocol_version=c.TESTING_UPGRADE_LEDGER_PROTOCOL_VERSION,
                base_fee=c.TESTING_UPGRADE_DESIRED_FEE,
                base_reserve=c.TESTING_UPGRADE_RESERVE,
                max_tx_set_size=c.TESTING_UPGRADE_MAX_TX_SET_SIZE,
                flags=c.TESTING_UPGRADE_FLAGS))

    # ----------------------------------------------------------- lifecycle --
    def start(self) -> None:
        """reference: ApplicationImpl::start :782 — load LCL or create
        genesis, then bring the herder up."""
        if not self.ledger_manager.load_last_known_ledger():
            # reference: USE_CONFIG_FOR_GENESIS — off means a protocol-0
            # genesis whose upgrades arrive through consensus voting
            genesis_protocol = self.config.LEDGER_PROTOCOL_VERSION \
                if self.config.USE_CONFIG_FOR_GENESIS else 0
            self.ledger_manager.start_new_ledger(
                self.config.network_id(), genesis_protocol)
            self.persistent_state.set(
                StateEntry.LAST_CLOSED_LEDGER,
                self.ledger_manager.get_last_closed_ledger_hash().hex())
        # boot snapshot: the read tier answers from the LCL before the
        # first close of this process ever lands
        self.snapshots.on_ledger_closed(
            self.ledger_manager.get_last_closed_ledger_header(),
            self.ledger_manager.get_last_closed_ledger_hash())
        self.herder.start()
        if self.overlay_manager is not None:
            self.overlay_manager.start()
        if self.config.FORCE_SCP and not self.config.MANUAL_CLOSE \
                and self.herder.scp is not None \
                and self.config.NODE_IS_VALIDATOR:
            self.herder.bootstrap()
        self.state = AppState.APP_SYNCED_STATE
        self.telemetry.start()
        self.controller.start()
        if self.config.AUTOMATIC_SELF_CHECK_PERIOD > 0:
            self._arm_self_check_timer()
        if self.config.AUTOMATIC_MAINTENANCE_PERIOD > 0:
            # cron-like history GC (reference: Maintainer::start with
            # AUTOMATIC_MAINTENANCE_PERIOD/_COUNT)
            self.maintainer.start(
                self.config.AUTOMATIC_MAINTENANCE_PERIOD,
                self.config.AUTOMATIC_MAINTENANCE_COUNT)
        if self.config.ARTIFICIALLY_SLEEP_MAIN_THREAD_FOR_TESTING_US > 0:
            # models a slow main thread: every crank pays the sleep
            # (reference: ARTIFICIALLY_SLEEP_MAIN_THREAD_FOR_TESTING)
            import time as _time
            us = self.config.ARTIFICIALLY_SLEEP_MAIN_THREAD_FOR_TESTING_US

            def _sleepy_poller() -> int:
                _time.sleep(us / 1e6)
                return 0

            self.clock.add_io_poller(_sleepy_poller)
        log.info("application started at ledger %d",
                 self.ledger_manager.get_last_closed_ledger_num())

    def _arm_self_check_timer(self) -> None:
        """Recurring background self-check (reference: scheduleSelfCheck,
        ApplicationImpl.cpp:823-826). The automatic run is bounded (short
        crypto bench, recent-headers-only rehash) so a firing cannot
        stall the single-threaded crank loop for long."""
        from ..util.timer import VirtualTimer
        period = self.config.AUTOMATIC_SELF_CHECK_PERIOD
        if getattr(self, "_self_check_timer", None) is None:
            self._self_check_timer = VirtualTimer(self.clock)

        def fire():
            from .self_check import self_check
            try:
                ok, report = self_check(self, crypto_bench_seconds=0.05,
                                        max_headers=1024)
                if not ok:
                    log.error("automatic self-check FAILED: %s", report)
                else:
                    log.info("automatic self-check ok")
            except Exception:            # noqa: BLE001 — keep rescheduling
                log.exception("automatic self-check crashed")
            if self.state != AppState.APP_STOPPING_STATE:
                self._self_check_timer.expires_from_now(period)
                self._self_check_timer.async_wait(fire)

        self._self_check_timer.expires_from_now(period)
        self._self_check_timer.async_wait(fire)

    def manual_close(self) -> None:
        """reference: Herder::setInSyncAndTriggerNextLedger via the
        `manualclose` admin command (requires MANUAL_CLOSE=true)."""
        if not self.config.MANUAL_CLOSE:
            raise RuntimeError("manualclose requires MANUAL_CLOSE=true")
        self.herder.trigger_next_ledger()
        self.persistent_state.set(
            StateEntry.LAST_CLOSED_LEDGER,
            self.ledger_manager.get_last_closed_ledger_hash().hex())

    def crank(self, block: bool = False) -> int:
        n = self.clock.crank(block)
        n += self.scheduler.run_all()
        return n

    def shutdown(self) -> None:
        self.state = AppState.APP_STOPPING_STATE
        self.telemetry.stop()
        self.controller.stop()
        if self.flight_recorder.active:
            # release the process-wide tracing.ENABLED refcount — a
            # dead app must not keep every other node paying for spans
            self.flight_recorder.stop()
        if getattr(self, "_self_check_timer", None) is not None:
            self._self_check_timer.cancel()
            self._self_check_timer = None
        if self.overlay_manager is not None:
            self.overlay_manager.shutdown()
        self.maintainer.stop()
        self.herder.shutdown()
        if self.batch_verifier is not None and \
                hasattr(self.batch_verifier, "breaker_state"):
            # cancel the breaker's probe timer + release quarantined
            # collect threads: a dead app must not re-probe the device
            self.batch_verifier.shutdown()
        self.work_scheduler.shutdown()
        self.process_manager.shutdown()
        # stop serving reads, then drop the snapshot tier's own pin so
        # shutdown-time GC is not held by a node that no longer serves
        self.query_service.shutdown()
        self.snapshots.shutdown()
        self.bucket_manager.shutdown()
        # drain the deferred close-completion tail before touching the
        # meta stream/debug files or closing the database under it
        self.ledger_manager.join_completion(reraise=False)
        self.ledger_manager.flush_delayed_meta()
        if self._meta_file is not None:
            self._meta_file.close()
        self.ledger_manager._close_debug_meta()
        self.database.close()
        # reset the process-global testing switches THIS app turned on
        # (a later default-config app must not inherit them)
        if self.config.ARTIFICIALLY_REPLAY_WITH_NEWEST_BUCKET_LOGIC_FOR_TESTING:
            from ..bucket.bucket import set_newest_merge_logic
            set_newest_merge_logic(False)
        if self.config.EXPERIMENTAL_BUCKETLIST_DB_PERSIST_INDEX:
            from ..bucket.bucket_index import set_persist_index
            set_persist_index(False)
        if self.config.ARTIFICIALLY_REDUCE_MERGE_COUNTS_FOR_TESTING:
            from ..bucket.bucket_list import set_reduced_merge_counts
            set_reduced_merge_counts(False)
        if self._tmp_bucket_dir is not None:
            self._tmp_bucket_dir.cleanup()
        # reclaim dead-app reference cycles: automatic full
        # collections are off (gcpolicy), so a process that churns
        # apps — the test suite, multi-leg benches — must not carry
        # every dead app's graph to exit. Throttled (every Nth
        # teardown): the deferred window is a few dead app graphs,
        # a full pass per teardown cost the suite minutes
        from ..util import gcpolicy
        gcpolicy.teardown_collect()

    def __enter__(self) -> "Application":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ---------------------------------------------------------- info/status --
    def info(self) -> dict:
        lm = self.ledger_manager
        lcl = lm.get_last_closed_ledger_header()
        from ..xdr.schema import identity as xdr_identity
        out = {
            "build": "stellar-core-tpu dev",
            # reference: the .x-file hashes embedded in the binary and
            # cross-checked against the Rust host (Makefile.am:28-32)
            "xdr": xdr_identity(),
            "ledger": {
                "num": lcl.ledgerSeq,
                "hash": lm.get_last_closed_ledger_hash().hex(),
                "version": lcl.ledgerVersion,
                "baseFee": lcl.baseFee,
                "baseReserve": lcl.baseReserve,
                "maxTxSetSize": lcl.maxTxSetSize,
                "closeTime": lcl.scpValue.closeTime,
            },
            "state": _state_name(self.state),
            "network": self.config.NETWORK_PASSPHRASE,
            "protocol_version": self.config.LEDGER_PROTOCOL_VERSION,
            "num_pending_txs": self.herder.tx_queue.size_txs(),
        }
        # actual bound admin port (set by the `run` command — with
        # HTTP_PORT=0 the OS picks it, and a harness polling `info`
        # learns where it actually landed)
        if getattr(self, "http_port", None):
            out["http_port"] = self.http_port
        return out


def _state_name(state: int) -> str:
    names = {
        AppState.APP_CREATED_STATE: "Booting",
        AppState.APP_ACQUIRING_CONSENSUS_STATE: "Joining SCP",
        AppState.APP_CONNECTED_STANDBY_STATE: "Connected",
        AppState.APP_CATCHING_UP_STATE: "Catching up",
        AppState.APP_SYNCED_STATE: "Synced!",
        AppState.APP_STOPPING_STATE: "Stopping",
    }
    return names.get(state, "Unknown")
