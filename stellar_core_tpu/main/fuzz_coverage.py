"""Coverage-guided fuzzing loop.

Reference: the AFL harness + build targets (docs/fuzzing.md:1-40,
Makefile.am:144) — instrumented edge coverage steering an input-mutation
loop.  The reference gets its instrumentation from afl-clang at compile
time; this build gets it from CPython's sys.monitoring (PEP 669): LINE
and BRANCH events over the package's own code, with per-location
DISABLE after first hit, so steady-state overhead is near zero and "any
callback fired" == "this input reached code no previous input reached".

The loop is AFL-shaped: seed corpus from the existing generators, pick
a corpus entry, mutate (bit/byte flips, arithmetic, block ops, splice),
run it through the same TransactionFuzzer/OverlayFuzzer targets the
one-shot `fuzz` command uses, keep inputs that light up new coverage,
record crashing inputs (any escape that is not a clean reject).
"""

from __future__ import annotations

import os
import random
import sys
import time
from typing import List, Optional

from ..util.logging import get_logger

log = get_logger("default")

_PKG_PREFIX = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class CoverageMonitor:
    """sys.monitoring-backed global-novelty coverage map."""

    TOOL_ID = 4     # a free slot (0-2 are claimed by debugger/coverage/
    # profiler conventions; 4 is ours)

    def __init__(self, prefix: str = _PKG_PREFIX):
        self.prefix = prefix
        self.total_locations = 0
        self._new_this_input = 0
        self._mon = sys.monitoring

    def start(self) -> None:
        m = self._mon
        m.use_tool_id(self.TOOL_ID, "stellar-fuzz-cov")
        m.register_callback(self.TOOL_ID, m.events.LINE, self._on_line)
        m.register_callback(self.TOOL_ID, m.events.BRANCH,
                            self._on_branch)
        m.set_events(self.TOOL_ID, m.events.LINE | m.events.BRANCH)

    def stop(self) -> None:
        m = self._mon
        m.set_events(self.TOOL_ID, 0)
        m.free_tool_id(self.TOOL_ID)

    # callbacks return DISABLE so each location reports exactly once —
    # the coverage map "fills up" and later hits cost nothing
    def _on_line(self, code, line):
        if code.co_filename.startswith(self.prefix):
            self.total_locations += 1
            self._new_this_input += 1
        return self._mon.DISABLE

    def _on_branch(self, code, offset, dest):
        if code.co_filename.startswith(self.prefix):
            self.total_locations += 1
            self._new_this_input += 1
        return self._mon.DISABLE

    def begin_input(self) -> None:
        self._new_this_input = 0

    def new_coverage(self) -> int:
        return self._new_this_input


class Mutator:
    """AFL-style havoc mutations on raw bytes."""

    def __init__(self, rng: random.Random):
        self.rng = rng

    def mutate(self, data: bytes, other: Optional[bytes] = None) -> bytes:
        buf = bytearray(data)
        rng = self.rng
        for _ in range(rng.randint(1, 8)):
            if not buf:
                buf = bytearray(rng.randbytes(rng.randint(1, 64)))
                continue
            k = rng.randint(0, 7)
            i = rng.randrange(len(buf))
            if k == 0:                         # bit flip
                buf[i] ^= 1 << rng.randint(0, 7)
            elif k == 1:                       # byte set
                buf[i] = rng.randint(0, 255)
            elif k == 2:                       # arithmetic +-
                buf[i] = (buf[i] + rng.choice((1, -1, 16, -16))) & 0xFF
            elif k == 3:                       # interesting 32-bit value
                v = rng.choice((0, 1, 0x7FFFFFFF, 0x80000000,
                                0xFFFFFFFF, 100, 255))
                chunk = v.to_bytes(4, rng.choice(("big", "little")))
                buf[i:i + 4] = chunk
            elif k == 4:                       # delete block
                j = min(len(buf), i + rng.randint(1, 16))
                del buf[i:j]
            elif k == 5:                       # duplicate block
                j = min(len(buf), i + rng.randint(1, 16))
                buf[i:i] = buf[i:j]
            elif k == 6:                       # insert random block
                buf[i:i] = rng.randbytes(rng.randint(1, 16))
            elif k == 7 and other:             # splice with another input
                j = rng.randrange(len(other))
                buf = bytearray(buf[:i] + other[j:])
        return bytes(buf)


class FuzzStats:
    def __init__(self):
        self.runs = 0
        self.interesting = 0
        self.crashes: List[bytes] = []
        self.corpus_size = 0
        self.total_locations = 0


def run_coverage_fuzz(mode: str, runs: int = 200, seed: int = 1,
                      corpus_dir: Optional[str] = None,
                      time_budget: Optional[float] = None) -> FuzzStats:
    """The loop.  `runs` bounds iterations (deterministic tests);
    `time_budget` (seconds) bounds wall clock (ops usage, e.g. the
    10-minute soak from the reference's fuzzing docs)."""
    import tempfile

    from .fuzzer import OverlayFuzzer, TransactionFuzzer

    rng = random.Random(seed)
    mut = Mutator(rng)
    stats = FuzzStats()
    cls = TransactionFuzzer if mode == "tx" else OverlayFuzzer

    # seed corpus from the generative fuzzer (reference: gen-fuzz seeds)
    tmp = tempfile.mkdtemp(prefix="fuzz-cov-")
    corpus: List[bytes] = []
    for i in range(8):
        p = os.path.join(tmp, f"seed{i}")
        cls.gen_fuzz(p, seed * 100 + i)
        with open(p, "rb") as f:
            corpus.append(f.read())

    target = cls()
    cov = CoverageMonitor()
    cov.start()
    inject_path = os.path.join(tmp, "cur")
    t0 = time.monotonic()
    try:
        # first pass: replay seeds so their coverage is in the map
        for data in list(corpus):
            with open(inject_path, "wb") as f:
                f.write(data)
            cov.begin_input()
            try:
                target.inject(inject_path)
            except Exception:
                stats.crashes.append(data)

        while stats.runs < runs:
            if time_budget is not None and \
                    time.monotonic() - t0 > time_budget:
                break
            stats.runs += 1
            base = rng.choice(corpus)
            other = rng.choice(corpus)
            data = mut.mutate(base, other)
            with open(inject_path, "wb") as f:
                f.write(data)
            cov.begin_input()
            try:
                target.inject(inject_path)
            except Exception as e:          # noqa: BLE001 — crash record
                stats.crashes.append(data)
                log.warning("fuzz crash (%s): %r", mode, e)
                # crashing targets may be wedged: rebuild
                try:
                    target.shutdown()
                except Exception:
                    pass
                target = cls()
                continue
            if cov.new_coverage():
                stats.interesting += 1
                corpus.append(data)
                if corpus_dir:
                    os.makedirs(corpus_dir, exist_ok=True)
                    name = f"{mode}-{len(corpus):05d}"
                    with open(os.path.join(corpus_dir, name), "wb") as f:
                        f.write(data)
    finally:
        cov.stop()
        try:
            target.shutdown()
        except Exception:
            pass
    stats.corpus_size = len(corpus)
    stats.total_locations = cov.total_locations
    if corpus_dir and stats.crashes:
        crash_dir = os.path.join(corpus_dir, "crashes")
        os.makedirs(crash_dir, exist_ok=True)
        for i, c in enumerate(stats.crashes):
            with open(os.path.join(crash_dir, f"{mode}-{i:03d}"),
                      "wb") as f:
                f.write(c)
    log.info("fuzz[%s]: %d runs, %d interesting, corpus %d, "
             "%d locations, %d crashes", mode, stats.runs,
             stats.interesting, stats.corpus_size,
             stats.total_locations, len(stats.crashes))
    return stats
