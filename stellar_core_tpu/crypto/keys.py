"""Keys and signature verification — the backend seam.

Reference: src/crypto/SecretKey.{h,cpp}. `PubKeyUtils.verify_sig` is the
single-signature hot path (SecretKey.cpp:427-460) with the global
RandomEvictionCache of 0xffff entries keyed by BLAKE2(key‖sig‖msg)
(SecretKey.cpp:37-60). Signing uses the OpenSSL-backed `cryptography` package
(signatures are standard RFC 8032, byte-identical to libsodium's).

Verification uses the strongest available strict backend:
  1. native C++ (stellar_core_tpu/native) when built — fast path
  2. strict prechecks (canonicality, small-order) + OpenSSL for the equation

Both agree with crypto/ed25519_ref.verify on every input by construction;
tests/test_crypto.py enforces it differentially.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

try:
    from cryptography.hazmat.primitives.asymmetric import \
        ed25519 as _ossl_ed
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import serialization as _ser
except ImportError:                                  # pragma: no cover
    # gate the OpenSSL backend: containers without the `cryptography`
    # wheel fall back to the pure-python reference implementation
    # (byte-identical RFC 8032 signatures, just slower)
    _ossl_ed = None
    _ser = None
    InvalidSignature = Exception

from . import ed25519_ref
from .sha import blake2b_256
from ..util.cache import RandomEvictionCache

# reference: crypto/SecretKey.cpp:44 — 0xffff entries
VERIFY_CACHE_SIZE = 0xFFFF
_verify_cache: RandomEvictionCache = RandomEvictionCache(VERIFY_CACHE_SIZE)


def flush_verify_cache_counts() -> tuple:
    """Return (hits, misses) and reset (reference: SecretKey.cpp:324-331)."""
    h, m = _verify_cache.hits, _verify_cache.misses
    _verify_cache.reset_counters()
    return h, m


def clear_verify_cache() -> None:
    _verify_cache.clear()


def verify_cache_key(pub: bytes, sig: bytes, msg: bytes) -> bytes:
    """The cache key verify_sig uses (reference: SecretKey.cpp:37-60) —
    exposed so batch front-ends share one derivation."""
    return blake2b_256(pub + sig + msg)


def probe_verify_cache(pub: bytes, sig: bytes,
                       msg: bytes) -> Optional[bool]:
    """Counting cache probe for batch front-ends (the txset
    prevalidator): same key derivation and hit/miss accounting as
    PubKeyUtils.verify_sig's own lookup."""
    return _verify_cache.maybe_get(verify_cache_key(pub, sig, msg))


def seed_verify_cache(pub: bytes, sig: bytes, msg: bytes,
                      ok: bool) -> None:
    """Write a batch-verify result through to the process-wide cache so
    later per-signature verifies of the same tuple (apply-time
    re-verification of flood-admitted or prevalidated txs) hit instead
    of re-verifying."""
    _verify_cache.put(verify_cache_key(pub, sig, msg), bool(ok))


def seed_verify_cache_by_key(key: bytes, ok: bool) -> None:
    """Key-based write-through for callers that already derived the
    key (the verify service derives it once per submit)."""
    _verify_cache.put(key, bool(ok))


def _native_verify() -> Optional[object]:
    """The native C++ strict verifier, if the extension is built."""
    try:
        from ..native import loader
        return loader.get_lib()
    except Exception:
        return None


class PublicKey:
    """32-byte Ed25519 public key (reference: PublicKey XDR union, one arm)."""

    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        assert len(raw) == 32
        self.raw = bytes(raw)

    def hint(self) -> bytes:
        """Last 4 bytes — the SignatureHint prefilter used before any crypto
        (reference: SignatureUtils::getHint, transactions/SignatureUtils.cpp)."""
        return self.raw[28:]

    def __eq__(self, other) -> bool:
        return isinstance(other, PublicKey) and self.raw == other.raw

    def __hash__(self) -> int:
        return hash(self.raw)

    def __repr__(self) -> str:
        from .strkey import StrKey
        return f"PublicKey({StrKey.encode_ed25519_public(self.raw)})"


class SecretKey:
    """Ed25519 secret key (seed form), reference: crypto/SecretKey.h:22."""

    __slots__ = ("seed", "_ossl", "_pub")

    def __init__(self, seed: bytes):
        assert len(seed) == 32
        self.seed = bytes(seed)
        if _ossl_ed is not None:
            self._ossl = _ossl_ed.Ed25519PrivateKey.from_private_bytes(
                self.seed)
            pub = self._ossl.public_key().public_bytes(
                _ser.Encoding.Raw, _ser.PublicFormat.Raw)
        else:
            self._ossl = None
            lib = _native_verify()
            pub = lib.public_from_seed(self.seed) if lib is not None \
                else ed25519_ref.secret_to_public(self.seed)
        self._pub = PublicKey(pub)

    @classmethod
    def random(cls) -> "SecretKey":
        return cls(os.urandom(32))

    @classmethod
    def from_seed(cls, seed: bytes) -> "SecretKey":
        return cls(seed)

    @classmethod
    def pseudo_random_for_testing(cls, n: int) -> "SecretKey":
        """Deterministic test keys (reference: SecretKey::pseudoRandomForTesting)."""
        return cls(hashlib.sha256(b"test-key-%d" % n).digest())

    def public_key(self) -> PublicKey:
        return self._pub

    def sign(self, msg: bytes) -> bytes:
        if self._ossl is not None:
            return self._ossl.sign(msg)
        # containers without the `cryptography` wheel: the native C
        # signer (byte-identical RFC 8032) — a pure-python pt_mul per
        # signature measured as the TPSMT leg's single largest cost
        # (ISSUE 12: 2.2s of a 6.4s ledger wall went to loadgen + SCP
        # envelope signing)
        lib = _native_verify()
        if lib is not None:
            return lib.sign(self.seed, self._pub.raw, msg)
        return ed25519_ref.sign(self.seed, msg)

    def __repr__(self) -> str:
        return "SecretKey(<hidden>)"


class PubKeyUtils:
    """Static verify helpers (reference: PubKeyUtils, crypto/SecretKey.h:127)."""

    @staticmethod
    def verify_sig(pub: PublicKey | bytes, sig: bytes, msg: bytes,
                   use_cache: bool = True) -> bool:
        raw = pub.raw if isinstance(pub, PublicKey) else pub
        if len(raw) != 32 or len(sig) != 64:
            return False
        if use_cache:
            key = blake2b_256(raw + sig + msg)
            hit = _verify_cache.maybe_get(key)
            if hit is not None:
                return hit
        ok = verify_sig_uncached(raw, sig, msg)
        if use_cache:
            _verify_cache.put(key, ok)
        return ok


def verify_sig_uncached(pub: bytes, sig: bytes, msg: bytes) -> bool:
    lib = _native_verify()
    if lib is not None:
        return lib.verify(pub, sig, msg)
    return _verify_strict_openssl(pub, sig, msg)


def _verify_strict_openssl(pub: bytes, sig: bytes, msg: bytes) -> bool:
    """Strict prechecks in Python + OpenSSL for the group equation."""
    if _ossl_ed is None:
        # no OpenSSL backend in this container: the reference
        # implementation is already strict end-to-end
        return ed25519_ref.verify(pub, sig, msg)
    S = int.from_bytes(sig[32:], "little")
    if S >= ed25519_ref.L:
        return False
    A = ed25519_ref.pt_decompress(pub, strict=True)
    if A is None or ed25519_ref.pt_is_small_order(A):
        return False
    R = ed25519_ref.pt_decompress(sig[:32], strict=True)
    if R is None or ed25519_ref.pt_is_small_order(R):
        return False
    try:
        _ossl_ed.Ed25519PublicKey.from_public_bytes(pub).verify(sig, msg)
        return True
    except InvalidSignature:
        return False
    except Exception:
        # encoding OpenSSL refuses outright — strict path rejects too
        return False
