"""Crypto layer (reference: src/crypto — SURVEY.md layer 2).

This is the abstraction the TPU backend slots behind: `PubKeyUtils.verify_sig`
is the single-signature seam (reference: crypto/SecretKey.h:127), and
`BatchVerifier` (crypto/batch.py) is the batch seam feeding the JAX kernel.

Verification semantics — identical across ALL backends ("strict" rules,
matching libsodium's crypto_sign_verify_detached as described in
crypto/SecretKey.cpp:427-460):
  * reject non-canonical scalar S (S >= L)
  * reject non-canonical point encodings (y >= p, and -0)
  * reject small-order A and R (order dividing 8)
  * cofactorless equation [S]B == R + [k]A with k = SHA512(R‖A‖M) mod L
"""

from .keys import PublicKey, SecretKey, PubKeyUtils
from .sha import sha256, sha512, hmac_sha256, hkdf_extract, hkdf_expand
from .strkey import StrKey

__all__ = [
    "PublicKey", "SecretKey", "PubKeyUtils",
    "sha256", "sha512", "hmac_sha256", "hkdf_extract", "hkdf_expand",
    "StrKey",
]
