"""StrKey — human-readable key encoding (reference: src/crypto/StrKey.{h,cpp}).

base32(version-byte ‖ payload ‖ CRC16-XMODEM), no padding. Version bytes per
the Stellar strkey spec (StrKey.h enum): G=public, S=seed, T=pre-auth-tx,
X=hash-x, P=signed-payload, M=muxed-account, C=contract.
"""

from __future__ import annotations

import base64


class StrKeyError(ValueError):
    pass


# version byte = enum << 3 (so the first base32 char is the letter)
VER_PUBKEY_ED25519 = 6 << 3       # 'G'
VER_SEED_ED25519 = 18 << 3        # 'S'
VER_PRE_AUTH_TX = 19 << 3         # 'T'
VER_HASH_X = 23 << 3              # 'X'
VER_SIGNED_PAYLOAD = 15 << 3      # 'P'
VER_MUXED_ACCOUNT = 12 << 3       # 'M'
VER_CONTRACT = 2 << 3             # 'C'


def crc16_xmodem(data: bytes) -> int:
    crc = 0
    for b in data:
        crc ^= b << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) & 0xFFFF if crc & 0x8000 else (crc << 1) & 0xFFFF
    return crc


class StrKey:
    @staticmethod
    def encode(version: int, payload: bytes) -> str:
        body = bytes([version]) + payload
        crc = crc16_xmodem(body)
        body += crc.to_bytes(2, "little")
        return base64.b32encode(body).decode().rstrip("=")

    @staticmethod
    def decode(expected_version: int, s: str) -> bytes:
        pad = "=" * (-len(s) % 8)
        try:
            body = base64.b32decode(s + pad)
        except Exception as e:
            raise StrKeyError(f"bad base32: {e}")
        if len(body) < 3:
            raise StrKeyError("too short")
        version, payload, crc = body[0], body[1:-2], body[-2:]
        if version != expected_version:
            raise StrKeyError(f"version byte mismatch: {version}")
        if crc16_xmodem(body[:-2]).to_bytes(2, "little") != crc:
            raise StrKeyError("checksum mismatch")
        # round-trip check rejects non-canonical encodings (reference:
        # StrKey.cpp decode verifies re-encode identity)
        if StrKey.encode(version, payload) != s:
            raise StrKeyError("non-canonical strkey")
        return payload

    # convenience wrappers
    @staticmethod
    def encode_ed25519_public(raw32: bytes) -> str:
        return StrKey.encode(VER_PUBKEY_ED25519, raw32)

    @staticmethod
    def decode_ed25519_public(s: str) -> bytes:
        out = StrKey.decode(VER_PUBKEY_ED25519, s)
        if len(out) != 32:
            raise StrKeyError("bad length")
        return out

    @staticmethod
    def encode_ed25519_seed(raw32: bytes) -> str:
        return StrKey.encode(VER_SEED_ED25519, raw32)

    @staticmethod
    def decode_ed25519_seed(s: str) -> bytes:
        out = StrKey.decode(VER_SEED_ED25519, s)
        if len(out) != 32:
            raise StrKeyError("bad length")
        return out

    @staticmethod
    def encode_contract(raw32: bytes) -> str:
        return StrKey.encode(VER_CONTRACT, raw32)

    @staticmethod
    def encode_muxed_account(ed25519_raw: bytes, mux_id: int) -> str:
        """M-address (SEP-23 / CAP-27): 40-byte payload = ed25519 key
        followed by the big-endian 8-byte mux id."""
        return StrKey.encode(VER_MUXED_ACCOUNT,
                             ed25519_raw + mux_id.to_bytes(8, "big"))

    @staticmethod
    def decode_muxed_account(s: str):
        out = StrKey.decode(VER_MUXED_ACCOUNT, s)
        if len(out) != 40:
            raise StrKeyError("bad length")
        return out[:32], int.from_bytes(out[32:], "big")
