"""Hashing primitives (reference: src/crypto/SHA.{h,cpp}, BLAKE2.{h,cpp}).

SHA-256 is the canonical object-hash of the protocol (ledger headers, tx
contents hashes, bucket hashes); HMAC-SHA256 + HKDF back the overlay's
per-connection message authentication (crypto/SHA.cpp, overlay/PeerAuth.h).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


def blake2b_256(data: bytes) -> bytes:
    """BLAKE2b-256 (reference: crypto/BLAKE2.cpp; used for the verify-cache key)."""
    return hashlib.blake2b(data, digest_size=32).digest()


class SHA256:
    """Incremental hasher (reference: SHA256 add/finish, crypto/SHA.h)."""

    def __init__(self):
        self._h = hashlib.sha256()

    def add(self, data: bytes) -> "SHA256":
        self._h.update(data)
        return self

    def finish(self) -> bytes:
        return self._h.digest()


def hmac_sha256(key: bytes, data: bytes) -> bytes:
    return _hmac.new(key, data, hashlib.sha256).digest()


def hmac_sha256_verify(key: bytes, data: bytes, mac: bytes) -> bool:
    return _hmac.compare_digest(hmac_sha256(key, data), mac)


def hkdf_extract(ikm: bytes, salt: bytes = b"\x00" * 32) -> bytes:
    """HKDF-Extract with SHA-256 (reference: crypto/SHA.cpp hkdfExtract)."""
    return hmac_sha256(salt, ikm)


def hkdf_expand(prk: bytes, info: bytes, length: int = 32) -> bytes:
    """HKDF-Expand with SHA-256 (RFC 5869; reference: SHA.cpp hkdfExpand)."""
    out = b""
    t = b""
    i = 1
    while len(out) < length:
        t = hmac_sha256(prk, t + info + bytes([i]))
        out += t
        i += 1
    return out[:length]
