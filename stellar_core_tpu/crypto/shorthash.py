"""ShortHash — SipHash-2-4 for hashtable seeds (reference: src/crypto/ShortHash.cpp:78).

The reference seeds a process-global SipHash key at startup from the CSPRNG,
with a deterministic re-seed hook for fuzzing (crypto/ShortHash.h). Used for
non-cryptographic hashing (BucketList shadow maps, unordered containers).
"""

from __future__ import annotations

import os
import struct

_MASK = 0xFFFFFFFFFFFFFFFF


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & _MASK


def siphash24(key16: bytes, data: bytes) -> int:
    """SipHash-2-4 returning a 64-bit int."""
    assert len(key16) == 16
    k0, k1 = struct.unpack("<QQ", key16)
    v0 = k0 ^ 0x736F6D6570736575
    v1 = k1 ^ 0x646F72616E646F6D
    v2 = k0 ^ 0x6C7967656E657261
    v3 = k1 ^ 0x7465646279746573

    def rounds(n):
        nonlocal v0, v1, v2, v3
        for _ in range(n):
            v0 = (v0 + v1) & _MASK
            v1 = _rotl(v1, 13) ^ v0
            v0 = _rotl(v0, 32)
            v2 = (v2 + v3) & _MASK
            v3 = _rotl(v3, 16) ^ v2
            v0 = (v0 + v3) & _MASK
            v3 = _rotl(v3, 21) ^ v0
            v2 = (v2 + v1) & _MASK
            v1 = _rotl(v1, 17) ^ v2
            v2 = _rotl(v2, 32)

    b = len(data) << 56
    i = 0
    while i + 8 <= len(data):
        m = struct.unpack_from("<Q", data, i)[0]
        v3 ^= m
        rounds(2)
        v0 ^= m
        i += 8
    tail = data[i:]
    m = b | int.from_bytes(tail, "little")
    v3 ^= m
    rounds(2)
    v0 ^= m
    v2 ^= 0xFF
    rounds(4)
    return (v0 ^ v1 ^ v2 ^ v3) & _MASK


_seed: bytes = os.urandom(16)


def initialize() -> None:
    global _seed
    _seed = os.urandom(16)


def seed_for_testing(key16: bytes) -> None:
    """Deterministic seed (reference: shortHash::seed for fuzzing)."""
    global _seed
    assert len(key16) == 16
    _seed = key16


def compute_hash(data: bytes) -> int:
    return siphash24(_seed, data)
