"""Pure-Python Ed25519 reference implementation — the semantic oracle.

Every production backend (native C++ in stellar_core_tpu/native, JAX/TPU in
stellar_core_tpu/ops) must agree bit-for-bit with this module on accept/reject
for every input. It implements RFC 8032 verification with the strict rules of
libsodium's crypto_sign_verify_detached (reference: crypto/SecretKey.cpp:453
and libsodium's ed25519_verify): non-canonical S/A/R rejected, small-order
A/R rejected, cofactorless check.

Slow (Python bignums) — used only for tests and one-off operations.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

# Extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, xy=T/Z.
Point = Tuple[int, int, int, int]
IDENTITY: Point = (0, 1, 1, 0)

# base point: y = 4/5
_by = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> Optional[int]:
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x == 0 and sign == 1:
        return None  # "-0" is not a valid encoding
    if x & 1 != sign:
        x = P - x
    return x


_bx = _recover_x(_by, 0)
assert _bx is not None
BASE: Point = (_bx, _by, 1, _bx * _by % P)


def pt_add(p: Point, q: Point) -> Point:
    # add-2008-hwcd-3 (same formulas the ref10/libsodium family uses)
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * D % P * T2 % P
    Dd = 2 * Z1 * Z2 % P
    E = B - A
    F = Dd - C
    G = Dd + C
    H = B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def pt_double(p: Point) -> Point:
    return pt_add(p, p)


def pt_mul(s: int, p: Point) -> Point:
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = pt_add(q, p)
        p = pt_double(p)
        s >>= 1
    return q


def pt_equal(p: Point, q: Point) -> bool:
    X1, Y1, Z1, _ = p
    X2, Y2, Z2, _ = q
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def pt_neg(p: Point) -> Point:
    X, Y, Z, T = p
    return (P - X if X else 0, Y, Z, P - T if T else 0)


def pt_is_small_order(p: Point) -> bool:
    """Order divides 8 <=> [8]P = identity (libsodium has_small_order)."""
    return pt_equal(pt_mul(8, p), IDENTITY)


def pt_compress(p: Point) -> bytes:
    X, Y, Z, _ = p
    zi = pow(Z, P - 2, P)
    x = X * zi % P
    y = Y * zi % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def pt_decompress(s: bytes, strict: bool = True) -> Optional[Point]:
    if len(s) != 32:
        return None
    val = int.from_bytes(s, "little")
    y = val & ((1 << 255) - 1)
    sign = val >> 255
    if strict and y >= P:
        return None
    y %= P
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def _clamp(h32: bytes) -> int:
    a = bytearray(h32)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


def secret_to_public(seed: bytes) -> bytes:
    a = _clamp(hashlib.sha512(seed).digest()[:32])
    return pt_compress(pt_mul(a, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h[:32])
    prefix = h[32:]
    A_enc = pt_compress(pt_mul(a, BASE))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = pt_mul(r, BASE)
    R_enc = pt_compress(R)
    k = int.from_bytes(hashlib.sha512(R_enc + A_enc + msg).digest(), "little") % L
    S = (r + k * a) % L
    return R_enc + int.to_bytes(S, 32, "little")


def compute_k(R_enc: bytes, A_enc: bytes, msg: bytes) -> int:
    """k = SHA512(R‖A‖M) mod L — the host-side hash step of batch verify."""
    return int.from_bytes(hashlib.sha512(R_enc + A_enc + msg).digest(), "little") % L


def verify(pub: bytes, sig: bytes, msg: bytes) -> bool:
    """Strict verification — the framework-wide accept/reject contract."""
    if len(pub) != 32 or len(sig) != 64:
        return False
    S = int.from_bytes(sig[32:], "little")
    if S >= L:
        return False
    A = pt_decompress(pub, strict=True)
    if A is None:
        return False
    R = pt_decompress(sig[:32], strict=True)
    if R is None:
        return False
    if pt_is_small_order(A) or pt_is_small_order(R):
        return False
    k = compute_k(sig[:32], pub, msg)
    # [S]B == R + [k]A
    return pt_equal(pt_mul(S, BASE), pt_add(R, pt_mul(k, A)))
