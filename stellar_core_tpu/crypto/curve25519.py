"""Curve25519 ECDH for overlay peer auth (reference: src/crypto/Curve25519.{h,cpp}).

The overlay handshake exchanges short-lived X25519 keys (signed by the node's
long-lived Ed25519 identity) and derives directional HMAC-SHA256 session keys
via ECDH → HKDF (reference: overlay/PeerAuth.h:17-48).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from cryptography.hazmat.primitives.asymmetric import x25519 as _x
from cryptography.hazmat.primitives import serialization as _ser

from .sha import hkdf_extract, hkdf_expand


@dataclass(frozen=True)
class Curve25519Public:
    key: bytes  # 32 bytes


class Curve25519Secret:
    __slots__ = ("key", "_priv")

    def __init__(self, raw32: bytes):
        assert len(raw32) == 32
        self.key = bytes(raw32)
        self._priv = _x.X25519PrivateKey.from_private_bytes(self.key)

    @classmethod
    def random(cls) -> "Curve25519Secret":
        return cls(os.urandom(32))

    def derive_public(self) -> Curve25519Public:
        pub = self._priv.public_key().public_bytes(
            _ser.Encoding.Raw, _ser.PublicFormat.Raw)
        return Curve25519Public(pub)

    def ecdh(self, remote: Curve25519Public, local_first: bool) -> bytes:
        """Shared key = HKDF-Extract(q ‖ publicA ‖ publicB) per the reference
        (crypto/Curve25519.cpp curve25519DeriveSharedKey); ordering is fixed
        by the caller's role so both sides derive the same bytes."""
        q = self._priv.exchange(_x.X25519PublicKey.from_public_bytes(remote.key))
        mine = self.derive_public().key
        ab = (mine + remote.key) if local_first else (remote.key + mine)
        return hkdf_extract(q + ab)


def expand_session_key(shared: bytes, info: bytes) -> bytes:
    """Directional session key (reference: PeerAuth HKDF-Expand usage)."""
    return hkdf_expand(shared, info, 32)
