"""Curve25519 ECDH for overlay peer auth (reference: src/crypto/Curve25519.{h,cpp}).

The overlay handshake exchanges short-lived X25519 keys (signed by the node's
long-lived Ed25519 identity) and derives directional HMAC-SHA256 session keys
via ECDH → HKDF (reference: overlay/PeerAuth.h:17-48).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

try:
    from cryptography.hazmat.primitives.asymmetric import x25519 as _x
    from cryptography.hazmat.primitives import serialization as _ser
except ImportError:                                  # pragma: no cover
    # gate the OpenSSL backend: fall back to the RFC 7748 ladder below
    _x = None
    _ser = None

from .sha import hkdf_extract, hkdf_expand

# ------------------------------------------------- RFC 7748 fallback --
_P = 2 ** 255 - 19
_A24 = 121665


def _x25519(k: bytes, u: bytes) -> bytes:
    """X25519 scalar multiplication (RFC 7748 §5): the pure-python
    Montgomery ladder used when the OpenSSL backend is unavailable."""
    sk = bytearray(k)
    sk[0] &= 248
    sk[31] &= 127
    sk[31] |= 64
    scalar = int.from_bytes(bytes(sk), "little")
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (scalar >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = z3 * z3 % _P
        z3 = z3 * x1 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, _P - 2, _P) % _P
    return out.to_bytes(32, "little")


@dataclass(frozen=True)
class Curve25519Public:
    key: bytes  # 32 bytes


class Curve25519Secret:
    __slots__ = ("key", "_priv")

    def __init__(self, raw32: bytes):
        assert len(raw32) == 32
        self.key = bytes(raw32)
        self._priv = (_x.X25519PrivateKey.from_private_bytes(self.key)
                      if _x is not None else None)

    @classmethod
    def random(cls) -> "Curve25519Secret":
        return cls(os.urandom(32))

    def derive_public(self) -> Curve25519Public:
        if self._priv is None:
            return Curve25519Public(
                _x25519(self.key, (9).to_bytes(32, "little")))
        pub = self._priv.public_key().public_bytes(
            _ser.Encoding.Raw, _ser.PublicFormat.Raw)
        return Curve25519Public(pub)

    def ecdh(self, remote: Curve25519Public, local_first: bool) -> bytes:
        """Shared key = HKDF-Extract(q ‖ publicA ‖ publicB) per the reference
        (crypto/Curve25519.cpp curve25519DeriveSharedKey); ordering is fixed
        by the caller's role so both sides derive the same bytes."""
        if self._priv is None:
            q = _x25519(self.key, remote.key)
        else:
            q = self._priv.exchange(
                _x.X25519PublicKey.from_public_bytes(remote.key))
        mine = self.derive_public().key
        ab = (mine + remote.key) if local_first else (remote.key + mine)
        return hkdf_extract(q + ab)


def expand_session_key(shared: bytes, info: bytes) -> bytes:
    """Directional session key (reference: PeerAuth HKDF-Expand usage)."""
    return hkdf_expand(shared, info, 32)
