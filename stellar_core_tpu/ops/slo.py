"""Declarative SLO rules + watchdog over the telemetry time-series.

The Tail-at-Scale argument (Dean & Barroso, CACM 2013; PAPERS.md
§Robustness) is that tail behavior must be gated continuously with
deadlines and health state, not inspected after the fact. This module
is that gate: a small set of declarative rules — close-latency p99
ceiling, tx-e2e p99 ceiling, breaker-OPEN dwell, flood duplicate-ratio
ceiling — evaluated against every sample the ``TelemetrySampler``
(util/timeseries.py) appends, each emitting an OK / WARN / BREACH
verdict.

Rule semantics (deterministic under VirtualClock — all timing reads
the sample's own ``t``, never the wall):

- a rule extracts one numeric from the sample by key path (a missing
  section or zero-count timer is OK — no data is not a breach);
- value ≥ ``threshold`` starts (or continues) a breach window; the
  verdict turns BREACH once the window has lasted ``dwell_s``
  (``dwell_s=0`` breaches immediately). Below threshold the window
  resets;
- value ≥ ``warn_ratio × threshold`` (default 0.8) is WARN — the
  early-warning band; a breach window still inside its dwell also
  reads WARN (breaching-but-not-yet-sustained).

Verdicts surface three ways: ``slo.<rule>.{ok,warn,breach}`` metrics
counters (metrics route + Prometheus exposition, SUMmable across
nodes), flight-recorder instants (``slo.<rule>``) on every verdict
TRANSITION while a trace is recording, and the ``slo`` admin route's
structured status document (per rule: verdict, last value, threshold,
breach tally, since-when). ``clearmetrics`` resets the window state
via ``reset()`` — the PR 7 reset contract: bench legs sharing one
process must start clean.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

OK = "OK"
WARN = "WARN"
BREACH = "BREACH"
_SEVERITY = {OK: 0, WARN: 1, BREACH: 2}


class SloRule:
    """One declarative objective: ``value(path) < threshold``,
    sustained-breach detection via ``dwell_s``."""

    __slots__ = ("name", "path", "threshold", "warn_ratio", "dwell_s",
                 "description")

    def __init__(self, name: str, path: Sequence[str], threshold: float,
                 warn_ratio: float = 0.8, dwell_s: float = 0.0,
                 description: str = ""):
        self.name = name
        self.path = tuple(path)
        self.threshold = float(threshold)
        self.warn_ratio = float(warn_ratio)
        self.dwell_s = max(0.0, float(dwell_s))
        self.description = description

    def value(self, sample: dict) -> Optional[float]:
        """Walk the key path; None when the section is absent (no
        overlay / no device backend / zero-count timer)."""
        node = sample
        for key in self.path:
            if not isinstance(node, dict) or key not in node \
                    or node[key] is None:
                return None
            node = node[key]
        if isinstance(node, bool) or not isinstance(node, (int, float)):
            return None
        return float(node)


class _RuleState:
    __slots__ = ("verdict", "value", "breach_since", "last_change_t",
                 "breaches", "warns")

    def __init__(self):
        self.verdict = OK
        self.value: Optional[float] = None
        self.breach_since: Optional[float] = None
        self.last_change_t: Optional[float] = None
        self.breaches = 0
        self.warns = 0


class SloWatchdog:
    """Evaluates every telemetry sample against the rule set; keeps
    per-rule sliding state keyed on sample time (VirtualClock in sims,
    wall clock in `run` mode — whatever stamped the sample)."""

    def __init__(self, rules: List[SloRule], metrics=None,
                 recorder=None):
        self.rules = list(rules)
        self._recorder = recorder
        self._metrics = metrics
        self._counters: Dict[Tuple[str, str], object] = {}
        if metrics is not None:
            for rule in self.rules:
                for verdict in (OK, WARN, BREACH):
                    self._counters[(rule.name, verdict)] = \
                        metrics.counter("slo", rule.name,
                                        verdict.lower())
        self._state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self.rules}
        self.evaluations = 0

    # ---------------------------------------------------------- evaluate --
    def observe(self, sample: dict) -> None:
        """TelemetrySampler observer hook: judge one sample."""
        self.evaluations += 1
        t = sample.get("t", 0.0)
        for rule in self.rules:
            st = self._state[rule.name]
            v = rule.value(sample)
            st.value = v
            if v is None or v < rule.threshold:
                st.breach_since = None
                verdict = WARN if (
                    v is not None
                    and rule.warn_ratio < 1.0
                    and v >= rule.warn_ratio * rule.threshold) else OK
            else:
                if st.breach_since is None:
                    st.breach_since = t
                verdict = BREACH if (t - st.breach_since
                                     >= rule.dwell_s) else WARN
            if verdict == BREACH:
                st.breaches += 1
            elif verdict == WARN:
                st.warns += 1
            counter = self._counters.get((rule.name, verdict))
            if counter is not None:
                counter.inc()
            if verdict != st.verdict:
                st.last_change_t = t
                self._instant(rule, verdict, v, t)
            st.verdict = verdict

    def _instant(self, rule: SloRule, verdict: str,
                 value: Optional[float], t: float) -> None:
        from ..util import tracing
        rec = self._recorder
        if tracing.ENABLED and rec is not None and rec.active:
            rec.instant("slo." + rule.name, {
                "verdict": verdict, "value": value,
                "threshold": rule.threshold, "t": t})

    # ------------------------------------------------------------ report --
    def overall(self) -> str:
        worst = OK
        for st in self._state.values():
            if _SEVERITY[st.verdict] > _SEVERITY[worst]:
                worst = st.verdict
        return worst

    def status(self) -> dict:
        """The `slo` admin route document."""
        rules = {}
        for rule in self.rules:
            st = self._state[rule.name]
            rules[rule.name] = {
                "verdict": st.verdict,
                "value": st.value,
                "threshold": rule.threshold,
                "warn_ratio": rule.warn_ratio,
                "dwell_s": rule.dwell_s,
                "breach_since": st.breach_since,
                "last_change_t": st.last_change_t,
                "breaches": st.breaches,
                "warns": st.warns,
                "description": rule.description,
            }
        return {"overall": self.overall(),
                "evaluations": self.evaluations,
                "rules": rules}

    def reset(self) -> None:
        """`clearmetrics` hook: drop every sliding window + tally (the
        slo.* counters live in the registry and reset with it)."""
        self.evaluations = 0
        for name in self._state:
            self._state[name] = _RuleState()


def default_rules(config) -> List[SloRule]:
    """The stock rule set, thresholds from config knobs (all
    docs/OBSERVABILITY.md §SLO watchdog)."""
    return [
        SloRule("close_p99", ("close", "p99_ms"),
                config.SLO_CLOSE_P99_MS,
                description="ledger close p99 ceiling (ms)"),
        SloRule("tx_e2e_p99", ("tx_e2e", "p99_ms"),
                config.SLO_TX_E2E_P99_MS,
                description="tx submit→externalize p99 ceiling (ms)"),
        SloRule("breaker_open_dwell", ("breaker_open",), 0.5,
                warn_ratio=1.0,
                dwell_s=config.SLO_BREAKER_OPEN_DWELL_S,
                description="device breaker OPEN longer than the "
                            "dwell (s) — degraded mode is no longer "
                            "transient"),
        SloRule("duplicate_ratio", ("flood", "duplicate_ratio"),
                config.SLO_DUPLICATE_RATIO_MAX,
                description="flood redundancy ceiling (duplicate "
                            "deliveries per unique message)"),
        SloRule("read_p99", ("query", "p99_ms"),
                config.SLO_READ_P99_MS,
                description="read-tier query latency p99 ceiling (ms) "
                            "— reads shed before writes on breach"),
    ]


def aggregate_status(docs: List[dict]) -> dict:
    """Merge per-node `slo` documents into one scenario-wide verdict
    section (bench artifacts, the cluster harness): worst verdict per
    rule across nodes, breach/warn tallies summed."""
    docs = [d for d in docs if d]
    if not docs:
        return {"overall": OK, "nodes": 0, "rules": {}}
    rules: Dict[str, dict] = {}
    overall = OK
    for doc in docs:
        doc_overall = doc.get("overall", OK)
        if _SEVERITY.get(doc_overall, 0) > _SEVERITY[overall]:
            overall = doc_overall
        for name, rd in doc.get("rules", {}).items():
            agg = rules.setdefault(name, {
                "verdict": OK, "breaches": 0, "warns": 0,
                "threshold": rd.get("threshold")})
            if _SEVERITY.get(rd.get("verdict"), 0) \
                    > _SEVERITY[agg["verdict"]]:
                agg["verdict"] = rd["verdict"]
            agg["breaches"] += rd.get("breaches", 0)
            agg["warns"] += rd.get("warns", 0)
    return {"overall": overall, "nodes": len(docs), "rules": rules}
