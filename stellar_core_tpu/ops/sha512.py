"""Batch SHA-512 + exact mod-L reduction on device (TPU, JAX/XLA).

Closes the last host-side per-signature cost in the verify pipeline:
k = SHA512(R‖A‖M) mod L was computed by one host core at ~47 k sig/s
(docs/KERNEL_PROFILE.md §4), bounding end-to-end throughput regardless
of kernel speed. For the dominant workload — transaction signatures,
which verify over a fixed 32-byte contents hash (SURVEY.md §3.2
"message shapes"; reference: transactions/TransactionFrame.cpp:99-107)
— R‖A‖M is exactly 96 bytes, one SHA-512 block after padding, with a
compile-time-constant layout. So the whole prep moves on device and the
host ships raw (A, R, S, M) bytes only.

TPU-first design:
- SHA-512's 64-bit words are (hi, lo) uint32 pairs — the VPU has no
  64-bit lanes. rotr/shr are shift/or pairs; 64-bit add is two uint32
  adds plus an unsigned-compare carry. All ops are elementwise over the
  batch (lane) axis: 80 unrolled rounds of straight-line vector code,
  zero control flow, fused by XLA.
- The 512-bit digest is reduced mod L (the edwards25519 group order)
  with byte-limb arithmetic matching fe8's layout: a table fold
  digest ≡ lo₃₂ + Σ d_{32+i}·(256^{32+i} mod L), repeated until the
  value fits 32 exact byte limbs, then four conditional subtractions
  of 8L/4L/2L/L. Exact reduction is semantics-critical: for a public
  key with a torsion component [k]A ≠ [k mod L]A, and libsodium
  (crypto/SecretKey.cpp:427-460 path → sc_reduce) uses k mod L.

Differentially tested against hashlib.sha512 and the pure-python oracle
(tests/test_tpu_verifier.py::TestDeviceSha).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

L = 2**252 + 27742317777372353535851937790883648493

# SHA-512 round constants as (hi, lo) uint32 pairs
_K = [
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f, 0xe9b5dba58189dbbc,
    0x3956c25bf348b538, 0x59f111f1b605d019, 0x923f82a4af194f9b, 0xab1c5ed5da6d8118,
    0xd807aa98a3030242, 0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235, 0xc19bf174cf692694,
    0xe49b69c19ef14ad2, 0xefbe4786384f25e3, 0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65,
    0x2de92c6f592b0275, 0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f, 0xbf597fc7beef0ee4,
    0xc6e00bf33da88fc2, 0xd5a79147930aa725, 0x06ca6351e003826f, 0x142929670a0e6e70,
    0x27b70a8546d22ffc, 0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6, 0x92722c851482353b,
    0xa2bfe8a14cf10364, 0xa81a664bbc423001, 0xc24b8b70d0f89791, 0xc76c51a30654be30,
    0xd192e819d6ef5218, 0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99, 0x34b0bcb5e19b48a8,
    0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb, 0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3,
    0x748f82ee5defb2fc, 0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915, 0xc67178f2e372532b,
    0xca273eceea26619c, 0xd186b8c721c0c207, 0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178,
    0x06f067aa72176fba, 0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc, 0x431d67c49c100d4c,
    0x4cc5d4becb3e42b6, 0x597f299cfc657e2a, 0x5fcb6fab3ad6faec, 0x6c44198c4a475817,
]
_IV = [
    0x6a09e667f3bcc908, 0xbb67ae8584caa73b, 0x3c6ef372fe94f82b, 0xa54ff53a5f1d36f1,
    0x510e527fade682d1, 0x9b05688c2b3e6c1f, 0x1f83d9abfb41bd6b, 0x5be0cd19137e2179,
]


def _split(c: int):
    return np.uint32(c >> 32), np.uint32(c & 0xFFFFFFFF)


def _add2(ah, al, bh, bl):
    """(a + b) mod 2^64 on (hi, lo) uint32 pairs."""
    lo = al + bl
    hi = ah + bh + (lo < al).astype(jnp.uint32)
    return hi, lo


def _rotr(h, l, n: int):
    n &= 63
    if n == 0:
        return h, l
    if n == 32:
        return l, h
    if n < 32:
        return ((h >> n) | (l << (32 - n)), (l >> n) | (h << (32 - n)))
    m = n - 32
    return ((l >> m) | (h << (32 - m)), (h >> m) | (l << (32 - m)))


def _shr(h, l, n: int):
    # n < 32 everywhere it is used (7 and 6)
    return h >> n, (l >> n) | (h << (32 - n))


def _xor3(a, b, c):
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


def _big_sigma0(h, l):
    return _xor3(_rotr(h, l, 28), _rotr(h, l, 34), _rotr(h, l, 39))


def _big_sigma1(h, l):
    return _xor3(_rotr(h, l, 14), _rotr(h, l, 18), _rotr(h, l, 41))


def _small_sigma0(h, l):
    return _xor3(_rotr(h, l, 1), _rotr(h, l, 8), _shr(h, l, 7))


def _small_sigma1(h, l):
    return _xor3(_rotr(h, l, 19), _rotr(h, l, 61), _shr(h, l, 6))


_K_ARR = np.array([[k >> 32, k & 0xFFFFFFFF] for k in _K], dtype=np.uint32)


import os as _os

# Scan-unroll factor for the 80 compression rounds: the sweet spot
# between compile time (fully unrolled ≈5k serially-dependent uint32 ops
# send XLA CPU past 9 minutes and stall the axon chip compile too) and
# scan-step overhead (each step copies the (16,2,B) schedule ring).
# Factors of 80 only. Swept on chip — see docs/KERNEL_PROFILE.md §5.
SHA_UNROLL = int(_os.environ.get("ED25519_SHA_UNROLL", "8"))


def sha512_96(r_u8, a_u8, m_u8):
    """Batch SHA-512 of the 96-byte message R‖A‖M (each (B,32) uint8).
    One block, compile-time-constant padding. Returns the digest as
    (64, B) int32 byte limbs in *little-endian byte position order*
    (d[0] = first digest byte), ready for mod-L reduction.

    The 80 rounds use the classic rolling 16-word schedule (W[t+16] is
    produced every step; it is first read at step t+16, so the
    recurrence is uniform over all 80 steps) as a lax.scan with
    SHA_UNROLL-chunked steps."""
    bsz = r_u8.shape[0]
    msg = jnp.concatenate([r_u8, a_u8, m_u8], axis=1).astype(jnp.uint32).T
    # (96, B) big-endian byte stream -> 12 (hi, lo) word pairs
    w = []
    for i in range(12):
        b8 = [msg[8 * i + j] for j in range(8)]
        hi = (b8[0] << 24) | (b8[1] << 16) | (b8[2] << 8) | b8[3]
        lo = (b8[4] << 24) | (b8[5] << 16) | (b8[6] << 8) | b8[7]
        w.append((hi, lo))
    # derive constants from the input so every scan-carry leaf shares the
    # input's device-varying type under shard_map (a replicated initial
    # carry vs a varying computed carry is a TypeError there)
    zero = msg[0] ^ msg[0]
    pad_h = zero + np.uint32(0x80000000)
    w.append((pad_h, zero))                       # byte 96 = 0x80
    w.append((zero, zero))
    w.append((zero, zero))
    w.append((zero, zero + np.uint32(96 * 8)))

    state = []
    for c in _IV:
        ch, cl = _split(c)
        state.append((zero + ch, zero + cl))

    def round_math(vars8, wh, wl, kh, kl):
        a, b, c_, d, e, f, g, hh = vars8
        ch = ((e[0] & f[0]) ^ (~e[0] & g[0]),
              (e[1] & f[1]) ^ (~e[1] & g[1]))
        t1 = _add2(*hh, *_big_sigma1(*e))
        t1 = _add2(*t1, *ch)
        t1 = _add2(*t1, kh, kl)
        t1 = _add2(*t1, wh, wl)
        maj = ((a[0] & b[0]) ^ (a[0] & c_[0]) ^ (b[0] & c_[0]),
               (a[1] & b[1]) ^ (a[1] & c_[1]) ^ (b[1] & c_[1]))
        t2 = _add2(*_big_sigma0(*a), *maj)
        e_n = _add2(*d, *t1)
        a_n = _add2(*t1, *t2)
        return (a_n, a, b, c_, e_n, e, f, g)

    def next_w(w_t, w_t1, w_t9, w_t14):
        # W[t+16] = σ1(W[t+14]) + W[t+9] + σ0(W[t+1]) + W[t]
        s0 = _small_sigma0(*w_t1)
        s1 = _small_sigma1(*w_t14)
        nw = _add2(*w_t, *w_t9)
        nw = _add2(*nw, *s0)
        return _add2(*nw, *s1)

    # carry = (vars8, 16-pair W ring) as TUPLES: rotating a tuple is
    # SSA renaming, so the scan body materializes no (16,2,B) ring
    # copy and no (8,2,B) state stack per round (the stacked-array
    # form measured ~60 ms of pure data movement per 16384-batch; a
    # fully unrolled emission sent XLA CPU compile past 9 minutes)
    def round_body(carry, kt):
        vars8, wv = carry
        wt = wv[0]
        out = round_math(vars8, wt[0], wt[1],
                         jnp.broadcast_to(kt[0], wt[0].shape),
                         jnp.broadcast_to(kt[1], wt[1].shape))
        nw = next_w(wt, wv[1], wv[9], wv[14])
        return (out, wv[1:] + (nw,)), None

    (st_pairs, _), _ = lax.scan(round_body, (tuple(state), tuple(w)),
                                jnp.asarray(_K_ARR), unroll=SHA_UNROLL)

    final = []
    for init, fin in zip(state, st_pairs):
        final.append(_add2(*init, *fin))

    # digest words (big-endian per word) -> little-endian byte positions
    limbs = []
    for vh, vl in final:
        for word in (vh, vl):
            for shift in (24, 16, 8, 0):
                limbs.append(((word >> shift) & 0xFF).astype(jnp.int32))
    return jnp.stack(limbs)                       # (64, B)


# --- mod-L reduction ---------------------------------------------------------

def _le_limbs(v: int, n: int) -> np.ndarray:
    return np.array([(v >> (8 * i)) & 0xFF for i in range(n)], dtype=np.int32)

# 256^(32+i) mod L for i in 0..31, as (32, 32) int32: row i = byte limbs
_POW_TAB = np.stack([_le_limbs(pow(256, 32 + i, L), 32) for i in range(32)])

# 8L, 4L, 2L, L as 33-limb arrays (8L has bit 255 set; 33 limbs keep the
# "add (2^264 - C)" conditional-subtract trick uniform)
_SUB_CONSTS = [_le_limbs((2**264 - m * L), 33) for m in (8, 4, 2, 1)]


def _seq_carry_ext(c):
    """Exact sequential byte carry over (32, B); returns (limbs, carry)."""
    outs = []
    carry = jnp.zeros_like(c[0])
    for i in range(32):
        t = c[i] + carry
        outs.append(t & 0xFF)
        carry = t >> 8
    return jnp.stack(outs), carry


def mod_l(d_limbs):
    """(64, B) int32 byte limbs (little-endian 512-bit value) -> (32, B)
    exact byte limbs of the value mod L.

    Fold 1: v = lo32 + Σ d[32+i]·(256^(32+i) mod L). Each accumulated
    limb < 255 + 32·255·255 < 2^21.1, so v < 2^269.1 and fits int32.
    Folds 2..n: sequential-carry to exact bytes + carry-out c < 2^14,
    then v = bytes + c0·(2^256 mod L) + c1·(2^264 mod L); each fold
    shrinks the value by ~3 bits (2^256 mod L ≈ 2^252.9), so after five
    the carry-out is 0 and v < 2^256 in exact byte limbs. Final: four
    conditional subtractions of 8L/4L/2L/L bring v < L (v/L < 16)."""
    tab = jnp.asarray(_POW_TAB)                   # (32, 32)
    lo = d_limbs[:32]
    hi = d_limbs[32:]                             # (32, B)
    acc = lo + jnp.einsum("ij,ib->jb", tab, hi)
    for _ in range(5):
        bytes_, carry = _seq_carry_ext(acc)
        c0 = carry & 0xFF
        c1 = carry >> 8
        acc = bytes_ + c0 * tab[0][:, None] + c1 * tab[1][:, None]
    v, carry = _seq_carry_ext(acc)                # carry == 0 now
    for const33 in _SUB_CONSTS:
        cst = jnp.asarray(const33[:, None])
        t = v + cst[:32]
        outs = []
        c = jnp.zeros_like(t[0])
        for i in range(32):
            s = t[i] + c
            outs.append(s & 0xFF)
            c = s >> 8
        c = c + cst[32]
        borrow_free = (c >> 8) > 0                # v + (2^264 - mL) >= 2^264
        tv = jnp.stack(outs)
        v = jnp.where(borrow_free, tv, v)
    return v


def k_mod_l_96(r_u8, a_u8, m_u8):
    """k = SHA512(R‖A‖M) mod L for 32-byte messages, fully on device.
    Returns (32, B) int32 exact byte limbs (the layout verify_kernel_full
    uses for scalars)."""
    return mod_l(sha512_96(r_u8, a_u8, m_u8))
