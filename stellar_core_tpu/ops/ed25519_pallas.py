"""Pallas ladder experiment — EXPERIMENTAL / interpret-mode only.

Status (measured on a real v5e): the production XLA formulation
(ed25519_kernel.py) runs the ladder at ~30% of VPU int32 peak with good
fusion; this Pallas formulation does NOT currently beat it —
(a) as written it trips a Mosaic layout bug (vector_extract_slice on
    sub-tile slices) when compiled for hardware, and
(b) Mosaic-safe rewrites of the row-broadcast (masked-sum reduction, or
    VMEM-scratch row loads) measured 17-30x slower per field mul than
    XLA's fused code, because the per-i sublane rolls and row broadcasts
    lower to many vector permutes.
Kept as the starting point for a future Mosaic-native attempt; correct
under interpret=True (differentially tested against the oracle).

Differences from the jnp path:
- field mul uses 32 static sublane rolls (pltpu.roll) with a x38 wrap
  mask instead of windowed updates into a 63-column buffer (unaligned
  sublane windows force relayouts; rolls lower to native shifts);
- scalar bits are extracted in-kernel from the byte limbs via a dynamic
  sublane row load (no precomputed (256,B) bit tensor in VMEM);
- the kernel returns the final point's loose (x, y) = (X/Z, Y/Z) limbs;
  canonicalization + sign/byte compare against R run in XLA (a handful
  of ops once per batch — off the hot loop);
- all (32,1) field constants ride in one (32,38) "constant bank" input
  (Pallas kernels cannot capture array constants).

Grid: 1-D over batch blocks of BLK lanes; each step's working set
(4 input blocks + tables + state) is ~2 MB VMEM at BLK=1024.

Semantics are identical to ed25519_kernel.verify_kernel (w=2 windowed ladder) — enforced
differentially in tests/test_tpu_verifier.py (interpret mode on CPU).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import fe8
from .ed25519_kernel import BASE_X, BASE_Y, BASE_T

BLK = 1024

# --- constant bank ---------------------------------------------------------
# cols 0..31: roll masks (col i: rows < i get 38 — the 2^256 ≡ 38 wrap)
# col 32: carry fold (38 at row 0), col 33: 16p sub bias, col 34: d
# cols 35..37: base point X, Y, T
_NCONST = 38
_CBANK = np.ones((32, _NCONST), dtype=np.int32)
for _i in range(32):
    _CBANK[:_i, _i] = 38
_CBANK[:, 32] = fe8._FOLD[:, 0]
_CBANK[:, 33] = fe8._BIAS16P[:, 0]
_CBANK[:, 34] = fe8.D[:, 0]
_CBANK[:, 35] = BASE_X[:, 0]
_CBANK[:, 36] = BASE_Y[:, 0]
_CBANK[:, 37] = BASE_T[:, 0]


class _FE:
    """Field helpers bound to the in-kernel constant bank."""

    def __init__(self, cbank):
        self.masks = [cbank[:, i:i + 1] for i in range(32)]
        self.fold = cbank[:, 32:33]
        self.bias = cbank[:, 33:34]
        self.d = cbank[:, 34:35]
        self.base = (cbank[:, 35:36], cbank[:, 36:37], cbank[:, 37:38])

    def carry(self, c):
        h = c >> 8
        l = c & 0xFF
        return l + pltpu.roll(h, shift=1, axis=0) * self.fold

    def mul(self, a, b):
        """Masked-roll schoolbook; inputs < 2^10, output < 2^9."""
        acc = a[0:1] * b               # i = 0: no wrap
        for i in range(1, 32):
            rb = pltpu.roll(b, shift=i, axis=0) * self.masks[i]
            acc = acc + a[i:i + 1] * rb
        for _ in range(5):
            acc = self.carry(acc)
        return acc

    def sq(self, a):
        return self.mul(a, a)

    def nsquare(self, a, n):
        return lax.fori_loop(0, n, lambda _, x: self.sq(x), a)

    def sub(self, a, b):
        return self.carry(self.carry(a + self.bias - b))

    def add_c(self, a, b):
        return self.carry(a + b)

    def ge_add(self, p, q):
        x1, y1, z1, t1 = p
        x2, y2, z2, t2 = q
        a = self.mul(self.sub(y1, x1), self.sub(y2, x2))
        b = self.mul(y1 + x1, y2 + x2)
        c = self.mul(self.mul(t1, t2), self.d)
        c = c + c
        d = self.mul(z1, z2)
        d = d + d
        e = self.sub(b, a)
        f = self.sub(d, c)
        g = self.add_c(d, c)
        h = b + a
        return (self.mul(e, f), self.mul(g, h),
                self.mul(f, g), self.mul(e, h))

    def invert(self, z):
        t0 = self.sq(z)
        t1 = self.nsquare(t0, 2)
        t1 = self.mul(z, t1)
        t0 = self.mul(t0, t1)
        t2 = self.sq(t0)
        t1 = self.mul(t1, t2)
        t2 = self.nsquare(t1, 5)
        t1 = self.mul(t2, t1)
        t2 = self.nsquare(t1, 10)
        t2 = self.mul(t2, t1)
        t3 = self.nsquare(t2, 20)
        t2 = self.mul(t3, t2)
        t2 = self.nsquare(t2, 10)
        t1 = self.mul(t2, t1)
        t2 = self.nsquare(t1, 50)
        t2 = self.mul(t2, t1)
        t3 = self.nsquare(t2, 100)
        t2 = self.mul(t3, t2)
        t2 = self.nsquare(t2, 50)
        t1 = self.mul(t2, t1)
        t1 = self.nsquare(t1, 5)
        return self.mul(t1, t0)


def _ladder_kernel(s_ref, k_ref, nax_ref, nay_ref, cb_ref, x_out, y_out):
    blk = s_ref.shape[1]
    fe = _FE(cb_ref[:])
    nax = nax_ref[:]
    nay = nay_ref[:]
    zero = jnp.zeros((32, blk), jnp.int32)
    # field element 1: limb 0 set (iota is generated in-kernel, so this
    # does not hit the no-captured-array-constants rule)
    one = (lax.broadcasted_iota(jnp.int32, (32, blk), 0) == 0)
    one = one.astype(jnp.int32)

    p_nega = (nax, nay, one, fe.mul(nax, nay))
    p_base = (zero + fe.base[0], zero + fe.base[1], one, zero + fe.base[2])
    p_both = fe.ge_add(p_base, p_nega)

    # Pallas TPU has no dynamic row indexing, so the scalar byte arrays
    # ride in the loop carry: each iteration reads the (static) top row
    # and the arrays roll up one limb every 8th iteration. 256 msb-first
    # iterations (bits 255..253 are zero for canonical scalars; garbage
    # bits of non-canonical S are masked by the host ok-flag anyway).
    def body(j, state):
        p, scur, kcur = state
        p = fe.ge_add(p, p)
        pos = 7 - (j % 8)
        bs = (scur[31:32, :] >> pos) & 1
        bk = (kcur[31:32, :] >> pos) & 1
        w1 = bs * (1 - bk)
        w2 = (1 - bs) * bk
        w3 = bs * bk
        w0 = 1 - w1 - w2 - w3
        q = (w1 * p_base[0] + w2 * p_nega[0] + w3 * p_both[0],
             w1 * p_base[1] + w2 * p_nega[1] + w3 * p_both[1] + w0 * one,
             w1 * p_base[2] + w2 * p_nega[2] + w3 * p_both[2] + w0 * one,
             w1 * p_base[3] + w2 * p_nega[3] + w3 * p_both[3])
        p = fe.ge_add(p, q)
        advance = (j % 8) == 7
        scur = jnp.where(advance, pltpu.roll(scur, shift=1, axis=0), scur)
        kcur = jnp.where(advance, pltpu.roll(kcur, shift=1, axis=0), kcur)
        return (p, scur, kcur)

    p0 = (zero, one, one, zero)
    (x, y, z, _), _, _ = lax.fori_loop(0, 256, body,
                                       (p0, s_ref[:], k_ref[:]))
    zi = fe.invert(z)
    x_out[:] = fe.mul(x, zi)
    y_out[:] = fe.mul(y, zi)


@functools.partial(jax.jit, static_argnames=("interpret", "blk"))
def ladder(s_bytes, k_bytes, neg_ax, neg_ay, interpret=False, blk=BLK):
    """(32,B) int32 byte limbs -> loose-limb affine (x, y) of
    [S]B + [k](-A). B must be a multiple of blk (or smaller than it)."""
    bsz = s_bytes.shape[1]
    if bsz < blk:
        blk = bsz
    grid = (bsz // blk,)
    spec = pl.BlockSpec((32, blk), lambda i: (0, i))
    cspec = pl.BlockSpec((32, _NCONST), lambda i: (0, 0))
    return pl.pallas_call(
        _ladder_kernel,
        grid=grid,
        in_specs=[spec] * 4 + [cspec],
        out_specs=[spec] * 2,
        out_shape=[jax.ShapeDtypeStruct((32, bsz), jnp.int32)] * 2,
        interpret=interpret,
    )(s_bytes, k_bytes, neg_ax, neg_ay, jnp.asarray(_CBANK))


def verify_kernel_pallas(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes,
                         interpret=False, blk=BLK):
    """Drop-in replacement for ed25519_kernel.verify_kernel using the
    Pallas ladder; canonicalization + compare stay in XLA."""
    x, y = ladder(s_bytes, k_bytes, neg_ax, neg_ay,
                  interpret=interpret, blk=blk)
    xa = fe8.to_canonical(x)
    ya = fe8.to_canonical(y)
    enc = ya.at[31].add((xa[0] & 1) << 7)
    return fe8.eq_canonical(enc, r_bytes)
