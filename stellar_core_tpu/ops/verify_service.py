"""Coalescing verification service: deadline micro-batching for the
live signature hot path.

The device batch verifier (ops/verifier.py) engages at the txset
validation and catchup-replay collection points, but the LIVE node
verifies flood-time tx admissions, SCP envelopes and StellarValue
signatures one at a time through PubKeyUtils.verify_sig. This module is
the dynamic-batching front-end that feeds the batch accelerator from
that stream of small independent requests — the Clipper / ORCA shape
from inference serving (PAPERS.md): deadline-bounded request coalescing
keeps device occupancy up without wrecking tail latency.

Mechanics: callers ``submit()`` (pub, sig, msg) tuples and get futures;
the pending queue drains into ONE ``verify_tuples_async`` dispatch when
the first of three triggers fires —

  - **batch_full** — pending count reached ``max_batch``;
  - **deadline**  — ``deadline_ms`` elapsed since the first pending
    submit (a VirtualTimer on the node clock, so virtual-time tests
    stay deterministic);
  - **demand**    — a caller blocked on ``result()`` of a pending
    future (the synchronous integration points: verify_envelope,
    verify_stellar_value_signature, batched flood admission).

Dispatch is double-buffered: a flush hands its tuples to the verifier's
async handle and returns immediately, so host prep + transfer of batch
i+1 overlaps device compute of batch i; collection happens when a
future is awaited (or at the deadline sweep).

Semantics contract — results are bit-identical to the sync path:

  - the device kernel's accept/reject is differentially pinned to the
    ed25519_ref oracle (tests/test_tpu_verifier.py), and the service's
    own parity suite pins service == PubKeyUtils.verify_sig
    (tests/test_verify_service.py);
  - ``submit`` probes a SERVICE-LOCAL result cache (same key
    derivation and capacity as the process-wide verify cache) and
    every batch result is written through BOTH caches, so flood-time
    verifies make close-time re-verification free. In a real
    deployment (one node per process) the local cache behaves exactly
    like probing the global one; in multi-node in-process simulations
    it keeps each node's coalescing honest — the global cache is
    shared across nodes there, and probing it would let one node's
    sync verifies short-circuit every other node's batches;
  - flushes below the verifier's device cutoff run the native
    per-signature path (VERIFY_DEVICE_MIN_BATCH, ops/verifier.py);
  - any device failure — at dispatch or at collection — falls back to
    native per-signature verify for that flush (PR 2 chaos contract;
    seam: ``ops.verify_service.flush``).

Observability: ``crypto.verify_service.occupancy`` histogram (tuples
per flush), ``crypto.verify_service.queue-wait`` timer (submit →
dispatch), ``crypto.verify_service.flush.<reason>`` counters,
``crypto.verify_service.fallback`` counter, and a
``crypto.verifyService.flush`` perf zone (batch/reason span args) that
rides the flight recorder like every other zone.

Threading: the node is single-logical-threaded (VirtualClock crank
loop); the internal lock only guards against admin-thread probes and
keeps the pending/inflight structures consistent if a future is
resolved from a different thread. Device collection happens outside
the lock.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional, Sequence, Tuple

from ..crypto.keys import (VERIFY_CACHE_SIZE, PublicKey,
                           seed_verify_cache_by_key, verify_cache_key,
                           verify_sig_uncached)
from ..util import chaos, tracing
from ..util.cache import RandomEvictionCache
from ..util.logging import get_logger

log = get_logger("Herder")

# flush triggers (metric suffixes: crypto.verify_service.flush.<reason>)
FLUSH_REASONS = ("batch_full", "deadline", "demand", "drain")

DEFAULT_MAX_BATCH = 256
DEFAULT_DEADLINE_MS = 2.0


class VerifyFuture:
    """Handle for one submitted (pub, sig, msg) verify. ``result()``
    blocks (forcing a demand flush + collection if needed) and returns
    the bool; ``done()`` is a non-blocking probe."""

    __slots__ = ("_service", "_flush", "_value")

    def __init__(self, service: Optional["VerifyService"] = None):
        self._service = service
        self._flush: Optional["_Flush"] = None   # set at dispatch
        self._value: Optional[bool] = None

    def done(self) -> bool:
        return self._value is not None

    def result(self) -> bool:
        if self._value is None:
            self._service._resolve(self)
        return self._value


class _Flush:
    """One dispatched batch: the verifier's collect handle plus the
    tuples/keys/futures it will resolve. ``collect`` is None when the
    dispatch itself failed — the batch resolves through the native
    fallback at collection time (outside the service lock)."""

    __slots__ = ("collect", "tuples", "keys", "futures")

    def __init__(self, collect, tuples, keys, futures):
        self.collect = collect
        self.tuples = tuples
        self.keys = keys
        self.futures = futures


class VerifyService:
    def __init__(self, verifier, clock=None, metrics=None, perf=None,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 deadline_ms: float = DEFAULT_DEADLINE_MS):
        self._verifier = verifier
        self._clock = clock
        self._max_batch = max(1, int(max_batch))
        self._deadline_s = max(0.0, float(deadline_ms)) / 1000.0
        if perf is None:
            from ..util.perf import default_registry
            perf = default_registry
        self.perf = perf
        if metrics is None:
            from ..util.metrics import MetricsRegistry
            metrics = MetricsRegistry()
        self._occupancy = metrics.histogram(
            "crypto", "verify_service", "occupancy")
        self._queue_wait = metrics.timer(
            "crypto", "verify_service", "queue-wait")
        self._submitted = metrics.meter(
            "crypto", "verify_service", "submitted")
        self._fallbacks = metrics.counter(
            "crypto", "verify_service", "fallback")
        self._reasons = {
            r: metrics.counter("crypto", "verify_service", "flush", r)
            for r in FLUSH_REASONS}
        self._lock = threading.Lock()
        self._pending_tuples: List[Tuple[bytes, bytes, bytes]] = []
        self._pending_keys: List[bytes] = []
        self._pending_futures: List[VerifyFuture] = []
        self._pending_times: List[float] = []
        self._inflight: deque = deque()
        self._timer = None
        self._timer_armed = False
        self._abandoned = False
        # node-local view of the verify cache (see module docstring)
        self._local_cache: RandomEvictionCache = RandomEvictionCache(
            VERIFY_CACHE_SIZE)

    # ------------------------------------------------------------ submit --
    def submit(self, pub, sig: bytes, msg: bytes,
               use_cache: bool = True) -> VerifyFuture:
        """Queue one verify; returns a future. Malformed keys/signatures
        resolve False immediately (mirroring verify_sig); cache hits
        resolve without queueing."""
        raw = pub.raw if isinstance(pub, PublicKey) else bytes(pub)
        sig = bytes(sig)
        msg = bytes(msg)
        fut = VerifyFuture(self)
        if len(raw) != 32 or len(sig) != 64:
            fut._value = False
            return fut
        key = verify_cache_key(raw, sig, msg)
        if use_cache:
            hit = self._local_cache.maybe_get(key)
            if hit is not None:
                fut._value = hit
                return fut
        self._submitted.mark()
        with self._lock:
            if self._abandoned:
                # the node is dead: resolve immediately (False, no
                # cache seed) rather than queue work nobody will flush
                fut._value = False
                return fut
            self._pending_tuples.append((raw, sig, msg))
            self._pending_keys.append(key)
            self._pending_futures.append(fut)
            self._pending_times.append(time.perf_counter())
            if len(self._pending_tuples) >= self._max_batch:
                self._flush_locked("batch_full")
            else:
                self._arm_timer_locked()
        return fut

    def submit_many(self, items: Sequence[Tuple[bytes, bytes, bytes]]
                    ) -> List[VerifyFuture]:
        """Queue a burst. Crossing ``max_batch`` dispatches mid-loop, so
        a large burst pipelines: while the caller awaits (or keeps
        submitting) chunk i+1, chunk i is already on the device."""
        return [self.submit(p, s, m) for p, s, m in items]

    def verify(self, pub, sig: bytes, msg: bytes) -> bool:
        """Synchronous verify through the service: coalesces with
        whatever else is pending, then demand-flushes."""
        return self.submit(pub, sig, msg).result()

    # -------------------------------------------------------------- knobs --
    def set_knobs(self, max_batch: Optional[int] = None,
                  deadline_ms: Optional[float] = None) -> None:
        """Live re-tune from the adaptive controller
        (ops/controller.py). Mutable-safe: swapped under the service
        lock, so a concurrent submit sees either the old or the new
        value, never a torn pair. Shrinking ``max_batch`` below the
        current backlog dispatches it immediately — the tighter knob
        takes effect now, not one batch later. A shortened deadline
        applies from the next arm (the in-flight timer keeps the
        deadline the batch was promised)."""
        with self._lock:
            if max_batch is not None:
                self._max_batch = max(1, int(max_batch))
            if deadline_ms is not None:
                self._deadline_s = max(0.0, float(deadline_ms)) / 1000.0
            if len(self._pending_tuples) >= self._max_batch:
                self._flush_locked("batch_full")

    def knobs(self) -> dict:
        with self._lock:
            return {"max_batch": self._max_batch,
                    "deadline_ms": round(self._deadline_s * 1000, 4)}

    # ------------------------------------------------------------- flush --
    def flush(self, reason: str = "drain") -> None:
        with self._lock:
            self._flush_locked(reason)

    def _arm_timer_locked(self) -> None:
        if self._clock is None or self._timer_armed or self._abandoned:
            return
        from ..util.timer import VirtualTimer
        if self._timer is None:
            self._timer = VirtualTimer(self._clock)
        self._timer.expires_from_now(self._deadline_s)
        self._timer.async_wait(self._on_deadline)
        self._timer_armed = True

    def _on_deadline(self) -> None:
        with self._lock:
            self._timer_armed = False
            if self._abandoned:
                return
            self._flush_locked("deadline")
        # nobody is awaiting these futures (sync callers demand-flush),
        # so collect here: results resolve and write through the cache
        self._collect_all()

    def _flush_locked(self, reason: str) -> None:
        """Dispatch everything pending as one batch. Lock held; device
        collection does NOT happen here (double-buffering: the handle is
        queued on ``_inflight`` and collected when awaited)."""
        tuples = self._pending_tuples
        keys = self._pending_keys
        futures = self._pending_futures
        times = self._pending_times
        if not tuples:
            return
        self._pending_tuples = []
        self._pending_keys = []
        self._pending_futures = []
        self._pending_times = []
        if self._timer_armed:
            self._timer.cancel()
            self._timer_armed = False
        n = len(tuples)
        self._occupancy.update(n)
        self._reasons.get(reason, self._reasons["drain"]).inc()
        now = time.perf_counter()
        for t0 in times:
            self._queue_wait.update(now - t0)
        targs = None
        if tracing.ENABLED:
            targs = {"batch": n, "reason": reason}
        collect = None
        try:
            with self.perf.zone("crypto.verifyService.flush",
                                targs=targs):
                try:
                    if chaos.ENABLED:
                        # service fault seam (PR 2 contract): an
                        # injected io_error raises before any dispatch
                        # — this flush falls back to native verify
                        chaos.point("ops.verify_service.flush", n=n,
                                    reason=reason)
                    collect = self._verifier.verify_tuples_async(tuples)
                except Exception:
                    # don't run the native fallback here: _flush_locked
                    # is called with the lock held, and a max_batch
                    # fallback is real work — mark the flush failed
                    # (collect=None) and resolve it at collection time,
                    # outside the lock
                    log.debug("verify service: dispatch failed "
                              "(batch=%d)", n, exc_info=True)
                    collect = None
        finally:
            # register the flush even when a SimulatedCrash
            # (BaseException) unwinds out of the chaos seam: the
            # futures must stay reachable so abandon() on the crash
            # path resolves them — a future must never be left unset
            fl = _Flush(collect, tuples, keys, futures)
            for f in futures:
                f._flush = fl
            self._inflight.append(fl)

    # ----------------------------------------------------------- collect --
    def _resolve(self, fut: VerifyFuture) -> None:
        """Block until `fut` has a value: demand-flush if it is still
        pending, then collect inflight batches in dispatch order (older
        batches finished first on the device anyway)."""
        with self._lock:
            if fut._value is None and fut._flush is None:
                self._flush_locked("demand")
        while fut._value is None:
            with self._lock:
                fl = self._inflight.popleft() if self._inflight else None
            if fl is None:
                if fut._value is None:   # pragma: no cover — invariant
                    raise RuntimeError("verify future lost its batch")
                return
            self._collect(fl)

    def _collect(self, fl: _Flush) -> None:
        if fl.collect is None:             # dispatch already failed
            self._fallback_resolve(fl)
            return
        try:
            results = fl.collect()
        except Exception:
            self._fallback_resolve(fl)
            return
        self._resolve_results(fl, results)

    def _resolve_results(self, fl: _Flush, results) -> None:
        """Resolve futures + write-through: the process-wide cache (so
        close-time verify_sig hits) AND the node-local one (so repeat
        submits resolve without queueing). Keys were derived once at
        submit."""
        for key, f, ok in zip(fl.keys, fl.futures, results):
            ok = bool(ok)
            f._value = ok
            f._flush = None
            seed_verify_cache_by_key(key, ok)
            self._local_cache.put(key, ok)

    def _collect_all(self) -> None:
        while True:
            with self._lock:
                fl = self._inflight.popleft() if self._inflight else None
            if fl is None:
                return
            self._collect(fl)

    def _fallback_resolve(self, fl: _Flush) -> None:
        """Device failure: resolve this batch through the native
        per-signature path — identical accept/reject, the chaos
        convergence scenario's contract. Runs outside the service lock
        (real per-signature work). A persistently-failing device (the
        chaos soak's always-on fault) logs once at warning, then debug
        — the fallback counter carries the tally."""
        self._fallbacks.inc()
        level = log.warning if self._fallbacks.count == 1 else log.debug
        level("verify service: device flush failed; falling back "
              "to native per-signature verify (batch=%d)",
              len(fl.tuples))
        self._resolve_results(
            fl, [verify_sig_uncached(p, s, m) for p, s, m in fl.tuples])

    # ---------------------------------------------------------- lifecycle --
    def drain(self) -> None:
        """Flush + collect everything (graceful shutdown, tests)."""
        self.flush("drain")
        self._collect_all()

    def abandon(self) -> None:
        """Hard stop: cancel the deadline timer and resolve EVERY
        pending and in-flight future to False — without touching the
        device or the caches (abandoned ≠ invalid; nothing is seeded).
        A crashed node loses in-flight verifies exactly like a real
        kill, but a caller blocked on ``result()`` from another thread
        must unblock rather than hang forever (Herder.shutdown routes
        here, including on the chaos crash path)."""
        with self._lock:
            self._abandoned = True
            if self._timer_armed:
                self._timer.cancel()
                self._timer_armed = False
            orphans = list(self._pending_futures)
            self._pending_tuples = []
            self._pending_keys = []
            self._pending_futures = []
            self._pending_times = []
            inflight, self._inflight = list(self._inflight), deque()
            for fl in inflight:
                orphans.extend(fl.futures)
            # resolve while STILL holding the lock: a result() caller
            # blocked on the lock must wake to a resolved future — if
            # it won the race instead, it would pop from the emptied
            # _inflight and die on the lost-its-batch invariant
            for f in orphans:
                if f._value is None:
                    f._value = False
                    f._flush = None

    # -------------------------------------------------------------- stats --
    def queue_depth(self) -> dict:
        """Live backlog snapshot for the telemetry sampler (Clipper's
        queue-occupancy signal, read per sample): tuples awaiting
        dispatch and tuples dispatched-but-uncollected."""
        with self._lock:
            return {"pending": len(self._pending_tuples),
                    "inflight": sum(len(fl.tuples)
                                    for fl in self._inflight)}

    def stats(self) -> dict:
        """Service counters for self-check / bench artifacts."""
        occ = self._occupancy.to_json()
        qw = self._queue_wait.to_json()
        return {
            "submitted": self._submitted.count,
            "flushes": occ["count"],
            "occupancy_mean": round(occ["mean"], 3),
            "occupancy_p50": occ["median"],
            "occupancy_p99": occ["99%"],
            "queue_wait_p50_ms": round(qw["median"] * 1000, 3),
            "queue_wait_p99_ms": round(qw["99%"] * 1000, 3),
            "flush_reasons": {r: c.count
                              for r, c in self._reasons.items()},
            "fallbacks": self._fallbacks.count,
        }
