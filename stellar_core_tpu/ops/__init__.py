"""TPU-native compute kernels (JAX/XLA).

The flagship component: batch Ed25519 signature verification on TPU,
slotted behind the crypto verifier abstraction (reference seam:
crypto/SecretKey.cpp:427-460 PubKeyUtils::verifySig and
transactions/SignatureChecker.cpp). See SURVEY.md §7.
"""
