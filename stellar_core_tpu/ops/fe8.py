"""GF(2^255-19) field arithmetic for TPU, radix 2^8, int32 limbs.

Design notes (TPU-first, not a port of any CPU bignum library):

- A field element is an int32 array of shape (32, B): 32 little-endian
  base-256 limbs on the sublane axis, B independent batch elements on the
  lane axis. With B >= 128 every vector op fills full 8x128 VPU tiles, and
  the batch dimension shards cleanly across a device mesh (pure data
  parallelism — signatures have no cross-element dependency).

- Radix 2^8 is chosen so schoolbook products and column sums stay inside
  int32 *without* 64-bit accumulators (TPUs have no native wide-multiply):
  with the loose-limb invariants below, every intermediate is < 2^31.

- Limb-bound contract (round-4 lazy schedule; executable proof in
  tests/test_fe8_bounds.py, narrative in docs/LIMB_WIDTHS.md):
    * rolled (TPU) mul/sq outputs: limbs <= 711 (3 passes; a stable
      fixpoint); scatter (CPU) outputs: < 2^9 (4 passes)
    * sub outputs < 2^9; sub1 outputs <= 1053 (1 pass — only for
      results that feed a multiply or a sub minuend)
    * add_c outputs <= 445 when fed two mul outputs
    * mul/sq accept inputs < MUL_INPUT_BOUND = 1349 (the worst folded
      column is 1179 * B^2, int32-safe up to B = 1349)
    * sub/sub1 subtrahends must stay under the smallest 16p bias limb
      (2033, limb 31) — every in-tree subtrahend is <= 1424

- Carry propagation is a *parallel* pass (shift-by-one-limb via roll on
  the sublane axis, with the wrap-around limb folded by x38 since
  2^256 ≡ 38 mod p) — no sequential 32-step ripple in the hot loop.
  Exact sequential carries are only used in `to_canonical` (once per
  point compression, off the hot loop).

Matches the semantic oracle stellar_core_tpu/crypto/ed25519_ref.py.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

P = 2**255 - 19

# wrap-around fold weight: limb 0 receives carry-out of limb 31 times 38
_FOLD = np.ones((32, 1), dtype=np.int32)
_FOLD[0, 0] = 38

# 16*p in base-256 limbs: per-limb bias >= 1023 everywhere, so
# a + BIAS16P - b is non-negative for any b with limbs < 2^10.
_BIAS16P = np.full((32, 1), 16 * 0xFF, dtype=np.int32)
_BIAS16P[0, 0] = 16 * 0xED
_BIAS16P[31, 0] = 16 * 0x7F


def const(v: int) -> np.ndarray:
    """Python int -> (32,1) canonical limb column (broadcasts over batch)."""
    v %= P
    return np.array([(v >> (8 * i)) & 0xFF for i in range(32)],
                    dtype=np.int32).reshape(32, 1)


ZERO = const(0)
ONE = const(1)
# d = -121665/121666 mod p (twisted Edwards constant)
D = const((-121665 * pow(121666, P - 2, P)) % P)


def from_bytes(b):
    """(32,B) uint8 limbs -> int32 field element (values are the limbs)."""
    return b.astype(jnp.int32)


def carry_pass(c):
    """One parallel carry pass; wrap-around limb folds with weight 38."""
    h = c >> 8
    l = c & 0xFF
    h = jnp.roll(h, 1, axis=0) * _FOLD
    return l + h


def add(a, b):
    """Plain limb add — output limbs < 2^10 when inputs are reduced-loose."""
    return a + b


def add_c(a, b):
    """Add + one carry pass — output < 560, safe wherever < 2^10 is needed
    even when inputs are already sums."""
    return carry_pass(a + b)


def sub(a, b):
    """a - b mod p; b limbs must be < 2^10. Output reduced-loose (< 2^9)."""
    c = a + _BIAS16P - b
    return carry_pass(carry_pass(c))


def sub1(a, b):
    """a - b mod p with a SINGLE carry pass — for results consumed as
    mul/sq inputs or as another sub's minuend, which tolerate limbs up
    to MUL_INPUT_BOUND (1349). Bounds (tests/test_fe8_bounds.py):
    a limbs <= 1424, b limbs per-limb under the 16p bias vector (its
    smallest limb is 2033 at index 31; in-tree subtrahends stay
    <= 1424) give outputs <= 1053. The group-law hot path uses this for
    every difference that feeds a multiply, saving one full-width pass
    per sub versus `sub`."""
    c = a + _BIAS16P - b
    return carry_pass(c)


# mul weight matrix: W[i, k] = 38 where column k received a wrapped
# product (j = k - i + 32, i.e. k < i), else 1 — the 2^256 ≡ 38 fold
# applied inline so no 63-column accumulator ever materializes
_MULW = np.ones((32, 32, 1), dtype=np.int32)
for _i in range(32):
    _MULW[_i, :_i, 0] = 38
del _i


def _use_rolled() -> bool:
    """Pick the mul formulation for the backend this trace targets.

    The rolled-FMA form is the TPU shape (zero dynamic-update-slices —
    docs/KERNEL_PROFILE.md measured the scatter-add form spending 70%
    of ladder time in data movement). The XLA *CPU* backend is the
    opposite: it compiles the 32-distinct-roll scan body pathologically
    slowly (minutes per bucket shape vs seconds for the scatter-add
    form), and tests/dryrun always run on the CPU mesh. Decided at
    trace time, so each backend caches its own formulation."""
    import jax
    return jax.default_backend() == "tpu"


def _mul_rolled(a, b):
    """32x32 product with the 2^256≡38 fold inline, THREE carry passes.

    Formulated as 32 fused vector FMAs over rolled copies of b:
        c[k] = sum_i a_i * b_{(k-i) mod 32} * W[i,k]
    (W applies x38 to wrapped columns). This shape matters on TPU: the
    63-column scatter-add version (`c.at[i:i+32].add(...)`) lowered to
    32 dynamic-update-slices PER MULTIPLY and the device trace showed
    70% of ladder time in pure data movement (docs/KERNEL_PROFILE.md);
    rolls + multiply-adds fuse into one elementwise loop instead.

    Carry schedule (round 4): with MUL_INPUT_BOUND = 1349 inputs every
    column stays < 2^31, and interval propagation (see
    tests/test_fe8_bounds.py and docs/LIMB_WIDTHS.md) shows THREE
    passes already bring every limb under 712 — itself a legal mul
    input — so the historical fourth pass was pure waste. The bound
    chain is a stable fixpoint: 711-bounded inputs produce 711-bounded
    outputs."""
    acc = (_MULW[0] * a[0]) * b
    for i in range(1, 32):
        acc = acc + (_MULW[i] * a[i]) * jnp.roll(b, i, axis=0)
    for _ in range(3):
        acc = carry_pass(acc)
    return acc


def _mul_scatter(a, b, bsz):
    """Schoolbook 32x32 -> 63-column product, 2^256≡38 fold, 4 carry
    passes — the CPU-backend formulation (see _use_rolled)."""
    c = jnp.zeros((63, bsz), jnp.int32)
    for i in range(32):
        c = c.at[i:i + 32].add(a[i] * b)
    lo = c[:32]
    lo = lo.at[:31].add(38 * c[32:])
    for _ in range(4):
        lo = carry_pass(lo)
    return lo


def mul(a, b):
    """Field multiply. Inputs: limbs < MUL_INPUT_BOUND (1349). Output:
    rolled (TPU) <= 711, scatter (CPU) < 2^9. Two formulations with
    identical column sums (differential-tested against each other and
    the pure-python oracle); backend picks."""
    bsz = max(a.shape[-1], b.shape[-1])
    a = jnp.broadcast_to(a, (32, bsz))
    b = jnp.broadcast_to(b, (32, bsz))
    if _use_rolled():
        return _mul_rolled(a, b)
    return _mul_scatter(a, b, bsz)


def _sq_scatter(a, bsz):
    """Specialized squaring for the CPU backend: symmetric schoolbook —
    528 limb products instead of 1024. Doubling the accumulated
    off-diagonal half-columns reconstructs exactly the full schoolbook
    column sums, so the bounds contract is identical to mul (columns
    < 32*(2^10-1)^2 < 2^25)."""
    c = jnp.zeros((63, bsz), jnp.int32)
    for i in range(32):
        # off-diagonal partial row: a_i * a_j for j > i
        if i + 1 < 32:
            c = c.at[2 * i + 1:i + 32].add(a[i] * a[i + 1:])
    c = c + c                                    # double off-diagonals
    for i in range(32):
        c = c.at[2 * i].add(a[i] * a[i])         # diagonal
    lo = c[:32]
    lo = lo.at[:31].add(38 * c[32:])
    for _ in range(4):
        lo = carry_pass(lo)
    return lo


def sq(a):
    """Squaring. On TPU: the rolled-FMA mul with both operands equal (a
    528-product symmetric schoolbook only pays off when products are
    scalar ops; in vector form both variants are 32 (32,B) FMAs, and
    its scatter-adds were the data-movement bottleneck). On CPU: the
    symmetric scatter form (half the products, and HLO-identical to
    prior rounds so persistent compile caches stay warm)."""
    if _use_rolled():
        return mul(a, a)
    bsz = a.shape[-1]
    a = jnp.broadcast_to(a, (32, bsz))
    return _sq_scatter(a, bsz)


def nsquare(a, n: int):
    """a^(2^n) via fori_loop (keeps the trace small for long chains)."""
    return lax.fori_loop(0, n, lambda _, x: sq(x), a)


def invert(z):
    """z^(p-2) — the standard curve25519 square-and-multiply chain."""
    t0 = sq(z)                    # 2
    t1 = nsquare(t0, 2)           # 8
    t1 = mul(z, t1)               # 9
    t0 = mul(t0, t1)              # 11
    t2 = sq(t0)                   # 22
    t1 = mul(t1, t2)              # 31 = 2^5-1
    t2 = nsquare(t1, 5)
    t1 = mul(t2, t1)              # 2^10-1
    t2 = nsquare(t1, 10)
    t2 = mul(t2, t1)              # 2^20-1
    t3 = nsquare(t2, 20)
    t2 = mul(t3, t2)              # 2^40-1
    t2 = nsquare(t2, 10)
    t1 = mul(t2, t1)              # 2^50-1
    t2 = nsquare(t1, 50)
    t2 = mul(t2, t1)              # 2^100-1
    t3 = nsquare(t2, 100)
    t2 = mul(t3, t2)              # 2^200-1
    t2 = nsquare(t2, 50)
    t1 = mul(t2, t1)              # 2^250-1
    t1 = nsquare(t1, 5)           # 2^255-2^5
    return mul(t1, t0)            # 2^255-21 = p-2


def _seq_carry(c):
    """Exact sequential base-256 carry; returns (limbs in [0,256), carry)."""
    outs = []
    carry = jnp.zeros_like(c[0])
    for i in range(32):
        t = c[i] + carry
        outs.append(t & 0xFF)
        carry = t >> 8
    return jnp.stack(outs), carry


def to_canonical(c):
    """Fully reduce to the unique representative in [0, p), exact byte
    limbs. Off-hot-loop (used once per compression)."""
    c = carry_pass(carry_pass(c))
    c, top = _seq_carry(c)
    c = c.at[0].add(38 * top)          # 2^256 ≡ 38
    c, top = _seq_carry(c)             # top == 0 now (value < 2^256)
    # fold bit 255 twice: 2^255 ≡ 19
    for _ in range(2):
        b = c[31] >> 7
        c = c.at[31].set(c[31] & 0x7F)
        c = c.at[0].add(19 * b)
        c, _ = _seq_carry(c)
    # value now < 2p: conditionally subtract p once.
    # t = value + 19: bit 255 of t set  <=>  value >= p
    t = c.at[0].add(19)
    t, _ = _seq_carry(t)
    geq = t[31] >> 7                    # 0/1
    t = t.at[31].set(t[31] & 0x7F)      # t - 2^255 = value - p
    return jnp.where(geq.astype(bool), t, c)


def is_zero_canonical(c):
    """(B,) bool — all-limb zero test on a to_canonical() output."""
    return jnp.all(c == 0, axis=0)


def eq_canonical(a, b):
    """(B,) bool — limbwise equality of two canonical encodings."""
    return jnp.all(a == b, axis=0)
