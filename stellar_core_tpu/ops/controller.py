"""SLO-driven adaptive control plane: close the telemetry loop.

PR 10 gave every node senses — the bounded telemetry time-series
(util/timeseries.py) and the declarative SLO watchdog (ops/slo.py) —
but the knobs they watch stayed hand-picked constants. This module is
the actuator: an ``AdaptiveController`` riding a recurring
``VirtualTimer`` on the APP clock (the exact ``TelemetrySampler``
discipline, so in-process simulations tick on the VirtualClock and
``run`` nodes on the wall clock) that each tick reads the newest
telemetry sample plus the watchdog's verdicts and moves three things:

**(a) AIMD batch-knob search** (Clipper, NSDI '17 — batch parameters
should be searched continuously from measured latency, not frozen at
config time), over the verify service's measured occupancy and
queue-wait p99:

  - queue-wait p99 above ``CONTROLLER_QUEUE_WAIT_TARGET_MS`` (or a
    pending backlog past 4x the batch ceiling) → **multiplicative
    decrease** of ``VERIFY_BATCH_DEADLINE_MS`` (dispatch sooner; the
    deadline is the latency knob) and of ``VERIFY_MAX_BATCH`` when the
    backlog itself is the signal;
  - queue-wait comfortably under target with batches filling
    (occupancy p99 ≥ 0.8 × max batch) → **additive increase** of
    ``VERIFY_MAX_BATCH`` (probe for more coalescing);
  - queue-wait under target but flushes too small to engage the device
    (occupancy p99 below the min-batch bypass) → stretch the deadline
    (× ``CONTROLLER_DEADLINE_GROW``) so batches fill toward device
    profitability;
  - ``VERIFY_DEVICE_MIN_BATCH`` follows the measured dispatch shape
    (judged only when new dispatches landed since the last tick — the
    accounting is cumulative): pad-waste ratio past 0.6 while
    dispatch batch p99 sits under 2× the cutoff raises it (tiny
    batches burn pow2 padding — keep them on the host); dispatch
    batch p99 past 4× the cutoff lowers it back toward the device.

**(b) graduated admission shedding** (The Tail at Scale, CACM '13: an
overloaded replica sheds to a good-enough answer now instead of
letting queues melt the p99): tx-submit and flood-admission drop
probabilities ramp from the SLO watchdog's WARN→BREACH verdicts on
``close_p99`` and ``tx_e2e_p99`` — WARN ramps the tx-submit gate
(backpressure local submitters first), BREACH ramps the flood gate
too; OK decays both toward zero. On top of the ladder sits the
**surge gate**: the controller learns the node's per-tx close cost
from the series (Δ applied txs / Δ ledgers vs the windowed close
median) and when the pending queue exceeds what would close inside
``SLO_CLOSE_P99_MS × CONTROLLER_BACKLOG_FACTOR`` it slams the
tx-submit shed to ``CONTROLLER_SHED_MAX`` — a million users arriving
in one burst are turned away BEFORE the node pays device time and
close latency for work it would drop anyway. Shedding engages at the
admission seams (herder tx submit, overlay flood admission), upstream
of the batched verify dispatch.

**(c) breaker interplay**: while the device breaker aggregate
(ops/backend_supervisor.py) is not CLOSED — which since the
per-device breaker array (PR 13) means the WHOLE mesh is unavailable
— the controller freezes batch-knob tuning: AIMD feedback measured
against the native fallback path would mis-train the device knobs.
The shed ladder keeps running either way: a degraded node needs
admission control more, not less. A PARTIALLY degraded mesh (sample
``mesh.active < mesh.devices``) does NOT freeze tuning — the batch
path is still the device path — but it scales the learned close
capacity (and with it the surge gate) by the surviving-device
fraction, read from the SAMPLE for replay determinism: a 7/8 mesh is
a 7/8 node until the canary probes regrow it.

Determinism contract: every decision reads the telemetry sample's own
``t`` (and the watchdog state derived from those samples), never the
wall clock, so identical seeded schedules on the VirtualClock replay
byte-identical decision logs; the only RNG (per-frame shed rolls) is
seeded from ``config.jitter_seed()`` and never feeds tick decisions.

Observability: ``controller.*`` counters/gauges (metrics route +
Prometheus), flight-recorder instants on every knob/shed change, a
bounded decision log, and the ``controller`` admin route
(``?action=freeze|reset`` behind ``ALLOW_CHAOS_INJECTION``) that
``simulation/cluster.py`` polls into CLUSTER artifacts.
``clearmetrics`` routes through ``reset()``: learned knob values,
shed probabilities and the decision log all drop and the controller
epoch rotates — exactly the PR 10 time-series contract, so
back-to-back bench legs in one process cannot leak tuning.
"""

from __future__ import annotations

import random
from collections import deque
from typing import Optional

from ..util import tracing
from ..util.logging import get_logger

log = get_logger("default")

# knob bounds: the AIMD search must stay inside the envelope the
# verify service / device kernels were validated over
MAX_BATCH_FLOOR, MAX_BATCH_CEIL = 16, 4096
DEADLINE_FLOOR_MS, DEADLINE_CEIL_MS = 0.25, 64.0
MIN_BATCH_FLOOR, MIN_BATCH_CEIL = 1, 1024

DECISION_LOG_CAPACITY = 256


def _clamp(v, lo, hi):
    return max(lo, min(hi, v))


class AdaptiveController:
    """The closed loop: telemetry sample in, knob moves + shed levels
    out. One per Application, wired beside the sampler/watchdog."""

    def __init__(self, app, metrics=None, recorder=None):
        self._app = app
        cfg = app.config
        self.period_s = max(0.0, float(cfg.CONTROLLER_TICK_PERIOD))
        self._queue_wait_target_ms = float(
            cfg.CONTROLLER_QUEUE_WAIT_TARGET_MS)
        self._aimd_increase = int(cfg.CONTROLLER_AIMD_INCREASE)
        self._aimd_decrease = float(cfg.CONTROLLER_AIMD_DECREASE)
        self._deadline_grow = float(cfg.CONTROLLER_DEADLINE_GROW)
        self._shed_step = float(cfg.CONTROLLER_SHED_STEP)
        self._shed_decay = float(cfg.CONTROLLER_SHED_DECAY)
        self._shed_max = float(cfg.CONTROLLER_SHED_MAX)
        self._backlog_factor = float(cfg.CONTROLLER_BACKLOG_FACTOR)
        # config-anchored knob values: reset() restores these
        self._cfg_knobs = {
            "max_batch": int(cfg.VERIFY_MAX_BATCH),
            "deadline_ms": float(cfg.VERIFY_BATCH_DEADLINE_MS),
            "min_batch": int(cfg.VERIFY_DEVICE_MIN_BATCH),
        }
        self.knobs = dict(self._cfg_knobs)
        self.shed_tx = 0.0
        self.shed_flood = 0.0
        # read-tier shed: ramps FIRST and FASTEST — reads degrade
        # before the write path (ledger close) ever sheds
        self.shed_read = 0.0
        self.frozen = False          # admin freeze: pin everything
        self.epoch = 1
        self.ticks = 0
        self.decisions: deque = deque(maxlen=DECISION_LOG_CAPACITY)
        self._recorder = recorder
        self._timer = None
        self._stopped = False
        # scrape bookkeeping: a tick re-run against the same sample
        # must not double-apply a ramp
        self._last_sample_key = None
        self._prev_ledger: Optional[int] = None
        self._prev_tx_applied: Optional[int] = None
        # None = resync on next tick: the dispatch histogram is
        # cumulative, and judging its lifetime ratios without a
        # baseline would move knobs on stale evidence
        self._prev_dispatch_count: Optional[int] = None
        self._cost_ms_per_tx: Optional[float] = None
        self._safe_txset = 0
        # surviving-device fraction of the verify mesh, read from each
        # sample (1.0 = full mesh / no mesh): scales the surge gate's
        # capacity estimate while the mesh is shrunk
        self._mesh_frac = 1.0
        # per-frame shed rolls ride their own seeded stream so the
        # admission volume can never perturb tick decisions
        self._shed_rng = random.Random(cfg.jitter_seed() ^ 0xC0117801)
        if metrics is None:
            from ..util.metrics import MetricsRegistry
            metrics = MetricsRegistry()
        self._metrics = metrics
        self._tick_counter = metrics.counter("controller", "tick")
        self._tune_counters = {
            d: metrics.counter("controller", "tune", d)
            for d in ("up", "down")}
        self._freeze_counter = metrics.counter(
            "controller", "freeze", "tick")
        self._shed_change_counter = metrics.counter(
            "controller", "shed", "change")
        self._shed_dropped = {
            k: metrics.counter("controller", "shed", k, "dropped")
            for k in ("tx", "flood", "read")}
        # level gauges (counter-as-gauge, the breaker-state idiom):
        # permille so Prometheus integer counters carry the fraction
        self._shed_gauges = {
            k: metrics.counter("controller", "shed", k, "permille")
            for k in ("tx", "flood", "read")}
        self._knob_gauges = {
            k: metrics.counter("controller", "knob",
                               "deadline_us" if k == "deadline_ms"
                               else k)
            for k in self.knobs}
        self._refresh_gauges()

    # ------------------------------------------------------------ lifecycle --
    def start(self) -> None:
        if self.period_s > 0 and not self._stopped:
            self._arm()

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _arm(self) -> None:
        from ..util.timer import VirtualTimer
        if self._timer is None:
            self._timer = VirtualTimer(self._app.clock)
        self._timer.expires_from_now(self.period_s)
        self._timer.async_wait(self._fire)

    def _fire(self) -> None:
        from ..main.application import AppState
        if self._stopped or \
                self._app.state == AppState.APP_STOPPING_STATE:
            # a dead node must not keep a recurring event on the
            # (possibly shared) simulation clock forever
            return
        try:
            self.tick()
        except Exception:                        # noqa: BLE001
            # control must never take the node down; the next fire
            # retries against whatever state then exists
            log.debug("controller tick failed", exc_info=True)
        self._arm()

    # ----------------------------------------------------------------- tick --
    def tick(self, sample: Optional[dict] = None) -> None:
        """One control step: read the newest telemetry sample (or the
        given one — the manual-tick benches/tests), judge, actuate.
        All timing reads the sample's ``t``; re-ticking against an
        already-consumed sample is a no-op."""
        if sample is None:
            sample = self._app.telemetry.series.latest()
        if sample is None:
            return
        # content-based identity: re-ticking against the same sample
        # (same epoch/cursor, or same `t` for cursor-less manual
        # samples) is a no-op — never id(), whose reuse after GC could
        # silently drop a control step
        key = (self._app.telemetry.series.epoch,
               sample.get("cursor"), sample.get("t"))
        if key == self._last_sample_key:
            return
        self._last_sample_key = key
        self.ticks += 1
        self._tick_counter.inc()
        t = sample.get("t", 0.0)
        self._learn_close_cost(sample)
        self._observe_mesh(sample, t)
        if self.frozen:
            self._freeze_counter.inc()
            return
        breaker = sample.get("breaker")
        if breaker is not None and breaker != "CLOSED":
            # breaker interplay: the aggregate leaves CLOSED only when
            # the WHOLE mesh is unavailable (per-device breakers,
            # ops/backend_supervisor.py) — every dispatch rides the
            # native fallback, so AIMD feedback would mis-train the
            # device knobs. Freeze tuning, keep shedding
            # (docs/ROBUSTNESS.md interaction table). A partial mesh
            # keeps tuning: the batch path is still the device path.
            self._freeze_counter.inc()
        else:
            self._tune(sample, t)
        self._shed(sample, t)
        self._refresh_gauges()

    def _observe_mesh(self, sample: dict, t: float) -> None:
        """Track the surviving-device fraction from the sample (never
        the live supervisor — replay determinism). Feeds the capacity
        scaling in _close_capacity_txs."""
        mesh = sample.get("mesh") or {}
        total = mesh.get("devices") or 0
        active = mesh.get("active")
        frac = (active / total) if total and active is not None else 1.0
        if frac != self._mesh_frac:
            self._record("mesh", "fraction",
                         round(self._mesh_frac, 4), round(frac, 4), t,
                         "verify mesh %s/%s devices"
                         % (active if total else "-",
                            total if total else "-"))
            self._mesh_frac = frac

    # ----------------------------------------------------------- AIMD tune --
    def _tune(self, sample: dict, t: float) -> None:
        v = sample.get("verify")
        if not v:
            return
        qw = v.get("queue_wait_p99_ms") or 0.0
        occ = v.get("occupancy_p99") or 0
        pending = v.get("queue_pending") or 0
        max_batch = self.knobs["max_batch"]
        deadline = self.knobs["deadline_ms"]
        min_batch = self.knobs["min_batch"]
        congested = qw > self._queue_wait_target_ms
        if congested:
            # multiplicative back-off on the latency knob
            self._set_knob("deadline_ms",
                           deadline * self._aimd_decrease, t,
                           "queue_wait_p99 %.2fms > %.2fms target"
                           % (qw, self._queue_wait_target_ms))
            if pending > 4 * max_batch:
                self._set_knob("max_batch",
                               int(max_batch * self._aimd_decrease), t,
                               "pending %d > 4x max_batch" % pending)
        elif v.get("flushes"):
            if occ >= 0.8 * max_batch:
                # batches filling with latency headroom: probe upward
                self._set_knob("max_batch",
                               max_batch + self._aimd_increase, t,
                               "occupancy_p99 %g >= 0.8x max_batch"
                               % occ)
            elif 0 < occ < min_batch:
                # flushes riding the host bypass: coalesce longer
                self._set_knob("deadline_ms",
                               deadline * self._deadline_grow, t,
                               "occupancy_p99 %g < min_batch %d"
                               % (occ, min_batch))
        disp = sample.get("dispatch")
        if disp:
            # only judge the dispatch shape when NEW dispatches landed
            # since the last tick: pad_waste_ratio is a lifetime
            # cumulative, so re-firing on stale evidence would ratchet
            # min_batch to the cap and silently disable the device
            count = disp.get("count") or 0
            if self._prev_dispatch_count is None:
                # resync tick (fresh controller, or reset() while the
                # cumulative dispatch accounting survived): record the
                # baseline, judge nothing
                fresh = False
            else:
                fresh = count > self._prev_dispatch_count
            self._prev_dispatch_count = count
            if not fresh:
                return
            waste = disp.get("pad_waste_ratio") or 0.0
            batch_p99 = disp.get("batch_p99") or 0
            if waste > 0.6 and batch_p99 < 2 * min_batch:
                self._set_knob("min_batch", min_batch * 2, t,
                               "pad_waste %.2f on small dispatches"
                               % waste)
            elif batch_p99 > 4 * min_batch and min_batch > \
                    self._cfg_knobs["min_batch"]:
                self._set_knob("min_batch", min_batch // 2, t,
                               "dispatch batch_p99 %g >> min_batch"
                               % batch_p99)

    def _set_knob(self, field: str, value, t: float,
                  reason: str) -> None:
        lo, hi = {"max_batch": (MAX_BATCH_FLOOR, MAX_BATCH_CEIL),
                  "deadline_ms": (DEADLINE_FLOOR_MS, DEADLINE_CEIL_MS),
                  "min_batch": (MIN_BATCH_FLOOR, MIN_BATCH_CEIL)}[field]
        if field == "deadline_ms":
            value = round(_clamp(float(value), lo, hi), 4)
        else:
            value = int(_clamp(int(value), lo, hi))
        old = self.knobs[field]
        if value == old:
            return
        self.knobs[field] = value
        self._tune_counters["up" if value > old else "down"].inc()
        self._apply_knobs()
        self._record("tune", field, old, value, t, reason)

    def _apply_knobs(self) -> None:
        """Push the searched values into the live subsystems —
        mutable-safe: the service swaps under its own lock, the
        verifier's bypass threshold is a plain attribute read
        per-flush."""
        svc = getattr(self._app, "verify_service", None)
        if svc is not None:
            svc.set_knobs(max_batch=self.knobs["max_batch"],
                          deadline_ms=self.knobs["deadline_ms"])
        bv = getattr(self._app, "batch_verifier", None)
        if bv is not None and hasattr(bv, "set_device_min_batch"):
            bv.set_device_min_batch(self.knobs["min_batch"])

    # ------------------------------------------------------------- shedding --
    def _shed(self, sample: dict, t: float) -> None:
        rules = self._app.slo.status().get("rules", {})
        from .slo import BREACH, WARN, _SEVERITY
        worst = "OK"
        for name in ("close_p99", "tx_e2e_p99"):
            verdict = rules.get(name, {}).get("verdict", "OK")
            if _SEVERITY.get(verdict, 0) > _SEVERITY.get(worst, 0):
                worst = verdict
        # read ladder FIRST: the read tier is the sacrificial layer.
        # It ramps on its own SLO (read_p99) AND on any write-path
        # pressure, twice as fast as the write ladders — by the time
        # close/tx_e2e would shed, reads are already mostly gone.
        read_verdict = rules.get("read_p99", {}).get("verdict", "OK")
        read_worst = read_verdict
        for name in ("close_p99", "tx_e2e_p99"):
            v = rules.get(name, {}).get("verdict", "OK")
            if _SEVERITY.get(v, 0) > _SEVERITY.get(read_worst, 0):
                read_worst = v
        read = self.shed_read
        if read_worst == BREACH:
            read = min(self._shed_max, read + 4 * self._shed_step)
        elif read_worst == WARN:
            read = min(self._shed_max, read + 2 * self._shed_step)
        else:
            read = max(0.0, read - self._shed_decay)
        tx, flood = self.shed_tx, self.shed_flood
        if worst == BREACH:
            tx = min(self._shed_max, tx + 2 * self._shed_step)
            flood = min(self._shed_max, flood + self._shed_step)
        elif worst == WARN:
            # backpressure local submitters first; flood relief
            # decays even under sustained WARN, or one BREACH tick
            # would pin flood drops at the high-water mark for as
            # long as the node hovers in the warn band
            tx = min(self._shed_max, tx + self._shed_step)
            flood = max(0.0, flood - self._shed_decay)
        else:
            tx = max(0.0, tx - self._shed_decay)
            flood = max(0.0, flood - self._shed_decay)
        # the surge gate: queue already holds more than can close
        # inside the SLO budget — slam the submit gate shut before the
        # node pays for work it would drop (Tail-at-Scale)
        capacity = self._close_capacity_txs()
        pending = sample.get("pending_txs") or 0
        if capacity is not None and pending > capacity:
            if self.shed_tx < self._shed_max:
                # record the gate ENGAGING, not every pinned tick
                self._record(
                    "shed", "backlog", round(self.shed_tx, 4),
                    self._shed_max, t,
                    "pending %d > close capacity %d" % (pending,
                                                        capacity))
            tx = self._shed_max
        if (tx, flood, read) != (self.shed_tx, self.shed_flood,
                                 self.shed_read):
            self._shed_change_counter.inc()
            if worst != "OK" or read_worst != "OK" or \
                    (tx, flood, read) == (0.0, 0.0, 0.0) or \
                    tx < self.shed_tx or flood < self.shed_flood or \
                    read < self.shed_read:
                reason = "slo %s/read %s" % (worst, read_verdict)
            else:
                reason = "ramp"
            self._record("shed", "levels",
                         [round(self.shed_tx, 4),
                          round(self.shed_flood, 4),
                          round(self.shed_read, 4)],
                         [round(tx, 4), round(flood, 4),
                          round(read, 4)], t, reason)
        self.shed_tx, self.shed_flood = round(tx, 4), round(flood, 4)
        self.shed_read = round(read, 4)

    def _learn_close_cost(self, sample: dict) -> None:
        """EWMA per-tx close cost from the series: Δ applied txs / Δ
        ledgers between ticks vs the windowed close median. Feeds the
        surge gate's capacity estimate; None until two ticks have seen
        a close."""
        ledger = sample.get("ledger")
        applied = sample.get("tx_applied")
        close = sample.get("close") or {}
        if ledger is None or applied is None:
            return
        prev_l, prev_a = self._prev_ledger, self._prev_tx_applied
        self._prev_ledger, self._prev_tx_applied = ledger, applied
        if prev_l is None or ledger <= prev_l or applied <= prev_a:
            return
        med = close.get("median_ms")
        if not med:
            return
        # closes measured on a SHRUNK mesh do not feed the cost model:
        # _close_capacity_txs already discounts by the surviving
        # fraction, and absorbing the degraded (higher) per-tx cost
        # too would double-count the outage — the EWMA must keep
        # meaning "full-mesh cost" for the discount to be sound. The
        # mesh state is read from THIS sample (not the live
        # supervisor) for replay determinism.
        mesh = sample.get("mesh") or {}
        if mesh.get("devices") and \
                mesh.get("active", mesh["devices"]) < mesh["devices"]:
            return
        avg_txset = (applied - prev_a) / (ledger - prev_l)
        if avg_txset <= 0:
            return
        cost = med / avg_txset
        if self._cost_ms_per_tx is None:
            self._cost_ms_per_tx = cost
        else:
            self._cost_ms_per_tx = round(
                0.7 * self._cost_ms_per_tx + 0.3 * cost, 6)
        # demonstrated-safe throughput: the largest average txset the
        # node closed while close p99 sat BELOW the warn band. The
        # average-cost model folds the fixed per-ledger overhead into
        # the per-tx cost, which understates capacity and would shed
        # baseline load the node demonstrably serves within SLO — the
        # floor keeps the gate honest, and because it only rises while
        # the verdict band is clean it self-regulates toward (never
        # past) the warn boundary.
        p99 = close.get("p99_ms") or med
        if p99 < 0.8 * self._app.config.SLO_CLOSE_P99_MS:
            self._safe_txset = max(self._safe_txset, int(avg_txset))

    def _close_capacity_txs(self) -> Optional[int]:
        if not self._cost_ms_per_tx:
            return None
        budget_ms = self._app.config.SLO_CLOSE_P99_MS \
            * self._backlog_factor
        # partial-mesh scaling: the cost model and the demonstrated-
        # safe floor were both learned on the full mesh — while the
        # verify mesh runs N-1/N, the surge gate must assume N-1/N of
        # that capacity or it admits a backlog the degraded node
        # cannot close inside the SLO budget
        return max(1, int(budget_ms / self._cost_ms_per_tx
                          * self._mesh_frac),
                   int(self._safe_txset * self._mesh_frac))

    # ------------------------------------------------------ admission rolls --
    def roll_tx_shed(self) -> bool:
        """One tx-submit admission decision (herder.recv_transaction,
        direct-submit path). True = shed this submission."""
        if self.shed_tx <= 0.0:
            return False
        if self._shed_rng.random() >= self.shed_tx:
            return False
        self._shed_dropped["tx"].inc()
        return True

    def roll_read_shed(self) -> bool:
        """One read-admission decision (query/service.py submit path,
        BEFORE the request queues). True = shed this read."""
        if self.shed_read <= 0.0:
            return False
        if self._shed_rng.random() >= self.shed_read:
            return False
        self._shed_dropped["read"].inc()
        return True

    def roll_flood_shed(self) -> bool:
        """One flood-admission decision (overlay _on_transaction,
        BEFORE the batched verify dispatch). True = shed this frame."""
        if self.shed_flood <= 0.0:
            return False
        if self._shed_rng.random() >= self.shed_flood:
            return False
        self._shed_dropped["flood"].inc()
        return True

    # ------------------------------------------------------------ recording --
    def _record(self, kind: str, field: str, old, new, t: float,
                reason: str) -> None:
        entry = {"t": round(t, 3), "kind": kind, "field": field,
                 "old": old, "new": new, "reason": reason}
        self.decisions.append(entry)
        if tracing.ENABLED:
            rec = self._recorder
            if rec is not None and rec.active:
                rec.instant("controller." + kind, dict(entry))

    def _refresh_gauges(self) -> None:
        self._shed_gauges["tx"].set_count(int(self.shed_tx * 1000))
        self._shed_gauges["flood"].set_count(
            int(self.shed_flood * 1000))
        self._shed_gauges["read"].set_count(
            int(self.shed_read * 1000))
        for k, v in self.knobs.items():
            if k == "deadline_ms":
                # exported in µs: the envelope reaches 0.25 ms, and an
                # integer ms gauge would read 0 across the whole
                # sub-millisecond half of the search space
                self._knob_gauges[k].set_count(int(v * 1000))
            else:
                self._knob_gauges[k].set_count(int(v))

    # --------------------------------------------------------------- control --
    def freeze(self) -> None:
        """Admin pin: no further tuning or shed-level moves; existing
        shed probabilities keep applying (the `controller` route)."""
        self.frozen = True

    def reset(self) -> None:
        """`clearmetrics` / `controller?action=reset` hook: drop every
        learned value — knobs back to config, shed probabilities to
        zero, decision log emptied, cost estimate forgotten — and
        rotate the epoch so a frozen or mis-trained controller cannot
        leak tuning into the next bench leg (the PR 10 time-series
        epoch contract)."""
        self.knobs = dict(self._cfg_knobs)
        self._apply_knobs()
        self.shed_tx = self.shed_flood = self.shed_read = 0.0
        self.frozen = False
        self.decisions.clear()
        self.ticks = 0
        self.epoch += 1
        self._last_sample_key = None
        self._prev_ledger = self._prev_tx_applied = None
        self._prev_dispatch_count = None
        self._cost_ms_per_tx = None
        self._safe_txset = 0
        self._mesh_frac = 1.0
        self._refresh_gauges()

    # ----------------------------------------------------------------- view --
    def status(self) -> dict:
        """The `controller` admin route document (also what
        simulation/cluster.py polls into CLUSTER artifacts)."""
        return {
            "enabled": self.period_s > 0,
            "period_s": self.period_s,
            "frozen": self.frozen,
            "epoch": self.epoch,
            "ticks": self.ticks,
            "knobs": dict(self.knobs),
            "config_knobs": dict(self._cfg_knobs),
            "shed": {"tx": self.shed_tx, "flood": self.shed_flood,
                     "read": self.shed_read,
                     "tx_dropped": self._shed_dropped["tx"].count,
                     "flood_dropped":
                         self._shed_dropped["flood"].count,
                     "read_dropped":
                         self._shed_dropped["read"].count},
            "cost_ms_per_tx": self._cost_ms_per_tx,
            "safe_txset": self._safe_txset,
            "mesh_fraction": round(self._mesh_frac, 4),
            "close_capacity_txs": self._close_capacity_txs(),
            "decisions": {
                "total": len(self.decisions),
                "tune_up": self._tune_counters["up"].count,
                "tune_down": self._tune_counters["down"].count,
                "shed_changes": self._shed_change_counter.count,
                "tail": list(self.decisions)[-20:],
            },
        }
