"""Batch Ed25519 verifier: host prep + TPU kernel + sharding.

This is the TPU implementation of the crypto-verifier seam (reference:
PubKeyUtils::verifySig, crypto/SecretKey.cpp:427-460; batch collection
points: txset validation herder/TxSetUtils.cpp:200 and catchup replay
catchup/ApplyCheckpointWork.h — see SURVEY.md §3.2/§3.3).

Pipeline per batch of (pubkey, sig, msg):
  1. host (native C++, Python-oracle fallback):
     k = SHA512(R‖A‖M) mod L; S<L check; strict decompress + small-order
     checks on A and R; affine -A coords.  (SHA-512's 64-bit rotates are
     hostile to TPU int ops — SURVEY §7 "hard parts" — so hashing stays
     host-side; only the scalar muls go on device.)
  2. pad to a power-of-two bucket (static shapes => one XLA program per
     bucket size, no recompiles).
  3. device: Shamir double-scalar-mult + compress + compare (ed25519_kernel).
  4. AND host flags, unpad.

Accept/reject is bit-identical to the oracle (ed25519_ref.verify) and is
enforced differentially in tests/test_tpu_verifier.py.

Multi-chip: `make_sharded_verify` shard_maps the kernel over a 1-D 'dp'
mesh axis — signatures are embarrassingly data-parallel (SURVEY §5.7),
so the only cross-device traffic is the result gather.

Mesh health (PR 13): `ShardedBatchVerifier` dispatches padded
PER-SHARD buckets over the mesh of *active* devices — the SNIPPETS §2–3
mesh-dispatch shape: a shard_map-wrapped jit per active set, with a
single-device short-circuit (plain jit pinned by `device_put`) when
only one device survives. `set_active_devices` shrinks/regrows the
mesh live (the per-device circuit breakers in
ops/backend_supervisor.py drive it), including non-power-of-two
surviving meshes — the global bucket stays a multiple of the ACTIVE
device count, doubling from the smallest such multiple ≥ MIN_BUCKET.
Per-device dispatch accounting (`crypto.verify.dispatch.device<N>.*`)
gives the breaker the signals to judge a sick chip against its
siblings. Results are byte-identical across mesh shapes: every lane
runs the identical per-lane kernel; only the shard layout moves.
"""

from __future__ import annotations

import hashlib
import time as _time
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as PSpec
try:
    from jax import shard_map
except ImportError:                                  # pragma: no cover
    # older jax exposes shard_map under jax.experimental
    from jax.experimental.shard_map import shard_map

from . import ed25519_kernel
from .shard_math import shard_shares
from ..crypto import ed25519_ref as _ref
from ..util import chaos

MIN_BUCKET = 8

# On-device SHA-512 for fixed-32-byte messages (the tx-hash hot path).
# Default ON: in the node the host core is the apply/consensus
# bottleneck, and freeing it from per-signature SHA-512 prep measured
# +13% catchup throughput (docs/KERNEL_PROFILE.md §5). A harness whose
# host is otherwise idle (the isolated verify bench) does better with
# host-side prep overlapped behind device compute — pass
# device_sha=False there. ED25519_DEVICE_SHA=0/1 overrides both for A/B.
# Semantics are identical either way (differentially enforced in
# tests/test_tpu_verifier.py).
import os as _os


def _device_sha_default(explicit):
    env = _os.environ.get("ED25519_DEVICE_SHA")
    if env is not None:
        return env != "0"
    return True if explicit is None else explicit


# Small-batch CPU bypass for verify_tuples_async: below this many
# signatures the fixed dispatch cost (array packing, transfer, XLA
# launch, result sync) loses to the native per-signature verifier, so
# tiny batches run on host instead (bench.py --min-batch measures the
# crossover; docs/APPLY_PERF.md records it). Semantics are identical
# either way — both paths are the same strict verify. The module
# default of 1 means "never bypass" so the kernel test tier keeps
# exercising the device path down to batch size 1; the node wires its
# VERIFY_DEVICE_MIN_BATCH config knob through Application.
# VERIFY_DEVICE_MIN_BATCH=<n> in the environment overrides both for A/B,
# like ED25519_DEVICE_SHA.
DEVICE_MIN_BATCH = 1


def _device_min_batch_default(explicit):
    env = _os.environ.get("VERIFY_DEVICE_MIN_BATCH")
    if env is not None:
        return int(env)
    return DEVICE_MIN_BATCH if explicit is None else int(explicit)


def _bucket_size(n: int, minimum: int = MIN_BUCKET) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def prevalidate_coalesce(counts: Sequence[int], max_fuse: int,
                         minimum: int = MIN_BUCKET) -> int:
    """How many pending checkpoints' signature batches the catchup
    pipeline should fuse into ONE device dispatch (catchup/pipeline.py's
    prevalidation stage sizing its batch from the ahead-window).

    `counts[i]` is checkpoint i's signature-tuple count, in replay
    order. Device batches pad to a power-of-two bucket (static shapes,
    one XLA program per size — `_bucket_size`), so fusing is accepted
    greedily while it wastes no padding slots versus separate
    dispatches: e.g. 300+300 fused costs bucket(600)=1024 = 512+512
    separate (equal slots, one launch saved — fuse), while 512+10
    fused costs bucket(522)=1024 > 512+16 (reject). Zero-count
    checkpoints fuse for free. Deterministic, pure, unit-tested in
    tests/test_catchup_pipeline.py."""
    if not counts:
        return 0
    k = 1
    total = counts[0]
    while k < min(len(counts), max_fuse):
        nxt = counts[k]
        if nxt:
            fused = _bucket_size(total + nxt, minimum)
            separate = (_bucket_size(total, minimum) if total else 0) \
                + _bucket_size(nxt, minimum)
            if fused > separate:
                break
            total += nxt
        k += 1
    return k


def _native():
    try:
        from ..native import loader
        return loader.get_lib()
    except Exception:
        return None


def _prep_python(pubs: np.ndarray, sigs: np.ndarray,
                 msgs: Sequence[bytes]):
    """Oracle-backed host prep (fallback when the native lib is absent)."""
    n = len(msgs)
    k_out = np.zeros((n, 32), dtype=np.uint8)
    neg_a = np.zeros((n, 64), dtype=np.uint8)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        pub, sig, msg = bytes(pubs[i]), bytes(sigs[i]), msgs[i]
        s = int.from_bytes(sig[32:], "little")
        if s >= _ref.L:
            continue
        a_pt = _ref.pt_decompress(pub, strict=True)
        if a_pt is None or _ref.pt_is_small_order(a_pt):
            continue
        r_pt = _ref.pt_decompress(sig[:32], strict=True)
        if r_pt is None or _ref.pt_is_small_order(r_pt):
            continue
        k = _ref.compute_k(sig[:32], pub, msg)
        k_out[i] = np.frombuffer(k.to_bytes(32, "little"), dtype=np.uint8)
        nx = (_ref.P - a_pt[0]) % _ref.P
        neg_a[i, :32] = np.frombuffer(nx.to_bytes(32, "little"),
                                      dtype=np.uint8)
        neg_a[i, 32:] = np.frombuffer(a_pt[1].to_bytes(32, "little"),
                                      dtype=np.uint8)
        ok[i] = True
    return k_out, neg_a, ok


def host_prepare(pubs: np.ndarray, sigs: np.ndarray, msgs: Sequence[bytes]):
    """Returns (k (n,32) u8, neg_a (n,64) u8, ok (n,) bool)."""
    lib = _native()
    if lib is None:
        return _prep_python(pubs, sigs, msgs)
    offsets = np.zeros(len(msgs) + 1, dtype=np.uint64)
    np.cumsum([len(m) for m in msgs], out=offsets[1:])
    blob = b"".join(msgs)
    k, s_ok = lib.batch_prepare(pubs, sigs, blob, offsets)
    neg_a, pt_ok = lib.batch_host_precheck(pubs, sigs)
    return k, neg_a, s_ok & pt_ok


def host_k(pubs: np.ndarray, sigs: np.ndarray, msgs: Sequence[bytes]):
    """v2 host prep: just k = SHA512(R‖A‖M) mod L, (n,32) u8 — point
    decompression and all canonicality checks run on device
    (ed25519_kernel.verify_kernel_full). SHA-512 stays host-side: 64-bit
    rotates are hostile to the TPU int units (SURVEY.md §7 hard parts)."""
    lib = _native()
    if lib is not None:
        offsets = np.zeros(len(msgs) + 1, dtype=np.uint64)
        np.cumsum([len(m) for m in msgs], out=offsets[1:])
        blob = b"".join(msgs)
        k, _ = lib.batch_prepare(pubs, sigs, blob, offsets)
        return k
    n = len(msgs)
    k = np.zeros((n, 32), dtype=np.uint8)
    for i in range(n):
        ki = _ref.compute_k(bytes(sigs[i, :32]), bytes(pubs[i]), msgs[i])
        k[i] = np.frombuffer(ki.to_bytes(32, "little"), dtype=np.uint8)
    return k


def _pad_u8(arr: np.ndarray, bucket: int) -> np.ndarray:
    """(n,32) u8 -> (bucket,32) u8, zero-padded (pad lanes decode as the
    torsion point y=0 and are rejected on device; results are sliced off)."""
    n = arr.shape[0]
    if n == bucket:
        return np.ascontiguousarray(arr)
    out = np.zeros((bucket, 32), dtype=np.uint8)
    out[:n] = arr
    return out


class TpuBatchVerifier:
    """Batch verifier on the default JAX backend (TPU in production,
    CPU mesh in tests). Thread-compatible with the sync seam: results are
    per-signature bools identical to PubKeyUtils.verify_sig.

    v2 pipeline: uint8 transfer (128 B/sig over the host link), SHA-512 on
    host, everything else — decompression, strict checks, double scalar
    mult, compare — on device."""

    _shared_jit = None   # one compiled program per process, not per instance
    _shared_jit_msg32 = None

    @classmethod
    def _ensure_shared_jits(cls):
        if TpuBatchVerifier._shared_jit is None:
            TpuBatchVerifier._shared_jit = jax.jit(
                ed25519_kernel.verify_kernel_full)
            TpuBatchVerifier._shared_jit_msg32 = jax.jit(
                ed25519_kernel.verify_kernel_msg32)

    def __init__(self, perf=None, device_sha=None, device_min_batch=None,
                 metrics=None):
        self._ensure_shared_jits()
        self._jit = TpuBatchVerifier._shared_jit
        self._jit_msg32 = TpuBatchVerifier._shared_jit_msg32
        self._min_bucket = MIN_BUCKET
        self._device_sha = _device_sha_default(device_sha)
        self._device_min_batch = _device_min_batch_default(device_min_batch)
        self.perf = perf  # per-app zone registry (None = process default)
        self._init_dispatch_metrics(metrics)

    def set_device_min_batch(self, n: int) -> None:
        """Live re-tune of the host-bypass cutoff (ops/controller.py;
        inherited by the sharded/hybrid verifiers, proxied through the
        backend supervisor). A plain attribute swap read once per
        flush — no torn state possible."""
        self._device_min_batch = max(1, int(n))

    def _init_dispatch_metrics(self, metrics) -> None:
        """Per-dispatch device accounting (telemetry time-series /
        ROADMAP item 1 groundwork): batch size, padding waste (lanes
        burnt on the power-of-two bucket), and dispatch→collect wall
        time — the per-device health signals a per-device breaker will
        consume. None = accounting off (the bench/test constructors)."""
        if metrics is None:
            self._m_batch = self._m_padding = self._m_wall = None
            return
        self._m_batch = metrics.new_histogram(
            "crypto.verify.dispatch.batch")
        self._m_padding = metrics.new_histogram(
            "crypto.verify.dispatch.padding")
        self._m_wall = metrics.new_timer("crypto.verify.dispatch.wall")

    def verify_batch(self, pubs: np.ndarray, sigs: np.ndarray,
                     msgs: Sequence[bytes]) -> np.ndarray:
        return self.verify_batch_async(pubs, sigs, msgs)()

    def verify_batch_async(self, pubs: np.ndarray, sigs: np.ndarray,
                           msgs: Sequence[bytes]):
        """Dispatch a batch without blocking; returns a zero-arg callable
        that yields the (n,) bool results. Callers with several batches in
        flight (catchup prevalidation, the bench harness) overlap host
        SHA-512 + transfer of batch i+1 with device compute of batch i."""
        n = len(msgs)
        if n == 0:
            return lambda: np.zeros(0, dtype=bool)
        pubs = np.asarray(pubs, dtype=np.uint8).reshape(n, 32)
        sigs = np.asarray(sigs, dtype=np.uint8).reshape(n, 64)
        bucket = _bucket_size(n, self._min_bucket)
        if self._device_sha and all(len(m) == 32 for m in msgs):
            # tx-hash hot path: ship M raw, SHA-512 + mod L on device —
            # zero per-signature host work (docs/KERNEL_PROFILE.md §4)
            m = np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(n, 32)
            out = self._jit_msg32(
                _pad_u8(pubs, bucket),
                _pad_u8(sigs[:, :32], bucket),
                _pad_u8(np.ascontiguousarray(sigs[:, 32:]), bucket),
                _pad_u8(m, bucket))
        else:
            k = host_k(pubs, sigs, msgs)
            out = self._jit(
                _pad_u8(pubs, bucket),
                _pad_u8(sigs[:, :32], bucket),
                _pad_u8(np.ascontiguousarray(sigs[:, 32:]), bucket),
                _pad_u8(k, bucket))
        if self._m_batch is None:
            return lambda: np.asarray(out)[:n]
        # dispatch accounting: occupancy and padding recorded at
        # dispatch, wall time at FIRST collect (the async split —
        # collect blocks on device completion, so first-collect wall
        # is the true dispatch→results latency)
        self._m_batch.update(n)
        self._m_padding.update(bucket - n)
        t0 = _time.perf_counter()
        state = {"done": False}

        def collect():
            res = np.asarray(out)[:n]
            if not state["done"]:
                state["done"] = True
                self._m_wall.update(_time.perf_counter() - t0)
            return res
        return collect

    def verify_tuples(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]) -> List[bool]:
        return self.verify_tuples_async(items)()

    def verify_tuples_async(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]):
        """Non-blocking verify_tuples: dispatches host prep + transfer +
        device compute and returns a zero-arg callable yielding the
        List[bool]. Used to overlap checkpoint N+1's signature batch with
        checkpoint N's sequential apply in catchup. The crypto.batchVerify
        perf zone wraps dispatch and (separately) collection, so the
        accounting survives the async split."""
        if not items:
            return lambda: []
        if chaos.ENABLED:
            # device-verifier fault seam: an injected io_error raises
            # BEFORE any dispatch — callers must fall back to the
            # native per-signature path (semantics are identical).
            # Fired before the small-batch bypass decision so the seam
            # contract is batch-size independent.
            chaos.point("ops.verifier.batch", n=len(items))
        from ..util import tracing
        from ..util.perf import default_registry
        registry = self.perf or default_registry
        targs = {"batch": len(items)} if tracing.ENABLED else None
        if len(items) < self._device_min_batch:
            # small-batch CPU bypass: the fixed device dispatch cost
            # loses to the native verifier below the cutoff, so tiny
            # flushes (the verify service's deadline stragglers) stay
            # on host — same strict accept/reject either way
            from ..crypto.keys import verify_sig_uncached
            with registry.zone("crypto.batchVerify.native", targs=targs):
                res = [verify_sig_uncached(p, s, m) for p, s, m in items]
            return lambda: res
        with registry.zone("crypto.batchVerify", targs=targs):
            pubs = np.frombuffer(b"".join(p for p, _, _ in items),
                                 dtype=np.uint8).reshape(-1, 32)
            sigs = np.frombuffer(b"".join(s for _, s, _ in items),
                                 dtype=np.uint8).reshape(-1, 64)
            handle = self.verify_batch_async(pubs, sigs,
                                             [m for _, _, m in items])

        def collect():
            with registry.zone("crypto.batchVerify", targs=targs):
                return list(handle())
        return collect

    def verify_tuples_async_on(self, device_index: int, items):
        """Pinned single-device dispatch — the per-device canary-probe
        entry point (ops/backend_supervisor.py). The single-device
        verifier has exactly one device, so this is the plain path;
        the sharded verifier overrides it with real placement."""
        if int(device_index) != 0:
            raise IndexError(
                f"single-device verifier has no device {device_index}")
        return self.verify_tuples_async(items)


def make_sharded_verify(mesh: Mesh, axis: str = "dp",
                        kernel=ed25519_kernel.verify_kernel_full):
    """shard_map'd v2/v3 kernel over a 1-D mesh axis: the batch axis of
    the (B,32) uint8 inputs is sharded, each device runs the identical
    decompress+scalar-mult program on its shard; the only cross-device
    traffic is the (B,) bool result gather. B must divide by mesh size."""
    spec = PSpec(axis, None)
    f = shard_map(kernel, mesh=mesh,
                  in_specs=(spec,) * 4, out_specs=PSpec(axis))
    return jax.jit(f)


class ShardedBatchVerifier(TpuBatchVerifier):
    """Data-parallel verifier over the ACTIVE subset of a 1-D device
    mesh.

    Each dispatch splits the batch into padded per-shard buckets —
    shard ``s`` owns rows ``[s*rows, s*rows+count_s)`` of the global
    array, the rest of its slice is zero padding (rejected on device
    like every pad lane) — and runs the SNIPPETS §2–3 mesh-dispatch
    pattern over the active devices: a ``shard_map``-wrapped jit when
    two or more survive, a plain jit pinned via ``device_put`` when
    exactly one does (the single-device short-circuit). Programs are
    cached per (active set, kernel), so 8→7→8 health transitions reuse
    compiled meshes. Non-power-of-two surviving meshes work because
    the global bucket doubles from the smallest multiple of the ACTIVE
    count ≥ MIN_BUCKET, never from a power of two."""

    def __init__(self, devices: Optional[list] = None, axis: str = "dp",
                 perf=None, device_sha=None, device_min_batch=None,
                 metrics=None):
        self.perf = perf
        self._device_sha = _device_sha_default(device_sha)
        self._device_min_batch = _device_min_batch_default(device_min_batch)
        self.devices = list(devices) if devices is not None \
            else list(jax.devices())
        self.ndev = len(self.devices)
        self._axis = axis
        self.mesh = Mesh(np.array(self.devices), (axis,))
        self._active: Tuple[int, ...] = tuple(range(self.ndev))
        # (active tuple, msg32) -> (compiled fn, pin device or None);
        # built lazily so a mesh shape is only compiled when
        # dispatched, LRU-bounded so independently flapping breakers
        # (up to 2^ndev distinct survivor subsets, each an XLA
        # executable) cannot grow the hot path's memory forever — the
        # shapes a live mesh actually revisits (full set, full-minus-
        # one, the current survivors) stay resident
        from collections import OrderedDict
        import threading
        self._programs: "OrderedDict" = OrderedDict()
        self._max_programs = 16
        # guards the cache bookkeeping only (never held across a
        # compile): probe timers and dispatch callers reach _program
        # concurrently, and a get/move_to_end racing an eviction
        # would KeyError on the hot path
        self._programs_lock = threading.Lock()
        # bucket sizes must stay divisible by the mesh size: start from the
        # smallest multiple of ndev >= MIN_BUCKET (doubling in _bucket_size
        # preserves divisibility)
        self._min_bucket = self._min_bucket_for(self.ndev)
        self._init_dispatch_metrics(metrics)

    # ------------------------------------------------------ mesh health --
    @staticmethod
    def _min_bucket_for(nact: int) -> int:
        return ((MIN_BUCKET + nact - 1) // nact) * nact

    def set_active_devices(self, indices) -> None:
        """Live mesh shrink/regrow (driven by the per-device breakers
        in ops/backend_supervisor.py): from the next dispatch on, the
        batch shards over exactly `indices` (global positions in
        ``self.devices``); an excluded device receives ZERO dispatches.
        A plain tuple swap — a concurrent dispatch sees the old or the
        new mesh, never a torn one."""
        idx = tuple(sorted({int(i) for i in indices}))
        if not idx:
            raise ValueError("active device set must not be empty "
                             "(mesh-empty falls back to native in the "
                             "backend supervisor)")
        if idx[0] < 0 or idx[-1] >= self.ndev:
            raise IndexError(f"device index out of range: {idx}")
        self._active = idx

    def active_indices(self) -> Tuple[int, ...]:
        return self._active

    def _program(self, active: Tuple[int, ...], msg32: bool):
        """(compiled fn, pin) for one active set: shard_map over the
        surviving mesh, or the shared single-device jit + an explicit
        pin device for the short-circuit."""
        key = (active, bool(msg32))
        with self._programs_lock:
            prog = self._programs.get(key)
            if prog is not None:
                self._programs.move_to_end(key)
                return prog
        # build OUTSIDE the lock: a concurrent duplicate build of the
        # same key is wasteful but harmless (last insert wins)
        prog = self._compile(active, msg32)
        with self._programs_lock:
            self._programs[key] = prog
            while len(self._programs) > self._max_programs:
                self._programs.popitem(last=False)
        return prog

    def _compile(self, active: Tuple[int, ...], msg32: bool):
        """Build one (compiled fn, pin device or None) for an active
        set — the only step subclasses override (the hybrid verifier's
        full-mesh 2-D program); the LRU protocol above stays in one
        place."""
        kernel = (ed25519_kernel.verify_kernel_msg32 if msg32
                  else ed25519_kernel.verify_kernel_full)
        if len(active) == 1:
            self._ensure_shared_jits()
            fn = (TpuBatchVerifier._shared_jit_msg32 if msg32
                  else TpuBatchVerifier._shared_jit)
            return (fn, self.devices[active[0]])
        mesh = Mesh(np.array([self.devices[i] for i in active]),
                    (self._axis,))
        return (make_sharded_verify(mesh, self._axis, kernel), None)

    # --------------------------------------------------------- metrics --
    def _init_dispatch_metrics(self, metrics) -> None:
        super()._init_dispatch_metrics(metrics)
        if metrics is None:
            self._m_dev = None
            return
        # per-device accounting (crypto.verify.dispatch.device<N>.*):
        # the per-device breaker judges a sick chip against its
        # siblings from these — batch share, padding burnt, and the
        # dispatch→collect wall the shard rode (for a collective
        # launch the wall is shared; the discriminating signals are
        # the per-device dispatch/skip/failure counters upstairs)
        self._m_dev = [
            {"batch": metrics.new_histogram(
                "crypto.verify.dispatch.device%d.batch" % i),
             "padding": metrics.new_histogram(
                 "crypto.verify.dispatch.device%d.padding" % i),
             "wall": metrics.new_timer(
                 "crypto.verify.dispatch.device%d.wall" % i)}
            for i in range(self.ndev)]

    # -------------------------------------------------------- dispatch --
    def verify_batch_async(self, pubs: np.ndarray, sigs: np.ndarray,
                           msgs: Sequence[bytes], _active=None):
        """Mesh dispatch: padded per-shard buckets over the active
        devices. `_active` pins an explicit set (the per-device canary
        probe path); None uses the live mesh."""
        n = len(msgs)
        if n == 0:
            return lambda: np.zeros(0, dtype=bool)
        active = tuple(_active) if _active is not None else self._active
        nact = len(active)
        pubs = np.asarray(pubs, dtype=np.uint8).reshape(n, 32)
        sigs = np.asarray(sigs, dtype=np.uint8).reshape(n, 64)
        bucket = _bucket_size(n, self._min_bucket_for(nact))
        rows = bucket // nact
        counts = shard_shares(n, nact)

        def layout(arr: np.ndarray) -> np.ndarray:
            # per-shard padded buckets: shard s gets its rows at the
            # head of its slice, zero padding behind (pad lanes decode
            # as the torsion point y=0 and are rejected on device)
            out = np.zeros((bucket, arr.shape[1]), dtype=np.uint8)
            off = 0
            for s, c in enumerate(counts):
                if c:
                    out[s * rows:s * rows + c] = arr[off:off + c]
                off += c
            return out

        msg32 = self._device_sha and all(len(m) == 32 for m in msgs)
        if msg32:
            # tx-hash hot path: SHA-512 + mod L on device (see
            # TpuBatchVerifier.verify_batch_async)
            last = np.frombuffer(b"".join(msgs),
                                 dtype=np.uint8).reshape(n, 32)
        else:
            last = host_k(pubs, sigs, msgs)
        args = (layout(pubs), layout(sigs[:, :32]),
                layout(np.ascontiguousarray(sigs[:, 32:])), layout(last))
        fn, pin = self._program(active, msg32)
        if pin is not None:
            args = tuple(jax.device_put(a, pin) for a in args)
        out = fn(*args)

        def unshard(res: np.ndarray) -> np.ndarray:
            parts = [res[s * rows:s * rows + counts[s]]
                     for s in range(nact)]
            return parts[0] if nact == 1 else np.concatenate(parts)

        if self._m_batch is None:
            return lambda: unshard(np.asarray(out))
        self._m_batch.update(n)
        self._m_padding.update(bucket - n)
        for s, c in enumerate(counts):
            dm = self._m_dev[active[s]]
            dm["batch"].update(c)
            dm["padding"].update(rows - c)
        t0 = _time.perf_counter()
        state = {"done": False}

        def collect():
            res = np.asarray(out)
            if not state["done"]:
                state["done"] = True
                dt = _time.perf_counter() - t0
                self._m_wall.update(dt)
                for s in range(nact):
                    self._m_dev[active[s]]["wall"].update(dt)
            return unshard(res)
        return collect

    def verify_tuples_async_on(self, device_index: int, items):
        """Dispatch one batch pinned to a SINGLE device, bypassing the
        active mesh — the per-device canary-probe path: probing a sick
        chip must not ride (or disturb) the survivors' mesh. Same
        min-batch bypass and accept/reject as verify_tuples_async."""
        device_index = int(device_index)
        if not 0 <= device_index < self.ndev:
            raise IndexError(f"no device {device_index} in this mesh")
        n = len(items)
        if n == 0:
            return lambda: []
        if chaos.ENABLED:
            # same seam contract as verify_tuples_async: the probe is
            # a device dispatch like any other
            chaos.point("ops.verifier.batch", n=n)
        if n < self._device_min_batch:
            from ..crypto.keys import verify_sig_uncached
            res = [verify_sig_uncached(p, s, m) for p, s, m in items]
            return lambda: res
        pubs = np.frombuffer(b"".join(p for p, _, _ in items),
                             dtype=np.uint8).reshape(n, 32)
        sigs = np.frombuffer(b"".join(s for _, s, _ in items),
                             dtype=np.uint8).reshape(n, 64)
        handle = self.verify_batch_async(pubs, sigs,
                                         [m for _, _, m in items],
                                         _active=(device_index,))
        return lambda: list(handle())
