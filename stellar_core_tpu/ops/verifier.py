"""Batch Ed25519 verifier: host prep + TPU kernel + sharding.

This is the TPU implementation of the crypto-verifier seam (reference:
PubKeyUtils::verifySig, crypto/SecretKey.cpp:427-460; batch collection
points: txset validation herder/TxSetUtils.cpp:200 and catchup replay
catchup/ApplyCheckpointWork.h — see SURVEY.md §3.2/§3.3).

Pipeline per batch of (pubkey, sig, msg):
  1. host (native C++, Python-oracle fallback):
     k = SHA512(R‖A‖M) mod L; S<L check; strict decompress + small-order
     checks on A and R; affine -A coords.  (SHA-512's 64-bit rotates are
     hostile to TPU int ops — SURVEY §7 "hard parts" — so hashing stays
     host-side; only the scalar muls go on device.)
  2. pad to a power-of-two bucket (static shapes => one XLA program per
     bucket size, no recompiles).
  3. device: Shamir double-scalar-mult + compress + compare (ed25519_kernel).
  4. AND host flags, unpad.

Accept/reject is bit-identical to the oracle (ed25519_ref.verify) and is
enforced differentially in tests/test_tpu_verifier.py.

Multi-chip: `make_sharded_verify` shard_maps the kernel over a 1-D 'dp'
mesh axis — signatures are embarrassingly data-parallel (SURVEY §5.7),
so the only cross-device traffic is the result gather.
"""

from __future__ import annotations

import hashlib
import time as _time
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as PSpec
try:
    from jax import shard_map
except ImportError:                                  # pragma: no cover
    # older jax exposes shard_map under jax.experimental
    from jax.experimental.shard_map import shard_map

from . import ed25519_kernel
from ..crypto import ed25519_ref as _ref
from ..util import chaos

MIN_BUCKET = 8

# On-device SHA-512 for fixed-32-byte messages (the tx-hash hot path).
# Default ON: in the node the host core is the apply/consensus
# bottleneck, and freeing it from per-signature SHA-512 prep measured
# +13% catchup throughput (docs/KERNEL_PROFILE.md §5). A harness whose
# host is otherwise idle (the isolated verify bench) does better with
# host-side prep overlapped behind device compute — pass
# device_sha=False there. ED25519_DEVICE_SHA=0/1 overrides both for A/B.
# Semantics are identical either way (differentially enforced in
# tests/test_tpu_verifier.py).
import os as _os


def _device_sha_default(explicit):
    env = _os.environ.get("ED25519_DEVICE_SHA")
    if env is not None:
        return env != "0"
    return True if explicit is None else explicit


# Small-batch CPU bypass for verify_tuples_async: below this many
# signatures the fixed dispatch cost (array packing, transfer, XLA
# launch, result sync) loses to the native per-signature verifier, so
# tiny batches run on host instead (bench.py --min-batch measures the
# crossover; docs/APPLY_PERF.md records it). Semantics are identical
# either way — both paths are the same strict verify. The module
# default of 1 means "never bypass" so the kernel test tier keeps
# exercising the device path down to batch size 1; the node wires its
# VERIFY_DEVICE_MIN_BATCH config knob through Application.
# VERIFY_DEVICE_MIN_BATCH=<n> in the environment overrides both for A/B,
# like ED25519_DEVICE_SHA.
DEVICE_MIN_BATCH = 1


def _device_min_batch_default(explicit):
    env = _os.environ.get("VERIFY_DEVICE_MIN_BATCH")
    if env is not None:
        return int(env)
    return DEVICE_MIN_BATCH if explicit is None else int(explicit)


def _bucket_size(n: int, minimum: int = MIN_BUCKET) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _native():
    try:
        from ..native import loader
        return loader.get_lib()
    except Exception:
        return None


def _prep_python(pubs: np.ndarray, sigs: np.ndarray,
                 msgs: Sequence[bytes]):
    """Oracle-backed host prep (fallback when the native lib is absent)."""
    n = len(msgs)
    k_out = np.zeros((n, 32), dtype=np.uint8)
    neg_a = np.zeros((n, 64), dtype=np.uint8)
    ok = np.zeros(n, dtype=bool)
    for i in range(n):
        pub, sig, msg = bytes(pubs[i]), bytes(sigs[i]), msgs[i]
        s = int.from_bytes(sig[32:], "little")
        if s >= _ref.L:
            continue
        a_pt = _ref.pt_decompress(pub, strict=True)
        if a_pt is None or _ref.pt_is_small_order(a_pt):
            continue
        r_pt = _ref.pt_decompress(sig[:32], strict=True)
        if r_pt is None or _ref.pt_is_small_order(r_pt):
            continue
        k = _ref.compute_k(sig[:32], pub, msg)
        k_out[i] = np.frombuffer(k.to_bytes(32, "little"), dtype=np.uint8)
        nx = (_ref.P - a_pt[0]) % _ref.P
        neg_a[i, :32] = np.frombuffer(nx.to_bytes(32, "little"),
                                      dtype=np.uint8)
        neg_a[i, 32:] = np.frombuffer(a_pt[1].to_bytes(32, "little"),
                                      dtype=np.uint8)
        ok[i] = True
    return k_out, neg_a, ok


def host_prepare(pubs: np.ndarray, sigs: np.ndarray, msgs: Sequence[bytes]):
    """Returns (k (n,32) u8, neg_a (n,64) u8, ok (n,) bool)."""
    lib = _native()
    if lib is None:
        return _prep_python(pubs, sigs, msgs)
    offsets = np.zeros(len(msgs) + 1, dtype=np.uint64)
    np.cumsum([len(m) for m in msgs], out=offsets[1:])
    blob = b"".join(msgs)
    k, s_ok = lib.batch_prepare(pubs, sigs, blob, offsets)
    neg_a, pt_ok = lib.batch_host_precheck(pubs, sigs)
    return k, neg_a, s_ok & pt_ok


def host_k(pubs: np.ndarray, sigs: np.ndarray, msgs: Sequence[bytes]):
    """v2 host prep: just k = SHA512(R‖A‖M) mod L, (n,32) u8 — point
    decompression and all canonicality checks run on device
    (ed25519_kernel.verify_kernel_full). SHA-512 stays host-side: 64-bit
    rotates are hostile to the TPU int units (SURVEY.md §7 hard parts)."""
    lib = _native()
    if lib is not None:
        offsets = np.zeros(len(msgs) + 1, dtype=np.uint64)
        np.cumsum([len(m) for m in msgs], out=offsets[1:])
        blob = b"".join(msgs)
        k, _ = lib.batch_prepare(pubs, sigs, blob, offsets)
        return k
    n = len(msgs)
    k = np.zeros((n, 32), dtype=np.uint8)
    for i in range(n):
        ki = _ref.compute_k(bytes(sigs[i, :32]), bytes(pubs[i]), msgs[i])
        k[i] = np.frombuffer(ki.to_bytes(32, "little"), dtype=np.uint8)
    return k


def _pad_u8(arr: np.ndarray, bucket: int) -> np.ndarray:
    """(n,32) u8 -> (bucket,32) u8, zero-padded (pad lanes decode as the
    torsion point y=0 and are rejected on device; results are sliced off)."""
    n = arr.shape[0]
    if n == bucket:
        return np.ascontiguousarray(arr)
    out = np.zeros((bucket, 32), dtype=np.uint8)
    out[:n] = arr
    return out


class TpuBatchVerifier:
    """Batch verifier on the default JAX backend (TPU in production,
    CPU mesh in tests). Thread-compatible with the sync seam: results are
    per-signature bools identical to PubKeyUtils.verify_sig.

    v2 pipeline: uint8 transfer (128 B/sig over the host link), SHA-512 on
    host, everything else — decompression, strict checks, double scalar
    mult, compare — on device."""

    _shared_jit = None   # one compiled program per process, not per instance
    _shared_jit_msg32 = None

    def __init__(self, perf=None, device_sha=None, device_min_batch=None,
                 metrics=None):
        if TpuBatchVerifier._shared_jit is None:
            TpuBatchVerifier._shared_jit = jax.jit(
                ed25519_kernel.verify_kernel_full)
            TpuBatchVerifier._shared_jit_msg32 = jax.jit(
                ed25519_kernel.verify_kernel_msg32)
        self._jit = TpuBatchVerifier._shared_jit
        self._jit_msg32 = TpuBatchVerifier._shared_jit_msg32
        self._min_bucket = MIN_BUCKET
        self._device_sha = _device_sha_default(device_sha)
        self._device_min_batch = _device_min_batch_default(device_min_batch)
        self.perf = perf  # per-app zone registry (None = process default)
        self._init_dispatch_metrics(metrics)

    def set_device_min_batch(self, n: int) -> None:
        """Live re-tune of the host-bypass cutoff (ops/controller.py;
        inherited by the sharded/hybrid verifiers, proxied through the
        backend supervisor). A plain attribute swap read once per
        flush — no torn state possible."""
        self._device_min_batch = max(1, int(n))

    def _init_dispatch_metrics(self, metrics) -> None:
        """Per-dispatch device accounting (telemetry time-series /
        ROADMAP item 1 groundwork): batch size, padding waste (lanes
        burnt on the power-of-two bucket), and dispatch→collect wall
        time — the per-device health signals a per-device breaker will
        consume. None = accounting off (the bench/test constructors)."""
        if metrics is None:
            self._m_batch = self._m_padding = self._m_wall = None
            return
        self._m_batch = metrics.new_histogram(
            "crypto.verify.dispatch.batch")
        self._m_padding = metrics.new_histogram(
            "crypto.verify.dispatch.padding")
        self._m_wall = metrics.new_timer("crypto.verify.dispatch.wall")

    def verify_batch(self, pubs: np.ndarray, sigs: np.ndarray,
                     msgs: Sequence[bytes]) -> np.ndarray:
        return self.verify_batch_async(pubs, sigs, msgs)()

    def verify_batch_async(self, pubs: np.ndarray, sigs: np.ndarray,
                           msgs: Sequence[bytes]):
        """Dispatch a batch without blocking; returns a zero-arg callable
        that yields the (n,) bool results. Callers with several batches in
        flight (catchup prevalidation, the bench harness) overlap host
        SHA-512 + transfer of batch i+1 with device compute of batch i."""
        n = len(msgs)
        if n == 0:
            return lambda: np.zeros(0, dtype=bool)
        pubs = np.asarray(pubs, dtype=np.uint8).reshape(n, 32)
        sigs = np.asarray(sigs, dtype=np.uint8).reshape(n, 64)
        bucket = _bucket_size(n, self._min_bucket)
        if self._device_sha and all(len(m) == 32 for m in msgs):
            # tx-hash hot path: ship M raw, SHA-512 + mod L on device —
            # zero per-signature host work (docs/KERNEL_PROFILE.md §4)
            m = np.frombuffer(b"".join(msgs), dtype=np.uint8).reshape(n, 32)
            out = self._jit_msg32(
                _pad_u8(pubs, bucket),
                _pad_u8(sigs[:, :32], bucket),
                _pad_u8(np.ascontiguousarray(sigs[:, 32:]), bucket),
                _pad_u8(m, bucket))
        else:
            k = host_k(pubs, sigs, msgs)
            out = self._jit(
                _pad_u8(pubs, bucket),
                _pad_u8(sigs[:, :32], bucket),
                _pad_u8(np.ascontiguousarray(sigs[:, 32:]), bucket),
                _pad_u8(k, bucket))
        if self._m_batch is None:
            return lambda: np.asarray(out)[:n]
        # dispatch accounting: occupancy and padding recorded at
        # dispatch, wall time at FIRST collect (the async split —
        # collect blocks on device completion, so first-collect wall
        # is the true dispatch→results latency)
        self._m_batch.update(n)
        self._m_padding.update(bucket - n)
        t0 = _time.perf_counter()
        state = {"done": False}

        def collect():
            res = np.asarray(out)[:n]
            if not state["done"]:
                state["done"] = True
                self._m_wall.update(_time.perf_counter() - t0)
            return res
        return collect

    def verify_tuples(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]) -> List[bool]:
        return self.verify_tuples_async(items)()

    def verify_tuples_async(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]):
        """Non-blocking verify_tuples: dispatches host prep + transfer +
        device compute and returns a zero-arg callable yielding the
        List[bool]. Used to overlap checkpoint N+1's signature batch with
        checkpoint N's sequential apply in catchup. The crypto.batchVerify
        perf zone wraps dispatch and (separately) collection, so the
        accounting survives the async split."""
        if not items:
            return lambda: []
        if chaos.ENABLED:
            # device-verifier fault seam: an injected io_error raises
            # BEFORE any dispatch — callers must fall back to the
            # native per-signature path (semantics are identical).
            # Fired before the small-batch bypass decision so the seam
            # contract is batch-size independent.
            chaos.point("ops.verifier.batch", n=len(items))
        from ..util import tracing
        from ..util.perf import default_registry
        registry = self.perf or default_registry
        targs = {"batch": len(items)} if tracing.ENABLED else None
        if len(items) < self._device_min_batch:
            # small-batch CPU bypass: the fixed device dispatch cost
            # loses to the native verifier below the cutoff, so tiny
            # flushes (the verify service's deadline stragglers) stay
            # on host — same strict accept/reject either way
            from ..crypto.keys import verify_sig_uncached
            with registry.zone("crypto.batchVerify.native", targs=targs):
                res = [verify_sig_uncached(p, s, m) for p, s, m in items]
            return lambda: res
        with registry.zone("crypto.batchVerify", targs=targs):
            pubs = np.frombuffer(b"".join(p for p, _, _ in items),
                                 dtype=np.uint8).reshape(-1, 32)
            sigs = np.frombuffer(b"".join(s for _, s, _ in items),
                                 dtype=np.uint8).reshape(-1, 64)
            handle = self.verify_batch_async(pubs, sigs,
                                             [m for _, _, m in items])

        def collect():
            with registry.zone("crypto.batchVerify", targs=targs):
                return list(handle())
        return collect


def make_sharded_verify(mesh: Mesh, axis: str = "dp",
                        kernel=ed25519_kernel.verify_kernel_full):
    """shard_map'd v2/v3 kernel over a 1-D mesh axis: the batch axis of
    the (B,32) uint8 inputs is sharded, each device runs the identical
    decompress+scalar-mult program on its shard; the only cross-device
    traffic is the (B,) bool result gather. B must divide by mesh size."""
    spec = PSpec(axis, None)
    f = shard_map(kernel, mesh=mesh,
                  in_specs=(spec,) * 4, out_specs=PSpec(axis))
    return jax.jit(f)


class ShardedBatchVerifier(TpuBatchVerifier):
    """Data-parallel verifier over all visible devices of a 1-D mesh."""

    def __init__(self, devices: Optional[list] = None, axis: str = "dp",
                 perf=None, device_sha=None, device_min_batch=None,
                 metrics=None):
        self.perf = perf
        self._device_sha = _device_sha_default(device_sha)
        self._device_min_batch = _device_min_batch_default(device_min_batch)
        self._init_dispatch_metrics(metrics)
        devices = devices if devices is not None else jax.devices()
        self.mesh = Mesh(np.array(devices), (axis,))
        self.ndev = len(devices)
        self._jit = make_sharded_verify(self.mesh, axis)
        self._jit_msg32 = make_sharded_verify(
            self.mesh, axis, ed25519_kernel.verify_kernel_msg32)
        # bucket sizes must stay divisible by the mesh size: start from the
        # smallest multiple of ndev >= MIN_BUCKET (doubling in _bucket_size
        # preserves divisibility)
        self._min_bucket = ((MIN_BUCKET + self.ndev - 1)
                            // self.ndev) * self.ndev
