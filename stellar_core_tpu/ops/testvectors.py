"""Adversarial Ed25519 vector generation for differential testing.

One generator feeds three consumers: the CPU-mesh pytest suite, the
real-chip differential job (scripts/tpu_differential.py), and ad-hoc
cross-checks.  The classes cover everything the strict verifier's
rejection surface distinguishes (reference semantics:
crypto/SecretKey.cpp verify + libsodium-strict rules; oracle:
crypto/ed25519_ref.py):

  - valid signatures over varied message lengths / reused keys
  - bit-flipped signatures, messages, and public keys
  - S = 0, S = L, S = L + s (non-canonical scalar), S = 2^256-1
  - non-canonical point encodings for A and R (y >= p, all-FF)
  - small-order (8-torsion) A and R, including the identity
  - torsion-defect signatures: A' = A + T8 for valid (A, sig) — the
    cofactorless/cofactored disagreement surface that RLC batch
    verification would get wrong (the reason this framework verifies
    strictly per-signature on device; see ed25519_kernel.py)
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

from ..crypto import ed25519_ref as ref
from ..crypto.keys import SecretKey

Tuples = List[Tuple[bytes, bytes, bytes]]


def _small_order_points() -> list:
    """All 8-torsion point encodings, found by clearing the prime-order
    component of arbitrary points ([L]Q)."""
    seen = {}
    i = 0
    while len(seen) < 8 and i < 4000:
        q = ref.pt_decompress(hashlib.sha256(b"torsion%d" % i).digest(),
                              strict=False)
        i += 1
        if q is None:
            continue
        t = ref.pt_mul(ref.L, q)
        if ref.pt_is_small_order(t):
            seen[ref.pt_compress(t)] = t
    return list(seen.keys())


def make_differential_vectors(n_random: int = 10000,
                              seed: int = 424242) -> Tuples:
    """n_random valid/corrupted tuples plus the full adversarial tail.
    Deterministic in (n_random, seed)."""
    items: Tuples = []
    keys = [SecretKey.pseudo_random_for_testing(seed + i)
            for i in range(64)]

    # --- bulk: valid + corrupted mix -----------------------------------
    for i in range(n_random):
        sk = keys[i % len(keys)]
        ln = (0, 1, 31, 32, 33, 64, 100)[i % 7]
        msg = (hashlib.sha256(b"dv%d-%d" % (seed, i)).digest() * 4)[:ln]
        sig = sk.sign(msg)
        pub = sk.public_key().raw
        k = i % 10
        if k == 7:      # corrupt sig R
            sig = bytes([sig[0] ^ 0x40]) + sig[1:]
        elif k == 8:    # corrupt sig S (low bits: stays canonical)
            sig = sig[:32] + bytes([sig[32] ^ 1]) + sig[33:]
        elif k == 9:    # corrupt msg (empty msg: corrupt pub instead)
            if msg:
                msg = bytes([msg[0] ^ 0x80]) + msg[1:]
            else:
                pub = bytes([pub[0] ^ 2]) + pub[1:]
        items.append((pub, sig, msg))

    # --- adversarial tail ----------------------------------------------
    sk = keys[0]
    msg = hashlib.sha256(b"adversarial").digest()
    sig = sk.sign(msg)
    pub = sk.public_key().raw
    R, S = sig[:32], sig[32:]
    s_val = int.from_bytes(S, "little")

    items.append((pub, R + bytes(32), msg))                      # S = 0
    items.append((pub, R + ref.L.to_bytes(32, "little"), msg))   # S = L
    items.append((pub, R + (s_val + ref.L).to_bytes(32, "little"),
                  msg))                                          # S + L
    items.append((pub, R + b"\xff" * 32, msg))                   # S huge

    for enc in ((ref.P + 1).to_bytes(32, "little"),
                (ref.P + 2).to_bytes(32, "little"),
                b"\xff" * 32):                   # non-canonical encodings
        items.append((enc, sig, msg))
        items.append((pub, enc + S, msg))

    for t in _small_order_points():              # 8-torsion A and R
        items.append((t, sig, msg))
        items.append((pub, t + S, msg))

    # torsion-defect: A' = A + T for every torsion T; strict cofactorless
    # semantics must treat each deterministically (mostly False, but the
    # oracle decides — the kernel must MATCH it bit-for-bit)
    A = ref.pt_decompress(pub, strict=True)
    for tenc in _small_order_points():
        T = ref.pt_decompress(tenc, strict=False)
        items.append((ref.pt_compress(ref.pt_add(A, T)), sig, msg))

    # duplicates (cache/dedup paths must not change results)
    items.append((pub, sig, msg))
    items.append((pub, sig, msg))
    return items


def oracle_results(items: Tuples) -> List[bool]:
    return [ref.verify(p, s, m) for p, s, m in items]
