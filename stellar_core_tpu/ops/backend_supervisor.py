"""Device-backend supervisor: circuit breaker + hung-dispatch watchdog
for the verify hot path.

PR 4 made the LIVE signature path depend on the device backend
(ops/verify_service.py coalesces into ops/verifier.py), but its failure
story was per-flush: every flush optimistically dispatched to the
device and paid the full failure latency again before falling back to
native verify — a flapping or dead backend degraded every batch
forever, and a *hung* dispatch (a collect handle that never completes)
blocked the flush path with no recourse. Clipper (NSDI 2017, PAPERS.md)
treats latency-deadline fallback as a first-class serving primitive and
"The Tail at Scale" (Dean & Barroso, CACM 2013) names the pattern:
bound every dependency with a deadline and a health gate so one slow
component cannot poison the whole request path.

This module is that gate. ``BackendSupervisor`` wraps the device batch
verifier behind the same ``verify_tuples_async`` interface and is
shared by EVERY device caller — the coalescing verify service, the
txset prevalidator (``_LazyBatchPrevalidator``), catchup's checkpoint
prevalidation and self_check — because it *is* ``app.batch_verifier``.
Unknown attributes delegate to the wrapped verifier, so callers that
peek at ``_device_min_batch`` or ``mesh`` keep working.

State machine (the classic circuit breaker):

- **CLOSED** — dispatches go to the device. Failures are classified:
  *transient* (OSError/IOError/TimeoutError — the shapes a flaky
  transport or runtime produces, including the chaos ``io_error``)
  count toward ``failure_threshold`` consecutive failures; *fatal*
  (anything else: shape errors, OOM, programming bugs — retrying the
  same dispatch cannot help) trip immediately. Every failed dispatch
  still resolves its batch through the native per-signature fallback,
  so results are always produced and always identical.
- **OPEN** — the device is not touched at all: ``verify_tuples_async``
  returns a native-resolving handle immediately (zero device dispatch
  attempts, zero failure latency — the degraded mode the chaos soak
  drives). A ``VirtualTimer`` re-probe is armed with exponential
  backoff plus deterministic seeded jitter (decorrelated across nodes,
  reproducible within one node — the chaos determinism contract).
- **HALF_OPEN** — the backoff timer fired: a small canary batch of
  known-good signatures probes the device (regular traffic stays on
  the native path until the probe verdict). Probe success → CLOSED
  (consecutive-failure count reset); probe failure → OPEN with the
  next backoff step.

Hung-dispatch watchdog: collection of a device handle runs on a helper
thread bounded by ``dispatch_deadline_ms``. An overdue flush is
resolved through the native fallback, the handle is QUARANTINED (the
helper thread parks on a release event; ``backendstatus`` lists the
quarantined handles), and the breaker records a timeout-class failure.
The chaos fault kind ``hang`` on the ``ops.backend.dispatch`` seam
exercises this deterministically.

Observability: ``crypto.verify_backend.state`` gauge (0=CLOSED 1=OPEN
2=HALF_OPEN), ``crypto.verify_backend.transition.to_*`` counters,
``crypto.verify_backend.dispatch``/``skip`` counters,
``crypto.verify_backend.failure.{transient,fatal,timeout}`` counters
and the ``crypto.verify_backend.probe`` timer — all on the admin
``metrics`` route and the Prometheus exposition. Breaker transitions
emit flight-recorder instants (``backend.breaker``) while a trace is
on, and the ``backendstatus`` admin route reports the live state plus
forced ``trip``/``reset`` actions gated behind ALLOW_CHAOS_INJECTION.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

from ..util import chaos, tracing
from ..util.logging import get_logger

log = get_logger("Herder")

# breaker states (gauge values follow this order)
CLOSED = "CLOSED"
OPEN = "OPEN"
HALF_OPEN = "HALF_OPEN"
_STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

# failure classes (metric suffixes: crypto.verify_backend.failure.<class>)
FAILURE_CLASSES = ("transient", "fatal", "timeout")

DEFAULT_FAILURE_THRESHOLD = 3
DEFAULT_DISPATCH_DEADLINE_MS = 2000.0
DEFAULT_PROBE_BASE_MS = 1000.0
DEFAULT_PROBE_MAX_MS = 30000.0
DEFAULT_CANARY_BATCH = 16
# jitter fraction on each backoff step: delay *= 1 + U[0, JITTER_FRAC)
JITTER_FRAC = 0.25


def classify_error(exc: BaseException) -> str:
    """Transient vs. fatal dispatch-error classification. I/O-shaped
    errors (a flaky transport/runtime, the chaos ``io_error``) are
    worth retrying after backoff; anything else — shape mismatches,
    OOM, programming errors — will fail identically on retry, so it
    trips the breaker immediately."""
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return "transient"
    return "fatal"


class _CollectWorker:
    """Reusable watchdog helper: one long-lived thread running one
    collect job at a time off its own queue, so the healthy hot path
    (hundreds of deadline flushes per second) pays a queue put/get
    instead of a thread spawn per collect. A deadline overrun
    quarantines the worker — its thread is stuck inside the hung
    collect — and the None sentinel queued behind the hung job lets
    the thread exit once the handle finally releases."""

    __slots__ = ("jobs", "thread")

    def __init__(self):
        import queue
        self.jobs = queue.Queue()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="verify-collect")
        self.thread.start()

    def _run(self):
        while True:
            job = self.jobs.get()
            if job is None:
                return
            fn, box, done = job
            try:
                box["r"] = fn()
            except BaseException as e:   # parked hung handles too
                box["e"] = e
            done.set()


class _Quarantined:
    """One hung collect handle: the helper thread that owns it parks on
    `release` so a long-lived process can let it go at shutdown."""

    __slots__ = ("batch", "since", "thread")

    def __init__(self, batch: int, since: float, thread: threading.Thread):
        self.batch = batch
        self.since = since
        self.thread = thread


class BackendSupervisor:
    """Circuit breaker + watchdog around a device batch verifier.

    Drop-in for the wrapped verifier everywhere ``verify_tuples`` /
    ``verify_tuples_async`` are consumed; unknown attributes delegate
    to the wrapped instance.
    """

    # duck-type marker the admin route / self_check key on
    breaker_state = True

    def __init__(self, inner, clock=None, metrics=None, perf=None,
                 failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
                 dispatch_deadline_ms: float = DEFAULT_DISPATCH_DEADLINE_MS,
                 probe_base_ms: float = DEFAULT_PROBE_BASE_MS,
                 probe_max_ms: float = DEFAULT_PROBE_MAX_MS,
                 canary_batch: int = DEFAULT_CANARY_BATCH,
                 jitter_seed: int = 0, chaos_label: str = ""):
        self._inner = inner
        self._clock = clock
        self._lock = threading.RLock()
        self._threshold = max(1, int(failure_threshold))
        self._deadline_s = max(0.0, float(dispatch_deadline_ms)) / 1000.0
        self._probe_base_s = max(0.001, float(probe_base_ms)) / 1000.0
        self._probe_max_s = max(self._probe_base_s,
                                float(probe_max_ms) / 1000.0)
        self._canary_batch = max(1, int(canary_batch))
        self._canary: Optional[List[Tuple[bytes, bytes, bytes]]] = None
        import random
        self._rng = random.Random(jitter_seed)
        self.chaos_label = chaos_label
        self.state = CLOSED
        self.consecutive_failures = 0
        self.probe_attempt = 0
        self._next_probe_at: Optional[float] = None
        self._probe_timer = None
        self._shut_down = False
        # [(clock time, from, to, reason, device dispatches so far)] —
        # the chaos scenario asserts zero dispatches while OPEN from
        # the counter snapshots in here. Bounded like the flight
        # recorder's ring buffer: a flapping device appends forever,
        # and status() serializes the whole list on every admin hit
        from collections import deque as _deque
        self.transitions = _deque(maxlen=64)
        self.transition_count = 0
        self._quarantined: List[_Quarantined] = []
        self._idle_workers: List[_CollectWorker] = []
        self._max_idle_workers = 4
        self._release = threading.Event()   # parks hung collect threads
        if perf is None:
            from ..util.perf import default_registry
            perf = default_registry
        self.perf = perf
        if metrics is None:
            from ..util.metrics import MetricsRegistry
            metrics = MetricsRegistry()
        self._state_gauge = metrics.counter("crypto", "verify_backend",
                                            "state")
        self._transition_counters = {
            s: metrics.counter("crypto", "verify_backend", "transition",
                               "to_" + s.lower())
            for s in (CLOSED, OPEN, HALF_OPEN)}
        self._dispatch_counter = metrics.counter(
            "crypto", "verify_backend", "dispatch")
        self._skip_counter = metrics.counter(
            "crypto", "verify_backend", "skip")
        self._failure_counters = {
            c: metrics.counter("crypto", "verify_backend", "failure", c)
            for c in FAILURE_CLASSES}
        self._probe_timer_metric = metrics.timer(
            "crypto", "verify_backend", "probe")

    # ------------------------------------------------------- delegation --
    def __getattr__(self, name):
        # transparent proxy: callers probing verifier attributes
        # (_device_min_batch, mesh, ndev, …) reach the wrapped instance
        return getattr(self._inner, name)

    # ----------------------------------------------------------- verify --
    def verify_tuples(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]) -> List[bool]:
        return self.verify_tuples_async(items)()

    def verify_tuples_async(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]):
        """The supervised dispatch: device when CLOSED, straight to the
        native path while OPEN / HALF_OPEN (no device attempt, no
        failure latency). Always returns a zero-arg collect callable
        whose results are identical to PubKeyUtils.verify_sig."""
        if not items:
            return lambda: []
        with self._lock:
            if self.state != CLOSED:
                self._skip_counter.inc()
                return self._native_handle(items)
        return self._dispatch(items)

    def _native_handle(self, items):
        def collect():
            from ..crypto.keys import verify_sig_uncached
            return [verify_sig_uncached(p, s, m) for p, s, m in items]
        return collect

    def _dispatch(self, items, probe: bool = False):
        """Dispatch to the device (breaker permitting) and wrap the
        collect handle with the watchdog deadline."""
        with self._lock:
            # re-check under the same lock transitions take: a caller
            # that passed the fast-path check can race a concurrent
            # trip, and a dispatch slipping through while OPEN would
            # both pay the failure latency OPEN exists to eliminate
            # and break the zero-dispatch-while-OPEN counter invariant
            # the chaos verdict audits
            if self.state != CLOSED and not probe:
                self._skip_counter.inc()
                return self._native_handle(items)
            self._dispatch_counter.inc()
        hung = False
        try:
            if chaos.ENABLED:
                # supervisor fault seam: io_error raises (a transient
                # dispatch failure), `hang` substitutes a handle that
                # never completes — only the watchdog deadline resolves
                # the flush (satellite: deterministic watchdog tests)
                out = chaos.point("ops.backend.dispatch", None,
                                  node=self.chaos_label, n=len(items),
                                  probe=probe)
                hung = out is chaos.HANG
            if hung:
                ev = self._release

                def inner_collect():
                    ev.wait()
                    raise TimeoutError("chaos: hung dispatch released")
            else:
                inner_collect = self._inner.verify_tuples_async(items)
        except Exception as e:
            self._record_failure(classify_error(e), e, probe=probe)
            if probe:
                raise
            return self._native_handle(items)
        return self._watched_collect(inner_collect, items, probe)

    def _watched_collect(self, inner_collect, items, probe: bool):
        """Bound collection by the dispatch deadline on a helper
        thread; on expiry quarantine the handle, record a timeout-class
        failure, and resolve the batch natively."""
        def collect():
            if self._deadline_s <= 0:
                box = {}
                try:
                    box["r"] = inner_collect()
                except Exception as e:
                    self._record_failure(classify_error(e), e, probe=probe)
                    if probe:
                        raise
                    return self._native_handle(items)()
                self._record_success()
                return list(box["r"])
            with self._lock:
                w = self._idle_workers.pop() if self._idle_workers \
                    else None
            if w is None:
                w = _CollectWorker()
            box = {}
            done = threading.Event()
            w.jobs.put((inner_collect, box, done))
            if not done.wait(self._deadline_s):
                # the worker thread is stuck inside the hung collect;
                # the sentinel behind it lets the thread exit once the
                # handle finally releases
                w.jobs.put(None)
                with self._lock:
                    self._quarantined.append(_Quarantined(
                        len(items), time.monotonic(), w.thread))
                exc = TimeoutError(
                    f"device collect overran "
                    f"{self._deadline_s * 1000:.0f}ms deadline")
                self._record_failure("timeout", exc, probe=probe)
                if probe:
                    raise exc
                return self._native_handle(items)()
            with self._lock:
                if self._shut_down or \
                        len(self._idle_workers) >= self._max_idle_workers:
                    w.jobs.put(None)
                else:
                    self._idle_workers.append(w)
            if "e" in box:
                e = box["e"]
                self._record_failure(classify_error(e), e, probe=probe)
                if probe:
                    raise e
                return self._native_handle(items)()
            self._record_success()
            return list(box["r"])
        return collect

    # ------------------------------------------------------ state moves --
    def _now(self) -> float:
        return self._clock.now() if self._clock is not None \
            else time.monotonic()

    def _transition(self, to: str, reason: str) -> None:
        """Lock held by callers."""
        frm = self.state
        if frm == to:
            return
        self.state = to
        self._state_gauge.set_count(_STATE_GAUGE[to])
        self._transition_counters[to].inc()
        self.transition_count += 1
        self.transitions.append(
            (self._now(), frm, to, reason, self._dispatch_counter.count))
        lvl = log.warning if to == OPEN else log.info
        lvl("verify backend breaker %s -> %s (%s)", frm, to, reason)
        if tracing.ENABLED:
            rec = getattr(self.perf, "tracer", None)
            if rec is not None and rec.active:
                rec.instant("backend.breaker", {
                    "from": frm, "to": to, "reason": reason})

    def _record_failure(self, cls: str, exc: BaseException,
                        probe: bool = False) -> None:
        with self._lock:
            self._failure_counters[cls].inc()
            self.consecutive_failures += 1
            lvl = log.warning if self.consecutive_failures <= \
                self._threshold else log.debug
            lvl("verify backend %s failure (%d consecutive): %r",
                cls, self.consecutive_failures, exc)
            if self.state == HALF_OPEN:
                if probe:
                    # failed probe: back to OPEN, next backoff step
                    self.probe_attempt += 1
                    self._transition(OPEN, f"probe_{cls}")
                    self._arm_probe_locked()
                # a late-collected pre-trip dispatch failing while the
                # canary is out is NOT a probe verdict: count it but
                # let the real probe decide the state
            elif self.state == CLOSED and (
                    cls == "fatal"
                    or self.consecutive_failures >= self._threshold):
                self._trip_locked("fatal_error" if cls == "fatal"
                                  else "failure_threshold")

    def _record_success(self, probe: bool = False) -> None:
        """Mirror of _record_failure's probe asymmetry: only the probe
        verdict — issued by probe_now AFTER checking the canary
        results' contents — may close a HALF_OPEN breaker. A collect
        that merely completes (the watchdog layer's notion of success,
        which a device answering wrong answers also satisfies) or a
        late-collected pre-trip dispatch succeeding while the canary
        is out resets the failure count but decides nothing."""
        with self._lock:
            self.consecutive_failures = 0
            if self.state == HALF_OPEN and probe:
                self._close_locked("probe_ok")

    def _trip_locked(self, reason: str) -> None:
        self.probe_attempt = 0
        self._transition(OPEN, reason)
        self._arm_probe_locked()

    def _close_locked(self, reason: str) -> None:
        self.consecutive_failures = 0
        self.probe_attempt = 0
        self._next_probe_at = None
        if self._probe_timer is not None:
            self._probe_timer.cancel()
        self._transition(CLOSED, reason)

    def _backoff_s(self) -> float:
        base = min(self._probe_base_s * (2 ** self.probe_attempt),
                   self._probe_max_s)
        return base * (1.0 + JITTER_FRAC * self._rng.random())

    def _arm_probe_locked(self) -> None:
        if self._clock is None or self._shut_down:
            # no clock (bare harnesses): probes only via probe_now()
            self._next_probe_at = None
            return
        from ..util.timer import VirtualTimer
        if self._probe_timer is None:
            self._probe_timer = VirtualTimer(self._clock)
        delay = self._backoff_s()
        self._next_probe_at = self._clock.now() + delay
        self._probe_timer.expires_from_now(delay)
        self._probe_timer.async_wait(self._on_probe_timer)

    def _on_probe_timer(self) -> None:
        if self._shut_down:
            return
        self.probe_now()

    # ------------------------------------------------------------ probe --
    def _canary_items(self) -> List[Tuple[bytes, bytes, bytes]]:
        """A batch of known-good signatures over 32-byte messages (the
        tx-hash hot-path shape). Built once; a probe succeeds iff every
        one verifies within the dispatch deadline."""
        if self._canary is None:
            import hashlib

            from ..crypto.keys import SecretKey
            sk = SecretKey.from_seed(
                b"backend-supervisor-canary".ljust(32, b"\x5c")[:32])
            pub = sk.public_key().raw
            items = []
            for i in range(self._canary_batch):
                msg = hashlib.sha256(b"canary-%d" % i).digest()
                items.append((pub, sk.sign(msg), msg))
            self._canary = items
        return self._canary

    def probe_now(self) -> bool:
        """Run one HALF_OPEN canary probe (timer callback; also the
        manual hook for clock-less harnesses). Returns probe verdict."""
        with self._lock:
            if self.state == CLOSED or self._shut_down:
                return True
            self._transition(HALF_OPEN, "probe_timer")
        items = self._canary_items()
        t0 = time.perf_counter()
        try:
            collect = self._dispatch(items, probe=True)
            results = collect()
            ok = bool(results) and all(bool(r) for r in results)
        except Exception:
            # _dispatch/_watched_collect already recorded the failure
            # and re-armed the probe timer (probe=True re-raises)
            self._probe_timer_metric.update(time.perf_counter() - t0)
            return False
        self._probe_timer_metric.update(time.perf_counter() - t0)
        if ok:
            self._record_success(probe=True)
        else:
            # the device answered but rejected known-good signatures:
            # wrong results are worse than no results — treat as fatal
            self._record_failure(
                "fatal", RuntimeError("canary batch rejected"),
                probe=True)
        return ok

    def refresh_gauge(self) -> None:
        """Re-assert the state gauge after a metrics clear: the gauge
        is a level, and `clearmetrics` zeroing it while the breaker is
        OPEN would read as CLOSED until the next transition."""
        with self._lock:
            self._state_gauge.set_count(_STATE_GAUGE[self.state])

    # ---------------------------------------------------- forced control --
    def force_trip(self) -> None:
        """Admin `backendstatus?action=trip` (ALLOW_CHAOS_INJECTION)."""
        with self._lock:
            if self.state == CLOSED:
                self._trip_locked("forced_trip")

    def force_reset(self) -> None:
        """Admin `backendstatus?action=reset`: straight to CLOSED."""
        with self._lock:
            self._close_locked("forced_reset")

    # -------------------------------------------------------- lifecycle --
    def shutdown(self) -> None:
        """Cancel the probe timer and release parked hung-collect
        threads; a dead app must not probe the device."""
        with self._lock:
            self._shut_down = True
            if self._probe_timer is not None:
                self._probe_timer.cancel()
                self._probe_timer = None
            self._next_probe_at = None
            workers, self._idle_workers = self._idle_workers, []
        for w in workers:
            w.jobs.put(None)
        self._release.set()

    # ------------------------------------------------------------ report --
    def status(self) -> dict:
        """Live state document for the `backendstatus` admin route and
        self_check."""
        with self._lock:
            now = self._now()
            mono = time.monotonic()
            self._quarantined = [q for q in self._quarantined
                                 if q.thread.is_alive()]
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "failure_threshold": self._threshold,
                "dispatches": self._dispatch_counter.count,
                "skips": self._skip_counter.count,
                "failures": {c: m.count
                             for c, m in self._failure_counters.items()},
                "probe_attempt": self.probe_attempt,
                "next_probe_in_s": (
                    round(max(0.0, self._next_probe_at - now), 3)
                    if self._next_probe_at is not None else None),
                "dispatch_deadline_ms": self._deadline_s * 1000.0,
                "transition_count": self.transition_count,
                "transitions": [
                    {"t": round(t, 3), "from": frm, "to": to,
                     "reason": reason, "dispatches": d}
                    for t, frm, to, reason, d in self.transitions],
                "quarantined": [
                    {"batch": q.batch,
                     "age_s": round(mono - q.since, 3)}
                    for q in self._quarantined],
            }
