"""Device-backend supervisor: PER-DEVICE circuit breakers + hung-
dispatch watchdog for the verify hot path.

PR 4 made the LIVE signature path depend on the device backend
(ops/verify_service.py coalesces into ops/verifier.py), but its failure
story was per-flush: every flush optimistically dispatched to the
device and paid the full failure latency again before falling back to
native verify — a flapping or dead backend degraded every batch
forever, and a *hung* dispatch (a collect handle that never completes)
blocked the flush path with no recourse. Clipper (NSDI 2017, PAPERS.md)
treats latency-deadline fallback as a first-class serving primitive and
"The Tail at Scale" (Dean & Barroso, CACM 2013) names the pattern:
bound every dependency with a deadline and a health gate so one slow
component cannot poison the whole request path.

This module is that gate. ``BackendSupervisor`` wraps the device batch
verifier behind the same ``verify_tuples_async`` interface and is
shared by EVERY device caller — the coalescing verify service, the
txset prevalidator (``_LazyBatchPrevalidator``), catchup's checkpoint
prevalidation and self_check — because it *is* ``app.batch_verifier``.
Unknown attributes delegate to the wrapped verifier, so callers that
peek at ``_device_min_batch`` or ``mesh`` keep working.

Health is per-device (PR 13). PR 5's single whole-backend breaker
threw away the other N−1 healthy chips the moment one got sick —
exactly the all-or-nothing failure mode Tail-at-Scale argues against.
Every device in the wrapped verifier's mesh now carries its own
breaker running the classic state machine:

- **CLOSED** — the device participates in mesh dispatches. Failures
  are classified: *transient* (OSError/IOError/TimeoutError — the
  shapes a flaky transport or runtime produces, including the chaos
  ``io_error``) count toward ``failure_threshold`` consecutive
  failures; *fatal* (anything else: shape errors, OOM, programming
  bugs — retrying the same dispatch cannot help) trip immediately.
  A failure attributable to ONE device (a device-matched chaos fault,
  a hang pinned to a chip) counts against that device only; an
  unattributable whole-dispatch failure implicates every participant
  — the per-device canary probes sort out who is actually sick.
- **OPEN** — the device is excluded from the active mesh: the verify
  batch shards over the survivors (8→7, its bucket share
  redistributed — non-pow2 surviving meshes included) and the sick
  chip receives ZERO dispatches. A per-device ``VirtualTimer``
  re-probe is armed with exponential backoff plus deterministic
  seeded jitter (decorrelated across devices AND nodes, reproducible
  within one node — the chaos determinism contract).
- **HALF_OPEN** — the backoff timer fired: a small canary batch of
  known-good signatures probes THAT device alone (pinned dispatch,
  off the survivors' mesh; regular traffic keeps riding the active
  mesh). Probe success → CLOSED, the mesh regrows 7→8; probe failure
  → OPEN with the next backoff step.

Every failed flush still resolves through the native per-signature
fallback, so results are always produced and always identical. The
FULL native fallback path engages only when the mesh is EMPTY (every
device OPEN/probing — the old whole-backend OPEN, and the only state
the aggregate gauge reports as OPEN).

Hung-dispatch watchdog: collection of a device handle runs on a helper
thread bounded by ``dispatch_deadline_ms``. An overdue flush is
resolved through the native fallback, the handle is QUARANTINED with
the device it was pinned to when known (the helper thread parks on a
release event; ``backendstatus`` lists the quarantined handles), and
the breaker records a timeout-class failure. The chaos fault kinds
``hang``/``io_error`` exercise this deterministically: the legacy
``ops.backend.dispatch`` seam fires once per flush (whole-dispatch
faults, hit ordinals unchanged from PR 5), and the per-device
``ops.backend.dispatch.device`` seam fires once per participating
device with ``device=<index>`` in the context, so a fault spec with a
device-index match hits exactly one shard (docs/CHAOS.md).

Observability: the aggregate ``crypto.verify_backend.*`` surface is
unchanged (state gauge 0=CLOSED 1=OPEN 2=HALF_OPEN over the AGGREGATE
state — CLOSED while at least one device serves, so partial
degradation never reads as a full outage — transition counters,
dispatch/skip counters, failure classes, probe timer), plus per-device
``crypto.verify_backend.device<N>.{dispatch,skip}`` counters. Breaker
transitions append to a bounded log with PER-DEVICE dispatch-counter
snapshots — the zero-dispatch-while-OPEN proof the chaos verdicts and
the MESH artifact audit — and emit flight-recorder instants
(``backend.breaker``) on aggregate changes. The ``backendstatus``
admin route reports per-device rows and accepts forced
``trip``/``reset`` actions, whole-mesh or ``device=N``-targeted,
gated behind ALLOW_CHAOS_INJECTION.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

from .shard_math import shard_shares
from ..util import chaos, tracing
from ..util.logging import get_logger

log = get_logger("Herder")

# breaker states (gauge values follow this order)
CLOSED = "CLOSED"
OPEN = "OPEN"
HALF_OPEN = "HALF_OPEN"
_STATE_GAUGE = {CLOSED: 0, OPEN: 1, HALF_OPEN: 2}

# failure classes (metric suffixes: crypto.verify_backend.failure.<class>)
FAILURE_CLASSES = ("transient", "fatal", "timeout")

DEFAULT_FAILURE_THRESHOLD = 3
DEFAULT_DISPATCH_DEADLINE_MS = 2000.0
DEFAULT_PROBE_BASE_MS = 1000.0
DEFAULT_PROBE_MAX_MS = 30000.0
DEFAULT_CANARY_BATCH = 16
# jitter fraction on each backoff step: delay *= 1 + U[0, JITTER_FRAC)
JITTER_FRAC = 0.25


def classify_error(exc: BaseException) -> str:
    """Transient vs. fatal dispatch-error classification. I/O-shaped
    errors (a flaky transport/runtime, the chaos ``io_error``) are
    worth retrying after backoff; anything else — shape mismatches,
    OOM, programming errors — will fail identically on retry, so it
    trips the breaker immediately."""
    if isinstance(exc, (OSError, TimeoutError, ConnectionError)):
        return "transient"
    return "fatal"


class _CollectWorker:
    """Reusable watchdog helper: one long-lived thread running one
    collect job at a time off its own queue, so the healthy hot path
    (hundreds of deadline flushes per second) pays a queue put/get
    instead of a thread spawn per collect. A deadline overrun
    quarantines the worker — its thread is stuck inside the hung
    collect — and the None sentinel queued behind the hung job lets
    the thread exit once the handle finally releases."""

    __slots__ = ("jobs", "thread")

    def __init__(self):
        import queue
        self.jobs = queue.Queue()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="verify-collect")
        self.thread.start()

    def _run(self):  # thread-domain: verify-collect
        from ..util import threads
        if threads.CHECK:
            threads.bind("verify-collect")
        while True:
            job = self.jobs.get()
            if job is None:
                return
            fn, box, done = job
            try:
                box["r"] = fn()
            except BaseException as e:   # parked hung handles too
                box["e"] = e
            done.set()


class _Quarantined:
    """One hung collect handle: the helper thread that owns it parks on
    `release` so a long-lived process can let it go at shutdown.
    `device` is the chip the hang was pinned to (None when the whole
    collective launch hung without attribution)."""

    __slots__ = ("batch", "since", "thread", "device")

    def __init__(self, batch: int, since: float, thread: threading.Thread,
                 device: Optional[int] = None):
        self.batch = batch
        self.since = since
        self.thread = thread
        self.device = device


class _DeviceBreaker:
    """Per-device breaker state: one classic CLOSED→OPEN→HALF_OPEN
    machine, its own backoff RNG stream and probe timer, and its own
    dispatch/skip counters (the zero-dispatch-while-OPEN evidence)."""

    __slots__ = ("index", "state", "consecutive_failures", "probe_attempt",
                 "next_probe_at", "timer", "rng", "dispatches", "skips",
                 "last_probe_at")

    def __init__(self, index: int, rng, dispatches, skips):
        self.index = index
        self.state = CLOSED
        self.consecutive_failures = 0
        self.probe_attempt = 0
        self.next_probe_at: Optional[float] = None
        self.timer = None
        self.rng = rng
        self.dispatches = dispatches
        self.skips = skips
        self.last_probe_at: Optional[float] = None


class BackendSupervisor:
    """Per-device circuit breakers + watchdog around a device batch
    verifier.

    Drop-in for the wrapped verifier everywhere ``verify_tuples`` /
    ``verify_tuples_async`` are consumed; unknown attributes delegate
    to the wrapped instance. A wrapped verifier without a mesh
    (``TpuBatchVerifier``, test fakes) is supervised as a one-device
    mesh, which reproduces the PR 5 whole-backend semantics exactly.
    """

    # duck-type marker the admin route / self_check key on
    breaker_state = True

    def __init__(self, inner, clock=None, metrics=None, perf=None,
                 failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
                 dispatch_deadline_ms: float = DEFAULT_DISPATCH_DEADLINE_MS,
                 probe_base_ms: float = DEFAULT_PROBE_BASE_MS,
                 probe_max_ms: float = DEFAULT_PROBE_MAX_MS,
                 canary_batch: int = DEFAULT_CANARY_BATCH,
                 jitter_seed: int = 0, chaos_label: str = ""):
        self._inner = inner
        self._clock = clock
        self._lock = threading.RLock()
        self._threshold = max(1, int(failure_threshold))
        self._deadline_s = max(0.0, float(dispatch_deadline_ms)) / 1000.0
        self._probe_base_s = max(0.001, float(probe_base_ms)) / 1000.0
        self._probe_max_s = max(self._probe_base_s,
                                float(probe_max_ms) / 1000.0)
        self._canary_batch = max(1, int(canary_batch))
        self._canary: Optional[List[Tuple[bytes, bytes, bytes]]] = None
        self.chaos_label = chaos_label
        self._shut_down = False
        # [(clock time, from, to, reason, total dispatches so far,
        #   device index, THAT device's dispatches so far)] — the chaos
        # scenario and the MESH artifact assert zero dispatches while
        # OPEN from the per-device counter snapshots in here. Bounded
        # like the flight recorder's ring buffer: a flapping device
        # appends forever, and status() serializes the whole list on
        # every admin hit
        from collections import deque as _deque
        self.transitions = _deque(maxlen=64)
        self.transition_count = 0
        self._quarantined: List[_Quarantined] = []
        self._idle_workers: List[_CollectWorker] = []
        self._max_idle_workers = 4
        self._release = threading.Event()   # parks hung collect threads
        if perf is None:
            from ..util.perf import default_registry
            perf = default_registry
        self.perf = perf
        if metrics is None:
            from ..util.metrics import MetricsRegistry
            metrics = MetricsRegistry()
        self._state_gauge = metrics.counter("crypto", "verify_backend",
                                            "state")
        self._transition_counters = {
            s: metrics.counter("crypto", "verify_backend", "transition",
                               "to_" + s.lower())
            for s in (CLOSED, OPEN, HALF_OPEN)}
        self._dispatch_counter = metrics.counter(
            "crypto", "verify_backend", "dispatch")
        self._skip_counter = metrics.counter(
            "crypto", "verify_backend", "skip")
        self._failure_counters = {
            c: metrics.counter("crypto", "verify_backend", "failure", c)
            for c in FAILURE_CLASSES}
        self._probe_timer_metric = metrics.timer(
            "crypto", "verify_backend", "probe")
        # the per-device breaker array: decorrelated seeded jitter
        # streams per device (and per node via jitter_seed), per-device
        # dispatch/skip counters on the shared registry
        import random
        self._ndev = max(1, int(getattr(inner, "ndev", 1) or 1))
        self._breakers = [
            _DeviceBreaker(
                i, random.Random(jitter_seed * 1000003 + i),
                metrics.counter("crypto", "verify_backend",
                                "device%d" % i, "dispatch"),
                metrics.counter("crypto", "verify_backend",
                                "device%d" % i, "skip"))
            for i in range(self._ndev)]
        self._agg_state = CLOSED

    # ------------------------------------------------------- delegation --
    def __getattr__(self, name):
        # transparent proxy: callers probing verifier attributes
        # (_device_min_batch, mesh, ndev, …) reach the wrapped instance
        return getattr(self._inner, name)

    # ------------------------------------------------------- aggregates --
    @property
    def state(self) -> str:
        """Aggregate breaker state: CLOSED while at least one device
        serves (the mesh may be degraded — see ``mesh_status``),
        HALF_OPEN when no device serves but a probe is out, OPEN when
        the whole mesh is unavailable. For a one-device mesh this IS
        the device state, i.e. the PR 5 semantics."""
        return self._agg_state

    @property
    def consecutive_failures(self) -> int:
        return max(b.consecutive_failures for b in self._breakers)

    @property
    def probe_attempt(self) -> int:
        return max(b.probe_attempt for b in self._breakers)

    def _active_locked(self) -> Tuple[int, ...]:
        return tuple(b.index for b in self._breakers
                     if b.state == CLOSED)

    def mesh_status(self) -> dict:
        """Surviving-mesh summary for telemetry samples and the
        adaptive controller's capacity scaling."""
        with self._lock:
            active = self._active_locked()
            return {"devices": self._ndev, "active": len(active),
                    "active_indices": list(active)}

    # ----------------------------------------------------------- verify --
    def verify_tuples(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]) -> List[bool]:
        return self.verify_tuples_async(items)()

    def verify_tuples_async(
            self, items: Sequence[Tuple[bytes, bytes, bytes]]):
        """The supervised dispatch: the active-device mesh when at
        least one device is CLOSED, straight to the native path when
        the mesh is empty (no device attempt, no failure latency).
        Always returns a zero-arg collect callable whose results are
        identical to PubKeyUtils.verify_sig."""
        if not items:
            return lambda: []
        with self._lock:
            if not self._active_locked():
                self._record_skip_locked()
                return self._native_handle(items)
        return self._dispatch(items)

    def _native_handle(self, items):
        def collect():
            from ..crypto.keys import verify_sig_uncached
            return [verify_sig_uncached(p, s, m) for p, s, m in items]
        return collect

    def _record_skip_locked(self) -> None:
        self._skip_counter.inc()
        for b in self._breakers:
            b.skips.inc()

    def _dispatch(self, items, probe_device: Optional[int] = None):
        """Dispatch to the active mesh (breakers permitting) and wrap
        the collect handle with the watchdog deadline. `probe_device`
        pins the dispatch to one device — the canary-probe path."""
        probe = probe_device is not None
        with self._lock:
            if probe:
                participants: Tuple[int, ...] = (probe_device,)
            else:
                # re-check under the same lock transitions take: a
                # caller that passed the fast-path check can race a
                # concurrent trip, and a dispatch slipping through to a
                # tripped device would both pay the failure latency
                # OPEN exists to eliminate and break the
                # zero-dispatch-while-OPEN counter invariant the chaos
                # verdicts audit
                participants = self._active_locked()
                if not participants:
                    self._record_skip_locked()
                    return self._native_handle(items)
            self._dispatch_counter.inc()
            for i in participants:
                self._breakers[i].dispatches.inc()
            if not probe:
                # a device outside the mesh sees this flush only as a
                # skip: its bucket share went to the survivors
                for b in self._breakers:
                    if b.state != CLOSED:
                        b.skips.inc()
        hung = False
        hung_device: Optional[int] = None
        try:
            if chaos.ENABLED:
                # whole-dispatch fault seam (hit ordinals unchanged
                # from PR 5): io_error raises — a transient failure
                # implicating every participant — and `hang`
                # substitutes a handle that never completes, so only
                # the watchdog deadline resolves the flush
                out = chaos.point("ops.backend.dispatch", None,
                                  node=self.chaos_label, n=len(items),
                                  probe=probe)
                hung = out is chaos.HANG
                # per-device fault seam: one firing per participating
                # device, so a spec with match={"device": N} hits
                # exactly that shard (satellite: chaos seam targeting).
                # shard_shares is the SAME split the sharded verifier
                # performs, so n= describes that shard's actual rows
                shares = shard_shares(len(items), len(participants))
                for s, i in enumerate(participants):
                    try:
                        out = chaos.point(
                            "ops.backend.dispatch.device", None,
                            node=self.chaos_label, device=i,
                            n=shares[s], probe=probe)
                    except Exception as e:
                        # attributable: exactly this device is sick.
                        # A probe re-raises UNRECORDED — the outer
                        # handler records it against the same single
                        # device (one record per injected fault)
                        if probe:
                            raise
                        self._record_failure(classify_error(e), e,
                                             participants=(i,),
                                             probe=probe)
                        return self._native_handle(items)
                    if out is chaos.HANG:
                        hung, hung_device = True, i
            if hung:
                ev = self._release

                def inner_collect():
                    ev.wait()
                    raise TimeoutError("chaos: hung dispatch released")
            elif probe and hasattr(self._inner, "verify_tuples_async_on"):
                inner_collect = self._inner.verify_tuples_async_on(
                    probe_device, items)
            else:
                inner_collect = self._inner.verify_tuples_async(items)
        except Exception as e:
            self._record_failure(classify_error(e), e,
                                 participants=participants, probe=probe)
            if probe:
                raise
            return self._native_handle(items)
        return self._watched_collect(inner_collect, items, participants,
                                     probe, hung_device)

    def _watched_collect(self, inner_collect, items, participants,
                         probe: bool, hung_device: Optional[int]):
        """Bound collection by the dispatch deadline on a helper
        thread; on expiry quarantine the handle, record a timeout-class
        failure (pinned to the hung device when known, the whole
        participant set otherwise), and resolve the batch natively."""
        blame = (hung_device,) if hung_device is not None else participants

        def collect():
            if self._deadline_s <= 0:
                box = {}
                try:
                    box["r"] = inner_collect()
                except Exception as e:
                    self._record_failure(classify_error(e), e,
                                         participants=blame, probe=probe)
                    if probe:
                        raise
                    return self._native_handle(items)()
                self._record_success(participants=participants)
                return list(box["r"])
            with self._lock:
                w = self._idle_workers.pop() if self._idle_workers \
                    else None
            if w is None:
                w = _CollectWorker()
            box = {}
            done = threading.Event()
            w.jobs.put((inner_collect, box, done))
            if not done.wait(self._deadline_s):
                # the worker thread is stuck inside the hung collect;
                # the sentinel behind it lets the thread exit once the
                # handle finally releases
                w.jobs.put(None)
                with self._lock:
                    self._quarantined.append(_Quarantined(
                        len(items), time.monotonic(), w.thread,
                        hung_device))
                exc = TimeoutError(
                    f"device collect overran "
                    f"{self._deadline_s * 1000:.0f}ms deadline")
                self._record_failure("timeout", exc,
                                     participants=blame, probe=probe)
                if probe:
                    raise exc
                return self._native_handle(items)()
            with self._lock:
                if self._shut_down or \
                        len(self._idle_workers) >= self._max_idle_workers:
                    w.jobs.put(None)
                else:
                    self._idle_workers.append(w)
            if "e" in box:
                e = box["e"]
                self._record_failure(classify_error(e), e,
                                     participants=blame, probe=probe)
                if probe:
                    raise e
                return self._native_handle(items)()
            self._record_success(participants=participants)
            return list(box["r"])
        return collect

    # ------------------------------------------------------ state moves --
    def _now(self) -> float:
        return self._clock.now() if self._clock is not None \
            else time.monotonic()

    def _transition_device_locked(self, i: int, to: str,
                                  reason: str) -> None:
        b = self._breakers[i]
        frm = b.state
        if frm == to:
            return
        b.state = to
        self.transition_count += 1
        self.transitions.append(
            (self._now(), frm, to, reason,
             self._dispatch_counter.count, i, b.dispatches.count))
        self._sync_inner_active_locked(reason)
        self._update_aggregate_locked(reason)

    def _sync_inner_active_locked(self, reason: str) -> None:
        """Push the surviving set into the wrapped verifier's mesh —
        the shrink/regrow. A mesh-less inner (one device) has nothing
        to shrink; an EMPTY set is not pushed (dispatches are skipped
        at this layer, native fallback serves)."""
        active = self._active_locked()
        if not active or not hasattr(self._inner, "set_active_devices"):
            return
        if tuple(getattr(self._inner, "active_indices", tuple)()) \
                == active:
            return
        self._inner.set_active_devices(active)
        log.warning("verify mesh now %d/%d devices %s (%s)",
                    len(active), self._ndev, list(active), reason)

    def _update_aggregate_locked(self, reason: str) -> None:
        states = [b.state for b in self._breakers]
        if any(s == CLOSED for s in states):
            agg = CLOSED
        elif any(s == HALF_OPEN for s in states):
            agg = HALF_OPEN
        else:
            agg = OPEN
        frm = self._agg_state
        if agg == frm:
            return
        self._agg_state = agg
        self._state_gauge.set_count(_STATE_GAUGE[agg])
        self._transition_counters[agg].inc()
        lvl = log.warning if agg == OPEN else log.info
        lvl("verify backend breaker %s -> %s (%s)", frm, agg, reason)
        if tracing.ENABLED:
            rec = getattr(self.perf, "tracer", None)
            if rec is not None and rec.active:
                rec.instant("backend.breaker", {
                    "from": frm, "to": agg, "reason": reason})

    def _record_failure(self, cls: str, exc: BaseException,
                        participants: Sequence[int],
                        probe: bool = False) -> None:
        with self._lock:
            self._failure_counters[cls].inc()
            worst = 0
            for i in participants:
                b = self._breakers[i]
                b.consecutive_failures += 1
                worst = max(worst, b.consecutive_failures)
                if b.state == HALF_OPEN:
                    if probe:
                        # failed probe: back to OPEN, next backoff step
                        b.probe_attempt += 1
                        self._transition_device_locked(
                            i, OPEN, f"probe_{cls}")
                        self._arm_probe_locked(i)
                    # a late-collected pre-trip dispatch failing while
                    # the canary is out is NOT a probe verdict: count
                    # it but let the real probe decide the state
                elif b.state == CLOSED and (
                        cls == "fatal"
                        or b.consecutive_failures >= self._threshold):
                    self._trip_device_locked(
                        i, "fatal_error" if cls == "fatal"
                        else "failure_threshold")
            lvl = log.warning if worst <= self._threshold else log.debug
            lvl("verify backend %s failure on device(s) %s "
                "(%d consecutive): %r", cls, list(participants),
                worst, exc)

    def _record_success(self, participants: Sequence[int],
                        probe: bool = False) -> None:
        """Mirror of _record_failure's probe asymmetry: only the probe
        verdict — issued by the probe path AFTER checking the canary
        results' contents — may close a HALF_OPEN device. A collect
        that merely completes (the watchdog layer's notion of success,
        which a device answering wrong answers also satisfies) or a
        late-collected pre-trip dispatch succeeding while the canary
        is out resets the failure count but decides nothing."""
        with self._lock:
            for i in participants:
                b = self._breakers[i]
                b.consecutive_failures = 0
                if b.state == HALF_OPEN and probe:
                    self._close_device_locked(i, "probe_ok")

    def _trip_device_locked(self, i: int, reason: str) -> None:
        b = self._breakers[i]
        b.probe_attempt = 0
        self._transition_device_locked(i, OPEN, reason)
        self._arm_probe_locked(i)

    def _close_device_locked(self, i: int, reason: str) -> None:
        b = self._breakers[i]
        b.consecutive_failures = 0
        b.probe_attempt = 0
        b.next_probe_at = None
        if b.timer is not None:
            b.timer.cancel()
        self._transition_device_locked(i, CLOSED, reason)

    def _backoff_s(self, b: _DeviceBreaker) -> float:
        base = min(self._probe_base_s * (2 ** b.probe_attempt),
                   self._probe_max_s)
        return base * (1.0 + JITTER_FRAC * b.rng.random())

    def _arm_probe_locked(self, i: int) -> None:
        b = self._breakers[i]
        if self._clock is None or self._shut_down:
            # no clock (bare harnesses): probes only via probe_now()
            b.next_probe_at = None
            return
        from ..util.timer import VirtualTimer
        if b.timer is None:
            b.timer = VirtualTimer(self._clock)
        delay = self._backoff_s(b)
        b.next_probe_at = self._clock.now() + delay
        b.timer.expires_from_now(delay)
        b.timer.async_wait(lambda: self._on_probe_timer(i))

    def _on_probe_timer(self, i: int) -> None:
        if self._shut_down:
            return
        self._probe_device(i)

    # ------------------------------------------------------------ probe --
    def _canary_items(self) -> List[Tuple[bytes, bytes, bytes]]:
        """A batch of known-good signatures over 32-byte messages (the
        tx-hash hot-path shape). Built once; a probe succeeds iff every
        one verifies within the dispatch deadline."""
        if self._canary is None:
            import hashlib

            from ..crypto.keys import SecretKey
            sk = SecretKey.from_seed(
                b"backend-supervisor-canary".ljust(32, b"\x5c")[:32])
            pub = sk.public_key().raw
            items = []
            for i in range(self._canary_batch):
                msg = hashlib.sha256(b"canary-%d" % i).digest()
                items.append((pub, sk.sign(msg), msg))
            self._canary = items
        return self._canary

    def probe_now(self, device: Optional[int] = None) -> bool:
        """Run canary probes now (the manual hook for clock-less
        harnesses and the admin route): every non-CLOSED device, or
        just `device`. Returns the conjunction of probe verdicts (True
        when nothing needed probing)."""
        with self._lock:
            if self._shut_down:
                return True
            if device is not None:
                targets = [device] if \
                    self._breakers[device].state != CLOSED else []
            else:
                targets = [b.index for b in self._breakers
                           if b.state != CLOSED]
        ok = True
        for i in targets:
            ok = self._probe_device(i) and ok
        return ok

    def _probe_device(self, i: int) -> bool:
        """One HALF_OPEN canary probe pinned to device `i` (timer
        callback + probe_now). Returns the probe verdict."""
        with self._lock:
            b = self._breakers[i]
            if b.state == CLOSED or self._shut_down:
                return True
            self._transition_device_locked(i, HALF_OPEN, "probe_timer")
        items = self._canary_items()
        t0 = time.perf_counter()
        try:
            collect = self._dispatch(items, probe_device=i)
            results = collect()
            ok = bool(results) and all(bool(r) for r in results)
        except Exception:
            # _dispatch/_watched_collect already recorded the failure
            # and re-armed the probe timer (probe re-raises)
            self._probe_timer_metric.update(time.perf_counter() - t0)
            with self._lock:
                b.last_probe_at = self._now()
            return False
        self._probe_timer_metric.update(time.perf_counter() - t0)
        with self._lock:
            b.last_probe_at = self._now()
        if ok:
            self._record_success(participants=(i,), probe=True)
        else:
            # the device answered but rejected known-good signatures:
            # wrong results are worse than no results — treat as fatal
            self._record_failure(
                "fatal", RuntimeError("canary batch rejected"),
                participants=(i,), probe=True)
        return ok

    def refresh_gauge(self) -> None:
        """Re-assert the state gauge after a metrics clear: the gauge
        is a level, and `clearmetrics` zeroing it while the breaker is
        OPEN would read as CLOSED until the next transition."""
        with self._lock:
            self._state_gauge.set_count(_STATE_GAUGE[self._agg_state])

    # ---------------------------------------------------- forced control --
    def force_trip(self, device: Optional[int] = None) -> None:
        """Admin `backendstatus?action=trip[&device=N]`
        (ALLOW_CHAOS_INJECTION): trip one device, or the whole mesh."""
        with self._lock:
            targets = [device] if device is not None \
                else range(self._ndev)
            for i in targets:
                if self._breakers[i].state == CLOSED:
                    self._trip_device_locked(i, "forced_trip")

    def force_reset(self, device: Optional[int] = None) -> None:
        """Admin `backendstatus?action=reset[&device=N]`: straight to
        CLOSED for one device, or the whole mesh."""
        with self._lock:
            targets = [device] if device is not None \
                else range(self._ndev)
            for i in targets:
                self._close_device_locked(i, "forced_reset")

    # -------------------------------------------------------- lifecycle --
    def shutdown(self) -> None:
        """Cancel every probe timer and release parked hung-collect
        threads; a dead app must not probe the device."""
        with self._lock:
            self._shut_down = True
            for b in self._breakers:
                if b.timer is not None:
                    b.timer.cancel()
                    b.timer = None
                b.next_probe_at = None
            workers, self._idle_workers = self._idle_workers, []
        for w in workers:
            w.jobs.put(None)
        self._release.set()

    # ------------------------------------------------------------ report --
    def status(self) -> dict:
        """Live state document for the `backendstatus` admin route and
        self_check: the aggregate surface PR 5 defined plus per-device
        rows and the surviving-mesh summary."""
        with self._lock:
            now = self._now()
            mono = time.monotonic()
            self._quarantined = [q for q in self._quarantined
                                 if q.thread.is_alive()]
            active = self._active_locked()
            probe_etas = [b.next_probe_at - now for b in self._breakers
                          if b.next_probe_at is not None]
            devices = []
            for b in self._breakers:
                devices.append({
                    "device": b.index,
                    "state": b.state,
                    "consecutive_failures": b.consecutive_failures,
                    "probe_attempt": b.probe_attempt,
                    "next_probe_in_s": (
                        round(max(0.0, b.next_probe_at - now), 3)
                        if b.next_probe_at is not None else None),
                    "last_probe_age_s": (
                        round(max(0.0, now - b.last_probe_at), 3)
                        if b.last_probe_at is not None else None),
                    "dispatches": b.dispatches.count,
                    "skips": b.skips.count,
                    "quarantined": sum(1 for q in self._quarantined
                                       if q.device == b.index),
                })
            return {
                "state": self._agg_state,
                "consecutive_failures": self.consecutive_failures,
                "failure_threshold": self._threshold,
                "dispatches": self._dispatch_counter.count,
                "skips": self._skip_counter.count,
                "failures": {c: m.count
                             for c, m in self._failure_counters.items()},
                "probe_attempt": self.probe_attempt,
                "next_probe_in_s": (
                    round(max(0.0, min(probe_etas)), 3)
                    if probe_etas else None),
                "dispatch_deadline_ms": self._deadline_s * 1000.0,
                "mesh": {"devices": self._ndev, "active": len(active),
                         "active_indices": list(active)},
                "devices": devices,
                "transition_count": self.transition_count,
                "transitions": [
                    {"t": round(t, 3), "from": frm, "to": to,
                     "reason": reason, "dispatches": d,
                     "device": dev, "device_dispatches": dd}
                    for t, frm, to, reason, d, dev, dd
                    in self.transitions],
                "quarantined": [
                    {"batch": q.batch,
                     "age_s": round(mono - q.since, 3),
                     "device": q.device}
                    for q in self._quarantined],
            }
