"""Shard-share arithmetic shared by the mesh dispatch path and the
backend supervisor (jax-free on purpose: the supervisor must stay
importable without the device stack for fake-verifier harnesses).

One definition, two consumers: `ShardedBatchVerifier.verify_batch_async`
splits a batch into per-shard row counts with it, and
`BackendSupervisor._dispatch` reports the same split to the per-device
chaos seam (`ops.backend.dispatch.device`, `n=<share>`). They MUST stay
in lockstep — a fault spec targeting one shard describes exactly the
rows that shard actually owns.
"""

from typing import List


def shard_shares(n: int, k: int) -> List[int]:
    """Row counts per shard for `n` items over `k` shards: the first
    ``n % k`` shards take one extra row. Sums to exactly `n`."""
    base, extra = divmod(n, k)
    return [base + (1 if s < extra else 0) for s in range(k)]
