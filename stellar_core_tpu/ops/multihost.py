"""Multi-host device meshes for the batch-verify service.

Reference analogue: the node's distributed comm backend (SURVEY.md §5.8).
Consensus traffic stays byte-exact XDR over the TCP overlay; THIS module
only scales the crypto service itself across accelerators:

- within a host, signatures shard over the chips on the ICI mesh axis;
- across hosts, over the DCN axis (slow network — each host keeps its
  own signature shard local, so DCN carries only the boolean
  result gather, never the tuples);
- the workload is embarrassingly data-parallel (SURVEY.md §5.7): no
  ring/all-to-all exchange exists because signatures share no state.

`initialize_distributed` wraps jax.distributed for multi-process
(one process per host) deployments; `make_hybrid_mesh` builds the
(dcn, ici) mesh; `ShardedBatchVerifier` accepts any 1-D mesh, and
`HybridShardedVerifier` flattens the 2-D hybrid mesh into the batch
axis with shard_map.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec as PSpec
try:
    from jax import shard_map
except ImportError:                                  # pragma: no cover
    # older jax exposes shard_map under jax.experimental
    from jax.experimental.shard_map import shard_map

from . import ed25519_kernel
from .verifier import MIN_BUCKET, ShardedBatchVerifier, TpuBatchVerifier


def initialize_distributed(coordinator: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """One-per-host jax.distributed init (no-op when single-process).
    In a multi-host pod each node service calls this before building the
    hybrid mesh; the coordinator address travels in the node config, the
    same way the reference distributes peer addresses via cfg
    (KNOWN_PEERS) rather than a discovery service."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def make_hybrid_mesh(devices: Optional[Sequence] = None,
                     n_hosts: Optional[int] = None) -> Mesh:
    """(dcn, ici) mesh: axis 0 spans hosts (slow network), axis 1 the
    chips within a host (fast ICI). With explicit `devices`/`n_hosts`
    (tests: a virtual CPU mesh standing in for N hosts x M chips), the
    flat device list is folded; in production the shape comes from
    jax.process_count()."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if n_hosts is None:
        n_hosts = max(1, jax.process_count())
    per_host = len(devices) // n_hosts
    assert per_host * n_hosts == len(devices), \
        f"{len(devices)} devices do not fold into {n_hosts} hosts"
    grid = np.array(devices).reshape(n_hosts, per_host)
    return Mesh(grid, ("dcn", "ici"))


def make_hybrid_verify(mesh: Mesh,
                       kernel=ed25519_kernel.verify_kernel_full):
    """shard_map'd verify over BOTH mesh axes: the (B,32) uint8 batch
    axis shards over dcn x ici jointly (pure dp). The only cross-device
    traffic is the (B,) bool gather — DCN never carries signatures."""
    spec = PSpec(("dcn", "ici"), None)
    f = shard_map(kernel, mesh=mesh,
                  in_specs=(spec,) * 4, out_specs=PSpec(("dcn", "ici")))
    return jax.jit(f)


class HybridShardedVerifier(ShardedBatchVerifier):
    """Data-parallel batch verifier over a 2-D (dcn, ici) hybrid mesh.

    The full-mesh program shards over both axes jointly (DCN carries
    only the result gather); the per-device health machinery is
    inherited from ShardedBatchVerifier over the FLATTENED device
    list, so a sick chip shrinks the hybrid mesh the same way — a
    degraded active set collapses to a 1-D mesh over the survivors
    (host boundaries stop mattering once the grid is ragged; the
    workload has no cross-shard traffic to place anyway)."""

    def __init__(self, mesh: Optional[Mesh] = None, perf=None,
                 device_sha=None, device_min_batch=None, metrics=None):
        full = mesh if mesh is not None else make_hybrid_mesh()
        super().__init__(devices=list(full.devices.flat), axis="dp",
                         perf=perf, device_sha=device_sha,
                         device_min_batch=device_min_batch,
                         metrics=metrics)
        self.mesh = full

    def _compile(self, active, msg32):
        if len(active) == self.ndev:
            kernel = (ed25519_kernel.verify_kernel_msg32 if msg32
                      else ed25519_kernel.verify_kernel_full)
            return (make_hybrid_verify(self.mesh, kernel), None)
        return super()._compile(active, msg32)
