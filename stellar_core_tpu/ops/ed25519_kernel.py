"""Batch Ed25519 verification kernel for TPU (JAX/XLA).

Computes, for a batch of prepared signatures, whether
    compress([S]B + [k](-A)) == R_bytes
which (given the host-side strict prechecks) is exactly libsodium's
cofactorless check [S]B == R + [k]A. Semantics oracle:
stellar_core_tpu/crypto/ed25519_ref.py; reference hot path:
crypto/SecretKey.cpp:427-460, batch collection points described in
SURVEY.md §3.2/§3.3.

Device-side design:
- Points in extended twisted-Edwards coordinates (X,Y,Z,T); the unified
  add-2008-hwcd-3 law is *complete* on edwards25519 (a=-1 square, d
  non-square), so the whole scalar ladder is branch-free — ideal for XLA:
  no data-dependent control flow, static shapes, one fused scan.
- Windowed Shamir/Straus interleaving (w=2): one shared doubling chain,
  127 iterations of two doublings plus one addition selected from the
  16-entry table [i]B + [j](-A) by arithmetic one-hot (no gather, no
  branches).
- Batch is the lane axis (see fe8.py); scan carries 4 field elements.

Host-side prep (native C++ or Python fallback, see verifier.py) supplies:
  S bytes, k = SHA512(R‖A‖M) mod L bytes, affine -A, R bytes, and the
  strict canonicality/small-order accept flags.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import fe8
from ..crypto import ed25519_ref as _ref

def _base_multiple_consts(m: int):
    """Affine limbs of [m]B computed in the python oracle (host-side,
    once at import; y = 4/5, x recovered with even sign)."""
    x, y, z, _ = _ref.pt_mul(m, _ref.BASE)
    zi = pow(z, _ref.P - 2, _ref.P)
    ax, ay = x * zi % _ref.P, y * zi % _ref.P
    return (fe8.const(ax), fe8.const(ay), fe8.ONE,
            fe8.const(ax * ay % _ref.P))


# [1]B, [2]B, [3]B — constants for the windowed Shamir table
_BASE_MULTS = [None] + [_base_multiple_consts(m) for m in (1, 2, 3)]
BASE_X, BASE_Y, _, BASE_T = _BASE_MULTS[1]

# identity (0, 1, 1, 0)
IDENT = (fe8.ZERO, fe8.ONE, fe8.ONE, fe8.ZERO)


# 2d mod p — cached-format table component (ref10 ge_cached T2d analogue)
D2 = fe8.const((2 * ((-121665 * pow(121666, _ref.P - 2, _ref.P)) % _ref.P))
               % _ref.P)


# Lane-concatenated "wide" muls measured slower than plain narrow muls on
# v5e (concat copies outweigh any latency win), so the stacked path is off;
# kept switchable for future hardware.
WIDE_MULS = False

# ladder scan unrolling (XLA scheduling freedom across iterations);
# round-2 measurement on v5e: see docs/KERNEL_NOTES.md
import os as _os
SCAN_UNROLL = int(_os.environ.get("ED25519_SCAN_UNROLL", "1"))


def _mulw(xs, ys):
    """len(xs) independent field muls, optionally packed into one wide op."""
    if not WIDE_MULS:
        return [fe8.mul(x, y) for x, y in zip(xs, ys)]
    n = len(xs)
    r = fe8.mul(jnp.concatenate(xs, axis=1), jnp.concatenate(ys, axis=1))
    return jnp.split(r, n, axis=1)


def _sqw(xs):
    if not WIDE_MULS:
        return [fe8.sq(x) for x in xs]
    n = len(xs)
    r = fe8.sq(jnp.concatenate(xs, axis=1))
    return jnp.split(r, n, axis=1)


def ge_dbl_w(p, need_t: bool = True):
    """Dedicated doubling: EFD dbl-2008-hwcd with a = -1, all four output
    coordinates scaled by -1 (a legal uniform projective scaling in
    extended coords) so every term is a plain positive field op — 4
    squarings + 4 muls vs a unified add's 9 muls; complete for every
    input. The 4 squarings / 4 output muls are optionally packed wide.

    need_t=False skips the T3 mul: the first doubling of each ladder
    iteration feeds only the second doubling, which never reads T."""
    x1, y1, z1, _ = p
    # carry schedule (round 4, tests/test_fe8_bounds.py): muls/squares
    # carry 3 passes (limbs < 712); sums that feed a multiply use add_c
    # (one pass); differences that feed a multiply use sub1 (one pass,
    # < 1054) — every multiply input stays < MUL_INPUT_BOUND = 1349
    a, b, zz, e0 = _sqw([x1, y1, z1, fe8.add_c(x1, y1)])
    c = fe8.add(zz, zz)
    s1 = fe8.add_c(a, b)
    e = fe8.sub1(e0, s1)
    g = fe8.sub1(b, a)
    f = fe8.sub1(c, g)
    if need_t:
        x3, y3, z3, t3 = _mulw([e, g, f, e], [f, s1, g, s1])
    else:
        x3, y3, z3 = _mulw([e, g, f], [f, s1, g])
        t3 = None
    return (x3, y3, z3, t3)


def to_cached(q):
    """(X,Y,Z,T) -> cached (Y+X, Y-X, 2Z, 2dT) — the ref10 ge_cached
    format: a cached-operand addition then needs only 2 wide muls.
    All four components are multiply operands downstream, so the sums
    carry once (add_c/sub1)."""
    x, y, z, t = q
    return (fe8.add_c(y, x), fe8.sub1(y, x), fe8.add_c(z, z),
            fe8.mul(t, D2))


def ge_add_cached(p, cq):
    """Complete addition of a cached-format operand: 2 wide muls."""
    x1, y1, z1, t1 = p
    yx2, ym2, z22, t2d = cq
    a, b, c, d2 = _mulw([fe8.sub1(y1, x1), fe8.add_c(y1, x1), t1, z1],
                        [ym2, yx2, t2d, z22])
    e = fe8.sub1(b, a)
    f = fe8.sub1(d2, c)
    g = fe8.add_c(d2, c)
    h = fe8.add_c(b, a)
    x3, y3, z3, t3 = _mulw([e, g, f, e], [f, h, g, h])
    return (x3, y3, z3, t3)


def _bits_le(limbs8):
    """(32,B) byte limbs -> (256,B) bits, little-endian bit order."""
    shifts = np.arange(8, dtype=np.int32).reshape(1, 8, 1)
    b = (limbs8[:, None, :] >> shifts) & 1
    return b.reshape(256, limbs8.shape[-1])


def compress(p):
    """Canonical 32-byte encoding: y with sign(x) in the top bit.
    Returns (32,B) exact byte limbs."""
    x, y, z, _ = p
    zi = fe8.invert(z)
    xa = fe8.to_canonical(fe8.mul(x, zi))
    ya = fe8.to_canonical(fe8.mul(y, zi))
    sign = xa[0] & 1
    return ya.at[31].add(sign << 7)


def _win2_msb(limbs8):
    """(32,B) byte limbs -> (127,B) 2-bit windows, msb-first, covering
    bits 0..253. S and k are canonical (< L < 2^253), so bits 253..255
    are zero: the top window pairs (bit 253, bit 252) and only its low
    position (bit 252) can be set."""
    bits = _bits_le(limbs8)[:254]            # (254,B) lsb-first
    lo = bits[0::2]                          # even bit positions
    hi = bits[1::2]
    return (2 * hi + lo)[::-1]               # (127,B) msb-first


def double_scalarmult_w2(s_bytes, k_bytes, neg_a):
    """[S]B + [k](-A) with a 2-bit combined Shamir window: a 16-entry
    table T[i,j] = [i]B + [j](-A) selected per window by arithmetic
    one-hot. 127 iterations of (2 doublings + 1 add) ≈ 381 point ops
    vs the 1-bit ladder's 506 — fewer field muls, same completeness
    (the unified add law covers every table combination)."""
    bsz = s_bytes.shape[-1]

    nax, nay = neg_a
    one = jnp.broadcast_to(fe8.ONE, (32, bsz))
    a1 = (nax, nay, one, fe8.mul(nax, nay))
    a2 = ge_dbl_w(a1)
    a3 = ge_add_cached(a2, to_cached(a1))
    p_ident = tuple(jnp.broadcast_to(c, (32, bsz)) for c in IDENT)
    a_mults = [p_ident, a1, a2, a3]
    b_mults = [p_ident] + [
        tuple(jnp.broadcast_to(c, (32, bsz)) for c in _BASE_MULTS[m])
        for m in (1, 2, 3)]

    # T[i + 4j] = [i]B + [j](-A) in cached format; i=0 or j=0 rows need no
    # extra adds
    table = []
    for j in range(4):
        cached_aj = to_cached(a_mults[j])
        for i in range(4):
            if i == 0:
                table.append(cached_aj)
            elif j == 0:
                table.append(to_cached(b_mults[i]))
            else:
                table.append(to_cached(ge_add_cached(b_mults[i],
                                                     cached_aj)))
    # (16, 4, 32, B) stacked once so the scan body reads one array
    table_arr = jnp.stack([jnp.stack(t) for t in table])

    sw = _win2_msb(s_bytes)                  # (127,B) values 0..3
    kw = _win2_msb(k_bytes)

    def body(p, wins):
        ws, wk = wins                        # (B,) int32 each
        p = ge_dbl_w(ge_dbl_w(p, need_t=False))
        idx = ws + 4 * wk                    # (B,) 0..15
        # arithmetic one-hot select, no gather (XLA-friendly)
        sel = (idx[None, :] ==
               jnp.arange(16, dtype=jnp.int32)[:, None])  # (16,B)
        q_all = jnp.einsum("tclb,tb->clb", table_arr,
                           sel.astype(jnp.int32))
        q = (q_all[0], q_all[1], q_all[2], q_all[3])
        return ge_add_cached(p, q), None

    zero = jnp.zeros_like(s_bytes)
    p0 = (zero, zero + fe8.ONE, zero + fe8.ONE, zero)
    p_fin, _ = lax.scan(body, p0, (sw, kw), unroll=SCAN_UNROLL)
    return p_fin


def verify_kernel(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes):
    """Device entry: all args (32,B) int32 byte limbs. Returns (B,) bool
    equation-match (host flags are ANDed outside)."""
    p = double_scalarmult_w2(s_bytes, k_bytes, (neg_ax, neg_ay))
    enc = compress(p)
    return fe8.eq_canonical(enc, r_bytes)


# ---------------------------------------------------------------------------
# v2: full-on-device pipeline — point decompression + strict byte checks on
# the TPU, so the (single-core) host only computes k = SHA512(R‖A‖M) mod L.
# Inputs travel as uint8 (B,32) arrays: 128 B/signature instead of the 2.6 KB
# an int32 limb layout would ship over the (slow, tunneled) host link.
# Semantics: bit-identical to ed25519_ref.verify / libsodium strict
# (crypto/SecretKey.cpp:427-460): canonical S/A/R, small-order A/R rejected,
# cofactorless equation.
# ---------------------------------------------------------------------------

_P_BYTES = [(( _ref.P >> (8 * i)) & 0xFF) for i in range(32)]
_L_BYTES = [(( _ref.L >> (8 * i)) & 0xFF) for i in range(32)]
SQRT_M1 = fe8.const(_ref.SQRT_M1)

# Canonical y-coordinates of the 8-torsion (identity, order-2, the two
# order-4 points share y=0, and the two order-8 y values); a canonical
# encoding is small-order iff its y is in this set (both x signs are
# torsion). Derived from the oracle at import.
_TORSION_Y = [0, 1, _ref.P - 1]
for _enc in ("26e8958fc2b227b045c3f489f2ef98f0d5dfac05d3c63339b13802886d53fc05",
             "c7176a703d4dd84fba3c0b760d10670f2a2053fa2c39ccc64ec7fd7792ac037a"):
    _pt = _ref.pt_decompress(bytes.fromhex(_enc), strict=True)
    assert _pt is not None and _ref.pt_is_small_order(_pt)
    _TORSION_Y.append(_pt[1] % _ref.P)
_TORSION_Y_BYTES = np.array(
    [[(y >> (8 * i)) & 0xFF for i in range(32)] for y in sorted(_TORSION_Y)],
    dtype=np.int32)                                   # (5, 32)


def _lt_const(b, const_bytes):
    """(B,) bool — little-endian byte array b (32,B) < the 32-byte constant."""
    lt = jnp.zeros(b.shape[-1], dtype=bool)
    eq = jnp.ones(b.shape[-1], dtype=bool)
    for i in range(31, -1, -1):
        c = const_bytes[i]
        lt = lt | (eq & (b[i] < c))
        eq = eq & (b[i] == c)
    return lt


def _is_torsion_y(y):
    """(B,) bool — canonical y bytes match one of the 5 torsion y values."""
    t = jnp.asarray(_TORSION_Y_BYTES)                # (5,32)
    return jnp.any(jnp.all(t[:, :, None] == y[None, :, :], axis=1), axis=0)


def _pow_p58(z):
    """z^((p-5)/8) = z^(2^252 - 3) — ref10 pow22523 chain."""
    t0 = fe8.sq(z)                     # 2
    t1 = fe8.nsquare(t0, 2)            # 8
    t1 = fe8.mul(z, t1)                # 9
    t0 = fe8.mul(t0, t1)               # 11
    t0 = fe8.sq(t0)                    # 22
    t0 = fe8.mul(t1, t0)               # 31 = 2^5 - 1
    t1 = fe8.nsquare(t0, 5)
    t0 = fe8.mul(t1, t0)               # 2^10 - 1
    t1 = fe8.nsquare(t0, 10)
    t1 = fe8.mul(t1, t0)               # 2^20 - 1
    t2 = fe8.nsquare(t1, 20)
    t1 = fe8.mul(t2, t1)               # 2^40 - 1
    t1 = fe8.nsquare(t1, 10)
    t0 = fe8.mul(t1, t0)               # 2^50 - 1
    t1 = fe8.nsquare(t0, 50)
    t1 = fe8.mul(t1, t0)               # 2^100 - 1
    t2 = fe8.nsquare(t1, 100)
    t1 = fe8.mul(t2, t1)               # 2^200 - 1
    t1 = fe8.nsquare(t1, 50)
    t0 = fe8.mul(t1, t0)               # 2^250 - 1
    t0 = fe8.nsquare(t0, 2)            # 2^252 - 4
    return fe8.mul(t0, z)              # 2^252 - 3


def decompress_neg(y_bytes, sign):
    """Strict decompression of (y, sign) with the result negated:
    returns (neg_x, y, valid) where neg_x is -x as loose limbs. Mirrors
    ed25519_ref._recover_x; total (branch-free) on invalid input."""
    y = fe8.from_bytes(y_bytes)
    y2 = fe8.sq(y)
    one = jnp.broadcast_to(fe8.ONE, y.shape)
    u = fe8.sub1(y2, one)                      # y^2 - 1
    v = fe8.add_c(fe8.mul(fe8.D, y2), one)     # d y^2 + 1
    v2 = fe8.sq(v)
    v3 = fe8.mul(v2, v)
    uv3 = fe8.mul(u, v3)
    uv7 = fe8.mul(uv3, fe8.sq(v2))             # u v^7
    x = fe8.mul(uv3, _pow_p58(uv7))            # candidate root
    vx2 = fe8.mul(v, fe8.sq(x))
    # v x^2 == +-u, each via one canonicalized difference/sum
    root_ok = fe8.is_zero_canonical(
        fe8.to_canonical(fe8.sub1(vx2, u)))
    root_flip = fe8.is_zero_canonical(
        fe8.to_canonical(fe8.add_c(vx2, u)))
    x = jnp.where(root_flip, fe8.mul(x, SQRT_M1), x)
    valid = root_ok | root_flip
    x_c = fe8.to_canonical(x)
    x_is_zero = fe8.is_zero_canonical(x_c)
    valid = valid & ~(x_is_zero & (sign == 1))  # "-0" is invalid
    # apply the sign bit, then negate: A = (x_signed, y), -A = (p-x_signed, y)
    flip = (x_c[0] & 1) != sign
    zero = jnp.zeros_like(x_c)
    x_signed = jnp.where(flip, fe8.sub1(zero, x_c), x_c)
    neg_x = fe8.sub1(zero, x_signed)
    return neg_x, y, valid


def verify_kernel_full(a_u8, r_u8, s_u8, k_u8):
    """Device entry v2: (B,32) uint8 arrays (A enc, R enc, S, k). Returns
    (B,) bool — the complete strict verdict, no host flags needed."""
    return _verify_full(a_u8.astype(jnp.int32).T, r_u8.astype(jnp.int32).T,
                        s_u8.astype(jnp.int32).T, k_u8.astype(jnp.int32).T)


def verify_kernel_msg32(a_u8, r_u8, s_u8, m_u8):
    """Device entry v3: like verify_kernel_full but takes the raw 32-byte
    message instead of k — k = SHA512(R‖A‖M) mod L is computed on device
    (ops/sha512.py), removing the last per-signature host work for the
    tx-hash hot path (fixed 32-byte contents hash, SURVEY.md §3.2;
    reference: transactions/TransactionFrame.cpp:99-107)."""
    from . import sha512 as _sha
    k_b = _sha.k_mod_l_96(r_u8, a_u8, m_u8)       # (32,B) exact bytes
    return _verify_full(a_u8.astype(jnp.int32).T, r_u8.astype(jnp.int32).T,
                        s_u8.astype(jnp.int32).T, k_b)


def _verify_full(a_b, r_b, s_b, k_b):
    """Shared v2/v3 body: (32,B) int32 byte limbs of A enc, R enc, S, k."""
    s_ok = _lt_const(s_b, _L_BYTES)
    sign_a = a_b[31] >> 7
    y_a = a_b.at[31].set(a_b[31] & 0x7F)
    a_canon = _lt_const(y_a, _P_BYTES)
    a_small = _is_torsion_y(y_a)
    y_r = r_b.at[31].set(r_b[31] & 0x7F)
    r_canon = _lt_const(y_r, _P_BYTES)
    r_small = _is_torsion_y(y_r)

    neg_ax, ay, a_valid = decompress_neg(y_a, sign_a)
    p = double_scalarmult_w2(s_b, k_b, (neg_ax, ay))
    enc = compress(p)
    eq = fe8.eq_canonical(enc, r_b)
    return (eq & s_ok & a_canon & ~a_small & a_valid
            & r_canon & ~r_small)
