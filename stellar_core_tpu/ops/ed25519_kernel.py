"""Batch Ed25519 verification kernel for TPU (JAX/XLA).

Computes, for a batch of prepared signatures, whether
    compress([S]B + [k](-A)) == R_bytes
which (given the host-side strict prechecks) is exactly libsodium's
cofactorless check [S]B == R + [k]A. Semantics oracle:
stellar_core_tpu/crypto/ed25519_ref.py; reference hot path:
crypto/SecretKey.cpp:427-460, batch collection points described in
SURVEY.md §3.2/§3.3.

Device-side design:
- Points in extended twisted-Edwards coordinates (X,Y,Z,T); the unified
  add-2008-hwcd-3 law is *complete* on edwards25519 (a=-1 square, d
  non-square), so the whole scalar ladder is branch-free — ideal for XLA:
  no data-dependent control flow, static shapes, one fused scan.
- Windowed Shamir/Straus interleaving (w=2): one shared doubling chain,
  127 iterations of two doublings plus one addition selected from the
  16-entry table [i]B + [j](-A) by arithmetic one-hot (no gather, no
  branches).
- Batch is the lane axis (see fe8.py); scan carries 4 field elements.

Host-side prep (native C++ or Python fallback, see verifier.py) supplies:
  S bytes, k = SHA512(R‖A‖M) mod L bytes, affine -A, R bytes, and the
  strict canonicality/small-order accept flags.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import fe8
from ..crypto import ed25519_ref as _ref

def _base_multiple_consts(m: int):
    """Affine limbs of [m]B computed in the python oracle (host-side,
    once at import; y = 4/5, x recovered with even sign)."""
    x, y, z, _ = _ref.pt_mul(m, _ref.BASE)
    zi = pow(z, _ref.P - 2, _ref.P)
    ax, ay = x * zi % _ref.P, y * zi % _ref.P
    return (fe8.const(ax), fe8.const(ay), fe8.ONE,
            fe8.const(ax * ay % _ref.P))


# [1]B, [2]B, [3]B — constants for the windowed Shamir table
_BASE_MULTS = [None] + [_base_multiple_consts(m) for m in (1, 2, 3)]
BASE_X, BASE_Y, _, BASE_T = _BASE_MULTS[1]

# identity (0, 1, 1, 0)
IDENT = (fe8.ZERO, fe8.ONE, fe8.ONE, fe8.ZERO)


def ge_add(p, q):
    """Complete unified addition. Input coord limbs < 2^9, output < 2^9."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe8.mul(fe8.sub(y1, x1), fe8.sub(y2, x2))
    b = fe8.mul(fe8.add(y1, x1), fe8.add(y2, x2))
    c = fe8.mul(fe8.mul(t1, t2), fe8.D)
    c = fe8.add(c, c)
    d = fe8.mul(z1, z2)
    d = fe8.add(d, d)
    e = fe8.sub(b, a)
    f = fe8.sub(d, c)
    g = fe8.add_c(d, c)
    h = fe8.add(b, a)
    return (fe8.mul(e, f), fe8.mul(g, h), fe8.mul(f, g), fe8.mul(e, h))


def _bits_le(limbs8):
    """(32,B) byte limbs -> (256,B) bits, little-endian bit order."""
    shifts = np.arange(8, dtype=np.int32).reshape(1, 8, 1)
    b = (limbs8[:, None, :] >> shifts) & 1
    return b.reshape(256, limbs8.shape[-1])


def compress(p):
    """Canonical 32-byte encoding: y with sign(x) in the top bit.
    Returns (32,B) exact byte limbs."""
    x, y, z, _ = p
    zi = fe8.invert(z)
    xa = fe8.to_canonical(fe8.mul(x, zi))
    ya = fe8.to_canonical(fe8.mul(y, zi))
    sign = xa[0] & 1
    return ya.at[31].add(sign << 7)


def _win2_msb(limbs8):
    """(32,B) byte limbs -> (127,B) 2-bit windows, msb-first, covering
    bits 0..253. S and k are canonical (< L < 2^253), so bits 253..255
    are zero: the top window pairs (bit 253, bit 252) and only its low
    position (bit 252) can be set."""
    bits = _bits_le(limbs8)[:254]            # (254,B) lsb-first
    lo = bits[0::2]                          # even bit positions
    hi = bits[1::2]
    return (2 * hi + lo)[::-1]               # (127,B) msb-first


def double_scalarmult_w2(s_bytes, k_bytes, neg_a):
    """[S]B + [k](-A) with a 2-bit combined Shamir window: a 16-entry
    table T[i,j] = [i]B + [j](-A) selected per window by arithmetic
    one-hot. 127 iterations of (2 doublings + 1 add) ≈ 381 point ops
    vs the 1-bit ladder's 506 — fewer field muls, same completeness
    (the unified add law covers every table combination)."""
    bsz = s_bytes.shape[-1]

    nax, nay = neg_a
    one = jnp.broadcast_to(fe8.ONE, (32, bsz))
    a1 = (nax, nay, one, fe8.mul(nax, nay))
    a2 = ge_add(a1, a1)
    a3 = ge_add(a2, a1)
    p_ident = tuple(jnp.broadcast_to(c, (32, bsz)) for c in IDENT)
    a_mults = [p_ident, a1, a2, a3]
    b_mults = [p_ident] + [
        tuple(jnp.broadcast_to(c, (32, bsz)) for c in _BASE_MULTS[m])
        for m in (1, 2, 3)]

    # T[i + 4j] = [i]B + [j](-A); i=0 or j=0 rows need no extra adds
    table = []
    for j in range(4):
        for i in range(4):
            if i == 0:
                table.append(a_mults[j])
            elif j == 0:
                table.append(b_mults[i])
            else:
                table.append(ge_add(b_mults[i], a_mults[j]))
    # (16, 4, 32, B) stacked once so the scan body reads one array
    table_arr = jnp.stack([jnp.stack(t) for t in table])

    sw = _win2_msb(s_bytes)                  # (127,B) values 0..3
    kw = _win2_msb(k_bytes)

    def body(p, wins):
        ws, wk = wins                        # (B,) int32 each
        p = ge_add(p, p)
        p = ge_add(p, p)
        idx = ws + 4 * wk                    # (B,) 0..15
        # arithmetic one-hot select, no gather (XLA-friendly)
        sel = (idx[None, :] ==
               jnp.arange(16, dtype=jnp.int32)[:, None])  # (16,B)
        q_all = jnp.einsum("tclb,tb->clb", table_arr,
                           sel.astype(jnp.int32))
        q = (q_all[0], q_all[1], q_all[2], q_all[3])
        return ge_add(p, q), None

    zero = jnp.zeros_like(s_bytes)
    p0 = (zero, zero + fe8.ONE, zero + fe8.ONE, zero)
    p_fin, _ = lax.scan(body, p0, (sw, kw))
    return p_fin


def verify_kernel(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes):
    """Device entry: all args (32,B) int32 byte limbs. Returns (B,) bool
    equation-match (host flags are ANDed outside)."""
    p = double_scalarmult_w2(s_bytes, k_bytes, (neg_ax, neg_ay))
    enc = compress(p)
    return fe8.eq_canonical(enc, r_bytes)


@partial(jax.jit, static_argnums=())
def verify_kernel_jit(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes):
    return verify_kernel(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes)
