"""Batch Ed25519 verification kernel for TPU (JAX/XLA).

Computes, for a batch of prepared signatures, whether
    compress([S]B + [k](-A)) == R_bytes
which (given the host-side strict prechecks) is exactly libsodium's
cofactorless check [S]B == R + [k]A. Semantics oracle:
stellar_core_tpu/crypto/ed25519_ref.py; reference hot path:
crypto/SecretKey.cpp:427-460, batch collection points described in
SURVEY.md §3.2/§3.3.

Device-side design:
- Points in extended twisted-Edwards coordinates (X,Y,Z,T); the unified
  add-2008-hwcd-3 law is *complete* on edwards25519 (a=-1 square, d
  non-square), so the whole scalar ladder is branch-free — ideal for XLA:
  no data-dependent control flow, static shapes, one fused scan.
- Shamir/Straus interleaving: one shared doubling chain over 253 bits,
  adding one of {identity, B, -A, B-A} per step, selected by the (S,k)
  bit pair via arithmetic one-hot (no gather, no branches).
- Batch is the lane axis (see fe8.py); scan carries 4 field elements.

Host-side prep (native C++ or Python fallback, see verifier.py) supplies:
  S bytes, k = SHA512(R‖A‖M) mod L bytes, affine -A, R bytes, and the
  strict canonicality/small-order accept flags.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from . import fe8
from ..crypto import ed25519_ref as _ref

# base point in canonical limbs (constants derived from first principles in
# the oracle: y = 4/5, x recovered with even sign)
_BX, _BY = _ref.BASE[0], _ref.BASE[1]
BASE_X = fe8.const(_BX)
BASE_Y = fe8.const(_BY)
BASE_T = fe8.const(_BX * _BY % _ref.P)

# identity (0, 1, 1, 0)
IDENT = (fe8.ZERO, fe8.ONE, fe8.ONE, fe8.ZERO)


def ge_add(p, q):
    """Complete unified addition. Input coord limbs < 2^9, output < 2^9."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = fe8.mul(fe8.sub(y1, x1), fe8.sub(y2, x2))
    b = fe8.mul(fe8.add(y1, x1), fe8.add(y2, x2))
    c = fe8.mul(fe8.mul(t1, t2), fe8.D)
    c = fe8.add(c, c)
    d = fe8.mul(z1, z2)
    d = fe8.add(d, d)
    e = fe8.sub(b, a)
    f = fe8.sub(d, c)
    g = fe8.add_c(d, c)
    h = fe8.add(b, a)
    return (fe8.mul(e, f), fe8.mul(g, h), fe8.mul(f, g), fe8.mul(e, h))


def _bits_le(limbs8):
    """(32,B) byte limbs -> (256,B) bits, little-endian bit order."""
    shifts = np.arange(8, dtype=np.int32).reshape(1, 8, 1)
    b = (limbs8[:, None, :] >> shifts) & 1
    return b.reshape(256, limbs8.shape[-1])


def double_scalarmult(s_bytes, k_bytes, neg_a):
    """[S]B + [k](-A) over the batch. s_bytes/k_bytes: (32,B) int32 byte
    limbs; neg_a: affine (x, y) pair of (32,B) canonical limbs."""
    bsz = s_bytes.shape[-1]

    nax, nay = neg_a
    nat = fe8.mul(nax, nay)
    one = jnp.broadcast_to(fe8.ONE, (32, bsz))
    p_nega = (nax, nay, one, nat)
    p_base = tuple(jnp.broadcast_to(c, (32, bsz))
                   for c in (BASE_X, BASE_Y, fe8.ONE, BASE_T))
    p_both = ge_add(p_base, p_nega)          # B + (-A)
    p_ident = tuple(jnp.broadcast_to(c, (32, bsz)) for c in IDENT)

    # L < 2^253, S is checked canonical host-side: 253 bits suffice
    sb = _bits_le(s_bytes)[:253][::-1]       # msb-first
    kb = _bits_le(k_bytes)[:253][::-1]

    def body(p, bits):
        bs, bk = bits                        # (B,) int32 each
        p = ge_add(p, p)
        w1 = bs * (1 - bk)
        w2 = (1 - bs) * bk
        w3 = bs * bk
        w0 = 1 - w1 - w2 - w3
        q = tuple(w0 * p_ident[c] + w1 * p_base[c]
                  + w2 * p_nega[c] + w3 * p_both[c] for c in range(4))
        return ge_add(p, q), None

    # derive the initial identity point from an input so its sharding
    # (varying manual axes under shard_map) matches the scan body output
    zero = jnp.zeros_like(s_bytes)
    p0 = (zero, zero + fe8.ONE, zero + fe8.ONE, zero)
    p_fin, _ = lax.scan(body, p0, (sb, kb))
    return p_fin


def compress(p):
    """Canonical 32-byte encoding: y with sign(x) in the top bit.
    Returns (32,B) exact byte limbs."""
    x, y, z, _ = p
    zi = fe8.invert(z)
    xa = fe8.to_canonical(fe8.mul(x, zi))
    ya = fe8.to_canonical(fe8.mul(y, zi))
    sign = xa[0] & 1
    return ya.at[31].add(sign << 7)


def verify_kernel(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes):
    """Device entry: all args (32,B) int32 byte limbs. Returns (B,) bool
    equation-match (host flags are ANDed outside)."""
    p = double_scalarmult(s_bytes, k_bytes, (neg_ax, neg_ay))
    enc = compress(p)
    return fe8.eq_canonical(enc, r_bytes)


@partial(jax.jit, static_argnums=())
def verify_kernel_jit(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes):
    return verify_kernel(s_bytes, k_bytes, neg_ax, neg_ay, r_bytes)
