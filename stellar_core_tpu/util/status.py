"""Rolling node-status strings (reference: src/util/StatusManager.h).

Subsystems publish one current status line each (history catchup progress,
out-of-sync notices, ...) surfaced through the HTTP `info` endpoint.
"""

from __future__ import annotations

from enum import Enum
from typing import Dict


class StatusCategory(Enum):
    HISTORY_CATCHUP = "history-catchup"
    HISTORY_PUBLISH = "history-publish"
    NTP = "ntp"
    OUT_OF_SYNC_RECOVERY = "out-of-sync"
    REQUIRES_UPGRADES = "requires-upgrades"


class StatusManager:
    def __init__(self):
        self._status: Dict[StatusCategory, str] = {}

    def set_status(self, cat: StatusCategory, msg: str) -> None:
        self._status[cat] = msg

    def remove_status(self, cat: StatusCategory) -> None:
        self._status.pop(cat, None)

    def get_status(self, cat: StatusCategory) -> str:
        return self._status.get(cat, "")

    def to_list(self) -> list:
        return [f"{c.value}: {m}" for c, m in self._status.items()]
