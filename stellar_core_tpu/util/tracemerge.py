"""Multi-node trace merge: one Chrome trace for a whole mesh.

Per-node FlightRecorders (util/tracing.py) each capture their own
timeline with their own zero point (the perf_counter at start()).
This module merges them into ONE Chrome trace-event document:

- **clock alignment** — every node's events shift by (t0 - min t0),
  so events that happened at the same instant line up across process
  lanes (in-process simulations share one perf_counter domain;
  ``merge_trace_docs`` is the multi-process variant, aligning the
  `dumptrace` exports collected by simulation/cluster.py on the
  wall-clock anchor each recorder stamps into ``otherData.t0_wall``);
- **process lanes** — each node keeps its pid + process_name metadata
  (the recorder's label = node id prefix); colliding pids (bare test
  apps all defaulting to the same port) are reassigned;
- **async-id scoping** — legacy async events ("b"/"e") correlate
  globally by (cat, id), so two nodes' `tx.e2e` tracks for the same
  tx would merge into one malformed track; ids are prefixed with the
  node label to keep per-node tracks distinct;
- **flow stitching** — `flood.send`/`flood.recv` instants carry the
  message hash (overlay/propagation.py); every hash seen on 2+ nodes
  becomes a flow chain (ph "s"/"t"/"f", cat "flood", id = hash) whose
  arrows follow the message across node lanes in delivery order —
  the Dapper-style cross-process causal edge (PAPERS.md, Sigelman
  et al. 2010) drawn from hash-keyed hops instead of propagated
  request ids (no wire-format change).

Consumers: `Simulation.merged_trace()`, `bench.py --trace`, and
`scripts/trace_report.py --slots/--flood`.
"""

from __future__ import annotations

from typing import Dict, List, Optional

# instant names carrying the propagation hash key (overlay/manager.py)
FLOOD_SEND = "flood.send"
FLOOD_RECV = "flood.recv"


def merge_recorders(recorders) -> dict:
    """Merge FlightRecorder buffers into one clock-aligned Chrome
    trace document with flow chains stitched across node lanes.
    Recorders with no events are skipped; active recorders are dumped
    without being stopped (the caller owns their lifecycle)."""
    recs = [r for r in recorders if len(r) or r.active]
    if not recs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(r.t0 for r in recs)
    # reassign colliding pids (all events of one recorder share one)
    pids = [r.pid for r in recs]
    remap = {}
    if len(set(pids)) < len(pids):
        remap = {id(r): i + 1 for i, r in enumerate(recs)}
    events: List[dict] = []
    dropped: Dict[str, int] = {}
    for r in recs:
        pid = remap.get(id(r), r.pid)
        # fallback label derives from the REMAPPED pid: two unlabeled
        # recorders must not share a label, or their async tracks merge
        label = r.label or "node-%d" % pid
        off_us = (r.t0 - base) * 1e6
        doc = r.to_chrome_trace()
        dropped[label] = doc["otherData"]["dropped_events"]
        for ev in doc["traceEvents"]:
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + off_us, 3)
            if ev.get("ph") in ("b", "e"):
                ev["id"] = "%s:%s" % (label, ev["id"])
            events.append(ev)
    events.extend(_stitch_flows(events))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"nodes": [r.label or str(r.pid) for r in recs],
                          "dropped_events": dropped}}


def merge_trace_docs(docs: List[dict],
                     labels: Optional[List[str]] = None) -> dict:
    """Merge already-exported Chrome trace documents — the `dumptrace`
    exports a multi-process cluster harness collects over HTTP — into
    one clock-aligned document with flow chains stitched across node
    lanes. Separate processes have incomparable perf_counter domains,
    so alignment uses the wall-clock anchor each FlightRecorder stamps
    into ``otherData.t0_wall`` at start() (the substitution the
    in-process merge above anticipated). NTP-grade wall skew between
    processes on one host is microseconds — well under a flood hop."""
    # pair docs with their labels BEFORE filtering empties, or a
    # skipped doc would shift every later lane onto the wrong label
    pairs = [(d, labels[i] if labels else None)
             for i, d in enumerate(docs or [])]
    pairs = [(d, lb) for d, lb in pairs if d and d.get("traceEvents")]
    if not pairs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    anchors = [(d.get("otherData") or {}).get("t0_wall") or 0.0
               for d, _ in pairs]
    # a doc from a recorder that never start()ed reports anchor 0.0;
    # min() over it would shove every real lane an epoch into the
    # future, so unanchored docs merge at offset 0 instead
    real = [a for a in anchors if a > 0]
    base = min(real) if real else 0.0
    events: List[dict] = []
    dropped: Dict[str, int] = {}
    names: List[str] = []
    used_pids: set = set()
    for i, (doc, label_in) in enumerate(pairs):
        od = doc.get("otherData") or {}
        pid = od.get("pid") or i + 1
        while pid in used_pids:       # colliding lanes stay distinct
            pid += 1
        used_pids.add(pid)
        label = label_in or od.get("label") or "node-%d" % pid
        names.append(label)
        off_us = (anchors[i] - base) * 1e6 if anchors[i] > 0 else 0.0
        dropped[label] = od.get("dropped_events", 0)
        for ev in doc["traceEvents"]:
            ev = dict(ev)             # callers keep their doc intact
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(ev["ts"] + off_us, 3)
            if ev.get("ph") in ("b", "e"):
                # same scoping rule as the in-process merge: two nodes'
                # async tracks for one tx must not fuse into one track
                ev["id"] = "%s:%s" % (label, ev["id"])
            events.append(ev)
    events.extend(_stitch_flows(events))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"nodes": names, "dropped_events": dropped}}


def _stitch_flows(events: List[dict]) -> List[dict]:
    """Build flow chains from hash-keyed send/recv instants: for every
    hash observed on 2+ process lanes, emit one chronological chain
    "s" → "t"… → "f" visiting each instant's (pid, tid, ts)."""
    by_hash: Dict[str, List[dict]] = {}
    for ev in events:
        if ev.get("ph") == "i" and ev.get("name") in (FLOOD_SEND,
                                                      FLOOD_RECV):
            h = (ev.get("args") or {}).get("hash")
            if h:
                by_hash.setdefault(h, []).append(ev)
    flows: List[dict] = []
    for h, endpoints in by_hash.items():
        if len({e["pid"] for e in endpoints}) < 2:
            continue                      # never crossed a node boundary
        endpoints.sort(key=lambda e: e["ts"])
        last = len(endpoints) - 1
        prev_ts = None
        for i, ep in enumerate(endpoints):
            ts = ep["ts"]
            if prev_ts is not None and ts <= prev_ts:
                # flow steps of one chain must strictly advance
                ts = prev_ts + 0.001
            prev_ts = ts
            flows.append({
                "ph": "s" if i == 0 else ("f" if i == last else "t"),
                "cat": "flood", "id": h, "name": "flood.hop",
                "pid": ep["pid"], "tid": ep["tid"], "ts": ts,
                "bp": "e",
            })
    return flows
