"""RandomEvictionCache — bounded map with random eviction.

Reference: src/util/RandomEvictionCache.h. Used most prominently as the
global signature-verification cache (crypto/SecretKey.cpp:37-60): 0xffff
entries keyed by BLAKE2(key‖sig‖msg) with hit/miss counters. Random (rather
than LRU) eviction keeps the hot path O(1) without bookkeeping writes.
"""

from __future__ import annotations

import random
from typing import Dict, Generic, Hashable, List, Optional, TypeVar

from .checks import releaseAssert

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class RandomEvictionCache(Generic[K, V]):
    def __init__(self, max_size: int, seed: int = 0):
        releaseAssert(max_size > 0, "cache max_size must be positive")
        self.max_size = max_size
        self._map: Dict[K, int] = {}       # key -> index into _slots
        self._slots: List[tuple] = []      # (key, value)
        self._rng = random.Random(seed)
        self.hits = 0
        self.misses = 0
        self.inserts = 0

    def __len__(self) -> int:
        return len(self._slots)

    def maybe_get(self, key: K) -> Optional[V]:
        idx = self._map.get(key)
        if idx is None:
            self.misses += 1
            return None
        self.hits += 1
        return self._slots[idx][1]

    def exists(self, key: K) -> bool:
        # Non-counting probe (reference exposes both exists() and get()).
        return key in self._map

    def put(self, key: K, value: V) -> None:
        self.inserts += 1
        idx = self._map.get(key)
        if idx is not None:
            self._slots[idx] = (key, value)
            return
        if len(self._slots) >= self.max_size:
            # evict a uniformly random victim: swap-with-last + pop, O(1)
            victim = self._rng.randrange(len(self._slots))
            vkey, _ = self._slots[victim]
            last_key, last_val = self._slots[-1]
            self._slots[victim] = (last_key, last_val)
            self._map[last_key] = victim
            self._slots.pop()
            del self._map[vkey]
        self._map[key] = len(self._slots)
        self._slots.append((key, value))

    def clear(self) -> None:
        self._map.clear()
        self._slots.clear()

    def reset_counters(self) -> None:
        self.hits = self.misses = self.inserts = 0
