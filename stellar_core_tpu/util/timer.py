"""VirtualClock / VirtualTimer — the deterministic event loop.

Reference: src/util/Timer.h:64-260. The whole node runs on a single logical
thread cranking a VirtualClock: each crank dispatches due timers, pending I/O
callbacks, and Scheduler actions. In VIRTUAL_TIME mode the clock only advances
when cranked and jumps straight to the next scheduled event, which makes every
test deterministic and lets simulated networks run "at fast simulated time"
(docs/architecture.md:33-36).
"""

from __future__ import annotations

import heapq
import itertools
import threading as _threading
import time as _time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable, List, Optional

from . import threads
from .checks import releaseAssert


class ClockMode(Enum):
    REAL_TIME = 0
    VIRTUAL_TIME = 1


# Error type passed to timer callbacks when cancelled, mirroring asio's
# operation_aborted convention the reference uses (util/Timer.h:244-310).
class TimerError(Enum):
    SUCCESS = 0
    CANCELLED = 1


# Crank phase boundaries reported to VirtualClock.crank_hooks. These
# values ARE the wire values of the replay input log's TICK records
# (replay/log.py mirrors them as TICK_*): the recorder writes one TICK
# per boundary and the replayer re-creates the phase machine from them.
CRANK_START = 0     # crank began; posted actions drain next
CRANK_DISPATCH = 1  # io pollers done; due timers dispatch next
CRANK_JUMP = 2      # idle blocked crank advanced virtual time; dispatching
CRANK_END = 3       # crank finished


@dataclass(order=True)
class _Event:
    when: float
    seq: int
    callback: Callable[[TimerError], None] = field(compare=False)
    cancelled: bool = field(default=False, compare=False)


class VirtualClock:
    """Deterministic time source + event dispatcher.

    crank(block=False) executes due work and returns the number of actions
    performed (reference: util/Timer.h:178-184). In VIRTUAL_TIME mode, a crank
    with no due work advances time to the next event.
    """

    def __init__(self, mode: ClockMode = ClockMode.VIRTUAL_TIME):
        self.mode = mode
        self._heap: List[_Event] = []
        self._seq = itertools.count()
        self._virtual_now = 0.0
        self._stopped = False
        # Callables polled every crank for ready work (I/O integration point;
        # the reference integrates asio's io_context here, Timer.h:120-140).
        self._io_pollers: List[Callable[[], int]] = []
        # One-shot actions posted to run "soon" (postToCurrentCrank
        # analogue). Lock-guarded: the admin HTTP server posts from its
        # socket threads (command_handler.run_http_server), and an
        # append racing crank()'s drain swap could silently lose the
        # posted command.
        self._actions: List[Callable[[], None]] = []
        self._actions_lock = _threading.Lock()
        self.scheduler = None  # attached by Application / tests
        # crank-phase observers: each hook is called (phase, now) at
        # every CRANK_* boundary of every crank. The input recorder
        # (replay/recorder.py) rides this to capture clock advances and
        # timer-firing order — intra-instant interleaving is invisible
        # to timestamps alone. Idle cost is one empty-list check.
        self.crank_hooks: List[Callable[[int, float], None]] = []

    # -- time ---------------------------------------------------------------
    def now(self) -> float:
        if self.mode is ClockMode.VIRTUAL_TIME:
            return self._virtual_now
        return _time.monotonic()

    def system_now(self) -> float:
        """Wall-clock seconds since epoch; virtual mode offsets from 0."""
        if self.mode is ClockMode.VIRTUAL_TIME:
            return self._virtual_now
        return _time.time()

    def set_virtual_time(self, t: float) -> None:
        releaseAssert(self.mode is ClockMode.VIRTUAL_TIME,
                      "set_virtual_time requires VIRTUAL_TIME mode")
        releaseAssert(t >= self._virtual_now, "time cannot move backwards")
        self._virtual_now = t

    # -- scheduling ---------------------------------------------------------
    def schedule_at(self, when: float, cb: Callable[[TimerError], None]) -> _Event:
        ev = _Event(when, next(self._seq), cb)
        heapq.heappush(self._heap, ev)
        return ev

    def post(self, action: Callable[[], None]) -> None:
        """Run `action` on the next crank (reference: postToCurrentCrank).
        Thread-safe: HTTP handler threads post admin commands here."""
        with self._actions_lock:
            self._actions.append(action)

    def add_io_poller(self, poller: Callable[[], int]) -> None:
        """Register a callable polled each crank; returns #actions it ran."""
        self._io_pollers.append(poller)

    def remove_io_poller(self, poller: Callable[[], int]) -> None:
        if poller in self._io_pollers:
            self._io_pollers.remove(poller)

    # -- crank loop ---------------------------------------------------------
    # The three phase methods below are public because the replay
    # driver (replay/replayer.py) re-creates the crank sequence from
    # recorded TICK boundaries instead of calling crank(): it drives
    # exactly these phases at exactly the recorded instants.
    def drain_actions(self) -> int:
        """Run every pending posted action (the crank's first phase)."""
        with self._actions_lock:
            actions, self._actions = self._actions, []
        for a in actions:
            a()
        return len(actions)

    def poll_io(self) -> int:
        """Run every registered io poller once (second phase)."""
        n = 0
        for p in list(self._io_pollers):
            n += p()
        return n

    def dispatch_due(self) -> int:
        """Fire every due timer in (when, seq) order (third phase)."""
        return self._dispatch_due()

    def _notify_crank(self, phase: int) -> None:
        now = self.now()
        for h in list(self.crank_hooks):
            h(phase, now)

    def _dispatch_due(self) -> int:
        n = 0
        now = self.now()
        while self._heap and self._heap[0].when <= now:
            ev = heapq.heappop(self._heap)
            if not ev.cancelled:
                ev.callback(TimerError.SUCCESS)
                n += 1
        return n

    def crank(self, block: bool = False) -> int:  # thread-domain: crank
        """One iteration of the main loop; returns number of actions run."""
        if threads.CHECK:
            # whoever cranks IS the logical main thread: posted
            # actions, timers and scheduler work all run under it
            threads.bind("crank")
        if self._stopped:
            return 0
        if self.crank_hooks:
            self._notify_crank(CRANK_START)
        # posted actions first
        n = self.drain_actions()
        # I/O
        n += self.poll_io()
        if self.crank_hooks:
            self._notify_crank(CRANK_DISPATCH)
        # due timers
        n += self._dispatch_due()
        # scheduler actions: at most ONE per crank, as the reference
        # interleaves fairly between queues (util/Scheduler.h:100-221)
        if self.scheduler is not None:
            n += self.scheduler.run_one()
        if n == 0 and block:
            if self.mode is ClockMode.VIRTUAL_TIME:
                nxt = self.next_event_time()
                if nxt is not None:
                    self._virtual_now = max(self._virtual_now, nxt)
                    if self.crank_hooks:
                        self._notify_crank(CRANK_JUMP)
                    n += self._dispatch_due()
            else:
                nxt = self.next_event_time()
                now = self.now()
                if nxt is not None and nxt > now:
                    _time.sleep(min(nxt - now, 0.050))
                elif nxt is None:
                    # nothing scheduled: sleep briefly so real-time run
                    # loops waiting on io pollers don't busy-spin
                    _time.sleep(0.010)
                if self.crank_hooks:
                    self._notify_crank(CRANK_JUMP)
                n += self._dispatch_due()
        if self.crank_hooks:
            self._notify_crank(CRANK_END)
        return n

    def next_event_time(self) -> Optional[float]:
        while self._heap and self._heap[0].cancelled:
            heapq.heappop(self._heap)
        return self._heap[0].when if self._heap else None

    def stop(self) -> None:
        self._stopped = True

    @property
    def stopped(self) -> bool:
        return self._stopped

    # -- test helpers (reference: Simulation::crankUntil) --------------------
    def crank_until(self, pred: Callable[[], bool], timeout: float) -> bool:
        deadline = self.now() + timeout
        while not pred():
            if self.now() > deadline:
                return False
            if self.crank(block=True) == 0 and self.next_event_time() is None:
                if self.scheduler is not None and self.scheduler.size() > 0:
                    continue
                return pred()
        return True

    def crank_for(self, duration: float) -> int:
        """Crank until `duration` seconds elapse; returns actions run.

        Events scheduled beyond the window do NOT fire; in virtual mode the
        clock lands exactly on `now + duration`.
        """
        deadline = self.now() + duration
        total = 0
        if self.mode is ClockMode.VIRTUAL_TIME:
            while True:
                n = self.crank(block=False)
                total += n
                if n == 0:
                    nxt = self.next_event_time()
                    if nxt is not None and nxt <= deadline:
                        self._virtual_now = max(self._virtual_now, nxt)
                    else:
                        break
            self._virtual_now = max(self._virtual_now, deadline)
        else:
            while self.now() < deadline:
                total += self.crank(block=True)
        return total


class VirtualTimer:
    """One-shot timer bound to a VirtualClock (reference: util/Timer.h:244).

    expires_from_now(d) + async_wait(cb, on_cancel) schedules cb on expiry;
    cancel() invokes on_cancel (if given) and drops cb — the (onSuccess,
    onFailure) pair mirrors the reference's VirtualTimer::async_wait overload.
    """

    def __init__(self, clock: VirtualClock):
        self._clock = clock
        self._event: Optional[_Event] = None
        self._cancel_cb: Optional[Callable[[], None]] = None
        self._deadline: Optional[float] = None

    def expires_from_now(self, seconds: float) -> None:
        self.cancel()
        self._deadline = self._clock.now() + seconds

    def expires_at(self, when: float) -> None:
        self.cancel()
        self._deadline = when

    def async_wait(
        self,
        cb: Callable[[], None],
        on_cancel: Optional[Callable[[], None]] = None,
    ) -> None:
        releaseAssert(self._event is None, "timer already armed")
        releaseAssert(self._deadline is not None,
                      "timer not armed: call expires_* first")
        self._cancel_cb = on_cancel

        def wrapped(err: TimerError) -> None:
            self._event = None
            if err is TimerError.SUCCESS:
                cb()

        self._event = self._clock.schedule_at(self._deadline, wrapped)

    def cancel(self) -> None:
        if self._event is not None:
            self._event.cancelled = True
            self._event = None
            if self._cancel_cb is not None:
                cb, self._cancel_cb = self._cancel_cb, None
                cb()

    @property
    def armed(self) -> bool:
        return self._event is not None
