"""Fair multi-queue action scheduler with load shedding.

Reference: src/util/Scheduler.h:100-221. The main thread interleaves overlay,
herder and ledger actions through named queues scheduled by accumulated
virtual runtime (least-run queue goes first); DROPPABLE actions are shed when
their queue's latency exceeds a limit, providing overload protection.
"""

from __future__ import annotations

import time
from collections import deque
from enum import Enum
from typing import Callable, Deque, Dict, Optional, Tuple


class ActionType(Enum):
    NORMAL = 0
    DROPPABLE = 1


class _Queue:
    __slots__ = ("name", "actions", "total_service_time")

    def __init__(self, name: str):
        self.name = name
        # (action, type, enqueue_time)
        self.actions: Deque[Tuple[Callable[[], None], ActionType, float]] = deque()
        self.total_service_time = 0.0


class Scheduler:
    """Fair scheduler over named action queues.

    enqueue(queue_name, action, action_type); run_one() picks the non-empty
    queue with the least accumulated service time and runs one action.
    DROPPABLE actions older than `latency_window` seconds are shed
    (reference: Scheduler::enqueue/runOne, util/Scheduler.cpp).
    """

    def __init__(self, clock=None, latency_window: float = 5.0):
        self._clock = clock
        self._queues: Dict[str, _Queue] = {}
        self.latency_window = latency_window
        # Highest service time across queues; new/idle queues are floored to
        # max - latency_window so they can't monopolize the scheduler
        # (reference: Scheduler.cpp:155,313 minTotalService clamp).
        self._max_total_service = 0.0
        self.stats_actions_enqueued = 0
        self.stats_actions_run = 0
        self.stats_actions_dropped = 0

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else time.monotonic()

    def enqueue(
        self,
        queue_name: str,
        action: Callable[[], None],
        action_type: ActionType = ActionType.NORMAL,
    ) -> None:
        q = self._queues.get(queue_name)
        if q is None:
            q = self._queues[queue_name] = _Queue(queue_name)
        if not q.actions:
            # queue was idle: floor its service time so it can't starve others
            q.total_service_time = max(
                q.total_service_time,
                self._max_total_service - self.latency_window)
        q.actions.append((action, action_type, self._now()))
        self.stats_actions_enqueued += 1

    def size(self) -> int:
        return sum(len(q.actions) for q in self._queues.values())

    def queue_length(self, queue_name: str) -> int:
        q = self._queues.get(queue_name)
        return len(q.actions) if q is not None else 0

    def _shed(self, q: _Queue, now: float) -> None:
        while q.actions:
            action, atype, t_enq = q.actions[0]
            if atype is ActionType.DROPPABLE and now - t_enq > self.latency_window:
                q.actions.popleft()
                self.stats_actions_dropped += 1
            else:
                break

    def run_one(self) -> int:
        """Run one action from the least-served non-empty queue. Returns 0/1."""
        now = self._now()
        best: Optional[_Queue] = None
        for q in self._queues.values():
            self._shed(q, now)
            if q.actions and (best is None
                              or q.total_service_time < best.total_service_time):
                best = q
        if best is None:
            return 0
        action, _, _ = best.actions.popleft()
        t0 = time.perf_counter()
        try:
            action()
        finally:
            best.total_service_time += time.perf_counter() - t0
            self._max_total_service = max(self._max_total_service,
                                          best.total_service_time)
            self.stats_actions_run += 1
        return 1

    def run_all(self, max_actions: int = 1_000_000) -> int:
        n = 0
        while n < max_actions and self.run_one():
            n += 1
        return n
