"""Metrics registry — counters / meters / timers / histograms.

Reference: libmedida (lib/libmedida) as catalogued in docs/metrics.md (e.g.
`ledger.transaction.apply` timer, `scp.envelope.receive`, `overlay.flood.*`).
Exposed over the HTTP admin `metrics` endpoint and resettable via
`clearmetrics` (main/CommandHandler.cpp:114).
"""

from __future__ import annotations

import bisect
import math
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .checks import releaseAssert


class Counter:
    def __init__(self):
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n

    def dec(self, n: int = 1) -> None:
        self.count -= n

    def set_count(self, n: int) -> None:
        self.count = n

    def reset(self) -> None:
        self.count = 0

    def to_json(self) -> dict:
        return {"type": "counter", "count": self.count}


class Meter:
    """Event rate meter with 1m/5m/15m EWMA rates (medida::Meter)."""

    _ALPHAS = {"1m": 1 - math.exp(-5.0 / 60),
               "5m": 1 - math.exp(-5.0 / 300),
               "15m": 1 - math.exp(-5.0 / 900)}

    def __init__(self, event_type: str = "event"):
        self.count = 0
        self.event_type = event_type
        self._rates = {k: 0.0 for k in self._ALPHAS}
        self._rates_initialized = False
        self._uncounted = 0
        self._start = self._last_tick = time.monotonic()

    def reset(self) -> None:
        self.__init__(self.event_type)

    def mark(self, n: int = 1) -> None:
        self._maybe_tick()
        self.count += n
        self._uncounted += n

    def _maybe_tick(self) -> None:
        now = time.monotonic()
        elapsed = now - self._last_tick
        if elapsed >= 5.0:
            ticks = int(elapsed // 5.0)
            inst = self._uncounted / elapsed
            self._uncounted = 0
            if not self._rates_initialized:
                # seed EWMAs with the first observed rate (Codahale/medida
                # convention) so early readings aren't ~alpha-times too low
                for k in self._ALPHAS:
                    self._rates[k] = inst
                self._rates_initialized = True
                ticks -= 1
                inst = 0.0
            for _ in range(min(ticks, 200)):
                for k, a in self._ALPHAS.items():
                    self._rates[k] += a * (inst - self._rates[k])
                inst = 0.0 if ticks > 1 else inst
            self._last_tick = now

    def mean_rate(self) -> float:
        dt = time.monotonic() - self._start
        return self.count / dt if dt > 0 else 0.0

    def one_minute_rate(self) -> float:
        self._maybe_tick()
        return self._rates["1m"]

    def five_minute_rate(self) -> float:
        self._maybe_tick()
        return self._rates["5m"]

    def fifteen_minute_rate(self) -> float:
        self._maybe_tick()
        return self._rates["15m"]

    def to_json(self) -> dict:
        # all three EWMA windows the meter already computes (medida
        # emits 1m/5m/15m; only surfacing 1m hid the slower windows
        # from the admin API and the Prometheus exposition)
        self._maybe_tick()
        return {"type": "meter", "count": self.count,
                "mean_rate": self.mean_rate(),
                "1_min_rate": self._rates["1m"],
                "5_min_rate": self._rates["5m"],
                "15_min_rate": self._rates["15m"]}


class Histogram:
    """Reservoir-sampled histogram (uniform reservoir,
    medida::Histogram); with `window_seconds` set, percentiles/mean/
    min/max reflect only the sliding window (reference:
    HISTOGRAM_WINDOW_SIZE — medida's sliding-window sample)."""

    def __init__(self, reservoir: int = 1028, seed: int = 0,
                 window_seconds: Optional[float] = None):
        self._reservoir = reservoir
        self._sample: List[float] = []
        self.count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._rng = random.Random(seed)
        self._window = window_seconds
        # bounded like medida's sliding-window sample: the window keeps
        # at most _reservoir recent events, so hot per-tx timers cannot
        # grow without bound
        self._events = deque(maxlen=reservoir)

    def reset(self) -> None:
        self._sample = []
        self.count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._events.clear()

    def update(self, value: float) -> None:
        self.count += 1
        self._sum += value
        self._min = min(self._min, value)
        self._max = max(self._max, value)
        if self._window is not None:
            now = time.monotonic()
            self._events.append((now, value))
            self._prune(now)
            return
        if len(self._sample) < self._reservoir:
            bisect.insort(self._sample, value)
        else:
            i = self._rng.randrange(self.count)
            if i < self._reservoir:
                del self._sample[self._rng.randrange(len(self._sample))]
                bisect.insort(self._sample, value)

    def _prune(self, now: float) -> None:
        cutoff = now - self._window
        ev = self._events
        while ev and ev[0][0] < cutoff:
            ev.popleft()

    def _window_values(self) -> List[float]:
        self._prune(time.monotonic())
        return sorted(v for _, v in self._events)

    @staticmethod
    def _pctl(sample: List[float], q: float) -> float:
        if not sample:
            return 0.0
        idx = min(len(sample) - 1, int(q * len(sample)))
        return sample[idx]

    def percentile(self, q: float) -> float:
        sample = self._window_values() if self._window is not None \
            else self._sample
        return self._pctl(sample, q)

    def mean(self) -> float:
        if self._window is not None:
            vals = self._window_values()
            return sum(vals) / len(vals) if vals else 0.0
        return self._sum / self.count if self.count else 0.0

    def to_json(self) -> dict:
        # "sum" is the LIFETIME total either way: the Prometheus
        # summary convention is windowed quantiles over a cumulative
        # _count/_sum pair — a windowed mean times a lifetime count
        # would make the exported _sum non-monotonic
        if self._window is not None:
            # ONE sort serves every stat, and min/max/mean reflect the
            # window like the percentiles do (lifetime totals would
            # contradict the window semantics operators read)
            vals = self._window_values()
            return {"type": "histogram", "count": self.count,
                    "sum": self._sum,
                    "mean": sum(vals) / len(vals) if vals else 0.0,
                    "min": vals[0] if vals else 0,
                    "max": vals[-1] if vals else 0,
                    "median": self._pctl(vals, 0.5),
                    "75%": self._pctl(vals, 0.75),
                    "99%": self._pctl(vals, 0.99)}
        return {"type": "histogram", "count": self.count,
                "sum": self._sum, "mean": self.mean(),
                "min": self._min if self.count else 0,
                "max": self._max if self.count else 0,
                "median": self.percentile(0.5),
                "75%": self.percentile(0.75), "99%": self.percentile(0.99)}


# cumulative-histogram bucket bounds for timers, in seconds: sub-ms
# verify flushes up to multi-second closes. Fixed process-wide so the
# exported `_bucket` families can be SUMMED across nodes — the whole
# point of exporting them (summary quantiles cannot be aggregated)
TIMER_BUCKET_BOUNDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                       0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Timer(Histogram):
    """Duration metric: histogram of seconds + throughput meter.

    Besides the reservoir/window sample (summary quantiles), every
    update also lands in a fixed-bound cumulative bucket array —
    exported as a Prometheus `histogram` family (`_bucket{le=…}`)
    that, unlike the summary, aggregates across nodes."""

    def __init__(self, window_seconds: Optional[float] = None):
        super().__init__(window_seconds=window_seconds)
        self.meter = Meter()
        self._bucket_counts = [0] * (len(TIMER_BUCKET_BOUNDS) + 1)

    def reset(self) -> None:
        super().reset()
        self.meter.reset()
        self._bucket_counts = [0] * (len(TIMER_BUCKET_BOUNDS) + 1)

    def update(self, seconds: float) -> None:  # type: ignore[override]
        super().update(seconds)
        self.meter.mark()
        self._bucket_counts[
            bisect.bisect_left(TIMER_BUCKET_BOUNDS, seconds)] += 1

    def time_scope(self):
        return _TimerScope(self)

    def to_json(self) -> dict:
        j = super().to_json()
        j["type"] = "timer"
        j["rate"] = self.meter.to_json()
        # cumulative counts per le-bound; the implicit +Inf bucket is
        # the lifetime count (Prometheus histogram convention)
        cum = []
        running = 0
        for c in self._bucket_counts[:-1]:
            running += c
            cum.append(running)
        j["buckets"] = {"le": list(TIMER_BUCKET_BOUNDS),
                        "cumulative": cum}
        return j


class _TimerScope:
    def __init__(self, timer: Timer):
        self._timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Dotted-name metric registry (reference: medida::MetricsRegistry)."""

    def __init__(self, window_minutes: Optional[float] = None):
        self._metrics: Dict[str, object] = {}
        # completion worker and crank both create metrics lazily; the
        # lock closes the create-create race (a lost metric object
        # would silently drop its counts)
        self._lock = threading.Lock()
        # reference: HISTOGRAM_WINDOW_SIZE (minutes) — applied to every
        # histogram/timer created through this registry
        self.window_seconds = (window_minutes * 60.0
                               if window_minutes else None)

    def _get(self, name: str, cls, *args, **kw):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = self._metrics[name] = cls(*args, **kw)
        releaseAssert(type(m) is cls, f"metric {name} type mismatch")
        return m

    def new_counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def new_meter(self, name: str, event_type: str = "event") -> Meter:
        return self._get(name, Meter, event_type)

    def new_timer(self, name: str) -> Timer:
        return self._get(name, Timer,
                         window_seconds=self.window_seconds)

    def new_histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram,
                         window_seconds=self.window_seconds)

    # medida-style multi-part names: NewTimer({"ledger","transaction","apply"})
    def counter(self, *parts: str) -> Counter:
        return self.new_counter(".".join(parts))

    def meter(self, *parts: str) -> Meter:
        return self.new_meter(".".join(parts))

    def timer(self, *parts: str) -> Timer:
        return self.new_timer(".".join(parts))

    def histogram(self, *parts: str) -> Histogram:
        return self.new_histogram(".".join(parts))

    def to_json(self) -> dict:
        return {name: m.to_json() for name, m in sorted(self._metrics.items())}

    def clear(self) -> None:
        """Reset every metric IN PLACE (reference: clearMetrics clears
        each medida metric, it does not deregister). Subsystems cache
        metric objects at construction (apply/close timers, the e2e
        timer, per-peer meters); emptying the registry dict would
        orphan those references — still counting, never reported."""
        for m in self._metrics.values():
            m.reset()


# ------------------------------------------------- Prometheus exposition --

def _prom_name(name: str) -> str:
    """Sanitize a dotted medida name into a Prometheus metric name:
    `ledger.transaction.apply` → `ledger_transaction_apply`."""
    out = "".join(c if c.isalnum() or c in "_:" else "_" for c in name)
    if out and not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


def _prom_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"') \
                .replace("\n", "\\n")


def _prom_num(v) -> str:
    f = float(v)
    if f != f:                       # NaN never reaches a scraper
        return "0"
    return repr(f) if not float(f).is_integer() else str(int(f))


def render_prometheus(metrics_json: Dict[str, dict],
                      zones: Optional[Dict[str, dict]] = None) -> str:
    """Render a MetricsRegistry.to_json() document (plus an optional
    ZoneRegistry.report()) in Prometheus text exposition format 0.0.4,
    for `metrics?format=prometheus` scraping.

    Mapping: counters are gauges (ours can dec); meters are a
    `<name>_total` counter plus `<name>_rate{window=…}` gauges; timers
    and histograms are summaries — quantiles as labeled samples plus
    `_count`/`_sum` (timers in seconds, `_seconds` suffix). Perf zones
    ride along as three labeled gauge families keyed by `zone=`.
    """
    lines: List[str] = []

    def family(pname: str, mtype: str, help_text: str) -> None:
        lines.append(f"# HELP {pname} {help_text}")
        lines.append(f"# TYPE {pname} {mtype}")

    for name in sorted(metrics_json):
        doc = metrics_json[name]
        p = _prom_name(name)
        t = doc.get("type")
        if t == "counter":
            family(p, "gauge", f"counter {name}")
            lines.append(f"{p} {_prom_num(doc['count'])}")
        elif t == "meter":
            family(f"{p}_total", "counter", f"meter {name} event count")
            lines.append(f"{p}_total {_prom_num(doc['count'])}")
            family(f"{p}_rate", "gauge",
                   f"meter {name} rates (events/sec)")
            lines.append(f'{p}_rate{{window="mean"}} '
                         f"{_prom_num(doc['mean_rate'])}")
            for window in ("1_min", "5_min", "15_min"):
                if f"{window}_rate" in doc:
                    lines.append(
                        f'{p}_rate{{window="{window[:-4]}m"}} '
                        f"{_prom_num(doc[f'{window}_rate'])}")
        elif t in ("timer", "histogram"):
            unit = "_seconds" if t == "timer" else ""
            family(f"{p}{unit}", "summary",
                   f"{t} {name}" + (" (seconds)" if unit else ""))
            for label, key in (("0.5", "median"), ("0.75", "75%"),
                               ("0.99", "99%")):
                lines.append(f'{p}{unit}{{quantile="{label}"}} '
                             f"{_prom_num(doc[key])}")
            lines.append(f"{p}{unit}_count {_prom_num(doc['count'])}")
            total = doc.get("sum", doc["mean"] * doc["count"])
            lines.append(f"{p}{unit}_sum {_prom_num(total)}")
            if t == "timer" and "buckets" in doc:
                # cumulative histogram family beside the summary: the
                # summary's quantile labels cannot be aggregated across
                # nodes, the fixed-bound buckets can (kept as a SEPARATE
                # `_hist` family — one family cannot be TYPEd twice)
                b = doc["buckets"]
                family(f"{p}{unit}_hist", "histogram",
                       f"timer {name} cumulative histogram (seconds)")
                for bound, c in zip(b["le"], b["cumulative"]):
                    lines.append(
                        f'{p}{unit}_hist_bucket{{le="{_prom_num(bound)}"'
                        f"}} {_prom_num(c)}")
                lines.append(f'{p}{unit}_hist_bucket{{le="+Inf"}} '
                             f"{_prom_num(doc['count'])}")
                lines.append(
                    f"{p}{unit}_hist_count {_prom_num(doc['count'])}")
                lines.append(f"{p}{unit}_hist_sum {_prom_num(total)}")
            if t == "timer":
                rate = doc.get("rate", {})
                family(f"{p}_rate", "gauge",
                       f"timer {name} throughput (events/sec)")
                for window, key in (("mean", "mean_rate"),
                                    ("1m", "1_min_rate"),
                                    ("5m", "5_min_rate"),
                                    ("15m", "15_min_rate")):
                    if key in rate:
                        lines.append(f'{p}_rate{{window="{window}"}} '
                                     f"{_prom_num(rate[key])}")
    if zones:
        family("perf_zone_count", "gauge",
               "perf zone hit count (util/perf.py)")
        for zname in sorted(zones):
            lines.append(f'perf_zone_count{{zone="{_prom_label(zname)}"}}'
                         f' {_prom_num(zones[zname]["count"])}')
        family("perf_zone_total_seconds", "gauge",
               "perf zone cumulative time")
        for zname in sorted(zones):
            lines.append(
                f'perf_zone_total_seconds{{zone="{_prom_label(zname)}"}} '
                f"{_prom_num(zones[zname]['total_ms'] / 1000.0)}")
        family("perf_zone_max_seconds", "gauge",
               "perf zone worst single hit")
        for zname in sorted(zones):
            lines.append(
                f'perf_zone_max_seconds{{zone="{_prom_label(zname)}"}} '
                f"{_prom_num(zones[zname]['max_ms'] / 1000.0)}")
    return "\n".join(lines) + "\n"
