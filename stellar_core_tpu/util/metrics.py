"""Metrics registry — counters / meters / timers / histograms.

Reference: libmedida (lib/libmedida) as catalogued in docs/metrics.md (e.g.
`ledger.transaction.apply` timer, `scp.envelope.receive`, `overlay.flood.*`).
Exposed over the HTTP admin `metrics` endpoint and resettable via
`clearmetrics` (main/CommandHandler.cpp:114).
"""

from __future__ import annotations

import bisect
import math
import random
import time
from collections import deque
from typing import Dict, List, Optional

from .checks import releaseAssert


class Counter:
    def __init__(self):
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n

    def dec(self, n: int = 1) -> None:
        self.count -= n

    def set_count(self, n: int) -> None:
        self.count = n

    def to_json(self) -> dict:
        return {"type": "counter", "count": self.count}


class Meter:
    """Event rate meter with 1m/5m/15m EWMA rates (medida::Meter)."""

    _ALPHAS = {"1m": 1 - math.exp(-5.0 / 60),
               "5m": 1 - math.exp(-5.0 / 300),
               "15m": 1 - math.exp(-5.0 / 900)}

    def __init__(self, event_type: str = "event"):
        self.count = 0
        self.event_type = event_type
        self._rates = {k: 0.0 for k in self._ALPHAS}
        self._rates_initialized = False
        self._uncounted = 0
        self._start = self._last_tick = time.monotonic()

    def mark(self, n: int = 1) -> None:
        self._maybe_tick()
        self.count += n
        self._uncounted += n

    def _maybe_tick(self) -> None:
        now = time.monotonic()
        elapsed = now - self._last_tick
        if elapsed >= 5.0:
            ticks = int(elapsed // 5.0)
            inst = self._uncounted / elapsed
            self._uncounted = 0
            if not self._rates_initialized:
                # seed EWMAs with the first observed rate (Codahale/medida
                # convention) so early readings aren't ~alpha-times too low
                for k in self._ALPHAS:
                    self._rates[k] = inst
                self._rates_initialized = True
                ticks -= 1
                inst = 0.0
            for _ in range(min(ticks, 200)):
                for k, a in self._ALPHAS.items():
                    self._rates[k] += a * (inst - self._rates[k])
                inst = 0.0 if ticks > 1 else inst
            self._last_tick = now

    def mean_rate(self) -> float:
        dt = time.monotonic() - self._start
        return self.count / dt if dt > 0 else 0.0

    def one_minute_rate(self) -> float:
        self._maybe_tick()
        return self._rates["1m"]

    def to_json(self) -> dict:
        return {"type": "meter", "count": self.count,
                "mean_rate": self.mean_rate(),
                "1_min_rate": self.one_minute_rate()}


class Histogram:
    """Reservoir-sampled histogram (uniform reservoir,
    medida::Histogram); with `window_seconds` set, percentiles/mean/
    min/max reflect only the sliding window (reference:
    HISTOGRAM_WINDOW_SIZE — medida's sliding-window sample)."""

    def __init__(self, reservoir: int = 1028, seed: int = 0,
                 window_seconds: Optional[float] = None):
        self._reservoir = reservoir
        self._sample: List[float] = []
        self.count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._rng = random.Random(seed)
        self._window = window_seconds
        # bounded like medida's sliding-window sample: the window keeps
        # at most _reservoir recent events, so hot per-tx timers cannot
        # grow without bound
        self._events = deque(maxlen=reservoir)

    def update(self, value: float) -> None:
        self.count += 1
        self._sum += value
        self._min = min(self._min, value)
        self._max = max(self._max, value)
        if self._window is not None:
            now = time.monotonic()
            self._events.append((now, value))
            self._prune(now)
            return
        if len(self._sample) < self._reservoir:
            bisect.insort(self._sample, value)
        else:
            i = self._rng.randrange(self.count)
            if i < self._reservoir:
                del self._sample[self._rng.randrange(len(self._sample))]
                bisect.insort(self._sample, value)

    def _prune(self, now: float) -> None:
        cutoff = now - self._window
        ev = self._events
        while ev and ev[0][0] < cutoff:
            ev.popleft()

    def _window_values(self) -> List[float]:
        self._prune(time.monotonic())
        return sorted(v for _, v in self._events)

    @staticmethod
    def _pctl(sample: List[float], q: float) -> float:
        if not sample:
            return 0.0
        idx = min(len(sample) - 1, int(q * len(sample)))
        return sample[idx]

    def percentile(self, q: float) -> float:
        sample = self._window_values() if self._window is not None \
            else self._sample
        return self._pctl(sample, q)

    def mean(self) -> float:
        if self._window is not None:
            vals = self._window_values()
            return sum(vals) / len(vals) if vals else 0.0
        return self._sum / self.count if self.count else 0.0

    def to_json(self) -> dict:
        if self._window is not None:
            # ONE sort serves every stat, and min/max/mean reflect the
            # window like the percentiles do (lifetime totals would
            # contradict the window semantics operators read)
            vals = self._window_values()
            return {"type": "histogram", "count": self.count,
                    "mean": sum(vals) / len(vals) if vals else 0.0,
                    "min": vals[0] if vals else 0,
                    "max": vals[-1] if vals else 0,
                    "median": self._pctl(vals, 0.5),
                    "75%": self._pctl(vals, 0.75),
                    "99%": self._pctl(vals, 0.99)}
        return {"type": "histogram", "count": self.count, "mean": self.mean(),
                "min": self._min if self.count else 0,
                "max": self._max if self.count else 0,
                "median": self.percentile(0.5),
                "75%": self.percentile(0.75), "99%": self.percentile(0.99)}


class Timer(Histogram):
    """Duration metric: histogram of seconds + throughput meter."""

    def __init__(self, window_seconds: Optional[float] = None):
        super().__init__(window_seconds=window_seconds)
        self.meter = Meter()

    def update(self, seconds: float) -> None:  # type: ignore[override]
        super().update(seconds)
        self.meter.mark()

    def time_scope(self):
        return _TimerScope(self)

    def to_json(self) -> dict:
        j = super().to_json()
        j["type"] = "timer"
        j["rate"] = self.meter.to_json()
        return j


class _TimerScope:
    def __init__(self, timer: Timer):
        self._timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Dotted-name metric registry (reference: medida::MetricsRegistry)."""

    def __init__(self, window_minutes: Optional[float] = None):
        self._metrics: Dict[str, object] = {}
        # reference: HISTOGRAM_WINDOW_SIZE (minutes) — applied to every
        # histogram/timer created through this registry
        self.window_seconds = (window_minutes * 60.0
                               if window_minutes else None)

    def _get(self, name: str, cls, *args, **kw):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(*args, **kw)
        releaseAssert(type(m) is cls, f"metric {name} type mismatch")
        return m

    def new_counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def new_meter(self, name: str, event_type: str = "event") -> Meter:
        return self._get(name, Meter, event_type)

    def new_timer(self, name: str) -> Timer:
        return self._get(name, Timer,
                         window_seconds=self.window_seconds)

    def new_histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram,
                         window_seconds=self.window_seconds)

    # medida-style multi-part names: NewTimer({"ledger","transaction","apply"})
    def counter(self, *parts: str) -> Counter:
        return self.new_counter(".".join(parts))

    def meter(self, *parts: str) -> Meter:
        return self.new_meter(".".join(parts))

    def timer(self, *parts: str) -> Timer:
        return self.new_timer(".".join(parts))

    def histogram(self, *parts: str) -> Histogram:
        return self.new_histogram(".".join(parts))

    def to_json(self) -> dict:
        return {name: m.to_json() for name, m in sorted(self._metrics.items())}

    def clear(self) -> None:
        self._metrics.clear()
