"""Util / runtime layer (reference: src/util — SURVEY.md layer 1)."""

from .timer import VirtualClock, VirtualTimer, ClockMode
from .scheduler import Scheduler, ActionType
from .cache import RandomEvictionCache
from .checks import releaseAssert, AssertionFailed

__all__ = [
    "VirtualClock",
    "VirtualTimer",
    "ClockMode",
    "Scheduler",
    "ActionType",
    "RandomEvictionCache",
    "releaseAssert",
    "AssertionFailed",
]
