"""Hard runtime checks (reference: src/util/GlobalChecks.h).

The reference crashes the node on invariant failure (releaseAssert/dbgAbort);
we raise a dedicated exception type that top-level drivers treat as fatal.
"""


class AssertionFailed(RuntimeError):
    """Raised when a release-mode assertion fails (reference: util/GlobalChecks.h)."""


def releaseAssert(cond: bool, msg: str = "releaseAssert failed") -> None:
    if not cond:
        raise AssertionFailed(msg)
