"""Lightweight performance zones + slow-execution warnings.

Reference: §5.1 of the survey — the reference vendors the Tracy frame
profiler (602 ``ZoneScoped`` annotations, crypto/SecretKey.cpp:431 etc.)
and a ``LogSlowExecution`` scope timer (util/LogSlowExecution.h, used in
closeLedger :711).  Tracy needs a native GUI protocol; the TPU-native
equivalent is an in-process zone registry: cheap monotonic timers
aggregated per zone (count/total/max), dumped via the admin API or
logged.  JAX device work is profiled separately with jax.profiler; these
zones cover the host-side runtime.

Each ``Application`` owns a ``ZoneRegistry`` so multi-node in-process
simulations don't cross-contaminate; the module-level helpers use a
process default registry for contexts with no app (CLI tools, library
calls).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, Optional

from . import tracing
from .logging import get_logger

log = get_logger("Perf")


class _ZoneStats:
    __slots__ = ("count", "total", "max")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.max = 0.0


class ZoneRegistry:
    def __init__(self):
        self._zones: Dict[str, _ZoneStats] = {}
        self._lock = threading.Lock()
        # the app's FlightRecorder (util/tracing.py), set by
        # Application: when it is recording, every zone ALSO emits a
        # begin/end span pair so the timeline gets the close phases,
        # completion jobs, bucket merges and verifier batches for free
        self.tracer = None

    @contextmanager
    def zone(self, name: str, targs: Optional[dict] = None):
        """Scoped timing zone (reference: Tracy ZoneScoped). `targs`
        are structured span args (ledger seq, tx count, …) recorded
        only while a trace is on — pass them pre-guarded by
        ``tracing.ENABLED`` so the disabled path allocates nothing."""
        tr = None
        if tracing.ENABLED:
            tr = self.tracer
            if tr is not None and tr.active:
                tr.begin(name, targs)
            else:
                tr = None
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if tr is not None:
                tr.end(name)
            with self._lock:
                st = self._zones.get(name)
                if st is None:
                    st = self._zones[name] = _ZoneStats()
                st.count += 1
                st.total += dt
                if dt > st.max:
                    st.max = dt

    @contextmanager
    def zone_into(self, name: str, sink: Optional[dict] = None,
                  targs: Optional[dict] = None):
        """A zone that ALSO accumulates its duration into `sink[name]`
        — the per-close phase breakdown the slow-execution log prints,
        so a 2.5 s stall names the guilty phase instead of one opaque
        number."""
        t0 = time.perf_counter()
        try:
            with self.zone(name, targs=targs):
                yield
        finally:
            if sink is not None:
                sink[name] = sink.get(name, 0.0) + \
                    (time.perf_counter() - t0)

    @contextmanager
    def log_slow_execution(self, name: str,
                           threshold_seconds: float = 1.0,
                           detail: Optional[Callable[[], str]] = None):
        """Warn when a scope overruns (reference:
        util/LogSlowExecution.h). `detail` (evaluated only on overrun)
        appends a breakdown, e.g. the per-phase times of a slow close."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if dt > threshold_seconds:
                extra = ""
                if detail is not None:
                    try:
                        extra = " [%s]" % detail()
                    except Exception:   # noqa: BLE001 — best-effort log
                        pass
                log.warning("performance issue: %s took %.0f ms%s", name,
                            dt * 1000, extra)

    def report(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {
                    "count": st.count,
                    "total_ms": round(st.total * 1000, 3),
                    "mean_ms": round(st.total / st.count * 1000, 3)
                    if st.count else 0.0,
                    "max_ms": round(st.max * 1000, 3),
                }
                for name, st in sorted(self._zones.items())
            }

    def reset(self) -> None:
        with self._lock:
            self._zones.clear()


# process-default registry for app-less contexts
default_registry = ZoneRegistry()


def zone(name: str):
    return default_registry.zone(name)


def log_slow_execution(name: str, threshold_seconds: float = 1.0):
    return default_registry.log_slow_execution(name, threshold_seconds)


def zone_report() -> Dict[str, dict]:
    return default_registry.report()


def reset_zones() -> None:
    default_registry.reset()
