"""xdrquery: a small query DSL over declarative XDR types.

Reference: src/util/xdrquery/ (XDRQuery.h:30-35, XDRFieldResolver.h:365-380,
XDRQueryEval.h) — queries are boolean expressions over dotted field paths
into an XDR message, e.g.::

    data.account.balance >= 100000 || data.trustLine.balance < 5000

Semantics (matching the reference's test suite):
- Walking through a union selects the active arm; naming a *valid but
  inactive* arm resolves to MISSING and every comparison on it is false.
- A path ending on an unset optional resolves to NULL; ``== NULL`` /
  ``!= NULL`` are the only comparisons allowed against the NULL literal.
- Leaf conversions mirror XDR-to-JSON: enums → their name strings,
  public keys → strkey ('G...'), fixed opaques → hex strings, Assets →
  virtual {assetCode, issuer} (+ liquidityPoolID for pool shares), a
  union's discriminant is addressable by its switch name (``type``).
- Integer literals are range-checked against the field's XDR type;
  comparing a string to an int field (or vice versa) is an error.

The reference parses with flex/bison; here a hand-rolled tokenizer +
recursive-descent parser (grammar: ``or := and ('||' and)*``,
``and := cmp ('&&' cmp)*``, ``cmp := operand OP operand | '(' or ')'``)
keeps it dependency-free.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

from ..xdr.runtime import (EnumType, Opaque, Optional as XdrOptional,
                           Struct, Union, VarOpaque, XdrString, _Bool,
                           _Composite, _Int32, _Int64, _Uint32, _Uint64)


def _norm(t: Any) -> Any:
    """Unwrap the runtime's _Composite adapter to the Struct/Union
    class it wraps."""
    return t.cls if isinstance(t, _Composite) else t


class XDRQueryError(Exception):
    """Raised on parse errors, invalid field paths, or type mismatches."""


class _Missing:
    """Union arm not selected — comparisons are always false."""

    def __repr__(self) -> str:
        return "MISSING"


class _Null:
    """Optional field not set — equal only to the NULL literal."""

    def __repr__(self) -> str:
        return "NULL"


MISSING = _Missing()
NULL = _Null()

_INT_RANGES = {
    _Int32: (-2**31, 2**31 - 1),
    _Uint32: (0, 2**32 - 1),
    _Int64: (-2**63, 2**63 - 1),
    _Uint64: (0, 2**64 - 1),
}

# the switch name unions are addressable by (reference: xdrpp names the
# discriminant after the union's switch declaration; stellar XDR uses
# `type` for every union our queries target)
_SWITCH_NAME = "type"

_ASSET_LEAVES = ("assetCode", "issuer", "liquidityPoolID")


def _is_asset_union(t: Any) -> bool:
    """Asset / TrustLineAsset / ChangeTrustAsset unions get a simplified
    {assetCode, issuer[, liquidityPoolID]} view
    (reference: XDRFieldResolver.h:340-354)."""
    t = _norm(t)
    if not (isinstance(t, type) and issubclass(t, Union)):
        return False
    arm_names = {arm[0] for arm in t._ARMS.values() if arm is not None}
    return "alphaNum4" in arm_names


def _is_public_key(t: Any) -> bool:
    from ..xdr.types import PublicKey
    t = _norm(t)
    return isinstance(t, type) and issubclass(t, PublicKey)


def _leaf_value(value: Any, t: Any) -> Any:
    """Convert a resolved leaf to its query representation."""
    from ..crypto.strkey import StrKey
    if _is_public_key(t):
        return StrKey.encode_ed25519_public(bytes(value.value))
    if isinstance(t, EnumType):
        return t.enum_cls(value).name
    if isinstance(t, XdrString):
        return bytes(value).decode("utf-8", "replace")
    if isinstance(t, Opaque):
        return bytes(value).hex()
    if isinstance(t, VarOpaque):
        return bytes(value).hex()
    if isinstance(t, _Bool):
        return bool(value)
    if isinstance(t, (_Int32, _Uint32, _Int64, _Uint64)):
        return int(value)
    raise XDRQueryError(
        f"field of type {getattr(t, '__name__', type(t).__name__)} "
        "is not a comparable leaf")


def _leaf_kind(t: Any) -> str:
    if isinstance(t, (_Int32, _Uint32, _Int64, _Uint64)):
        return "int"
    if isinstance(t, _Bool):
        return "bool"
    return "str"


def _asset_leaf(value: Any, t: Any, comp: str) -> Tuple[Any, Any]:
    """Resolve assetCode/issuer/liquidityPoolID on an asset union."""
    from ..crypto.strkey import StrKey
    arm_name = value.arm_name
    if comp == "liquidityPoolID":
        if arm_name in ("liquidityPoolID", "liquidityPool"):
            return bytes(value.value).hex(), Opaque(32)
        return MISSING, Opaque(32)
    if arm_name not in ("alphaNum4", "alphaNum12"):
        return MISSING, XdrString()
    alpha = value.value
    if comp == "assetCode":
        code = bytes(alpha.assetCode).rstrip(b"\x00")
        return code.decode("utf-8", "replace"), XdrString()
    return StrKey.encode_ed25519_public(
        bytes(alpha.issuer.value)), XdrString()


def validate_path(t: Any, path: Sequence[str]) -> Any:
    """Statically check `path` against type `t`, exploring every union
    arm; returns the leaf's XdrType-ish descriptor.  Raises
    XDRQueryError when no arm makes the path valid (reference:
    getXDRFieldValidated)."""
    t = _norm(t)
    if isinstance(t, XdrOptional):
        return validate_path(t.elem, path)
    if not path:
        if _is_public_key(t):
            return XdrString()
        if isinstance(t, (EnumType, XdrString, Opaque, VarOpaque, _Bool,
                          _Int32, _Uint32, _Int64, _Uint64)):
            return t
        raise XDRQueryError("field path ends on a non-leaf value")
    comp, rest = path[0], path[1:]
    if isinstance(t, type) and issubclass(t, Struct):
        for fn, ft in t._FIELDS:
            if fn == comp:
                return validate_path(ft, rest)
        raise XDRQueryError(f"invalid field '{comp}'")
    if _is_asset_union(t) and comp in _ASSET_LEAVES:
        if rest:
            raise XDRQueryError(f"'{comp}' is a leaf field")
        return XdrString() if comp != "liquidityPoolID" else Opaque(32)
    if isinstance(t, type) and issubclass(t, Union):
        if comp == _SWITCH_NAME:
            if rest:
                raise XDRQueryError(f"'{_SWITCH_NAME}' is a leaf field")
            return t._SWITCH
        for arm in t._ARMS.values():
            if arm is None or arm[1] is None:
                continue
            if arm[0] == comp:
                return validate_path(arm[1], rest)
        raise XDRQueryError(f"invalid field '{comp}'")
    raise XDRQueryError(f"invalid field path at '{comp}'")


def resolve_field(obj: Any, path: Sequence[str]) -> Tuple[Any, Any]:
    """Resolve a dotted path against an XDR message instance.

    Returns (value, leaf_type) where value may be MISSING (union arm not
    selected) or NULL (optional unset)."""
    t: Any = type(obj)
    value: Any = obj
    i = 0
    while i < len(path):
        t = _norm(t)
        comp = path[i]
        if isinstance(t, type) and issubclass(t, Struct):
            ft = None
            for fn, ft_ in t._FIELDS:
                if fn == comp:
                    ft = ft_
                    break
            if ft is None:
                raise XDRQueryError(f"invalid field '{comp}'")
            value = getattr(value, comp)
            t = ft
            if isinstance(t, XdrOptional):
                if value is None:
                    if i + 1 != len(path):
                        raise XDRQueryError(
                            f"invalid field path past unset '{comp}'")
                    return NULL, t.elem
                t = t.elem
            i += 1
            continue
        if _is_asset_union(t) and comp in _ASSET_LEAVES:
            if i + 1 != len(path):
                raise XDRQueryError(f"'{comp}' is a leaf field")
            return _asset_leaf(value, t, comp)
        if isinstance(t, type) and issubclass(t, Union):
            if comp == _SWITCH_NAME:
                if i + 1 != len(path):
                    raise XDRQueryError(f"'{_SWITCH_NAME}' is a leaf")
                disc = value.disc
                if isinstance(t._SWITCH, EnumType):
                    return t._SWITCH.enum_cls(disc).name, t._SWITCH
                return int(disc), t._SWITCH
            arm = t._ARMS.get(value.disc)
            active_name = arm[0] if arm is not None else None
            if comp == active_name:
                t = arm[1]
                value = value.value
                i += 1
                continue
            # valid-but-inactive arm → MISSING; still validate statically
            for a in t._ARMS.values():
                if a is not None and a[0] == comp and a[1] is not None:
                    leaf = validate_path(a[1], path[i + 1:])
                    return MISSING, leaf
            raise XDRQueryError(f"invalid field '{comp}'")
        raise XDRQueryError(f"invalid field path at '{comp}'")
    return _leaf_value(value, _norm(t)), _norm(t)


# ---------------------------------------------------------------------------
# Tokenizer / parser
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<op>==|!=|<=|>=|<|>|\|\||&&|\(|\)|,)
    | (?P<int>-?\d+)(?![\w.])
    | '(?P<sq>[^']*)'
    | "(?P<dq>[^"]*)"
    | (?P<path>[A-Za-z_][A-Za-z_0-9]*(?:\.[A-Za-z_][A-Za-z_0-9]*)*)
    )""", re.VERBOSE)


def _tokenize(query: str) -> List[Tuple[str, Any]]:
    tokens: List[Tuple[str, Any]] = []
    pos = 0
    while pos < len(query):
        m = _TOKEN_RE.match(query, pos)
        if m is None or m.end() == pos:
            rest = query[pos:].strip()
            if not rest:
                break
            raise XDRQueryError(f"syntax error near '{rest[:20]}'")
        if m.group("op"):
            tokens.append(("op", m.group("op")))
        elif m.group("int") is not None:
            tokens.append(("int", int(m.group("int"))))
        elif m.group("sq") is not None:
            tokens.append(("str", m.group("sq")))
        elif m.group("dq") is not None:
            tokens.append(("str", m.group("dq")))
        else:
            p = m.group("path")
            if p == "NULL":
                tokens.append(("null", None))
            else:
                tokens.append(("path", p.split(".")))
        pos = m.end()
    return tokens


class _Comparison:
    _OPS = {
        "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
        "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
        ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
    }

    def __init__(self, left, op, right):
        self.left, self.op, self.right = left, op, right
        self._validated = False

    def _operand(self, node, obj):
        kind, v = node
        if kind == "path":
            return resolve_field(obj, v)
        return v, kind

    def _check_types(self, lv, lt, rv, rt) -> None:
        """First-evaluation validation (reference: XDRMatcher lazy
        parse + validate)."""
        sides = [(lv, lt), (rv, rt)]
        for (v, t), (ov, ot) in (sides, sides[::-1]):
            if not hasattr(t, "pack"):  # literal
                continue
            # t is an XdrType leaf descriptor; other side must agree
            if hasattr(ot, "pack"):
                if _leaf_kind(t) != _leaf_kind(ot):
                    raise XDRQueryError(
                        "type mismatch: cannot compare "
                        f"{_leaf_kind(t)} field with {_leaf_kind(ot)} "
                        "field")
                continue
            if ot == "null":
                if self.op not in ("==", "!="):
                    raise XDRQueryError(
                        "NULL only supports == and != comparisons")
                continue
            kind = _leaf_kind(t)
            if ot == "int":
                if kind != "int":
                    raise XDRQueryError(
                        "type mismatch: int literal vs non-int field")
                rng = _INT_RANGES.get(type(t))
                if rng and not rng[0] <= ov <= rng[1]:
                    raise XDRQueryError(
                        f"int literal {ov} out of range for field")
            elif ot == "str" and kind != "str":
                raise XDRQueryError(
                    "type mismatch: string literal vs non-string field")

    def eval(self, obj) -> bool:
        lv, lt = self._operand(self.left, obj)
        rv, rt = self._operand(self.right, obj)
        if not self._validated:
            # statically validate paths across all union arms once
            for kind, v in (self.left, self.right):
                if kind == "path":
                    validate_path(type(obj), v)
            self._check_types(lv, lt, rv, rt)
            self._validated = True
        if lv is MISSING or rv is MISSING:
            return False
        ln = lv is NULL or (self.left[0] == "null")
        rn = rv is NULL or (self.right[0] == "null")
        if ln or rn:
            if self.op == "==":
                return ln and rn
            if self.op == "!=":
                return ln != rn
            raise XDRQueryError("NULL only supports == and !=")
        return self._OPS[self.op](lv, rv)


class _BoolOp:
    def __init__(self, op, children):
        self.op, self.children = op, children

    def eval(self, obj) -> bool:
        if self.op == "&&":
            return all(c.eval(obj) for c in self.children)
        return any(c.eval(obj) for c in self.children)


class _Parser:
    def __init__(self, tokens: List[Tuple[str, Any]]):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else None

    def next(self):
        tok = self.peek()
        if tok is None:
            raise XDRQueryError("unexpected end of query")
        self.pos += 1
        return tok

    def expect_op(self, op: str) -> None:
        tok = self.next()
        if tok != ("op", op):
            raise XDRQueryError(f"expected '{op}'")

    def parse_expr(self):
        node = self.parse_and()
        children = [node]
        while self.peek() == ("op", "||"):
            self.next()
            children.append(self.parse_and())
        return children[0] if len(children) == 1 else _BoolOp("||", children)

    def parse_and(self):
        node = self.parse_primary()
        children = [node]
        while self.peek() == ("op", "&&"):
            self.next()
            children.append(self.parse_primary())
        return children[0] if len(children) == 1 else _BoolOp("&&", children)

    def parse_primary(self):
        if self.peek() == ("op", "("):
            self.next()
            node = self.parse_expr()
            self.expect_op(")")
            return node
        return self.parse_comparison()

    def parse_operand(self):
        kind, v = self.next()
        if kind in ("int", "str", "path", "null"):
            return (kind, v)
        raise XDRQueryError(f"unexpected token {v!r}")

    def parse_comparison(self):
        left = self.parse_operand()
        tok = self.next()
        if tok[0] != "op" or tok[1] not in _Comparison._OPS:
            raise XDRQueryError("expected comparison operator")
        right = self.parse_operand()
        return _Comparison(left, tok[1], right)


class XDRMatcher:
    """Match XDR messages against a boolean query
    (reference: XDRQuery.h:36-66)."""

    def __init__(self, query: str):
        self.query = query
        self._root = None

    def match_xdr(self, obj: Any) -> bool:
        if self._root is None:
            parser = _Parser(_tokenize(self.query))
            root = parser.parse_expr()
            if parser.peek() is not None:
                raise XDRQueryError("trailing tokens in query")
            if isinstance(root, _Comparison) or isinstance(root, _BoolOp):
                self._root = root
            else:
                raise XDRQueryError("the query doesn't evaluate to bool")
        return self._root.eval(obj)


class XDRFieldExtractor:
    """Extract comma-separated leaf fields
    (reference: XDRQuery.h:68-100)."""

    def __init__(self, query: str):
        self.paths: List[List[str]] = []
        for part in query.split(","):
            part = part.strip()
            if not part:
                raise XDRQueryError("empty field in extractor query")
            toks = _tokenize(part)
            if len(toks) != 1 or toks[0][0] != "path":
                raise XDRQueryError(f"not a field path: '{part}'")
            self.paths.append(toks[0][1])
        self._validated = False

    def field_names(self) -> List[str]:
        return [".".join(p) for p in self.paths]

    def extract_fields(self, obj: Any) -> List[Any]:
        if not self._validated:
            for p in self.paths:
                validate_path(type(obj), p)
            self._validated = True
        out = []
        for p in self.paths:
            v, _ = resolve_field(obj, p)
            out.append(None if v is MISSING or v is NULL else v)
        return out


# ---------------------------------------------------------------------------
# Accumulators (reference: XDRQueryEval.h:163-200 — sum/avg/count)
# ---------------------------------------------------------------------------

_AGG_RE = re.compile(
    r"\s*(sum|avg|count)\s*\(\s*([A-Za-z_0-9.]*)\s*\)\s*$")


class XDRAccumulator:
    """Aggregate leaf fields over a stream of messages; the aggregate
    query is comma-separated `sum(path)` / `avg(path)` / `count()`."""

    def __init__(self, query: str):
        self.parts: List[Tuple[str, Optional[List[str]]]] = []
        for part in query.split(","):
            m = _AGG_RE.match(part)
            if m is None:
                raise XDRQueryError(f"bad accumulator: '{part.strip()}'")
            op, path = m.group(1), m.group(2)
            if op == "count":
                if path:
                    raise XDRQueryError("count() takes no field")
                self.parts.append((op, None))
            else:
                if not path:
                    raise XDRQueryError(f"{op}() needs a field")
                self.parts.append((op, path.split(".")))
        self._sums = [0] * len(self.parts)
        self._counts = [0] * len(self.parts)

    def add_entry(self, obj: Any) -> None:
        for i, (op, path) in enumerate(self.parts):
            if op == "count":
                self._counts[i] += 1
                continue
            v, t = resolve_field(obj, path)
            if v is MISSING or v is NULL:
                continue
            if not isinstance(v, (int, bool)) or isinstance(v, bool):
                raise XDRQueryError(
                    f"{op}({'.'.join(path)}) needs an integer field")
            self._sums[i] += v
            self._counts[i] += 1

    def get_values(self) -> "dict[str, Any]":
        out: "dict[str, Any]" = {}
        for i, (op, path) in enumerate(self.parts):
            if op == "count":
                out["count"] = self._counts[i]
            elif op == "sum":
                out[f"sum({'.'.join(path)})"] = self._sums[i]
            else:
                avg = (self._sums[i] / self._counts[i]
                       if self._counts[i] else 0.0)
                out[f"avg({'.'.join(path)})"] = avg
        return out
