"""CPython GC policy: keep full-heap collections off the close path.

Measured on the TPSMT leg (ISSUE 12): automatic generation-2
collections scanned the whole multi-app heap for 50-1600 ms apiece —
16.2 s of a 50 s measured window — and freed approximately nothing
(0-710 objects per pass), because the live set (ledger state, XDR type
tables, bucket indexes) only grows. Those pauses landed inside
`closeLedger` (the 3 s `fees`-phase outliers in the close-phase
report) and inside the overlay crank, where they also expire
single-flight FLOOD_DEMANDs that were answered promptly.

Policy (process-wide, installed once by the first Application):

- gen0/gen1 stay automatic — young-object churn is cheap to collect
  and actually yields garbage;
- the startup heap is frozen (`gc.freeze`) into the permanent
  generation so no future full collection re-walks imports, XDR type
  tables and constant pools;
- automatic gen2 collection is pushed out (threshold 1e6 instead of
  the heuristic) — a full scan may only run when something asks for
  it deliberately;
- `maintenance_collect()` runs the explicit full pass from the
  Maintainer's cron (reference: Maintainer::performMaintenance
  cadence, i.e. history-GC time, never close time) so reference
  cycles from long runs still get reclaimed.
"""

from __future__ import annotations

import gc

from .logging import get_logger

log = get_logger("Perf")

_installed = False


def install() -> bool:
    """Idempotent, process-wide. Returns True on the first install."""
    global _installed
    if _installed:
        return False
    _installed = True
    gc.collect()
    # everything alive at first-app construction is effectively
    # immortal (modules, XDR metaclass tables, jitted callables):
    # keep gen2 from ever re-scanning it
    gc.freeze()
    t0, t1, _t2 = gc.get_threshold()
    gc.set_threshold(t0, t1, 1_000_000)
    log.debug("gc policy installed: startup heap frozen, automatic "
              "full collections disabled")
    return True


def maintenance_collect() -> int:
    """Explicit full collection for maintenance windows (the sanctioned
    full-heap pass once `install` ran — the permanent generation stays
    excluded, so this scans only what the process allocated since
    startup). No re-freeze: freezing live node state (entry caches,
    flow-control queues) would make it immortal when it later becomes
    garbage. Returns the number of collected objects."""
    return gc.collect()


# reclaim cadence for app teardown: a full pass per shutdown measured
# ~150s across the 900-test suite (hundreds of app churns), while the
# leak window of deferring is a handful of dead app graphs — collect
# on the Nth teardown, not every one
TEARDOWN_COLLECT_EVERY = 8
_teardowns = 0


def teardown_collect(force: bool = False) -> int:
    """Application.shutdown hook: with automatic full collections
    disabled, torn-down apps' reference cycles (app↔herder↔overlay
    back-pointers) must be reclaimed HERE or a process that builds
    many short-lived apps — the test suite, multi-leg bench runs —
    accumulates every dead app until exit. Throttled to every
    `TEARDOWN_COLLECT_EVERY`th shutdown: the deferred window is a few
    dead app graphs, the saving is one full heap scan per test."""
    global _teardowns
    _teardowns += 1
    if not force and _teardowns % TEARDOWN_COLLECT_EVERY:
        return 0
    return gc.collect()
