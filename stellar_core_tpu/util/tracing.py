"""Flight recorder: event-level span tracing with Chrome-trace export.

Reference: the Tracy frame profiler the reference vendors (602
``ZoneScoped`` annotations, zone values carrying the ledger seq —
SURVEY.md §5.1). Tracy needs a native GUI protocol; the shippable
Python analogue is an in-process ring buffer of begin/end span events
(thread id, monotonic timestamp, structured args) dumped as Chrome
trace-event JSON, loadable in Perfetto / chrome://tracing.

Layering: ``util/perf.py``'s ZoneRegistry keeps the cheap always-on
count/total/max aggregates; when a FlightRecorder is recording, every
zone ALSO emits a begin/end event pair here, so the ``ledger.close.*``
phases, completion-queue jobs, bucket merges and device-verifier
batches appear on the timeline for free. Subsystems without zones
(overlay send/recv, SCP lifecycle, tx end-to-end tracks) instrument
directly against their Application's recorder.

Cost contract (mirrors ``chaos.ENABLED``): when no recorder in the
process is recording — the default, always in production — every
instrumented site executes exactly one module-level constant check
(``if tracing.ENABLED:``) and nothing else: no config lookup, no
function call, no allocation. ``FlightRecorder.start()`` /``stop()``
are the sole writers of the constant (refcounted: multi-node in-process
simulations record several apps at once).

Each ``Application`` owns one FlightRecorder so multi-node simulations
don't cross-contaminate; the recorder's ``pid``/``label`` separate
nodes into distinct Perfetto process tracks.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# ---------------------------------------------------------------- guard --
# Module-level constant guard: instrumented hot paths check ONLY this
# before paying anything. _retain()/_release() are the sole writers.
ENABLED = False
_active_count = 0
_state_lock = threading.Lock()

# default ring capacity: ~256k events ≈ tens of seconds of a busy node,
# a few MB of tuples — bounded no matter how long a trace stays on
DEFAULT_CAPACITY = 262_144


def _retain() -> None:
    global ENABLED, _active_count
    with _state_lock:
        _active_count += 1
        ENABLED = True


def _release() -> None:
    global ENABLED, _active_count
    with _state_lock:
        _active_count = max(0, _active_count - 1)
        if _active_count == 0:
            ENABLED = False


class FlightRecorder:
    """Per-Application ring buffer of trace events.

    Events are compact tuples ``(ph, name, ts, tid, args, id)`` with
    ``ph`` one of the Chrome trace-event phases we emit:

    - ``"B"``/``"E"`` — nested span begin/end on a thread track;
    - ``"i"`` — instant event (a point in time, e.g. one overlay send);
    - ``"b"``/``"e"`` — async track begin/end correlated by ``id``
      across threads (the tx end-to-end latency track).

    Appends are lock-free (deque append is atomic); the buffer is a
    ring, so a long recording keeps the newest events and counts what
    it overwrote.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 label: str = "", pid: int = 1):
        self.active = False
        self.label = label
        self.pid = pid
        self._capacity = capacity
        self._buf: deque = deque(maxlen=capacity)
        self._t0 = 0.0
        self._t0_wall = 0.0
        self._appended = 0
        self._lock = threading.Lock()   # start/stop/dump, not append

    # ----------------------------------------------------------- control --
    def start(self, capacity: Optional[int] = None) -> None:
        """Begin recording (admin route ``starttrace``). Clears any
        previous recording; flips the process-wide ENABLED constant."""
        with self._lock:
            if capacity is not None and capacity != self._capacity:
                self._capacity = max(1, capacity)
                self._buf = deque(maxlen=self._capacity)
            else:
                self._buf.clear()
            self._appended = 0
            self._t0 = time.perf_counter()
            # wall-clock anchor of the same instant: separate PROCESSES
            # have incomparable perf_counter domains, so the multi-
            # process trace merge aligns dumptrace exports by this
            # (util/tracemerge.merge_trace_docs)
            self._t0_wall = time.time()
            if not self.active:
                self.active = True
                _retain()

    def stop(self) -> dict:
        """Stop recording; the buffer stays dumpable until the next
        start(). Returns a summary for the admin route."""
        with self._lock:
            if self.active:
                self.active = False
                _release()
            return {"events": len(self._buf), "dropped": self.dropped,
                    "capacity": self._capacity}

    @property
    def dropped(self) -> int:
        return max(0, self._appended - len(self._buf))

    @property
    def t0(self) -> float:
        """perf_counter at the last start(): the zero of this
        recorder's timestamps. Recorders started at different times
        disagree on zero; util/tracemerge.py aligns a multi-node
        capture by shifting each node's events by (t0 - min t0)."""
        return self._t0

    def __len__(self) -> int:
        return len(self._buf)

    # ---------------------------------------------------------- recording --
    # Callers MUST pre-guard with ``if tracing.ENABLED:`` (and check
    # ``.active`` when several recorders share the process) so disabled
    # runs pay one module-constant read.
    def begin(self, name: str, args: Optional[dict] = None) -> None:
        self._appended += 1
        self._buf.append(("B", name, time.perf_counter() - self._t0,
                          threading.get_ident(), args, None))

    def end(self, name: Optional[str] = None) -> None:
        self._appended += 1
        self._buf.append(("E", name, time.perf_counter() - self._t0,
                          threading.get_ident(), None, None))

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        self._appended += 1
        self._buf.append(("i", name, time.perf_counter() - self._t0,
                          threading.get_ident(), args, None))

    def async_begin(self, name: str, correlation_id: str,
                    args: Optional[dict] = None) -> None:
        """Open an async span correlated by id — begin and end may land
        on different threads (tx submit → externalize)."""
        self._appended += 1
        self._buf.append(("b", name, time.perf_counter() - self._t0,
                          threading.get_ident(), args, correlation_id))

    def async_end(self, name: str, correlation_id: str,
                  args: Optional[dict] = None) -> None:
        self._appended += 1
        self._buf.append(("e", name, time.perf_counter() - self._t0,
                          threading.get_ident(), args, correlation_id))

    # ------------------------------------------------------------ export --
    def to_chrome_trace(self) -> dict:
        """Render the buffer as a Chrome trace-event JSON document
        (Perfetto / chrome://tracing / `scripts/trace_report.py`).

        The ring can orphan events (a "B" overwritten while its "E"
        survived, or spans still open at dump time); the dump
        reconciles per-thread so every emitted "B" has a matching "E"
        and per-thread timestamps are non-decreasing — consumers never
        see a malformed nesting.
        """
        with self._lock:
            events = sorted(self._buf, key=lambda e: e[2])
        out: List[dict] = []
        thread_names = {t.ident: t.name for t in threading.enumerate()}
        out.append({"ph": "M", "name": "process_name", "pid": self.pid,
                    "tid": 0, "args": {
                        "name": self.label or "stellar-core-tpu"}})
        named: set = set()
        open_stacks: Dict[int, List[dict]] = {}
        max_ts = events[-1][2] if events else 0.0
        for ph, name, ts, tid, args, cid in events:
            if tid not in named:
                named.add(tid)
                out.append({"ph": "M", "name": "thread_name",
                            "pid": self.pid, "tid": tid,
                            "args": {"name": thread_names.get(
                                tid, "thread-%d" % tid)}})
            ev = {"ph": ph, "name": name, "pid": self.pid, "tid": tid,
                  "ts": round(ts * 1e6, 3)}
            if ph == "B":
                ev["args"] = args or {}
                open_stacks.setdefault(tid, []).append(ev)
            elif ph == "E":
                stack = open_stacks.get(tid)
                if not stack:
                    continue        # orphaned end (begin overwritten)
                opened = stack.pop()
                if name is None:
                    ev["name"] = opened["name"]
            elif ph == "i":
                ev["s"] = "t"       # thread-scoped instant
                ev["args"] = args or {}
            else:                   # async b/e
                ev["cat"] = name.split(".", 1)[0]
                ev["id"] = cid
                ev["args"] = args or {}
            out.append(ev)
        # close anything still open, innermost first, at the dump edge
        for tid, stack in open_stacks.items():
            while stack:
                opened = stack.pop()
                out.append({"ph": "E", "name": opened["name"],
                            "pid": self.pid, "tid": tid,
                            "ts": round(max_ts * 1e6, 3)})
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped,
                              # cross-process merge metadata: label and
                              # wall-clock zero let merge_trace_docs
                              # align exports from separate node
                              # processes (in-process merges keep using
                              # the shared perf_counter t0)
                              "label": self.label,
                              "pid": self.pid,
                              "t0_wall": self._t0_wall}}


# process-default recorder for app-less contexts (CLI tools, scripts);
# mirrors perf.default_registry
default_recorder = FlightRecorder()
