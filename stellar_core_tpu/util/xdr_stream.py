"""Record-marked XDR file streams.

Reference: util/XDRStream.h — bucket files and history checkpoint files
are sequences of XDR records with RFC 5531 record marking: a 4-byte
big-endian length word with the high bit set (single-fragment records),
followed by the XDR payload.
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Iterator, Type


def write_record(f: BinaryIO, payload: bytes) -> None:
    f.write(struct.pack(">I", len(payload) | 0x80000000))
    f.write(payload)


def read_record(f: BinaryIO) -> bytes | None:
    hdr = f.read(4)
    if len(hdr) == 0:
        return None
    if len(hdr) != 4:
        raise IOError("truncated XDR record header")
    (word,) = struct.unpack(">I", hdr)
    if not word & 0x80000000:
        raise IOError("multi-fragment XDR records not supported")
    n = word & 0x7FFFFFFF
    payload = f.read(n)
    if len(payload) != n:
        raise IOError("truncated XDR record payload")
    return payload


def read_all(f: BinaryIO, cls: Type) -> Iterator:
    while True:
        raw = read_record(f)
        if raw is None:
            return
        yield cls.from_bytes(raw)
