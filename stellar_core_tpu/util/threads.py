"""Thread-domain declarations + opt-in runtime affinity assertions.

The static analyzer (stellar_core_tpu/analysis/, docs/ANALYSIS.md)
propagates *declared* thread domains through the call graph to find
cross-thread writes at analysis time. This module closes the loop at
runtime: entry points bind their thread to the declared domain, and
domain-sensitive code asserts it is running where the declaration says
it runs — so a wrong declaration (which would silently weaken the
static race check) fails a sim test instead of lying forever.

Domain names are the same four the analyzer knows, plus the worker
domains that grew since:

- ``crank``              the single logical main thread (VirtualClock)
- ``http``               admin-API socket threads (command_handler)
- ``completion-worker``  CloseCompletionQueue's FIFO worker
- ``verify-collect``     backend supervisor watchdog / collect helpers
- ``catchup-worker``     _AsyncResult batch-resolve threads
- ``pg-writer``          pg_stub's replication writer
- ``apply-worker``       staged-apply pool (ledger/parallel_apply.py)

Cost contract (same as ``chaos.ENABLED`` / ``tracing.ENABLED``): every
instrumented site pre-guards with ``if threads.CHECK:`` — one
module-constant check and nothing else when disabled, which is the
default everywhere outside debug/sim runs. ``enable()``/``disable()``
are the sole writers of CHECK, mirroring chaos.install/uninstall.

Static declaration convention (what the analyzer reads): a structured
comment on the entry point's ``def`` line, or the line directly above:

    def _run(self):  # thread-domain: completion-worker
        if threads.CHECK:
            threads.bind("completion-worker")

The comment is the declaration; the guarded ``bind`` makes it true at
runtime. Keep them adjacent so neither can drift alone.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

# ---------------------------------------------------------------- guard --
# Module-level constant guard: instrumented sites check ONLY this.
# enable()/disable() are the sole writers; SC_THREAD_CHECK=1 turns it
# on at import for whole-process debug runs.
CHECK = os.environ.get("SC_THREAD_CHECK", "") == "1"

# the declared-domain universe (analysis/domains.py validates against it)
DOMAINS = ("crank", "http", "completion-worker", "verify-collect",
           "catchup-worker", "pg-writer", "cluster-poll", "apply-worker",
           "query-worker")

_tls = threading.local()

# violations observed while raise_on_violation is False (sim tests that
# want to crank to completion and assert an empty list at the end)
_violations: list = []
_violations_lock = threading.Lock()
_raise = True


class ThreadDomainViolation(AssertionError):
    """Code declared for one domain executed on a thread bound to
    another. The static analyzer's domain propagation trusts the
    declarations — fix the declaration or the call path, never the
    assertion."""


def enable(raise_on_violation: bool = True) -> None:
    """Turn affinity checking on (debug builds / sim tests only)."""
    global CHECK, _raise
    _raise = raise_on_violation
    with _violations_lock:
        _violations.clear()
    CHECK = True


def disable() -> None:
    global CHECK
    CHECK = False
    with _violations_lock:
        _violations.clear()


def violations() -> list:
    """Violations recorded since enable() (raise_on_violation=False)."""
    with _violations_lock:
        return list(_violations)


def bind(domain: str) -> None:
    """Bind the calling thread to `domain` (entry points only).

    Rebinding the same thread is fine — the crank loop binds every
    crank, HTTP handler threads bind every request.
    """
    if domain not in DOMAINS:
        raise ValueError(f"unknown thread domain {domain!r}; "
                         f"add it to threads.DOMAINS")
    _tls.domain = domain


def current() -> Optional[str]:
    """The calling thread's bound domain, or None if never bound."""
    return getattr(_tls, "domain", None)


def assert_domain(*allowed: str) -> None:
    """Assert the calling thread is bound to one of `allowed`.

    Unbound threads pass: binding is opt-in per entry point, and an
    assertion must not fail just because a test drives the code
    directly from an undeclared pytest thread.
    """
    got = getattr(_tls, "domain", None)
    if got is None or got in allowed:
        return
    site = _caller_site()
    msg = (f"thread-domain violation at {site[0]}:{site[1]}: running in "
           f"{got!r}, declared for {allowed!r} — fix the declaration or "
           f"route the call through clock.post(...)")
    if _raise:
        raise ThreadDomainViolation(msg)
    with _violations_lock:
        _violations.append(msg)


def _caller_site() -> Tuple[str, int]:
    import inspect
    frame = inspect.currentframe()
    try:
        # assert_domain -> _caller_site: caller is two frames up
        f = frame.f_back.f_back if frame and frame.f_back else None
        if f is None:
            return ("<unknown>", 0)
        return (f.f_code.co_filename, f.f_lineno)
    finally:
        del frame
