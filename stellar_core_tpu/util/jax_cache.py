"""Platform-partitioned persistent XLA compile cache.

One shared cache directory serving both the CPU test mesh and the real
TPU chip poisons cross-platform runs: XLA:CPU AOT artifacts compiled on
one host generation are loaded on another (cpu_aot_loader machine-feature
mismatch warnings, and a real SIGILL footgun when the features actually
differ), and an 8-device CPU dryrun must never load chip AOT results.
Partition by backend platform + (for CPU) the host ISA so each target
only ever sees artifacts it produced.
"""

from __future__ import annotations

import os
import platform as _platform


def cache_dir_for_backend(base: str, namespace: str = "") -> str:
    """`base`/<backend>[-<machine>][-<namespace>] — resolved after
    backend init."""
    import jax
    backend = jax.default_backend()
    suffix = backend
    if backend == "cpu":
        # partition CPU artifacts by host ISA: AOT results embed machine
        # features and do not transfer between host generations
        suffix = "cpu-" + _platform.machine()
    if namespace:
        suffix += "-" + namespace
    return os.path.join(base, suffix)


def enable_compile_cache(base: str,
                         min_compile_secs: float = 2.0,
                         namespace: str = "") -> str:
    """Point JAX's persistent compilation cache at a platform-partitioned
    subdirectory of `base`; returns the resolved directory.

    `namespace` further isolates writers whose XLA tuning may differ
    from other processes on the same host (e.g. the driver's CPU-mesh
    dryrun): a namespace only ever loads artifacts it compiled itself,
    so its log tail stays free of cpu_aot_loader feature-mismatch
    noise by construction."""
    import jax
    d = cache_dir_for_backend(base, namespace)
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
    return d
