"""Deterministic RNG wrappers (reference: src/util/Math.h, util/RandHasher.h).

The reference bans std::rand / std::uniform_int_distribution / std::shuffle
(platform-varying) via the check-nondet lint and routes all randomness through
a seeded global engine so tests replay identically. We mirror that: all node
randomness must come from this module, never the bare `random` module.
"""

from __future__ import annotations

import random as _random

_engine = _random.Random(0)


def seed(n: int) -> None:
    global _engine
    _engine = _random.Random(n)


def rand_int(upper_exclusive: int) -> int:
    """Uniform in [0, upper) — stable across platforms (util/Math.h)."""
    return _engine.randrange(upper_exclusive)


def rand_range(lo: int, hi_exclusive: int) -> int:
    return _engine.randrange(lo, hi_exclusive)


def rand_fraction() -> float:
    return _engine.random()


def rand_flip() -> bool:
    return _engine.random() < 0.5


def shuffle(xs: list) -> None:
    _engine.shuffle(xs)


def sample(xs, k: int):
    return _engine.sample(list(xs), k)


def rand_bytes(n: int) -> bytes:
    return _engine.randbytes(n)
