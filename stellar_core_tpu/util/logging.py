"""Partitioned logging (reference: src/util/Logging.h + util/LogPartitions.def).

The reference routes spdlog through 14 named partitions with per-partition
runtime-adjustable levels (CLI `--ll`, HTTP `ll` endpoint). We mirror that on
top of the stdlib logging module.
"""

from __future__ import annotations

import logging as _pylogging
from typing import Dict

from .checks import releaseAssert

# reference: util/LogPartitions.def; "default" is the unpartitioned
# spdlog default logger the plain LOG(...) macros use
PARTITIONS = [
    "Fs", "SCP", "Bucket", "Database", "History", "Process", "Ledger",
    "Overlay", "Herder", "Tx", "LoadGen", "Work", "Invariant", "Perf",
    "Chaos", "Query", "Replay", "default",
]

_LEVELS = {
    "trace": 5,
    "debug": _pylogging.DEBUG,
    "info": _pylogging.INFO,
    "warning": _pylogging.WARNING,
    "error": _pylogging.ERROR,
    "fatal": _pylogging.CRITICAL,
    "none": _pylogging.CRITICAL + 10,
}
_pylogging.addLevelName(5, "TRACE")

_loggers: Dict[str, _pylogging.Logger] = {}


def get_logger(partition: str) -> _pylogging.Logger:
    releaseAssert(partition in PARTITIONS, f"unknown log partition {partition}")
    lg = _loggers.get(partition)
    if lg is None:
        lg = _pylogging.getLogger(f"stellar.{partition}")
        _loggers[partition] = lg
    return lg


def set_log_level(level: str, partition: str | None = None) -> None:
    """Set one or all partitions' levels (reference: Logging::setLogLevel)."""
    lvl = _LEVELS[level.lower()]
    targets = [partition] if partition else PARTITIONS
    for p in targets:
        get_logger(p).setLevel(lvl)


_FMT = "%(asctime)s [%(name)s %(levelname)s] %(message)s"

_COLORS = {"WARNING": "\x1b[33m", "ERROR": "\x1b[31m",
           "CRITICAL": "\x1b[41m", "INFO": "\x1b[32m"}


class _ColorFormatter(_pylogging.Formatter):
    """ANSI level colors (reference: LOG_COLOR, Config.h)."""

    def format(self, record):
        out = super().format(record)
        color = _COLORS.get(record.levelname)
        return f"{color}{out}\x1b[0m" if color else out


def init_logging(level: str = "info", log_file_path: str = "",
                 color: bool = False) -> None:
    """Configure handlers (reference: Logging::init + LOG_FILE_PATH /
    LOG_COLOR Config fields — file handler in addition to console)."""
    _pylogging.basicConfig(format=_FMT)
    root = _pylogging.getLogger()
    if color:
        for h in root.handlers:
            h.setFormatter(_ColorFormatter(_FMT))
    if log_file_path:
        fh = _pylogging.FileHandler(log_file_path)
        fh.setFormatter(_pylogging.Formatter(_FMT))
        root.addHandler(fh)
    set_log_level(level)


# CLOG_* macro analogues
def clog(partition: str, level: str, msg: str, *args) -> None:
    get_logger(partition).log(_LEVELS[level], msg, *args)
