"""Deterministic chaos injection: seeded fault schedules at named seams.

The reference validates crash/partition behavior with simulation tests
(lost/restored nodes, stop-mid-catchup — src/simulation) and per-seam
fault knobs (LoopbackPeer damage/drop probabilities). This module is the
generalized, TPU-native form: one process-global engine holding a SEEDED
schedule of faults keyed by named injection points. Instrumented seams
ask ``chaos.point("overlay.send", raw, node=..., peer=...)`` and the
engine decides — deterministically — whether to drop, corrupt, delay,
fail or crash right there.

Cost contract: when chaos is disabled (the default, always in
production) every instrumented seam executes exactly one module-level
constant check (``if chaos.ENABLED:``) and nothing else — no config
lookup, no function call, no allocation.

Determinism contract: a fault schedule is keyed by per-spec *matched-hit
ordinals* (the Nth time a point fires with a matching context), plus an
optional per-spec seeded RNG for probabilistic firing and corruption
byte choice. Two runs that make the same sequence of point calls inject
the same faults at the same places — asserted by ``ChaosEngine.log``
equality. Hit ordinals are only well-defined when the instrumented code
runs single-threaded; deterministic scenarios therefore run nodes with
inline close completion and synchronous bucket merges (see
docs/CHAOS.md).
"""

from __future__ import annotations

import random
import threading
import time as _time
from typing import Dict, List, Optional

from .logging import get_logger

log = get_logger("Chaos")

# ---------------------------------------------------------------- guard --
# Module-level constant guard: hot paths check ONLY this before paying
# anything. install()/uninstall() are the sole writers.
ENABLED = False
_engine: Optional["ChaosEngine"] = None

# Fire observers (record/replay, ISSUE 18): called OUTSIDE the engine
# lock on every `fire` — injected or not, because input recorders key
# faults by node-local matched-hit ordinals and must count the
# pass-throughs too. Empty in production; one list-read when chaos is
# already enabled.
_observers: List = []


def add_observer(obs) -> None:
    """`obs(point, ctx, kind_or_None, spec_or_None)` on every fire."""
    if obs not in _observers:
        _observers.append(obs)


def remove_observer(obs) -> None:
    if obs in _observers:
        _observers.remove(obs)


# sentinels returned by point() for caller-interpreted faults
DROP = object()      # message/payload must be dropped by the caller
REORDER = object()   # caller should reorder delivery (loopback queues)
FAIL = object()      # caller should substitute its failure path
HANG = object()      # caller's async operation must never complete
EQUIVOCATE = object()  # caller signs+emits a CONFLICTING twin envelope

# fault kinds. `equivocate`/`bad_sig_flood`/`malformed_xdr`/`churn` are
# the Byzantine family (ISSUE 7): `equivocate` (two conflicting signed
# SCP envelopes for one slot), `bad_sig_flood` (bursts of well-formed
# payloads with invalid signatures), `malformed_xdr` (truncation /
# multi-byte mangling beyond the single-byte `corrupt`), and `churn`
# (kill + later restart from persisted state, vs `crash` which kills
# forever). `partition`/`flap`/`slow_link` are the wide-area link
# family (ISSUE 20): time-windowed rather than hit-ordinal-windowed —
# see TIMED_KINDS below.
KINDS = ("io_error", "drop", "corrupt", "delay", "reorder", "crash",
         "fail", "hang", "equivocate", "bad_sig_flood", "malformed_xdr",
         "churn", "partition", "flap", "slow_link")

# The link-fault family is driven by elapsed TIME, not matched-hit
# ordinals: a severed or shaped link is a condition that holds over a
# window, not an event that fires N times. Specs of these kinds ignore
# start/count/prob and instead fire on EVERY matched hit while their
# window is open. The time base is `ctx["now"]` when the seam provides
# one (the VirtualClock — loopback simulations stay deterministic in
# virtual time; real-socket nodes pass their monotonic run clock), else
# time.monotonic(). The window opens at the first matched hit.
TIMED_KINDS = frozenset({"partition", "flap", "slow_link"})


class Delay:
    """Deferred delivery: the caller must schedule `payload` on the
    VirtualClock `seconds` from now. NEVER a real sleep — a wall-clock
    sleep inside a single-process virtual-time simulation blocks every
    node at once and burns wall time proportional to nodes × latency
    (the PR 2 `delay` bug). Seams that cannot defer (TCP stream chunks,
    DB commits) treat an unhandled Delay as passthrough."""

    __slots__ = ("payload", "seconds")

    def __init__(self, payload, seconds: float):
        self.payload = payload
        self.seconds = seconds


class Shape:
    """Per-link traffic shaping verdict from a `slow_link` spec: the
    caller must hold `payload` for `delay_s` before release and pace the
    link at `bytes_per_s` (None = latency only). Returned on every
    matched hit while the spec's window is open, so callers stay
    stateless about the schedule — they shape exactly the frames the
    engine tells them to."""

    __slots__ = ("delay_s", "bytes_per_s")

    def __init__(self, delay_s: float, bytes_per_s: Optional[float]):
        self.delay_s = delay_s
        self.bytes_per_s = bytes_per_s


class BadSigBurst:
    """The caller forges `burst` well-formed payloads carrying INVALID
    signatures from a real template and feeds them down its normal
    admission path — modeling a flooder aimed at the verify service's
    batch admission."""

    __slots__ = ("burst",)

    def __init__(self, burst: int):
        self.burst = burst


class ChaosError(IOError):
    """An injected I/O fault. Subclasses IOError/OSError so it travels
    the same error paths a real transport/disk failure would."""


class SimulatedCrash(BaseException):
    """A simulated process kill. BaseException on purpose: generic
    ``except Exception`` recovery code must NOT swallow it — it unwinds
    to the application boundary (the crank loop / test driver), which
    treats the node as dead."""

    def __init__(self, point: str, ctx: Optional[dict] = None):
        super().__init__(f"chaos: simulated crash at {point}")
        self.point = point
        self.ctx = dict(ctx or {})


class SimulatedChurn(SimulatedCrash):
    """Kill + restart: unwinds exactly like a crash (the node is buried,
    in-memory state past the last durable commit is lost), but the
    scenario driver restarts the node from its persisted DB + bucket dir
    (`Simulation.restart_node`) after a delay and expects it to catch
    back up while chaos is still active."""


# Crash points at the ledger-close phase boundaries (the crash-point
# matrix). Points before/inside the consensus-critical SQL transaction
# roll the whole close back; points after it exercise the
# `lastclosecompleted` recovery path from the close pipeline.
CLOSE_CRASH_POINTS = (
    "ledger.close.crash.prepare",        # before the close transaction
    "ledger.close.crash.fees",           # after the fee pass (in-txn)
    "ledger.close.crash.applyTx",        # after the apply loop (in-txn)
    "ledger.close.crash.upgrades",       # after upgrades (in-txn)
    "ledger.close.crash.evictionScan",   # after the eviction scan (in-txn)
    "ledger.close.crash.seal",           # after seal, before COMMIT
    "ledger.close.crash.commit",         # header durable, nothing queued
    "ledger.close.crash.queued",         # checkpoint queued, tail pending
    "ledger.close.crash.complete.meta",  # meta emitted, marker pending
    "ledger.close.crash.complete.marker",  # marker durable, publish pending
)


class FaultSpec:
    """One scheduled fault: fire `kind` at `point` on matched hits
    [start, start+count), optionally with probability `prob` instead of
    the hit window, only when `match` is a subset of the call context."""

    __slots__ = ("point", "kind", "start", "count", "prob", "match",
                 "delay_ms", "burst", "window_s", "period_s", "duty",
                 "bps")

    def __init__(self, point: str, kind: str, start: int = 0,
                 count: int = 1, prob: Optional[float] = None,
                 match: Optional[dict] = None, delay_ms: float = 1.0,
                 burst: int = 8, window_s: float = 0.0,
                 period_s: float = 4.0, duty: float = 0.5,
                 bps: Optional[float] = None):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind: {kind}")
        self.point = point
        self.kind = kind
        self.start = start
        self.count = count
        self.prob = prob
        self.match = dict(match or {})
        self.delay_ms = delay_ms
        self.burst = burst
        # link-fault family (TIMED_KINDS): active window in seconds
        # from the first matched hit, 0 = until the engine is cleared
        # (the harness heals a partition either way — scheduled via
        # window_s, or explicitly via chaos?mode=clear)
        self.window_s = window_s
        self.period_s = period_s   # flap: one down+up cycle
        self.duty = duty           # flap: fraction of period spent DOWN
        self.bps = bps             # slow_link: bytes/second, None = ∞

    def to_json(self) -> dict:
        doc = {"point": self.point, "kind": self.kind,
               "start": self.start, "count": self.count}
        if self.prob is not None:
            doc["prob"] = self.prob
        if self.match:
            doc["match"] = dict(self.match)
        if self.kind == "delay":
            doc["delay_ms"] = self.delay_ms
        if self.kind == "bad_sig_flood":
            doc["burst"] = self.burst
        if self.kind in TIMED_KINDS:
            doc["window_s"] = self.window_s
        if self.kind == "flap":
            doc["period_s"] = self.period_s
            doc["duty"] = self.duty
        if self.kind == "slow_link":
            doc["delay_ms"] = self.delay_ms
            if self.bps is not None:
                doc["bps"] = self.bps
        return doc

    @classmethod
    def from_json(cls, doc: dict) -> "FaultSpec":
        bps = doc.get("bps")
        return cls(doc["point"], doc["kind"],
                   start=int(doc.get("start", 0)),
                   count=int(doc.get("count", 1)),
                   prob=doc.get("prob"),
                   match=doc.get("match"),
                   delay_ms=float(doc.get("delay_ms", 1.0)),
                   burst=int(doc.get("burst", 8)),
                   window_s=float(doc.get("window_s", 0.0)),
                   period_s=float(doc.get("period_s", 4.0)),
                   duty=float(doc.get("duty", 0.5)),
                   bps=float(bps) if bps is not None else None)


def schedule_from_json(docs: List[dict]) -> List[FaultSpec]:
    return [FaultSpec.from_json(d) for d in docs]


class ChaosEngine:
    """Process-global fault scheduler. One instance is installed at a
    time; every instrumented seam routes through `fire`."""

    def __init__(self, seed: int, schedule: Optional[List[FaultSpec]]
                 = None):
        self.seed = seed
        self.schedule: List[FaultSpec] = list(schedule or [])
        self._lock = threading.Lock()
        # per-spec seeded RNGs: independent streams, so adding a spec
        # never perturbs another spec's decisions
        self._rngs = [random.Random(seed * 1000003 + i)
                      for i in range(len(self.schedule))]
        self._spec_hits = [0] * len(self.schedule)
        # TIMED_KINDS: window-open timestamp, set at first matched hit
        self._spec_t0: List[Optional[float]] = [None] * len(self.schedule)
        self.point_hits: Dict[str, int] = {}   # observability
        self.injected: Dict[str, int] = {}     # chaos.injected.<kind>
        # reproducibility record: (point, spec index, matched hit, kind)
        self.log: List[tuple] = []

    # ------------------------------------------------------------- firing --
    def fire(self, point: str, payload, ctx: dict):
        chosen = None
        with self._lock:
            self.point_hits[point] = self.point_hits.get(point, 0) + 1
            for i, spec in enumerate(self.schedule):
                if spec.point != point:
                    continue
                if spec.match and any(ctx.get(k) != v
                                      for k, v in spec.match.items()):
                    continue
                hit = self._spec_hits[i]
                self._spec_hits[i] = hit + 1
                if spec.kind in TIMED_KINDS:
                    # time-windowed link faults: every matched hit
                    # inside the open window fires; start/count/prob
                    # do not apply (a severed link is a condition, not
                    # an event). Window opens at the first matched hit.
                    now = ctx.get("now")
                    if not isinstance(now, (int, float)):
                        now = _time.monotonic()
                    t0 = self._spec_t0[i]
                    if t0 is None:
                        t0 = self._spec_t0[i] = float(now)
                    elapsed = now - t0
                    if spec.window_s > 0 and elapsed >= spec.window_s:
                        continue    # window elapsed: the link healed
                    if spec.kind == "flap" and spec.period_s > 0 and \
                            (elapsed % spec.period_s) >= \
                            spec.duty * spec.period_s:
                        continue    # up-phase of the flap cycle
                    chosen = (i, spec, hit)
                    break
                if spec.prob is not None:
                    if self._rngs[i].random() >= spec.prob:
                        continue
                elif not spec.start <= hit < spec.start + spec.count:
                    continue
                if spec.kind in ("corrupt", "malformed_xdr") and not (
                        isinstance(payload, (bytes, bytearray))
                        and payload):
                    # nothing to corrupt at this point: the hit ordinal
                    # was consumed but no fault is injected — counting
                    # it would let injected/log claim an effect that
                    # never happened
                    continue
                if spec.kind == "delay" and not ctx.get("_can_delay"):
                    # same rule for delay: only seams that declare they
                    # can defer delivery (``_can_delay=True`` — the
                    # loopback transport) honor a Delay; elsewhere the
                    # hit passes through UNCOUNTED rather than letting
                    # injected/log claim a delay that never happened
                    continue
                chosen = (i, spec, hit)
                break
            if chosen is not None:
                i, spec, hit = chosen
                key = f"chaos.injected.{spec.kind}"
                self.injected[key] = self.injected.get(key, 0) + 1
                self.log.append((point, i, hit, spec.kind))
                mangled = None
                if spec.kind == "corrupt":
                    pos = self._rngs[i].randrange(len(payload))
                    b = bytearray(payload)
                    b[pos] ^= 0xFF
                    mangled = bytes(b)
                elif spec.kind == "malformed_xdr":
                    # deterministic per-spec-RNG mangling, one of three
                    # shapes beyond the single-byte `corrupt`: the
                    # result must still be handed to the XDR decoder —
                    # a Byzantine peer sends it as a framed message
                    mangled = self._mangle(self._rngs[i], bytes(payload))
        if _observers:
            kind = chosen[1].kind if chosen is not None else None
            spec_or_none = chosen[1] if chosen is not None else None
            for obs in list(_observers):
                obs(point, ctx, kind, spec_or_none)
        if chosen is None:
            return payload
        _, spec, _ = chosen
        log.debug("chaos: injecting %s at %s %s", spec.kind, point, ctx)
        if spec.kind == "io_error":
            raise ChaosError(f"chaos injected io_error at {point}")
        if spec.kind == "crash":
            raise SimulatedCrash(point, ctx)
        if spec.kind == "churn":
            raise SimulatedChurn(point, ctx)
        if spec.kind in ("drop", "partition", "flap"):
            # partition/flap land as DROP at the link seam: the caller
            # severs (or refuses) the connection while the window is
            # open and lets the jittered redial re-knit it after heal
            return DROP
        if spec.kind == "slow_link":
            return Shape(spec.delay_ms / 1000.0, spec.bps)
        if spec.kind == "reorder":
            return REORDER
        if spec.kind == "fail":
            return FAIL
        if spec.kind == "hang":
            # delay-forever: the caller substitutes a handle that never
            # completes, so only a dispatch deadline (the backend
            # supervisor's watchdog) can resolve the operation
            return HANG
        if spec.kind == "equivocate":
            return EQUIVOCATE
        if spec.kind == "bad_sig_flood":
            return BadSigBurst(spec.burst)
        if spec.kind == "delay":
            # virtual time only: the caller schedules delivery on the
            # clock (a real sleep here would stall the whole
            # single-process simulation — see Delay's docstring)
            return Delay(payload, spec.delay_ms / 1000.0)
        if spec.kind in ("corrupt", "malformed_xdr"):
            return mangled
        return payload

    @staticmethod
    def _mangle(rng: random.Random, payload: bytes) -> bytes:
        mode = rng.randrange(3)
        if mode == 0:
            # truncate: length-prefixed XDR arrays now read past the end
            return payload[:rng.randrange(len(payload))]
        if mode == 1:
            # flip several bytes: union discriminants / counts go wild
            b = bytearray(payload)
            for _ in range(min(4, len(b))):
                b[rng.randrange(len(b))] ^= 0xFF
            return bytes(b)
        # inflate: garbage appended past the declared structure
        extra = bytes(rng.randrange(256) for _ in range(8))
        return payload + extra

    # -------------------------------------------------------------- report --
    def status(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "seed": self.seed,
                "schedule": [s.to_json() for s in self.schedule],
                "injected": dict(self.injected),
                "points": dict(self.point_hits),
                "log_entries": len(self.log),
            }


# ------------------------------------------------------------ module API --
def install(engine: ChaosEngine) -> None:
    """Enable chaos with `engine`'s schedule. Global and test-gated:
    production configs never call this."""
    global _engine, ENABLED
    _engine = engine
    ENABLED = True
    log.info("chaos engine installed (seed=%d, %d specs)", engine.seed,
             len(engine.schedule))


def uninstall() -> None:
    global _engine, ENABLED
    ENABLED = False
    _engine = None


def engine() -> Optional[ChaosEngine]:
    return _engine


def status() -> dict:
    eng = _engine
    if eng is None:
        return {"enabled": False}
    return eng.status()


def point(name: str, payload=None, **ctx):
    """Fire injection point `name`. Returns `payload` (possibly
    corrupted), or a sentinel (DROP / REORDER / FAIL / HANG), or raises
    (ChaosError / SimulatedCrash / sleeps) per the installed schedule.
    Callers MUST pre-guard with ``if chaos.ENABLED:`` so disabled runs
    pay one attribute read."""
    eng = _engine
    if eng is None:
        return payload
    return eng.fire(name, payload, ctx)
