"""Telemetry time-series: a bounded ring of periodic metric snapshots.

Every metric surface before this was a point-in-time snapshot — the
`metrics`/`clusterstatus` routes answer "what is the p99 NOW", the
flight recorder answers "what happened in THIS span". This module adds
the time dimension (Dean & Barroso, *The Tail at Scale*, CACM 2013:
tail behavior must be watched continuously, not sampled once): a
``TelemetrySampler`` periodically snapshots the node's health signals
— close/tx-e2e/slot-phase quantiles, verify-service occupancy and
queue depth, breaker state, flood duplicate ratio, per-dispatch device
batch size + padding waste, host loadavg — into a bounded
``TimeSeries`` ring.

Clock discipline: the sampler rides a recurring ``VirtualTimer`` on
the application clock, so an in-process simulation samples on the
VirtualClock (deterministic: the series and every SLO verdict derived
from it replay bit-identically under a seeded scenario) and a `run`
node samples on the wall clock. Samples are cheap — a handful of
windowed-timer reads — and the ring is strictly bounded, so telemetry
can stay always-on in production.

Scrape contract (the `timeseries` admin route): every sample carries a
monotonically increasing ``cursor`` within an ``epoch`` that changes on
process restart and on ``clearmetrics``. A scraper passes the opaque
``cursor`` token from the previous reply as ``since=``; the node
returns only newer samples — or the full buffer with ``reset: true``
when the epoch changed (restart, metrics clear) or the asked-for
cursor already fell off the ring. ``simulation/cluster.py`` polls this
per node into a merged cluster-wide series for CLUSTER artifacts.

Consumers: the `timeseries`/`slo` admin routes (main/command_handler),
the SLO watchdog (ops/slo.py observes every appended sample), bench
artifact summaries (bench.py), and the multi-process cluster harness.
"""

from __future__ import annotations

import itertools
import os
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_CAPACITY = 600          # 10 minutes at the 1 Hz default period
DEFAULT_PERIOD_S = 1.0

_epoch_counter = itertools.count(1)


def _new_epoch() -> str:
    """Unique per (process, clear) epoch token: a restarted node or a
    cleared ring must invalidate every outstanding scrape cursor —
    pid + boot-millis + an in-process counter make collisions across
    restarts practically impossible."""
    return "%x.%x.%d" % (os.getpid(), time.time_ns() // 1_000_000,
                         next(_epoch_counter))


class TimeSeries:
    """Bounded ring of samples with epoch/cursor scrape bookkeeping.

    ``append`` stamps each sample with the next cursor; when the ring
    is full the oldest sample is evicted (counted in ``dropped`` — the
    scrape contract reports the loss, it never blocks the writer)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque()
        self.epoch = _new_epoch()
        self._next_cursor = 1
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._ring)

    def append(self, sample: dict) -> int:
        cursor = self._next_cursor
        self._next_cursor += 1
        sample["cursor"] = cursor
        self._ring.append(sample)
        if len(self._ring) > self.capacity:
            self._ring.popleft()
            self.dropped += 1
        return cursor

    def samples(self) -> List[dict]:
        return list(self._ring)

    def latest(self) -> Optional[dict]:
        return self._ring[-1] if self._ring else None

    def cursor_token(self) -> str:
        """Opaque resume token for the NEXT scrape: epoch + the last
        assigned cursor (not last-retained — an evicted tail must not
        be re-served)."""
        return f"{self.epoch}:{self._next_cursor - 1}"

    def since(self, token: Optional[str]
              ) -> Tuple[List[dict], bool]:
        """Samples newer than `token` (an earlier ``cursor_token()``).
        Returns ``(samples, reset)``: ``reset`` is True when the token
        was absent/foreign-epoch/fallen-off-the-ring — the full buffer
        is returned and the scraper must treat it as a fresh start."""
        if not token:
            return self.samples(), True
        epoch, _, cur = token.rpartition(":")
        try:
            cur = int(cur)
        except ValueError:
            return self.samples(), True
        if epoch != self.epoch:
            return self.samples(), True
        if self._ring and cur < self._ring[0]["cursor"] - 1:
            # the asked-for continuation point was evicted: serve the
            # whole ring and say so, rather than silently gap the series
            return self.samples(), True
        return [s for s in self._ring if s["cursor"] > cur], False

    def to_doc(self, since: Optional[str] = None,
               limit: Optional[int] = None) -> dict:
        samples, reset = self.since(since)
        truncated = False
        if limit is not None and 0 <= limit < len(samples):
            # serve the OLDEST `limit` of the newer samples, and point
            # the reply cursor at the last one actually served — the
            # next scrape continues from there. Truncating the head
            # while advancing the cursor to the newest sample would be
            # a permanent silent gap, the one thing this contract
            # promises never to do.
            samples = samples[:limit]
            truncated = True
        if samples:
            cursor = f"{self.epoch}:{samples[-1]['cursor']}"
        elif truncated and not reset:
            cursor = since       # limit=0: scraper stays where it was
        elif reset:
            # nothing served AND no valid continuation point (foreign
            # epoch / eviction with limit=0): resume from the ring start
            cursor = f"{self.epoch}:0"
        else:
            cursor = self.cursor_token()       # caught up
        return {
            "epoch": self.epoch,
            "cursor": cursor,
            "capacity": self.capacity,
            "dropped": self.dropped,
            "reset": reset,
            "truncated": truncated,
            "samples": samples,
        }

    def clear(self) -> None:
        """`clearmetrics` hook: empty the ring AND rotate the epoch so
        every outstanding scrape cursor resyncs from scratch — bench
        legs sharing one process start each window from a clean slate,
        and a scraper that cached `epoch:cursor` gets `reset: true` on
        its next poll instead of a silent gap."""
        self._ring.clear()
        self.dropped = 0
        self.epoch = _new_epoch()
        self._next_cursor = 1


# ------------------------------------------------------------- sampling --

def timer_quantiles(metrics, name: str) -> dict:
    """Windowed quantiles of one timer, ms. THE shared read
    discipline for per-timer health snapshots (clusterstatus route,
    telemetry samples): get-or-create keeps the families stable from
    boot, and reading the six-or-so consumed timers directly avoids a
    full registry to_json() (which would sort every reservoir) per
    poll."""
    doc = metrics.new_timer(name).to_json()
    if not doc.get("count"):
        return {"count": 0}
    return {"count": doc["count"],
            "median_ms": round(doc["median"] * 1000, 3),
            "p99_ms": round(doc["99%"] * 1000, 3),
            "max_ms": round(doc["max"] * 1000, 3)}


def collect_sample(app) -> dict:
    """One telemetry snapshot of an Application. Every field is read
    defensively: a node without an overlay / verify service / device
    backend simply omits that section (None), and the SLO rules treat
    a missing value as OK."""
    m = app.metrics
    sample: dict = {
        "t": round(app.clock.now(), 3),
        "wall": time.time(),
        "ledger": app.ledger_manager.get_last_closed_ledger_num(),
        "pending_txs": app.herder.tx_queue.size_txs(),
        # cumulative applied-tx count: the controller's per-tx close
        # cost estimate reads Δtx_applied/Δledger between samples
        "tx_applied": m.new_meter("ledger.transaction.count").count,
        "close": timer_quantiles(m, "ledger.ledger.close"),
        "tx_e2e": timer_quantiles(m, "ledger.transaction.e2e"),
        "slot_p99_ms": {
            p: timer_quantiles(m, "scp.slot." + p).get("p99_ms", 0.0)
            for p in ("nominate", "prepare", "confirm", "total")},
    }
    # verify service: batch occupancy + live queue depth (Clipper's
    # first-class monitored signals — occupancy and queue wait)
    svc = getattr(app, "verify_service", None)
    if svc is not None:
        occ = svc._occupancy.to_json()
        qw = svc._queue_wait.to_json()
        depth = svc.queue_depth()
        sample["verify"] = {
            "flushes": occ["count"],
            "occupancy_p99": occ["99%"] if occ["count"] else 0,
            # submit→dispatch wait p99 — the AIMD latency signal the
            # adaptive controller searches against (ops/controller.py)
            "queue_wait_p99_ms": round(qw["99%"] * 1000, 3)
            if qw.get("count") else 0.0,
            "queue_pending": depth["pending"],
            "queue_inflight": depth["inflight"],
        }
    else:
        sample["verify"] = None
    # per-dispatch device accounting (ops/verifier.py): batch size,
    # padding waste, dispatch wall time — the per-device telemetry
    # ROADMAP item 1's per-device breaker consumes
    bt = m.new_histogram("crypto.verify.dispatch.batch").to_json()
    if bt.get("count"):
        pad = m.new_histogram(
            "crypto.verify.dispatch.padding").to_json()
        wall = m.new_timer("crypto.verify.dispatch.wall").to_json()
        padded_lanes = bt["sum"] + pad["sum"]
        sample["dispatch"] = {
            "count": bt["count"],
            "batch_p50": bt["median"],
            "batch_p99": bt["99%"],
            "pad_waste_ratio": round(
                pad["sum"] / padded_lanes, 4) if padded_lanes else 0.0,
            "wall_p99_ms": round(wall["99%"] * 1000, 3)
            if wall.get("count") else 0.0,
        }
    else:
        sample["dispatch"] = None
    # breaker state (ops/backend_supervisor.py): level, not flow —
    # breaker_open is the numeric form the OPEN-dwell SLO rule reads.
    # The aggregate is OPEN only when the WHOLE mesh is unavailable; a
    # partially degraded mesh reads CLOSED here and shows in `mesh`
    # (devices vs active), which the adaptive controller scales its
    # capacity estimate by (ops/controller.py, replay-deterministic
    # because it reads the sample, not the live supervisor).
    sup = getattr(app, "batch_verifier", None)
    if sup is not None and hasattr(sup, "breaker_state"):
        sample["breaker"] = sup.state
        sample["breaker_open"] = 1.0 if sup.state == "OPEN" else 0.0
        mesh = sup.mesh_status()
        sample["mesh"] = {"devices": mesh["devices"],
                          "active": mesh["active"]}
    else:
        sample["breaker"] = None
        sample["breaker_open"] = 0.0
        sample["mesh"] = None
    prop = getattr(app, "propagation", None)
    if prop is not None:
        rep = prop.report()
        sample["flood"] = {k: rep[k] for k in
                           ("unique", "duplicates", "duplicate_ratio")}
    else:
        sample["flood"] = None
    # read-serving tier (query/): read latency quantiles feed the
    # read_p99 SLO rule; queue depth + shed/hedge tallies feed the
    # controller's read ladder and the ops routes
    qsvc = getattr(app, "query_service", None)
    if qsvc is not None:
        q = timer_quantiles(m, "query.read.latency") or {}
        st = qsvc.stats()
        sample["query"] = {
            "count": q.get("count", 0),
            "p50_ms": q.get("median_ms", 0.0),
            "p99_ms": q.get("p99_ms", 0.0),
            "queue": st["queue"],
            "p95_estimate_ms": st["p95_estimate_ms"],
            "shed": st["shed"],
            "hedge": st["hedge"],
            "timeouts": st["timeouts"],
        }
        snaps = getattr(app, "snapshots", None)
        if snaps is not None:
            # telemetry cadence is where the heavy pinned recount runs
            snaps.refresh_pinned_gauge()
            sample["query"]["snapshots"] = snaps.stats()
    else:
        sample["query"] = None
    try:
        load1 = os.getloadavg()[0]
    except (AttributeError, OSError):            # pragma: no cover
        load1 = 0.0
    sample["host"] = {"load1": round(load1, 2),
                      "ncpu": os.cpu_count() or 1}
    return sample


class TelemetrySampler:
    """Periodic snapshot pump: a recurring VirtualTimer on the app
    clock appends ``collect_sample(app)`` to the ring and feeds every
    registered observer (the SLO watchdog). ``period_s=0`` leaves the
    timer unarmed — ``sample_now()`` still works, which is how the
    manual-close benches and virtual-time tests drive deterministic
    sampling without a recurring event on the clock heap."""

    def __init__(self, app, capacity: int = DEFAULT_CAPACITY,
                 period_s: float = DEFAULT_PERIOD_S):
        self._app = app
        self.period_s = max(0.0, float(period_s))
        self.series = TimeSeries(capacity)
        self.observers: List[Callable[[dict], None]] = []
        self._timer = None
        self._stopped = False

    # ----------------------------------------------------------- sampling --
    def sample_now(self) -> dict:
        sample = collect_sample(self._app)
        self.series.append(sample)
        for obs in self.observers:
            obs(sample)
        return sample

    def _fire(self) -> None:
        from ..main.application import AppState
        if self._stopped or \
                self._app.state == AppState.APP_STOPPING_STATE:
            # a crashed/stopping node must not keep a recurring event
            # on the (possibly shared) simulation clock forever
            return
        try:
            self.sample_now()
        except Exception:                        # noqa: BLE001
            # telemetry must never take the node down; the next fire
            # retries with whatever subsystem state then exists
            from .logging import get_logger
            get_logger("default").debug(
                "telemetry sample failed", exc_info=True)
        self._arm()

    def _arm(self) -> None:
        from .timer import VirtualTimer
        if self._timer is None:
            self._timer = VirtualTimer(self._app.clock)
        self._timer.expires_from_now(self.period_s)
        self._timer.async_wait(self._fire)

    # ---------------------------------------------------------- lifecycle --
    def start(self) -> None:
        if self.period_s > 0 and not self._stopped:
            self._arm()

    def stop(self) -> None:
        self._stopped = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def clear(self) -> None:
        self.series.clear()


# ------------------------------------------------------------ summaries --

def summarize_samples(samples: List[dict]) -> dict:
    """Bounded per-node series summary for bench artifacts: the
    attributable facts (host-load envelope, worst tails, queue/backoff
    evidence) without shipping the whole ring into a committed JSON."""
    if not samples:
        return {"samples": 0}
    loads = [s["host"]["load1"] for s in samples if s.get("host")]
    closes = [s["close"]["p99_ms"] for s in samples
              if s.get("close", {}).get("count")]
    e2es = [s["tx_e2e"]["p99_ms"] for s in samples
            if s.get("tx_e2e", {}).get("count")]
    depths = [s["verify"]["queue_pending"] for s in samples
              if s.get("verify")]
    dups = [s["flood"]["duplicate_ratio"] for s in samples
            if s.get("flood")]
    pads = [s["dispatch"]["pad_waste_ratio"] for s in samples
            if s.get("dispatch")]
    out = {
        "samples": len(samples),
        "span_s": round(samples[-1]["t"] - samples[0]["t"], 3),
        "host_load": {
            "min": round(min(loads), 2),
            "mean": round(sum(loads) / len(loads), 2),
            "max": round(max(loads), 2),
        } if loads else None,
        "close_p99_ms_max": max(closes) if closes else None,
        "tx_e2e_p99_ms_max": max(e2es) if e2es else None,
        "queue_pending_max": max(depths) if depths else None,
        "duplicate_ratio_last": dups[-1] if dups else None,
        "pad_waste_ratio_last": pads[-1] if pads else None,
        "breaker_open_samples": sum(
            1 for s in samples if s.get("breaker_open")),
        # samples taken while the verify mesh was shrunk (some device's
        # breaker OPEN) — the graceful-degradation counterpart of the
        # whole-backend breaker_open count above
        "mesh_degraded_samples": sum(
            1 for s in samples
            if (s.get("mesh") or {}).get("active", 0)
            < (s.get("mesh") or {}).get("devices", 0)),
    }
    return out


def scenario_reports(apps) -> Tuple[dict, dict]:
    """THE shared artifact-section builder for in-process scenarios
    (bench legs, the byzantine runner): take a final sample of every
    app — manual-close scenarios barely advance the clock, so the
    series must reflect the end state — then return the merged
    ``(timeseries, slo)`` sections. One implementation, so a
    summary-shape change propagates to every artifact producer."""
    from ..ops.slo import aggregate_status
    summaries = []
    statuses = []
    for a in apps:
        try:
            a.telemetry.sample_now()
        except Exception:                        # noqa: BLE001
            pass
        summaries.append(summarize_samples(a.telemetry.series.samples()))
        statuses.append(a.slo.status())
    return aggregate_summaries(summaries), aggregate_status(statuses)


def aggregate_summaries(summaries: List[dict]) -> dict:
    """Merge per-node summaries into one cluster/scenario-wide doc:
    sums where the stat is volume, worst-case where it is a tail, the
    widest envelope for host load (the nodes shared one host)."""
    summaries = [s for s in summaries if s and s.get("samples")]
    if not summaries:
        return {"samples": 0, "nodes": 0}

    def _max(key):
        vals = [s[key] for s in summaries if s.get(key) is not None]
        return max(vals) if vals else None

    loads = [s["host_load"] for s in summaries if s.get("host_load")]
    total = sum(s["samples"] for s in summaries)
    return {
        "samples": total,
        "nodes": len(summaries),
        "span_s": _max("span_s"),
        "host_load": {
            "min": min(h["min"] for h in loads),
            "mean": round(sum(h["mean"] * s["samples"]
                              for h, s in zip(loads, summaries))
                          / max(1, sum(s["samples"]
                                       for s in summaries)), 2),
            "max": max(h["max"] for h in loads),
        } if loads else None,
        "close_p99_ms_max": _max("close_p99_ms_max"),
        "tx_e2e_p99_ms_max": _max("tx_e2e_p99_ms_max"),
        "queue_pending_max": _max("queue_pending_max"),
        "duplicate_ratio_last": _max("duplicate_ratio_last"),
        "pad_waste_ratio_last": _max("pad_waste_ratio_last"),
        "breaker_open_samples": sum(
            s.get("breaker_open_samples") or 0 for s in summaries),
        "mesh_degraded_samples": sum(
            s.get("mesh_degraded_samples") or 0 for s in summaries),
    }
