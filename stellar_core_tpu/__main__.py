"""`python -m stellar_core_tpu <cmd>` — alias of the main CLI."""

import sys

from .main.command_line import main

if __name__ == "__main__":
    sys.exit(main())
