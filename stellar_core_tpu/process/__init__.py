"""Subprocess runner (reference: src/process)."""

from .process_manager import ProcessManager

__all__ = ["ProcessManager"]
