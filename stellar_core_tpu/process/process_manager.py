"""Bounded-concurrency subprocess execution.

Reference: src/process/ProcessManagerImpl.{h,cpp} — posix_spawn'd shell
commands (history archive get/put) with a MAX_CONCURRENT_SUBPROCESSES
gate, exit reaping integrated with the event loop, and kill-on-shutdown.
Here: subprocess.Popen polled from a clock io-poller.
"""

from __future__ import annotations

import shlex
import subprocess
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..util.logging import get_logger

log = get_logger("Process")

# reference: ProcessManagerImpl MAX_CONCURRENT_SUBPROCESSES (config)
DEFAULT_MAX_CONCURRENT = 16


class ProcessExitEvent:
    """Handle for one queued/running command; `on_exit(code)` fires when
    the process exits (reference: ProcessExitEvent + its asio timer)."""

    def __init__(self, cmd: str):
        self.cmd = cmd
        self.proc: Optional[subprocess.Popen] = None
        self.exit_code: Optional[int] = None
        self.on_exit: Optional[Callable[[int], None]] = None

    @property
    def running(self) -> bool:
        return self.proc is not None and self.exit_code is None


class ProcessManager:
    def __init__(self, app, max_concurrent: int = DEFAULT_MAX_CONCURRENT):
        self.app = app
        self.max_concurrent = max_concurrent
        self._pending: Deque[ProcessExitEvent] = deque()
        self._running: List[ProcessExitEvent] = []
        self._shutdown = False
        app.clock.add_io_poller(self._poll)

    def run_process(self, cmd: str,
                    on_exit: Optional[Callable[[int], None]] = None
                    ) -> ProcessExitEvent:
        """Queue a shell command (reference: runProcess)."""
        ev = ProcessExitEvent(cmd)
        ev.on_exit = on_exit
        self._pending.append(ev)
        self._maybe_start()
        return ev

    def _maybe_start(self) -> None:
        while self._pending and len(self._running) < self.max_concurrent \
                and not self._shutdown:
            ev = self._pending.popleft()
            try:
                ev.proc = subprocess.Popen(
                    ev.cmd, shell=True,
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            except OSError as e:
                log.error("spawn failed for %r: %s", ev.cmd, e)
                ev.exit_code = 127
                if ev.on_exit is not None:
                    ev.on_exit(127)
                continue
            self._running.append(ev)

    def _poll(self) -> int:
        n = 0
        for ev in list(self._running):
            code = ev.proc.poll()
            if code is not None:
                ev.exit_code = code
                self._running.remove(ev)
                n += 1
                if ev.on_exit is not None:
                    ev.on_exit(code)
        if n:
            self._maybe_start()
        return n

    def num_running(self) -> int:
        return len(self._running)

    def num_pending(self) -> int:
        return len(self._pending)

    def shutdown(self) -> None:
        self._shutdown = True
        self._pending.clear()
        for ev in self._running:
            try:
                ev.proc.kill()
            except OSError:
                pass
        for ev in self._running:
            try:
                ev.proc.wait(timeout=5)
            except Exception:
                pass
        self._running = []
        self.app.clock.remove_io_poller(self._poll)
