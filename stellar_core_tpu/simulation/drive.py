"""Reusable end-to-end drives for tests and the driver's dryrun entry.

Reference analogue: test/TxTests.h helpers shared between test tiers —
logic exercised by both the pytest suite and __graft_entry__ lives here
so the two can't drift apart.
"""

from __future__ import annotations

from typing import List


def validate_txset_through_batch_verifier(app, n_accounts: int = 4,
                                          n_payments: int = 4) -> List[int]:
    """Fund accounts, queue payments, then validate the proposed txset
    the way an SCP validator receiving it from a peer would
    (herder/scp_driver.py validateValue → is_tx_set_valid — the node's
    batch collection point), finishing with a ledger close.

    Returns the batch sizes that flowed through app.batch_verifier;
    asserts the close advanced the ledger.  The verify cache is cleared
    before validation: queue admission warmed it, but a remote
    validator's cache is cold, and only a cold cache dispatches the
    device batch.
    """
    from ..crypto.keys import clear_verify_cache
    from ..herder.tx_set import make_tx_set_from_transactions
    from .load_generator import LoadGenerator

    bv = app.batch_verifier
    assert bv is not None, "app has no batch verifier configured"
    calls: List[int] = []
    orig = bv.verify_tuples
    bv.verify_tuples = lambda t: (calls.append(len(t)), orig(t))[1]
    try:
        gen = LoadGenerator(app)
        assert gen.generate_accounts(n_accounts) == n_accounts
        app.manual_close()
        gen.sync_account_seqs()
        assert gen.generate_payments(n_payments) == n_payments
        lcl_header = app.ledger_manager.get_last_closed_ledger_header()
        frame, _applicable, _excluded = make_tx_set_from_transactions(
            app.herder.tx_queue.get_transactions(), lcl_header,
            app.config.network_id())
        clear_verify_cache()
        assert app.herder.is_tx_set_valid(frame)
        assert calls, "validation bypassed the batch verifier"
        before = app.ledger_manager.get_last_closed_ledger_num()
        app.manual_close()
        assert app.ledger_manager.get_last_closed_ledger_num() == before + 1
    finally:
        bv.verify_tuples = orig
    return calls
