"""Multi-node in-process networks for tests.

Reference: src/simulation/Simulation.{h,cpp} — N full Applications on a
shared VirtualClock, wired OVER_LOOPBACK (in-memory Peer pairs) so whole
consensus/flooding/catchup scenarios run hermetically and
deterministically. Loopback delivery is registered as an io-poller on
the clock, so `crank_until` advances timers and message queues together
exactly like the reference's crank loop.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..main.application import Application
from ..main.config import Config, QuorumSetConfig
from ..overlay.loopback import LoopbackPeerConnection
from ..util.logging import get_logger
from ..util.timer import ClockMode, VirtualClock

log = get_logger("default")


class Simulation:
    OVER_LOOPBACK = 0
    OVER_TCP = 1  # arrives with TCPPeer

    def __init__(self, mode: int = OVER_LOOPBACK,
                 network_passphrase: str = "(V) (;,,;) (V)",
                 clock: Optional[VirtualClock] = None,
                 data_dir: Optional[str] = None):
        assert mode == Simulation.OVER_LOOPBACK
        self.mode = mode
        self.network_passphrase = network_passphrase
        self.clock = clock or VirtualClock(ClockMode.VIRTUAL_TIME)
        self.nodes: Dict[bytes, Application] = {}   # node id -> app
        self.connections: List[LoopbackPeerConnection] = []
        self.crashed: set = set()                   # node ids killed
        # file-backed node state (churn scenarios): each node gets its
        # own sqlite file + bucket dir under here, so crash_node →
        # restart_node can rebuild the Application from persisted state
        self.data_dir = data_dir
        # rebuild recipe per node: (index, seed, qset, configure)
        self._node_specs: Dict[bytes, tuple] = {}
        # desired topology: (a, b, latency_s, bandwidth_bps) — replayed
        # by restart_node to re-wire a restarted node to live neighbors
        self._adjacency: List[tuple] = []
        self.clock.add_io_poller(self._pump_connections)

    # --------------------------------------------------------------- nodes --
    def _make_config(self, index: int, seed: SecretKey,
                     qset: QuorumSetConfig,
                     configure: Optional[Callable[[Config], None]]
                     ) -> Config:
        cfg = Config()
        cfg.NETWORK_PASSPHRASE = self.network_passphrase
        cfg.NODE_SEED = seed
        cfg.NODE_IS_VALIDATOR = True
        cfg.RUN_STANDALONE = True
        cfg.FORCE_SCP = True
        cfg.MANUAL_CLOSE = False
        cfg.EXPECTED_LEDGER_CLOSE_TIME = 1.0
        cfg.MAX_TX_SET_SIZE = 1000
        cfg.INVARIANT_CHECKS = [".*"]
        cfg.PEER_PORT = 35000 + index
        cfg.QUORUM_SET = qset
        # telemetry sampling is opt-in per scenario (the get_test_config
        # discipline): a recurring timer on every sim node's shared
        # clock would keep idle crank_until loops stepping to their
        # timeouts; bench legs and telemetry tests re-enable it in
        # their `configure` callback
        cfg.TELEMETRY_SAMPLE_PERIOD = 0.0
        # same discipline for the adaptive controller's tick
        cfg.CONTROLLER_TICK_PERIOD = 0.0
        if self.data_dir is not None:
            cfg.DATABASE = "sqlite3://%s" % os.path.join(
                self.data_dir, "node-%d.db" % index)
            cfg.BUCKET_DIR_PATH = os.path.join(
                self.data_dir, "buckets-%d" % index)
        if configure is not None:
            configure(cfg)
        return cfg

    def add_node(self, seed: SecretKey, qset: QuorumSetConfig,
                 configure: Optional[Callable[[Config], None]] = None
                 ) -> Application:
        index = len(self.nodes)
        cfg = self._make_config(index, seed, qset, configure)
        app = Application.create(self.clock, cfg)
        self.nodes[cfg.node_id()] = app
        self._node_specs[cfg.node_id()] = (index, seed, qset, configure)
        return app

    def get_node(self, node_id: bytes) -> Application:
        return self.nodes[node_id]

    def apps(self) -> List[Application]:
        return list(self.nodes.values())

    # --------------------------------------------------------- connections --
    def add_pending_connection(self, a: bytes, b: bytes,
                               latency_s: float = 0.0,
                               bandwidth_bps: Optional[float] = None
                               ) -> None:
        self._adjacency.append((a, b, latency_s, bandwidth_bps))
        self.connections.append(
            LoopbackPeerConnection(self.nodes[a], self.nodes[b],
                                   latency_s=latency_s,
                                   bandwidth_bps=bandwidth_bps))

    def start_all_nodes(self) -> None:
        for app in self.nodes.values():
            app.start()

    def stop_all_nodes(self) -> None:
        for node_id, app in self.nodes.items():
            if node_id not in self.crashed:
                app.shutdown()
        self.clock.remove_io_poller(self._pump_connections)

    def record_all(self, extras: Optional[dict] = None) -> None:
        """Attach an in-memory input recorder (replay/recorder.py) to
        every node. Call BEFORE wiring connections so the recorded
        handshakes are complete — a late recorder flags its conns
        unreplayable. `extras` records driver-level determinism
        settings (e.g. {"defer_completion": False}) the replayer must
        re-apply."""
        from ..replay.recorder import InputRecorder
        for app in self.nodes.values():
            rec = InputRecorder(app, extras=extras)
            rec.begin()
            app.input_recorder = rec

    def finish_recording(self) -> Dict[bytes, "object"]:
        """End every live node's recording with an END marker and
        return {node_id: InputLog}. Crashed nodes' recorders were
        aborted mid-stream by crash_node — their logs end at the kill,
        like a real ``kill -9``, and are NOT returned here (read them
        from the aborted recorder's buffer if the tear itself is under
        test)."""
        logs: Dict[bytes, object] = {}
        for node_id, app in self.nodes.items():
            rec = getattr(app, "input_recorder", None)
            if rec is None or not rec.active:
                continue
            rec.finish(reason="ok")
            logs[node_id] = rec.to_log()
        return logs

    def crash_node(self, node_id: bytes) -> None:
        """Simulate a process kill (reference: Simulation::removeNode in
        the lost/restored-node tests): sever every loopback link without
        any goodbye bytes, then silence the dead app's timers and DROP
        its pending deferred-completion tails. Deliberately NOT the
        graceful Application.shutdown — draining completion, flushing
        meta and closing the database would persist exactly the
        in-memory state a real kill loses. The app object must not be
        reused."""
        app = self.nodes[node_id]
        for conn in list(self.connections):
            a, b = conn.initiator, conn.acceptor
            if a.app is not app and b.app is not app:
                continue
            dead, live = (a, b) if a.app is app else (b, a)
            # nothing more crosses the wire in either direction
            dead.partner = None
            live.partner = None
            live.drop("peer crashed")      # standard remote-vanished path
            self.connections.remove(conn)
        self.crashed.add(node_id)
        rec = getattr(app, "input_recorder", None)
        if rec is not None and rec.active:
            # kill semantics: detach with NO END marker — the log ends
            # mid-stream, exactly what a real kill -9 leaves on disk
            rec.abort()
        if app.flight_recorder.active:
            # a dead process takes its tracing refcount with it: without
            # this, the process-wide tracing.ENABLED flag stays latched
            # after the sim ends. The buffer stays dumpable.
            app.flight_recorder.stop()
        from ..main.application import AppState
        app.state = AppState.APP_STOPPING_STATE
        try:
            app.ledger_manager.discard_pending_completion()
            app.herder.shutdown()     # nomination/ballot/flood timers
            bv = getattr(app, "batch_verifier", None)
            if bv is not None and hasattr(bv, "breaker_state"):
                # the dead node's breaker must not keep probing the
                # device on the shared clock
                bv.shutdown()
            app.maintainer.stop()
            timer = getattr(app, "_self_check_timer", None)
            if timer is not None:
                timer.cancel()
                app._self_check_timer = None
            app.work_scheduler.shutdown()
            app.process_manager.shutdown()
        except BaseException:              # noqa: BLE001 — dead is dead
            log.exception("ignoring error while burying crashed node")

    def restart_node(self, node_id: bytes) -> Application:
        """Bring a crashed node back as a NEW process (reference: the
        lost/RESTORED-node simulation tests): rebuild the Application
        from its persisted sqlite file + bucket dir (requires the
        Simulation's `data_dir` — in-memory nodes have nothing to
        restart from), re-wire its recorded loopback links to the
        neighbors still alive, and start it. The restarted node's LCL
        is whatever its last durable commit was; it catches back up
        over the overlay (peers answer its GET_SCP_STATE with recent
        externalize envelopes) or through archive catchup — while any
        installed chaos schedule keeps running."""
        if node_id not in self.crashed:
            raise RuntimeError("restart_node: node is not crashed")
        if self.data_dir is None:
            raise RuntimeError(
                "restart_node requires a data_dir-backed Simulation "
                "(in-memory nodes lose everything on crash)")
        index, seed, qset, configure = self._node_specs[node_id]
        old = self.nodes[node_id]
        try:
            # the dead process's file descriptors are closed by the OS;
            # close its sqlite handle so the restarted node owns the
            # file (an uncommitted transaction rolls back — exactly
            # what the kill lost)
            old.database.close()
        except Exception:              # noqa: BLE001 — dead is dead
            log.exception("ignoring error closing crashed node's DB")
        cfg = self._make_config(index, seed, qset, configure)
        app = Application.create(self.clock, cfg, new_db=False)
        self.nodes[node_id] = app
        self.crashed.discard(node_id)
        app.start()
        for a, b, lat, bw in self._adjacency:
            if node_id not in (a, b):
                continue
            other = b if a == node_id else a
            if other in self.crashed or other not in self.nodes:
                continue
            self.connections.append(LoopbackPeerConnection(
                self.nodes[a], self.nodes[b], latency_s=lat,
                bandwidth_bps=bw))
        log.info("restarted node %s at ledger %d", node_id.hex()[:8],
                 app.ledger_manager.get_last_closed_ledger_num())
        return app

    def alive_apps(self) -> List[Application]:
        return [a for nid, a in self.nodes.items()
                if nid not in self.crashed]

    def _pump_connections(self) -> int:
        n = 0
        for conn in self.connections:
            n += conn.initiator.deliver_all()
            n += conn.acceptor.deliver_all()
        return n

    # ------------------------------------------------------------- cranking --
    def crank_until(self, pred: Callable[[], bool],
                    timeout_virtual_seconds: float = 120.0) -> bool:
        """Crank clock + connections until pred or virtual timeout
        (reference: Simulation::crankUntil)."""
        deadline = self.clock.now() + timeout_virtual_seconds
        while not pred() and self.clock.now() < deadline:
            if self.clock.crank(False) == 0:
                self.clock.crank(True)  # jump virtual time to next timer
        return pred()

    def crank_for_at_least(self, virtual_seconds: float) -> None:
        target = self.clock.now() + virtual_seconds
        self.crank_until(lambda: self.clock.now() >= target,
                         virtual_seconds + 60)

    # ------------------------------------------------------------- tracing --
    def start_tracing(self) -> None:
        """Begin flight recording on every node (mesh observatory):
        each node's recorder captures its own lane; `merged_trace`
        aligns and stitches them into one cluster-wide document."""
        for app in self.alive_apps():
            app.flight_recorder.start()

    def merged_trace(self) -> dict:
        """One Chrome trace for the whole mesh (util/tracemerge.py):
        per-node process lanes clock-aligned, per-node async tracks
        kept distinct, and hash-keyed flood hops stitched into flow
        chains that follow a tx / SCP envelope across node lanes."""
        from ..util.tracemerge import merge_recorders
        return merge_recorders(
            [a.flight_recorder for a in self.nodes.values()])

    def dump_merged_trace(self, path: str, stop: bool = True) -> dict:
        """Write the merged cluster trace to `path` (Perfetto /
        chrome://tracing / scripts/trace_report.py --slots/--flood);
        stops the recorders afterwards unless told otherwise."""
        import json
        doc = self.merged_trace()
        with open(path, "w") as f:
            json.dump(doc, f)
        if stop:
            for app in self.nodes.values():
                if app.flight_recorder.active:
                    app.flight_recorder.stop()
        return doc

    # -------------------------------------------------------------- helpers --
    def have_all_externalized(self, ledger_seq: int) -> bool:
        return all(a.ledger_manager.get_last_closed_ledger_num() >=
                   ledger_seq for a in self.nodes.values())

    def have_alive_externalized(self, ledger_seq: int) -> bool:
        """Like have_all_externalized but over surviving nodes only —
        chaos scenarios assert liveness on the quorum that's left."""
        return all(a.ledger_manager.get_last_closed_ledger_num() >=
                   ledger_seq for a in self.alive_apps())

    def ledger_hashes_agree(self, ledger_seq: int) -> bool:
        hashes = set()
        for app in self.nodes.values():
            row = app.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
                (ledger_seq,))
            if row is None:
                return False
            hashes.add(bytes(row[0]))
        return len(hashes) == 1
