"""Seeded multinode chaos scenarios: topology + fault schedule in,
liveness/safety/reproducibility verdicts out.

The reference validates this class of behavior with simulation tests
(lost/restored nodes, stop-mid-catchup — src/simulation); here the
fault side is generalized through util/chaos.py and the verdicts are
made byte-exact:

- **liveness** — after the fault window clears, every SURVIVING node
  keeps externalizing ledgers up to the target;
- **safety** — surviving nodes' per-ledger header hashes are
  byte-identical to a fault-free run of the same scenario (close times
  are pinned via ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING so header
  bytes cannot drift with consensus timing);
- **reproducibility** — running the same seeded schedule twice injects
  the same faults at the same points (ChaosEngine.log equality) and
  converges to the same final hashes.

Determinism prerequisites (see docs/CHAOS.md): nodes run single-threaded
— inline close completion, synchronous bucket merges — so chaos hit
ordinals are well-defined.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..crypto.keys import SecretKey
from ..herder.tx_queue import AddResult
from ..tx.frame import make_frame
from ..util import chaos
from ..util.chaos import (ChaosEngine, FaultSpec, SimulatedChurn,
                          SimulatedCrash)
from ..util.logging import get_logger
from ..xdr.ledger_entries import Asset, AssetType, LedgerKey
from ..xdr.transaction import (DecoratedSignature, Memo, MemoType,
                               MuxedAccount, Operation, OperationType,
                               PaymentOp, Preconditions, PreconditionType,
                               Transaction, TransactionEnvelope,
                               TransactionV1Envelope, _OperationBody,
                               _TxExt)
from ..xdr.types import EnvelopeType
from . import topologies

log = get_logger("Chaos")

DEFAULT_TARGET = 12
FIRST_LOADED_LEDGER = 3      # ledger 2 closes clean before load starts


# device-outage window on node 0's supervised backend (ISSUE 5): long
# enough that consecutive dispatch failures trip the circuit breaker
# (threshold 3) AND the first HALF_OPEN canary probes still land inside
# the window — the probes consume the remaining fault hits, so the
# breaker must trip, back off, and re-close before the run ends
DEVICE_OUTAGE_FAULTS = 6


def default_schedule(node_ids: List[bytes]) -> List[FaultSpec]:
    """The canonical ≥5-class schedule over a 4-node core quorum:
    message drops (node 1's sends), reordering (node 2's sends), byte
    corruption on the n1→n2 link (lands as an HMAC failure → the
    standard peer-drop path), a SimulatedCrash at a close-phase
    boundary on node 3, a device-outage window on node 0's supervised
    backend (breaker trips OPEN, degraded native mode, canary probes,
    re-close), and a first-attempt archive fetch failure."""
    n0, n1, n2, n3 = (nid.hex() for nid in node_ids[:4])
    return [
        # message loss: a window of node 1's sends vanish (pre-MAC, so
        # the link survives the loss — SCP retransmission recovers)
        FaultSpec("overlay.message", "drop", start=30, count=20,
                  match={"node": n1}),
        # latency/reorder: node 2's messages get held one slot back
        FaultSpec("overlay.message", "reorder", start=8, count=15,
                  match={"node": n2}),
        # transport corruption INTO node 2 from node 1: MAC check fails,
        # the link dies through send_error_and_drop — the peer-drop class
        FaultSpec("overlay.recv", "corrupt", start=30, count=2,
                  match={"node": n2, "peer": n1}),
        # crash node 3 between applyTx and upgrades on its 5th close
        # (seq 6): the close transaction rolls back, the node is dead
        FaultSpec("ledger.close.crash.applyTx", "crash", start=4,
                  count=1, match={"node": n3}),
        # device outage on node 0: every supervised dispatch inside the
        # window fails. The breaker trips after the threshold (zero
        # device attempts while OPEN — pure native degraded mode), the
        # backoff probes burn the rest of the window, then a probe
        # succeeds and the breaker re-closes. Validation must stay
        # byte-identical throughout.
        FaultSpec("ops.backend.dispatch", "io_error", start=0,
                  count=DEVICE_OUTAGE_FAULTS, match={"node": n0}),
        # first archive fetch attempt fails; the work system retries
        FaultSpec("history.get", "fail", start=0, count=1),
    ]


class _RootPayer:
    """Deterministic per-ledger load: one root self-payment, submitted
    to EVERY alive node so any slot leader proposes the identical tx
    set regardless of which flood messages chaos ate."""

    def __init__(self, sim, network_id: bytes):
        self.sim = sim
        self.network_id = network_id
        self.key = SecretKey.from_seed(network_id)
        app = sim.apps()[0]
        from ..ledger.ledger_txn import LedgerTxn
        from ..xdr.types import PublicKey
        with LedgerTxn(app.ledger_manager.root) as ltx:
            le = ltx.load_without_record(LedgerKey.account(
                PublicKey.ed25519(self.key.public_key().raw)))
            self.seq = le.data.value.seqNum
        self.submitted = 0

    def submit_one(self) -> None:
        self.seq += 1
        muxed = MuxedAccount.from_ed25519(self.key.public_key().raw)
        tx = Transaction(
            sourceAccount=muxed, fee=100, seqNum=self.seq,
            cond=Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE),
            operations=[Operation(sourceAccount=None, body=_OperationBody(
                OperationType.PAYMENT, PaymentOp(
                    destination=muxed,
                    asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                    amount=1)))],
            ext=_TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=tx, signatures=[]))
        probe = make_frame(env, self.network_id)
        sig = self.key.sign(probe.contents_hash())
        env.value.signatures = [DecoratedSignature(
            hint=self.key.public_key().hint(), signature=sig)]
        raw = env.to_bytes()
        for app in self.sim.alive_apps():
            # fresh frame per node: frames carry mutable per-node state
            frame = make_frame(TransactionEnvelope.from_bytes(raw),
                               self.network_id)
            # batched admission path: with a verify service installed
            # the envelope signature rides the supervised device
            # backend (ISSUE 5 — admission load must survive a device
            # outage); without one it falls back to the sync path
            res = app.herder.recv_transactions([frame])[0]
            if res not in (AddResult.ADD_STATUS_PENDING,
                           AddResult.ADD_STATUS_DUPLICATE):
                raise RuntimeError(f"chaos load tx rejected: {res}")
        self.submitted += 1


def _build_sim(n_nodes: int = 4):
    def configure(cfg):
        # pinned close times → header bytes identical across runs
        cfg.ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING = 1
        # single-threaded node: merge schedule on the calling thread
        cfg.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING = True

    sim = topologies.core(n_nodes, configure=configure)
    for app in sim.apps():
        # inline completion: chaos hit ordinals stay deterministic
        app.ledger_manager.defer_completion = False
    return sim


def _crank_with_crashes(sim, pred, timeout: float,
                        churned: Optional[List[bytes]] = None
                        ) -> List[bytes]:
    """crank_until that treats SimulatedCrash as a node death: the
    crashed node is buried (links severed, timers silenced) and the
    rest of the network cranks on. A SimulatedChurn — a crash the
    caller will resurrect via Simulation.restart_node — is buried the
    same way but lands in `churned` (when given) instead of the
    returned permanent-death list. Shared with simulation/byzantine.py."""
    crashed: List[bytes] = []
    deadline = sim.clock.now() + timeout
    while not pred() and sim.clock.now() < deadline:
        try:
            if sim.clock.crank(False) == 0:
                sim.clock.crank(True)
        except SimulatedCrash as cr:
            node = bytes.fromhex(cr.ctx.get("node", ""))
            is_churn = isinstance(cr, SimulatedChurn)
            log.info("chaos: node %s %s at %s", node.hex()[:8],
                     "churned" if is_churn else "crashed", cr.point)
            sim.crash_node(node)
            if is_churn and churned is not None:
                churned.append(node)
            else:
                crashed.append(node)
    return crashed


def _collect_hashes(sim, upto: int) -> Dict[bytes, List[bytes]]:
    """node id -> [header hash for seq 2..upto] for surviving nodes."""
    out: Dict[bytes, List[bytes]] = {}
    for nid, app in sim.nodes.items():
        if nid in sim.crashed:
            continue
        hashes = []
        for seq in range(2, upto + 1):
            row = app.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
                (seq,))
            hashes.append(bytes(row[0]) if row else b"")
        out[nid] = hashes
    return out


def _archive_fetch_leg(app, archive_dir: str) -> dict:
    """Exercise archive-get failure + retry through the real work
    machinery: seed a HAS into a tmpdir archive, fetch it via
    GetHistoryArchiveStateWork while the chaos schedule fails the first
    attempt."""
    from ..catchup.catchup_work import GetHistoryArchiveStateWork
    from ..history.archive import (HAS_PATH, HistoryArchiveState,
                                   make_tmpdir_archive)
    from ..work import run_work_to_completion
    from ..work.basic_work import State

    archive = make_tmpdir_archive("chaos", archive_dir)
    has_path = os.path.join(archive_dir, HAS_PATH)
    os.makedirs(os.path.dirname(has_path), exist_ok=True)
    if not os.path.exists(has_path):
        with open(has_path, "w") as f:
            f.write(HistoryArchiveState(
                current_ledger=1,
                network_passphrase=app.config.NETWORK_PASSPHRASE)
                .to_json())
    work = GetHistoryArchiveStateWork(app, archive)
    final = run_work_to_completion(app, work)
    return {"ok": final == State.WORK_SUCCESS and work.has is not None,
            "fetched_ledger": work.has.current_ledger
            if work.has is not None else None}


def _run_leg(seed: int, target: int, archive_dir: Optional[str],
             with_faults: bool) -> dict:
    """One full scenario leg. Returns hashes + chaos evidence."""
    # every leg starts with a COLD process-wide verify cache: the
    # coalescing verify service probes it on submit, so a cache warmed
    # by an earlier leg would change which verifies enqueue → which
    # flushes fire → which chaos hit ordinals match, breaking the
    # leg-to-leg reproducibility the verdict asserts
    from ..crypto.keys import clear_verify_cache
    clear_verify_cache()
    sim = _build_sim()
    node_ids = list(sim.nodes.keys())
    eng = None
    if with_faults:
        eng = ChaosEngine(seed, default_schedule(node_ids))
        chaos.install(eng)
    try:
        sim.start_all_nodes()
        # crash-aware from the first crank: a schedule may legally
        # crash a node before ledger 2
        crashed: List[bytes] = []
        crashed += _crank_with_crashes(
            sim, lambda: sim.have_alive_externalized(2), timeout=60.0)
        if not sim.have_alive_externalized(2):
            raise RuntimeError("network never closed ledger 2")
        payer = _RootPayer(sim, sim.apps()[0].config.network_id())
        if with_faults:
            # only the faulted legs carry the device stack — the FULL
            # stack on EVERY node (ISSUE 4/5): batch verifier behind
            # the backend supervisor, plus the coalescing verify
            # service, so SCP envelope and StellarValue verifies ride
            # micro-batches through the circuit breaker. Node 0's
            # outage window (DEVICE_OUTAGE_FAULTS dispatch failures)
            # trips its breaker OPEN — degraded native mode with ZERO
            # device attempts — then the seeded-backoff canary probes
            # burn the window and the breaker re-closes, all while
            # accept/reject stays identical (safety leg) and the
            # schedule reproduces (repro leg). device_min_batch=16 and
            # canary_batch=4 keep every dispatch on the host: the
            # scenario must not depend on XLA compiles. Probe backoff
            # jitter is seeded by node id — deterministic per node,
            # decorrelated across nodes.
            from ..ops.backend_supervisor import BackendSupervisor
            from ..ops.verifier import TpuBatchVerifier
            from ..ops.verify_service import VerifyService
            for vapp in sim.alive_apps():
                inner = TpuBatchVerifier(perf=vapp.perf,
                                         device_min_batch=16)
                sup = BackendSupervisor(
                    inner, clock=sim.clock, metrics=vapp.metrics,
                    perf=vapp.perf, failure_threshold=3,
                    probe_base_ms=500.0, probe_max_ms=2000.0,
                    canary_batch=4,
                    jitter_seed=vapp.config.jitter_seed(),
                    chaos_label=vapp.config.node_id().hex())
                vapp.batch_verifier = sup
                vapp.herder.batch_verifier = sup
                vapp.verify_service = VerifyService(
                    sup, clock=sim.clock, metrics=vapp.metrics,
                    perf=vapp.perf)
                vapp.herder.verify_service = vapp.verify_service
        for seq in range(FIRST_LOADED_LEDGER, target + 1):
            payer.submit_one()
            if with_faults:
                # drive a candidate set with the fresh payment through
                # node 0's full validation path (its own proposals are
                # validity-cache-seeded, so a foreign-set validation is
                # modeled explicitly): the device-verifier fault fires
                # and the native fallback must still accept the set.
                # Cold verify cache first — the prevalidator only
                # dispatches cache misses, and admission warmed it
                # (deterministic: every faulted leg clears at the same
                # points)
                clear_verify_cache()
                from ..herder import make_tx_set_from_transactions
                app0 = sim.apps()[0]
                lcl = app0.ledger_manager.get_last_closed_ledger_header()
                frame, _, _ = make_tx_set_from_transactions(
                    app0.herder.tx_queue.get_transactions(), lcl,
                    app0.config.network_id())
                if not app0.herder._check_tx_set_valid(frame):
                    raise RuntimeError(
                        "native fallback rejected a valid tx set")
            crashed += _crank_with_crashes(
                sim, lambda s=seq: sim.have_alive_externalized(s),
                timeout=120.0)
            if not sim.have_alive_externalized(seq):
                raise RuntimeError(
                    f"liveness lost: survivors stalled before {seq}")
        breaker = None
        if with_faults:
            # let node 0's breaker settle: its outage window is sized
            # so the backoff probes exhaust it and re-close the breaker
            # — crank until that happens (probe timers keep the clock
            # moving even after the target ledger externalized)
            sup0 = sim.apps()[0].batch_verifier
            crashed += _crank_with_crashes(
                sim, lambda: sup0.state == "CLOSED", timeout=30.0)
            breaker = sup0.status()
        hashes = _collect_hashes(sim, target)
        # every surviving node must serve a valid clusterstatus
        # snapshot (mesh observatory): the structured health document
        # the multi-process harness (ROADMAP item 4) will collect over
        # HTTP instead of poking app objects
        import json as _json
        cluster: Dict[str, bool] = {}
        for nid, vapp in sim.nodes.items():
            if nid in sim.crashed:
                continue
            try:
                doc = vapp.command_handler.handle("clusterstatus")
                _json.dumps(doc)            # must be valid JSON
                cs = doc["clusterstatus"]
                cluster[nid.hex()[:8]] = bool(
                    cs["ledger"]["num"] >= target
                    and "close" in cs and "flood" in cs)
            except Exception:               # noqa: BLE001 — verdict data
                cluster[nid.hex()[:8]] = False
        archive_leg = None
        if archive_dir is not None:
            archive_leg = _archive_fetch_leg(sim.apps()[0], archive_dir)
        return {
            "hashes": hashes,
            "clusterstatus": cluster,
            "crashed": [n.hex() for n in crashed],
            "survivors": [n.hex() for n in sim.nodes
                          if n not in sim.crashed],
            "injected": dict(eng.injected) if eng else {},
            "log": list(eng.log) if eng else [],
            "virtual_end": sim.clock.now(),
            "archive": archive_leg,
            "breaker": breaker,
        }
    finally:
        if with_faults:
            chaos.uninstall()
        sim.stop_all_nodes()


def _breaker_verdict(status: Optional[dict]) -> dict:
    """Judge one node's breaker evidence (ISSUE 5 acceptance,
    per-device since ISSUE 13): some device must have tripped OPEN,
    probed via HALF_OPEN, re-closed (aggregate back to CLOSED), and
    made ZERO dispatch attempts while OPEN — per DEVICE: the device's
    own dispatch-counter snapshot at each of its OPEN→HALF_OPEN
    transitions equals the snapshot at its preceding →OPEN one.
    Sibling devices and probes of other chips may dispatch in between
    (that is the point of the mesh); the OPEN device itself must not."""
    if not status:
        return {"ok": False, "reason": "no breaker evidence"}
    trans = status["transitions"]
    tripped = any(t["to"] == "OPEN" for t in trans)
    probed = any(t["to"] == "HALF_OPEN" for t in trans)
    # re-close is judged PER DEVICE: the aggregate reads CLOSED the
    # moment any one chip serves, so it alone would certify a mesh
    # with a sibling stuck OPEN — every device that ever tripped must
    # have been readmitted by the end of the run
    tripped_devices = {t.get("device", 0) for t in trans
                       if t["to"] == "OPEN"}
    rows = {d["device"]: d["state"]
            for d in status.get("devices", [])}
    devices_reclosed = all(rows.get(d, "CLOSED") == "CLOSED"
                           for d in tripped_devices)
    reclosed = tripped and status["state"] == "CLOSED" \
        and devices_reclosed
    quiet = True
    last_open: Dict[int, int] = {}       # device -> snapshot at →OPEN
    for t in trans:
        dev = t.get("device", 0)
        snap = t.get("device_dispatches", t["dispatches"])
        if t["to"] == "OPEN":
            last_open[dev] = snap
        elif t["to"] == "HALF_OPEN" and dev in last_open:
            quiet = quiet and snap == last_open[dev]
    return {
        "ok": tripped and probed and reclosed and quiet,
        "tripped": tripped,
        "probed": probed,
        "reclosed": reclosed,
        "quiet_while_open": quiet,
        "transitions": trans,
        "skips": status["skips"],
        "dispatches": status["dispatches"],
        "failures": status["failures"],
    }


def run_scenario(seed: int = 6, target: int = DEFAULT_TARGET,
                 archive_dir: Optional[str] = None,
                 check_repro: bool = True) -> dict:
    """Run the canonical chaos scenario: a fault-free baseline, the
    seeded chaos leg, and (optionally) a second chaos leg to prove the
    schedule reproduces. Returns a verdict dict; every `*_ok` flag must
    be True for the scenario to count as converged."""
    # a baseline failure is a broken harness, not a chaos verdict —
    # let it raise
    baseline = _run_leg(seed, target, None, with_faults=False)
    try:
        chaos_a = _run_leg(seed, target, archive_dir, with_faults=True)
    except (RuntimeError, SimulatedCrash) as e:
        # survivors stalled / load rejected under faults — or a crash
        # fired outside the crash-aware crank (e.g. inside submission):
        # liveness lost, recorded as a verdict rather than an abort
        log.error("chaos leg failed: %r", e)
        return {"seed": seed, "target": target, "liveness_ok": False,
                "safety_ok": False, "repro_ok": False,
                "archive_ok": False, "breaker_ok": False,
                "clusterstatus_ok": False, "error": repr(e)}

    # safety: every surviving node's chain is byte-identical to the
    # fault-free run's (any baseline node is a reference — they agree)
    ref = next(iter(baseline["hashes"].values()))
    safety_ok = all(h == ref for h in chaos_a["hashes"].values()) and \
        all(h != b"" for h in ref)
    # the chaos leg reached `target` without raising; liveness still
    # requires somebody to have survived to do it
    liveness_ok = bool(chaos_a["survivors"])

    repro_ok = True
    if check_repro:
        try:
            chaos_b = _run_leg(seed, target, archive_dir,
                               with_faults=True)
        except (RuntimeError, SimulatedCrash) as e:
            # same schedule, different outcome: not reproducible
            log.error("repro leg failed: %r", e)
            chaos_b = None
        repro_ok = (chaos_b is not None and
                    chaos_b["log"] == chaos_a["log"] and
                    chaos_b["hashes"] == chaos_a["hashes"] and
                    chaos_b["injected"] == chaos_a["injected"])

    classes = sorted(k.split(".")[-1] for k in chaos_a["injected"])
    # the archive leg is part of the verdict (see below for the
    # single-node device-outage leg, run_device_outage): a fetch that
    # never recovers from the injected failure is a failed fault class
    archive_ok = chaos_a["archive"] is None or \
        bool(chaos_a["archive"]["ok"])
    # node 0's circuit breaker must have tripped on the outage window,
    # probed on the backoff schedule and re-closed — with zero device
    # dispatch attempts while OPEN (ISSUE 5 acceptance)
    breaker = _breaker_verdict(chaos_a.get("breaker"))
    return {
        "seed": seed,
        "target": target,
        "liveness_ok": liveness_ok,
        "safety_ok": safety_ok,
        "repro_ok": repro_ok,
        "archive_ok": archive_ok,
        "breaker_ok": breaker["ok"],
        "breaker": breaker,
        # every survivor served a valid clusterstatus document
        "clusterstatus_ok": bool(chaos_a["clusterstatus"]) and
        all(chaos_a["clusterstatus"].values()),
        "clusterstatus": chaos_a["clusterstatus"],
        "survivors": chaos_a["survivors"],
        "crashed": chaos_a["crashed"],
        "injected": chaos_a["injected"],
        "fault_classes": classes,
        "archive_retry": chaos_a["archive"],
        "virtual_seconds": chaos_a["virtual_end"],
        "baseline_virtual_seconds": baseline["virtual_end"],
    }


def run_device_outage(seed: int = 9, ledgers: int = 14,
                      outage_at: int = 4) -> dict:
    """Single-node device-outage leg for ``bench.py --chaos`` (ISSUE 5
    satellite): fail the supervised backend mid-run and measure the
    operational envelope the breaker buys — time-to-trip (how long the
    node pays failure latency), degraded-mode tps (ledgers closed while
    the breaker is OPEN and every verify is native), and
    time-to-recovery (outage end → breaker re-CLOSED via a canary
    probe).

    A MANUAL_CLOSE standalone node closes `ledgers` ledgers, each
    carrying one root self-payment admitted through
    ``herder.recv_transactions`` so the envelope signature rides the
    verify service into the supervised backend (one dispatch per
    ledger). From ledger `outage_at` a seeded chaos schedule fails
    ``DEVICE_OUTAGE_FAULTS`` consecutive dispatches; between ledgers
    the virtual clock advances one second so the breaker's backoff
    probes fire on schedule. Times are VIRTUAL seconds (deterministic);
    tps is wall-clock (the artifact's measurement)."""
    import time as _time

    from ..ledger.ledger_txn import LedgerTxn
    from ..main import Application, get_test_config
    from ..util.timer import ClockMode, VirtualClock
    from ..xdr.types import PublicKey

    from ..crypto.keys import clear_verify_cache
    clear_verify_cache()
    cfg = get_test_config()
    cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
    # every dispatch stays on the host (no XLA compiles in the bench
    # leg); the breaker semantics under test are identical either way
    cfg.VERIFY_DEVICE_MIN_BATCH = 1 << 20
    cfg.VERIFY_BREAKER_CANARY_BATCH = 4
    cfg.VERIFY_BREAKER_PROBE_BASE_MS = 500.0
    cfg.VERIFY_BREAKER_PROBE_MAX_MS = 2000.0
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    app = Application.create(clock, cfg)
    app.start()
    sup = app.batch_verifier
    key = SecretKey.from_seed(cfg.network_id())
    with LedgerTxn(app.ledger_manager.root) as ltx:
        le = ltx.load_without_record(LedgerKey.account(
            PublicKey.ed25519(key.public_key().raw)))
        seq = le.data.value.seqNum
    phase_wall: Dict[str, List[float]] = {}
    outage_started_at = None
    try:
        for i in range(ledgers):
            if i == outage_at:
                chaos.install(ChaosEngine(seed, [FaultSpec(
                    "ops.backend.dispatch", "io_error", start=0,
                    count=DEVICE_OUTAGE_FAULTS,
                    match={"node": cfg.node_id().hex()})]))
                outage_started_at = clock.now()
            seq += 1
            muxed = MuxedAccount.from_ed25519(key.public_key().raw)
            tx = Transaction(
                sourceAccount=muxed, fee=100, seqNum=seq,
                cond=Preconditions(PreconditionType.PRECOND_NONE),
                memo=Memo(MemoType.MEMO_NONE),
                operations=[Operation(
                    sourceAccount=None,
                    body=_OperationBody(
                        OperationType.PAYMENT, PaymentOp(
                            destination=muxed,
                            asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                            amount=1)))],
                ext=_TxExt(0))
            env = TransactionEnvelope(
                EnvelopeType.ENVELOPE_TYPE_TX,
                TransactionV1Envelope(tx=tx, signatures=[]))
            probe = make_frame(env, cfg.network_id())
            env.value.signatures = [DecoratedSignature(
                hint=key.public_key().hint(),
                signature=key.sign(probe.contents_hash()))]
            frame = make_frame(env, cfg.network_id())
            # classify by breaker state AT DISPATCH: the ledger whose
            # failing verify trips the breaker pays failure latency
            # with the breaker still CLOSED on entry — it belongs in
            # "failing", not in the degraded-tps "open" bucket
            state = sup.state
            tripped = any(t[2] == "OPEN" for t in sup.transitions)
            t0 = _time.perf_counter()
            res = app.herder.recv_transactions([frame])[0]
            if res != AddResult.ADD_STATUS_PENDING:
                raise RuntimeError(f"outage-leg tx rejected: {res}")
            app.manual_close()
            if outage_started_at is None:
                ph = "before"
            elif state != "CLOSED":
                ph = "open"                # degraded mode: native, no
                #                            device attempt
            elif tripped:
                ph = "after"               # breaker re-closed, healthy
            else:
                ph = "failing"             # outage active, not yet
                #                            tripped: the full failure
                #                            latency the breaker exists
                #                            to eliminate
            phase_wall.setdefault(ph, []).append(
                _time.perf_counter() - t0)
            # advance virtual time so backoff probe timers fire
            clock.crank_for(1.0)
        verdict = _breaker_verdict(sup.status())
        trans = {(t["from"], t["to"]): t["t"]
                 for t in reversed(verdict.get("transitions", []))}
        tripped_at = trans.get(("CLOSED", "OPEN"))
        reclosed_at = None
        for t in verdict.get("transitions", []):
            if t["to"] == "CLOSED":
                reclosed_at = t["t"]
        tps = {ph: round(len(v) / sum(v), 1)
               for ph, v in phase_wall.items() if v}
        return {
            "ok": bool(verdict["ok"]),
            "ledgers": ledgers,
            "outage_faults": DEVICE_OUTAGE_FAULTS,
            "time_to_trip_s": round(tripped_at - outage_started_at, 3)
            if tripped_at is not None and outage_started_at is not None
            else None,
            "time_to_recovery_s": round(reclosed_at - tripped_at, 3)
            if reclosed_at is not None and tripped_at is not None
            else None,
            "degraded_tps": tps.get("open"),
            "tps": tps,
            "breaker": verdict,
        }
    finally:
        chaos.uninstall()
        app.shutdown()


class _HostMeshVerifier:
    """N-device mesh stand-in with host-side verify (no XLA): the
    sick-device window's subject is the supervisor's breaker/mesh
    machinery, and the soak must not pay kernel compiles. Duck-types
    the ShardedBatchVerifier mesh surface the supervisor drives."""

    def __init__(self, ndev: int):
        self.ndev = ndev
        self._active = tuple(range(ndev))
        self.active_log: List[tuple] = []

    def set_active_devices(self, indices) -> None:
        self._active = tuple(sorted(int(i) for i in indices))
        self.active_log.append(self._active)

    def active_indices(self):
        return self._active

    def verify_tuples_async(self, items):
        from ..crypto.keys import verify_sig_uncached
        res = [verify_sig_uncached(p, s, m) for p, s, m in items]
        return lambda: res

    def verify_tuples_async_on(self, device_index, items):
        return self.verify_tuples_async(items)


def run_sick_device_window(seed: int = 11, ndev: int = 4, sick: int = 2,
                           flushes: int = 10) -> dict:
    """Sick-device chaos window (ISSUE 13, the chaos_soak leg): a
    device-index-matched ``io_error`` window on the per-device dispatch
    seam (``ops.backend.dispatch.device``, match={"device": sick})
    must trip exactly ONE chip of an N-device mesh — the mesh shrinks
    to the survivors, the open device sees ZERO further dispatches
    while its siblings keep serving and every result stays exact —
    and once the window is exhausted the canary probes must readmit
    it, regrowing the mesh to N/N. Deterministic: same seed → same
    injected faults → same transition log (the soak asserts repro by
    running it twice)."""
    from ..crypto.keys import SecretKey, verify_sig_uncached
    from ..ops.backend_supervisor import BackendSupervisor

    threshold = 2
    window = threshold + 1      # trip consumes 2 hits, first probe 1
    inner = _HostMeshVerifier(ndev)
    sup = BackendSupervisor(inner, clock=None,
                            failure_threshold=threshold,
                            probe_base_ms=100.0, probe_max_ms=400.0,
                            canary_batch=4, jitter_seed=seed,
                            chaos_label="sickdev")
    sk = SecretKey.pseudo_random_for_testing(seed)
    items = []
    for i in range(6):
        msg = (b"sick-%d" % i).ljust(32, b".")
        items.append((sk.public_key().raw, sk.sign(msg), msg))
    items[4] = (items[4][0], b"\x01" * 64, items[4][2])   # one invalid
    want = [verify_sig_uncached(p, s, m) for p, s, m in items]
    eng = ChaosEngine(seed, [FaultSpec(
        "ops.backend.dispatch.device", "io_error", start=0,
        count=window, match={"device": sick})])
    chaos.install(eng)
    exact = True
    agg_during_outage = []
    try:
        for _ in range(flushes):
            exact = exact and sup.verify_tuples(items) == want
            if sup.status()["devices"][sick]["state"] == "OPEN":
                agg_during_outage.append(sup.state)
        st = sup.status()
        survivors = [d for d in st["devices"] if d["device"] != sick]
        sick_row = st["devices"][sick]
        tripped = sick_row["state"] == "OPEN"
        siblings_closed = all(d["state"] == "CLOSED" for d in survivors)
        # zero dispatches to the open device: its counter froze at the
        # trip snapshot while the siblings kept dispatching
        trip_snap = next((t["device_dispatches"]
                          for t in reversed(st["transitions"])
                          if t["device"] == sick and t["to"] == "OPEN"),
                         None)
        quiet = trip_snap is not None and \
            sick_row["dispatches"] == trip_snap
        siblings_served = all(d["dispatches"] > trip_snap
                              for d in survivors) if tripped else False
        shrunk = inner.active_indices() == tuple(
            i for i in range(ndev) if i != sick)
        # first probe burns the window's last hit, the second readmits
        probe1 = sup.probe_now(device=sick)
        probe2 = sup.probe_now(device=sick)
        regrown = inner.active_indices() == tuple(range(ndev)) and \
            sup.status()["devices"][sick]["state"] == "CLOSED"
        return {
            "ok": bool(exact and tripped and siblings_closed and quiet
                       and siblings_served and shrunk
                       and not probe1 and probe2 and regrown
                       and all(s == "CLOSED"
                               for s in agg_during_outage)),
            "exact": bool(exact),
            "tripped": bool(tripped),
            "siblings_closed": bool(siblings_closed),
            "quiet_while_open": bool(quiet),
            "siblings_served": bool(siblings_served),
            "shrunk": bool(shrunk),
            "probe_in_window_failed": bool(not probe1),
            "regrown": bool(regrown),
            "aggregate_stayed_closed": bool(
                all(s == "CLOSED" for s in agg_during_outage)),
            "injected": dict(eng.injected),
            "log": list(eng.log),
            "transitions": sup.status()["transitions"],
        }
    finally:
        chaos.uninstall()
        sup.shutdown()
