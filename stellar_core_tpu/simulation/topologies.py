"""Standard quorum/connection topologies for simulations.

Reference: src/simulation/Topologies.{h,cpp} — pair, cycle, core
(complete graph), and hierarchical arrangements used across the herder,
overlay, and history test suites. The `tiered` generator (ISSUE 7)
scales to 50–100 in-process nodes: orgs × validators with an org-level
quorum structure (the pubnet shape), an optional watcher tier, and a
deterministic per-link latency/bandwidth model riding the loopback
delay machinery on the VirtualClock (docs/SIMULATION.md).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..main.config import QuorumSetConfig
from .simulation import Simulation


def _seeds(n: int, tag: bytes) -> List[SecretKey]:
    return [SecretKey.from_seed(sha256(b"topo-%s-%d" % (tag, i)))
            for i in range(n)]


def pair(passphrase: str = "(V) (;,,;) (V)") -> Simulation:
    """Two validators, each requiring both (reference: Topologies::pair)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(2, b"pair")
    ids = [s.public_key().raw for s in seeds]
    qset = QuorumSetConfig(threshold=2, validators=ids)
    for s in seeds:
        sim.add_node(s, qset)
    sim.add_pending_connection(ids[0], ids[1])
    return sim


def core(n: int, threshold: Optional[int] = None,
         passphrase: str = "(V) (;,,;) (V)",
         configure=None) -> Simulation:
    """n validators, complete connection graph, one flat qset
    (reference: Topologies::core; `configure` mirrors the reference's
    per-node confGen callback)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n, b"core")
    ids = [s.public_key().raw for s in seeds]
    qset = QuorumSetConfig(threshold=threshold or (2 * n + 2) // 3,
                           validators=ids)
    for s in seeds:
        sim.add_node(s, qset, configure=configure)
    for i in range(n):
        for j in range(i + 1, n):
            sim.add_pending_connection(ids[i], ids[j])
    return sim


def cycle(n: int, passphrase: str = "(V) (;,,;) (V)") -> Simulation:
    """n validators in a ring: each trusts itself + both neighbours
    (threshold 2 of 3), connected in a cycle (reference:
    Topologies::cycle4 generalized)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n, b"cycle")
    ids = [s.public_key().raw for s in seeds]
    for i, s in enumerate(seeds):
        neighbours = [ids[i], ids[(i - 1) % n], ids[(i + 1) % n]]
        sim.add_node(s, QuorumSetConfig(threshold=2,
                                        validators=neighbours))
    for i in range(n):
        sim.add_pending_connection(ids[i], ids[(i + 1) % n])
    return sim


# ------------------------------------------------------------ tiered -----
class LinkLatency:
    """Deterministic per-link latency/bandwidth assignment: intra-org
    links are LAN-fast, cross-org links draw WAN latencies from a
    seeded RNG (the Tail-at-Scale shape — a few links are much slower
    than the median), watcher links sit in between. All figures are
    VIRTUAL seconds; delivery rides the VirtualClock."""

    def __init__(self, seed: int = 7,
                 intra_org_ms: float = 2.0,
                 cross_org_ms: tuple = (30.0, 150.0),
                 watcher_ms: float = 20.0,
                 bandwidth_bps: Optional[float] = None):
        self._rng = random.Random(seed)
        self.intra_org_ms = intra_org_ms
        self.cross_org_ms = cross_org_ms
        self.watcher_ms = watcher_ms
        self.bandwidth_bps = bandwidth_bps

    def for_link(self, kind: str) -> tuple:
        if kind == "intra":
            ms = self.intra_org_ms
        elif kind == "watcher":
            ms = self.watcher_ms
        else:
            lo, hi = self.cross_org_ms
            ms = lo + (hi - lo) * self._rng.random()
        return ms / 1000.0, self.bandwidth_bps


def tiered_org_seeds(n_orgs: int, validators_per_org: int
                     ) -> List[List[SecretKey]]:
    return [_seeds(validators_per_org, b"tier-org-%d" % o)
            for o in range(n_orgs)]


def tiered_qset(org_ids: List[List[bytes]],
                org_threshold: Optional[int] = None,
                top_threshold: Optional[int] = None,
                unsafe: bool = False) -> QuorumSetConfig:
    """The pubnet-shaped quorum set every tiered node runs: inner set
    per org (`org_threshold`-of-members, default simple majority + 1
    rounding = byzantine-safe 2f+1 for 3) and `top_threshold` of the
    orgs (default 2f+1). Deliberately under-thresholded configs are
    REJECTED unless `unsafe=True` — an org threshold at or below half,
    or a top threshold at or below 2/3 of orgs, forfeits quorum
    intersection (test_quorum_intersection.py feeds the weak shapes
    through the checker and watches it find the split)."""
    n_orgs = len(org_ids)
    per_org = len(org_ids[0]) if org_ids else 0
    org_thr = org_threshold if org_threshold is not None else \
        (2 * per_org + 2) // 3
    top_thr = top_threshold if top_threshold is not None else \
        (2 * n_orgs + 2) // 3
    if not unsafe:
        # quorum intersection needs a strict majority at BOTH levels:
        # two disjoint threshold-subsets exist the moment thr*2 <= n
        # (the checker in test_quorum_intersection.py finds the split
        # for exactly these shapes)
        if org_thr * 2 <= per_org:
            raise ValueError(
                "org threshold %d of %d validators cannot guarantee "
                "quorum intersection (need a strict majority); pass "
                "unsafe=True to build it anyway" % (org_thr, per_org))
        if top_thr * 2 <= n_orgs:
            raise ValueError(
                "top-level threshold %d of %d orgs cannot guarantee "
                "quorum intersection (need a strict majority); pass "
                "unsafe=True to build it anyway" % (top_thr, n_orgs))
    inner = [QuorumSetConfig(threshold=org_thr, validators=list(org))
             for org in org_ids]
    return QuorumSetConfig(threshold=top_thr, validators=[],
                           inner_sets=inner)


def tiered_qmap(n_orgs: int = 3, validators_per_org: int = 3,
                org_threshold: Optional[int] = None,
                top_threshold: Optional[int] = None,
                unsafe: bool = False) -> Dict[bytes, object]:
    """node id -> SCPQuorumSet for the tiered topology WITHOUT building
    any Application — feeds the quorum intersection checker directly
    (tests/test_quorum_intersection.py)."""
    org_seeds = tiered_org_seeds(n_orgs, validators_per_org)
    org_ids = [[s.public_key().raw for s in org] for org in org_seeds]
    qset = tiered_qset(org_ids, org_threshold, top_threshold,
                       unsafe=unsafe).to_scp_quorum_set()
    return {nid: qset for org in org_ids for nid in org}


def tiered_links(org_ids: List[List[bytes]],
                 watcher_ids: Optional[List[bytes]] = None
                 ) -> List[tuple]:
    """The tiered topology's link list as ``(a, b, kind)`` tuples —
    complete graph inside each org, each validator braided to its
    positional peer in the next org, two validator uplinks per
    watcher. Shared by the in-process ``tiered()`` Simulation builder
    and the multi-process cluster harness (simulation/cluster.py),
    which wires the same mesh over real TCP sockets."""
    links: List[tuple] = []
    seen: set = set()

    def _add(a: bytes, b: bytes, kind: str) -> None:
        # undirected dedupe: with 2 orgs the braid's wrap-around emits
        # each cross pair from both sides, and a doubled link would
        # overstate every harness node's expected mesh degree
        if a == b or frozenset((a, b)) in seen:
            return
        seen.add(frozenset((a, b)))
        links.append((a, b, kind))

    for org in org_ids:
        for i in range(len(org)):
            for j in range(i + 1, len(org)):
                _add(org[i], org[j], "intra")
    n_orgs = len(org_ids)
    for o in range(n_orgs):
        nxt = org_ids[(o + 1) % n_orgs]
        for i, nid in enumerate(org_ids[o]):
            _add(nid, nxt[i % len(nxt)], "cross")
    flat_ids = [nid for org in org_ids for nid in org]
    for w, wid in enumerate(watcher_ids or []):
        for k in range(2):
            _add(wid, flat_ids[(w + k * 7) % len(flat_ids)], "watcher")
    return links


def tiered(n_orgs: int = 3, validators_per_org: int = 3,
           watchers: int = 0,
           org_threshold: Optional[int] = None,
           top_threshold: Optional[int] = None,
           passphrase: str = "(V) (;,,;) (V)",
           configure=None, data_dir: Optional[str] = None,
           latency: Optional[LinkLatency] = None,
           unsafe: bool = False) -> Simulation:
    """Tiered-quorum network (ISSUE 7): `n_orgs` orgs ×
    `validators_per_org` validators plus a non-validating watcher tier,
    scaling to 50–100 in-process nodes. Connections: complete graph
    inside each org, each validator linked to its positional peer in
    the next org (a braided inter-org ring — O(n) links, no O(n²)
    blowup at 100 nodes), watchers fanned across the validators. With
    `latency`, every link gets a deterministic virtual-time
    latency/bandwidth assignment."""
    sim = Simulation(network_passphrase=passphrase, data_dir=data_dir)
    org_seeds = tiered_org_seeds(n_orgs, validators_per_org)
    org_ids = [[s.public_key().raw for s in org] for org in org_seeds]
    qset = tiered_qset(org_ids, org_threshold, top_threshold,
                       unsafe=unsafe)
    for org in org_seeds:
        for s in org:
            sim.add_node(s, qset, configure=configure)

    def watcher_configure(cfg):
        if configure is not None:
            configure(cfg)
        cfg.NODE_IS_VALIDATOR = False
        cfg.FORCE_SCP = False

    watcher_seeds = _seeds(watchers, b"tier-watcher")
    for s in watcher_seeds:
        sim.add_node(s, qset, configure=watcher_configure)
    # the shared edge list (also the cluster harness's mesh): intra-org
    # complete graphs, braided inter-org ring, two validator uplinks
    # per watcher spread across orgs
    for a, b, kind in tiered_links(
            org_ids, [s.public_key().raw for s in watcher_seeds]):
        lat, bw = latency.for_link(kind) if latency else (0.0, None)
        sim.add_pending_connection(a, b, latency_s=lat,
                                   bandwidth_bps=bw)
    return sim


def hierarchical_quorum(n_core: int, n_outer: int,
                        passphrase: str = "(V) (;,,;) (V)") -> Simulation:
    """A core clique plus outer validators that trust the core
    (reference: Topologies::hierarchicalQuorum, simplified)."""
    sim = Simulation(network_passphrase=passphrase)
    core_seeds = _seeds(n_core, b"hcore")
    core_ids = [s.public_key().raw for s in core_seeds]
    core_qset = QuorumSetConfig(threshold=(2 * n_core + 2) // 3,
                                validators=core_ids)
    for s in core_seeds:
        sim.add_node(s, core_qset)
    outer_seeds = _seeds(n_outer, b"houter")
    for s in outer_seeds:
        # outer nodes: require a core majority
        sim.add_node(s, QuorumSetConfig(
            threshold=(n_core // 2) + 1, validators=list(core_ids)))
    for i in range(n_core):
        for j in range(i + 1, n_core):
            sim.add_pending_connection(core_ids[i], core_ids[j])
    for i, s in enumerate(outer_seeds):
        sim.add_pending_connection(s.public_key().raw,
                                   core_ids[i % n_core])
    return sim
