"""Standard quorum/connection topologies for simulations.

Reference: src/simulation/Topologies.{h,cpp} — pair, cycle, core
(complete graph), and hierarchical arrangements used across the herder,
overlay, and history test suites.
"""

from __future__ import annotations

from typing import List, Optional

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..main.config import QuorumSetConfig
from .simulation import Simulation


def _seeds(n: int, tag: bytes) -> List[SecretKey]:
    return [SecretKey.from_seed(sha256(b"topo-%s-%d" % (tag, i)))
            for i in range(n)]


def pair(passphrase: str = "(V) (;,,;) (V)") -> Simulation:
    """Two validators, each requiring both (reference: Topologies::pair)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(2, b"pair")
    ids = [s.public_key().raw for s in seeds]
    qset = QuorumSetConfig(threshold=2, validators=ids)
    for s in seeds:
        sim.add_node(s, qset)
    sim.add_pending_connection(ids[0], ids[1])
    return sim


def core(n: int, threshold: Optional[int] = None,
         passphrase: str = "(V) (;,,;) (V)",
         configure=None) -> Simulation:
    """n validators, complete connection graph, one flat qset
    (reference: Topologies::core; `configure` mirrors the reference's
    per-node confGen callback)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n, b"core")
    ids = [s.public_key().raw for s in seeds]
    qset = QuorumSetConfig(threshold=threshold or (2 * n + 2) // 3,
                           validators=ids)
    for s in seeds:
        sim.add_node(s, qset, configure=configure)
    for i in range(n):
        for j in range(i + 1, n):
            sim.add_pending_connection(ids[i], ids[j])
    return sim


def cycle(n: int, passphrase: str = "(V) (;,,;) (V)") -> Simulation:
    """n validators in a ring: each trusts itself + both neighbours
    (threshold 2 of 3), connected in a cycle (reference:
    Topologies::cycle4 generalized)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n, b"cycle")
    ids = [s.public_key().raw for s in seeds]
    for i, s in enumerate(seeds):
        neighbours = [ids[i], ids[(i - 1) % n], ids[(i + 1) % n]]
        sim.add_node(s, QuorumSetConfig(threshold=2,
                                        validators=neighbours))
    for i in range(n):
        sim.add_pending_connection(ids[i], ids[(i + 1) % n])
    return sim


def hierarchical_quorum(n_core: int, n_outer: int,
                        passphrase: str = "(V) (;,,;) (V)") -> Simulation:
    """A core clique plus outer validators that trust the core
    (reference: Topologies::hierarchicalQuorum, simplified)."""
    sim = Simulation(network_passphrase=passphrase)
    core_seeds = _seeds(n_core, b"hcore")
    core_ids = [s.public_key().raw for s in core_seeds]
    core_qset = QuorumSetConfig(threshold=(2 * n_core + 2) // 3,
                                validators=core_ids)
    for s in core_seeds:
        sim.add_node(s, core_qset)
    outer_seeds = _seeds(n_outer, b"houter")
    for s in outer_seeds:
        # outer nodes: require a core majority
        sim.add_node(s, QuorumSetConfig(
            threshold=(n_core // 2) + 1, validators=list(core_ids)))
    for i in range(n_core):
        for j in range(i + 1, n_core):
            sim.add_pending_connection(core_ids[i], core_ids[j])
    for i, s in enumerate(outer_seeds):
        sim.add_pending_connection(s.public_key().raw,
                                   core_ids[i % n_core])
    return sim
