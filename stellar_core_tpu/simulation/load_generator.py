"""Synthetic transaction load.

Reference: src/simulation/LoadGenerator.{h,cpp} — modes CREATE / PAY
(LoadGenerator.h:28-35): synthesize accounts from the network root, then
rate-controlled payments among them, submitted through the herder like
any external transaction; completion is tracked against ledger closes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..herder.tx_queue import AddResult
from ..ledger.ledger_txn import LedgerTxn
from ..tx.frame import make_frame
from ..tx.tx_utils import starting_sequence_number
from ..util.logging import get_logger
from ..xdr.ledger_entries import LedgerKey
from ..xdr.transaction import (Memo, MemoType, MuxedAccount, Operation,
                               Preconditions, PreconditionType, Transaction,
                               TransactionEnvelope, TransactionV1Envelope,
                               _TxExt, DecoratedSignature, _OperationBody,
                               CreateAccountOp, PaymentOp)
from ..xdr.types import EnvelopeType, PublicKey
from ..xdr.transaction import OperationType
from ..xdr.ledger_entries import Asset, AssetType

log = get_logger("LoadGen")


class GeneratedAccount:
    def __init__(self, key: SecretKey, seq: int):
        self.key = key
        self.seq = seq

    @property
    def account_id(self) -> PublicKey:
        return PublicKey.ed25519(self.key.public_key().raw)

    @property
    def muxed(self) -> MuxedAccount:
        return MuxedAccount.from_ed25519(self.key.public_key().raw)


class LoadGenerator:
    def __init__(self, app):
        self.app = app
        self.network_id = app.config.network_id()
        self.accounts: List[GeneratedAccount] = []
        self.submitted = 0
        self.failed = 0
        root_key = SecretKey.from_seed(self.network_id)
        self.root = GeneratedAccount(root_key, self._live_seq(root_key))

    def _live_seq(self, key: SecretKey) -> int:
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            le = ltx.load_without_record(LedgerKey.account(
                PublicKey.ed25519(key.public_key().raw)))
            return le.data.value.seqNum if le else 0

    # ------------------------------------------------------------ building --
    def _sign_and_submit(self, source: GeneratedAccount,
                         ops: List[Operation]) -> AddResult:
        source.seq += 1
        tx = Transaction(
            sourceAccount=source.muxed, fee=100 * max(1, len(ops)),
            seqNum=source.seq,
            cond=Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE), operations=ops, ext=_TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=tx, signatures=[]))
        frame = make_frame(env, self.network_id)
        sig = source.key.sign(frame.contents_hash())
        frame.signatures.append(DecoratedSignature(
            hint=source.key.public_key().hint(), signature=sig))
        env.value.signatures = frame.signatures
        res = self.app.herder.recv_transaction(frame)
        self.submitted += 1
        if res != AddResult.ADD_STATUS_PENDING:
            self.failed += 1
            source.seq -= 1
        return res

    # --------------------------------------------------------------- modes --
    def generate_accounts(self, n: int,
                          balance: int = 10_000_0000000) -> int:
        """CREATE mode: fan accounts out of the root (reference:
        LoadGenerator::createAccounts)."""
        created = 0
        batch: List[Operation] = []
        new_accounts: List[GeneratedAccount] = []
        for i in range(n):
            key = SecretKey.from_seed(sha256(
                b"loadgen-%d-%d" % (len(self.accounts) + i,
                                    self.app.config.PEER_PORT)))
            new_accounts.append(GeneratedAccount(key, 0))
            batch.append(Operation(
                sourceAccount=None,
                body=_OperationBody(
                    OperationType.CREATE_ACCOUNT,
                    CreateAccountOp(
                        destination=PublicKey.ed25519(
                            key.public_key().raw),
                        startingBalance=balance))))
            if len(batch) == 100 or i == n - 1:
                if self._sign_and_submit(self.root, batch) == \
                        AddResult.ADD_STATUS_PENDING:
                    created += len(batch)
                    self.accounts.extend(new_accounts)
                batch, new_accounts = [], []
        return created

    def sync_account_seqs(self) -> None:
        """After a close, learn created accounts' live seqnums."""
        for acct in self.accounts:
            if acct.seq == 0:
                acct.seq = self._live_seq(acct.key)

    def generate_payments(self, n: int, amount: int = 10000) -> int:
        """PAY mode: random-ish payments among generated accounts."""
        assert len(self.accounts) >= 2, "run generate_accounts first"
        ok = 0
        for i in range(n):
            src = self.accounts[i % len(self.accounts)]
            dst = self.accounts[(i + 1) % len(self.accounts)]
            op = Operation(
                sourceAccount=None,
                body=_OperationBody(
                    OperationType.PAYMENT,
                    PaymentOp(destination=dst.muxed,
                              asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                              amount=amount)))
            if self._sign_and_submit(src, [op]) == \
                    AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok
