"""Synthetic transaction load.

Reference: src/simulation/LoadGenerator.{h,cpp} — modes CREATE / PAY /
PRETEND / MIXED_CLASSIC (payments + DEX offers) / SOROBAN upload
(LoadGenerator.h:28-35): synthesize accounts from the network root, then
rate-controlled transactions among them, submitted through the herder like
any external transaction; completion is tracked against ledger closes.
SOROBAN mode synthesizes random upload-wasm transactions sized against the
live SorobanNetworkConfig limits (LoadGenerator.cpp:469-494).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..herder.tx_queue import AddResult
from ..ledger.ledger_txn import LedgerTxn
from ..tx.frame import make_frame
from ..tx.tx_utils import starting_sequence_number
from ..util.checks import releaseAssert
from ..util.logging import get_logger
from ..xdr.ledger_entries import LedgerKey
from ..xdr.transaction import (Memo, MemoType, MuxedAccount, Operation,
                               Preconditions, PreconditionType, Transaction,
                               TransactionEnvelope, TransactionV1Envelope,
                               _TxExt, DecoratedSignature, _OperationBody,
                               CreateAccountOp, PaymentOp)
from ..xdr.types import EnvelopeType, PublicKey
from ..xdr.transaction import OperationType
from ..xdr.ledger_entries import Asset, AssetType

log = get_logger("LoadGen")


class GeneratedAccount:
    def __init__(self, key: SecretKey, seq: int):
        self.key = key
        self.seq = seq

    @property
    def account_id(self) -> PublicKey:
        return PublicKey.ed25519(self.key.public_key().raw)

    @property
    def muxed(self) -> MuxedAccount:
        return MuxedAccount.from_ed25519(self.key.public_key().raw)


class LoadGenerator:
    def __init__(self, app, seed: Optional[int] = None):
        self.app = app
        self.network_id = app.config.network_id()
        self.accounts: List[GeneratedAccount] = []
        self.submitted = 0
        self.failed = 0
        root_key = SecretKey.from_seed(self.network_id)
        self.root = GeneratedAccount(root_key, self._live_seq(root_key))
        # per-node-id seeded RNG (the PR 5 decorrelated-jitter pattern:
        # config.jitter_seed() is stable for one node and decorrelated
        # across nodes), so multi-node load is reproducible under a
        # fixed scenario seed yet no two nodes pick the same pattern;
        # an explicit `seed` pins the traffic shape regardless of node
        # identity (cross-app differential tests)
        self._rng = random.Random(app.config.jitter_seed()
                                  if seed is None else seed)
        self._perm: List[int] = []

    def _account_order(self) -> List[int]:
        """Seeded permutation of account indices, rebuilt when the
        account set grows: random-LOOKING traffic shape that is a
        deterministic function of the node id (never a per-tx random
        draw — that would skew the per-source spread and overflow the
        queue's pending depth)."""
        if len(self._perm) != len(self.accounts):
            self._perm = list(range(len(self.accounts)))
            self._rng.shuffle(self._perm)
        return self._perm

    def _live_seq(self, key: SecretKey) -> int:
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            le = ltx.load_without_record(LedgerKey.account(
                PublicKey.ed25519(key.public_key().raw)))
            return le.data.value.seqNum if le else 0

    # ------------------------------------------------------------ building --
    def _sign_and_submit(self, source: GeneratedAccount,
                         ops: List[Operation], fee: Optional[int] = None,
                         ext=None) -> AddResult:
        source.seq += 1
        tx = Transaction(
            sourceAccount=source.muxed,
            fee=fee if fee is not None else 100 * max(1, len(ops)),
            seqNum=source.seq,
            cond=Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE), operations=ops,
            ext=ext if ext is not None else _TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=tx, signatures=[]))
        frame = make_frame(env, self.network_id)
        sig = source.key.sign(frame.contents_hash())
        frame.signatures.append(DecoratedSignature(
            hint=source.key.public_key().hint(), signature=sig))
        env.value.signatures = frame.signatures
        res = self.app.herder.recv_transaction(frame)
        self.submitted += 1
        if res != AddResult.ADD_STATUS_PENDING:
            self.failed += 1
            source.seq -= 1
        return res

    # --------------------------------------------------------------- modes --
    def generate_accounts(self, n: int,
                          balance: int = 10_000_0000000) -> int:
        """CREATE mode: fan accounts out of the root (reference:
        LoadGenerator::createAccounts)."""
        created = 0
        batch: List[Operation] = []
        new_accounts: List[GeneratedAccount] = []
        # snapshot the numbering base: self.accounts grows batch-by-batch
        # inside this loop, so indexing off its live length would hand out
        # the same derivation index twice across calls
        base = len(self.accounts)
        for i in range(n):
            key = SecretKey.from_seed(sha256(
                b"loadgen-%d-%d" % (base + i,
                                    self.app.config.PEER_PORT)))
            new_accounts.append(GeneratedAccount(key, 0))
            batch.append(Operation(
                sourceAccount=None,
                body=_OperationBody(
                    OperationType.CREATE_ACCOUNT,
                    CreateAccountOp(
                        destination=PublicKey.ed25519(
                            key.public_key().raw),
                        startingBalance=balance))))
            if len(batch) == 100 or i == n - 1:
                if self._sign_and_submit(self.root, batch) == \
                        AddResult.ADD_STATUS_PENDING:
                    created += len(batch)
                    self.accounts.extend(new_accounts)
                batch, new_accounts = [], []
        return created

    def sync_account_seqs(self) -> None:
        """After a close, learn created accounts' live seqnums."""
        for acct in self.accounts:
            if acct.seq == 0:
                acct.seq = self._live_seq(acct.key)

    def generate_payments(self, n: int, amount: int = 10000) -> int:
        """PAY mode: random-ish payments among generated accounts —
        source order follows the node-seeded permutation, so every node
        of a multi-node scenario drives a different (but reproducible)
        traffic shape."""
        assert len(self.accounts) >= 2, "run generate_accounts first"
        order = self._account_order()
        ok = 0
        for i in range(n):
            src = self.accounts[order[i % len(order)]]
            dst = self.accounts[order[(i + 1) % len(order)]]
            if self._sign_and_submit(src, [self._payment_op(dst, amount)]) \
                    == AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok

    def generate_payments_zipf(self, n: int, amount: int = 10000,
                               exponent: float = 1.0) -> int:
        """PAY mode with Zipfian hot accounts: source and destination
        are drawn rank-weighted (rank r gets weight 1/r^exponent) over
        the node-seeded permutation, so a handful of accounts carry
        most of the traffic — the adversarial cell for conflict-staged
        apply, where clustering must degrade gracefully toward
        sequential. Draws come from the same seeded RNG as every other
        mode (config.jitter_seed() discipline): reproducible per node,
        decorrelated across nodes."""
        import bisect
        assert len(self.accounts) >= 2, "run generate_accounts first"
        order = self._account_order()
        cum: List[float] = []
        tot = 0.0
        for r in range(1, len(order) + 1):
            tot += 1.0 / (r ** exponent)
            cum.append(tot)
        ok = 0
        for _ in range(n):
            si = bisect.bisect_left(cum, self._rng.random() * tot)
            di = si
            while di == si:
                di = bisect.bisect_left(cum, self._rng.random() * tot)
            src = self.accounts[order[min(si, len(order) - 1)]]
            dst = self.accounts[order[min(di, len(order) - 1)]]
            if self._sign_and_submit(src, [self._payment_op(dst, amount)]) \
                    == AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok

    def _payment_op(self, dst: GeneratedAccount, amount: int) -> Operation:
        return Operation(
            sourceAccount=None,
            body=_OperationBody(
                OperationType.PAYMENT,
                PaymentOp(destination=dst.muxed,
                          asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                          amount=amount)))

    def generate_pretend(self, n: int, ops_per_tx: int = 3) -> int:
        """PRETEND mode: transactions that carry realistic weight but leave
        balances alone — SetOptions home-domain + ManageData padding ops
        (reference: LoadGenerator::pretendTransaction)."""
        from ..xdr.transaction import (ManageDataOp, SetOptionsOp,
                                       _OperationBody as OB)
        assert self.accounts, "run generate_accounts first"
        ok = 0
        for i in range(n):
            src = self.accounts[i % len(self.accounts)]
            ops: List[Operation] = []
            for j in range(max(1, ops_per_tx)):
                if j % 2 == 0:
                    body = OB(OperationType.SET_OPTIONS, SetOptionsOp(
                        inflationDest=None, clearFlags=None, setFlags=None,
                        masterWeight=None, lowThreshold=None,
                        medThreshold=None, highThreshold=None,
                        homeDomain=b"pretend-%02d.example.com" % (j % 100),
                        signer=None))
                else:
                    pad = sha256(b"pretend-%d-%d" % (i, j))
                    body = OB(OperationType.MANAGE_DATA, ManageDataOp(
                        dataName=b"load%02d" % j, dataValue=pad))
                ops.append(Operation(sourceAccount=None, body=body))
            if self._sign_and_submit(src, ops) == \
                    AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok

    # ------------------------------------------------------------- mixed --
    LOAD_ASSET_CODE = b"LOAD"

    def setup_dex(self) -> int:
        """Create the trustlines MIXED mode's offers trade against (each
        generated account trusts LOAD issued by the root)."""
        from ..xdr.transaction import ChangeTrustAsset, ChangeTrustOp
        from ..xdr.ledger_entries import AlphaNum4
        ok = 0
        line = ChangeTrustAsset(
            AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
            AlphaNum4(assetCode=self.LOAD_ASSET_CODE,
                      issuer=self.root.account_id))
        for acct in self.accounts:
            op = Operation(sourceAccount=None, body=_OperationBody(
                OperationType.CHANGE_TRUST,
                ChangeTrustOp(line=line, limit=2**62)))
            if self._sign_and_submit(acct, [op]) == \
                    AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok

    def generate_mixed(self, n: int, dex_percent: int = 50,
                       amount: int = 10000) -> int:
        """MIXED_CLASSIC mode: a blend of payments and DEX manage-offer
        transactions (reference: GENERATE_LOAD_MIXED_CLASSIC with
        DEX_TX_PERCENT). Offers all sell native for LOAD on the same book
        side, so they rest without crossing."""
        from ..xdr.transaction import ManageSellOfferOp
        from ..xdr.ledger_entries import Price
        assert len(self.accounts) >= 2, "run generate_accounts first"
        order = self._account_order()
        ok = 0
        buying = Asset.credit(self.LOAD_ASSET_CODE, self.root.account_id)
        for i in range(n):
            src = self.accounts[order[i % len(order)]]
            # Bresenham-style interleave so any n gets the requested blend
            if (i * dex_percent) % 100 < dex_percent:
                op = Operation(sourceAccount=None, body=_OperationBody(
                    OperationType.MANAGE_SELL_OFFER,
                    ManageSellOfferOp(
                        selling=Asset(AssetType.ASSET_TYPE_NATIVE),
                        buying=buying, amount=amount,
                        price=Price(n=100 + (i % 32), d=100),
                        offerID=0)))
            else:
                dst = self.accounts[order[(i + 1) % len(order)]]
                op = self._payment_op(dst, amount)
            if self._sign_and_submit(src, [op]) == \
                    AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok

    # ----------------------------------------------------------- soroban --
    def _soroban_ext(self, ro, rw, instructions=4_000_000,
                     read=50_000, write=50_000,
                     resource_fee=10_000_000):
        from ..xdr import contract as cx
        return _TxExt(1, cx.SorobanTransactionData(
            resources=cx.SorobanResources(
                footprint=cx.LedgerFootprint(readOnly=list(ro),
                                             readWrite=list(rw)),
                instructions=instructions, readBytes=read,
                writeBytes=write),
            resourceFee=resource_fee))

    def setup_sac(self) -> bytes:
        """Deploy the native-asset Stellar Asset Contract; returns its
        contract id (reference: the SOROBAN loadgen family invokes real
        host functions, LoadGenerator.cpp:469-494)."""
        from ..xdr import contract as cx
        from ..soroban.host import contract_id_from_preimage, instance_key
        preimage = cx.ContractIDPreimage(
            cx.ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET,
            Asset(AssetType.ASSET_TYPE_NATIVE))
        cid = contract_id_from_preimage(self.network_id, preimage)
        addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                            cid)
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            if ltx.load_without_record(instance_key(addr)) is not None:
                return cid          # already deployed
        body = _OperationBody(
            OperationType.INVOKE_HOST_FUNCTION,
            cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
                cx.HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT,
                cx.CreateContractArgs(
                    contractIDPreimage=preimage,
                    executable=cx.ContractExecutable(
                        cx.ContractExecutableType
                        .CONTRACT_EXECUTABLE_STELLAR_ASSET))), auth=[]))
        self._sign_and_submit(
            self.root, [Operation(sourceAccount=None, body=body)],
            fee=100 + 10_000_000,
            ext=self._soroban_ext([], [instance_key(addr)]))
        return cid

    def generate_sac_transfers(self, cid: bytes, n: int,
                               amount: int = 1000) -> int:
        """n native-SAC `transfer` invocations between generated
        accounts — the wasm-VM/SAC analogue of PAY mode."""
        from ..soroban import sac as sac_mod
        from ..soroban.host import instance_key
        from ..xdr import contract as cx
        assert self.accounts, "run generate_accounts first"
        addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                            cid)
        ok = 0
        for i in range(n):
            src = self.accounts[(self.submitted + i) % len(self.accounts)]
            dst = self.accounts[(self.submitted + i + 1)
                                % len(self.accounts)]
            src_addr = cx.SCAddress(
                cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT, src.account_id)
            dst_addr = cx.SCAddress(
                cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT, dst.account_id)
            args = [sac_mod._addr_scval(src_addr),
                    sac_mod._addr_scval(dst_addr),
                    sac_mod.sc_i128(amount)]
            invoke = cx.InvokeContractArgs(
                contractAddress=addr, functionName=b"transfer",
                args=list(args))
            auth = cx.SorobanAuthorizationEntry(
                credentials=cx.SorobanCredentials(
                    cx.SorobanCredentialsType
                    .SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
                rootInvocation=cx.SorobanAuthorizedInvocation(
                    function=cx.SorobanAuthorizedFunction(
                        cx.SorobanAuthorizedFunctionType
                        .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                        invoke),
                    subInvocations=[]))
            body = _OperationBody(
                OperationType.INVOKE_HOST_FUNCTION,
                cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
                    cx.HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT,
                    invoke), auth=[auth]))
            ro = [instance_key(addr)]
            rw = [LedgerKey.account(src.account_id),
                  LedgerKey.account(dst.account_id)]
            if self._sign_and_submit(
                    src, [Operation(sourceAccount=None, body=body)],
                    fee=100 + 10_000_000,
                    ext=self._soroban_ext(ro, rw)) == \
                    AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok

    def setup_counter_contract(self) -> bytes:
        """Upload + create the in-repo counter contract (wasm build);
        returns the contract id for generate_counter_invokes."""
        from ..soroban import scvm
        from ..soroban.scvm_wasm import make_wasm_code
        from ..soroban.host import contract_id_from_preimage, instance_key
        from ..xdr import contract as cx

        functions = {"increment": scvm.op(
            scvm.sym("put"), scvm.op(scvm.sym("lit"), scvm.sym("count")),
            scvm.op(scvm.sym("add"),
                    scvm.op(scvm.sym("if"),
                            scvm.op(scvm.sym("eq"),
                                    scvm.op(scvm.sym("get"),
                                            scvm.op(scvm.sym("lit"),
                                                    scvm.sym("count"))),
                                    cx.SCVal(cx.SCValType.SCV_VOID)),
                            scvm.u64(0),
                            scvm.op(scvm.sym("get"),
                                    scvm.op(scvm.sym("lit"),
                                            scvm.sym("count")))),
                    scvm.u64(1)))}
        code = make_wasm_code(functions)
        code_hash = sha256(code)
        code_key = LedgerKey.contract_code(code_hash)
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            have_code = ltx.load_without_record(code_key) is not None
        if not have_code:
            self._sign_and_submit(
                self.root, [Operation(sourceAccount=None,
                                      body=_OperationBody(
                    OperationType.INVOKE_HOST_FUNCTION,
                    cx.InvokeHostFunctionOp(
                        hostFunction=cx.HostFunction(
                            cx.HostFunctionType
                            .HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM,
                            code), auth=[])))],
                fee=100 + 10_000_000,
                ext=self._soroban_ext([], [code_key], write=100_000))
        preimage = cx.ContractIDPreimage(
            cx.ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ADDRESS,
            cx._ContractIDPreimageFromAddress(
                address=cx.SCAddress(
                    cx.SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                    self.root.account_id),
                salt=sha256(b"loadgen-counter")))
        cid = contract_id_from_preimage(self.network_id, preimage)
        addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                            cid)
        create_args = cx.CreateContractArgs(
            contractIDPreimage=preimage,
            executable=cx.ContractExecutable(
                cx.ContractExecutableType.CONTRACT_EXECUTABLE_WASM,
                code_hash))
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            have_inst = ltx.load_without_record(
                instance_key(addr)) is not None
        if not have_inst:
            self._sign_and_submit(
                self.root, [Operation(sourceAccount=None,
                                      body=_OperationBody(
                    OperationType.INVOKE_HOST_FUNCTION,
                    cx.InvokeHostFunctionOp(
                        hostFunction=cx.HostFunction(
                            cx.HostFunctionType
                            .HOST_FUNCTION_TYPE_CREATE_CONTRACT,
                            create_args),
                        auth=[cx.SorobanAuthorizationEntry(
                            credentials=cx.SorobanCredentials(
                                cx.SorobanCredentialsType
                                .SOROBAN_CREDENTIALS_SOURCE_ACCOUNT),
                            rootInvocation=cx.SorobanAuthorizedInvocation(
                                function=cx.SorobanAuthorizedFunction(
                                    cx.SorobanAuthorizedFunctionType
                                    .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN,
                                    create_args),
                                subInvocations=[]))])))],
                fee=100 + 10_000_000,
                ext=self._soroban_ext([code_key], [instance_key(addr)]))
        self._counter_code_key = code_key
        return cid

    def generate_counter_invokes(self, cid: bytes, n: int) -> int:
        """n `increment` invocations through the wasm VM — the
        InvokeHostFunction analogue of a contract-call workload."""
        from ..soroban.host import instance_key
        from ..xdr import contract as cx
        assert self.accounts, "run generate_accounts first"
        addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                            cid)
        ckey = LedgerKey.contract_data(
            addr, cx.SCVal(cx.SCValType.SCV_SYMBOL, b"count"),
            cx.ContractDataDurability.PERSISTENT)
        ro = [self._counter_code_key, instance_key(addr)]
        ok = 0
        for i in range(n):
            src = self.accounts[(self.submitted + i) % len(self.accounts)]
            body = _OperationBody(
                OperationType.INVOKE_HOST_FUNCTION,
                cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
                    cx.HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT,
                    cx.InvokeContractArgs(contractAddress=addr,
                                          functionName=b"increment",
                                          args=[])), auth=[]))
            if self._sign_and_submit(
                    src, [Operation(sourceAccount=None, body=body)],
                    fee=100 + 10_000_000,
                    ext=self._soroban_ext(ro, [ckey])) == \
                    AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok

    def generate_soroban_uploads(self, n: int,
                                 resource_fee: int = 10_000_000) -> int:
        """SOROBAN mode: random upload-wasm transactions sized against the
        live SorobanNetworkConfig limits (reference:
        LoadGenerator::createUploadWasmTransaction,
        LoadGenerator.cpp:469-494)."""
        from ..soroban.network_config import SorobanNetworkConfig
        from ..xdr import contract as cx
        assert self.accounts, "run generate_accounts first"
        with LedgerTxn(self.app.ledger_manager.root) as ltx:
            ncfg = SorobanNetworkConfig(ltx)
            max_code = min(ncfg.max_contract_size,
                           ncfg.ledger_cost.txMaxWriteBytes // 2)
        ok = 0
        for i in range(n):
            src = self.accounts[i % len(self.accounts)]
            # unique random-ish body per tx, sized within the live limits
            size = max(64, (max_code // 8) + (i % 7) * 16)
            seed = sha256(b"loadgen-wasm-%d-%d" % (i, self.submitted))
            code = (seed * (size // 32 + 1))[:size]
            code_hash = sha256(code)
            op_body = _OperationBody(
                OperationType.INVOKE_HOST_FUNCTION,
                cx.InvokeHostFunctionOp(hostFunction=cx.HostFunction(
                    cx.HostFunctionType.HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM,
                    code), auth=[]))
            from ..xdr.ledger_entries import LedgerKey
            sd = cx.SorobanTransactionData(
                resources=cx.SorobanResources(
                    footprint=cx.LedgerFootprint(
                        readOnly=[],
                        readWrite=[LedgerKey.contract_code(code_hash)]),
                    instructions=4_000_000,
                    readBytes=0, writeBytes=size + 1024),
                resourceFee=resource_fee)
            op = Operation(sourceAccount=None, body=op_body)
            if self._sign_and_submit(src, [op], fee=100 + resource_fee,
                                     ext=_TxExt(1, sd)) == \
                    AddResult.ADD_STATUS_PENDING:
                ok += 1
        return ok


# ------------------------------------------------------- bulk state seeding --
# Million-account ledgers for the read-serving and big-state benches
# (ISSUE 17): materialize accounts DIRECTLY into deep bucket-list levels
# as pre-built buckets — no per-tx close loop, no ed25519 keygen (the
# synthetic account ids are sha256 digests used as raw key bytes; these
# accounts only ever get READ, never signed for).
#
# Placement is the load-bearing subtlety: a level's `snap` slot is
# REPLACED by snap_curr() when the level below spills, so seeded data in
# a snap slot would silently vanish. Deep-level `curr` slots are always
# a merge INPUT (level i's curr merges with the spilled snap from i-1)
# and are never dropped, so seeding only ever installs into curr of
# levels deep enough not to spill during a bench window.

BIGSTATE_LEVELS = (7, 8, 9, 10)


def bulk_account_id(i: int, tag: bytes = b"bigstate") -> bytes:
    """Deterministic raw 32-byte account id of seeded account #i —
    benches re-derive read targets from the same function."""
    return sha256(b"%s-%d" % (tag, i))


def build_bigstate_buckets(n: int, protocol: int, ledger_seq: int,
                           tag: bytes = b"bigstate",
                           balance: int = 1_000_0000000):
    """Build the seed buckets for `n` synthetic accounts, split across
    the deep seeding levels. Returns [(level, Bucket), ...]. Building
    once and installing into EVERY node of a simulation keeps the
    immutable Bucket objects (and their lazy indexes) shared — a
    million-account topology pays the entry memory once, and identical
    buckets on every node keep the consensus bucketListHash agreeing."""
    from ..bucket.bucket import Bucket
    from ..tx.tx_utils import make_account_ledger_entry
    levels = list(BIGSTATE_LEVELS)
    per = (n + len(levels) - 1) // len(levels)
    out = []
    start = 0
    seq = starting_sequence_number(max(1, ledger_seq))
    for lvl in levels:
        stop = min(n, start + per)
        if stop <= start:
            break
        entries = []
        for i in range(start, stop):
            le = make_account_ledger_entry(
                PublicKey.ed25519(bulk_account_id(i, tag)),
                balance, seq)
            le.lastModifiedLedgerSeq = ledger_seq
            entries.append(le)
        out.append((lvl, Bucket.fresh(protocol, entries, [], [])))
        start = stop
    return out


def install_bigstate_buckets(app, buckets) -> None:
    """Install pre-built seed buckets into one app's bucket list (deep-
    level curr slots, which must be empty — seeding composes with a
    freshly-booted ledger, not an aged one). The next close recomputes
    bucketListHash over the seeded levels, so every node of a consensus
    group must install the SAME buckets before its next close."""
    bl = app.bucket_manager.bucket_list
    for lvl_idx, bucket in buckets:
        lvl = bl.levels[lvl_idx]
        lvl.commit()
        releaseAssert(lvl.curr.is_empty(),
                      f"bigstate seeding needs empty level {lvl_idx}")
        lvl.curr = bucket
    # the boot snapshot predates the seeded state; recapture so reads
    # see the seeded accounts before the first post-seed close lands
    snaps = getattr(app, "snapshots", None)
    if snaps is not None:
        snaps.on_ledger_closed(
            app.ledger_manager.get_last_closed_ledger_header(),
            app.ledger_manager.get_last_closed_ledger_hash())


def seed_accounts_bulk(app, n: int, tag: bytes = b"bigstate",
                       balance: int = 1_000_0000000) -> int:
    """Convenience one-app path: build + install `n` synthetic accounts
    into this app's bucket list. Returns n."""
    lcl = app.ledger_manager.get_last_closed_ledger_num()
    protocol = app.ledger_manager.get_last_closed_ledger_header().ledgerVersion
    install_bigstate_buckets(
        app, build_bigstate_buckets(n, protocol, lcl, tag=tag,
                                    balance=balance))
    return n
