"""In-process multi-node simulation (reference: src/simulation)."""

from .load_generator import LoadGenerator
from .simulation import Simulation
from . import topologies

__all__ = ["Simulation", "LoadGenerator", "topologies"]
