"""Multi-process cluster harness: real node processes, real TCP.

ROADMAP item 4's designated gap: every scale/chaos scenario before this
ran nodes in-process, where one GIL and the shared ``_verify_cache``
distort wall-clock numbers (both already bit PR 4). This harness gives
each node what production gives it — its own process, its own sqlite
file + bucket ``data_dir``, its own ports — and drives everything
through the admin HTTP API a real operator would use:

- **config rendering** — one TOML file per node (unique overlay/HTTP
  ports, quorum sets from ``simulation/topologies.tiered_qset``,
  ``ALLOW_CHAOS_INJECTION`` only here, never in production configs),
  then ``new-db`` and a real ``python -m stellar_core_tpu run``
  subprocess per node with ``HTTP_PORT=0`` + ``--port-file`` so
  parallel clusters never collide on ports;
- **mesh wiring** — the same tiered link list the in-process builder
  uses (``topologies.tiered_links``), carried by ``KNOWN_PEERS`` dial
  retry plus harness-driven ``connect`` nudges over the admin API;
- **load** — ``generateload`` create/pay rounds against one node, the
  flood crossing real authenticated TCP sockets;
- **chaos** — seeded per-process fault schedules installed over the
  ``chaos`` route; **churn is a real ``kill -9``** (SIGKILL, not a
  simulated crash), restart from the persisted ``data_dir``, catchup
  over the wire (peers answer GET_SCP_STATE within
  MAX_SLOTS_TO_REMEMBER);
- **verdicts** — collected from ``clusterstatus``/``peers``/``metrics``
  with deadline-bounded polls and per-node seeded, decorrelated retry
  jitter (Dean & Barroso, *Tail at Scale*, CACM 2013: never a blocking
  wait on one slow node; the ``config.jitter_seed()`` derivation keeps
  N freshly spawned pollers from hammering a still-booting peer in
  lockstep). Safety is ``simulation/byzantine.header_chains_agree`` —
  byte-identical honest-survivor header chains — over
  ``clusterstatus?headers=A-B`` exports;
- **tracing** — per-node ``starttrace``/``dumptrace`` exports stitched
  into ONE cluster-wide Chrome trace by
  ``util/tracemerge.merge_trace_docs`` (wall-clock-anchored lanes).

Consumers: ``bench.py --tps-cluster`` (the CLUSTER artifact: the first
wall-clock-faithful multinode numbers beside the in-process TPSM/TPSMT
ones), ``tests/test_cluster_harness.py`` (tier-1 3-process smoke, slow
9-node chaos leg).
"""

from __future__ import annotations

import json
import os
import random
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional

from ..crypto.strkey import StrKey
from ..util.logging import get_logger
from . import topologies
from .byzantine import header_chains_agree

log = get_logger("Chaos")

# one HTTP request never waits longer than this; slow nodes are retried
# (with per-node jitter) until the caller's DEADLINE, not blocked on
REQUEST_TIMEOUT_S = 3.0
POLL_BASE_S = 0.1
# retry jitter fraction: sleep = base * (1 + U[0, JITTER_FRAC)) drawn
# from the node's own seeded RNG — decorrelated across nodes, stable
# per node (the PR 5 config.jitter_seed() pattern)
JITTER_FRAC = 1.0

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class ClusterError(RuntimeError):
    pass


# ------------------------------------------------------------ rendering --
def _toml_value(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        return json.dumps(v)          # TOML basic string, ASCII-safe
    if isinstance(v, (list, tuple)):
        return "[" + ", ".join(_toml_value(x) for x in v) + "]"
    raise TypeError(f"unrenderable TOML value: {v!r}")


def _render_quorum_set(qset, path: str = "QUORUM_SET",
                       _as_array: bool = False) -> List[str]:
    """TOML table (+ nested array-of-tables) in exactly the shape
    Config._parse_quorum_set reads back."""
    lines = [("[[%s]]" if _as_array else "[%s]") % path,
             f"THRESHOLD = {qset.threshold}",
             "VALIDATORS = [" + ", ".join(
                 json.dumps(StrKey.encode_ed25519_public(v))
                 for v in qset.validators) + "]"]
    for inner in qset.inner_sets:
        lines.append("")
        lines.extend(_render_quorum_set(inner, path + ".INNER_SETS",
                                        _as_array=True))
    return lines


# ----------------------------------------------------------------- nodes --
class ClusterNode:
    """One spawned node: rendered config, subprocess handle, admin-API
    client with deadline-bounded, jitter-decorrelated polling."""

    def __init__(self, name: str, seed, peer_port: int, data_dir: str):
        self.name = name
        self.seed = seed
        self.node_id: bytes = seed.public_key().raw
        self.peer_port = peer_port
        self.data_dir = data_dir
        self.cfg_path = os.path.join(data_dir, "node.cfg")
        self.port_file = os.path.join(data_dir, "http.port")
        self.log_path = os.path.join(data_dir, "node.log")
        self.proc: Optional[subprocess.Popen] = None
        self._log_file = None
        self.http_port: Optional[int] = None
        self.known_peers: List[str] = []
        self.neighbors: List["ClusterNode"] = []
        self.is_validator = True
        # incremental telemetry scrape state (the `timeseries` route's
        # since=<cursor> contract): the last cursor token this harness
        # saw, and every sample collected so far. A restart rotates
        # the node's epoch, so the next scrape self-heals with
        # reset=true — no harness-side restart bookkeeping needed.
        self.ts_token: Optional[str] = None
        self.ts_samples: List[dict] = []
        self.ts_resets = 0
        # the config.jitter_seed() derivation, computed harness-side:
        # stable for this node, decorrelated from every other node's
        # poller — N spawned processes never retry in lockstep
        self._rng = random.Random(
            int.from_bytes(self.node_id[:8], "little"))

    # ------------------------------------------------------------- state --
    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def jittered_sleep(self, base: float = POLL_BASE_S) -> None:
        time.sleep(base * (1.0 + self._rng.random() * JITTER_FRAC))

    # -------------------------------------------------------------- http --
    def get(self, command: str, params: Optional[dict] = None,
            timeout: float = REQUEST_TIMEOUT_S) -> dict:
        """One admin-API request. Raises OSError/ValueError on
        transport/parse failure, ClusterError on an app-level
        ``{"exception": ...}`` reply."""
        if self.http_port is None:
            raise ClusterError(f"{self.name}: no HTTP port yet")
        url = f"http://127.0.0.1:{self.http_port}/{command}"
        if params:
            url += "?" + urllib.parse.urlencode(params)
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            doc = json.loads(resp.read().decode())
        if isinstance(doc, dict) and "exception" in doc:
            raise ClusterError(f"{self.name}: {command}: "
                               f"{doc['exception']}")
        return doc

    def poll(self, command: str, params: Optional[dict] = None,
             deadline: float = 0.0,
             ok: Optional[Callable[[dict], bool]] = None
             ) -> Optional[dict]:
        """Deadline-bounded poll: retry (jittered) until `ok(doc)` or
        the monotonic `deadline`; returns None on expiry — the caller
        decides whether a slow node fails a verdict, the poll itself
        never blocks past the deadline."""
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                doc = self.get(command, params,
                               timeout=min(REQUEST_TIMEOUT_S,
                                           max(0.1, remaining)))
                if ok is None or ok(doc):
                    return doc
            except (OSError, ValueError, ClusterError):
                pass
            self.jittered_sleep()


# --------------------------------------------------------------- cluster --
class Cluster:
    """A tiered quorum of real node processes on localhost TCP.

    ``Cluster(3, 3, root_dir)`` renders nine configs, initializes nine
    databases, spawns nine ``run`` subprocesses on ephemeral admin
    ports, and wires the tiered mesh. Lifecycle: ``start_all`` →
    (drive) → ``stop_all(graceful=True)`` / ``close()``.
    """

    def __init__(self, n_orgs: int, validators_per_org: int,
                 root_dir: str, passphrase: str = "cluster harness net",
                 close_time: float = 0.5, max_tx_set_size: int = 2000,
                 bad_sig_threshold: int = 16,
                 max_slots_to_remember: int = 64,
                 log_level: str = "warning",
                 extra_config: Optional[dict] = None):
        self.root_dir = root_dir
        self.passphrase = passphrase
        self.close_time = close_time
        self.max_tx_set_size = max_tx_set_size
        self.bad_sig_threshold = bad_sig_threshold
        self.max_slots_to_remember = max_slots_to_remember
        self.log_level = log_level
        self.extra_config = dict(extra_config or {})

        org_seeds = topologies.tiered_org_seeds(n_orgs,
                                                validators_per_org)
        org_ids = [[s.public_key().raw for s in org]
                   for org in org_seeds]
        self.qset = topologies.tiered_qset(org_ids)
        flat_seeds = [s for org in org_seeds for s in org]
        ports = _free_ports(len(flat_seeds))
        self.nodes: List[ClusterNode] = []
        for i, s in enumerate(flat_seeds):
            name = "node%02d" % i
            data_dir = os.path.join(root_dir, name)
            os.makedirs(data_dir, exist_ok=True)
            self.nodes.append(ClusterNode(name, s, ports[i], data_dir))
        self._by_id: Dict[bytes, ClusterNode] = {
            n.node_id: n for n in self.nodes}
        self.links = topologies.tiered_links(org_ids)
        index = {n.node_id: i for i, n in enumerate(self.nodes)}
        for a, b, _kind in self.links:
            na, nb = self._by_id[a], self._by_id[b]
            na.neighbors.append(nb)
            nb.neighbors.append(na)
            # the later node dials the earlier (the TCP-bench pattern);
            # the harness's connect nudges cover any link that fails to
            # come up from dial retry alone
            dialer, listener = (na, nb) if index[a] > index[b] \
                else (nb, na)
            dialer.known_peers.append(
                f"127.0.0.1:{listener.peer_port}")
        for node in self.nodes:
            self._render_config(node)

    # --------------------------------------------------------- rendering --
    def _render_config(self, node: ClusterNode) -> None:
        doc = {
            "NETWORK_PASSPHRASE": self.passphrase,
            "NODE_SEED": StrKey.encode_ed25519_seed(node.seed.seed)
            + " self",
            "NODE_IS_VALIDATOR": node.is_validator,
            "FORCE_SCP": True,
            "RUN_STANDALONE": False,
            "MANUAL_CLOSE": False,
            "EXPECTED_LEDGER_CLOSE_TIME": float(self.close_time),
            # ephemeral admin port (satellite: parallel harness nodes
            # never collide); the run command reports the bound port
            # via --port-file
            "HTTP_PORT": 0,
            "PEER_PORT": node.peer_port,
            "KNOWN_PEERS": list(node.known_peers),
            "DATABASE": "sqlite3://" + os.path.join(node.data_dir,
                                                    "node.db"),
            "BUCKET_DIR_PATH": os.path.join(node.data_dir, "buckets"),
            "ALLOW_LOCALHOST_FOR_TESTING": True,
            # ONLY in rendered harness configs — the chaos route's
            # install/clear modes stay refused on production nodes
            "ALLOW_CHAOS_INJECTION": True,
            # input recording armed on every harness node (ISSUE 20
            # satellite): a failed matrix cell ships each node's
            # per-process replay log alongside its data_dir
            "ALLOW_INPUT_RECORDING": True,
            "MAX_TX_SET_SIZE": self.max_tx_set_size,
            "TESTING_UPGRADE_MAX_TX_SET_SIZE": self.max_tx_set_size,
            # generous overlay catchup window: a kill -9'd node must be
            # able to rejoin over GET_SCP_STATE even when its restart
            # (a full process boot) costs several slots
            "MAX_SLOTS_TO_REMEMBER": self.max_slots_to_remember,
            "PEER_BAD_SIG_DROP_THRESHOLD": self.bad_sig_threshold,
            # hourly timers have no place in a minutes-long scenario
            "AUTOMATIC_MAINTENANCE_PERIOD": 0.0,
        }
        doc.update(self.extra_config)
        lines = [f"{k} = {_toml_value(v)}" for k, v in doc.items()]
        lines.append("")
        lines.extend(_render_quorum_set(self.qset))
        lines.append("")
        with open(node.cfg_path, "w") as f:
            f.write("\n".join(lines))

    # --------------------------------------------------------- lifecycle --
    def _cli(self, node: ClusterNode, *args: str) -> List[str]:
        return [sys.executable, "-m", "stellar_core_tpu",
                "--conf", node.cfg_path, "--ll", self.log_level,
                *args]

    def new_db(self, node: ClusterNode) -> None:
        res = subprocess.run(self._cli(node, "new-db"),
                             cwd=_REPO_ROOT, capture_output=True,
                             text=True, timeout=120)
        if res.returncode != 0:
            raise ClusterError(f"{node.name}: new-db failed: "
                               f"{res.stderr[-500:]}")

    def spawn(self, node: ClusterNode) -> None:
        """Start (or restart) the node's ``run`` subprocess. The stale
        port file is removed first: an ephemeral port changes across
        restarts, and reading last boot's port would poll a ghost."""
        if node.alive:
            raise ClusterError(f"{node.name} is already running")
        if os.path.exists(node.port_file):
            os.unlink(node.port_file)
        node.http_port = None
        if node._log_file is not None:
            # kill -9 leaves the previous handle open; a churn loop
            # must not leak one fd per restart cycle
            node._log_file.close()
        node._log_file = open(node.log_path, "ab")
        node.proc = subprocess.Popen(
            self._cli(node, "run", "--port-file", node.port_file),
            cwd=_REPO_ROOT, stdout=node._log_file,
            stderr=subprocess.STDOUT,
            start_new_session=True)
        log.info("%s: spawned pid %d (peer port %d)", node.name,
                 node.proc.pid, node.peer_port)

    def start_all(self, deadline_s: float = 120.0) -> None:
        """new-db + spawn every node, then wait (deadline-bounded) for
        every admin API to come up."""
        for node in self.nodes:
            self.new_db(node)
        for node in self.nodes:
            self.spawn(node)
        self.wait_ready(deadline_s)

    def _await_all(self, nodes: List[ClusterNode], deadline_s: float,
                   step: Callable[[ClusterNode], bool],
                   sleep_base: float = POLL_BASE_S
                   ) -> List[ClusterNode]:
        """THE shared waiter discipline (Tail at Scale): each pass
        gives every pending node one short `step`; a node leaves the
        pending set when its step returns True. A wedged node can only
        burn its own verdict — never the budget of nodes stepped after
        it. Returns the stragglers still pending at the deadline
        (empty = success)."""
        deadline = time.monotonic() + deadline_s
        pending = list(nodes)
        while pending and time.monotonic() < deadline:
            pending = [n for n in pending if not step(n)]
            if pending:
                pending[0].jittered_sleep(sleep_base)
        return pending

    def wait_ready(self, deadline_s: float,
                   nodes: Optional[List[ClusterNode]] = None) -> None:
        """Wait until each booting node has written its port file and
        answers ``info``; a node process dying during boot fails fast
        with its log path."""
        def step(node: ClusterNode) -> bool:
            if not node.alive:
                raise ClusterError(
                    f"{node.name} died during boot "
                    f"(rc={node.proc.returncode}); see {node.log_path}")
            if node.http_port is None:
                if not os.path.exists(node.port_file):
                    return False
                with open(node.port_file) as f:
                    node.http_port = int(f.read().strip())
            try:
                doc = node.get("info", timeout=1.0)
                return doc.get("info", {}).get("ledger", {}) \
                    .get("num", 0) >= 1
            except (OSError, ValueError, ClusterError):
                return False

        stragglers = self._await_all(
            list(nodes if nodes is not None else self.nodes),
            deadline_s, step)
        if stragglers:
            raise ClusterError(
                "nodes never became ready: "
                + ", ".join(n.name for n in stragglers))

    def stop_all(self, graceful: bool = True,
                 timeout_s: float = 30.0) -> Dict[str, Optional[int]]:
        """SIGTERM every live node (the graceful-drain satellite) and
        wait; stragglers past the timeout get SIGKILL. Returns each
        node's exit code (None = had to be killed / never ran)."""
        rcs: Dict[str, Optional[int]] = {}
        live = [n for n in self.nodes if n.alive]
        for node in live:
            node.proc.send_signal(
                signal.SIGTERM if graceful else signal.SIGKILL)
        deadline = time.monotonic() + timeout_s
        for node in live:
            try:
                node.proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                node.proc.kill()
                node.proc.wait(10)
        for node in self.nodes:
            rcs[node.name] = node.proc.returncode \
                if node.proc is not None else None
            if node._log_file is not None:
                node._log_file.close()
                node._log_file = None
        return rcs

    def close(self) -> None:
        if any(n.alive for n in self.nodes):
            self.stop_all(graceful=False, timeout_s=10.0)
        for node in self.nodes:
            if node._log_file is not None:
                node._log_file.close()
                node._log_file = None

    def __enter__(self) -> "Cluster":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------------- churn --
    def kill_node(self, node: ClusterNode) -> None:
        """A REAL kill -9: no drain, no goodbye — everything past the
        last durable commit is lost, exactly what the recovery-marker
        machinery must absorb on restart."""
        if not node.alive:
            raise ClusterError(f"{node.name} is not running")
        log.info("%s: kill -9 pid %d", node.name, node.proc.pid)
        node.proc.kill()
        node.proc.wait(30)

    def restart_node(self, node: ClusterNode,
                     deadline_s: float = 60.0) -> None:
        """Respawn from the persisted data_dir (``run`` without
        --new-db restores LCL + buckets), wait for the admin API, and
        nudge the node's topology links back up via ``connect`` — its
        own dials plus every neighbor's KNOWN_PEERS retry re-knit the
        mesh."""
        self.spawn(node)
        self.wait_ready(deadline_s, nodes=[node])
        for peer in node.neighbors:
            try:
                node.get("connect", {"peer": "127.0.0.1",
                                     "port": str(peer.peer_port)})
            except (OSError, ValueError, ClusterError):
                pass                     # dial retry keeps trying

    # --------------------------------------------------------------- mesh --
    def expected_degree(self, node: ClusterNode) -> int:
        return len(node.neighbors)

    def wait_mesh(self, deadline_s: float = 60.0) -> None:
        """Wait until every node has authenticated its full topology
        degree. KNOWN_PEERS dial retry does most of the work; links
        still missing at each pass get an explicit ``connect`` nudge
        (jitter-decorrelated per node, so a restarted or slow listener
        isn't hammered in lockstep)."""
        def step(node: ClusterNode) -> bool:
            try:
                doc = node.get("clusterstatus", timeout=1.0)
                have = doc["clusterstatus"]["peers"]["authenticated"]
            except (OSError, ValueError, ClusterError, KeyError):
                return False
            if have >= self.expected_degree(node):
                return True
            for peer in node.neighbors:
                try:
                    node.get("connect", {"peer": "127.0.0.1",
                                         "port": str(peer.peer_port)})
                except (OSError, ValueError, ClusterError):
                    pass
            return False

        stragglers = self._await_all(self.nodes, deadline_s, step,
                                     sleep_base=POLL_BASE_S * 2)
        if stragglers:
            raise ClusterError(
                "mesh never fully authenticated: "
                + ", ".join(n.name for n in stragglers))

    # ----------------------------------------------------------- consensus --
    def lcl(self, node: ClusterNode, deadline_s: float = 15.0) -> int:
        """Current LCL, retried (jittered) within a deadline: admin
        requests queue behind the node's crank loop, so a node busy
        applying a big txset can miss one 3s request without meaning
        anything — the same discipline as every other poll here."""
        doc = node.poll("info", deadline=time.monotonic() + deadline_s,
                        ok=lambda d: "info" in d)
        if doc is None:
            raise ClusterError(f"{node.name}: info never answered "
                               f"within {deadline_s}s")
        return int(doc["info"]["ledger"]["num"])

    def min_lcl(self, nodes: Optional[List[ClusterNode]] = None) -> int:
        return min(self.lcl(n)
                   for n in (nodes if nodes is not None else self.nodes))

    def wait_slot(self, target: int, deadline_s: float,
                  nodes: Optional[List[ClusterNode]] = None) -> None:
        """Every given node externalizes ledger >= target — the shared
        round-robin waiter, so a lagging node only burns its own
        budget and the failure names the node that actually stalled."""
        def step(node: ClusterNode) -> bool:
            try:
                return node.get("info", timeout=1.0) \
                    .get("info", {}).get("ledger", {}) \
                    .get("num", 0) >= target
            except (OSError, ValueError, ClusterError):
                return False

        stragglers = self._await_all(
            list(nodes if nodes is not None else self.nodes),
            deadline_s, step)
        if stragglers:
            raise ClusterError(
                "never externalized ledger %d: %s" % (target, ", ".join(
                    f"{n.name} (at {self._lcl_or_unknown(n)})"
                    for n in stragglers)))

    def _lcl_or_unknown(self, node: ClusterNode):
        """Best-effort LCL for error messages: ONE short request — the
        node just proved unresponsive, a retried poll per straggler
        would stack minutes onto an already-failed wait."""
        try:
            return int(node.get("info", timeout=1.0)
                       ["info"]["ledger"]["num"])
        except (OSError, ValueError, ClusterError, KeyError):
            return "unknown"

    # ---------------------------------------------------------------- load --
    def generate_load(self, node: ClusterNode, mode: str,
                      **params) -> dict:
        return node.get("generateload", {"mode": mode, **{
            k: str(v) for k, v in params.items()}},
            timeout=max(REQUEST_TIMEOUT_S, 30.0))

    def submit_tx(self, node: ClusterNode, envelope_b64: str) -> dict:
        """Submit one base64-XDR TransactionEnvelope over the `tx`
        route (the raw-operator path beside generateload; the smoke
        test drives a hand-built envelope through it)."""
        return node.get("tx", {"blob": envelope_b64})

    def drain_pending(self, node: ClusterNode,
                      deadline_s: float = 60.0) -> bool:
        """Poll until the node's pending tx queue is empty (all load
        externalized or expired)."""
        deadline = time.monotonic() + deadline_s
        return node.poll(
            "info", deadline=deadline,
            ok=lambda d: d.get("info", {}).get("num_pending_txs", 1)
            == 0) is not None

    # --------------------------------------------------------------- chaos --
    def install_chaos(self, node: ClusterNode, seed: int,
                      schedule: List[dict]) -> dict:
        """Install a seeded fault schedule on ONE process over the
        `chaos` route (requires the rendered ALLOW_CHAOS_INJECTION)."""
        return node.get("chaos", {
            "mode": "install", "seed": str(seed),
            "schedule": json.dumps(schedule)})

    def clear_chaos(self, node: ClusterNode) -> None:
        node.get("chaos", {"mode": "clear"})

    # ------------------------------------- wide-area faults (ISSUE 20) --
    # Schedule builders return {node_name: [spec, ...]} so callers can
    # merge several fault families before installing: `chaos
    # ?mode=install` REPLACES the node's engine, so every fault a node
    # must carry has to travel in ONE schedule (merge_schedules +
    # install_schedules).
    def cut_edges(self, minority: List[ClusterNode]
                  ) -> List[tuple]:
        """The topology edges crossing minority <-> rest — the link
        set a partition of `minority` must sever."""
        cut_ids = {n.node_id for n in minority}
        edges = []
        for a, b, _kind in self.links:
            if (a in cut_ids) != (b in cut_ids):
                edges.append((self._by_id[a], self._by_id[b]))
        return edges

    @staticmethod
    def _link_fault(kind: str, other: ClusterNode, **extra) -> dict:
        spec = {"point": "overlay.link", "kind": kind,
                "match": {"peer": other.node_id.hex()}}
        spec.update(extra)
        return spec

    def partition_schedules(self, minority: List[ClusterNode],
                            window_s: float
                            ) -> Dict[str, List[dict]]:
        """`overlay.link` partition specs for BOTH endpoints of every
        edge crossing the cut: the next send on a severed link drops
        the connection, and `peer_authenticated` refuses re-dials
        while the window is open. Heals by window elapse (window_s=0:
        only an explicit chaos?mode=clear heals)."""
        per_node: Dict[str, List[dict]] = {}
        for na, nb in self.cut_edges(minority):
            per_node.setdefault(na.name, []).append(
                self._link_fault("partition", nb, window_s=window_s))
            per_node.setdefault(nb.name, []).append(
                self._link_fault("partition", na, window_s=window_s))
        return per_node

    def flap_schedules(self, edges: List[tuple], window_s: float,
                       period_s: float = 3.0, duty: float = 0.4
                       ) -> Dict[str, List[dict]]:
        """`overlay.link` flap specs (periodic down/up inside the
        window) on both endpoints of each given edge."""
        per_node: Dict[str, List[dict]] = {}
        for na, nb in edges:
            for src, dst in ((na, nb), (nb, na)):
                per_node.setdefault(src.name, []).append(
                    self._link_fault("flap", dst, window_s=window_s,
                                     period_s=period_s, duty=duty))
        return per_node

    def shape_schedules(self, latency, window_s: float = 0.0
                        ) -> Dict[str, List[dict]]:
        """`overlay.send` slow_link specs from a
        ``topologies.LinkLatency`` model — the PR 6 per-link
        latency/bandwidth shapes, ported off loopback onto the real
        TCP sockets. Both endpoints shape their outbound side of the
        link, so the WAN delay applies in each direction."""
        per_node: Dict[str, List[dict]] = {}
        for a, b, kind in self.links:
            delay_s, bps = latency.for_link(kind)
            na, nb = self._by_id[a], self._by_id[b]
            for src, dst in ((na, nb), (nb, na)):
                spec = {"point": "overlay.send", "kind": "slow_link",
                        "delay_ms": delay_s * 1000.0,
                        "window_s": window_s,
                        "match": {"peer": dst.node_id.hex()}}
                if bps is not None:
                    # LinkLatency speaks bits/s (the loopback port
                    # divides by 8 too); the chaos Shape wants bytes/s
                    spec["bps"] = float(bps) / 8.0
                per_node.setdefault(src.name, []).append(spec)
        return per_node

    @staticmethod
    def merge_schedules(*per_node_dicts: Dict[str, List[dict]]
                        ) -> Dict[str, List[dict]]:
        merged: Dict[str, List[dict]] = {}
        for d in per_node_dicts:
            for name, specs in d.items():
                merged.setdefault(name, []).extend(specs)
        return merged

    def install_schedules(self, per_node: Dict[str, List[dict]],
                          seed: int) -> int:
        """ONE chaos install per named node (install replaces the
        engine — merged schedules only). Returns specs installed."""
        by_name = {n.name: n for n in self.nodes}
        total = 0
        for name, specs in per_node.items():
            self.install_chaos(by_name[name], seed, specs)
            total += len(specs)
        return total

    def clear_all_chaos(self) -> None:
        for node in self.nodes:
            if node.alive:
                try:
                    self.clear_chaos(node)
                except (OSError, ValueError, ClusterError):
                    pass

    # ---------------------------------------------------------- recording --
    def record_all(self) -> Dict[str, str]:
        """Arm streaming input recording on every live node
        (`recordstart?path=<data_dir>/input.rec`, the ISSUE 18 flight
        recorder): a failed matrix cell keeps each node's replay log
        next to its sqlite/bucket state. Best-effort — a node already
        recording (restart) just keeps its existing log."""
        paths: Dict[str, str] = {}
        for node in self.nodes:
            if not node.alive:
                continue
            path = os.path.join(node.data_dir, "input.rec")
            try:
                node.get("recordstart", {"path": path})
                paths[node.name] = path
            except (OSError, ValueError, ClusterError):
                if os.path.exists(path):
                    paths[node.name] = path   # armed on a prior boot
        return paths

    def recordstop_all(self) -> None:
        """Seal every node's streaming record (writes the END frame so
        replay knows the log is complete, not truncated by a crash)."""
        for node in self.nodes:
            if node.alive:
                try:
                    node.get("recordstop")
                except (OSError, ValueError, ClusterError):
                    pass

    def flow_report(self, deadline_s: float = 15.0) -> dict:
        """Per-link outbound backpressure evidence off the `peers`
        route (ISSUE 20): cluster-wide queue high-water vs the byte
        budget, plus per-class shed totals. The verdicts the
        backpressure cell gates on: a slow peer's queue never exceeds
        its budget, and SCP is never shed while lower classes were
        available to shed (the drop-priority contract — scp drops
        require gossip+tx shed first, so scp_dropped stays 0 in every
        matrix cell)."""
        docs = self._sweep("peers", None, deadline_s,
                           ok=lambda d: "authenticated_peers" in d)
        high = 0
        budget = 0
        drops = {"scp": 0, "tx": 0, "gossip": 0}
        for _name, doc in docs.items():
            if doc is None:
                continue
            peers = doc["authenticated_peers"]
            for row in peers.get("inbound", []) + \
                    peers.get("outbound", []):
                fl = row.get("flow") or {}
                high = max(high, int(fl.get("queue_high_water", 0)))
                budget = int(fl.get("queue_budget", 0)) or budget
                for cls, n in (fl.get("drops") or {}).items():
                    if cls in drops:
                        drops[cls] += int(n)
        return {
            "queue_high_water_max": high,
            "queue_budget": budget,
            "drops": drops,
            "within_budget": budget == 0 or high <= budget,
            "scp_never_shed_first": drops["scp"] == 0
            or (drops["tx"] + drops["gossip"]) > 0,
        }

    # ------------------------------------------------------------ verdicts --
    def _sweep(self, command: str, params: Optional[dict],
               deadline_s: float,
               ok: Callable[[dict], bool]) -> Dict[str, Optional[dict]]:
        """Round-robin collection from every live node against ONE
        shared deadline: each pass gives each pending node one short
        request, so a single wedged node can only lose its own verdict
        — never eat the budget of the nodes polled after it (the
        Tail-at-Scale discipline, applied to collection)."""
        out: Dict[str, Optional[dict]] = {
            n.name: None for n in self.nodes}

        def step(node: ClusterNode) -> bool:
            try:
                doc = node.get(command, params, timeout=1.0)
                if ok(doc):
                    out[node.name] = doc
                    return True
            except (OSError, ValueError, ClusterError):
                pass
            return False

        self._await_all([n for n in self.nodes if n.alive],
                        deadline_s, step)
        return out

    def collect_clusterstatus(self, deadline_s: float = 20.0,
                              headers: Optional[str] = None
                              ) -> Dict[str, Optional[dict]]:
        """One deadline-bounded sweep: every live node's clusterstatus
        document (None for nodes that never answered — the caller's
        verdict decides what a silent node means)."""
        docs = self._sweep("clusterstatus",
                           {"headers": headers} if headers else None,
                           deadline_s,
                           ok=lambda d: "clusterstatus" in d)
        return {name: (doc["clusterstatus"] if doc else None)
                for name, doc in docs.items()}

    def headers_agree(self, upto: int,
                      statuses: Dict[str, Optional[dict]],
                      expected: Optional[int] = None) -> bool:
        """Byte-identical honest-survivor chains over [2, upto] — the
        byzantine.py verdict, fed from HTTP-collected header maps.
        `expected` pins how many chains MUST be present: agreement
        among the two nodes that happened to answer says nothing
        about the six that timed out."""
        chains = {}
        for name, doc in statuses.items():
            if doc is None:
                continue
            hdrs = doc.get("headers", {})
            chains[name] = [hdrs.get(str(seq), "")
                            for seq in range(2, upto + 1)]
        if expected is not None and len(chains) < expected:
            return False
        return header_chains_agree(chains)

    def flood_report(self, deadline_s: float = 15.0) -> dict:
        """Aggregate flood redundancy + per-peer byte counters from
        every live node's `peers` route (the bench _flood_report shape,
        collected over HTTP)."""
        from ..overlay.manager import (finalize_flood_evidence,
                                       merge_flood_evidence)
        docs = self._sweep("peers", None, deadline_s,
                           ok=lambda d: "authenticated_peers" in d)
        unique = dup = bytes_sent = bytes_recv = 0
        per_peer = []
        demand: dict = {}
        encode: dict = {}
        by_kind: dict = {}
        by_name = {n.name: n for n in self.nodes}
        for name, doc in docs.items():
            node = by_name[name]
            if doc is None:
                continue
            peers = doc["authenticated_peers"]
            flood = peers.get("flood") or {}
            unique += flood.get("unique", 0)
            dup += flood.get("duplicates", 0)
            # ISSUE 12 wire-path evidence, per node over HTTP:
            # single-flight demand totals, encode-cache efficiency
            # and the SCP-vs-tx dedup split
            merge_flood_evidence(demand, flood.get("demand"))
            merge_flood_evidence(encode, flood.get("encode"))
            merge_flood_evidence(by_kind, flood.get("by_kind"))
            for row in peers.get("inbound", []) + \
                    peers.get("outbound", []):
                bytes_sent += row["bytes_sent"]
                bytes_recv += row["bytes_received"]
                per_peer.append({
                    "node": node.name, "peer": row["id"][:12],
                    "bytes_sent": row["bytes_sent"],
                    "bytes_received": row["bytes_received"],
                    "messages_sent": row["messages_sent"],
                    "messages_received": row["messages_received"],
                    "duplicates": row["duplicates"],
                })
        finalize_flood_evidence(demand, encode)
        return {
            "unique": unique,
            "duplicates": dup,
            "duplicate_ratio": round(dup / max(1, unique), 4),
            "bytes_sent_total": bytes_sent,
            "bytes_received_total": bytes_recv,
            "per_peer_bytes": per_peer,
            "demand": demand,
            "encode": encode,
            "by_kind": by_kind,
        }

    # ----------------------------------------------------------- telemetry --
    # stored samples per node are capped: the node-side ring is already
    # bounded, but an incremental scrape accumulates across the whole
    # run — a long soak must not grow the harness without bound either
    MAX_SAMPLES_PER_NODE = 10_000

    def poll_timeseries(self, deadline_s: float = 15.0) -> int:
        """One incremental telemetry sweep (the `timeseries` route's
        since=<cursor> contract): each live node is asked only for
        samples newer than the cursor the previous sweep returned.
        A node that restarted (new epoch) or evicted past the cursor
        answers reset=true with its full ring — the harness drops its
        stale tail and resyncs. Returns the number of new samples."""
        new = [0]

        def step(node: ClusterNode) -> bool:
            try:
                params = {"since": node.ts_token} if node.ts_token \
                    else None
                doc = node.get("timeseries", params, timeout=1.0)
            except (OSError, ValueError, ClusterError):
                return False
            ts = doc.get("timeseries")
            if ts is None:
                return False
            if ts.get("reset") and node.ts_token is not None:
                node.ts_resets += 1
            samples = ts.get("samples", [])
            for s in samples:
                s["node"] = node.name
            node.ts_samples.extend(samples)
            if len(node.ts_samples) > self.MAX_SAMPLES_PER_NODE:
                node.ts_samples = \
                    node.ts_samples[-self.MAX_SAMPLES_PER_NODE:]
            node.ts_token = ts.get("cursor")
            new[0] += len(samples)
            return True

        self._await_all([n for n in self.nodes if n.alive],
                        deadline_s, step)
        return new[0]

    def series_summary(self) -> dict:
        """Cluster-wide bounded series summary (the CLUSTER artifact
        form): per-node summaries plus the aggregate envelope."""
        from ..util.timeseries import (aggregate_summaries,
                                       summarize_samples)
        per_node = {n.name: summarize_samples(n.ts_samples)
                    for n in self.nodes}
        out = aggregate_summaries(list(per_node.values()))
        out["per_node"] = per_node
        out["scrape_resets"] = sum(n.ts_resets for n in self.nodes)
        return out

    def collect_controller(self, deadline_s: float = 15.0) -> dict:
        """Sweep every live node's `controller` route (ISSUE 11): the
        adaptive control plane's live knob values, shed levels, and
        decision tallies, merged into per-node docs plus cluster-wide
        shed/tune totals for the CLUSTER artifact."""
        docs = self._sweep("controller", None, deadline_s,
                           ok=lambda d: "controller" in d)
        per_node = {}
        totals = {"tx_dropped": 0, "flood_dropped": 0,
                  "tune_up": 0, "tune_down": 0, "shed_changes": 0}
        for name, doc in docs.items():
            c = doc.get("controller") if doc else None
            if c is None:
                per_node[name] = None
                continue
            per_node[name] = {
                "knobs": c.get("knobs"),
                "shed": c.get("shed"),
                "frozen": c.get("frozen"),
                "ticks": c.get("ticks"),
            }
            shed = c.get("shed") or {}
            dec = c.get("decisions") or {}
            totals["tx_dropped"] += shed.get("tx_dropped", 0)
            totals["flood_dropped"] += shed.get("flood_dropped", 0)
            totals["tune_up"] += dec.get("tune_up", 0)
            totals["tune_down"] += dec.get("tune_down", 0)
            totals["shed_changes"] += dec.get("shed_changes", 0)
        return {"per_node": per_node, "totals": totals}

    def collect_backend(self, deadline_s: float = 15.0) -> dict:
        """Sweep every live node's `backendstatus` route (ISSUE 13):
        aggregate breaker state, the surviving-mesh summary and the
        per-device breaker rows, merged into per-node docs plus
        cluster-wide degradation totals for the CLUSTER artifact. A
        node without a supervised device backend reports None."""
        docs = self._sweep("backendstatus", None, deadline_s,
                           ok=lambda d: "backend" in d
                           or "exception" in d)
        per_node = {}
        totals = {"devices": 0, "active": 0, "open_devices": 0,
                  "quarantined": 0}
        for name, doc in docs.items():
            b = (doc or {}).get("backend")
            if b is None:
                per_node[name] = None
                continue
            mesh = b.get("mesh") or {}
            per_node[name] = {
                "state": b.get("state"),
                "mesh": mesh,
                "devices": [
                    {k: d.get(k) for k in ("device", "state",
                                           "consecutive_failures",
                                           "dispatches", "skips")}
                    for d in b.get("devices", [])],
                "failures": b.get("failures"),
                "transition_count": b.get("transition_count"),
            }
            totals["devices"] += mesh.get("devices", 0)
            totals["active"] += mesh.get("active", 0)
            totals["open_devices"] += sum(
                1 for d in b.get("devices", [])
                if d.get("state") == "OPEN")
            totals["quarantined"] += len(b.get("quarantined", []))
        return {"per_node": per_node, "totals": totals}

    def collect_slo(self, deadline_s: float = 15.0) -> dict:
        """Sweep every live node's `slo` route and aggregate: worst
        verdict per rule across the cluster, breach tallies summed,
        plus each node's own composite verdict."""
        from ..ops.slo import aggregate_status
        docs = self._sweep("slo", None, deadline_s,
                           ok=lambda d: "slo" in d)
        statuses = {name: (doc["slo"] if doc else None)
                    for name, doc in docs.items()}
        out = aggregate_status([s for s in statuses.values() if s])
        out["per_node"] = {
            name: (s.get("overall") if s else None)
            for name, s in statuses.items()}
        return out

    # ------------------------------------------------------------- tracing --
    def start_tracing(self) -> None:
        for node in self.nodes:
            if node.alive:
                node.get("starttrace")

    def merged_trace(self, deadline_s: float = 30.0) -> dict:
        """Collect every live node's `dumptrace` export and stitch them
        into one cluster-wide Chrome trace (wall-clock-aligned process
        lanes, cross-node flood flow chains)."""
        from ..util.tracemerge import merge_trace_docs
        collected = self._sweep("dumptrace", None, deadline_s,
                                ok=lambda d: "trace" in d)
        docs, labels = [], []
        for node in self.nodes:
            doc = collected.get(node.name)
            if doc is not None:
                docs.append(doc["trace"])
                labels.append(node.name)
        return merge_trace_docs(docs, labels=labels)


def _free_ports(n: int) -> List[int]:
    """OS-assigned free TCP ports for the overlay listeners. All
    sockets are held open until every port is drawn, so one call can't
    hand out duplicates. Known limitation: unlike HTTP_PORT=0 (bound
    by the node itself, race-free), overlay ports must be rendered
    into every neighbor's KNOWN_PEERS before any node boots — probe
    and bind are therefore separated by seconds, and another process
    can steal a port in between. The loss is LOUD, not silent: the
    node fails to bind, dies during boot, and wait_ready raises with
    the node's log path."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


# --------------------------------------------------------------- scenario --
def bad_sig_flood_schedule(flooder_hex: str, burst: int = 6
                           ) -> List[dict]:
    """The cluster chaos schedule (JSON form, installed over HTTP on
    every honest node): each TRANSACTION body received from the
    flooder grows a burst of forged bad-signature twins — the
    byzantine.py flood modeled at the receiving seam."""
    return [{"point": "overlay.transaction.recv",
             "kind": "bad_sig_flood", "start": 0, "count": 1_000_000,
             "burst": burst, "match": {"peer": flooder_hex}}]


def run_cluster_scenario(root_dir: str, n_orgs: int = 3,
                         validators_per_org: int = 3,
                         close_time: float = 0.5,
                         target_slots: int = 5,
                         load_accounts: int = 100,
                         load_rounds: int = 3,
                         txs_per_round: int = 300,
                         chaos: bool = True, churn: bool = True,
                         chaos_seed: int = 9,
                         trace: bool = False,
                         trace_path: Optional[str] = None,
                         boot_deadline_s: float = 180.0,
                         log_level: str = "warning") -> dict:
    """The full harness scenario (bench --tps-cluster / the slow test):
    boot a tiered process-per-node cluster over real TCP, measure pay
    TPS over the wire, run the chaos leg (seeded bad-sig flood over
    HTTP + a real kill -9 churn with catchup over the wire), and
    collect all verdicts from the admin APIs. Returns the CLUSTER
    artifact core (bench adds metric/host_load wrapping)."""
    import time as _wall

    n_nodes = n_orgs * validators_per_org
    cluster = Cluster(n_orgs, validators_per_org, root_dir,
                      close_time=close_time, log_level=log_level)
    wall0 = _wall.perf_counter()
    result: dict = {"nodes": n_nodes,
                    "topology": f"tiered {n_orgs}x{validators_per_org}"}
    with cluster:
        cluster.start_all(boot_deadline_s)
        cluster.wait_mesh(60.0 + 5.0 * n_nodes)
        cluster.wait_slot(2, 60.0)
        node0 = cluster.nodes[0]
        result["boot_wall_s"] = round(_wall.perf_counter() - wall0, 1)

        # ---- load phase: accounts, then measured pay rounds --------
        cluster.generate_load(node0, "create", accounts=load_accounts)
        cluster.wait_slot(cluster.lcl(node0) + 2, 60.0)
        if trace:
            cluster.start_tracing()
        applied = 0
        t0 = time.monotonic()
        for _ in range(load_rounds):
            r = cluster.generate_load(node0, "pay", txs=txs_per_round)
            applied += int(r.get("submitted", 0))
            if not cluster.drain_pending(node0, 90.0):
                raise ClusterError("load never drained from node0")
            # node0's queue drained at its CURRENT tip: every other
            # node must close that same ledger before the round's
            # clock stops — the measured rate covers the full
            # wire+consensus+apply pipeline on the SLOWEST node, not
            # just the submitter
            cluster.wait_slot(cluster.lcl(node0), 90.0)
            # incremental telemetry scrape per load round: the ring is
            # bounded node-side, so waiting for one final sweep could
            # lose the run's early samples on a long leg
            cluster.poll_timeseries(10.0)
        dt = time.monotonic() - t0
        tps = applied / dt if dt else 0.0
        result["tps"] = round(tps, 1)
        result["applied"] = applied
        result["load_wall_s"] = round(dt, 1)
        if trace:
            merged = cluster.merged_trace()
            result["trace_events"] = len(merged.get("traceEvents", []))
            if trace_path:
                # the inspectable artifact is the point of the merge —
                # the sibling benches all write trace_*.json too
                with open(trace_path, "w") as f:
                    json.dump(merged, f)
                result["trace_path"] = trace_path

        # ---- chaos leg: bad-sig flood over HTTP ---------------------
        if chaos:
            flooder = cluster.nodes[-1]
            honest = [n for n in cluster.nodes if n is not flooder]
            for node in honest:
                cluster.install_chaos(
                    node, chaos_seed,
                    bad_sig_flood_schedule(flooder.node_id.hex()))
            # template traffic must ORIGINATE at the flooder so the
            # receivers' seam attributes the forged burst to it; pay
            # txs (one op = one TRANSACTION frame each) give the seam
            # enough templates to push every direct neighbor past the
            # drop threshold — a CREATE batch is just one frame
            cluster.generate_load(flooder, "create", accounts=8)
            cluster.wait_slot(cluster.lcl(flooder) + 2, 60.0)
            cluster.generate_load(flooder, "pay", txs=30)

            def flooder_dropped(d) -> bool:
                cs = d.get("clusterstatus", {})
                return cs.get("peers", {}).get("drop_reasons", {}) \
                    .get("bad sig flood", 0) > 0
            # round-robin sweep: only the flooder's direct topology
            # neighbors receive frames attributed to it, so ANY honest
            # node tripping the threshold passes — and no single
            # never-tripping node may burn the shared deadline
            deadline = time.monotonic() + 60.0
            dropped_on = None
            while dropped_on is None and time.monotonic() < deadline:
                for node in honest:
                    try:
                        if flooder_dropped(node.get("clusterstatus",
                                                    timeout=1.0)):
                            dropped_on = node.name
                            break
                    except (OSError, ValueError, ClusterError):
                        pass
                if dropped_on is None:
                    honest[0].jittered_sleep(POLL_BASE_S * 3)
            # cumulative drop counter off the `metrics` route — the
            # per-peer counter on `peers` dies with each dropped
            # connection (the flooder re-dials with a fresh Peer), so
            # only the aggregate survives to the final sweep
            bad_sig_total = 0
            deadline = time.monotonic() + 15.0
            for node in honest:
                doc = node.poll("metrics", deadline=deadline,
                                ok=lambda d: "metrics" in d)
                if doc is not None:
                    bad_sig_total += doc["metrics"].get(
                        "overlay.peer.drop.bad_sig", {}).get("count", 0)
            result["chaos"] = {
                "kind": "bad_sig_flood",
                "flooder": flooder.name,
                "flooder_dropped": dropped_on is not None,
                "dropped_on": dropped_on,
                "bad_sig_drops": bad_sig_total,
            }

        # ---- churn leg: REAL kill -9, restart, catchup over the wire
        if churn:
            victim = cluster.nodes[1]
            # survivors = honest nodes only: the just-dropped flooder
            # may legitimately lag or stall, and it must neither gate
            # the survivors' liveness check nor drag net_lcl down to
            # its stale tip (a false-pass catchup verdict)
            survivors = [n for n in cluster.nodes
                         if n is not victim
                         and not (chaos and n is cluster.nodes[-1])]
            lcl_at_kill = cluster.lcl(victim)
            t_churn = time.monotonic()
            cluster.kill_node(victim)
            # the survivors must keep externalizing without the victim
            cluster.wait_slot(lcl_at_kill + 2, 90.0, nodes=survivors)
            cluster.restart_node(victim, deadline_s=90.0)
            net_lcl = cluster.min_lcl(survivors)
            caught = victim.poll(
                "info", deadline=time.monotonic() + 120.0,
                ok=lambda d: d.get("info", {}).get("ledger", {})
                .get("num", 0) >= net_lcl) is not None
            result["churn"] = {
                "victim": victim.name,
                "lcl_at_kill": lcl_at_kill,
                "network_lcl_at_restart": net_lcl,
                "caught_up": caught,
                "recovery_wall_s": round(
                    time.monotonic() - t_churn, 1),
            }

        # ---- verdict sweep ------------------------------------------
        # honest survivors (the byzantine.py semantics): the flooder's
        # neighbors legitimately dropped it, so — like the in-process
        # scenarios — it is excluded from the agreement/liveness/
        # health verdicts; everyone else must hold them
        honest_nodes = [n for n in cluster.nodes
                        if not (chaos and n is cluster.nodes[-1])]
        cluster.wait_slot(2 + target_slots, 120.0, nodes=honest_nodes)
        live = [n for n in honest_nodes if n.alive]
        upto = cluster.min_lcl(live)
        honest_names = {n.name for n in honest_nodes}
        statuses = cluster.collect_clusterstatus(
            30.0, headers=f"2-{upto}")
        per_node = {}
        clusterstatus_ok = True
        for name, doc in statuses.items():
            if doc is None:
                if name in honest_names:
                    clusterstatus_ok = False
                per_node[name] = {"clusterstatus_ok": False}
                continue
            per_node[name] = {
                "clusterstatus_ok": True,
                "healthy": doc.get("healthy", False),
                "ledger": doc.get("ledger", {}).get("num", 0),
                "close": doc.get("close", {}),
                "tx_e2e": doc.get("tx_e2e", {}),
            }
            if name in honest_names:
                clusterstatus_ok &= bool(doc.get("healthy"))
        safety_ok = cluster.headers_agree(
            upto, {k: v for k, v in statuses.items()
                   if k in honest_names},
            expected=len(honest_nodes))
        result["flood"] = cluster.flood_report()
        # final telemetry sweep + the merged cluster-wide series
        # summary and SLO verdict section (ISSUE 10: the CLUSTER
        # artifact carries the time dimension, not just endpoints)
        cluster.poll_timeseries(15.0)
        result["timeseries"] = cluster.series_summary()
        result["slo"] = cluster.collect_slo(15.0)
        # adaptive control plane state per node (ISSUE 11): knob
        # positions, shed levels and decision tallies ride the
        # artifact beside the series they were derived from
        result["controller"] = cluster.collect_controller(15.0)
        # per-device breaker state per node (ISSUE 13): surviving-mesh
        # summaries and per-device dispatch/skip evidence
        result["backend"] = cluster.collect_backend(15.0)
        result["verdicts"] = per_node
        result["clusterstatus_ok"] = clusterstatus_ok
        result["safety_ok"] = safety_ok
        result["slots_externalized"] = upto
        result["liveness_ok"] = upto >= 2 + target_slots
        # graceful teardown (the SIGTERM satellite): every node drains
        # its completion queue and exits 0
        rcs = cluster.stop_all(graceful=True)
        result["graceful_shutdown_ok"] = all(
            rc == 0 for rc in rcs.values())
        result["shutdown_rcs"] = rcs
    result["wall_seconds"] = round(_wall.perf_counter() - wall0, 1)
    result["ok"] = bool(
        result.get("safety_ok") and result.get("liveness_ok")
        and result.get("clusterstatus_ok")
        and (not chaos or result["chaos"]["flooder_dropped"])
        and (not churn or result["churn"]["caught_up"])
        and result.get("graceful_shutdown_ok"))
    return result


# ---------------------------------------------------- scenario matrix --
def run_matrix_cell(root_dir: str, cell: dict) -> dict:
    """One cell of the wide-area survival matrix (ISSUE 20): boot a
    real-process tiered mesh, drive the cell's load shape (uniform or
    Zipf-skewed, optional surge burst), overlay its fault legs
    (partition / flap / slow-link / sick-device — any subset), and
    return a TYPED verdict doc the MATRIX artifact schema checks
    per-cell:

    - ``survival_ok`` — the quorum-holding side kept externalizing
      through every fault window and no node process crashed;
    - ``rejoin_ok`` — every partitioned/flapped-out node caught back
      up to the network LCL within the cell's bounded rejoin window
      (vacuously true for cells without a link fault);
    - ``safety_ok`` — byte-identical header chains across ALL live
      nodes over the common prefix (the byzantine.py verdict), which
      is what makes a rejoin count: agreeing late is still agreeing;
    - ``slo_ok`` — the cluster-wide SLO aggregate did not BREACH;
    - ``crashes`` — node processes dead at verdict time (must be 0:
      a minority partition STALLS safely, it never dies).

    Every node records its input stream (`recordstart`, ISSUE 18) so a
    failing cell ships per-node replay logs in ``record_paths``."""
    import time as _wall

    name = cell["name"]
    n_orgs = int(cell.get("n_orgs", 3))
    vpo = int(cell.get("validators_per_org", 1))
    n_nodes = n_orgs * vpo
    close_time = float(cell.get("close_time", 1.0))
    target_slots = int(cell.get("target_slots", 3))
    seed = int(cell.get("chaos_seed", 20))
    cluster = Cluster(n_orgs, vpo, root_dir, close_time=close_time,
                      log_level=cell.get("log_level", "warning"))
    wall0 = _wall.perf_counter()
    doc: dict = {"name": name, "nodes": n_nodes,
                 "topology": f"tiered {n_orgs}x{vpo}",
                 "survival_ok": False, "rejoin_ok": True,
                 "safety_ok": False, "slo_ok": False,
                 "crashes": n_nodes, "ok": False, "faults": []}
    survival_ok = True
    rejoin_ok = True
    with cluster:
        cluster.start_all(float(cell.get("boot_deadline_s", 240.0)))
        cluster.wait_mesh(90.0 + 5.0 * n_nodes)
        cluster.wait_slot(2, 120.0)
        if cell.get("record", True):
            doc["record_paths"] = cluster.record_all()
        node0 = cluster.nodes[0]

        # ---- load phase: the cell's traffic shape ------------------
        cluster.generate_load(node0, "create",
                              accounts=int(cell.get("accounts", 40)))
        cluster.wait_slot(cluster.lcl(node0) + 2, 120.0)
        load_mode = cell.get("load", "uniform")
        txs_per_round = int(cell.get("txs_per_round", 80))
        applied = 0
        t0 = time.monotonic()
        for _ in range(int(cell.get("rounds", 1))):
            if load_mode == "zipf":
                r = cluster.generate_load(
                    node0, "zipf", txs=txs_per_round,
                    exponent=float(cell.get("zipf_exponent", 1.2)))
            else:
                r = cluster.generate_load(node0, "pay",
                                          txs=txs_per_round)
            applied += int(r.get("submitted", 0))
            if not cluster.drain_pending(node0, 180.0):
                raise ClusterError(f"{name}: load never drained")
            cluster.wait_slot(cluster.lcl(node0), 180.0)
        dt = time.monotonic() - t0
        doc["tps"] = round(applied / dt, 1) if dt else 0.0
        doc["applied"] = applied

        # ---- surge leg: one oversized burst ------------------------
        surge = int(cell.get("surge", 0))
        if surge:
            doc["faults"].append("surge")
            cluster.generate_load(node0, "pay", txs=surge)
            if not cluster.drain_pending(node0, 240.0):
                survival_ok = False
            else:
                cluster.wait_slot(cluster.lcl(node0), 180.0)

        # ---- slow-link leg: WAN shapes on the real sockets ---------
        sl = cell.get("slow_link")
        if sl:
            doc["faults"].append("slow_link")
            latency = topologies.LinkLatency(
                seed=int(sl.get("seed", 7)),
                intra_org_ms=float(sl.get("intra_org_ms", 2.0)),
                cross_org_ms=tuple(sl.get("cross_org_ms",
                                          (30.0, 120.0))),
                bandwidth_bps=sl.get("bps"))
            cluster.install_schedules(
                cluster.shape_schedules(
                    latency, window_s=float(sl.get("window_s", 0.0))),
                seed)
            lcl0 = cluster.lcl(node0)
            cluster.generate_load(node0, "pay",
                                  txs=int(sl.get("txs", 60)))
            try:
                # shaped links are slow, not dead: consensus must keep
                # externalizing under the WAN delays
                cluster.wait_slot(lcl0 + 2, 300.0)
            except ClusterError:
                survival_ok = False
            cluster.clear_all_chaos()

        # ---- flap leg: one node's links cycle down/up under load ---
        fl = cell.get("flap")
        if fl:
            doc["faults"].append("flap")
            window = float(fl.get("window_s", 10.0))
            victim = cluster.nodes[-1]
            others = [n for n in cluster.nodes if n is not victim]
            cluster.install_schedules(
                cluster.flap_schedules(
                    [(victim, nb) for nb in victim.neighbors],
                    window,
                    period_s=float(fl.get("period_s", 3.0)),
                    duty=float(fl.get("duty", 0.4))),
                seed + 1)
            lcl0 = cluster.min_lcl(others)
            cluster.generate_load(node0, "pay",
                                  txs=int(fl.get("txs", 60)))
            try:
                cluster.wait_slot(lcl0 + 2, 240.0, nodes=others)
            except ClusterError:
                survival_ok = False
            # let the windows elapse everywhere, then heal explicitly
            # (belt and braces) and require the flapped node to catch
            # back up — a flapping WAN link must degrade, not detach
            time.sleep(window)
            cluster.clear_all_chaos()
            net = cluster.min_lcl(others)
            caught = victim.poll(
                "info", deadline=time.monotonic()
                + float(fl.get("rejoin_s", 150.0)),
                ok=lambda d: d.get("info", {}).get("ledger", {})
                .get("num", 0) >= net)
            if caught is None:
                rejoin_ok = False

        # ---- partition leg: cut one org off the quorum -------------
        pt = cell.get("partition")
        if pt:
            doc["faults"].append("partition")
            window = float(pt.get("window_s", 10.0))
            minority = cluster.nodes[:vpo]           # org 0, < top tier
            majority = cluster.nodes[vpo:]
            maj0 = majority[0]
            cluster.install_schedules(
                cluster.partition_schedules(minority, window),
                seed + 2)
            # traffic originates on the MAJORITY side: the partition
            # fires at the send/dial seams, so the cut links must see
            # sends — SCP traffic alone would do it, load makes it
            # immediate
            cluster.generate_load(maj0, "create", accounts=8)
            lcl0 = cluster.min_lcl(majority)
            try:
                cluster.wait_slot(lcl0 + 3, 240.0, nodes=majority)
            except ClusterError:
                survival_ok = False
            # the minority must STALL SAFELY: still alive, no crash.
            # ONE short request per stalled node — they just lost
            # their quorum, a retried poll would burn the cell budget
            mlcls = []
            for n in minority:
                v = cluster._lcl_or_unknown(n)
                mlcls.append(v if isinstance(v, int) else 0)
            doc["partition"] = {
                "window_s": window,
                "minority": [n.name for n in minority],
                "majority_lcl_mid": cluster.min_lcl(majority),
                "minority_alive_mid": all(n.alive for n in minority),
                "minority_lcl_mid": min(mlcls),
            }
            if not doc["partition"]["minority_alive_mid"]:
                survival_ok = False
            # heal: let every window elapse, clear any remainder, and
            # re-knit the mesh (jittered dial retry + connect nudges)
            time.sleep(window)
            cluster.clear_all_chaos()
            try:
                cluster.wait_mesh(120.0 + 5.0 * n_nodes)
            except ClusterError:
                rejoin_ok = False
            net = cluster.min_lcl(majority)
            rejoin_deadline = time.monotonic() \
                + float(pt.get("rejoin_s", 180.0))
            t_heal = time.monotonic()
            for n in minority:
                ok_doc = n.poll(
                    "info", deadline=rejoin_deadline,
                    ok=lambda d: d.get("info", {}).get("ledger", {})
                    .get("num", 0) >= net)
                if ok_doc is None:
                    rejoin_ok = False
            doc["partition"]["rejoin_wall_s"] = round(
                time.monotonic() - t_heal, 1)
            doc["partition"]["network_lcl_at_heal"] = net

        # ---- sick-device leg: trip one node's accel breaker --------
        sd = cell.get("sick_device")
        if sd:
            doc["faults"].append("sick_device")
            sick = cluster.nodes[-1]
            tripped = False
            try:
                sick.get("backendstatus", {"action": "trip"})
                tripped = True
            except (OSError, ValueError, ClusterError):
                pass    # no supervised backend on this build: the leg
                        # still asserts plain survival
            lcl0 = cluster.lcl(node0)
            try:
                cluster.wait_slot(lcl0 + 2, 180.0)
            except ClusterError:
                survival_ok = False
            time.sleep(float(sd.get("hold_s", 2.0)))
            if tripped:
                try:
                    sick.get("backendstatus", {"action": "reset"})
                except (OSError, ValueError, ClusterError):
                    pass
            doc["sick_device"] = {"node": sick.name,
                                  "tripped": tripped}

        # ---- verdict sweep -----------------------------------------
        try:
            cluster.wait_slot(2 + target_slots, 240.0)
        except ClusterError:
            survival_ok = False
        live = [n for n in cluster.nodes if n.alive]
        doc["crashes"] = n_nodes - len(live)
        upto = cluster.min_lcl(live)
        statuses = cluster.collect_clusterstatus(45.0,
                                                 headers=f"2-{upto}")
        safety_ok = cluster.headers_agree(upto, statuses,
                                          expected=len(live))
        flood = cluster.flood_report()
        doc["duplicate_ratio"] = flood.get("duplicate_ratio", 0.0)
        doc["flood"] = {k: flood[k] for k in
                        ("unique", "duplicates", "duplicate_ratio")}
        doc["flow"] = cluster.flow_report()
        slo = cluster.collect_slo(20.0)
        doc["slo"] = {"overall": slo.get("overall"),
                      "nodes": slo.get("nodes", 0)}
        doc["slots"] = upto
        cluster.recordstop_all()
        rcs = cluster.stop_all(graceful=True)
        doc["graceful_shutdown_ok"] = all(
            rc == 0 for rc in rcs.values())
    doc["survival_ok"] = bool(survival_ok and doc["crashes"] == 0)
    # a rejoin only COUNTS when the rejoined chain is byte-identical
    # to the survivors' — agreeing late is still agreeing; diverging
    # after a heal is the failure this matrix exists to catch
    doc["safety_ok"] = bool(safety_ok)
    doc["rejoin_ok"] = bool(rejoin_ok and safety_ok)
    doc["slo_ok"] = slo.get("overall") != "BREACH"
    doc["wall_s"] = round(_wall.perf_counter() - wall0, 1)
    doc["ok"] = bool(
        doc["survival_ok"] and doc["rejoin_ok"] and doc["safety_ok"]
        and doc["slo_ok"] and doc["flow"]["within_budget"]
        and doc["flow"]["scp_never_shed_first"]
        and doc["graceful_shutdown_ok"])
    return doc
