"""Adversarial (Byzantine) multinode scenarios: equivocating SCP votes,
invalid-signature floods against the verify service, malformed XDR on
the wire, and node churn with catchup-under-chaos.

Mazières 2015 (PAPERS.md) specifies what SCP must survive: safety under
*ill-behaved* nodes, not just crashed ones. PR 2/PR 5's chaos scenarios
(simulation/chaos.py) cover the honest-but-faulty family; this module is
the adversarial counterpart on the tiered 50–100-node topologies
(simulation/topologies.py). Verdict semantics differ from chaos.py in
one key way: with a Byzantine proposer in the mix, the externalized
values legitimately DIFFER from a fault-free run (the equivocator's
forged twin can win a slot), so **safety is honest-survivor agreement**
— every honest node's header chain byte-identical to every other
honest node's — not equality with a baseline leg.

Scenario shapes:

- ``run_smoke`` — the tier-1 acceptance leg: a 9-node tiered quorum
  (3 orgs × 3) with one equivocator and one bad-sig flooder; honest
  nodes must externalize ≥ `target_slots` slots with byte-identical
  headers while the flooder gets dropped by per-peer accounting.
- ``run_tiered_chaos`` — the `slow` leg: 50+ nodes (orgs + watcher
  tier) with the per-link latency model, equivocation, bad-sig flood,
  a malformed-XDR window, and churn: a validator is killed mid-close
  (`SimulatedChurn`), restarted from its persisted DB + bucket dir a
  few slots later, and must catch back up over the overlay while the
  equivocator is still active.
- ``run_byzantine_bench`` — the ``bench.py --byzantine`` artifact:
  measured slots-to-externalize under equivocation (vs a clean leg),
  verify-service throughput under the bad-sig flood, and churn
  recovery time.
"""

from __future__ import annotations

import time as _wall
from typing import Dict, List, Optional

from ..crypto.keys import SecretKey, clear_verify_cache
from ..herder.tx_queue import AddResult
from ..tx.frame import make_frame
from ..util import chaos
from ..util.chaos import ChaosEngine, FaultSpec, SimulatedCrash
from ..util.logging import get_logger
from ..xdr.ledger_entries import Asset, AssetType, LedgerKey
from ..xdr.transaction import (DecoratedSignature, Memo, MemoType,
                               MuxedAccount, Operation, OperationType,
                               PaymentOp, Preconditions, PreconditionType,
                               Transaction, TransactionEnvelope,
                               TransactionV1Envelope, _OperationBody,
                               _TxExt)
from ..xdr.types import EnvelopeType
from . import topologies
# crash/churn-aware crank loop shared with the honest-but-faulty
# scenarios (one copy: simulation/chaos.py)
from .chaos import _crank_with_crashes as _crank_byz

log = get_logger("Chaos")

FIRST_LOADED_LEDGER = 3


def _configure(threshold: int = 16):
    def conf(cfg):
        # pinned close times + synchronous merges: deterministic,
        # reproducible runs (docs/CHAOS.md determinism contract)
        cfg.ARTIFICIALLY_SET_CLOSE_TIME_FOR_TESTING = 1
        cfg.ARTIFICIALLY_PESSIMIZE_MERGES_FOR_TESTING = True
        # per-peer flooder accounting trips fast enough to matter
        # within a short scenario (satellite: PEER_BAD_SIG_DROP_THRESHOLD)
        cfg.PEER_BAD_SIG_DROP_THRESHOLD = threshold
        # telemetry on the shared VirtualClock (ISSUE 10): one sample
        # per virtual second per node feeds the BYZ artifact's
        # time-series summary + SLO verdicts — deterministic, since
        # the scenario clock is seeded-virtual
        cfg.TELEMETRY_SAMPLE_PERIOD = 1.0
    return conf


def _prep(sim) -> None:
    for app in sim.apps():
        # inline completion: deterministic chaos hit ordinals
        app.ledger_manager.defer_completion = False


def _install_verify_stack(app, clock) -> None:
    """Batch verifier + coalescing verify service on one node, host
    dispatch only (device_min_batch beyond any batch — the Byzantine
    verdicts must not depend on XLA compiles). The flood admission path
    then rides the service exactly as in production."""
    from ..ops.verifier import TpuBatchVerifier
    from ..ops.verify_service import VerifyService
    bv = TpuBatchVerifier(perf=app.perf, device_min_batch=1 << 20)
    app.batch_verifier = bv
    app.herder.batch_verifier = bv
    app.verify_service = VerifyService(bv, clock=clock,
                                       metrics=app.metrics,
                                       perf=app.perf)
    app.herder.verify_service = app.verify_service


class _TargetedPayer:
    """Per-ledger root self-payment submitted to ONE node (the flood
    template source): the tx propagates to everyone else over the real
    advert/demand/TRANSACTION path, which is exactly the wire the
    bad-sig flooder rides."""

    def __init__(self, sim, target_app):
        self.sim = sim
        self.network_id = target_app.config.network_id()
        self.key = SecretKey.from_seed(self.network_id)
        self.target = target_app
        from ..ledger.ledger_txn import LedgerTxn
        from ..xdr.types import PublicKey
        with LedgerTxn(target_app.ledger_manager.root) as ltx:
            le = ltx.load_without_record(LedgerKey.account(
                PublicKey.ed25519(self.key.public_key().raw)))
            self.seq = le.data.value.seqNum
        self.submitted = 0

    def submit_one(self) -> AddResult:
        self.seq += 1
        muxed = MuxedAccount.from_ed25519(self.key.public_key().raw)
        tx = Transaction(
            sourceAccount=muxed, fee=100, seqNum=self.seq,
            cond=Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE),
            operations=[Operation(sourceAccount=None, body=_OperationBody(
                OperationType.PAYMENT, PaymentOp(
                    destination=muxed,
                    asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                    amount=1)))],
            ext=_TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=tx, signatures=[]))
        probe = make_frame(env, self.network_id)
        env.value.signatures = [DecoratedSignature(
            hint=self.key.public_key().hint(),
            signature=self.key.sign(probe.contents_hash()))]
        frame = make_frame(env, self.network_id)
        res = self.target.herder.recv_transactions([frame])[0]
        if res not in (AddResult.ADD_STATUS_PENDING,
                       AddResult.ADD_STATUS_DUPLICATE):
            raise RuntimeError(f"byzantine load tx rejected: {res}")
        self.submitted += 1
        return res




def _restart_and_catch_up(sim, node: bytes, honest: List[bytes]) -> dict:
    """Resurrect a churned node from persisted state and crank until it
    reaches the honest tip — catchup-under-chaos (any installed
    schedule keeps firing). Returns the churn evidence dict."""
    t0 = sim.clock.now()
    lcl_before = sim.nodes[node].ledger_manager \
        .get_last_closed_ledger_num()
    app = sim.restart_node(node)
    app.ledger_manager.defer_completion = False
    _install_verify_stack(app, sim.clock)
    net_lcl = max(sim.nodes[n].ledger_manager
                  .get_last_closed_ledger_num()
                  for n in honest if n not in sim.crashed)
    caught = sim.crank_until(
        lambda: app.ledger_manager.get_last_closed_ledger_num()
        >= net_lcl, timeout_virtual_seconds=300.0)
    return {
        "node": node.hex(),
        "lcl_at_restart": lcl_before,
        "network_lcl_at_restart": net_lcl,
        "caught_up": bool(caught),
        "recovery_virtual_s": round(sim.clock.now() - t0, 3),
    }


def _honest_hashes(sim, honest: List[bytes], upto: int
                   ) -> Dict[bytes, List[bytes]]:
    out: Dict[bytes, List[bytes]] = {}
    for nid in honest:
        if nid in sim.crashed:
            continue
        app = sim.nodes[nid]
        hashes = []
        for seq in range(2, upto + 1):
            row = app.database.query_one(
                "SELECT ledgerhash FROM ledgerheaders WHERE ledgerseq=?",
                (seq,))
            hashes.append(bytes(row[0]) if row else b"")
        out[nid] = hashes
    return out


def header_chains_agree(hashes: Dict) -> bool:
    """THE honest-survivor safety verdict (module docstring): every
    surviving honest node's header chain complete (no missing rows)
    and byte-identical to every other's. Chains may be lists of raw
    bytes (in-process scenarios) or hex strings (the multi-process
    cluster harness collecting `clusterstatus?headers=` over HTTP) —
    a missing header is the falsy value either way."""
    chains = list(hashes.values())
    return bool(chains) and all(h for h in chains[0]) and \
        all(c == chains[0] for c in chains[1:])


# internal alias kept for the scenario runners below
_honest_agree = header_chains_agree


def byzantine_schedule(eq_hex: str, flooder_hex: str,
                       burst: int = 8) -> List[FaultSpec]:
    """The canonical 2-adversary schedule: `eq_hex` equivocates on
    every SCP emit; every honest node receiving a TRANSACTION body
    from `flooder_hex` gets a burst of forged bad-sig twins attached
    (modeling the flooder's own sends)."""
    return [
        FaultSpec("scp.emit", "equivocate", start=0, count=1_000_000,
                  match={"node": eq_hex}),
        FaultSpec("overlay.transaction.recv", "bad_sig_flood", start=0,
                  count=1_000_000, burst=burst,
                  match={"peer": flooder_hex}),
    ]


def run_smoke(seed: int = 7, target_slots: int = 5, burst: int = 8,
              bad_sig_threshold: int = 16,
              with_faults: bool = True) -> dict:
    """9-node tiered smoke (tier-1 acceptance): 1 equivocator + 1
    bad-sig flooder; honest nodes externalize ≥ `target_slots` slots
    with byte-identical headers, the flooder is dropped by per-peer
    accounting, and the verify service absorbs the flood."""
    clear_verify_cache()
    sim = topologies.tiered(3, 3, configure=_configure(bad_sig_threshold))
    _prep(sim)
    ids = list(sim.nodes.keys())
    equivocator = ids[4]       # org 1, validator 1
    flooder = ids[8]           # org 2, validator 2
    honest = [n for n in ids if n not in (equivocator, flooder)]
    eng = None
    if with_faults:
        eng = ChaosEngine(seed, byzantine_schedule(
            equivocator.hex(), flooder.hex(), burst=burst))
        chaos.install(eng)
    wall0 = _wall.perf_counter()
    try:
        sim.start_all_nodes()
        for app in sim.apps():
            _install_verify_stack(app, sim.clock)
        if not sim.crank_until(lambda: sim.have_all_externalized(2),
                               timeout_virtual_seconds=60.0):
            raise RuntimeError("network never closed ledger 2")
        chaos_t0 = sim.clock.now()
        payer = _TargetedPayer(sim, sim.nodes[flooder])
        target = 2 + target_slots

        def honest_at(seq):
            return all(sim.nodes[n].ledger_manager
                       .get_last_closed_ledger_num() >= seq
                       for n in honest if n not in sim.crashed)

        for seq in range(FIRST_LOADED_LEDGER, target + 1):
            payer.submit_one()
            _crank_byz(sim, lambda s=seq: honest_at(s), timeout=120.0)
            if not honest_at(seq):
                raise RuntimeError(
                    f"liveness lost: honest nodes stalled before {seq}")
        virtual_elapsed = sim.clock.now() - chaos_t0
        hashes = _honest_hashes(sim, honest, target)
        bad_sig_total = sum(
            sim.nodes[n].metrics.new_counter(
                "overlay.peer.drop.bad_sig").count for n in honest)
        flood_dropped = any(
            sim.nodes[n].overlay_manager.drop_reasons.get(
                "bad sig flood", 0) > 0 for n in honest)
        svc = [sim.nodes[n].verify_service.stats() for n in honest]
        # merged honest-node telemetry + SLO verdicts (ISSUE 10): the
        # BYZ artifact carries the run's time dimension, not just the
        # end-state figures
        from ..util.timeseries import scenario_reports
        telemetry, slo = scenario_reports(
            [sim.nodes[n] for n in honest if n not in sim.crashed])
        return {
            "timeseries": telemetry,
            "slo": slo,
            "ok": _honest_agree(hashes),
            "liveness_ok": True,
            "safety_ok": _honest_agree(hashes),
            "slots": target_slots,
            "virtual_seconds": round(virtual_elapsed, 3),
            "virtual_s_per_slot": round(
                virtual_elapsed / target_slots, 3),
            "wall_seconds": round(_wall.perf_counter() - wall0, 1),
            "equivocator": equivocator.hex(),
            "flooder": flooder.hex(),
            "flooder_dropped": flood_dropped,
            "bad_sig_drops": bad_sig_total,
            "verify_submitted": sum(s["submitted"] for s in svc),
            "verify_flushes": sum(s["flushes"] for s in svc),
            "injected": dict(eng.injected) if eng else {},
        }
    finally:
        if with_faults:
            chaos.uninstall()
        sim.stop_all_nodes()


def run_tiered_chaos(seed: int = 11, n_orgs: int = 3,
                     validators_per_org: int = 12, watchers: int = 14,
                     target_slots: int = 4, data_dir: str = None,
                     churn_down_slots: int = 2,
                     bad_sig_threshold: int = 16,
                     burst: int = 6) -> dict:
    """The `slow` 50+-node leg: tiered quorum + watcher tier with the
    per-link latency model, equivocation + bad-sig flood + a
    malformed-XDR window, and CHURN: one validator is killed mid-close
    by a `churn` fault, restarted from persisted state
    `churn_down_slots` slots later, and must catch back up over the
    overlay while the equivocator is still active."""
    if data_dir is None:
        raise ValueError("run_tiered_chaos needs a data_dir for churn")
    clear_verify_cache()
    sim = topologies.tiered(
        n_orgs, validators_per_org, watchers=watchers,
        configure=_configure(bad_sig_threshold), data_dir=data_dir,
        latency=topologies.LinkLatency(seed))
    _prep(sim)
    ids = list(sim.nodes.keys())
    n_validators = n_orgs * validators_per_org
    equivocator = ids[validators_per_org + 1]        # org 1
    flooder = ids[2 * validators_per_org + 2]        # org 2
    victim = ids[1]                                  # org 0, validator 1
    honest = [n for n in ids[:n_validators]
              if n not in (equivocator, flooder)]
    schedule = byzantine_schedule(equivocator.hex(), flooder.hex(),
                                  burst=burst)
    # churn: kill the victim inside its 3rd loaded close, mid-apply —
    # the close transaction rolls back, restart resumes from the
    # previous durable header
    schedule.append(FaultSpec("ledger.close.crash.applyTx", "churn",
                              start=2, count=1,
                              match={"node": victim.hex()}))
    # malformed XDR window: a few of the equivocator's transport sends
    # are truncated/mangled — receivers kill the link through the
    # standard malformed-message drop path
    schedule.append(FaultSpec("overlay.send", "malformed_xdr",
                              start=40, count=3,
                              match={"node": equivocator.hex()}))
    eng = ChaosEngine(seed, schedule)
    chaos.install(eng)
    wall0 = _wall.perf_counter()
    churned: List[bytes] = []
    restart_evidence = None
    try:
        sim.start_all_nodes()
        for app in sim.apps():
            _install_verify_stack(app, sim.clock)
        if not sim.crank_until(lambda: sim.have_all_externalized(2),
                               timeout_virtual_seconds=300.0):
            raise RuntimeError("network never closed ledger 2")
        payer = _TargetedPayer(sim, sim.nodes[flooder])
        target = 2 + target_slots

        def honest_at(seq):
            return all(sim.nodes[n].ledger_manager
                       .get_last_closed_ledger_num() >= seq
                       for n in honest if n not in sim.crashed)

        restart_due_at = None
        for seq in range(FIRST_LOADED_LEDGER, target + 1):
            payer.submit_one()
            _crank_byz(sim, lambda s=seq: honest_at(s), timeout=600.0,
                       churned=churned)
            if not honest_at(seq):
                raise RuntimeError(
                    f"liveness lost: honest nodes stalled before {seq}")
            if churned and restart_due_at is None:
                restart_due_at = seq + churn_down_slots
            if restart_due_at is not None and seq >= restart_due_at \
                    and churned[0] in sim.crashed:
                # catchup-under-chaos: the equivocator is still firing
                # while the restarted node resyncs over the overlay
                restart_evidence = _restart_and_catch_up(
                    sim, churned[0], honest)
        if not churned:
            raise RuntimeError("churn fault never fired")
        if restart_evidence is None and churned[0] in sim.crashed:
            # churn fired on the last slot: restart + catch up now
            restart_evidence = _restart_and_catch_up(
                sim, churned[0], honest)
        # the restarted node rejoins the honest set for the safety
        # verdict: its post-catchup chain must match everyone else's
        survivors = [n for n in honest if n not in sim.crashed]
        check_upto = min(sim.nodes[n].ledger_manager
                         .get_last_closed_ledger_num()
                         for n in survivors + churned
                         if n not in sim.crashed)
        hashes = _honest_hashes(sim, survivors + churned, check_upto)
        flood_dropped = any(
            sim.nodes[n].overlay_manager.drop_reasons.get(
                "bad sig flood", 0) > 0
            for n in honest if n not in sim.crashed)
        return {
            "ok": (_honest_agree(hashes) and
                   bool(restart_evidence and
                        restart_evidence["caught_up"])),
            "nodes": len(ids),
            "validators": n_validators,
            "watchers": watchers,
            "safety_ok": _honest_agree(hashes),
            "liveness_ok": True,
            "churn": restart_evidence,
            "flooder_dropped": flood_dropped,
            "injected": dict(eng.injected),
            "virtual_seconds": round(sim.clock.now(), 1),
            "wall_seconds": round(_wall.perf_counter() - wall0, 1),
        }
    finally:
        chaos.uninstall()
        sim.stop_all_nodes()


def run_byzantine_bench(seed: int = 7) -> dict:
    """``bench.py --byzantine`` artifact: all figures MEASURED in this
    process — slots-to-externalize under equivocation vs a clean run
    of the same topology, verify-service throughput under the bad-sig
    flood (valid+forged submissions over the faulted leg's wall time),
    and churn recovery time on a 9-node tiered network with persisted
    node state."""
    import shutil
    import tempfile

    clean = run_smoke(seed=seed, with_faults=False)
    byz = run_smoke(seed=seed, with_faults=True)
    flood_wall = byz["wall_seconds"]
    verify_tput = round(byz["verify_submitted"] / flood_wall, 1) \
        if flood_wall else None
    clean_tput = round(clean["verify_submitted"] /
                       clean["wall_seconds"], 1) \
        if clean["wall_seconds"] else None
    root = tempfile.mkdtemp(prefix="byz-churn-")
    try:
        churn = run_tiered_chaos(
            seed=seed + 1, n_orgs=3, validators_per_org=3, watchers=0,
            target_slots=6, data_dir=root, churn_down_slots=1)
    except (Exception, SimulatedCrash) as e:      # noqa: BLE001
        churn = {"ok": False, "error": repr(e)}
    finally:
        shutil.rmtree(root, ignore_errors=True)
    ok = bool(byz["ok"] and byz["flooder_dropped"] and
              churn.get("ok"))
    return {
        "metric": "byzantine_convergence",
        "value": 1.0 if ok else 0.0,
        "unit": "pass",
        "vs_baseline": 1.0 if ok else 0.0,
        "slots_to_externalize": {
            "clean_virtual_s_per_slot": clean["virtual_s_per_slot"],
            "byzantine_virtual_s_per_slot": byz["virtual_s_per_slot"],
            "slowdown": round(byz["virtual_s_per_slot"] /
                              clean["virtual_s_per_slot"], 3)
            if clean["virtual_s_per_slot"] else None,
        },
        "verify_under_flood": {
            "submitted": byz["verify_submitted"],
            "flushes": byz["verify_flushes"],
            "verifies_per_s_wall": verify_tput,
            "clean_verifies_per_s_wall": clean_tput,
            "bad_sig_drops": byz["bad_sig_drops"],
            "flooder_dropped": byz["flooder_dropped"],
        },
        "churn": {
            "recovery_virtual_s":
                (churn.get("churn") or {}).get("recovery_virtual_s"),
            "caught_up": (churn.get("churn") or {}).get("caught_up"),
            "safety_ok": churn.get("safety_ok"),
        },
        "smoke": {k: byz[k] for k in
                  ("ok", "safety_ok", "injected", "virtual_seconds")},
        "tiered_churn": churn,
        # the faulted leg's merged time-series summary + SLO section
        # (ISSUE 10 artifact contract, linted by check_artifacts)
        "timeseries": byz.get("timeseries", {"samples": 0}),
        "slo": byz.get("slo", {"overall": "OK", "rules": {}}),
    }
