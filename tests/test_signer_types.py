"""Alternate signer types: HASH_X, PRE_AUTH_TX, ED25519_SIGNED_PAYLOAD.

Reference behaviors: TxEnvelopeTests.cpp "alternate signatures" tier —
a sha256-preimage signer authorizes with the preimage as its
"signature"; a pre-auth-tx signer authorizes that exact tx with no
signatures at all and is consumed on apply (TransactionFrame
removeOneTimeSignerFromAllSourceAccounts); a signed-payload signer
verifies the ed25519 signature over the PAYLOAD (not the tx hash) with
the hint XOR rule (SignatureUtils::getSignedPayloadHint). Negative
cases pin the strict rejections: oversized preimage, wrong payload,
wrong hints.
"""

import hashlib

import pytest

from stellar_core_tpu.xdr.ledger_entries import Signer
from stellar_core_tpu.xdr.transaction import DecoratedSignature
from stellar_core_tpu.xdr.results import TransactionResultCode
from stellar_core_tpu.xdr.types import (Ed25519SignedPayload, SignerKey,
                                        SignerKeyType)

from txtest_utils import (TestAccount, TestLedger, op_payment,
                          op_set_options, signed_payload_hint)

XLM = 10_000_000


@pytest.fixture
def ledger():
    return TestLedger()


@pytest.fixture
def root(ledger):
    return ledger.root_account


def tx_code(frame):
    return frame.result.result.disc


def _replace_sigs(frame, sigs):
    """Swap in a custom signature list (TestAccount.tx always signs
    with the master key; these tests authorize without it)."""
    frame.signatures[:] = list(sigs)
    frame.envelope.value.signatures = frame.signatures


def _mk_account(ledger, root):
    a = TestAccount.fresh(ledger)
    b = TestAccount.fresh(ledger)
    assert root.create(a, 100 * XLM)
    assert root.create(b, 100 * XLM)
    a.sync_seq()
    return a, b


class TestHashX:
    def test_preimage_authorizes(self, ledger, root):
        a, b = _mk_account(ledger, root)
        preimage = b"open sesame, 32 bytes or longer!"
        hx = hashlib.sha256(preimage).digest()
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_HASH_X, hx)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        _replace_sigs(frame, [DecoratedSignature(hint=hx[28:],
                                                 signature=preimage)])
        assert ledger.apply_tx(frame), frame.result
        assert tx_code(frame) == TransactionResultCode.txSUCCESS

    def test_wrong_preimage_rejected(self, ledger, root):
        a, b = _mk_account(ledger, root)
        preimage = b"the real preimage"
        hx = hashlib.sha256(preimage).digest()
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_HASH_X, hx)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        _replace_sigs(frame, [DecoratedSignature(hint=hx[28:],
                                                 signature=b"not it")])
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH

    def test_oversized_preimage_rejected(self, ledger, root):
        """A >64-byte preimage can never match (the wire type caps a
        DecoratedSignature at 64 bytes; the checker enforces it even if
        a hand-built frame smuggles more)."""
        a, b = _mk_account(ledger, root)
        preimage = b"x" * 65
        hx = hashlib.sha256(preimage).digest()
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_HASH_X, hx)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        _replace_sigs(frame, [DecoratedSignature(hint=hx[28:],
                                                 signature=preimage)])
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH

    def test_hint_must_match(self, ledger, root):
        a, b = _mk_account(ledger, root)
        preimage = b"hinted"
        hx = hashlib.sha256(preimage).digest()
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_HASH_X, hx)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        bad_hint = bytes(x ^ 0xFF for x in hx[28:])
        _replace_sigs(frame, [DecoratedSignature(hint=bad_hint,
                                                 signature=preimage)])
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH


class TestPreAuthTx:
    def test_preauth_tx_applies_unsigned_and_is_consumed(self, ledger,
                                                         root):
        a, b = _mk_account(ledger, root)
        # build the FUTURE tx first (its hash is the signer key);
        # seq = current + 2: one SetOptions lands in between
        future = a.tx([op_payment(b.muxed, XLM)], seq=a.seq + 2)
        _replace_sigs(future, [])
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX,
                        future.contents_hash())
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        acct = ledger.account(a.account_id)
        assert len(acct.signers) == 1
        # the unsigned pre-authorized tx applies...
        assert ledger.apply_tx(future), future.result
        # ...and the one-time signer is gone afterwards
        acct = ledger.account(a.account_id)
        assert len(acct.signers) == 0

    def test_different_tx_not_authorized(self, ledger, root):
        a, b = _mk_account(ledger, root)
        future = a.tx([op_payment(b.muxed, XLM)], seq=a.seq + 2)
        _replace_sigs(future, [])
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX,
                        future.contents_hash())
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        other = a.tx([op_payment(b.muxed, 2 * XLM)], seq=a.seq + 1)
        _replace_sigs(other, [])
        assert not ledger.check_valid(other)
        assert tx_code(other) == TransactionResultCode.txBAD_AUTH

    def test_preauth_consumed_on_failed_tx_unmatched_survives(
            self, ledger, root):
        """One-time signers are removed for the MATCHING tx even when
        its operations FAIL (the reference removes them in apply
        regardless of op results) — while a pre-auth signer for a
        DIFFERENT tx survives untouched."""
        a, b = _mk_account(ledger, root)
        # a payment that will fail: overdraw
        future = a.tx([op_payment(b.muxed, 10_000 * XLM)], seq=a.seq + 3)
        _replace_sigs(future, [])
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX,
                        future.contents_hash())
        other_key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX,
                              b"\x42" * 32)     # some other tx's hash
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        assert a.apply([op_set_options(
            signer=Signer(key=other_key, weight=1))])
        assert len(ledger.account(a.account_id).signers) == 2
        assert not ledger.apply_tx(future)      # op fails (underfunded)
        acct = ledger.account(a.account_id)
        # the matching signer is spent; the unrelated one survives
        assert [s.key for s in acct.signers] == [other_key]


class TestSignedPayload:
    def _payload_signer(self, signer_acct, payload):
        sp = Ed25519SignedPayload(
            ed25519=signer_acct.key.public_key().raw, payload=payload)
        return SignerKey(
            SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD, sp)

    def _payload_hint(self, signer_acct, payload):
        return signed_payload_hint(signer_acct.key.public_key().raw,
                                   payload)

    def test_payload_signature_authorizes(self, ledger, root):
        a, b = _mk_account(ledger, root)
        c = TestAccount.fresh(ledger)
        payload = b"this exact payload"
        key = self._payload_signer(c, payload)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        # signature is over the PAYLOAD, not the tx hash
        _replace_sigs(frame, [DecoratedSignature(
            hint=self._payload_hint(c, payload),
            signature=c.key.sign(payload))])
        assert ledger.apply_tx(frame), frame.result

    def test_short_payload_hint_pads(self, ledger, root):
        """Payloads under 4 bytes zero-pad the hint tail (reference
        getSignedPayloadHint)."""
        a, b = _mk_account(ledger, root)
        c = TestAccount.fresh(ledger)
        payload = b"xy"
        key = self._payload_signer(c, payload)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        _replace_sigs(frame, [DecoratedSignature(
            hint=self._payload_hint(c, payload),
            signature=c.key.sign(payload))])
        assert ledger.apply_tx(frame), frame.result

    def test_tx_hash_signature_does_not_match_payload_signer(self, ledger,
                                                             root):
        a, b = _mk_account(ledger, root)
        c = TestAccount.fresh(ledger)
        payload = b"expected payload"
        key = self._payload_signer(c, payload)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        # signing the tx hash (the usual thing) must NOT satisfy a
        # signed-payload signer
        _replace_sigs(frame, [DecoratedSignature(
            hint=self._payload_hint(c, payload),
            signature=c.key.sign(frame.contents_hash()))])
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH

    def test_wrong_signer_key_rejected(self, ledger, root):
        a, b = _mk_account(ledger, root)
        c = TestAccount.fresh(ledger)
        d = TestAccount.fresh(ledger)
        payload = b"payload"
        key = self._payload_signer(c, payload)
        assert a.apply([op_set_options(signer=Signer(key=key, weight=1))])
        frame = a.tx([op_payment(b.muxed, XLM)])
        _replace_sigs(frame, [DecoratedSignature(
            hint=self._payload_hint(c, payload),
            signature=d.key.sign(payload))])      # signed by the wrong key
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH


class TestMixedAlternate:
    def test_hashx_plus_master_reach_threshold(self, ledger, root):
        """Weights accumulate across signer kinds: master (weight 1) +
        hash-x (weight 1) meet medThreshold 2."""
        a, b = _mk_account(ledger, root)
        preimage = b"second factor"
        hx = hashlib.sha256(preimage).digest()
        key = SignerKey(SignerKeyType.SIGNER_KEY_TYPE_HASH_X, hx)
        assert a.apply([op_set_options(
            signer=Signer(key=key, weight=1),
            masterWeight=1, medThreshold=2)])
        # master alone: below threshold
        frame = a.tx([op_payment(b.muxed, XLM)])
        assert not ledger.apply_tx(frame)
        # master + preimage: passes
        frame = a.tx([op_payment(b.muxed, XLM)])
        frame.signatures.append(DecoratedSignature(hint=hx[28:],
                                                   signature=preimage))
        frame.envelope.value.signatures = frame.signatures
        assert ledger.apply_tx(frame), frame.result

    def test_unused_alternate_signature_is_bad_auth_extra(self, ledger,
                                                          root):
        """A preimage signature matching NO signer on the account trips
        the all-signatures-used check (txBAD_AUTH_EXTRA)."""
        a, b = _mk_account(ledger, root)
        preimage = b"nobody registered this"
        hx = hashlib.sha256(preimage).digest()
        frame = a.tx([op_payment(b.muxed, XLM)])
        frame.signatures.append(DecoratedSignature(hint=hx[28:],
                                                   signature=preimage))
        frame.envelope.value.signatures = frame.signatures
        assert not ledger.check_valid(frame)
        assert tx_code(frame) == TransactionResultCode.txBAD_AUTH_EXTRA
