"""Transaction test helpers (reference: src/test/TxTests.{h,cpp} and
TestAccount.{h,cpp} — op builders + a TestAccount that tracks seqnums and
signs envelopes against an in-memory ledger root)."""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.ledger.ledger_txn import (InMemoryLedgerTxnRoot,
                                                LedgerTxn)
from stellar_core_tpu.tx import make_frame
from stellar_core_tpu.tx import tx_utils
from stellar_core_tpu.xdr.ledger import LedgerHeader, StellarValue
from stellar_core_tpu.xdr.ledger_entries import (
    AlphaNum4, Asset, AssetType, LedgerKey, Price, Signer,
)
from stellar_core_tpu.xdr.transaction import (
    ChangeTrustAsset, ChangeTrustOp, CreateAccountOp, DecoratedSignature,
    ManageBuyOfferOp, ManageDataOp, ManageSellOfferOp, Memo, MemoType,
    MuxedAccount, Operation, OperationType, PathPaymentStrictReceiveOp,
    PathPaymentStrictSendOp, PaymentOp, Preconditions, PreconditionType,
    SetOptionsOp, Transaction, TransactionEnvelope, TransactionV1Envelope,
    _OperationBody, _TxExt, BumpSequenceOp, AllowTrustOp,
    SetTrustLineFlagsOp, CreatePassiveSellOfferOp,
    LiquidityPoolDepositOp, LiquidityPoolWithdrawOp,
)
from stellar_core_tpu.xdr.types import (AccountID, EnvelopeType, PublicKey,
                                        SignerKey, SignerKeyType)

TEST_NETWORK_ID = hashlib.sha256(b"tpu test network").digest()

GENESIS_BALANCE = 1_000_000_000 * 10_000_000  # 1B XLM in stroops


def make_header(ledger_version: int = 21, ledger_seq: int = 2,
                base_fee: int = 100,
                base_reserve: int = 5_000_000) -> LedgerHeader:
    return LedgerHeader(
        ledgerVersion=ledger_version, ledgerSeq=ledger_seq,
        baseFee=base_fee, baseReserve=base_reserve,
        totalCoins=GENESIS_BALANCE, maxTxSetSize=100,
        scpValue=StellarValue(closeTime=1_700_000_000))


class TestLedger:
    """In-memory root + root account, one object per test."""

    def __init__(self, **header_kwargs):
        self.root = InMemoryLedgerTxnRoot(make_header(**header_kwargs))
        self.root_account = TestAccount(self, SecretKey.from_seed(
            hashlib.sha256(b"root").digest()))
        with LedgerTxn(self.root) as ltx:
            le = tx_utils.make_account_ledger_entry(
                self.root_account.account_id, GENESIS_BALANCE,
                tx_utils.starting_sequence_number(1))
            ltx.create(le)
            ltx.commit()
        self.root_account.sync_seq()

    def header(self) -> LedgerHeader:
        return self.root.get_header()

    def advance_ledger(self, n: int = 1) -> None:
        """Bump the header ledgerSeq (reference analogue: closing n empty
        ledgers); needed e.g. to merge an account created this ledger."""
        self.root._header.ledgerSeq += n

    # ------------------------------------------------------------ lifecycle --
    def apply_tx(self, frame, base_fee: Optional[int] = None) -> bool:
        """fee + apply against the root (simplified closeLedger for
        op-level tests)."""
        with LedgerTxn(self.root) as ltx:
            bf = base_fee if base_fee is not None else self.header().baseFee
            frame.process_fee_seq_num(ltx, bf)
            # pass base_fee to apply exactly like closeLedger does
            # (ledger_manager._apply_transactions) so result.feeCharged
            # matches the balance actually charged
            ok = frame.apply(ltx, bf)
            ltx.commit()
        return ok

    def check_valid(self, frame) -> bool:
        with LedgerTxn(self.root) as ltx:
            return frame.check_valid(ltx)

    def balance(self, account_id: PublicKey) -> int:
        with LedgerTxn(self.root) as ltx:
            le = ltx.load_without_record(LedgerKey.account(account_id))
            return le.data.value.balance if le else -1

    def account(self, account_id: PublicKey):
        with LedgerTxn(self.root) as ltx:
            le = ltx.load_without_record(LedgerKey.account(account_id))
            return le.data.value if le else None

    def trustline(self, account_id: PublicKey, asset: Asset):
        with LedgerTxn(self.root) as ltx:
            from stellar_core_tpu.xdr.ledger_entries import TrustLineAsset
            le = ltx.load_without_record(LedgerKey.trust_line(
                account_id, TrustLineAsset.from_asset(asset)))
            return le.data.value if le else None


class TestAccount:
    def __init__(self, ledger: TestLedger, key: SecretKey):
        self.ledger = ledger
        self.key = key
        self.seq = 0

    _counter = [0]

    @classmethod
    def fresh(cls, ledger: TestLedger) -> "TestAccount":
        cls._counter[0] += 1
        return cls(ledger, SecretKey.pseudo_random_for_testing(
            cls._counter[0]))

    @property
    def account_id(self) -> PublicKey:
        return PublicKey.ed25519(self.key.public_key().raw)

    @property
    def muxed(self) -> MuxedAccount:
        return MuxedAccount.from_ed25519(self.key.public_key().raw)

    def sync_seq(self) -> None:
        acc = self.ledger.account(self.account_id)
        if acc is not None:
            self.seq = acc.seqNum

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq

    # --------------------------------------------------------------- txs --
    def tx(self, ops: Sequence[Operation], fee: Optional[int] = None,
           seq: Optional[int] = None, cond: Optional[Preconditions] = None,
           extra_signers: Sequence[SecretKey] = ()) -> "object":
        if seq is None:
            seq = self.next_seq()
        if fee is None:
            fee = 100 * max(1, len(ops))
        t = Transaction(
            sourceAccount=self.muxed, fee=fee, seqNum=seq,
            cond=cond or Preconditions(PreconditionType.PRECOND_NONE),
            memo=Memo(MemoType.MEMO_NONE), operations=list(ops),
            ext=_TxExt(0))
        env = TransactionEnvelope(
            EnvelopeType.ENVELOPE_TYPE_TX,
            TransactionV1Envelope(tx=t, signatures=[]))
        frame = make_frame(env, TEST_NETWORK_ID)
        for sk in (self.key, *extra_signers):
            sign_frame(frame, sk)
        return frame

    def apply(self, ops: Sequence[Operation], **kw) -> bool:
        frame = self.tx(ops, **kw)
        return self.ledger.apply_tx(frame)

    # ------------------------------------------------------- op shortcuts --
    def create(self, dest: "TestAccount", balance: int) -> bool:
        return self.apply([op_create_account(dest.account_id, balance)])

    def pay(self, dest: "TestAccount", amount: int,
            asset: Optional[Asset] = None) -> bool:
        return self.apply([op_payment(dest.muxed, amount, asset)])


from stellar_core_tpu.tx.signature_checker import signed_payload_hint  # noqa: E402,F401  (re-export: tests build hints with the production rule)


def sign_frame(frame, sk: SecretKey) -> None:
    sig = sk.sign(frame.contents_hash())
    frame.signatures.append(DecoratedSignature(
        hint=sk.public_key().hint(), signature=sig))
    frame.envelope.value.signatures = frame.signatures


# ------------------------------------------------------------- op builders --

def _op(op_type: OperationType, body, source=None) -> Operation:
    return Operation(sourceAccount=source,
                     body=_OperationBody(op_type, body))


def op_create_account(dest: PublicKey, balance: int,
                      source=None) -> Operation:
    return _op(OperationType.CREATE_ACCOUNT,
               CreateAccountOp(destination=dest, startingBalance=balance),
               source)


def native() -> Asset:
    return Asset(AssetType.ASSET_TYPE_NATIVE)


def make_asset(code: bytes, issuer: PublicKey) -> Asset:
    assert len(code) <= 4
    return Asset(AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
                 AlphaNum4(assetCode=code.ljust(4, b"\x00"), issuer=issuer))


def op_payment(dest: MuxedAccount, amount: int,
               asset: Optional[Asset] = None, source=None) -> Operation:
    return _op(OperationType.PAYMENT,
               PaymentOp(destination=dest, asset=asset or native(),
                         amount=amount), source)


def op_change_trust(asset: Asset, limit: int, source=None) -> Operation:
    line = ChangeTrustAsset(asset.disc, asset.value) \
        if asset.disc != AssetType.ASSET_TYPE_NATIVE \
        else ChangeTrustAsset(AssetType.ASSET_TYPE_NATIVE)
    return _op(OperationType.CHANGE_TRUST,
               ChangeTrustOp(line=line, limit=limit), source)


def op_set_options(source=None, **kw) -> Operation:
    return _op(OperationType.SET_OPTIONS, SetOptionsOp(**kw), source)


def op_manage_data(name: bytes, value: Optional[bytes],
                   source=None) -> Operation:
    return _op(OperationType.MANAGE_DATA,
               ManageDataOp(dataName=name, dataValue=value), source)


def op_bump_sequence(bump_to: int, source=None) -> Operation:
    return _op(OperationType.BUMP_SEQUENCE, BumpSequenceOp(bumpTo=bump_to),
               source)


def op_account_merge(dest: MuxedAccount, source=None) -> Operation:
    return _op(OperationType.ACCOUNT_MERGE, dest, source)


def op_allow_trust(trustor: PublicKey, code: bytes, authorize: int,
                   source=None) -> Operation:
    from stellar_core_tpu.xdr.ledger_entries import AssetCode
    ac = AssetCode(AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
                   code.ljust(4, b"\x00"))
    return _op(OperationType.ALLOW_TRUST,
               AllowTrustOp(trustor=trustor, asset=ac, authorize=authorize),
               source)


def op_set_trustline_flags(trustor: PublicKey, asset: Asset,
                           set_flags: int = 0, clear_flags: int = 0,
                           source=None) -> Operation:
    return _op(OperationType.SET_TRUST_LINE_FLAGS,
               SetTrustLineFlagsOp(trustor=trustor, asset=asset,
                                   setFlags=set_flags,
                                   clearFlags=clear_flags), source)


def op_manage_sell_offer(selling: Asset, buying: Asset, amount: int,
                         price: Price, offer_id: int = 0,
                         source=None) -> Operation:
    return _op(OperationType.MANAGE_SELL_OFFER,
               ManageSellOfferOp(selling=selling, buying=buying,
                                 amount=amount, price=price,
                                 offerID=offer_id), source)


def op_manage_buy_offer(selling: Asset, buying: Asset, buy_amount: int,
                        price: Price, offer_id: int = 0,
                        source=None) -> Operation:
    return _op(OperationType.MANAGE_BUY_OFFER,
               ManageBuyOfferOp(selling=selling, buying=buying,
                                buyAmount=buy_amount, price=price,
                                offerID=offer_id), source)


def op_path_payment_strict_receive(send_asset: Asset, send_max: int,
                                   dest: MuxedAccount, dest_asset: Asset,
                                   dest_amount: int,
                                   path: Sequence[Asset] = (),
                                   source=None) -> Operation:
    return _op(OperationType.PATH_PAYMENT_STRICT_RECEIVE,
               PathPaymentStrictReceiveOp(
                   sendAsset=send_asset, sendMax=send_max,
                   destination=dest, destAsset=dest_asset,
                   destAmount=dest_amount, path=list(path)), source)


def op_path_payment_strict_send(send_asset: Asset, send_amount: int,
                                dest: MuxedAccount, dest_asset: Asset,
                                dest_min: int, path: Sequence[Asset] = (),
                                source=None) -> Operation:
    return _op(OperationType.PATH_PAYMENT_STRICT_SEND,
               PathPaymentStrictSendOp(
                   sendAsset=send_asset, sendAmount=send_amount,
                   destination=dest, destAsset=dest_asset,
                   destMin=dest_min, path=list(path)), source)


# ---------------------------------------------------------------------------
# Protocol-version sweep helpers (reference: TEST_CASE_VERSIONS +
# for_versions_to/from/all, test/test.h:41-60): run a body once per ledger
# protocol version, each against a fresh ledger pinned at that version.
# ---------------------------------------------------------------------------

# v1 tx envelopes are txNOT_SUPPORTED before protocol 13 (the reference
# sweeps lower via v0 envelopes; our builders emit v1)
MIN_TESTED_PROTOCOL = 13
MAX_TESTED_PROTOCOL = 21


def for_versions(from_v: int, to_v: int, fn, **header_kwargs) -> None:
    """fn(ledger, version) for every version in [from_v, to_v]."""
    for v in range(from_v, to_v + 1):
        fn(TestLedger(ledger_version=v, **header_kwargs), v)


def for_versions_to(v: int, fn, **kw) -> None:
    for_versions(MIN_TESTED_PROTOCOL, v, fn, **kw)


def for_versions_from(v: int, fn, **kw) -> None:
    for_versions(v, MAX_TESTED_PROTOCOL, fn, **kw)


def for_all_versions(fn, **kw) -> None:
    for_versions(MIN_TESTED_PROTOCOL, MAX_TESTED_PROTOCOL, fn, **kw)
