"""Wide-area link-fault family (ISSUE 20): the time-windowed
partition/flap/slow_link chaos kinds, their loopback seams (the
`overlay.link` sever + re-dial refusal, the `overlay.send` traffic
shape with FIFO/MAC safety, heal by window elapse), and the
jitter-decorrelated dial-retry tick that re-knits a healed mesh
without a thundering herd."""

import random

import pytest

from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import (ChaosEngine, FaultSpec, Shape,
                                         TIMED_KINDS)
from stellar_core_tpu.xdr.overlay import MessageType, StellarMessage

import test_overlay as ovl

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leftover_engine():
    chaos.uninstall()
    yield
    chaos.uninstall()


# ------------------------------------------------- engine: timed kinds --

def test_timed_kinds_registry():
    assert TIMED_KINDS == {"partition", "flap", "slow_link"}
    assert TIMED_KINDS <= set(chaos.KINDS)


def test_partition_window_opens_at_first_matched_hit():
    eng = ChaosEngine(1, [FaultSpec("overlay.link", "partition",
                                    window_s=5.0, match={"peer": "aa"})])
    chaos.install(eng)
    # unmatched traffic neither fires nor opens the window
    assert chaos.point("overlay.link", None, now=100.0, peer="bb") is None
    # the window anchors at the FIRST matched hit (t=107), not t=100
    assert chaos.point("overlay.link", None, now=107.0,
                       peer="aa") is chaos.DROP
    # a condition, not an event: every matched hit inside fires
    assert chaos.point("overlay.link", None, now=111.9,
                       peer="aa") is chaos.DROP
    assert eng.injected["chaos.injected.partition"] == 2
    # window elapses -> the link heals, permanently
    assert chaos.point("overlay.link", None, now=112.0, peer="aa") is None
    assert chaos.point("overlay.link", None, now=500.0, peer="aa") is None


def test_partition_window_zero_holds_until_cleared():
    chaos.install(ChaosEngine(1, [FaultSpec("p", "partition",
                                            window_s=0.0)]))
    assert chaos.point("p", None, now=0.0) is chaos.DROP
    assert chaos.point("p", None, now=1e6) is chaos.DROP
    chaos.uninstall()              # only an explicit clear heals
    assert chaos.point("p", None, now=2e6) is None


def test_flap_duty_cycle_phases():
    # period 4s, duty 0.5: DOWN for [0,2), UP for [2,4) of each cycle
    chaos.install(ChaosEngine(1, [FaultSpec(
        "p", "flap", window_s=20.0, period_s=4.0, duty=0.5)]))
    assert chaos.point("p", None, now=50.0) is chaos.DROP   # t0: down
    assert chaos.point("p", None, now=51.9) is chaos.DROP
    assert chaos.point("p", None, now=52.0) is None         # up phase
    assert chaos.point("p", None, now=53.9) is None
    assert chaos.point("p", None, now=54.5) is chaos.DROP   # next cycle
    assert chaos.point("p", None, now=57.0) is None
    assert chaos.point("p", None, now=70.1) is None         # window done


def test_slow_link_returns_shape_then_heals():
    chaos.install(ChaosEngine(1, [FaultSpec(
        "p", "slow_link", window_s=10.0, delay_ms=40.0, bps=125_000.0)]))
    out = chaos.point("p", b"x" * 100, now=7.0)
    assert isinstance(out, Shape)
    assert out.delay_s == pytest.approx(0.040)
    assert out.bytes_per_s == pytest.approx(125_000.0)
    # past the window the payload passes through unshaped
    assert chaos.point("p", b"y", now=17.1) == b"y"


def test_timed_spec_json_roundtrip():
    specs = [FaultSpec("l", "partition", window_s=6.0,
                       match={"peer": "aa"}),
             FaultSpec("l", "flap", window_s=9.0, period_s=3.0,
                       duty=0.4),
             FaultSpec("s", "slow_link", window_s=0.0, delay_ms=25.0,
                       bps=250_000.0)]
    docs = [s.to_json() for s in specs]
    back = chaos.schedule_from_json(docs)
    assert [s.to_json() for s in back] == docs
    assert docs[0]["window_s"] == 6.0
    assert docs[1]["period_s"] == 3.0 and docs[1]["duty"] == 0.4
    assert docs[2]["delay_ms"] == 25.0 and docs[2]["bps"] == 250_000.0


# --------------------------------------------------- loopback seams --

def _link_spec(kind, src_app, dst_app, **extra):
    return FaultSpec("overlay.link", kind,
                     match={"node": src_app.config.node_id().hex(),
                            "peer": dst_app.config.node_id().hex()},
                     **extra)


def _probe(tag):
    return StellarMessage(MessageType.GET_SCP_QUORUMSET,
                          bytes([tag]) * 32)


def test_loopback_partition_severs_refuses_redial_then_heals():
    """The `overlay.link` seam end to end: the first send inside the
    window kills the link, a re-dial during the window is refused at
    admission (`peer_authenticated`), and after the window elapses the
    redial re-knits the mesh."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        om0 = apps[0].overlay_manager
        assert conn.initiator in om0.get_authenticated_peers()
        chaos.install(ChaosEngine(20, [
            _link_spec("partition", apps[0], apps[1], window_s=5.0),
            _link_spec("partition", apps[1], apps[0], window_s=5.0)]))
        conn.initiator.send_message(_probe(0x07))
        assert conn.initiator.state.name == "CLOSING"
        assert conn.initiator not in om0.get_authenticated_peers()
        # a real socket sever kills BOTH ends; the loopback partner
        # doesn't learn on its own — model the remote's FIN explicitly
        conn.acceptor.drop("remote closed")
        # window still open: admission refuses the re-dial
        conn2 = LoopbackPeerConnection(apps[0], apps[1])
        conn2.crank()
        assert conn2.initiator not in om0.get_authenticated_peers()
        # heal by window elapse (virtual time), then redial succeeds
        clock._virtual_now += 10.0
        conn3 = LoopbackPeerConnection(apps[0], apps[1])
        conn3.crank()
        assert conn3.initiator in om0.get_authenticated_peers()
        assert conn3.initiator.state.name == "GOT_AUTH"
        assert conn3.acceptor.state.name == "GOT_AUTH"
        # and traffic flows over the re-knit link
        before = conn3.acceptor.messages_read
        conn3.initiator.send_message(_probe(0x08))
        conn3.crank()
        assert conn3.acceptor.messages_read == before + 1
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


def test_loopback_flap_cycles_down_and_up():
    """Flap = periodic partition: the down phase severs, the up phase
    lets a redial land and traffic flow, the next down phase severs
    again — degrade, never detach."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        om0 = apps[0].overlay_manager
        chaos.install(ChaosEngine(21, [
            _link_spec("flap", apps[0], apps[1], window_s=0.0,
                       period_s=4.0, duty=0.5)]))
        conn.initiator.send_message(_probe(0x11))   # t0 -> down phase
        assert conn.initiator.state.name == "CLOSING"
        conn.acceptor.drop("remote closed")         # far end's FIN
        # up phase: re-dial lands and traffic flows
        clock._virtual_now += 2.0
        conn2 = LoopbackPeerConnection(apps[0], apps[1])
        conn2.crank()
        assert conn2.initiator in om0.get_authenticated_peers()
        before = conn2.acceptor.messages_read
        conn2.initiator.send_message(_probe(0x12))
        conn2.crank()
        assert conn2.acceptor.messages_read == before + 1
        # next cycle's down phase severs again
        clock._virtual_now += 2.0
        conn2.initiator.send_message(_probe(0x13))
        assert conn2.initiator.state.name == "CLOSING"
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


def test_loopback_slow_link_shapes_fifo_without_mac_trips():
    """slow_link at the `overlay.send` seam: shaped frames ride the
    virtual clock (nothing arrives instantly), arrive complete and in
    order, and the link stays authenticated — the FIFO clamp means the
    HMAC sequence never sees an overtake."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        node0 = apps[0].config.node_id().hex()
        chaos.install(ChaosEngine(22, [FaultSpec(
            "overlay.send", "slow_link", window_s=0.0, delay_ms=50.0,
            bps=100_000.0, match={"node": node0})]))
        before = conn.acceptor.messages_read
        for i in range(4):
            conn.initiator.send_message(_probe(i))
        conn.crank()
        assert conn.acceptor.messages_read == before   # still in flight
        for _ in range(64):
            clock.crank(True)
            conn.crank()
            if conn.acceptor.messages_read >= before + 4:
                break
        assert conn.acceptor.messages_read == before + 4
        assert conn.initiator.state.name == "GOT_AUTH"
        assert conn.acceptor.state.name == "GOT_AUTH"
        # the healed link still works: an unshaped send lands too
        chaos.uninstall()
        conn.initiator.send_message(_probe(0x09))
        for _ in range(16):
            clock.crank(True)
            conn.crank()
            if conn.acceptor.messages_read >= before + 5:
                break
        assert conn.acceptor.messages_read == before + 5
        assert conn.acceptor.state.name == "GOT_AUTH"
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


# ------------------------------------------- jittered dial-retry tick --

def test_tick_interval_jitter_bounds_and_determinism():
    """The KNOWN_PEERS dial-retry re-arm draws from [3.75, 6.25) s,
    seeded per node (config.jitter_seed()) so each node's sequence is
    reproducible while different nodes stay decorrelated — no redial
    herd against a listener healing from the same window."""
    clock, apps = ovl.make_apps(2)
    try:
        om0 = apps[0].overlay_manager
        om1 = apps[1].overlay_manager
        vals0 = [om0.tick_interval() for _ in range(100)]
        assert all(3.75 <= v < 6.25 for v in vals0)
        assert len({round(v, 9) for v in vals0}) > 1    # actually jitters
        # decorrelated across nodes (different jitter seeds)
        vals1 = [om1.tick_interval() for _ in range(100)]
        assert vals0 != vals1
        # seeded determinism: a fresh stream reproduces exactly
        om0._tick_rng = None
        rng = random.Random(apps[0].config.jitter_seed() ^ 0x7E9C_11A3)
        got = [om0.tick_interval() for _ in range(5)]
        want = [5.0 * (0.75 + 0.5 * rng.random()) for _ in range(5)]
        assert got == want
    finally:
        ovl.shutdown(apps)
