"""Bucket layer tests (reference coverage model: BucketTests.cpp,
BucketListTests.cpp, BucketManagerTests.cpp)."""

import hashlib

import pytest

from stellar_core_tpu.bucket import (Bucket, BucketList, BucketManager,
                                     EMPTY_HASH, NUM_LEVELS, merge_buckets)
from stellar_core_tpu.bucket.bucket_list import level_half, level_should_spill
from stellar_core_tpu.xdr.ledger import BucketEntryType
from stellar_core_tpu.xdr.ledger_entries import (
    AccountEntry, LedgerEntry, LedgerEntryType, LedgerKey, _LedgerEntryData)
from stellar_core_tpu.xdr.types import PublicKey, PublicKeyType


def _acc_id(n):
    return PublicKey(PublicKeyType.PUBLIC_KEY_TYPE_ED25519,
                     n.to_bytes(4, "big") * 8)


def _entry(n, balance=100):
    ae = AccountEntry(accountID=_acc_id(n), balance=balance,
                      thresholds=b"\x01\x00\x00\x00")
    return LedgerEntry(lastModifiedLedgerSeq=1,
                       data=_LedgerEntryData(LedgerEntryType.ACCOUNT, ae))


def _key(n):
    return LedgerKey.account(_acc_id(n))


def test_fresh_bucket_sorted_and_hashed():
    b = Bucket.fresh(21, [_entry(3), _entry(1)], [_entry(2)], [_key(4)])
    keys = [e.disc for e in b.entries()]
    assert len(keys) == 4
    assert b.hash != EMPTY_HASH
    # same content, different construction order -> same hash
    b2 = Bucket.fresh(21, [_entry(1), _entry(3)], [_entry(2)], [_key(4)])
    assert b2.hash == b.hash


def test_bucket_file_roundtrip(tmp_path):
    b = Bucket.fresh(21, [_entry(1)], [], [])
    p = str(tmp_path / "b.xdr")
    b.write_to(p)
    b2 = Bucket.from_file(p)
    assert b2.hash == b.hash
    assert len(b2.entries()) == len(b.entries())


def test_merge_lifecycle_rules():
    T = BucketEntryType
    old = Bucket.fresh(21, [_entry(1)], [_entry(2)], [_key(3)])
    # new: 1 updated (LIVE), 2 dead, 3 re-created (INIT)
    new = Bucket.fresh(21, [_entry(3)], [_entry(1, balance=7)], [_key(2)])
    m = merge_buckets(old, new)
    by_key = {}
    for be in m.entries():
        if be.disc == T.DEADENTRY:
            by_key[be.value.value.accountID.value] = ("dead", None)
        else:
            by_key[be.value.data.value.accountID.value] = (
                be.disc, be.value.data.value.balance)
    # old INIT + new LIVE -> INIT with new data
    assert by_key[_acc_id(1).value] == (T.INITENTRY, 7)
    # old LIVE + new DEAD -> DEAD
    assert by_key[_acc_id(2).value][0] == "dead"
    # old DEAD + new INIT -> LIVE
    assert by_key[_acc_id(3).value][0] == T.LIVEENTRY


def test_merge_init_dead_annihilates():
    old = Bucket.fresh(21, [_entry(1)], [], [])
    new = Bucket.fresh(21, [], [], [_key(1)])
    m = merge_buckets(old, new)
    assert m.is_empty()


def test_merge_drop_dead_at_bottom():
    old = Bucket.fresh(21, [], [_entry(1)], [])
    new = Bucket.fresh(21, [], [], [_key(1)])
    m = merge_buckets(old, new, keep_dead=False)
    assert m.is_empty()


def test_spill_cadence():
    assert level_half(0) == 2
    assert level_should_spill(2, 0) and not level_should_spill(3, 0)
    assert level_should_spill(8, 1) and not level_should_spill(4, 1)


def test_bucket_list_accumulates_and_hash_changes():
    bl = BucketList()
    h0 = bl.get_hash()
    for seq in range(1, 20):
        bl.add_batch(seq, 21, [_entry(seq)], [], [])
    assert bl.get_hash() != h0
    # an entry may appear at several levels (snap stays while its merge
    # also lands in the next level's curr) — count >= inserts
    assert bl.total_entry_count() >= 19
    # every entry findable through the list
    for n in range(1, 20):
        be = bl.get_entry(_key(n))
        assert be is not None and be.disc != BucketEntryType.DEADENTRY


def test_bucket_list_deterministic():
    def build():
        bl = BucketList()
        for seq in range(1, 50):
            bl.add_batch(seq, 21, [_entry(seq)],
                         [_entry(seq - 1, balance=seq)] if seq > 1 else [],
                         [_key(seq - 2)] if seq > 2 else [])
        return bl.get_hash()
    assert build() == build()


def test_bucket_list_erase_visible():
    bl = BucketList()
    bl.add_batch(1, 21, [_entry(1)], [], [])
    bl.add_batch(2, 21, [], [], [_key(1)])
    be = bl.get_entry(_key(1))
    # either annihilated entirely or a tombstone — never a live entry
    assert be is None or be.disc == BucketEntryType.DEADENTRY


def test_manager_dedup_and_gc(tmp_path):
    mgr = BucketManager(str(tmp_path / "buckets"))
    b1 = Bucket.fresh(21, [_entry(1)], [], [])
    b2 = Bucket.fresh(21, [_entry(1)], [], [])
    a1 = mgr.adopt_bucket(b1)
    a2 = mgr.adopt_bucket(b2)
    assert a1 is a2
    assert mgr.get_bucket_by_hash(b1.hash).hash == b1.hash
    # unreferenced (not in the list) -> GC drops it
    dropped = mgr.forget_unreferenced_buckets()
    assert dropped == 1
    mgr.shutdown()


def test_manager_ledger_flow_and_restart(tmp_path):
    d = str(tmp_path / "buckets")
    mgr = BucketManager(d)
    for seq in range(1, 10):
        mgr.add_batch(seq, 21, [_entry(seq)], [], [])
    h = mgr.snapshot_ledger_hash()
    mgr.shutdown()
    # restart: manager reloads from dir; hashes of reloaded buckets match
    mgr2 = BucketManager(d)
    for ref in (mgr.referenced_hashes()):
        assert mgr2.get_bucket_by_hash(ref) is not None
    mgr2.shutdown()


def test_background_merges_match_sync():
    from concurrent.futures import ThreadPoolExecutor
    ex = ThreadPoolExecutor(2)
    bl_sync = BucketList()
    bl_async = BucketList(ex)
    for seq in range(1, 65):
        batch = ([_entry(seq)], [_entry(seq - 1, balance=seq)]
                 if seq > 1 else [], [])
        bl_sync.add_batch(seq, 21, *batch)
        bl_async.add_batch(seq, 21, *batch)
    assert bl_sync.get_hash() == bl_async.get_hash()
    ex.shutdown()


# ---------------------------------------------------------------------------
# BucketIndex (reference: BucketIndexImpl — bloom + individual/range index,
# bucket/readme.md:55-90)
# ---------------------------------------------------------------------------

def _mk_live_entries(n, seed=0):
    from stellar_core_tpu.xdr.ledger import BucketEntry
    return [BucketEntry(BucketEntryType.LIVEENTRY,
                        _entry(1000 * seed + i, balance=100 + i))
            for i in range(n)]


def test_bucket_index_individual_and_bloom():
    from stellar_core_tpu.bucket.bucket_index import BucketIndex
    from stellar_core_tpu.xdr.ledger_entries import (LedgerKey,
                                                     ledger_entry_key)
    b = Bucket.from_entries(_mk_live_entries(50))
    idx = b._build_index()
    assert idx.kind == BucketIndex.INDIVIDUAL
    assert idx.entry_count == 50
    # every key resolves through the index; misses hit the bloom gate
    for be in b.entries():
        key = ledger_entry_key(be.value)
        got = b.get(key)
        assert got is not None and got.value.to_bytes() == \
            be.value.to_bytes()
    missing = _key(999999)
    assert b.get(missing) is None
    assert idx.bloom_misses > 0


def test_bucket_index_range_pages_equivalent():
    from stellar_core_tpu.bucket.bucket_index import BucketIndex
    from stellar_core_tpu.xdr.ledger_entries import (LedgerKey,
                                                     ledger_entry_key)
    b = Bucket.from_entries(_mk_live_entries(200, seed=2))
    # force the range style with a tiny cutoff and page size
    idx = BucketIndex.build(b.raw_bytes(), cutoff=1, page_size=512)
    assert idx.kind == BucketIndex.RANGE
    assert idx.entry_count == 200
    assert len(idx._page_keys) > 2
    for be in b.entries():
        key = ledger_entry_key(be.value)
        got = idx.lookup(b.raw_bytes(), key)
        assert got is not None and got.value.to_bytes() == \
            be.value.to_bytes()
    assert idx.lookup(b.raw_bytes(), _key(424242)) is None


def test_bucket_index_dead_entries():
    dead_key = _key(700007)
    from stellar_core_tpu.xdr.ledger import BucketEntry
    live = _mk_live_entries(5, seed=3)
    b = Bucket.from_entries(live +
                            [BucketEntry(BucketEntryType.DEADENTRY,
                                         dead_key)])
    got = b.get(dead_key)
    assert got is not None
    assert got.disc == BucketEntryType.DEADENTRY


# ----------------------------------------------------- shadow-era merges ---
# reference: Bucket.cpp maybePut (:446-523) + calculateMergeProtocolVersion
# (:566-605); test shapes mirror bucket/test/BucketTests.cpp's shadow cases

def test_pre11_fresh_has_no_init_or_meta():
    """Before protocol 11 there is no INITENTRY and no METAENTRY
    (reference: Bucket::fresh useInit + checkProtocolLegality)."""
    b = Bucket.fresh(10, [_entry(1)], [_entry(2)], [_key(3)])
    kinds = {e.disc for e in b.entries()}
    assert BucketEntryType.INITENTRY not in kinds
    assert BucketEntryType.METAENTRY not in kinds
    assert b.meta_protocol == 0
    b11 = Bucket.fresh(11, [_entry(1)], [], [])
    assert b11.meta_protocol == 11
    assert any(e.disc == BucketEntryType.INITENTRY for e in b11.entries())


def test_pre11_shadow_elides_everything():
    """Protocol <11 merges drop ANY shadowed record — live or dead
    (reference: maybePut with keepShadowedLifecycleEntries=false)."""
    from stellar_core_tpu.bucket.bucket import merge_buckets
    old = Bucket.fresh(10, [], [_entry(1)], [_key(2)])
    new = Bucket.fresh(10, [], [_entry(3)], [])
    shadow = Bucket.fresh(10, [], [_entry(1, balance=9)], [_key(2)])
    m = merge_buckets(old, new, shadows=[shadow])
    keys = set()
    for e in m.entries():
        v = e.value if e.disc == BucketEntryType.DEADENTRY else None
        acc = (v or e.value.data).value
        keys.add((acc.accountID.value if hasattr(acc, "accountID")
                  else acc.value.accountID.value))
    # entry 1 (live, shadowed) and key 2 (dead, shadowed) are gone;
    # entry 3 (unshadowed) survives
    from test_bucket import _acc_id
    assert _acc_id(3).value in keys
    assert _acc_id(1).value not in keys
    assert _acc_id(2).value not in keys


def test_protocol11_shadow_keeps_lifecycle_entries():
    """At protocol 11, shadows elide LIVE records but must keep INIT and
    DEAD so INIT+DEAD annihilation stays sound (reference: maybePut's
    keepShadowedLifecycleEntries=true branch)."""
    from stellar_core_tpu.bucket.bucket import merge_buckets
    old = Bucket.fresh(11, [_entry(1)], [_entry(2)], [_key(3)])
    new = Bucket.fresh(11, [], [], [])
    shadow = Bucket.fresh(11, [_entry(1, balance=5)],
                          [_entry(2, balance=5)], [_key(3)])
    m = merge_buckets(old, new, shadows=[shadow])
    by_kind = {}
    for e in m.entries():
        by_kind.setdefault(e.disc, set()).add(
            e.value.to_bytes() if e.disc == BucketEntryType.DEADENTRY
            else e.value.data.value.accountID.value)
    # INIT(1) kept, DEAD(3) kept, LIVE(2) elided by the shadow
    assert _acc_id(1).value in by_kind.get(BucketEntryType.INITENTRY, set())
    assert BucketEntryType.LIVEENTRY not in by_kind
    assert len(by_kind.get(BucketEntryType.DEADENTRY, set())) == 1


def test_protocol12_merge_ignores_shadows():
    """From protocol 12 shadows are retired: merging with or without
    them is byte-identical (reference: FIRST_PROTOCOL_SHADOWS_REMOVED)."""
    from stellar_core_tpu.bucket.bucket import merge_buckets
    old = Bucket.fresh(12, [], [_entry(1)], [])
    new = Bucket.fresh(12, [], [_entry(2)], [])
    shadow = Bucket.fresh(12, [], [_entry(1, balance=9)], [])
    assert merge_buckets(old, new, shadows=[shadow]).hash == \
        merge_buckets(old, new).hash


def test_merge_protocol_is_max_of_inputs():
    from stellar_core_tpu.bucket.bucket import (merge_buckets,
                                                merge_protocol_version)
    old = Bucket.fresh(11, [_entry(1)], [], [])
    new = Bucket.fresh(12, [], [_entry(2)], [])
    assert merge_protocol_version(old, new) == 12
    m = merge_buckets(old, new)
    assert m.meta_protocol == 12
    # the cap is enforced (reference: "exceeds maxProtocolVersion")
    import pytest as _pytest
    with _pytest.raises(ValueError, match="exceeds"):
        merge_buckets(old, new, protocol=11)


def test_init_entry_illegal_before_11():
    from stellar_core_tpu.bucket.bucket import merge_buckets
    import pytest as _pytest
    bad = Bucket.fresh(11, [_entry(1)], [], [])   # INIT inside
    pre = Bucket.fresh(10, [], [_entry(2)], [])
    # merge protocol = max(meta) = 11 -> INIT is legal; but force a
    # pre-11 shadow context by merging two pre-11 buckets with an INIT
    # record smuggled in
    from stellar_core_tpu.xdr.ledger import BucketEntry
    from stellar_core_tpu.bucket.bucket import Bucket as B
    smuggled = B(bad.entries(), bad.raw_bytes(), bad.hash,
                 meta_protocol=0)
    with _pytest.raises(ValueError, match="unsupported entry type"):
        merge_buckets(smuggled, pre)


def test_bucket_list_shadow_sweep_protocols():
    """BucketList end-to-end determinism sweep across the three shadow
    eras; pre-12 lists actually exercise the shadow path (reference:
    BucketListTests' merge sweeps)."""
    for proto in (5, 10, 11, 12, 21):
        def build():
            bl = BucketList()
            for seq in range(1, 65):
                init = [_entry(seq)]
                live = [_entry(seq - 1, balance=seq)] if seq > 1 else []
                dead = [_key(seq - 3)] if seq > 3 else []
                bl.add_batch(seq, proto, init, live, dead)
            return bl.get_hash()
        assert build() == build(), f"protocol {proto}"


def test_shadow_era_vs_modern_era_differ():
    """The same workload produces different bucket state pre- and
    post-shadow-removal (proves the shadow code path runs)."""
    def run(proto):
        bl = BucketList()
        for seq in range(1, 33):
            bl.add_batch(seq, proto, [_entry(seq)],
                         [_entry(seq - 1, balance=7)] if seq > 1 else [],
                         [])
        bl.resolve_all_merges()
        return bl.total_entry_count()
    # pre-11 shadows elide shadowed LIVE copies in older levels, so the
    # total record count is smaller than the modern era's
    assert run(10) < run(12)


# -------------------------------------------------------- merge-map dedup ---
def test_merge_map_shares_identical_merges():
    """Two bucket lists driven with the same workload through one
    BucketMergeMap share merge futures: every spill after the first
    list's is a reuse (reference: BucketMergeMap +
    BucketManagerImpl::getMergeFuture)."""
    from stellar_core_tpu.bucket.bucket_list import (BucketList,
                                                     BucketMergeMap)
    mm = BucketMergeMap()

    def run():
        bl = BucketList(merge_map=mm)
        for seq in range(1, 33):
            bl.add_batch(seq, 21, [_entry(seq)], [], [])
        return bl.get_hash()

    h1 = run()
    started_first = mm.started
    assert started_first > 0
    h2 = run()
    assert h2 == h1
    assert mm.reused >= started_first     # second run rode the memo
    assert mm.started == started_first    # no new merges needed


def test_merge_map_distinguishes_semantics():
    """Same inputs with different keep_dead/shadows/protocol are
    DIFFERENT merges (MergeKey captures the semantic knobs)."""
    from stellar_core_tpu.bucket.bucket import merge_buckets
    from stellar_core_tpu.bucket.bucket_list import (BucketMergeMap,
                                                     MergeKey)
    mm = BucketMergeMap()
    old = Bucket.fresh(21, [], [_entry(1)], [])
    new = Bucket.fresh(21, [], [], [_key(1)])
    k_keep = MergeKey(True, old, new, (), 21)
    k_drop = MergeKey(False, old, new, (), 21)
    assert k_keep != k_drop
    fb1 = mm.get_or_start(k_keep, lambda: merge_buckets(old, new), None)
    fb2 = mm.get_or_start(k_drop, lambda: merge_buckets(
        old, new, keep_dead=False), None)
    assert fb1 is not fb2
    assert fb1.resolve().hash != fb2.resolve().hash
    # identical key → same future object
    assert mm.get_or_start(k_keep, lambda: None, None) is fb1
    assert mm.reused == 1


def test_manager_gc_retains_live_merge_inputs(tmp_path):
    """forgetUnreferencedBuckets must treat in-progress merge inputs as
    referenced (reference: the in-progress exclusion)."""
    from stellar_core_tpu.bucket.bucket import merge_buckets
    from stellar_core_tpu.bucket.bucket_list import MergeKey

    mgr = BucketManager(str(tmp_path / "buckets"))
    try:
        b1 = mgr.adopt_bucket(Bucket.fresh(21, [_entry(1)], [], []))
        b2 = mgr.adopt_bucket(Bucket.fresh(21, [_entry(2)], [], []))
        key = MergeKey(True, b1, b2, (), 21)
        # a REAL lazily-resolved future registered in the map: its
        # inputs must survive GC until it resolves
        fb = mgr.merge_map.get_or_start(
            key, lambda: merge_buckets(b1, b2), None)
        assert fb.is_live()
        dropped = mgr.forget_unreferenced_buckets()
        assert dropped == 0
        assert mgr.get_bucket_by_hash(b1.hash) is not None
        fb.resolve()
        assert not fb.is_live()
        assert mgr.forget_unreferenced_buckets() == 2
    finally:
        mgr.shutdown()


def test_gc_does_not_resolve_pending_merges(tmp_path):
    """forget_unreferenced_buckets must not block on (resolve) pending
    level merges (reference: GC never waits on in-flight merges)."""
    mgr = BucketManager(str(tmp_path / "buckets"))
    try:
        bl = mgr.bucket_list
        for seq in range(1, 3):
            bl.add_batch(seq, 21, [_entry(seq)], [], [])
        # level 1 now has a pending future (ledger 2 spilled level 0)
        pending = [lvl._next for lvl in bl.levels if lvl._next is not None]
        assert pending
        resolved_before = [fb.is_live() for fb in pending]
        mgr.forget_unreferenced_buckets()
        resolved_after = [fb.is_live() for fb in pending]
        assert resolved_before == resolved_after  # GC didn't touch them
    finally:
        mgr.shutdown()
