"""Real-chip differential job wrapper (VERDICT round-1 weak #3).

The normal suite forces JAX to the CPU platform (conftest.py), so the
hardware job runs in subprocesses with their own env.  Enabled with
RUN_TPU_TESTS=1; kept out of the default run because the chip-side
kernel compile costs minutes per fresh process on the tunneled backend.
A small smoke variant (RUN_TPU_TESTS unset) still exercises the
orchestration path end-to-end on the CPU platform only, so the job
itself cannot rot.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "tpu_differential.py")


@pytest.mark.skipif(not os.environ.get("RUN_TPU_TESTS"),
                    reason="needs the real TPU (set RUN_TPU_TESTS=1)")
def test_differential_suite_on_real_chip():
    r = subprocess.run(
        [sys.executable, SCRIPT, "orchestrate", "--n", "10000"],
        capture_output=True, text=True, timeout=3600)
    sys.stdout.write(r.stdout)
    sys.stderr.write(r.stderr)
    assert r.returncode == 0
    assert "TPU DIFFERENTIAL: PASS" in r.stdout


@pytest.mark.skipif(not os.environ.get("RUN_TPU_TESTS"),
                    reason="needs the real TPU (set RUN_TPU_TESTS=1)")
def test_differential_fast_on_real_chip():
    """Small-bucket chip tier: full strict-check corpus vs the oracle,
    <2 min warm (VERDICT r04 #8) — `RUN_TPU_TESTS=1 pytest -k fast`."""
    r = subprocess.run(
        [sys.executable, SCRIPT, "fast"],
        capture_output=True, text=True, timeout=600)
    sys.stdout.write(r.stdout)
    sys.stderr.write(r.stderr)
    assert r.returncode == 0
    assert "FAST DIFFERENTIAL: PASS" in r.stdout


def test_differential_vectors_on_cpu_smoke():
    """The same job, CPU-platform subprocess, small n: proves the
    vectors + runner stay green without the chip."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    out = os.path.join(REPO, "tests", ".tpu-diff-smoke.npz")
    r = subprocess.run(
        [sys.executable, SCRIPT, "run", "--out", out, "--n", "64"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    sys.stdout.write(r.stdout)
    sys.stderr.write(r.stderr)
    assert r.returncode == 0
    assert '"mismatches_vs_oracle": 0' in r.stdout
    os.unlink(out)
