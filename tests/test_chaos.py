"""Deterministic chaos injection (ISSUE 2 tentpole).

Engine semantics (seeded schedules, hit ordinals, fault kinds), every
instrumented seam (overlay send/recv, archive get/put, DB commit,
completion queue, device verifier), the overlay send-error hardening,
the frozen-result-pair guard, the crash-point matrix over the close
phase boundaries (recovery must be byte-identical via the
`lastclosecompleted` path), the durable publish queue across a crash,
and the seeded multinode convergence scenario.
"""

import json
import os
import time

import pytest

from stellar_core_tpu.crypto.keys import SecretKey
from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.db.database import Database
from stellar_core_tpu.herder import make_tx_set_from_transactions
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import (CLOSE_CRASH_POINTS, ChaosEngine,
                                         ChaosError, FaultSpec,
                                         SimulatedCrash)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock
from stellar_core_tpu.xdr.ledger import StellarValue
from stellar_core_tpu.xdr.ledger_entries import Asset, AssetType
from stellar_core_tpu.xdr.transaction import (DecoratedSignature, Memo,
                                              MemoType, MuxedAccount,
                                              Operation, OperationType,
                                              PaymentOp, Preconditions,
                                              PreconditionType, Transaction,
                                              TransactionEnvelope,
                                              TransactionV1Envelope,
                                              _OperationBody, _TxExt)
from stellar_core_tpu.xdr.types import EnvelopeType

import test_ledger_close as lc
import test_overlay as ovl
from txtest_utils import op_create_account

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _no_leftover_engine():
    """Every test starts and ends with chaos disabled."""
    chaos.uninstall()
    yield
    chaos.uninstall()


# ------------------------------------------------------------ the engine --

def test_disabled_is_passthrough():
    assert chaos.ENABLED is False
    assert chaos.point("anything", b"payload", node="x") == b"payload"


def test_hit_window_scheduling_and_status():
    eng = ChaosEngine(3, [FaultSpec("p", "drop", start=1, count=2)])
    chaos.install(eng)
    assert chaos.ENABLED
    outs = [chaos.point("p", b"m") for _ in range(4)]
    assert outs[0] == b"m" and outs[3] == b"m"
    assert outs[1] is chaos.DROP and outs[2] is chaos.DROP
    st = chaos.status()
    assert st["injected"] == {"chaos.injected.drop": 2}
    assert st["points"] == {"p": 4}


def test_match_filters_by_context():
    eng = ChaosEngine(1, [FaultSpec("p", "drop", start=0, count=10,
                                    match={"node": "aa"})])
    chaos.install(eng)
    assert chaos.point("p", b"m", node="bb") == b"m"
    assert chaos.point("p", b"m", node="aa") is chaos.DROP
    # matched-hit ordinals count only matching calls
    assert eng._spec_hits[0] == 1


def test_fault_kinds():
    eng = ChaosEngine(9, [
        FaultSpec("io", "io_error"),
        FaultSpec("cr", "crash"),
        FaultSpec("co", "corrupt"),
        FaultSpec("fa", "fail"),
        FaultSpec("ha", "hang"),
    ])
    chaos.install(eng)
    with pytest.raises(ChaosError):
        chaos.point("io")
    with pytest.raises(SimulatedCrash) as exc:
        chaos.point("cr", node="deadbeef")
    assert exc.value.ctx["node"] == "deadbeef"
    out = chaos.point("co", b"\x00" * 8)
    assert out != b"\x00" * 8 and len(out) == 8
    assert sum(b != 0 for b in out) == 1   # exactly one byte flipped
    assert chaos.point("fa") is chaos.FAIL
    # hang (ISSUE 5): caller-interpreted sentinel — the backend
    # supervisor substitutes a never-completing handle for it
    assert chaos.point("ha") is chaos.HANG
    assert eng.injected["chaos.injected.hang"] == 1


def test_same_seed_reproduces_same_log():
    def run(seed):
        eng = ChaosEngine(seed, [
            FaultSpec("a", "drop", prob=0.5),
            FaultSpec("b", "drop", start=2, count=3),
        ])
        chaos.install(eng)
        for i in range(20):
            chaos.point("a", b"x")
            chaos.point("b", b"x")
        chaos.uninstall()
        return list(eng.log), dict(eng.injected)

    assert run(42) == run(42)
    assert run(42) != run(43)


def test_schedule_json_roundtrip():
    specs = [FaultSpec("p", "delay", start=1, count=2, delay_ms=5.0),
             FaultSpec("q", "drop", prob=0.25, match={"node": "aa"})]
    docs = [s.to_json() for s in specs]
    back = chaos.schedule_from_json(json.loads(json.dumps(docs)))
    assert [s.to_json() for s in back] == docs
    with pytest.raises(ValueError):
        FaultSpec("p", "not-a-kind")


def test_admin_chaos_route():
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        h = app.command_handler
        assert h.handle("chaos")["chaos"] == {"enabled": False}
        out = h.handle("chaos", {
            "mode": "install", "seed": "5",
            "schedule": json.dumps([{"point": "p", "kind": "drop"}])})
        assert out["chaos"]["enabled"] and out["chaos"]["seed"] == 5
        assert chaos.point("p", b"x") is chaos.DROP
        # injected counters surface on the metrics route too
        assert "chaos" in h.handle("metrics")
        assert h.handle("chaos", {"mode": "clear"})["status"] == "ok"
        assert chaos.ENABLED is False
        # production gate: without ALLOW_CHAOS_INJECTION the route
        # serves status but refuses install/clear
        app.config.ALLOW_CHAOS_INJECTION = False
        out = h.handle("chaos", {
            "mode": "install", "seed": "5",
            "schedule": json.dumps([{"point": "p", "kind": "drop"}])})
        assert "exception" in out
        assert chaos.ENABLED is False
        assert h.handle("chaos")["chaos"] == {"enabled": False}
    finally:
        app.shutdown()


# -------------------------------------------- overlay seams + hardening --

def test_overlay_send_io_error_takes_drop_path_not_scheduler():
    """Satellite: a transport error mid-write must tear the peer down
    through the standard drop path (floodgate unsubscribed, advert
    queue gone) and never unwind into the caller."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        om = apps[0].overlay_manager
        assert conn.initiator in om.get_authenticated_peers()
        node0 = apps[0].config.node_id().hex()
        chaos.install(ChaosEngine(1, [FaultSpec(
            "overlay.send", "io_error", start=0, count=1,
            match={"node": node0})]))
        from stellar_core_tpu.xdr.overlay import (MessageType,
                                                  StellarMessage)
        msg = StellarMessage(MessageType.GET_SCP_QUORUMSET,
                             b"\x01" * 32)
        conn.initiator.send_message(msg)      # must NOT raise
        assert conn.initiator.state.name == "CLOSING"
        assert conn.initiator not in om.get_authenticated_peers()
        assert id(conn.initiator) not in om._advert_queues
        assert chaos.engine().injected["chaos.injected.io_error"] == 1
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


def test_overlay_recv_corruption_drops_peer_cleanly():
    """Transport corruption lands as a MAC failure and takes the
    standard ERR_AUTH drop path on the receiving side."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        node1 = apps[1].config.node_id().hex()
        chaos.install(ChaosEngine(2, [FaultSpec(
            "overlay.recv", "corrupt", start=0, count=1,
            match={"node": node1})]))
        from stellar_core_tpu.xdr.overlay import (MessageType,
                                                  StellarMessage)
        conn.initiator.send_message(StellarMessage(
            MessageType.GET_SCP_QUORUMSET, b"\x02" * 32))
        conn.crank()                          # must NOT raise
        assert conn.acceptor.state.name == "CLOSING"
        assert conn.acceptor not in \
            apps[1].overlay_manager.get_authenticated_peers()
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


def test_overlay_message_drop_keeps_link_alive():
    """Pre-MAC message loss does NOT violate HMAC sequencing: the
    message vanishes, the link stays authenticated."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        node0 = apps[0].config.node_id().hex()
        chaos.install(ChaosEngine(3, [FaultSpec(
            "overlay.message", "drop", start=0, count=1,
            match={"node": node0})]))
        from stellar_core_tpu.xdr.overlay import (MessageType,
                                                  StellarMessage)
        before = conn.acceptor.messages_read
        conn.initiator.send_message(StellarMessage(
            MessageType.GET_SCP_QUORUMSET, b"\x03" * 32))
        conn.crank()
        assert conn.acceptor.messages_read == before   # dropped
        chaos.uninstall()
        conn.initiator.send_message(StellarMessage(
            MessageType.GET_SCP_QUORUMSET, b"\x04" * 32))
        conn.crank()
        assert conn.acceptor.messages_read == before + 1
        assert conn.initiator.state.name == "GOT_AUTH"
        assert conn.acceptor.state.name == "GOT_AUTH"
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


def test_loopback_recv_io_error_drops_receiver_not_crank_loop():
    """An injected io_error at the loopback recv seam takes the
    receiving peer's standard drop path — the simulation crank loop
    never sees the exception (TCP-path symmetry)."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        node1 = apps[1].config.node_id().hex()
        chaos.install(ChaosEngine(12, [FaultSpec(
            "overlay.recv", "io_error", start=0, count=1,
            match={"node": node1})]))
        from stellar_core_tpu.xdr.overlay import (MessageType,
                                                  StellarMessage)
        conn.initiator.send_message(StellarMessage(
            MessageType.GET_SCP_QUORUMSET, b"\x06" * 32))
        conn.crank()                          # must NOT raise
        assert conn.acceptor.state.name == "CLOSING"
        assert conn.acceptor not in \
            apps[1].overlay_manager.get_authenticated_peers()
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


def test_transport_seam_ignores_meaningless_sentinels():
    """A mis-kinded schedule (fail at a transport seam) must not leak
    the sentinel object into the byte stream or the scheduler: the
    frame goes out unchanged."""
    from stellar_core_tpu.overlay import LoopbackPeerConnection
    clock, apps = ovl.make_apps(2)
    try:
        conn = LoopbackPeerConnection(apps[0], apps[1])
        conn.crank()
        chaos.install(ChaosEngine(11, [
            FaultSpec("overlay.send", "fail", start=0, count=1 << 30),
            FaultSpec("overlay.recv", "fail", start=0, count=1 << 30),
        ]))
        from stellar_core_tpu.xdr.overlay import (MessageType,
                                                  StellarMessage)
        before = conn.acceptor.messages_read
        conn.initiator.send_message(StellarMessage(
            MessageType.GET_SCP_QUORUMSET, b"\x05" * 32))
        conn.crank()                          # must NOT raise
        assert conn.acceptor.messages_read == before + 1
        assert conn.initiator.state.name == "GOT_AUTH"
        assert conn.acceptor.state.name == "GOT_AUTH"
    finally:
        chaos.uninstall()
        ovl.shutdown(apps)


# ----------------------------------------------------- archive + db + cq --

def test_archive_get_failure_is_retried(tmp_path):
    """An injected archive fetch failure takes the real command-failed
    path; GetRemoteFileWork's retry succeeds once the fault clears."""
    from stellar_core_tpu.catchup.catchup_work import GetRemoteFileWork
    from stellar_core_tpu.history.archive import make_tmpdir_archive
    from stellar_core_tpu.work import run_work_to_completion
    from stellar_core_tpu.work.basic_work import State

    root = str(tmp_path / "archive")
    archive = make_tmpdir_archive("t", root)
    with open(os.path.join(root, "blob"), "w") as f:
        f.write("payload")
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        chaos.install(ChaosEngine(4, [FaultSpec(
            "history.get", "fail", start=0, count=1)]))
        local = str(tmp_path / "out")
        work = GetRemoteFileWork(app, archive, "blob", local)
        assert run_work_to_completion(app, work) == State.WORK_SUCCESS
        assert open(local).read() == "payload"
        assert chaos.engine().injected["chaos.injected.fail"] == 1
        # the failed first attempt landed on the operator counter
        # (ISSUE 5 satellite: history.archive.failure in metrics)
        j = app.command_handler.handle("metrics")["metrics"]
        assert j["history.archive.failure"]["count"] == 1
    finally:
        chaos.uninstall()
        app.shutdown()


def test_db_commit_failure_rolls_back_cleanly(tmp_path):
    db = Database(str(tmp_path / "t.db"))
    db.initialize()
    chaos.install(ChaosEngine(5, [FaultSpec(
        "db.commit", "io_error", start=0, count=1)]))
    with pytest.raises(ChaosError):
        with db.transaction():
            db.execute("INSERT OR REPLACE INTO storestate "
                       "(statename, state) VALUES ('k', 'v')")
    # rolled back, connection healthy, next commit lands
    assert db.query_one(
        "SELECT state FROM storestate WHERE statename='k'") is None
    with db.transaction():
        db.execute("INSERT OR REPLACE INTO storestate "
                   "(statename, state) VALUES ('k', 'v2')")
    assert db.query_one(
        "SELECT state FROM storestate WHERE statename='k'")[0] == "v2"
    db.close()


def test_completion_fault_surfaces_sticky_error():
    from stellar_core_tpu.ledger.completion import CloseCompletionQueue
    q = CloseCompletionQueue()
    chaos.install(ChaosEngine(6, [FaultSpec(
        "ledger.completion.run", "io_error", start=0, count=1)]))
    ran = []
    q.submit(5, lambda: ran.append(5))
    with pytest.raises(RuntimeError, match="ledger 5"):
        q.join()
    assert ran == []            # the injected fault pre-empted the job


def test_verifier_failure_falls_back_to_native():
    """Device-verifier fault at the txset-validation collection point:
    the herder's lazy batch prevalidator must fall back to the native
    per-signature path and still accept the valid set."""
    pytest.importorskip("jax")
    from stellar_core_tpu.ops.verifier import TpuBatchVerifier
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        import test_standalone_app as m1
        master = m1.master_account(app)
        dest = m1.AppAccount(app, SecretKey.from_seed(b"\x21" * 32))
        m1.submit(app, master.tx(
            [op_create_account(dest.account_id, 10 ** 10)]))
        app.herder.batch_verifier = TpuBatchVerifier(perf=app.perf)
        chaos.install(ChaosEngine(7, [FaultSpec(
            "ops.verifier.batch", "io_error", start=0, count=1 << 30)]))
        # admission warmed the verify cache; the prevalidator only
        # dispatches cache MISSES, so model a remote validator's cold
        # cache to force the device batch (and the injected fault)
        from stellar_core_tpu.crypto.keys import clear_verify_cache
        clear_verify_cache()
        lcl = app.ledger_manager.get_last_closed_ledger_header()
        frame, _, _ = make_tx_set_from_transactions(
            app.herder.tx_queue.get_transactions(), lcl,
            app.config.network_id())
        assert app.herder._check_tx_set_valid(frame) is True
        assert chaos.engine().injected["chaos.injected.io_error"] >= 1
    finally:
        chaos.uninstall()
        app.shutdown()


# ----------------------------------------------------- frozen result pairs --

def test_result_pair_frozen_after_close():
    """The frame actually APPLIED by a close (the one the stored
    TransactionResultPair and any held-back delay-meta reference)
    carries a frozen result: a late in-place mutation that skips
    _reset_result asserts instead of silently corrupting committed
    history."""
    from stellar_core_tpu.ledger.ledger_manager import LedgerCloseData
    db = Database(":memory:")
    db.initialize()
    lm = lc.make_manager(db=db)
    mk = lc.master_key()
    dest = SecretKey.from_seed(b"\x31" * 32)
    tx = lc.make_tx(lm, mk, lc.master_seq(lm) + 1,
                    [op_create_account(lc.xpk(dest), 10 ** 9)])
    lcl = lm.get_last_closed_ledger_header()
    frame, applicable, _ = make_tx_set_from_transactions(
        [tx], lcl, lc.NETWORK_ID)
    applied = applicable.get_txs_in_apply_order()[0]
    value = StellarValue(txSetHash=frame.get_contents_hash(),
                         closeTime=1000)
    lm.close_ledger(LedgerCloseData(2, applicable, value))
    lm.join_completion()
    assert getattr(applied.result, "_frozen", False)
    from stellar_core_tpu.util.checks import AssertionFailed
    from stellar_core_tpu.xdr.results import TransactionResultCode
    with pytest.raises(AssertionFailed, match="closed ledger"):
        applied.set_error(TransactionResultCode.txINTERNAL_ERROR)
    with pytest.raises(AssertionFailed, match="closed ledger"):
        applied.mark_result_failed()
    # a fresh validation pass REPLACES the result and unfreezes
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    with LedgerTxn(lm.root) as ltx:
        applied.check_valid(ltx)
    assert not getattr(applied.result, "_frozen", False)
    applied.set_error(TransactionResultCode.txINTERNAL_ERROR)


# ------------------------------------------------- crash-point matrix --

def _matrix_cfg(base):
    cfg = get_test_config()
    cfg.DATABASE = f"sqlite3://{base}/node.db"
    cfg.BUCKET_DIR_PATH = str(base / "buckets")
    return cfg


def _scheduled_tx(app, seq: int):
    """Deterministic tx for ledger `seq`: a master self-payment whose
    seqNum depends only on `seq` — re-derivable after any rollback."""
    from stellar_core_tpu.tx.frame import make_frame
    from stellar_core_tpu.tx.tx_utils import starting_sequence_number
    key = SecretKey.from_seed(app.config.network_id())
    muxed = MuxedAccount.from_ed25519(key.public_key().raw)
    tx = Transaction(
        sourceAccount=muxed, fee=100,
        seqNum=starting_sequence_number(1) + (seq - 1),
        cond=Preconditions(PreconditionType.PRECOND_NONE),
        memo=Memo(MemoType.MEMO_NONE),
        operations=[Operation(sourceAccount=None, body=_OperationBody(
            OperationType.PAYMENT, PaymentOp(
                destination=muxed,
                asset=Asset(AssetType.ASSET_TYPE_NATIVE),
                amount=1)))],
        ext=_TxExt(0))
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX,
        TransactionV1Envelope(tx=tx, signatures=[]))
    frame = make_frame(env, app.config.network_id())
    sig = key.sign(frame.contents_hash())
    frame.signatures.append(DecoratedSignature(
        hint=key.public_key().hint(), signature=sig))
    env.value.signatures = frame.signatures
    return frame


def _close_seq(app, seq: int) -> None:
    from stellar_core_tpu.ledger.ledger_manager import LedgerCloseData
    lm = app.ledger_manager
    frame = _scheduled_tx(app, seq)
    lcl = lm.get_last_closed_ledger_header()
    tx_set, applicable, _ = make_tx_set_from_transactions(
        [frame], lcl, app.config.network_id())
    value = StellarValue(txSetHash=tx_set.get_contents_hash(),
                         closeTime=1000 + seq)
    lm.close_ledger(LedgerCloseData(seq, tx_set, value))
    lm.join_completion()


def _chain_state(app, upto: int):
    rows = app.database.query_all(
        "SELECT ledgerseq, ledgerhash FROM ledgerheaders "
        "WHERE ledgerseq <= ? ORDER BY ledgerseq", (upto,))
    from stellar_core_tpu.main.persistent_state import StateEntry
    return ([(r[0], bytes(r[1])) for r in rows],
            app.ledger_manager.get_last_closed_ledger_hash(),
            int(app.persistent_state.get(StateEntry.LAST_CLOSE_COMPLETED)),
            app.history_manager.publish_queue_length())


_TARGET = 6
_CRASH_AT = 4          # close of seq 4 = the 3rd close → hit index 2


def _run_matrix(base, crash_point):
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             _matrix_cfg(base))
    app.start()
    seq = 2
    crashed = False
    if crash_point is not None:
        chaos.install(ChaosEngine(8, [FaultSpec(
            crash_point, "crash", start=_CRASH_AT - 2, count=1)]))
    try:
        while seq <= _TARGET:
            try:
                _close_seq(app, seq)
            except SimulatedCrash:
                crashed = True
                break
            except RuntimeError as e:       # deferred-completion crash
                assert isinstance(e.__cause__, SimulatedCrash), e
                crashed = True
                break
            seq += 1
    finally:
        chaos.uninstall()
    if crash_point is None:
        state = _chain_state(app, _TARGET)
        app.shutdown()
        return state
    assert crashed, f"{crash_point} never fired"
    # abandon the crashed app (no shutdown) and restart from its files
    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                              _matrix_cfg(base))
    app2.start()
    try:
        resume = app2.ledger_manager.get_last_closed_ledger_num() + 1
        for s in range(resume, _TARGET + 1):
            _close_seq(app2, s)
        return _chain_state(app2, _TARGET)
    finally:
        app2.shutdown()


@pytest.fixture(scope="module")
def matrix_control(tmp_path_factory):
    return _run_matrix(tmp_path_factory.mktemp("ctl"), None)


@pytest.mark.parametrize("point", CLOSE_CRASH_POINTS)
def test_crash_point_matrix(tmp_path, matrix_control, point):
    """A SimulatedCrash between each adjacent pair of close phases:
    restart recovers through the `lastclosecompleted` path and the
    resumed chain is byte-identical to a crash-free run — same header
    hashes, healed completion marker, consistent publish queue."""
    state = _run_matrix(tmp_path, point)
    assert state[0] == matrix_control[0], "header chain diverged"
    assert state[1] == matrix_control[1]
    assert state[2] == _TARGET          # marker healed to the LCL
    assert state[3] == 0                # publish queue consistent


@pytest.mark.parametrize("crash_point", ["ledger.close.crash.commit",
                                         "ledger.close.crash.queued"])
def test_publish_queue_survives_crash_after_queueing(tmp_path,
                                                     crash_point):
    """Crash on either side of the checkpoint close's COMMIT (the row
    rides the close transaction, so even a kill immediately after
    COMMIT — before in-memory adoption — keeps it): the durable
    publish queue re-queues it on restart with the queue-time HAS, and
    the retried publish lands in the archive."""
    root = str(tmp_path / "archive")
    cfg = _matrix_cfg(tmp_path)
    cfg.HISTORY = {"t": {
        "get": f"cp {root}/{{0}} {{1}}",
        "put": f"mkdir -p $(dirname {root}/{{1}}) && cp {{0}} "
               f"{root}/{{1}}",
    }}
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    # crash at a post-COMMIT boundary of the checkpoint close (seq 63)
    chaos.install(ChaosEngine(9, [FaultSpec(
        crash_point, "crash", start=61, count=1)]))
    try:
        seq = 2
        while True:
            try:
                _close_seq(app, seq)
            except SimulatedCrash:
                break
            seq += 1
        assert seq == 63
    finally:
        chaos.uninstall()
    # the queue row is durable even though the node never published
    assert app.database.query_one(
        "SELECT ledgerseq FROM publishqueue")[0] == 63
    assert app.history_manager.published_count == 0

    cfg2 = _matrix_cfg(tmp_path)
    cfg2.HISTORY = cfg.HISTORY
    app2 = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg2)
    app2.start()
    try:
        hm = app2.history_manager
        assert hm.publish_queue_length() == 1
        assert hm._publish_queue[0].seq == 63
        assert hm.queued_bucket_hashes()      # GC keeps its buckets
        assert hm.publish_queued_history() == 1
        with open(os.path.join(
                root, ".well-known/stellar-history.json")) as f:
            assert json.load(f)["currentLedger"] == 63
        assert app2.database.query_one(
            "SELECT COUNT(*) FROM publishqueue")[0] == 0
    finally:
        app2.shutdown()


# ------------------------------------------------- seal zone split --

def test_seal_zone_children_emitted(tmp_path):
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             _matrix_cfg(tmp_path))
    app.start()
    try:
        _close_seq(app, 2)
        report = app.perf.report()
        for zone in ("ledger.close.seal", "ledger.close.seal.sql",
                     "ledger.close.seal.fsync"):
            assert zone in report, f"missing {zone}"
        assert report["ledger.close.seal"]["total_ms"] >= \
            report["ledger.close.seal.sql"]["total_ms"]
    finally:
        app.shutdown()


# ------------------------------------------------- multinode scenario --

def test_multinode_chaos_scenario_converges(tmp_path):
    """The acceptance scenario: ≥5 fault classes under one seeded
    schedule; survivors stay live, their header chains are
    byte-identical to the fault-free run, the whole run reproduces
    from its seed (schedule run twice → same faults, same hashes),
    and node 0's circuit breaker rides the device-outage window
    (ISSUE 5): trips OPEN after the failure threshold, makes ZERO
    device dispatch attempts while OPEN, probes HALF_OPEN on the
    backoff schedule, and re-closes once the window exhausts."""
    from stellar_core_tpu.simulation.chaos import run_scenario
    res = run_scenario(seed=6, target=10,
                       archive_dir=str(tmp_path / "archive"))
    assert res["liveness_ok"], res
    assert res["safety_ok"], res
    assert res["repro_ok"], res
    assert res["archive_ok"], res
    assert len(res["crashed"]) == 1
    assert len(res["survivors"]) == 3
    classes = set(res["fault_classes"])
    assert {"drop", "reorder", "corrupt", "crash", "io_error",
            "fail"} <= classes
    assert res["archive_retry"]["ok"]
    # every survivor served a valid clusterstatus snapshot (ISSUE 8:
    # the structured health document the multi-process harness reads)
    assert res["clusterstatus_ok"], res["clusterstatus"]
    assert len(res["clusterstatus"]) == 3
    # breaker evidence (ISSUE 5 acceptance)
    assert res["breaker_ok"], res["breaker"]
    b = res["breaker"]
    assert b["tripped"] and b["probed"] and b["reclosed"]
    assert b["quiet_while_open"]           # dispatch counter frozen
    assert b["skips"] > 0                  # degraded-mode traffic ran
    moves = [(t["from"], t["to"]) for t in b["transitions"]]
    assert moves[0] == ("CLOSED", "OPEN")
    assert ("OPEN", "HALF_OPEN") in moves
    assert moves[-1] == ("HALF_OPEN", "CLOSED")


@pytest.mark.slow
@pytest.mark.soak
def test_chaos_convergence_soak(tmp_path):
    """Longer randomized-but-seeded soak: every seed must converge."""
    from stellar_core_tpu.simulation.chaos import run_scenario
    for i in range(3):
        res = run_scenario(seed=1000 + i, target=10,
                           archive_dir=str(tmp_path / f"archive-{i}"))
        assert res["liveness_ok"] and res["safety_ok"] \
            and res["repro_ok"], res
