"""Simulation + Topologies + LoadGenerator tests (reference:
simulation-driven suites like HerderTests/CoreTests: whole networks
cranked deterministically on virtual time)."""

import pytest

from stellar_core_tpu.simulation import LoadGenerator, Simulation, topologies


def test_pair_reaches_consensus():
    sim = topologies.pair()
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(3))
        assert sim.ledger_hashes_agree(2)
        assert sim.ledger_hashes_agree(3)
    finally:
        sim.stop_all_nodes()


def test_core4_with_load():
    sim = topologies.core(4)
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(2))
        app = sim.apps()[0]
        lg = LoadGenerator(app)
        assert lg.generate_accounts(10) == 10
        target = app.ledger_manager.get_last_closed_ledger_num() + 2
        assert sim.crank_until(lambda: sim.have_all_externalized(target))
        lg.sync_account_seqs()
        assert lg.generate_payments(20) == 20
        target = app.ledger_manager.get_last_closed_ledger_num() + 2
        assert sim.crank_until(lambda: sim.have_all_externalized(target))
        # the payments landed identically everywhere
        seq = min(a.ledger_manager.get_last_closed_ledger_num()
                  for a in sim.apps())
        assert sim.ledger_hashes_agree(seq)
        assert lg.failed == 0
    finally:
        sim.stop_all_nodes()


def test_cycle6_converges():
    """Ring quorums: every node trusts its neighbours; the whole ring
    still converges on one chain."""
    sim = topologies.cycle(6)
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(3),
                               timeout_virtual_seconds=300)
        assert sim.ledger_hashes_agree(2)
    finally:
        sim.stop_all_nodes()


def test_hierarchical_outer_follows_core():
    sim = topologies.hierarchical_quorum(3, 2)
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(2),
                               timeout_virtual_seconds=300)
        assert sim.ledger_hashes_agree(2)
    finally:
        sim.stop_all_nodes()


def test_continuous_operation_many_ledgers():
    """The network keeps closing ledgers on cadence without drift."""
    sim = topologies.core(3)
    try:
        sim.start_all_nodes()
        assert sim.crank_until(lambda: sim.have_all_externalized(10),
                               timeout_virtual_seconds=300)
        assert sim.ledger_hashes_agree(10)
    finally:
        sim.stop_all_nodes()


def test_loadgen_pretend_mixed_soroban_modes():
    """PRETEND / MIXED_CLASSIC / SOROBAN-upload loadgen modes (reference:
    LoadGenerator.h:28-35, LoadGenerator.cpp:469-494) drive a standalone
    manual-close app end to end."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    with Application.create(clock, cfg) as app:
        app.start()
        lg = LoadGenerator(app)
        assert lg.generate_accounts(4) == 4
        app.manual_close()
        lg.sync_account_seqs()

        assert lg.generate_pretend(6) == 6
        app.manual_close()

        assert lg.setup_dex() == 4
        app.manual_close()
        assert lg.generate_mixed(10, dex_percent=50) == 10
        app.manual_close()
        # the blend really is mixed: ~half the txs rested offers on the
        # book and the rest were payments
        row = app.database.query_one("SELECT COUNT(*) FROM offers", ())
        assert row[0] == 5

        assert lg.generate_soroban_uploads(3) == 3
        app.manual_close()
        row = app.database.query_one(
            "SELECT COUNT(*) FROM contractcode", ())
        assert row[0] >= 3
        assert lg.failed == 0


def test_loadgen_sac_and_invoke_modes():
    """SAC-transfer + contract-invoke loadgen (VERDICT r04 #7): the
    measured workloads exercise the wasm VM and the built-in SAC."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timer import ClockMode, VirtualClock

    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    cfg = get_test_config()
    with Application.create(clock, cfg) as app:
        app.start()
        lg = LoadGenerator(app)
        assert lg.generate_accounts(4) == 4
        app.manual_close()
        lg.sync_account_seqs()

        cid = lg.setup_sac()
        app.manual_close()
        lg.sync_account_seqs()
        before = [app_balance(app, a) for a in lg.accounts]
        assert lg.generate_sac_transfers(cid, 4, amount=1000) == 4
        app.manual_close()
        lg.sync_account_seqs()
        # every account sent 1000 and received 1000, minus its fee;
        # balances moved => the SAC transfers really applied
        after = [app_balance(app, a) for a in lg.accounts]
        assert all(b != a for a, b in zip(before, after))
        assert lg.failed == 0

        ccid = lg.setup_counter_contract()
        app.manual_close()
        lg.sync_account_seqs()
        assert lg.generate_counter_invokes(ccid, 5) == 5
        app.manual_close()
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.xdr import contract as cx
        from stellar_core_tpu.xdr.ledger_entries import LedgerKey
        addr = cx.SCAddress(cx.SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                            ccid)
        with LedgerTxn(app.ledger_manager.root) as ltx:
            le = ltx.load_without_record(LedgerKey.contract_data(
                addr, cx.SCVal(cx.SCValType.SCV_SYMBOL, b"count"),
                cx.ContractDataDurability.PERSISTENT))
            assert le is not None and le.data.value.val.value == 5
        assert lg.failed == 0


def app_balance(app, acct):
    from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
    from stellar_core_tpu.xdr.ledger_entries import LedgerKey
    with LedgerTxn(app.ledger_manager.root) as ltx:
        return ltx.load_without_record(
            LedgerKey.account(acct.account_id)).data.value.balance
