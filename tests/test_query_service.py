"""Snapshot-consistent read-serving tier (query/, ISSUE 17).

Covers the tentpole contracts: a reader holding the snapshot of
ledger N sees byte-identical results no matter how many ledgers close
after it while a late reader sees the newest seq; bucket GC honors
live read-snapshot pins across churn and collects once the last
reader drops; the tx-status store is fed from the deferred-completion
stream and stays bounded by capacity and TTL; the QueryService sheds
at the admission door (queue-full and controller), times out past the
deadline, and hedges slow lookups; the read shed ladder ramps on a
read_p99 breach while the write ladder stays untouched; bulk seeding
installs synthetic accounts the read path can serve while ledgers
keep closing; and the bucket-index meters drain into the registry.
"""

import threading
import time

from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.crypto.strkey import StrKey
from stellar_core_tpu.main import Application, get_test_config
from stellar_core_tpu.query.tx_status import TxStatusStore
from stellar_core_tpu.simulation.load_generator import (
    LoadGenerator, bulk_account_id, seed_accounts_bulk)
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


def _app(cfg=None):
    cfg = cfg or get_test_config()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def _pay_app():
    """App with a few loadgen accounts whose balances move per close."""
    app = _app()
    gen = LoadGenerator(app)
    gen.generate_accounts(4)
    app.manual_close()
    gen.sync_account_seqs()
    return app, gen


# ------------------------------------------------------- snapshot reads --

def test_reader_holding_snapshot_sees_frozen_bytes():
    app, gen = _pay_app()
    try:
        svc = app.query_service
        target = gen.accounts[0].key.public_key().raw
        snap_n = app.snapshots.acquire()
        seq_n = snap_n.ledger_seq
        before = svc.query_account(target, snapshot=snap_n)
        assert before["found"] and before["ledger_seq"] == seq_n
        # three more ledgers rewrite the account's balance
        for _ in range(3):
            gen.generate_payments(4)
            app.manual_close()
            gen.sync_account_seqs()
        # the held snapshot answers byte-identically at seq N
        for _ in range(2):
            again = svc.query_account(target, snapshot=snap_n)
            assert again["ledger_seq"] == seq_n
            assert again["entry_xdr"] == before["entry_xdr"]
        # a late reader (no pinned snapshot) sees N+3 and new bytes
        late = svc.query_account(target)
        assert late["found"] and late["ledger_seq"] == seq_n + 3
        assert late["entry_xdr"] != before["entry_xdr"]
        app.snapshots.release(snap_n)
    finally:
        app.shutdown()


def test_every_response_seq_names_a_closed_ledger():
    app, gen = _pay_app()
    try:
        closed = {app.ledger_manager.get_last_closed_ledger_num()}
        app.ledger_manager.closed_hooks.insert(
            0, lambda h, _: closed.add(h.ledgerSeq))
        target = gen.accounts[1].key.public_key().raw
        for _ in range(3):
            gen.generate_payments(4)
            app.manual_close()
            gen.sync_account_seqs()
            res = app.query_service.query_account(target)
            assert res["ledger_seq"] in closed
    finally:
        app.shutdown()


def test_missing_account_not_found_with_seq():
    app = _app()
    try:
        res = app.query_service.query_account(sha256(b"nobody-home"))
        assert res["found"] is False
        assert res["ledger_seq"] == \
            app.ledger_manager.get_last_closed_ledger_num()
        assert res["entry_xdr"] is None
    finally:
        app.shutdown()


# ------------------------------------------------------------ GC pinning --

def test_bucket_gc_honors_snapshot_pins_across_churn():
    app, gen = _pay_app()
    try:
        snap_n = app.snapshots.acquire()
        # churn: enough closes that level-0/1 spills replace the
        # buckets snap_n captured in the live list
        for _ in range(6):
            gen.generate_payments(4)
            app.manual_close()
            gen.sync_account_seqs()
        bm = app.bucket_manager
        orphaned = snap_n.bucket_hashes() - bm.referenced_hashes()
        assert orphaned, "churn never orphaned a snapshot bucket"
        bm.forget_unreferenced_buckets()
        for h in orphaned:
            assert h in bm._buckets, \
                "GC dropped a bucket a live snapshot still reads"
        # consistency survives the GC pass: the pinned snapshot still
        # answers at its own seq
        target = gen.accounts[0].key.public_key().raw
        res = app.query_service.query_account(target, snapshot=snap_n)
        assert res["found"] and res["ledger_seq"] == snap_n.ledger_seq
        app.snapshots.release(snap_n)
        bm.forget_unreferenced_buckets()
        assert all(h not in bm._buckets for h in orphaned), \
            "released snapshot still pinned its buckets"
    finally:
        app.shutdown()


# --------------------------------------------------------- tx status store --

class _Pair:
    def __init__(self, h, raw):
        class _R:
            def to_bytes(self, _raw=raw):
                return _raw
        self.transactionHash = h
        self.result = _R()


def test_tx_status_store_capacity_and_ttl():
    store = TxStatusStore(capacity=4, ttl_s=100.0)
    store.record_ledger(2, 1000, [_Pair(sha256(b"%d" % i), b"r%d" % i)
                                  for i in range(3)])
    assert len(store) == 3
    assert store.lookup(sha256(b"0")) == (b"r0", 2)
    assert store.lookup(sha256(b"nope")) is None
    # capacity ring: oldest evicted first
    store.record_ledger(3, 1010, [_Pair(sha256(b"%d" % i), b"s%d" % i)
                                  for i in range(3, 6)])
    assert len(store) == 4
    assert store.lookup(sha256(b"0")) is None
    assert store.lookup(sha256(b"5")) == (b"s5", 3)
    # TTL prune: a close far in the future expires everything older
    store.record_ledger(9, 5000, [_Pair(sha256(b"new"), b"n")])
    assert store.lookup(sha256(b"4")) is None
    assert store.lookup(sha256(b"new")) == (b"n", 9)


def test_completion_stream_feeds_tx_status():
    app, gen = _pay_app()
    try:
        captured = []
        app.ledger_manager.completion_hooks.append(
            lambda seq, ct, pairs: captured.extend(
                (bytes(p.transactionHash), seq) for p in pairs))
        gen.generate_payments(4)
        app.manual_close()
        app.ledger_manager.join_completion()
        assert captured, "completion hook never fired"
        for tx_hash, seq in captured:
            res = app.query_service.query_tx_status(tx_hash)
            assert res["found"] and res["ledger_seq"] == seq
            assert res["result_xdr"]
        missing = app.query_service.query_tx_status(sha256(b"ghost"))
        assert missing["found"] is False
    finally:
        app.shutdown()


# -------------------------------------------------- admission / deadlines --

def test_queue_full_sheds_at_the_door():
    app = _app()
    try:
        svc = app.query_service
        svc.queue_limit = 0          # every admission sees a full queue
        res = svc.query_account(sha256(b"x"))
        assert res["shed"] == "queue-full" and res["found"] is False
        assert svc.shed_counters["queue-full"].count == 1
    finally:
        app.shutdown()


def test_controller_shed_rejects_reads():
    app = _app()
    try:
        app.controller.shed_read = 1.0   # always-drop read admission
        res = app.query_service.query_account(sha256(b"x"))
        assert res["shed"] == "controller"
        assert app.query_service.shed_counters["controller"].count == 1
        assert app.controller.status()["shed"]["read_dropped"] >= 1
    finally:
        app.shutdown()


def test_expired_deadline_resolves_as_timeout():
    app = _app()
    try:
        res = app.query_service.query_account(
            sha256(b"x"), deadline_ms=-50.0)
        assert res.get("timeout") is True and res["found"] is False
        assert app.query_service.timeout_counter.count >= 1
    finally:
        app.shutdown()


def test_slow_lookup_triggers_hedge():
    app = _app()
    try:
        svc = app.query_service
        svc.hedge_min_ms = 1.0
        real = app.snapshots

        class _SlowSnap:
            def __init__(self, snap):
                self._snap = snap
                self.ledger_seq = snap.ledger_seq

            def read_entry(self, key):
                time.sleep(0.03)
                return self._snap.read_entry(key)

        class _SlowSnaps:
            def acquire(self):
                return _SlowSnap(real.acquire())

            def release(self, s):
                real.release(s._snap)

        svc._snapshots = _SlowSnaps()
        res = svc.query_account(sha256(b"x"))
        assert res["ledger_seq"] is not None
        assert svc.hedge_counters["issued"].count >= 1
        # the losing leg is still in flight when the caller returns;
        # give it a beat to land in won/wasted
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and \
                svc.hedge_counters["won"].count + \
                svc.hedge_counters["wasted"].count < 1:
            time.sleep(0.01)
        assert svc.hedge_counters["won"].count + \
            svc.hedge_counters["wasted"].count >= 1
    finally:
        app.shutdown()


def test_batch_read_answers_from_one_snapshot():
    app = _app()
    try:
        seed_accounts_bulk(app, 50)
        ids = [bulk_account_id(i) for i in (0, 7, 49)] + \
            [sha256(b"absent")]
        res = app.query_service.query_accounts(ids)
        assert res["found"] is True
        assert res["ledger_seq"] == \
            app.ledger_manager.get_last_closed_ledger_num()
        entries = res["entries_xdr"]
        assert len(entries) == 4
        assert all(e is not None for e in entries[:3])
        assert entries[3] is None
    finally:
        app.shutdown()


# ----------------------------------------------------------- shed ladder --

def _query_sample(t, read_p99, close_p99=100.0):
    return {
        "t": float(t), "ledger": int(t), "pending_txs": 0,
        "tx_applied": 0,
        "close": {"count": 5, "median_ms": close_p99 / 2,
                  "p99_ms": close_p99, "max_ms": close_p99},
        "tx_e2e": {"count": 0},
        "query": {"count": 50, "p50_ms": read_p99 / 2,
                  "p99_ms": read_p99, "queue": 0,
                  "p95_estimate_ms": read_p99, "shed": {},
                  "hedge": {}, "timeouts": 0, "snapshots": {}},
        "verify": None, "breaker": None, "breaker_open": 0.0,
        "flood": None, "dispatch": None, "mesh": None,
        "host": {"load1": 0.0, "ncpu": 1},
    }


def test_read_breach_sheds_reads_before_writes():
    app = _app()
    try:
        ctl = app.controller
        # read p99 breaching hard (SLO_READ_P99_MS=100), close healthy
        for t in (1.0, 2.0, 3.0):
            s = _query_sample(t, read_p99=500.0)
            app.slo.observe(s)
            ctl.tick(s)
        assert ctl.shed_read > 0.0, "read ladder never ramped"
        assert ctl.shed_tx == 0.0 and ctl.shed_flood == 0.0, \
            "write ladders moved on a read-only breach"
        # reads actually dropped at the admission door now
        dropped = sum(ctl.roll_read_shed() for _ in range(300))
        assert dropped > 0
        # recovery decays the ladder back down
        peak = ctl.shed_read
        for t in range(4, 24):
            s = _query_sample(float(t), read_p99=1.0)
            app.slo.observe(s)
            ctl.tick(s)
        assert ctl.shed_read < peak
        assert ctl.shed_read < 0.1
    finally:
        app.shutdown()


def test_write_pressure_sheds_reads_faster_than_writes():
    app = _app()
    try:
        ctl = app.controller
        s = _query_sample(1.0, read_p99=1.0, close_p99=10_000.0)
        app.slo.observe(s)
        ctl.tick(s)
        # close breach: reads shed at 2x the write ramp (sacrificial)
        assert ctl.shed_read > ctl.shed_tx > 0.0
    finally:
        app.shutdown()


# -------------------------------------------------------- seeding / index --

def test_bulk_seeding_serves_reads_and_survives_closes():
    app, gen = _pay_app()
    try:
        seed_accounts_bulk(app, 200)
        res = app.query_service.query_account(bulk_account_id(123))
        assert res["found"], "seeded account unreadable"
        # the seeded list still closes ledgers (hash recomputed over
        # the seeded levels) and the account stays readable after
        gen.generate_payments(4)
        app.manual_close()
        res2 = app.query_service.query_account(bulk_account_id(123))
        assert res2["found"]
        assert res2["ledger_seq"] == res["ledger_seq"] + 1
        assert res2["entry_xdr"] == res["entry_xdr"]
    finally:
        app.shutdown()


def test_bucket_index_meters_drain_into_registry():
    app = _app()
    try:
        seed_accounts_bulk(app, 100)
        svc = app.query_service
        for i in range(20):
            svc.query_account(bulk_account_id(i))
        svc.query_account(sha256(b"not-seeded"))
        rep = app.bucket_manager.drain_index_meters(
            app.metrics,
            extra_buckets=app.snapshots.live_buckets())
        assert rep["lookups"] > 0 and rep["hit"] >= 20
        assert app.metrics.meter("bucket", "index", "hit").count >= 20
        # second drain starts from zero (take_stats resets)
        rep2 = app.bucket_manager.drain_index_meters(
            app.metrics,
            extra_buckets=app.snapshots.live_buckets())
        assert rep2["lookups"] == 0
    finally:
        app.shutdown()


# ---------------------------------------------------------------- routes --

def test_http_routes_answer_reads():
    app, gen = _pay_app()
    try:
        raw = gen.accounts[0].key.public_key().raw
        out = app.command_handler.handle(
            "account", {"id": StrKey.encode_ed25519_public(raw)})
        assert out["found"] and out["ledger_seq"] == \
            app.ledger_manager.get_last_closed_ledger_num()
        assert out["entry"]                       # base64 entry XDR
        out_hex = app.command_handler.handle(
            "account", {"id": raw.hex()})
        assert out_hex["entry"] == out["entry"]
        gen.generate_payments(4)
        app.manual_close()
        app.ledger_manager.join_completion()
        captured = []
        app.ledger_manager.completion_hooks.append(
            lambda seq, ct, pairs: captured.extend(pairs))
        gen.generate_payments(2)
        app.manual_close()
        app.ledger_manager.join_completion()
        tx_hash = bytes(captured[0].transactionHash)
        st = app.command_handler.handle(
            "txstatus", {"hash": tx_hash.hex()})
        assert st["found"] and st["result"]
        info = app.command_handler.handle("snapshotinfo", {})
        assert info["snapshot"]["ledger_seq"] == \
            app.ledger_manager.get_last_closed_ledger_num()
        assert info["pinned_buckets"] >= 1
        assert info["tx_status_entries"] >= 2
    finally:
        app.shutdown()


def test_concurrent_readers_against_closing_ledgers():
    """Four reader threads hammer the pool while the main thread
    closes ledgers — every response seq must name a closed ledger and
    nothing deadlocks (the miniature of bench.py --read)."""
    app, gen = _pay_app()
    try:
        seed_accounts_bulk(app, 100)
        lock = threading.Lock()
        closed = {app.ledger_manager.get_last_closed_ledger_num()}

        def rec(h, _):
            with lock:
                closed.add(h.ledgerSeq)
        app.ledger_manager.closed_hooks.insert(0, rec)
        bad, done = [], threading.Event()

        def reader(k):
            i = 0
            while not done.is_set():
                res = app.query_service.query_accounts(
                    [bulk_account_id((k * 31 + i + j) % 100)
                     for j in range(4)])
                i += 1
                if res.get("shed") or res.get("timeout"):
                    continue
                with lock:
                    if res["ledger_seq"] not in closed:
                        bad.append(res["ledger_seq"])
        ts = [threading.Thread(target=reader, args=(k,), daemon=True)
              for k in range(4)]
        for t in ts:
            t.start()
        for _ in range(4):
            gen.generate_payments(4)
            app.manual_close()
            gen.sync_account_seqs()
        done.set()
        for t in ts:
            t.join(timeout=10.0)
        assert not bad, f"responses named unclosed seqs: {bad[:5]}"
    finally:
        app.shutdown()
