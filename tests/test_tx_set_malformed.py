"""Herder tx-set malformed-input tests.

Each test names the rejection it mirrors from
src/herder/test/TxSetTests.cpp (structurally invalid Generalized
TransactionSets, wrong prev-hash, duplicates, size overflow, seqnum
gaps) — the externalized-value hardening VERDICT round-1 weak #6
flagged."""

import pytest

from stellar_core_tpu.crypto.sha import sha256
from stellar_core_tpu.herder.tx_set import (TxSetFrame,
                                            make_tx_set_from_transactions)
from stellar_core_tpu.xdr.ledger import (GeneralizedTransactionSet,
                                         TransactionPhase, TransactionSet,
                                         TransactionSetV1, TxSetComponent,
                                         TxSetComponentType)

from test_ledger_close import (NETWORK_ID, make_manager, make_tx,
                               master_key, master_seq,
                               op_manage_data_stub)


@pytest.fixture
def lm():
    return make_manager(invariants=False)


def lcl(lm):
    return lm.get_last_closed_ledger_header()


def header_hash(h):
    return sha256(h.to_bytes())


def build_valid(lm, n=2):
    mk = master_key()
    seq = master_seq(lm)
    txs = [make_tx(lm, mk, seq + i + 1, [op_manage_data_stub(i)])
           for i in range(n)]
    frame, applicable, excluded = make_tx_set_from_transactions(
        txs, lcl(lm), NETWORK_ID)
    assert not excluded
    return txs, frame, applicable


def rebuild(lm, xdr_set):
    """Re-wrap mutated XDR and run the full validation pipeline."""
    frame = TxSetFrame(xdr_set, NETWORK_ID)
    applicable = frame.prepare_for_apply(lcl(lm))
    if applicable is None:
        return None
    return applicable.check_valid(lm.root)


# ----------------------------------------------------------------- happy --
def test_valid_set_passes(lm):
    _, frame, applicable = build_valid(lm)
    assert applicable.check_valid(lm.root)


# ------------------------------------------------------------- prev hash --
def test_wrong_previous_ledger_hash_rejected(lm):
    """TxSetTests: prev-hash must link the LCL."""
    _, frame, _ = build_valid(lm)
    xdr = frame.to_xdr()
    xdr.value.previousLedgerHash = b"\x13" * 32
    assert rebuild(lm, xdr) is False


# ------------------------------------------------------------ duplicates --
def test_duplicate_tx_rejected(lm):
    """TxSetTests 'duplicate txs'."""
    txs, frame, _ = build_valid(lm, n=1)
    xdr = frame.to_xdr()
    comp = xdr.value.phases[0].value[0]
    comp.value.txs = list(comp.value.txs) * 2
    assert rebuild(lm, xdr) is False


def test_same_tx_across_components_rejected(lm):
    txs, frame, _ = build_valid(lm, n=1)
    xdr = frame.to_xdr()
    phase = xdr.value.phases[0]
    first = phase.value[0]
    dup = TxSetComponent(
        TxSetComponentType.TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE)
    dup.value.baseFee = 500
    dup.value.txs = list(first.value.txs)
    phase.value = list(phase.value) + [dup]
    assert rebuild(lm, xdr) is False


# ------------------------------------------------------------- size caps --
def test_op_count_over_max_tx_set_size_rejected(lm):
    """maxTxSetSize counts OPS from protocol 11 (TxSetTests size)."""
    mk = master_key()
    seq = master_seq(lm)
    header = lcl(lm)
    cap = header.maxTxSetSize
    ops_per_tx = 10
    n_txs = cap // ops_per_tx + 1
    txs = [make_tx(lm, mk, seq + i + 1,
                   [op_manage_data_stub(i * ops_per_tx + j)
                    for j in range(ops_per_tx)])
           for i in range(n_txs)]
    # assemble by hand so surge pricing cannot trim it back to legal
    comp = TxSetComponent(
        TxSetComponentType.TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE)
    comp.value.baseFee = None
    comp.value.txs = [t.envelope for t in txs]
    v1 = TransactionSetV1(
        previousLedgerHash=header_hash(header),
        phases=[TransactionPhase(0, [comp]), TransactionPhase(0, [])])
    assert rebuild(lm, GeneralizedTransactionSet(1, v1)) is False


def test_make_tx_set_respects_cap_via_surge_pricing(lm):
    mk = master_key()
    seq = master_seq(lm)
    header = lcl(lm)
    txs = [make_tx(lm, mk, seq + i + 1, [op_manage_data_stub(i)],
                   fee=100 + i)
           for i in range(header.maxTxSetSize + 5)]
    frame, applicable, excluded = make_tx_set_from_transactions(
        txs, header, NETWORK_ID)
    assert len(excluded) == 5
    assert applicable.check_valid(lm.root)


# ---------------------------------------------------------------- seqnums --
def test_seqnum_gap_rejected(lm):
    """Chained account txs must be contiguous (TxSetTests seqnum gap)."""
    mk = master_key()
    seq = master_seq(lm)
    t1 = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)])
    t3 = make_tx(lm, mk, seq + 3, [op_manage_data_stub(1)])
    frame, applicable, _ = make_tx_set_from_transactions(
        [t1, t3], lcl(lm), NETWORK_ID)
    assert applicable.check_valid(lm.root) is False


def test_wrong_starting_seqnum_rejected(lm):
    mk = master_key()
    seq = master_seq(lm)
    t = make_tx(lm, mk, seq + 2, [op_manage_data_stub(0)])
    frame, applicable, _ = make_tx_set_from_transactions(
        [t], lcl(lm), NETWORK_ID)
    assert applicable.check_valid(lm.root) is False


def test_unsigned_tx_rejected(lm):
    mk = master_key()
    seq = master_seq(lm)
    t = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)])
    t.envelope.value.signatures = []
    t.signatures = t.envelope.value.signatures
    frame, applicable, _ = make_tx_set_from_transactions(
        [t], lcl(lm), NETWORK_ID)
    assert applicable.check_valid(lm.root) is False


# ----------------------------------------------------- structural breaks --
def test_generalized_set_before_protocol_20_rejected(lm):
    """A GeneralizedTransactionSet externalized on a pre-20 ledger is
    invalid (TxSetTests protocol gating)."""
    _, frame, applicable = build_valid(lm, n=1)
    header = lcl(lm).clone()
    header.ledgerVersion = 19
    re_applicable = frame.prepare_for_apply(header)
    # prev hash also differs, but version alone must already reject:
    # rebuild the set against the doctored header's own hash
    xdr = frame.to_xdr()
    xdr.value.previousLedgerHash = header_hash(header)
    f2 = TxSetFrame(xdr, NETWORK_ID)
    a2 = f2.prepare_for_apply(header)
    assert a2 is None or a2.check_valid(lm.root) is False


def test_undecodable_component_envelope_is_malformed(lm):
    """prepare_for_apply must return None (not raise) when an envelope
    cannot build a frame (TxSetXDRFrame::prepareForApply totality)."""
    txs, frame, _ = build_valid(lm, n=1)
    xdr = frame.to_xdr()
    comp = xdr.value.phases[0].value[0]
    env = comp.value.txs[0]

    class Hostile:
        def __getattr__(self, name):
            raise ValueError("hostile envelope")

    comp.value.txs = [Hostile()]
    f2 = TxSetFrame.__new__(TxSetFrame)
    f2._xdr = xdr
    f2._generalized = True
    f2.network_id = NETWORK_ID
    f2._hash = b"\x00" * 32
    assert f2.prepare_for_apply(lcl(lm)) is None


def test_close_ledger_rejects_malformed_externalized_set(lm):
    """closeLedger refuses a set whose hash does not match the
    externalized StellarValue (LedgerManagerTests 'bad tx set')."""
    from stellar_core_tpu.ledger.ledger_manager import LedgerCloseData
    from stellar_core_tpu.xdr.ledger import StellarValue
    _, frame, _ = build_valid(lm, n=1)
    sv = StellarValue(txSetHash=b"\x66" * 32, closeTime=1000)
    lcd = LedgerCloseData(lm.get_last_closed_ledger_num() + 1, frame, sv)
    with pytest.raises(ValueError, match="hash"):
        lm.close_ledger(lcd)


def test_component_base_fee_below_minimum_still_applies_floor(lm):
    """Component base fees are floored at the header base fee when
    building (the reference clamps the clearing fee)."""
    mk = master_key()
    seq = master_seq(lm)
    header = lcl(lm)
    txs = [make_tx(lm, mk, seq + i + 1, [op_manage_data_stub(i)],
                   fee=10_000)
           for i in range(3)]
    frame, applicable, _ = make_tx_set_from_transactions(
        txs, header, NETWORK_ID)
    for t in applicable.txs:
        bf = applicable.base_fee_for(t)
        assert bf is None or bf >= header.baseFee


def test_base_fee_for_unknown_tx_raises(lm):
    mk = master_key()
    seq = master_seq(lm)
    _, frame, applicable = build_valid(lm, n=1)
    foreign = make_tx(lm, mk, seq + 9, [op_manage_data_stub(9)])
    with pytest.raises(KeyError):
        applicable.base_fee_for(foreign)


def test_duplicate_seqnum_candidates_deduped_by_fee(lm):
    """Two same-account txs at one seqnum (replace-by-fee race): the
    builder keeps the better-paying one so the set stays chain-valid
    (reference: per-account TxStacks can never hold both)."""
    mk = master_key()
    seq = master_seq(lm)
    a = make_tx(lm, mk, seq + 1, [op_manage_data_stub(0)], fee=100)
    b = make_tx(lm, mk, seq + 1, [op_manage_data_stub(1)], fee=900)
    c = make_tx(lm, mk, seq + 2, [op_manage_data_stub(2)], fee=100)
    frame, applicable, excluded = make_tx_set_from_transactions(
        [a, b, c], lcl(lm), NETWORK_ID)
    hashes = {t.full_hash() for t in applicable.txs}
    assert b.full_hash() in hashes and c.full_hash() in hashes
    assert a.full_hash() not in hashes
    assert applicable.check_valid(lm.root)
