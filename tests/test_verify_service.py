"""Coalescing verify service (ops/verify_service.py) — determinism,
flush triggers, chaos fallback, cache write-through, batched flood
admission, and sharded min-bucket divisibility.

Parity contract: service results must be identical to the sync
PubKeyUtils.verify_sig path over valid, corrupted and non-canonical
signatures, on both the device path and the small-batch native bypass.
"""

import hashlib

import pytest

from stellar_core_tpu.crypto import ed25519_ref as ref
from stellar_core_tpu.crypto.keys import (PubKeyUtils, SecretKey,
                                          clear_verify_cache,
                                          flush_verify_cache_counts,
                                          verify_sig_uncached)
from stellar_core_tpu.ops.verifier import (ShardedBatchVerifier,
                                           TpuBatchVerifier)
from stellar_core_tpu.ops.verify_service import VerifyService
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import ChaosEngine, FaultSpec
from stellar_core_tpu.util.timer import ClockMode, VirtualClock


def _mk_valid(n, tag=b"vs"):
    items = []
    for i in range(n):
        sk = SecretKey.pseudo_random_for_testing(7000 + i)
        msg = hashlib.sha256(tag + b"-%d" % i).digest()
        items.append((sk.public_key().raw, sk.sign(msg), msg))
    return items


def _mixed_vectors():
    """Valid + corrupted + non-canonical signatures, 32-byte msgs (the
    tx-hash hot path the service feeds). Sized to pad into the SAME
    msg32 bucket (16) the kernel tier already compiles, so the full
    suite pays no extra trace/lower for the device-path parity test."""
    items = _mk_valid(4, b"mixed")
    pub, sig, msg = items[0]
    # corrupted signature byte
    bad_sig = sig[:10] + bytes([sig[10] ^ 0xFF]) + sig[11:]
    items.append((pub, bad_sig, msg))
    # wrong message
    items.append((pub, sig, hashlib.sha256(b"other").digest()))
    # non-canonical S: S' = s + L still satisfies the lax equation but
    # the strict verifier must reject it
    s = int.from_bytes(sig[32:], "little")
    bad_s = sig[:32] + ((s + ref.L) % (1 << 256)).to_bytes(32, "little")
    items.append((pub, bad_s, msg))
    # corrupted pubkey
    items.append((bytes([pub[0] ^ 0x01]) + pub[1:], sig, msg))
    return items


def _service(verifier=None, clock=None, **kw):
    return VerifyService(verifier or TpuBatchVerifier(), clock=clock,
                         **kw)


# ---------------------------------------------------------------- parity --

def test_parity_device_path():
    """Service over the device verifier == sync verify_sig, on valid +
    corrupted + non-canonical inputs."""
    clear_verify_cache()
    items = _mixed_vectors()
    svc = _service(TpuBatchVerifier(device_min_batch=1), max_batch=16)
    futures = svc.submit_many(items)
    got = [f.result() for f in futures]
    want = [verify_sig_uncached(p, s, m) for p, s, m in items]
    assert got == want
    # and the cached sync path agrees after write-through
    assert [PubKeyUtils.verify_sig(p, s, m) for p, s, m in items] == want


def test_parity_native_bypass():
    """Same vectors through the small-batch CPU bypass (cutoff above
    the batch size): identical accept/reject."""
    clear_verify_cache()
    items = _mixed_vectors()
    svc = _service(TpuBatchVerifier(device_min_batch=64), max_batch=8)
    got = [f.result() for f in svc.submit_many(items)]
    assert got == [verify_sig_uncached(p, s, m) for p, s, m in items]


def test_malformed_inputs_resolve_false():
    svc = _service(TpuBatchVerifier(device_min_batch=64))
    assert svc.submit(b"\x00" * 31, b"\x00" * 64, b"m").result() is False
    assert svc.submit(b"\x00" * 32, b"\x00" * 63, b"m").result() is False


# --------------------------------------------------------- flush triggers --

def test_max_batch_flush():
    """Crossing max_batch dispatches WITHOUT anyone awaiting — the
    double-buffered handle collects lazily at result()."""
    clear_verify_cache()
    items = _mk_valid(4, b"maxb")
    svc = _service(TpuBatchVerifier(device_min_batch=64), max_batch=4)
    futures = svc.submit_many(items)
    st = svc.stats()
    assert st["flushes"] == 1
    assert st["flush_reasons"]["batch_full"] == 1
    assert st["flush_reasons"]["demand"] == 0
    assert st["occupancy_mean"] == 4
    assert all(f.result() for f in futures)
    assert svc.stats()["flush_reasons"]["demand"] == 0


def test_demand_flush():
    clear_verify_cache()
    items = _mk_valid(2, b"dem")
    svc = _service(TpuBatchVerifier(device_min_batch=64), max_batch=8)
    futures = svc.submit_many(items)
    assert svc.stats()["flushes"] == 0      # below threshold, no await
    assert futures[1].result() is True      # forces ONE flush for both
    st = svc.stats()
    assert st["flushes"] == 1
    assert st["flush_reasons"]["demand"] == 1
    assert st["occupancy_mean"] == 2
    assert futures[0].done()                # same batch, already resolved
    assert st["queue_wait_p99_ms"] >= 0.0


def test_deadline_flush_on_virtual_clock():
    """Un-awaited submissions resolve when the deadline timer fires —
    and the results write through the verify cache."""
    clear_verify_cache()
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    items = _mk_valid(3, b"dl")
    svc = _service(TpuBatchVerifier(device_min_batch=64), clock=clock,
                   max_batch=8, deadline_ms=2.0)
    futures = svc.submit_many(items)
    assert not any(f.done() for f in futures)
    clock.crank(True)                        # jumps to the deadline timer
    assert all(f.done() for f in futures)
    st = svc.stats()
    assert st["flush_reasons"]["deadline"] == 1
    # write-through happened without anyone calling result()
    h, m = flush_verify_cache_counts()
    p, s, msg = items[0]
    assert PubKeyUtils.verify_sig(p, s, msg) is True
    h, m = flush_verify_cache_counts()
    assert h == 1 and m == 0


def test_pipeline_double_buffer():
    """A burst larger than max_batch dispatches in chunks; earlier
    chunks are already in flight (inflight queue) before any await."""
    clear_verify_cache()
    items = _mk_valid(10, b"pipe")
    svc = _service(TpuBatchVerifier(device_min_batch=64), max_batch=4)
    futures = svc.submit_many(items)
    st = svc.stats()
    assert st["flushes"] == 2                # 4 + 4 dispatched, 2 pending
    assert [f.result() for f in futures] == [True] * 10
    st = svc.stats()
    assert st["flushes"] == 3
    assert st["flush_reasons"]["batch_full"] == 2
    assert st["flush_reasons"]["demand"] == 1


# ------------------------------------------------------- cache interplay --

def test_cache_probe_skips_queue_and_write_through():
    clear_verify_cache()
    items = _mk_valid(2, b"wc")
    svc = _service(TpuBatchVerifier(device_min_batch=64), max_batch=8)
    assert svc.verify(*items[0]) is True
    flushes = svc.stats()["flushes"]
    # same tuple again: cache hit, no new flush, future pre-resolved
    fut = svc.submit(*items[0])
    assert fut.done() and fut.result() is True
    assert svc.stats()["flushes"] == flushes
    # sync path hits the cache seeded by the service
    flush_verify_cache_counts()
    assert PubKeyUtils.verify_sig(*items[0]) is True
    h, _ = flush_verify_cache_counts()
    assert h == 1


# ------------------------------------------------------------------ chaos --

def test_chaos_fallback_at_service_seam():
    """io_error at ops.verify_service.flush: every flush falls back to
    native per-signature verify with identical accept/reject."""
    clear_verify_cache()
    items = _mixed_vectors()
    svc = _service(TpuBatchVerifier(device_min_batch=1), max_batch=8)
    chaos.install(ChaosEngine(11, [FaultSpec(
        "ops.verify_service.flush", "io_error", start=0,
        count=1 << 30)]))
    try:
        got = [f.result() for f in svc.submit_many(items)]
        assert got == [verify_sig_uncached(p, s, m) for p, s, m in items]
        assert svc.stats()["fallbacks"] >= 1
        assert chaos.engine().injected["chaos.injected.io_error"] >= 1
    finally:
        chaos.uninstall()


def test_chaos_fallback_at_verifier_seam():
    """io_error at the underlying ops.verifier.batch seam (the PR 2
    contract): the service catches the dispatch failure and falls back."""
    clear_verify_cache()
    items = _mixed_vectors()
    svc = _service(TpuBatchVerifier(device_min_batch=1), max_batch=8)
    chaos.install(ChaosEngine(12, [FaultSpec(
        "ops.verifier.batch", "io_error", start=0, count=1 << 30)]))
    try:
        got = [f.result() for f in svc.submit_many(items)]
        assert got == [verify_sig_uncached(p, s, m) for p, s, m in items]
        assert svc.stats()["fallbacks"] >= 1
    finally:
        chaos.uninstall()


# ------------------------------------------------------------ integration --

def _tpu_app(clock=None):
    from stellar_core_tpu.main import Application, get_test_config
    cfg = get_test_config()
    cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
    app = Application.create(
        clock or VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    return app


def test_batched_flood_admission():
    """herder.recv_transactions: a burst admits through ONE service
    flush (occupancy == burst signature count) and every tx lands in
    the queue."""
    import test_standalone_app as m1
    from txtest_utils import op_payment
    from stellar_core_tpu.herder.tx_queue import AddResult

    clear_verify_cache()
    app = _tpu_app()
    try:
        master = m1.master_account(app)
        frames = [master.tx([op_payment(master.muxed, i + 1)])
                  for i in range(3)]
        before = app.verify_service.stats()["flushes"]
        res = app.herder.recv_transactions(frames)
        assert res == [AddResult.ADD_STATUS_PENDING] * 3
        for f in frames:
            assert app.herder.tx_queue.get_tx(f.full_hash()) is not None
        st = app.verify_service.stats()
        assert st["flushes"] == before + 1
        assert st["occupancy_p99"] >= 3
        # admission wrote through the cache: apply-time verify is free
        flush_verify_cache_counts()
        p = frames[0]
        assert PubKeyUtils.verify_sig(
            bytes(p.source_id.value), bytes(p.signatures[0].signature),
            p.contents_hash()) is True
        h, _ = flush_verify_cache_counts()
        assert h == 1
    finally:
        app.shutdown()


def test_stellar_value_signature_via_service():
    clear_verify_cache()
    app = _tpu_app()
    try:
        herder = app.herder
        sv = herder.make_stellar_value(b"\x42" * 32, 123, [])
        submitted = app.verify_service.stats()["submitted"]
        assert herder.verify_stellar_value_signature(sv) is True
        assert app.verify_service.stats()["submitted"] == submitted + 1
        # second verify of the same value: served from the cache
        assert herder.verify_stellar_value_signature(sv) is True
        assert app.verify_service.stats()["submitted"] == submitted + 1
    finally:
        app.shutdown()


def test_overlay_burst_drains_as_one_batch():
    """TRANSACTION bodies delivered in one crank buffer in the overlay
    and admit via ONE recv_transactions batch on the next crank."""
    from stellar_core_tpu.xdr.overlay import MessageType, StellarMessage
    import test_standalone_app as m1
    from txtest_utils import op_payment

    clear_verify_cache()
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    sender = _tpu_app(clock)
    receiver = _tpu_app(clock)
    receiver.config.NETWORK_PASSPHRASE = sender.config.NETWORK_PASSPHRASE
    try:
        master = m1.master_account(sender)
        frames = [master.tx([op_payment(master.muxed, i + 1)])
                  for i in range(3)]
        om = receiver.overlay_manager

        class _FakePeer:
            pass

        for f in frames:
            om._on_transaction(_FakePeer(), StellarMessage(
                MessageType.TRANSACTION, f.envelope))
        # buffered, not yet admitted
        assert receiver.herder.tx_queue.size_txs() == 0
        assert len(om._tx_recv_buffer) == 3
        clock.crank(False)                  # posted drain runs
        assert receiver.herder.tx_queue.size_txs() == 3
        st = receiver.verify_service.stats()
        assert st["flushes"] >= 1
        assert st["occupancy_p99"] >= 3
    finally:
        sender.shutdown()
        receiver.shutdown()


def test_crash_abandon_cancels_deadline_timer():
    """Herder.shutdown abandons the service: every pending future is
    resolved (False — no cache seed) so a blocked result() can never
    hang, and the deadline timer cannot fire into a dead app."""
    clear_verify_cache()
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    items = _mk_valid(2, b"ab")
    svc = _service(TpuBatchVerifier(device_min_batch=64), clock=clock,
                   max_batch=8, deadline_ms=1.0)
    futures = svc.submit_many(items)
    svc.abandon()
    clock.crank(True)
    assert all(f.done() for f in futures)
    assert [f.result() for f in futures] == [False, False]
    assert svc.stats()["flushes"] == 0
    # the abandoned verdicts must NOT have been seeded into the cache
    # (abandoned ≠ invalid): the sync path still verifies them
    p, s, m = items[0]
    assert PubKeyUtils.verify_sig(p, s, m) is True
    # a post-abandon submit resolves immediately instead of queueing
    fut = svc.submit(*items[1])
    assert fut.done() and fut.result() is False


def test_abandon_resolves_inflight_double_buffered_flush():
    """abandon() must resolve futures of an already-DISPATCHED flush
    (the double-buffered in-flight case), not only the pending queue."""
    clear_verify_cache()
    items = _mk_valid(6, b"abif")
    svc = _service(TpuBatchVerifier(device_min_batch=64), max_batch=4)
    futures = svc.submit_many(items)
    # 4 dispatched (in-flight, uncollected), 2 still pending
    assert svc.stats()["flushes"] == 1
    svc.abandon()
    assert all(f.done() for f in futures)
    assert [f.result() for f in futures] == [False] * 6


def test_no_future_left_unset_after_chaos_crash_leg():
    """A SimulatedCrash unwinding out of the flush seam (the chaos
    crash leg) must leave every submitted future reachable: the flush
    registers before the crash propagates, so the crash path's
    abandon() resolves them all — no future is ever left unset."""
    from stellar_core_tpu.util.chaos import SimulatedCrash

    clear_verify_cache()
    items = _mk_valid(4, b"crash")
    svc = _service(TpuBatchVerifier(device_min_batch=64), max_batch=4)
    chaos.install(ChaosEngine(13, [FaultSpec(
        "ops.verify_service.flush", "crash", start=0, count=1)]))
    try:
        futures = svc.submit_many(items[:3])     # pending, no flush yet
        with pytest.raises(SimulatedCrash):
            svc.submit(*items[3])                # crosses max_batch
    finally:
        chaos.uninstall()
    # the crash unwound out of the flush seam, but the flush registered
    # its futures first: they are reachable (in-flight, collect=None)
    assert not any(f.done() for f in futures)
    assert len(svc._inflight) == 1
    svc.abandon()                # the crash path buries the node
    assert all(f.done() for f in futures)
    assert [f.result() for f in futures] == [False] * 3


def test_cache_meters_on_metrics_route():
    """crypto.verify.cache.{hit,miss} meters surface the process-wide
    cache counters on the admin metrics route and in the Prometheus
    exposition (ISSUE 4 satellite)."""
    from stellar_core_tpu.main import Application, get_test_config

    clear_verify_cache()
    flush_verify_cache_counts()
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME),
                             get_test_config())
    app.start()
    try:
        sk = SecretKey.pseudo_random_for_testing(42)
        msg = b"cache meter probe"
        sig = sk.sign(msg)
        pub = sk.public_key().raw
        PubKeyUtils.verify_sig(pub, sig, msg)   # miss
        PubKeyUtils.verify_sig(pub, sig, msg)   # hit
        out = app.command_handler.handle("metrics")
        j = out["metrics"]
        assert j["crypto.verify.cache.hit"]["count"] >= 1
        assert j["crypto.verify.cache.miss"]["count"] >= 1
        prom = app.command_handler.handle(
            "metrics", {"format": "prometheus"})["_raw_body"]
        assert "crypto_verify_cache_hit_total" in prom
        assert "crypto_verify_cache_miss_total" in prom
    finally:
        app.shutdown()


# ----------------------------------------------------------- sharded mesh --

def test_sharded_min_bucket_divisibility():
    """ShardedBatchVerifier on the 8-device CPU mesh: every bucket the
    service can produce stays divisible by the mesh size, including
    uneven flush sizes that pad up — and for mesh sizes that are not
    powers of two, where the naive MIN_BUCKET would not divide."""
    from stellar_core_tpu.ops.verifier import MIN_BUCKET, _bucket_size
    import jax

    sharded = ShardedBatchVerifier(device_min_batch=1)
    assert sharded.ndev == 8
    assert sharded._min_bucket % sharded.ndev == 0
    for n in (1, 3, 5, 8, 9, 13, 200, 255):
        assert _bucket_size(n, sharded._min_bucket) % sharded.ndev == 0

    # non-power-of-two mesh (3 of the 8 CPU devices): min bucket climbs
    # to the smallest multiple of ndev >= MIN_BUCKET and doubling keeps
    # divisibility for every batch the verify service can flush
    three = ShardedBatchVerifier(devices=jax.devices()[:3],
                                 device_min_batch=1)
    assert three.ndev == 3
    assert three._min_bucket % 3 == 0
    assert three._min_bucket >= MIN_BUCKET
    for n in range(1, 64):
        assert _bucket_size(n, three._min_bucket) % 3 == 0

    # service-over-sharded flush path (native bypass: the padded
    # sharded DEVICE dispatch itself is pinned by the kernel tier in
    # test_tpu_verifier — re-tracing a fresh per-instance shard_map jit
    # here would cost ~70 s for no new device coverage)
    clear_verify_cache()
    items = _mk_valid(5, b"shard")
    svc = _service(ShardedBatchVerifier(device_min_batch=64), max_batch=8)
    got = [f.result() for f in svc.submit_many(items)]
    assert got == [True] * 5
