"""Per-device health mesh (ISSUE 13): sharded verify dispatch over the
ACTIVE device subset plus the per-device breaker array in
ops/backend_supervisor.py.

Two tiers, mirroring the subsystem's layering:

- **mesh dispatch** (ops/verifier.py `ShardedBatchVerifier` on the
  conftest 8-virtual-device CPU mesh): results byte-identical across
  8→7→8 shrink/regrow transitions, non-power-of-two surviving meshes
  keep the bucket divisible by the ACTIVE count, the single-survivor
  short-circuit rides the plain pinned jit, and the pinned
  `verify_tuples_async_on` canary-probe path stays exact.
- **per-device breakers** (ops/backend_supervisor.py against a fake
  mesh verifier — no XLA): a device-matched chaos fault trips exactly
  one chip (siblings uninterrupted, ZERO dispatches to the OPEN device
  — the counter-snapshot proof), unattributable whole-dispatch failures
  implicate every participant, the aggregate gauge leaves CLOSED only
  when the mesh is empty, per-device VirtualTimer probes regrow the
  mesh, and the sick-device chaos window reproduces under one seed.
"""

import pytest

from stellar_core_tpu.crypto import ed25519_ref as ref
from stellar_core_tpu.crypto.keys import verify_sig_uncached
from stellar_core_tpu.ops.backend_supervisor import (CLOSED, HALF_OPEN,
                                                     OPEN,
                                                     BackendSupervisor)
from stellar_core_tpu.ops.verifier import (MIN_BUCKET,
                                           ShardedBatchVerifier,
                                           _bucket_size)
from stellar_core_tpu.util import chaos
from stellar_core_tpu.util.chaos import ChaosEngine, FaultSpec
from stellar_core_tpu.util.timer import ClockMode, VirtualClock

from test_tpu_verifier import _mk


# ----------------------------------------------------- mesh dispatch --

def _oracle(items):
    return [ref.verify(p, s, m) for p, s, m in items]


@pytest.mark.slow
def test_results_byte_identical_across_shrink_regrow():
    """8→7→3→8: the same batch (valid + corrupted lanes) verifies to
    the identical result list on every mesh shape, including a
    non-power-of-two NON-CONTIGUOUS survivor set — only the shard
    layout moves, never the per-lane math. Slow tier: each distinct
    multi-device active set traces+lowers its own shard_map program
    (~50 s/shape on the 1-core CPU mesh, and the XLA disk cache
    cannot skip the lowering); the tier-1 shrink/regrow parity proof
    is test_shrink_regrow_parity_via_short_circuit below, and every
    MESH bench phase asserts the same oracle parity per flush."""
    v = ShardedBatchVerifier(device_min_batch=1)
    assert v.ndev == 8, "conftest should expose 8 virtual devices"
    items = _mk(13, seed=31)
    items[2] = (items[2][0], b"\x01" * 64, items[2][2])   # bad sig
    items[9] = (items[9][0], items[9][1], b"tampered msg")
    want = _oracle(items)
    assert v.verify_tuples(items) == want                 # 8 devices
    v.set_active_devices([i for i in range(8) if i != 5])
    assert v.active_indices() == (0, 1, 2, 3, 4, 6, 7)
    assert v.verify_tuples(items) == want                 # 7 survivors
    v.set_active_devices((0, 2, 6))                       # non-pow2,
    assert v.verify_tuples(items) == want                 # sparse
    v.set_active_devices(range(8))
    assert v.verify_tuples(items) == want                 # regrown


def test_shrink_regrow_parity_via_short_circuit():
    """Tier-1 shrink/regrow byte-parity: N→1→N through the
    single-survivor short-circuit (the shared jit — no new program
    lowering, so this stays cheap on the 1-core mesh). The layout/
    unshard path and the live active-set swap are the subjects; the
    multi-shard shapes ride the slow-tier test above and every MESH
    bench phase."""
    v = ShardedBatchVerifier(device_min_batch=1)
    items = _mk(6, seed=36)
    items[1] = (items[1][0], b"\x02" * 64, items[1][2])   # bad sig
    want = _oracle(items)
    v.set_active_devices([4])                             # shrink N→1
    assert v.verify_tuples(items) == want
    v.set_active_devices([2])                             # move chips
    assert v.verify_tuples(items) == want
    v.set_active_devices(range(v.ndev))                   # regrow
    assert v.active_indices() == tuple(range(v.ndev))


def test_bucket_divisible_by_any_active_count():
    """The global bucket doubles from the smallest multiple of the
    ACTIVE device count ≥ MIN_BUCKET — divisibility holds for every
    surviving-mesh size, power of two or not."""
    from stellar_core_tpu.ops.shard_math import shard_shares
    for nact in range(1, 9):
        minimum = ShardedBatchVerifier._min_bucket_for(nact)
        assert minimum % nact == 0 and minimum >= MIN_BUCKET
        for n in (1, 5, 13, 17, 100, 224):
            b = _bucket_size(n, minimum)
            assert b % nact == 0, (nact, n, b)
            assert b >= n
            # the shared split (dispatch layout AND the per-device
            # chaos seam's n=) sums exactly and fits the shard rows
            counts = shard_shares(n, nact)
            assert sum(counts) == n and len(counts) == nact
            assert max(counts) <= b // nact


def test_single_survivor_short_circuit():
    """One active device rides the plain shared jit pinned via
    device_put (the SNIPPETS §2–3 short-circuit), not a 1-shard
    shard_map — and stays exact."""
    v = ShardedBatchVerifier(device_min_batch=1)
    v.set_active_devices([3])
    items = _mk(5, seed=32)
    items[1] = (items[1][0], items[1][1][:63] + b"\x00", items[1][2])
    assert v.verify_tuples(items) == _oracle(items)
    fn, pin = v._program((3,), True)
    assert pin is v.devices[3]                # pinned, not meshed


def test_set_active_devices_validation():
    v = ShardedBatchVerifier(device_min_batch=1)
    with pytest.raises(ValueError):
        v.set_active_devices([])
    with pytest.raises(IndexError):
        v.set_active_devices([0, 99])
    v.set_active_devices([7, 1, 1, 4])        # dedup + sort
    assert v.active_indices() == (1, 4, 7)


def test_program_cache_bounded_lru():
    """The per-(active set, kernel) compiled-program cache is
    LRU-bounded: independently flapping breakers (up to 2^ndev
    survivor subsets) must not grow hot-path memory forever, while
    the shapes a live mesh revisits stay resident. Single-device keys
    ride the shared jit, so this exercises the cache without paying
    compiles."""
    v = ShardedBatchVerifier(device_min_batch=1)
    v._max_programs = 3
    for i in range(5):
        v._program((i,), True)
    assert len(v._programs) == 3
    assert ((4,), True) in v._programs
    assert ((0,), True) not in v._programs    # oldest evicted
    v._program((2,), True)                    # hit → most recent
    v._program((5,), True)
    v._program((6,), True)
    assert ((2,), True) in v._programs        # refreshed, kept
    assert ((3,), True) not in v._programs


def test_pinned_probe_dispatch_bypasses_active_mesh():
    """verify_tuples_async_on: the canary-probe entry point dispatches
    to ONE device regardless of the active set (probing a sick chip
    must not ride the survivors' mesh) and rejects bad indices."""
    v = ShardedBatchVerifier(device_min_batch=1)
    v.set_active_devices([0, 1])              # device 6 NOT active
    items = _mk(4, seed=33)
    assert v.verify_tuples_async_on(6, items)() == _oracle(items)
    with pytest.raises(IndexError):
        v.verify_tuples_async_on(8, items)
    assert v.verify_tuples_async_on(0, [])() == []


# ------------------------------------------------ per-device breakers --

class FakeMeshVerifier:
    """4-device mesh stand-in (host verify, no XLA) duck-typing the
    ShardedBatchVerifier surface the supervisor drives."""

    _device_min_batch = 1

    def __init__(self, ndev=4):
        self.ndev = ndev
        self._active = tuple(range(ndev))
        self.active_log = []
        self.fail_with = None
        self.probe_pins = []

    def set_active_devices(self, indices):
        self._active = tuple(sorted(int(i) for i in indices))
        self.active_log.append(self._active)

    def active_indices(self):
        return self._active

    def verify_tuples_async(self, items):
        if self.fail_with is not None:
            raise self.fail_with
        res = [verify_sig_uncached(p, s, m) for p, s, m in items]
        return lambda: res

    def verify_tuples_async_on(self, device_index, items):
        self.probe_pins.append(int(device_index))
        return self.verify_tuples_async(items)


def _sup(fv, clock=None, **kw):
    kw.setdefault("failure_threshold", 2)
    kw.setdefault("probe_base_ms", 100.0)
    kw.setdefault("probe_max_ms", 400.0)
    kw.setdefault("canary_batch", 2)
    return BackendSupervisor(fv, clock=clock, **kw)


def test_sick_device_window_isolates_one_chip():
    """The canonical sick-device chaos window (simulation/chaos.py,
    the chaos_soak leg): a device-matched io_error trips exactly one
    chip, the mesh shrinks around it with zero dispatches to the OPEN
    device while siblings keep serving, the canary probe regrows it —
    and the whole run reproduces under one seed."""
    from stellar_core_tpu.simulation.chaos import run_sick_device_window
    one = run_sick_device_window(seed=11)
    assert one["ok"], one
    for flag in ("exact", "tripped", "siblings_closed",
                 "quiet_while_open", "siblings_served", "shrunk",
                 "probe_in_window_failed", "regrown",
                 "aggregate_stayed_closed"):
        assert one[flag] is True, flag
    two = run_sick_device_window(seed=11)

    def shape(r):
        return (r["injected"], r["log"],
                [{k: t[k] for k in t if k != "t"}
                 for t in r["transitions"]])

    assert shape(one) == shape(two)


def test_device_matched_hang_quarantines_that_device():
    """A chaos `hang` matched to one device index pins the timeout
    blame AND the quarantined handle to that chip; siblings stay
    CLOSED and the mesh shrinks around it."""
    fv = FakeMeshVerifier(ndev=3)
    sup = _sup(fv, dispatch_deadline_ms=40.0, failure_threshold=1)
    items = _mk(3, seed=34)
    chaos.install(ChaosEngine(9, [FaultSpec(
        "ops.backend.dispatch.device", "hang", start=0, count=1,
        match={"device": 1})]))
    try:
        assert sup.verify_tuples(items) == _oracle(items)
        st = sup.status()
        assert st["devices"][1]["state"] == OPEN
        assert [d["state"] for d in st["devices"]] == \
            [CLOSED, OPEN, CLOSED]
        assert st["failures"]["timeout"] == 1
        assert st["quarantined"] and \
            st["quarantined"][0]["device"] == 1
        assert fv.active_indices() == (0, 2)
        assert st["state"] == CLOSED          # aggregate: mesh serves
    finally:
        chaos.uninstall()
        sup.shutdown()


def test_unattributable_failure_implicates_all_participants():
    """A whole-dispatch failure with no device attribution counts
    against every participant: after `threshold` consecutive failures
    ALL of them trip, the mesh is empty, the aggregate goes OPEN and
    dispatch skips straight to native with frozen counters."""
    fv = FakeMeshVerifier(ndev=4)
    sup = _sup(fv, failure_threshold=2)
    items = _mk(2, seed=35)
    want = _oracle(items)
    fv.fail_with = OSError("link flap")
    assert sup.verify_tuples(items) == want
    assert sup.state == CLOSED
    assert sup.verify_tuples(items) == want
    assert sup.state == OPEN                  # every device tripped
    assert sup.mesh_status()["active"] == 0
    snap = [d["dispatches"] for d in sup.status()["devices"]]
    skips = sup.status()["skips"]
    for _ in range(3):
        assert sup.verify_tuples(items) == want
    st = sup.status()
    assert [d["dispatches"] for d in st["devices"]] == snap
    assert st["skips"] == skips + 3
    sup.force_reset()
    assert sup.state == CLOSED
    assert fv.active_indices() == (0, 1, 2, 3)
    sup.shutdown()


def test_aggregate_leaves_closed_only_when_mesh_empty():
    fv = FakeMeshVerifier(ndev=3)
    sup = _sup(fv)
    sup.force_trip(device=0)
    assert sup.state == CLOSED and sup.mesh_status()["active"] == 2
    sup.force_trip(device=2)
    assert sup.state == CLOSED and sup.mesh_status()["active"] == 1
    assert fv.active_indices() == (1,)
    sup.force_trip(device=1)
    assert sup.state == OPEN and sup.mesh_status()["active"] == 0
    sup.force_reset(device=1)
    assert sup.state == CLOSED
    assert fv.active_indices() == (1,)        # only the reset chip
    sup.shutdown()


def test_per_device_probe_timer_regrows_mesh():
    """Each device's VirtualTimer probe is its own backoff stream: one
    tripped chip probes HALF_OPEN→CLOSED on the clock crank (pinned
    via verify_tuples_async_on), regrowing the mesh, while its
    siblings never transition at all."""
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    fv = FakeMeshVerifier(ndev=4)
    sup = _sup(fv, clock=clock, jitter_seed=5)
    sup.force_trip(device=2)
    assert fv.active_indices() == (0, 1, 3)
    assert sup.status()["devices"][2]["next_probe_in_s"] is not None
    clock.crank(True)                         # probe timer fires
    st = sup.status()
    assert st["devices"][2]["state"] == CLOSED
    assert st["devices"][2]["last_probe_age_s"] is not None
    assert fv.active_indices() == (0, 1, 2, 3)
    assert fv.probe_pins == [2]               # pinned, off the mesh
    moves = [(t["device"], t["from"], t["to"])
             for t in st["transitions"]]
    assert moves == [(2, CLOSED, OPEN), (2, OPEN, HALF_OPEN),
                     (2, HALF_OPEN, CLOSED)]
    sup.shutdown()


def test_backendstatus_per_device_rows_and_targeted_actions():
    """The admin route (main/command_handler.py): per-device rows, a
    device-targeted trip shrinks the mesh without leaving aggregate
    CLOSED, the telemetry sample reads the degraded mesh, bad indices
    reject, and reset regrows."""
    from stellar_core_tpu.main import Application, get_test_config
    from stellar_core_tpu.util.timeseries import collect_sample

    cfg = get_test_config()
    cfg.SIGNATURE_VERIFY_BACKEND = "tpu"
    app = Application.create(VirtualClock(ClockMode.VIRTUAL_TIME), cfg)
    app.start()
    try:
        out = app.command_handler.handle("backendstatus")["backend"]
        assert len(out["devices"]) == 8
        assert out["mesh"] == {"devices": 8, "active": 8,
                               "active_indices": list(range(8))}
        out = app.command_handler.handle(
            "backendstatus", {"action": "trip", "device": "3"})
        b = out["backend"]
        assert b["state"] == CLOSED           # 7 devices still serve
        assert b["devices"][3]["state"] == OPEN
        assert b["mesh"]["active"] == 7
        assert 3 not in b["mesh"]["active_indices"]
        sample = collect_sample(app)
        assert sample["breaker"] == CLOSED
        assert sample["mesh"] == {"devices": 8, "active": 7}
        # per-device counters are on the metrics route
        j = app.command_handler.handle("metrics")["metrics"]
        assert "crypto.verify_backend.device3.skip" in j
        out = app.command_handler.handle(
            "backendstatus", {"action": "reset", "device": "3"})
        assert out["backend"]["mesh"]["active"] == 8
        out = app.command_handler.handle(
            "backendstatus", {"action": "trip", "device": "42"})
        assert "exception" in out
    finally:
        app.shutdown()


def test_mesh_degraded_samples_in_series_summary():
    """summarize_samples / aggregate_summaries count samples taken
    while the mesh was shrunk — the graceful-degradation counterpart
    of breaker_open_samples."""
    from stellar_core_tpu.util.timeseries import (aggregate_summaries,
                                                  summarize_samples)
    samples = [
        {"t": 1.0, "mesh": {"devices": 8, "active": 8}},
        {"t": 2.0, "mesh": {"devices": 8, "active": 7}},
        {"t": 3.0, "mesh": {"devices": 8, "active": 5}},
        {"t": 4.0, "mesh": None},
    ]
    s = summarize_samples(samples)
    assert s["mesh_degraded_samples"] == 2
    agg = aggregate_summaries([s, s])
    assert agg["mesh_degraded_samples"] == 4
