"""Artifact schema lint (ISSUE 8 satellite): every committed
BENCH/TPS*/BYZ/CHAOS/VERIFY/… JSON artifact must satisfy
scripts/check_artifacts.py, and the checker must actually catch
malformed documents — a bench refactor can no longer silently ship a
broken artifact."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "scripts"))

import check_artifacts                                     # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_committed_artifacts_all_valid():
    paths = check_artifacts.find_artifacts(ROOT)
    assert paths, "no artifacts found in repo root"
    problems = []
    for p in paths:
        problems.extend(check_artifacts.check_artifact(p))
    assert not problems, problems
    # every known family with a committed artifact got matched
    prefixes = {os.path.basename(p).split("_r")[0] for p in paths}
    assert {"BENCH", "TPSM", "TPSMT", "CHAOS", "BYZ",
            "VERIFY"} <= prefixes


def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def test_checker_accepts_valid_and_error_forms(tmp_path):
    good = _write(tmp_path, "TPSM_r09.json", {
        "metric": "loadgen_pay_tps_multinode", "value": 188.5,
        "unit": "txs/sec", "vs_baseline": 0.94,
        "flood": {"duplicate_ratio": 1.4, "per_peer_bytes": []}})
    assert check_artifacts.check_artifact(good) == []
    # a recorded harness failure is a legal artifact
    err = _write(tmp_path, "CATCHUP_r09.json", {
        "metric": "catchup_replay_throughput",
        "error": "RuntimeError('stalled')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_rejects_malformed_artifacts(tmp_path):
    # missing required key
    p = _write(tmp_path, "TPS_r09.json", {
        "metric": "loadgen_pay_tps", "value": 200.0,
        "unit": "txs/sec"})
    assert any("vs_baseline" in x
               for x in check_artifacts.check_artifact(p))
    # string where a number belongs
    p = _write(tmp_path, "TPSMT_r09.json", {
        "metric": "x", "value": "fast", "unit": "txs/sec",
        "vs_baseline": 1.0, "flood": {}})
    assert any("'value'" in x for x in check_artifacts.check_artifact(p))
    # bool is not a number
    p = _write(tmp_path, "VERIFY_r09.json", {
        "metric": "x", "value": True, "unit": "v/s",
        "vs_baseline": 1.0})
    assert any("'value'" in x for x in check_artifacts.check_artifact(p))
    # verdict flag must be a bool
    p = _write(tmp_path, "CHAOS_r09.json", {
        "metric": "x", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
        "liveness_ok": "yes", "safety_ok": True, "repro_ok": True,
        "clusterstatus_ok": True})
    assert any("liveness_ok" in x
               for x in check_artifacts.check_artifact(p))
    # new-round artifacts must carry the flood section
    p = _write(tmp_path, "TPSM_r08.json", {
        "metric": "x", "value": 1.0, "unit": "u", "vs_baseline": 1.0})
    assert any("flood" in x for x in check_artifacts.check_artifact(p))
    # unparseable JSON
    bad = tmp_path / "BYZ_r09.json"
    bad.write_text("{not json")
    assert check_artifacts.check_artifact(str(bad))
    # unrecognized artifact name
    assert check_artifacts.check_artifact(str(tmp_path / "NOPE_r1.json"))


def test_checker_cluster_family(tmp_path):
    """The CLUSTER family (ISSUE 9): per-node verdicts, every-survivor
    clusterstatus health, the real-wire flood section and host_load
    hygiene are required; a doc missing any of them is rejected."""
    core = {"metric": "loadgen_pay_tps_cluster", "value": 52.1,
            "unit": "txs/sec", "vs_baseline": 0.26,
            "verdicts": {"node00": {"clusterstatus_ok": True}},
            "clusterstatus_ok": True, "safety_ok": True,
            "liveness_ok": True,
            "chaos": {"flooder_dropped": True},
            "churn": {"caught_up": True},
            "flood": {"duplicate_ratio": 2.4, "per_peer_bytes": []},
            "host_load": {"start": {}, "end": {}}}
    good = _write(tmp_path, "CLUSTER_r09.json", core)
    assert check_artifacts.check_artifact(good) == []
    for missing in ("verdicts", "clusterstatus_ok", "flood",
                    "host_load", "chaos", "churn", "safety_ok",
                    "liveness_ok"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "CLUSTER_r10.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # verdict flag must be a bool, not a truthy string
    p = _write(tmp_path, "CLUSTER_r11.json",
               dict(core, clusterstatus_ok="yes"))
    assert any("clusterstatus_ok" in x
               for x in check_artifacts.check_artifact(p))
    # a recorded harness failure stays legal
    err = _write(tmp_path, "CLUSTER_r12.json", {
        "metric": "loadgen_pay_tps_cluster",
        "error": "ClusterError('boot stalled')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_trend_family(tmp_path):
    """The TREND family (ISSUE 10): the per-family trajectories and
    the regression list are the artifact's whole point — a doc
    missing either is rejected."""
    core = {"metric": "bench_trend", "value": 0.0,
            "unit": "regressions", "vs_baseline": 1.0,
            "tolerance": 0.3, "artifacts_total": 26,
            "families": {"TPSM": {"rounds": {"5": {"value": 188.5}}}},
            "regressions": []}
    good = _write(tmp_path, "TREND_r10.json", core)
    assert check_artifacts.check_artifact(good) == []
    for missing in ("families", "regressions", "tolerance", "value"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "TREND_r11.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    err = _write(tmp_path, "TREND_r12.json", {
        "metric": "bench_trend", "error": "RuntimeError('empty')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_requires_slo_and_timeseries_on_new_rounds(tmp_path):
    """ISSUE 10: from round 10 on, TPS*/CLUSTER/BYZ artifacts must
    carry the SLO verdict section and the bounded series summary;
    older committed rounds stay legal."""
    base = {"metric": "m", "value": 1.0, "unit": "u",
            "vs_baseline": 1.0}
    telem = {"slo": {"overall": "OK", "rules": {}},
             "timeseries": {"samples": 3}}
    # old round: keys not yet required
    old = _write(tmp_path, "TPS_r09.json", base)
    assert check_artifacts.check_artifact(old) == []
    # new round without them: rejected, naming both keys
    p = _write(tmp_path, "TPS_r10.json", base)
    probs = check_artifacts.check_artifact(p)
    assert any("slo" in x for x in probs)
    assert any("timeseries" in x for x in probs)
    # with them: accepted — across every family on the hook
    ok = _write(tmp_path, "TPSS_r10.json", {**base, **telem})
    assert check_artifacts.check_artifact(ok) == []
    byz = _write(tmp_path, "BYZ_r10.json",
                 {**base, "smoke": {}, **telem})
    assert check_artifacts.check_artifact(byz) == []
    # type-checked, not just present
    bad = _write(tmp_path, "TPSM_r10.json",
                 {**base, "flood": {}, "slo": "OK",
                  "timeseries": {"samples": 1}})
    assert any("'slo'" in x for x in check_artifacts.check_artifact(bad))


def test_checker_surge_family(tmp_path):
    """The SURGE family (ISSUE 11, bench.py --surge): the static and
    adaptive legs must EACH carry their SLO verdicts, time-series
    summary and shed/decision counts — the A/B evidence is the
    artifact's whole point — plus the verdict section."""
    leg = {"slo": {"overall": "OK", "rules": {}},
           "timeseries": {"samples": 12},
           "shed": {"tx": 0.95, "tx_dropped": 9070},
           "decisions": {"total": 97, "shed_changes": 24}}
    core = {"metric": "surge_close_p99_control", "value": 8.25,
            "unit": "x", "vs_baseline": 8.25,
            "slo_close_p99_ms": 800.0,
            "static": dict(leg), "adaptive": dict(leg),
            "verdict": {"static_breaches": True,
                        "adaptive_holds": True, "ok": True}}
    good = _write(tmp_path, "SURGE_r11.json", core)
    assert check_artifacts.check_artifact(good) == []
    # a leg missing any evidence key is rejected, naming the leg
    for missing in ("slo", "timeseries", "shed", "decisions"):
        doc = dict(core, adaptive={k: v for k, v in leg.items()
                                   if k != missing})
        p = _write(tmp_path, "SURGE_r12.json", doc)
        assert any("adaptive" in x and missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # top-level legs/verdict required
    for missing in ("static", "adaptive", "verdict"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "SURGE_r13.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # leg evidence is type-checked, not just present
    p = _write(tmp_path, "SURGE_r14.json",
               dict(core, static=dict(leg, timeseries="lots")))
    assert any("static.timeseries" in x
               for x in check_artifacts.check_artifact(p))
    # a recorded harness failure stays legal
    err = _write(tmp_path, "SURGE_r15.json", {
        "metric": "surge_close_p99_control",
        "error": "RuntimeError('leg stalled')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_cluster_requires_controller_on_new_rounds(tmp_path):
    """ISSUE 11: from round 11 on, CLUSTER artifacts must carry the
    adaptive-control-plane poll beside slo/timeseries."""
    core = {"metric": "loadgen_pay_tps_cluster", "value": 52.1,
            "unit": "txs/sec", "vs_baseline": 0.26,
            "verdicts": {}, "clusterstatus_ok": True,
            "safety_ok": True, "liveness_ok": True,
            "chaos": {}, "churn": {},
            "flood": {}, "host_load": {},
            "slo": {"overall": "OK"}, "timeseries": {"samples": 1}}
    # r10: controller not yet required
    old = _write(tmp_path, "CLUSTER_r10.json", core)
    assert check_artifacts.check_artifact(old) == []
    p = _write(tmp_path, "CLUSTER_r11.json", core)
    assert any("controller" in x
               for x in check_artifacts.check_artifact(p))
    ok = _write(tmp_path, "CLUSTER_r12.json",
                dict(core, controller={"per_node": {}, "totals": {}},
                     flood={"demand": {}, "encode": {}}))
    assert check_artifacts.check_artifact(ok) == []


def test_checker_requires_flood_evidence_since_r12(tmp_path):
    """ISSUE 12: from round 12 on, TPSMT/CLUSTER artifacts must carry
    the single-flight demand and encode-cache sections inside their
    flood dict — the wire-path verdict counters; older rounds stay
    legal, and the sections are type-checked."""
    base = {"metric": "loadgen_pay_tps_multinode_tcp", "value": 150.0,
            "unit": "txs/sec", "vs_baseline": 0.75,
            "slo": {}, "timeseries": {}}
    # r11: evidence not yet required
    old = _write(tmp_path, "TPSMT_r11.json",
                 {**base, "flood": {"duplicate_ratio": 1.5}})
    assert check_artifacts.check_artifact(old) == []
    # r12 without the sections: rejected, naming both
    p = _write(tmp_path, "TPSMT_r12.json",
               {**base, "flood": {"duplicate_ratio": 0.4}})
    probs = check_artifacts.check_artifact(p)
    assert any("demand" in x for x in probs)
    assert any("encode" in x for x in probs)
    # with them: accepted
    ok = _write(tmp_path, "TPSMT_r13.json", {**base, "flood": {
        "duplicate_ratio": 0.4,
        "demand": {"sent": 10, "suppressed": 5},
        "encode": {"cache_hit": 100, "cache_miss": 10}}})
    assert check_artifacts.check_artifact(ok) == []
    # type-checked, not just present
    bad = _write(tmp_path, "TPSMT_r14.json", {**base, "flood": {
        "duplicate_ratio": 0.4, "demand": "lots", "encode": {}}})
    assert any("flood.demand" in x
               for x in check_artifacts.check_artifact(bad))


def test_checker_mesh_family(tmp_path):
    """The MESH family (ISSUE 13, bench.py --mesh-degrade): the
    healthy/degraded/recovered phase throughputs, per-device dispatch
    evidence, the zero-dispatch-while-OPEN proof and host-load hygiene
    are required; each phase's tps and the quiet-proof fields are
    type-checked."""
    phase = {"tps": 200.0, "flushes": 4, "batch": 224,
             "wall_s": 4.5, "active_devices": 8}
    core = {"metric": "mesh_degrade_retention", "value": 0.97,
            "unit": "ratio", "vs_baseline": 1.11,
            "phases": {"healthy": dict(phase),
                       "degraded": dict(phase, active_devices=7),
                       "recovered": dict(phase)},
            "mesh": {"devices": 8, "sick_device": 7,
                     "survivors": [0, 1, 2, 3, 4, 5, 6]},
            "per_device": [{"device": 0, "state": "CLOSED",
                            "dispatches": 14, "skips": 0}],
            "quiet_proof": {"trip_snapshot": 6,
                            "dispatches_after_degraded_phase": 6,
                            "zero_dispatch_while_open": True},
            "transitions": [{"from": "CLOSED", "to": "OPEN",
                             "device": 7, "device_dispatches": 6}],
            "verdict": {"degraded_ok": True, "ok": True},
            "host_load": {"start": {}, "end": {}}}
    good = _write(tmp_path, "MESH_r13.json", core)
    assert check_artifacts.check_artifact(good) == []
    for missing in ("phases", "mesh", "per_device", "quiet_proof",
                    "transitions", "verdict", "host_load"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "MESH_r14.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # a missing phase leg is rejected, naming it
    p = _write(tmp_path, "MESH_r15.json", dict(core, phases={
        "healthy": dict(phase), "recovered": dict(phase)}))
    assert any("degraded" in x
               for x in check_artifacts.check_artifact(p))
    # a phase without a numeric tps is rejected
    p = _write(tmp_path, "MESH_r16.json", dict(core, phases={
        **core["phases"], "degraded": dict(phase, tps="fast")}))
    assert any("phases.degraded.tps" in x
               for x in check_artifacts.check_artifact(p))
    # the quiet proof must prove: snapshots + flag, type-checked
    p = _write(tmp_path, "MESH_r17.json", dict(core, quiet_proof={
        "trip_snapshot": 6, "zero_dispatch_while_open": True}))
    assert any("dispatches_after_degraded_phase" in x
               for x in check_artifacts.check_artifact(p))
    p = _write(tmp_path, "MESH_r18.json", dict(core, quiet_proof={
        "trip_snapshot": 6, "dispatches_after_degraded_phase": 6,
        "zero_dispatch_while_open": "yes"}))
    assert any("zero_dispatch_while_open" in x
               for x in check_artifacts.check_artifact(p))
    # a recorded harness failure stays legal (single-device hosts)
    err = _write(tmp_path, "MESH_r19.json", {
        "metric": "mesh_degrade_retention",
        "error": "RuntimeError('needs >= 2 devices')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_cli_exit_codes(tmp_path, capsys):
    good = _write(tmp_path, "TPS_r09.json", {
        "metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0})
    assert check_artifacts.main([good]) == 0
    bad = _write(tmp_path, "TPS_r10.json", {"metric": "m"})
    assert check_artifacts.main([good, bad]) == 1
    capsys.readouterr()


def test_checker_read_family(tmp_path):
    """The READ family (ISSUE 17, bench.py --read): the read-qps
    headline must carry the two-sided consistency verdict, the hedge
    counters, shed/write evidence and host-load hygiene — the nested
    hedge/consistency keys are type-checked too."""
    core = {"metric": "query_read_qps", "value": 25000.0,
            "unit": "reads/sec", "vs_baseline": 2.5,
            "accounts": 1000000, "read_p50_ms": 0.4,
            "read_p99_ms": 3.1,
            "hedge": {"issued": 12, "won": 3, "wasted": 9,
                      "rate": 0.002},
            "consistency": {"responses": 5000, "seq_mismatches": 0,
                            "reread_checked": 180,
                            "reread_violations": 0, "ok": True},
            "shed": {"batches": 0, "controller": 0, "queue-full": 0},
            "write": {"ledgers": 10, "applied": 2000, "tps": 180.0},
            "host_load": {"start": {}, "end": {}},
            "slo": {"overall": "OK", "rules": {}},
            "timeseries": {"samples": 10}}
    good = _write(tmp_path, "READ_r17.json", core)
    assert check_artifacts.check_artifact(good) == []
    for missing in ("accounts", "read_p50_ms", "read_p99_ms", "hedge",
                    "consistency", "shed", "write", "host_load",
                    "slo", "timeseries"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "READ_r18.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # nested evidence type-checked: the consistency verdict must be a
    # real bool and the hedge counters real numbers
    p = _write(tmp_path, "READ_r19.json", dict(
        core, consistency=dict(core["consistency"], ok="yes")))
    assert any("consistency.ok" in x
               for x in check_artifacts.check_artifact(p))
    p = _write(tmp_path, "READ_r20.json", dict(
        core, hedge={"issued": 12, "won": 3, "wasted": 9}))
    assert any("hedge" in x and "rate" in x
               for x in check_artifacts.check_artifact(p))
    # a recorded harness failure stays legal
    err = _write(tmp_path, "READ_r21.json", {
        "metric": "query_read_qps", "error": "RuntimeError('x')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_tpsm_bigstate_family(tmp_path):
    """The TPSM_BIGSTATE family (ISSUE 17, bench.py --bigstate): the
    seeded-state scale and the bucket-index hit/bloom evidence ride
    the TPS headline; the multi-word prefix must resolve to its OWN
    family, not a TPSM round."""
    core = {"metric": "loadgen_pay_tps_multinode_bigstate",
            "value": 140.0, "unit": "txs/sec", "vs_baseline": 0.7,
            "accounts": 1000000,
            "bucket_index": {"lookups": 4000, "hit": 500,
                             "miss": 3450, "bloom_fp": 50},
            "host_load": {"start": {}, "end": {}},
            "slo": {"overall": "OK", "rules": {}},
            "timeseries": {"samples": 10}}
    good = _write(tmp_path, "TPSM_BIGSTATE_r17.json", core)
    assert check_artifacts.check_artifact(good) == []
    for missing in ("accounts", "bucket_index", "host_load", "slo",
                    "timeseries"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "TPSM_BIGSTATE_r18.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    p = _write(tmp_path, "TPSM_BIGSTATE_r19.json", dict(
        core, bucket_index={"lookups": 1, "hit": 1, "miss": 0}))
    assert any("bloom_fp" in x
               for x in check_artifacts.check_artifact(p))
    # the plain-TPSM schema must NOT swallow the bigstate name (the
    # bench_trend family split depends on the same distinction)
    assert "TPSM_BIGSTATE" in check_artifacts.SCHEMAS


_CATCHUP_STAGES_DOC = {
    "wall_s": 1.8,
    "stages": {s: {"busy_s": 0.5, "occupancy": 0.28, "items": 3}
               for s in ("download", "verify", "prevalidate",
                         "apply")},
    "queues": {"bytes_hwm": 120000, "byte_budget": 67108864,
               "ready_hwm": 2, "backpressure_stalls": 1},
    "overlap": {"device_busy_while_download_s": 0.2,
                "apply_busy_while_download_s": 0.4}}
_CATCHUP_PAPPLY_DOC = {"workers": 4, "ledgers": 120,
                       "stages_total": 240, "width_max": 3,
                       "fallbacks": 0}


def test_checker_catchup_requires_pipeline_evidence_since_r19(tmp_path):
    """ISSUE 19: from round 19 on, CATCHUP artifacts must carry the
    pipeline stage-occupancy record and the parallel-apply section;
    older committed rounds stay legal, and the nested per-stage
    triples are type-checked."""
    base = {"metric": "catchup_replay_throughput", "value": 450.0,
            "unit": "ledgers/sec", "vs_baseline": 3.2}
    # old round: evidence not yet required
    old = _write(tmp_path, "CATCHUP_r05.json", base)
    assert check_artifacts.check_artifact(old) == []
    # new round without it: rejected, naming both sections
    p = _write(tmp_path, "CATCHUP_r19.json", base)
    probs = check_artifacts.check_artifact(p)
    assert any("stages" in x for x in probs)
    assert any("parallel_apply" in x for x in probs)
    # with the evidence: accepted
    ok = _write(tmp_path, "CATCHUP_r20.json", {
        **base, "stages": dict(_CATCHUP_STAGES_DOC),
        "parallel_apply": dict(_CATCHUP_PAPPLY_DOC)})
    assert check_artifacts.check_artifact(ok) == []
    # a stage missing from the occupancy record is rejected, named
    partial = dict(_CATCHUP_STAGES_DOC,
                   stages={k: v
                           for k, v in
                           _CATCHUP_STAGES_DOC["stages"].items()
                           if k != "prevalidate"})
    p = _write(tmp_path, "CATCHUP_r21.json", {
        **base, "stages": partial,
        "parallel_apply": dict(_CATCHUP_PAPPLY_DOC)})
    assert any("prevalidate" in x
               for x in check_artifacts.check_artifact(p))
    # stage triples are type-checked, not just present
    typo = dict(_CATCHUP_STAGES_DOC,
                stages=dict(_CATCHUP_STAGES_DOC["stages"],
                            apply={"busy_s": "long", "occupancy": 0.5,
                                   "items": 1}))
    p = _write(tmp_path, "CATCHUP_r22.json", {
        **base, "stages": typo,
        "parallel_apply": dict(_CATCHUP_PAPPLY_DOC)})
    assert any("stages.stages.apply.busy_s" in x
               for x in check_artifacts.check_artifact(p))
    # the parallel-apply section must carry every counter
    p = _write(tmp_path, "CATCHUP_r23.json", {
        **base, "stages": dict(_CATCHUP_STAGES_DOC),
        "parallel_apply": {"workers": 4}})
    assert any("parallel_apply" in x and "ledgers" in x
               for x in check_artifacts.check_artifact(p))
    # a recorded harness failure stays legal
    err = _write(tmp_path, "CATCHUP_r24.json", {
        "metric": "catchup_replay_throughput",
        "error": "RuntimeError('archive stalled')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_catchup_bigstate_family(tmp_path):
    """The CATCHUP_BIGSTATE family (ISSUE 19, bench.py
    --catchup-bigstate): streaming replay over the seeded
    million-account state must carry the seeded scale plus the same
    pipeline evidence as CATCHUP; the multi-word prefix resolves to
    its OWN family, not a CATCHUP round."""
    core = {"metric": "catchup_replay_throughput_bigstate",
            "value": 300.0, "unit": "ledgers/sec", "vs_baseline": 2.4,
            "accounts": 1000000,
            "stages": dict(_CATCHUP_STAGES_DOC),
            "parallel_apply": dict(_CATCHUP_PAPPLY_DOC),
            "host_load": {"start": {}, "end": {}}}
    good = _write(tmp_path, "CATCHUP_BIGSTATE_r19.json", core)
    assert check_artifacts.check_artifact(good) == []
    for missing in ("accounts", "stages", "parallel_apply",
                    "host_load"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "CATCHUP_BIGSTATE_r20.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # nested stage evidence applies here at every round
    p = _write(tmp_path, "CATCHUP_BIGSTATE_r21.json", dict(
        core, stages=dict(_CATCHUP_STAGES_DOC, overlap="yes")))
    assert any("stages.overlap" in x
               for x in check_artifacts.check_artifact(p))
    # the plain-CATCHUP schema must NOT swallow the bigstate name
    assert "CATCHUP_BIGSTATE" in check_artifacts.SCHEMAS
    # a recorded harness failure stays legal
    err = _write(tmp_path, "CATCHUP_BIGSTATE_r22.json", {
        "metric": "catchup_replay_throughput_bigstate",
        "error": "RuntimeError('seeding stalled')"})
    assert check_artifacts.check_artifact(err) == []


def test_checker_replay_family(tmp_path):
    """The REPLAY family (ISSUE 18, bench.py --replay): the six
    determinism verdicts and the divergence-injection probe ARE the
    claim — a doc missing any of them is rejected."""
    verdicts = {"chains_match_live": True, "decisions_match_live": True,
                "end_markers_match": True,
                "replays_zero_trace_diff": True,
                "crash_replayed": True, "divergence_caught": True}
    core = {"metric": "replay_ledgers_per_sec", "value": 57.8,
            "unit": "ledgers/sec", "vs_baseline": 6.9, "ok": True,
            "nodes": 4, "verdicts": dict(verdicts),
            "replay": {"seed": 7, "target": 8, "survivors": 3},
            "divergence": {"caught": True, "index": 1402,
                           "chain_len": 8},
            "host_load": {"start": {}, "end": {}}}
    good = _write(tmp_path, "REPLAY_r18.json", core)
    assert check_artifacts.check_artifact(good) == []
    for missing in ("verdicts", "replay", "divergence", "ok",
                    "host_load", "nodes"):
        doc = {k: v for k, v in core.items() if k != missing}
        p = _write(tmp_path, "REPLAY_r19.json", doc)
        assert any(missing in x
                   for x in check_artifacts.check_artifact(p)), missing
    # every verdict flag is required and must be a real bool
    for key in verdicts:
        doc = dict(core, verdicts={k: v for k, v in verdicts.items()
                                   if k != key})
        p = _write(tmp_path, "REPLAY_r20.json", doc)
        assert any(key in x
                   for x in check_artifacts.check_artifact(p)), key
    p = _write(tmp_path, "REPLAY_r21.json",
               dict(core, verdicts=dict(verdicts,
                                        divergence_caught="yes")))
    assert any("divergence_caught" in x
               for x in check_artifacts.check_artifact(p))
    # the probe must always say whether the flipped byte was caught
    p = _write(tmp_path, "REPLAY_r22.json",
               dict(core, divergence={"index": 3}))
    assert any("caught" in x
               for x in check_artifacts.check_artifact(p))
    # a recorded harness failure stays legal
    err = _write(tmp_path, "REPLAY_r23.json", {
        "metric": "replay_ledgers_per_sec",
        "error": "RuntimeError('liveness lost')"})
    assert check_artifacts.check_artifact(err) == []
