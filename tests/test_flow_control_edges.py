"""FlowControl byte/message-capacity edge cases.

Each test names the behavior it mirrors from
src/overlay/test/FlowControlTests.cpp — VERDICT round-1 weak #6's
missing byte-capacity edge coverage."""

import pytest

from stellar_core_tpu.herder.tx_queue import TransactionQueue  # noqa: F401
from stellar_core_tpu.main.config import Config
from stellar_core_tpu.overlay.flow_control import (FlowControl,
                                                   is_flow_controlled,
                                                   msg_body_size)
from stellar_core_tpu.xdr.overlay import (MessageType, SendMoreExtended,
                                          StellarMessage)


def cfg(msgs=4, byts=10_000, batch_msgs=2, batch_bytes=5_000):
    c = Config()
    c.PEER_FLOOD_READING_CAPACITY = msgs
    c.PEER_FLOOD_READING_CAPACITY_BYTES = byts
    c.FLOW_CONTROL_SEND_MORE_BATCH_SIZE = batch_msgs
    c.FLOW_CONTROL_SEND_MORE_BATCH_SIZE_BYTES = batch_bytes
    return c


def tx_msg(size_hint=0):
    """A flooded TRANSACTION message, optionally padded via memo-free
    envelope bytes (size varies with signature count)."""
    from stellar_core_tpu.xdr.transaction import (
        Memo, MemoType, MuxedAccount, Preconditions, PreconditionType,
        Transaction, TransactionEnvelope, TransactionV1Envelope, _TxExt,
        DecoratedSignature)
    from stellar_core_tpu.xdr.types import EnvelopeType
    tx = Transaction(
        sourceAccount=MuxedAccount.from_ed25519(b"\x01" * 32),
        fee=100, seqNum=1,
        cond=Preconditions(PreconditionType.PRECOND_NONE),
        memo=Memo(MemoType.MEMO_NONE), operations=[], ext=_TxExt(0))
    sigs = [DecoratedSignature(hint=b"\x00" * 4, signature=b"\x00" * 64)
            for _ in range(size_hint)]
    env = TransactionEnvelope(
        EnvelopeType.ENVELOPE_TYPE_TX,
        TransactionV1Envelope(tx=tx, signatures=sigs))
    return StellarMessage(MessageType.TRANSACTION, env)


def peers_msg():
    return StellarMessage(MessageType.GET_PEERS)


def grant(fc, msgs, byts):
    return fc.on_send_more(msgs, byts)


# ------------------------------------------------------------- send side --
def test_non_flood_bypasses_flow_control():
    """FlowControlTests: only flood traffic is throttled."""
    fc = FlowControl(cfg())
    assert fc.remote_capacity_msgs == 0      # no grant yet
    m = peers_msg()
    assert not is_flow_controlled(m)
    assert fc.try_send(m) is m               # passes with zero capacity


def test_send_blocked_until_first_grant():
    fc = FlowControl(cfg())
    m = tx_msg()
    assert fc.try_send(m) is None
    assert fc.outbound_queue_len() == 1
    out = grant(fc, 1, msg_body_size(m))
    assert out == [m]


def test_byte_capacity_blocks_even_with_message_credit():
    """FlowControlTests byte-capacity edge: message credit alone is not
    enough."""
    fc = FlowControl(cfg())
    m = tx_msg()
    grant(fc, 5, msg_body_size(m) - 1)       # one byte short
    assert fc.try_send(m) is None
    assert fc.outbound_queue_len() == 1
    assert fc.remote_capacity_msgs == 5      # nothing consumed


def test_message_capacity_blocks_even_with_byte_credit():
    fc = FlowControl(cfg())
    m = tx_msg()
    grant(fc, 0, 10_000_000)
    assert fc.try_send(m) is None


def test_exact_byte_boundary_sends():
    fc = FlowControl(cfg())
    m = tx_msg()
    grant(fc, 1, msg_body_size(m))
    assert fc.try_send(m) is m
    assert fc.remote_capacity_bytes == 0
    assert fc.remote_capacity_msgs == 0


def test_queued_messages_release_in_fifo_order():
    fc = FlowControl(cfg())
    m1, m2, m3 = tx_msg(), tx_msg(1), tx_msg(2)
    for m in (m1, m2, m3):
        assert fc.try_send(m) is None
    sz = msg_body_size(m1) + msg_body_size(m2)
    out = grant(fc, 2, sz)
    assert out == [m1, m2]
    assert fc.outbound_queue_len() == 1
    assert grant(fc, 1, msg_body_size(m3)) == [m3]


def test_partial_release_stops_at_byte_shortfall():
    """on_send_more releases head-of-line only while BOTH credits
    cover it (no reordering around a stuck head)."""
    fc = FlowControl(cfg())
    big, small = tx_msg(3), tx_msg()
    assert fc.try_send(big) is None
    assert fc.try_send(small) is None
    # enough bytes for small but not for big: nothing moves (FIFO)
    out = grant(fc, 2, msg_body_size(small))
    assert out == []
    assert fc.outbound_queue_len() == 2


def test_new_send_behind_nonempty_queue_never_jumps():
    fc = FlowControl(cfg())
    m1 = tx_msg(2)
    assert fc.try_send(m1) is None
    grant(fc, 5, 10_000_000)
    # queue drained by the grant; further sends pass directly
    m2 = tx_msg()
    assert fc.try_send(m2) is m2


def test_queue_jump_prevented_while_blocked():
    fc = FlowControl(cfg())
    big = tx_msg(3)
    grant(fc, 2, msg_body_size(big) - 1)
    assert fc.try_send(big) is None          # blocked on bytes
    small = tx_msg()
    assert fc.try_send(small) is None        # must queue BEHIND big
    assert fc.outbound_queue_len() == 2


# ---------------------------------------------------------- receive side --
def test_receive_overflow_on_messages_is_violation():
    """throwIfOutOfSyncRecv: peer exceeding its message allowance."""
    c = cfg(msgs=1, byts=10_000)
    fc = FlowControl(c)
    m = tx_msg()
    assert fc.on_message_received(m) is True
    assert fc.on_message_received(m) is False


def test_receive_overflow_on_bytes_is_violation():
    m = tx_msg()
    c = cfg(msgs=10, byts=msg_body_size(m) * 2 - 1)
    fc = FlowControl(c)
    assert fc.on_message_received(m) is True
    assert fc.on_message_received(m) is False   # second exceeds bytes


def test_non_flood_receive_never_consumes():
    c = cfg(msgs=1, byts=100)
    fc = FlowControl(c)
    for _ in range(10):
        assert fc.on_message_received(peers_msg()) is True
    assert fc.local_capacity_msgs == 1
    assert fc.local_capacity_bytes == 100


def test_send_more_batches_at_message_threshold():
    """SEND_MORE_EXTENDED fires after batch_msgs processed messages and
    returns exactly the processed amounts."""
    c = cfg(batch_msgs=2, batch_bytes=10**9)
    fc = FlowControl(c)
    m = tx_msg()
    fc.on_message_received(m)
    assert fc.maybe_send_more(m) is None
    fc.on_message_received(m)
    sm = fc.maybe_send_more(m)
    assert sm is not None and sm.disc == MessageType.SEND_MORE_EXTENDED
    assert sm.value.numMessages == 2
    assert sm.value.numBytes == 2 * msg_body_size(m)


def test_send_more_batches_at_byte_threshold():
    m = tx_msg(3)
    c = cfg(batch_msgs=10**6, batch_bytes=msg_body_size(m))
    fc = FlowControl(c)
    fc.on_message_received(m)
    sm = fc.maybe_send_more(m)
    assert sm is not None and sm.value.numMessages == 1


def test_send_more_replenishes_local_capacity():
    m = tx_msg()
    sz = msg_body_size(m)
    c = cfg(msgs=2, byts=2 * sz, batch_msgs=2, batch_bytes=10**9)
    fc = FlowControl(c)
    for _ in range(2):
        assert fc.on_message_received(m) is True
        sm = fc.maybe_send_more(m)
    assert sm is not None
    assert fc.local_capacity_msgs == 2       # restored
    assert fc.local_capacity_bytes == 2 * sz
    # the cycle is sustainable indefinitely
    for _ in range(6):
        assert fc.on_message_received(m) is True
        fc.maybe_send_more(m)


def test_non_flood_never_triggers_send_more():
    fc = FlowControl(cfg(batch_msgs=1, batch_bytes=1))
    assert fc.maybe_send_more(peers_msg()) is None


def test_initial_send_more_carries_config_capacity():
    c = cfg(msgs=7, byts=777)
    fc = FlowControl(c)
    sm = fc.initial_send_more(c)
    assert sm.disc == MessageType.SEND_MORE_EXTENDED
    assert sm.value.numMessages == 7
    assert sm.value.numBytes == 777


def test_two_peer_handshake_symmetric_flow():
    """End-to-end credit loop between two FlowControls (the loopback
    shape of FlowControlTests)."""
    ca, cb = cfg(msgs=2, byts=10_000), cfg(msgs=2, byts=10_000)
    a, b = FlowControl(ca), FlowControl(cb)
    # exchange initial grants
    a.on_send_more(cb.PEER_FLOOD_READING_CAPACITY,
                   cb.PEER_FLOOD_READING_CAPACITY_BYTES)
    b.on_send_more(ca.PEER_FLOOD_READING_CAPACITY,
                   ca.PEER_FLOOD_READING_CAPACITY_BYTES)
    m = tx_msg()
    sent = 0
    for _ in range(10):
        out = a.try_send(m)
        if out is None:
            break
        assert b.on_message_received(out)
        sent += 1
        sm = b.maybe_send_more(out)
        if sm is not None:
            a.on_send_more(sm.value.numMessages, sm.value.numBytes)
    assert sent == 10                        # credits kept flowing
