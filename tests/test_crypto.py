"""Crypto-layer tests (reference: src/crypto/test/CryptoTests.cpp).

The load-bearing property: every backend — pure-Python oracle, native C++,
OpenSSL-precheck path, and (in test_ops_ed25519.py) the JAX/TPU kernel —
agrees on accept/reject for every input, including canonicality edges.
"""

import hashlib

import numpy as np
import pytest

from stellar_core_tpu.crypto import ed25519_ref as ref
from stellar_core_tpu.crypto.keys import (
    PublicKey, SecretKey, PubKeyUtils, verify_sig_uncached,
    _verify_strict_openssl, flush_verify_cache_counts, clear_verify_cache)
from stellar_core_tpu.crypto.sha import (
    sha256, sha512, hmac_sha256, hkdf_extract, hkdf_expand, blake2b_256)
from stellar_core_tpu.crypto.strkey import StrKey, StrKeyError
from stellar_core_tpu.crypto import shorthash
from stellar_core_tpu.crypto.curve25519 import Curve25519Secret
from stellar_core_tpu.native import loader


@pytest.fixture(scope="module")
def native():
    return loader.get_lib()


def test_sha_matches_hashlib():
    for n in (0, 1, 55, 56, 63, 64, 100):
        data = bytes(range(n))
        assert sha256(data) == hashlib.sha256(data).digest()
        assert sha512(data) == hashlib.sha512(data).digest()


def test_native_sha512_matches_hashlib(native):
    for n in (0, 1, 111, 112, 127, 128, 129, 1000):
        data = (b"\xab" * n)
        assert native.sha512(data) == hashlib.sha512(data).digest()


def test_hkdf_rfc5869_shape():
    prk = hkdf_extract(b"\x0b" * 22, salt=bytes(range(13)))
    okm = hkdf_expand(prk, b"\xf0\xf1", 42)
    assert len(okm) == 42
    # expand is prefix-consistent
    assert hkdf_expand(prk, b"\xf0\xf1", 16) == okm[:16]
    # extract == HMAC(salt, ikm) by definition
    assert prk == hmac_sha256(bytes(range(13)), b"\x0b" * 22)


def test_siphash24_known_vectors():
    # widely-published SipHash-2-4 reference vectors (Aumasson/Bernstein)
    key = bytes(range(16))
    assert shorthash.siphash24(key, b"") == 0x726FDB47DD0E0E31
    assert shorthash.siphash24(key, bytes(range(15))) == 0xA129CA6149BE45E5


def test_shorthash_seeding():
    shorthash.seed_for_testing(b"\x01" * 16)
    a = shorthash.compute_hash(b"bucket-key")
    shorthash.seed_for_testing(b"\x02" * 16)
    b = shorthash.compute_hash(b"bucket-key")
    shorthash.seed_for_testing(b"\x01" * 16)
    assert shorthash.compute_hash(b"bucket-key") == a
    assert a != b


def test_strkey_roundtrip_and_tamper():
    raw = hashlib.sha256(b"acct").digest()
    s = StrKey.encode_ed25519_public(raw)
    assert s.startswith("G")
    assert StrKey.decode_ed25519_public(s) == raw
    seed = StrKey.encode_ed25519_seed(raw)
    assert seed.startswith("S")
    # tampered checksum rejected
    bad = s[:-1] + ("A" if s[-1] != "A" else "B")
    with pytest.raises(StrKeyError):
        StrKey.decode_ed25519_public(bad)
    # wrong version byte rejected
    with pytest.raises(StrKeyError):
        StrKey.decode_ed25519_seed(s)


def test_sign_verify_roundtrip():
    sk = SecretKey.pseudo_random_for_testing(1)
    msg = b"transaction contents hash"
    sig = sk.sign(msg)
    assert len(sig) == 64
    assert PubKeyUtils.verify_sig(sk.public_key(), sig, msg)
    assert not PubKeyUtils.verify_sig(sk.public_key(), sig, msg + b"x")
    sk2 = SecretKey.pseudo_random_for_testing(2)
    assert not PubKeyUtils.verify_sig(sk2.public_key(), sig, msg)
    # determinstic test keys are stable
    assert SecretKey.pseudo_random_for_testing(1).seed == sk.seed


def test_signature_hint():
    sk = SecretKey.pseudo_random_for_testing(3)
    assert sk.public_key().hint() == sk.public_key().raw[28:]


def test_verify_cache_counters():
    clear_verify_cache()
    flush_verify_cache_counts()  # zero counters accumulated by earlier tests
    sk = SecretKey.pseudo_random_for_testing(4)
    msg = b"cached message"
    sig = sk.sign(msg)
    PubKeyUtils.verify_sig(sk.public_key(), sig, msg)
    h0, m0 = flush_verify_cache_counts()
    assert (h0, m0) == (0, 1)
    for _ in range(5):
        assert PubKeyUtils.verify_sig(sk.public_key(), sig, msg)
    h1, m1 = flush_verify_cache_counts()
    assert (h1, m1) == (5, 0)


def _edge_cases():
    seed = hashlib.sha256(b"edge").digest()
    pub = ref.secret_to_public(seed)
    msg = b"the message"
    sig = ref.sign(seed, msg)
    cases = [(pub, sig, msg, True)]
    # S >= L
    S = int.from_bytes(sig[32:], "little")
    cases.append((pub, sig[:32] + int.to_bytes(S + ref.L, 32, "little"), msg, False))
    # non-canonical R (y = p+1 re-encodes point y=1)
    noncanon = int.to_bytes(ref.P + 1, 32, "little")
    cases.append((pub, noncanon + sig[32:], msg, False))
    # non-canonical A
    cases.append((noncanon, sig, msg, False))
    # small-order A: identity point (y=1)
    ident = int.to_bytes(1, 32, "little")
    cases.append((ident, sig, msg, False))
    # corrupted
    bad = bytearray(sig)
    bad[3] ^= 0x40
    cases.append((pub, bytes(bad), msg, False))
    return cases


def test_strict_semantics_all_backends(native):
    for pub, sig, msg, expected in _edge_cases():
        assert ref.verify(pub, sig, msg) == expected, "oracle"
        assert native.verify(pub, sig, msg) == expected, "native C++"
        assert _verify_strict_openssl(pub, sig, msg) == expected, "openssl path"
        assert verify_sig_uncached(pub, sig, msg) == expected, "default path"


def test_native_differential_random(native):
    rng = np.random.default_rng(7)
    for i in range(15):
        seed = hashlib.sha256(b"d%d" % i).digest()
        pub = ref.secret_to_public(seed)
        msg = bytes(rng.integers(0, 256, int(rng.integers(0, 100)),
                                 dtype=np.uint8))
        sig = ref.sign(seed, msg)
        assert native.verify(pub, sig, msg)
        b = bytearray(sig)
        b[int(rng.integers(0, 64))] ^= 1 << int(rng.integers(0, 8))
        assert native.verify(pub, bytes(b), msg) == ref.verify(pub, bytes(b), msg)


def test_native_sign_differential(native):
    """sc_ed25519_sign (ISSUE 12): byte-identical to the RFC 8032
    oracle across message lengths (incl. 0 and >stack-buffer sizes),
    and public_from_seed agrees with the oracle derivation — the
    signer SecretKey.sign uses when the OpenSSL wheel is absent."""
    rng = np.random.default_rng(12)
    for i, msglen in enumerate((0, 1, 31, 32, 64, 100, 511, 512, 600,
                                2000)):
        seed = hashlib.sha256(b"s%d" % i).digest()
        pub = native.public_from_seed(seed)
        assert pub == ref.secret_to_public(seed)
        msg = bytes(rng.integers(0, 256, msglen, dtype=np.uint8))
        sig = native.sign(seed, pub, msg)
        assert sig == ref.sign(seed, msg), msglen
        assert native.verify(pub, sig, msg)


def test_secret_key_sign_uses_fastest_backend():
    """SecretKey.sign output must stay RFC 8032 canonical whatever
    backend the container resolves (OpenSSL > native C > pure
    python)."""
    sk = SecretKey.from_seed(hashlib.sha256(b"backend-seam").digest())
    msg = b"the backend seam must not change the bytes"
    sig = sk.sign(msg)
    assert sig == ref.sign(sk.seed, msg)
    assert PubKeyUtils.verify_sig(sk.public_key(), sig, msg)


def test_native_batch(native):
    n = 64
    pubs, sigs, msgs = [], [], []
    for i in range(n):
        sk = SecretKey.pseudo_random_for_testing(100 + i)
        m = b"batch-%d" % i
        pubs.append(sk.public_key().raw)
        sigs.append(sk.sign(m))
        msgs.append(m)
    # corrupt a few
    bad_idx = {5, 17, 63}
    for i in bad_idx:
        b = bytearray(sigs[i])
        b[0] ^= 1
        sigs[i] = bytes(b)
    pubs_a = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(n, 32)
    sigs_a = np.frombuffer(b"".join(sigs), dtype=np.uint8).reshape(n, 64)
    cat = b"".join(msgs)
    offs = np.zeros(n + 1, dtype=np.uint64)
    for i, m in enumerate(msgs):
        offs[i + 1] = offs[i] + len(m)
    res = native.batch_verify(pubs_a, sigs_a, cat, offs)
    assert [i for i in range(n) if not res[i]] == sorted(bad_idx)
    # batch_prepare k matches oracle
    k, s_ok = native.batch_prepare(pubs_a, sigs_a, cat, offs)
    assert s_ok.all()
    for i in (0, 31, 63):
        expect = ref.compute_k(sigs[i][:32], pubs[i], msgs[i])
        assert int.from_bytes(k[i].tobytes(), "little") == expect


def test_curve25519_ecdh():
    a = Curve25519Secret.random()
    b = Curve25519Secret.random()
    ka = a.ecdh(b.derive_public(), local_first=True)
    kb = b.ecdh(a.derive_public(), local_first=False)
    assert ka == kb
    assert len(ka) == 32
    # role ordering matters: both claiming "first" diverges
    assert a.ecdh(b.derive_public(), True) != b.ecdh(a.derive_public(), True)


def test_blake2b():
    assert blake2b_256(b"x") == hashlib.blake2b(b"x", digest_size=32).digest()
